# Convenience targets; everything also runs as the plain commands shown.

.PHONY: test test-fast bench dryrun proto-check api-docs telemetry-check chaos-check byzantine-check observatory-check perf-check async-check fleetobs-check recovery-check parity-check wire-check privacy-check analyze race-check population-check asyncpop-check devobs-check campaign-check soak-check doctor-check

test:            ## full suite on the virtual 8-device CPU mesh (~30 min, 1 core)
	python -m pytest tests/ -q

test-fast:       ## CI subset (~2 min)
	python -m pytest tests/ -m "not slow" -q

bench:           ## north-star benchmark (real TPU; waits for the tunnel)
	python bench.py

dryrun:          ## 5-phase multichip dryrun on an 8-device virtual CPU mesh
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

proto-check:     ## fail if node_pb2.py is stale w.r.t. node.proto
	python -m p2pfl_tpu.comm.grpc.generate_proto --check

telemetry-check: ## 2-node in-memory round; asserts the telemetry snapshot (fast, CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/telemetry_check.py

chaos-check:     ## 3-node round with one mid-round kill; survivors must finish fast (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/chaos_check.py

byzantine-check: ## 3-node round with one signflip adversary; admission must reject, honest must learn (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/byzantine_check.py

observatory-check: ## 3-node gate: digests propagate, slow peer tops the straggler score, kill dumps the flight recorder (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/observatory_check.py

perf-check:      ## 3-node gate: critical path produced, slow node gates it, perf_diff exit codes verified (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/perf_check.py

async-check:     ## 3-node gate: async windows beat sync rounds with a 3x straggler; mid-run join contributes within 2 windows (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/async_check.py

fleetobs-check:  ## 3-node gate: staleness sketches propagate on beats, window attribution flags a 3x-slow peer, v1-digest peer tolerated (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/fleetobs_check.py

recovery-check:  ## 3-node gate: kill one journaled node mid-round, resume it from its journal as the same addr, federation finishes (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/recovery_check.py

parity-check:    ## sim↔real gate: one seeded 3-node scenario on the wire AND the fused mesh must emit aligned trajectory ledgers with bit-exact aggregate hashes (CPU-only, ~25 s)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/parity_check.py

wire-check:      ## 3-node gate: int4+coalesced codec matches f32 accuracy, sparse bytes shrink >=2x, measured train<->diffuse overlap > 0 (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/wire_check.py

privacy-check:   ## 3-node gate: masked run matches plaintext accuracy, one masker killed mid-round does not corrupt the aggregate, epsilon reported nonzero (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/privacy_check.py

population-check: ## 64-node fused gate: 10% cohort + seeded churn finishes, cohort stream replay-identical across chunked runs and fresh plans (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/population_check.py

campaign-check:  ## campaign-universe gate: replays the committed baseline prefix (incl. the adaptive-adversary family) on both backends, parity-differed and invariant-graded, hashes bit-identical to tests/campaign_fixtures/ (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/campaign_check.py

asyncpop-check:  ## fused async-window gate: slow-tier windows close by fill, flash-crowd trace sustains throughput, wire-vs-fused parity bit-exact at n=4 (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/asyncpop_check.py

devobs-check:    ## device-observatory gate: in-scan sketches chunking-invariant, devobs on/off params hash bit-identical, NaN tripwire parks+aborts in-chunk, fused/wire snapshot shape parity (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/devobs_check.py

soak-check:      ## supervisor gate: seeded 64-vnode run healed through kill/OOM/SIGTERM on both engines, final hash bit-identical to fault-free control, event-log replay identical, degrade ladder deterministic (CPU-only, ~60 s)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/soak_check.py

doctor-check:    ## diagnosis gate: 3 seeded fault scenarios (straggler/signflip/kill) each diagnose to their injected cause, clean control yields NO diagnosis, bundle manifests replay-identical (CPU-only, ~30 s)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/doctor_check.py

analyze:         ## static correctness pass (C1-C5: lock order, blocking-under-lock, unguarded writes, jit purity, drift); exit 0 clean / 1 new finding / 2 stale suppression
	PYTHONPATH=. python scripts/analyze.py --baseline analysis_baseline.json

race-check:      ## 3-node chaos round under the instrumented-lock sentinel: observed acquisition graph must be acyclic; deliberate inversion must be caught (CPU-only)
	JAX_PLATFORMS=cpu PYTHONPATH=. python scripts/race_check.py

api-docs:        ## regenerate docs/api.md from the live package
	PYTHONPATH=. python scripts/gen_api_docs.py
