"""North-star benchmark: 100-node MNIST MLP FedAvg simulation, 10 rounds.

BASELINE.json: "FL rounds/sec & sec/round (100-node MNIST FedAvg); final
test-acc parity", target >= 50x wall-clock vs the Ray+PyTorch CPU baseline,
zero host-side weight transfers during aggregation.

The TPU path runs the whole experiment as ONE jitted XLA program
(p2pfl_tpu.parallel.MeshSimulation): weights stay in HBM across all rounds.
The baseline is a faithful stand-in for the reference's per-node compute: an
identical MLP trained per committee member with an eager PyTorch CPU loop
(the reference's simulation executes exactly this inside Ray actors,
p2pfl/learning/frameworks/simulation/actor_pool.py:38-63 — our measurement
omits Ray/gossip overhead, which makes the baseline strictly conservative).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value
is TPU sec/round and vs_baseline is the speedup factor (baseline sec/round /
TPU sec/round).
"""

from __future__ import annotations

import json
import sys
import time


def _phase(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)

NUM_NODES = 100
ROUNDS = 10
EPOCHS = 1
COMMITTEE = 4
BATCH = 64
SAMPLES_PER_NODE = 600  # MNIST 60k / 100 nodes
TEST_SAMPLES = 1024


def bench_tpu() -> dict:
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    import jax
    import jax.numpy as jnp

    _phase("generating data on device")

    # Same distribution as synthetic_mnist (class templates + noise), but
    # generated directly on the accelerator: with a tunneled TPU, uploading
    # the ~190MB stacked dataset dominates startup otherwise.
    @jax.jit
    def make_data(key):
        kt, ky, kn, kyt, knt = jax.random.split(key, 5)
        templates = jax.random.uniform(kt, (10, 28, 28), jnp.float32)
        y = jax.random.randint(ky, (NUM_NODES, SAMPLES_PER_NODE), 0, 10)
        x = jnp.clip(
            templates[y]
            + 0.35 * jax.random.normal(kn, (NUM_NODES, SAMPLES_PER_NODE, 28, 28)),
            0.0,
            1.0,
        )
        mask = jnp.ones((NUM_NODES, SAMPLES_PER_NODE), jnp.float32)
        yt = jax.random.randint(kyt, (TEST_SAMPLES,), 0, 10)
        xt = jnp.clip(
            templates[yt] + 0.35 * jax.random.normal(knt, (TEST_SAMPLES, 28, 28)), 0.0, 1.0
        )
        return x, y.astype(jnp.int32), mask, xt, yt.astype(jnp.int32)

    x, y, mask, xt, yt = make_data(jax.random.key(42))
    jax.block_until_ready(x)
    _phase("building simulation")
    sim = MeshSimulation(
        mlp_model(seed=0),
        (x, y, mask),
        test_data=(xt, yt),
        train_set_size=COMMITTEE,
        batch_size=BATCH,
        seed=1,
    )
    _phase("warmup compile + timed run")
    res = sim.run(rounds=ROUNDS, epochs=EPOCHS, warmup=True)
    _phase(f"tpu done: {res.seconds_per_round:.4f}s/round acc={res.test_acc[-1]:.3f}")
    return {
        "sec_per_round": res.seconds_per_round,
        "rounds_per_sec": 1.0 / res.seconds_per_round,
        "final_test_acc": res.test_acc[-1],
    }


def bench_torch_cpu_baseline() -> float:
    """One federated round of committee compute, eager PyTorch CPU.

    Returns sec/round (committee of COMMITTEE nodes, EPOCHS local epochs
    each, same model/batch/data sizes as the TPU path).
    """
    import numpy as np
    import torch
    from torch import nn

    torch.manual_seed(0)
    rng = np.random.default_rng(0)
    x = torch.from_numpy(rng.normal(size=(SAMPLES_PER_NODE, 784)).astype(np.float32))
    y = torch.from_numpy(rng.integers(0, 10, size=SAMPLES_PER_NODE).astype(np.int64))

    def one_node_epoch() -> None:
        model = nn.Sequential(
            nn.Flatten(), nn.Linear(784, 256), nn.ReLU(), nn.Linear(256, 128),
            nn.ReLU(), nn.Linear(128, 10),
        )
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        loss_fn = nn.CrossEntropyLoss()
        for _ in range(EPOCHS):
            for i in range(0, SAMPLES_PER_NODE, BATCH):
                opt.zero_grad()
                loss = loss_fn(model(x[i : i + BATCH]), y[i : i + BATCH])
                loss.backward()
                opt.step()

    one_node_epoch()  # warmup
    t0 = time.monotonic()
    for _ in range(COMMITTEE):
        one_node_epoch()
    return time.monotonic() - t0


def main() -> None:
    tpu = bench_tpu()
    _phase("torch cpu baseline")
    baseline_sec_per_round = bench_torch_cpu_baseline()
    _phase("baseline done")
    value = tpu["sec_per_round"]
    out = {
        "metric": "sec_per_round_100node_mnist_fedavg",
        "value": round(value, 6),
        "unit": "s/round",
        "vs_baseline": round(baseline_sec_per_round / value, 3),
        "extra": {
            "rounds_per_sec": round(tpu["rounds_per_sec"], 3),
            "final_test_acc": round(tpu["final_test_acc"], 4),
            "baseline_sec_per_round_torch_cpu": round(baseline_sec_per_round, 6),
            "rounds": ROUNDS,
            "nodes": NUM_NODES,
            "committee": COMMITTEE,
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
