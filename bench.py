"""North-star benchmark: 100-node MNIST MLP FedAvg simulation, 10 rounds.

BASELINE.json: "FL rounds/sec & sec/round (100-node MNIST FedAvg); final
test-acc parity", target >= 50x wall-clock vs the reference's CPU baseline,
zero host-side weight transfers during aggregation.

The TPU path runs the whole experiment as ONE jitted XLA program
(p2pfl_tpu.parallel.MeshSimulation): weights stay in HBM across all rounds;
``rounds_per_call`` is swept over {1, 5, 10} and the best dispatch
amortization is reported.

The baseline is the REFERENCE ITSELF, measured (BASELINE.md: "must be
measured, not cited"): a real `/root/reference` p2pfl federation — its Node,
in-memory protocol, gossip stack, and Flax learner (the only reference ML
backend whose deps exist in this image; Ray/lightning are absent, so
learners run inline, the reference's documented no-Ray fallback) — on the
same 100-node/600-samples/committee-4 shape, with the reference's own
``set_test_settings`` pacing (which *shrinks* its protocol waits, making the
measured baseline conservative). It runs in a subprocess pinned to CPU with
a hard timeout; if the reference cannot complete, an eager-PyTorch committee
loop stands in and the JSON says so.

Utilization is reported separately: the same simulation at a wide-MLP
configuration with analytic FLOPs/step -> measured TFLOP/s and MFU vs the
chip's peak (the 235k-param parity model cannot utilize an MXU; the wide
config shows what the framework achieves when the model has real math).

Accuracy is meaningful: 10% of labels (train and test) are flipped, so the
achievable test accuracy is ~0.9 and "final_test_acc" reflects actual
learning; the reference baseline run reports its aggregated model's
held-out accuracy on the same distribution for the parity pair. (Caveat
discovered while measuring: the reference's FlaxLearner never writes its
trained TrainState back into the model it returns, so its federation
aggregates initial weights and that accuracy stays ~random — see the
baseline "note" field and SURVEY.md §7 quirks.)

If the TPU probe fails (the tunneled chip can be unreachable for hours),
the bench does NOT give up after minutes (rounds 3 and 4 lost the capture
race exactly that way — the outage pattern is hours-scale with spontaneous
recovery). Instead the parent process is a pure orchestrator that never
imports jax (so a wedged backend init can never poison it) and:

1. probes the chip in a SUBPROCESS (a hang is killed, not inherited);
2. while the tunnel is down, pre-computes the honest degraded fallback
   (reduced-scale CPU-mesh measurement + matched-node-count reference
   baseline) so a numeric answer is ready at any instant;
3. keeps re-probing with backoff until only the measurement reserve of
   the soft budget remains, then prints the degraded line;
4. if the tunnel returns in time, runs the full TPU measurement (itself a
   subprocess) followed by the reference baseline, and prints the real
   line;
5. on SIGTERM/SIGINT (an impatient driver), immediately prints the best
   line it has — degraded beats empty.

The soft budget defaults to 3000 s and is tunable via
``P2PFL_TPU_BENCH_BUDGET``; the wait ladder consumes whatever the
measurement reserve (~900 s) does not need.

Always prints exactly ONE JSON line:
{"metric", "value", "unit", "vs_baseline", "extra", ["error"]}.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import traceback

REPO = os.path.dirname(os.path.abspath(__file__))

# --- north-star parity config (BASELINE.json) --------------------------------
NUM_NODES = 100
ROUNDS = 10
EPOCHS = 1
COMMITTEE = 4
BATCH = 64
SAMPLES_PER_NODE = 600  # MNIST 60k / 100 nodes
TEST_SAMPLES = 1024
NOISE = 0.35
LABEL_FLIP = 0.10  # caps achievable acc at ~0.9 -> accuracy is informative

# --- utilization (MFU) config ------------------------------------------------
# Component isolation on the real chip (round 4) showed the training path
# itself runs at 66-83% MFU once per-call tunnel dispatch (~77 ms/call) and
# per-round committee machinery (~tens of ms of gather/diffuse/scatter HBM
# traffic) are amortized; a 1-epoch batch-2048 round is overhead-dominated
# and measured 18%. The probe therefore makes the round compute-dominated
# the honest way: batch 8192 (adam's 9x f32 param traffic amortized over 4x
# the matmul work — 83% vs 66% measured at 2048), 4 local epochs (a standard
# FedAvg knob, McMahan et al.'s E), eval every 5 rounds, 10 rounds in ONE
# compiled call. The analytic FLOP count below includes the epochs factor.
MFU_NODES = 8
MFU_HIDDEN = (4096, 4096)
MFU_BATCH = 8192
MFU_SAMPLES_PER_NODE = 32768
MFU_EPOCHS = 4
MFU_ROUNDS = 10
MFU_EVAL_EVERY = 5
MFU_TEST_SAMPLES = 256

# HBM bandwidth per chip by device kind (public TPU specs, bytes/s) — for
# the roofline term in the MFU probe.
HBM_BW = {
    "TPU v4": 1.2e12,
    "TPU v5": 2.8e12,
    "TPU v5p": 2.8e12,
    "TPU v5e": 8.1e11,
    "TPU v5 lite": 8.1e11,
    "TPU v6e": 1.6e12,
    "TPU v6 lite": 1.6e12,
}

# bf16 peak FLOP/s per chip by device kind (public TPU specs)
PEAK_FLOPS = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}

# --- scale config (BASELINE.json config #5 shape: FEMNIST-style) -------------
# The reference collapses at 100 in-process nodes (BASELINE.md: heartbeat
# convergence fails); MeshSimulation's population is just a sharded array
# axis, so 5x that is a demonstration, not a redesign.
SCALE_NODES = 512  # divisible by an 8-wide nodes mesh axis (stays sharded)
SCALE_SAMPLES = 120
SCALE_COMMITTEE = 50  # 10% sampling
SCALE_ROUNDS = 10
SCALE_ALPHA = 0.5  # Dirichlet non-IID concentration
SCALE_FEDPROX_MU = 0.01

# --- CIFAR ResNet-18 config (BASELINE.json configs #3/#4) ---------------------
CIFAR_NODES = 56  # >= 50-node shape, divisible by an 8-wide nodes mesh axis
CIFAR_SAMPLES = 256
CIFAR_COMMITTEE = 8
CIFAR_ROUNDS = 60  # device time is trivial (~0.2 s/round); training volume
CIFAR_ROUNDS_PER_CALL = 10  # fuse rounds into one lax.scan'd call
CIFAR_EVAL_EVERY = 5
CIFAR_POISON = 0.1
# 10x-scaled-delta model poisoning: the attack where the defended/undefended
# contrast is visible at bench scale (label flipping at 10% is survivable by
# plain FedAvg, so it demonstrates nothing; the scaled attack wrecks FedAvg
# while Multi-Krum's distance filter excludes the attackers).
CIFAR_ATTACK = "scaled"

# --- multi-host config (--multihost: the bench path across processes) -------
# 2 OS processes x 4 virtual CPU devices each -> an 8-wide process-spanning
# "nodes" mesh axis (the CI-runnable analogue of a DCN-spanning pod slice;
# the reference's counterpart is Ray-cluster scale-out, actor_pool.py:69).
# 96 nodes ~ the north-star population rounded to the mesh axis width.
MH_PROCS = 2
MH_DEVICES_PER_PROC = 4
MH_NODES = 96
MH_SAMPLES = 192  # CPU-affordable; override via P2PFL_TPU_MH_* for full shape
MH_ROUNDS = 10
MH_RPC = 5

# Reference-baseline attempt ladder: (nodes, rounds, subprocess timeout).
# The reference's flax learner is unjitted at batch size 1, so its rounds
# take minutes; measuring it at fewer nodes than the 100-node metric shape
# UNDERSTATES its cost (less gossip + eval load) and therefore keeps
# vs_baseline conservative. The largest completing config is reported.
BASELINE_LADDER = [(20, 1, 700.0), (4, 1, 240.0)]
BASELINE_SAMPLES = SAMPLES_PER_NODE


def _phase(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


# --- shared bench-JSON meta block ---------------------------------------------
# Every arm stamps the same versioned meta so scripts/perf_diff.py can refuse
# cross-schema comparisons instead of mis-diffing structurally different runs.
BENCH_SCHEMA_VERSION = 1


def _git_sha() -> str:
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True, text=True, cwd=REPO, timeout=10,
        )
        return proc.stdout.strip()[:40] if proc.returncode == 0 else ""
    except Exception:  # noqa: BLE001 — meta must never kill a bench
        return ""


#: Whether the environment pinned the CPU backend BEFORE this bench started
#: (captured at import, before any arm sets JAX_PLATFORMS itself): a main-
#: metric run degrading under a pre-pinned env is reason "forced_env", not a
#: tunnel outage.
_FORCED_CPU_AT_START = "cpu" in (os.environ.get("JAX_PLATFORMS") or "").lower()

#: Why the last TPU probe failed ("tpu_probe_timeout" | "tpu_absent" |
#: "tpu_probe_error" | "assumed_backend"); None while no probe has failed.
#: BENCH_r03–r05 degraded silently and the trajectory doc had to
#: reverse-engineer which — the meta block now records it.
_TPU_FAIL_REASON: list = [None]

#: Per-invocation probe verdict cache: ONE probe, all arms. Only DEFINITIVE
#: verdicts are cached — a found chip ("up", kind) or a clean negative
#: ("down", "tpu_absent" / "tpu_probe_error"). A timeout is a transient
#: non-answer the wait ladder must keep re-asking, so it is never cached.
_PROBE_CACHE: list = [None]


def _assumed_backend() -> str:
    """The validated ``P2PFL_TPU_BENCH_ASSUME_BACKEND`` knob ("" when the
    operator made no assertion). "cpu" skips every probe and the whole wait
    ladder (the r03+ budget burner) and stamps ``fallback_reason=
    "assumed_backend"``; "tpu" asserts the tunnel is up. The orchestrator
    also SELF-propagates its first settled verdict through this knob into
    per-arm subprocesses."""
    from p2pfl_tpu.config import Settings  # light import: config only

    return str(Settings.BENCH_ASSUME_BACKEND)


def _fallback_reason() -> str | None:
    """The reason a main-metric run fell back to CPU, for the meta block."""
    if _FORCED_CPU_AT_START:
        return "forced_env"
    return _TPU_FAIL_REASON[0]


def _bench_meta(seed=None, backend=None, fallback_reason=None) -> dict:
    return {
        "schema_version": BENCH_SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "backend": backend or os.environ.get("JAX_PLATFORMS", "") or "default",
        "seed": seed,
        # None on a run that measured its intended backend; otherwise why
        # this run degraded to CPU ("tpu_probe_timeout" — the tunnel probe
        # hung; "tpu_absent" — the probe ran and found no TPU platform;
        # "tpu_probe_error" — the probe itself crashed; "forced_env" — the
        # environment pinned JAX_PLATFORMS=cpu before the bench started).
        "fallback_reason": fallback_reason,
        # The federation-wide run id (telemetry/bundle.py) — joins this
        # arm's JSON line to the ledger/flightrec/snapshot artifacts it
        # produced. Empty when the arm never touched p2pfl_tpu.
        "run_id": _run_id_or_empty(),
        "created_at": round(time.time(), 3),
    }


def _run_id_or_empty() -> str:
    try:
        from p2pfl_tpu.telemetry.bundle import current_run_id

        return current_run_id()
    except Exception:  # noqa: BLE001 — meta must never kill a bench
        return ""


def _emit(out: dict, seed=None, backend=None, fallback_reason=None) -> None:
    """Stamp the shared meta block, print the arm's ONE JSON line, exit."""
    if backend is None:
        backend = (out.get("extra") or {}).get("device_kind")
    out.setdefault(
        "meta",
        _bench_meta(seed=seed, backend=backend, fallback_reason=fallback_reason),
    )
    if "error" in out:
        # A failed arm assertion is an incident: capture the evidence
        # bundle before the hard exit (never raises, skipped when the
        # doctor plane is disabled or p2pfl_tpu never loaded).
        try:
            from p2pfl_tpu.telemetry.bundle import write_bundle

            out["bundle"] = write_bundle(
                "bench_assertion",
                context={"error": str(out.get("error")), "meta": out.get("meta")},
            )
        except Exception:  # noqa: BLE001 — the JSON line must still print
            pass
    print(json.dumps(out), flush=True)
    os._exit(1 if "error" in out else 0)


def probe_backend(attempts: int = 2, timeout: float = 180.0) -> str:
    """Bounded, retried backend-init probe: a flaky TPU client must produce
    a JSON error line, not a hang or a bare rc=1 (round-1/2 failure mode)."""
    if _assumed_backend() == "cpu":
        # Operator (or the orchestrator's settled first verdict) asserts no
        # chip: pin CPU before jax initializes instead of burning the
        # timeout ladder against a dead tunnel. fallback_reason still
        # stamps how this arm ended up on CPU.
        _TPU_FAIL_REASON[0] = "assumed_backend"
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    last_err: list[str] = ["backend probe never ran"]

    for attempt in range(1, attempts + 1):
        result: dict = {}

        def _try() -> None:
            try:
                import jax

                devs = jax.devices()
                result["kind"] = devs[0].device_kind
                result["n"] = len(devs)
            except Exception as e:  # noqa: BLE001
                result["err"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=_try, daemon=True)
        t.start()
        t.join(timeout)
        if result.get("kind"):
            _phase(f"backend ok: {result['n']}x {result['kind']}")
            return result["kind"]
        last_err[0] = result.get("err", f"backend init timed out after {timeout}s")
        _phase(f"backend probe attempt {attempt}/{attempts} failed: {last_err[0]}")
        if attempt < attempts:  # no backoff after the final attempt
            time.sleep(min(30.0, 5.0 * attempt))
    raise RuntimeError(f"TPU backend unavailable: {last_err[0]}")


def _probe_timeout() -> float:
    """The validated probe-timeout knob (``P2PFL_TPU_BENCH_PROBE_TIMEOUT``,
    default 90s, fail-fast-validated at import by config.py). BENCH_r03–r05
    fell back to CPU on 90s probe timeouts that a longer leash would have
    survived — the timeout is now an operator decision, not a constant."""
    from p2pfl_tpu.config import Settings  # light import: config only

    return float(Settings.BENCH_PROBE_TIMEOUT)


def _subprocess_tpu_probe(
    timeout: float | None = None, retries: int = 0
) -> str | None:
    """Probe the tunneled chip in a THROWAWAY subprocess.

    The tunnel's failure mode is a backend init that hangs forever while
    holding jax's process-wide backend lock — an in-process probe that
    wedges poisons every later in-process retry (round-2 lesson). A
    subprocess probe is killed on timeout and leaves the parent pristine,
    so the wait ladder can probe for as long as the budget allows.
    ``timeout`` defaults to the ``P2PFL_TPU_BENCH_PROBE_TIMEOUT`` knob;
    ``retries`` re-probes after a TIMEOUT only (a clean "no TPU platform"
    answer is definitive — re-asking cannot change it). Every failure still
    stamps ``_TPU_FAIL_REASON`` so the meta block's ``fallback_reason``
    (and perf_diff's backend refusal) keep firing.
    Returns the device kind (e.g. "TPU v5 lite") or None.
    """
    assumed = _assumed_backend()
    if assumed == "cpu":
        _TPU_FAIL_REASON[0] = "assumed_backend"
        return None
    if assumed == "tpu":
        return "TPU (assumed)"
    if _PROBE_CACHE[0] is not None:
        state, payload = _PROBE_CACHE[0]
        if state == "up":
            return payload
        _TPU_FAIL_REASON[0] = payload
        return None
    if timeout is None:
        timeout = _probe_timeout()
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # the parent may have pinned cpu
    code = (
        "import jax\n"
        "d = jax.devices()[0]\n"
        "print(f'{d.platform}|{d.device_kind}', flush=True)\n"
    )
    for attempt in range(int(retries) + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True, timeout=timeout, env=env,
            )
            line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
            platform, _, kind = line.partition("|")
            if platform.lower() == "tpu" and kind:
                _PROBE_CACHE[0] = ("up", kind)
                return kind
            # The probe RAN and found no TPU platform — a different failure
            # (and a different fix) than a hung tunnel. Definitive:
            # re-asking cannot change it, so the verdict caches.
            _TPU_FAIL_REASON[0] = "tpu_absent"
            _PROBE_CACHE[0] = ("down", "tpu_absent")
            return None
        except subprocess.TimeoutExpired:
            _TPU_FAIL_REASON[0] = "tpu_probe_timeout"
            if attempt < retries:
                _phase(
                    f"tpu probe timed out after {timeout:.0f}s — retrying "
                    f"({attempt + 1}/{retries})"
                )
        except Exception:  # noqa: BLE001 — a broken probe reads as "down"
            _TPU_FAIL_REASON[0] = "tpu_probe_error"
            _PROBE_CACHE[0] = ("down", "tpu_probe_error")
            traceback.print_exc(file=sys.stderr)
            return None
    return None


def wait_for_tpu(deadline: float, probe_timeout: float | None = None) -> str | None:
    """Retry ladder: subprocess-probe the chip with backoff until it
    answers or ``deadline`` (time.monotonic clock) nears. The outage
    pattern is hours-scale with spontaneous recovery, so patience here is
    the whole game — six minutes of it lost rounds 3 and 4."""
    if _assumed_backend() == "cpu":
        _phase("wait ladder skipped: P2PFL_TPU_BENCH_ASSUME_BACKEND=cpu")
        _TPU_FAIL_REASON[0] = "assumed_backend"
        return None
    if probe_timeout is None:
        probe_timeout = _probe_timeout()
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining < probe_timeout:
            _phase("wait ladder: reserve reached, giving up on the tunnel")
            return None
        attempt += 1
        _phase(
            f"wait ladder: probe {attempt} (up to {probe_timeout:.0f}s; "
            f"{remaining:.0f}s of wait budget left)"
        )
        kind = _subprocess_tpu_probe(probe_timeout)
        if kind:
            _phase(f"wait ladder: tunnel UP after {attempt} probe(s): {kind}")
            return kind
        if _PROBE_CACHE[0] is not None and _PROBE_CACHE[0][0] == "down":
            # A clean negative verdict is definitive for the whole
            # invocation — sleeping the ladder against it is the r03+
            # budget burn this cache exists to stop.
            _phase(f"wait ladder: definitive verdict ({_PROBE_CACHE[0][1]}) — done")
            return None
        # Short sleeps early (catch a quick flap), 120s cruise after.
        time.sleep(min(120.0, 30.0 * attempt))


def _make_data(num_nodes: int, samples: int, test_samples: int, seed: int = 42):
    """Class-template + gaussian-noise dataset with 10% label flip, generated
    ON DEVICE (a tunneled TPU makes host upload of ~190MB dominate startup)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def make(key):
        kt, ky, kn, kf, kfl, kyt, knt, kft, kftl = jax.random.split(key, 9)
        templates = jax.random.uniform(kt, (10, 28, 28), jnp.float32)
        y = jax.random.randint(ky, (num_nodes, samples), 0, 10)
        x = jnp.clip(
            templates[y] + NOISE * jax.random.normal(kn, (num_nodes, samples, 28, 28)),
            0.0, 1.0,
        )
        flip = jax.random.uniform(kf, y.shape) < LABEL_FLIP
        y_noisy = jnp.where(flip, jax.random.randint(kfl, y.shape, 0, 10), y)
        mask = jnp.ones((num_nodes, samples), jnp.float32)
        yt = jax.random.randint(kyt, (test_samples,), 0, 10)
        xt = jnp.clip(
            templates[yt] + NOISE * jax.random.normal(knt, (test_samples, 28, 28)), 0.0, 1.0
        )
        flip_t = jax.random.uniform(kft, yt.shape) < LABEL_FLIP
        yt_noisy = jnp.where(flip_t, jax.random.randint(kftl, yt.shape, 0, 10), yt)
        return x, y_noisy.astype(jnp.int32), mask, xt, yt_noisy.astype(jnp.int32)

    out = make(jax.random.key(seed))
    jax.block_until_ready(out[0])
    return out


_metric_data_cache: dict = {}


def _metric_sim_run(nodes: int, rounds: int, rpc: int) -> dict:
    """One measurement of the metric simulation at the given scale —
    the ONE place the metric's sim config lives (primary TPU path and CPU
    fallback must never drift apart)."""
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    if nodes not in _metric_data_cache:  # the rpc sweep reuses one dataset
        _phase("generating data on device")
        _metric_data_cache[nodes] = _make_data(nodes, SAMPLES_PER_NODE, TEST_SAMPLES)
    x, y, mask, xt, yt = _metric_data_cache[nodes]
    # close() each sweep point: the jit cache pins every simulation that ran
    # (static self), so without it the sweep accumulates dead populations in
    # HBM; the cached dataset survives via _metric_data_cache's own refs.
    with MeshSimulation(
        mlp_model(seed=0), (x, y, mask), test_data=(xt, yt),
        train_set_size=COMMITTEE, batch_size=BATCH, seed=1,
    ) as sim:
        res = sim.run(rounds=rounds, epochs=EPOCHS, warmup=True, rounds_per_call=rpc)
    return {
        "sec_per_round": res.seconds_per_round,
        "rounds_per_sec": 1.0 / res.seconds_per_round,
        "final_test_acc": res.test_acc[-1],
        "rounds_per_call": rpc,
        "nodes": nodes,
        "rounds": rounds,
    }


def bench_tpu(budget_deadline: float = float("inf")) -> dict:
    """Sweep rounds_per_call for the metric config. The sweep is ordered
    best-guess-first and bails out when the soft budget deadline nears, so
    a slow tunnel compile can cost sweep POINTS but never the metric."""
    _phase("building simulation")
    sweep: dict[int, float] = {}
    best = None
    for rpc in (10, 1, 5):  # r3 winner first: a budget bail keeps the best point
        if best is not None and time.monotonic() > budget_deadline:
            _phase(f"soft budget tight: skipping rounds_per_call={rpc}")
            continue
        _phase(f"rounds_per_call={rpc}: warmup compile + timed run")
        out = _metric_sim_run(NUM_NODES, ROUNDS, rpc)
        sweep[rpc] = out["sec_per_round"]
        _phase(
            f"rounds_per_call={rpc}: {out['sec_per_round']:.5f}s/round "
            f"acc={out['final_test_acc']:.3f}"
        )
        if best is None or out["sec_per_round"] < best["sec_per_round"]:
            best = out
    best["rounds_per_call_sweep"] = {str(k): round(v, 6) for k, v in sweep.items()}
    if 1 in sweep and 10 in sweep:
        # The sweep doubles as a dispatch probe: going 1->10 rounds/call
        # removes 9 of 10 per-call overheads, so the spread estimates the
        # tunnel's fixed cost — the floor under sec/round on THIS link
        # (non-tunneled hardware would sit lower at identical device time).
        best["est_dispatch_s_per_call"] = round(
            max(0.0, (sweep[1] - sweep[10]) * 10.0 / 9.0), 4
        )
    return best


def run_cpu_fallback() -> None:
    """Subprocess body: reduced-scale measurement on the virtual CPU mesh.

    Runs when the TPU probe fails (the tunneled chip can be unreachable for
    hours): the number is honest — same simulation code path, same measured
    reference baseline — just on CPU at 8 nodes x 4 rounds, and the parent
    relabels the metric so it can never be misread as the 100-node result.
    A SUBPROCESS is mandatory: a hung axon client init in the parent holds
    jax's backend-init lock, deadlocking any in-process CPU retry.
    """
    out: dict = {}
    try:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        out = _metric_sim_run(nodes=8, rounds=4, rpc=4)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out), flush=True)
    os._exit(0)


# Live measurement children: _bail (the SIGTERM hedge) must kill them
# before exiting, or an orphaned --baseline-ref subprocess keeps saturating
# the single core for minutes and skews whatever the driver measures next.
_live_children: set = set()


def _json_subprocess(args: list, timeout: float, env: dict) -> dict:
    """Run a bench subprocess mode, parse its single JSON line; on any
    failure raise with a stderr tail so crashes are diagnosable. Children
    are tracked in ``_live_children`` for the signal hedge."""
    stderr_tail = ""
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py"), *args],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO,
    )
    _live_children.add(proc)
    try:
        try:
            stdout, stderr = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            stdout, stderr = proc.communicate()
            stderr_tail = (stderr or "")[-1500:]
            raise
        stderr_tail = (stderr or "")[-1500:]
        line = stdout.strip().splitlines()[-1] if stdout.strip() else ""
        out = json.loads(line)
        if "error" in out:
            raise RuntimeError(out["error"])
        return out
    except Exception as e:  # noqa: BLE001
        raise RuntimeError(
            f"{type(e).__name__}: {e}\n--- subprocess stderr tail ---\n{stderr_tail}"
        ) from e
    finally:
        _live_children.discard(proc)


def measure_cpu_fallback(budget: float) -> dict:
    """Run the reduced-scale CPU measurement in a subprocess and parse it."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return _json_subprocess(["--cpu-fallback"], max(120.0, budget), env)


def _train_path_probe(
    device_kind: str, model, x, y, matmul_params: int,
    members: int = COMMITTEE, batch: int = MFU_BATCH, steps: int = 64,
) -> dict:
    """Isolated fit-path utilization: ``members`` vmapped member steps
    chained under ONE ``lax.scan`` — no vote, no gather/diffuse, no eval,
    no optimizer-state re-init. Round 4 claimed "66-83% once per-round
    machinery amortizes" from component isolation but never landed it in
    an artifact (VERDICT r4 weak #6); this measures that exact quantity
    into the MFU probe's JSON. Params chain step-to-step, so every
    iteration's inputs differ structurally (replay-proof by construction).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax

    samples = x.shape[1]
    n_batches = samples // batch
    xk = x[:members, : n_batches * batch].reshape(
        members, n_batches, batch, *x.shape[2:]
    )
    yk = y[:members, : n_batches * batch].reshape(members, n_batches, batch)
    tx = optax.adam(1e-3)
    p0 = model.params
    stack = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (members,) + a.shape) + 0.0, p0
    )
    opt0 = jax.vmap(tx.init)(stack)

    def member_step(p, o, bx, by):
        def loss_fn(pp):
            logits = model.apply_fn(pp, bx)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, by
            ).mean()

        loss, g = jax.value_and_grad(loss_fn)(p)
        u, o = tx.update(g, o, p)
        return optax.apply_updates(p, u), o, loss

    @jax.jit
    def run(stack, opt):
        def body(carry, i):
            stack, opt = carry
            bi = i % n_batches
            bx = lax.dynamic_index_in_dim(xk, bi, axis=1, keepdims=False)
            by = lax.dynamic_index_in_dim(yk, bi, axis=1, keepdims=False)
            stack, opt, loss = jax.vmap(member_step)(stack, opt, bx, by)
            return (stack, opt), loss.mean()

        (stack, opt), losses = lax.scan(body, (stack, opt), jnp.arange(steps))
        return stack, opt, losses[-1]

    stack1, opt1, last = run(stack, opt0)  # compile + warmup
    np.asarray(last)
    t0 = time.monotonic()
    stack2, opt2, last = run(stack1, opt1)  # warmed call, distinct inputs
    np.asarray(last)
    dt = time.monotonic() - t0
    flops = members * steps * 6.0 * batch * matmul_params
    achieved = flops / dt
    peak = PEAK_FLOPS.get(device_kind)
    return {
        "members": members, "batch": batch, "steps": steps,
        "seconds": round(dt, 4),
        "achieved_tflops": round(achieved / 1e12, 3),
        "mfu": round(achieved / peak, 4) if peak else None,
        "note": "pure fit path (vmapped member steps under one scan): no "
        "vote/gather/diffuse/eval — the training-kernel ceiling the "
        "full-round MFU is measured against",
    }


def bench_mfu(device_kind: str) -> dict:
    """Wide-MLP utilization probe: analytic FLOPs / measured time vs peak."""
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    _phase("MFU config: generating data on device")
    x, y, mask, xt, yt = _make_data(MFU_NODES, MFU_SAMPLES_PER_NODE, MFU_TEST_SAMPLES, seed=7)

    model = mlp_model(seed=0, hidden_sizes=MFU_HIDDEN)
    matmul_params = (
        784 * MFU_HIDDEN[0] + MFU_HIDDEN[0] * MFU_HIDDEN[1] + MFU_HIDDEN[1] * 10
    )
    with MeshSimulation(
        model, (x, y, mask), test_data=(xt, yt),
        train_set_size=COMMITTEE, batch_size=MFU_BATCH, seed=1,
    ) as sim:
        _phase("MFU config: warmup compile + timed run")
        res = sim.run(
            rounds=MFU_ROUNDS, epochs=MFU_EPOCHS, warmup=True,
            rounds_per_call=MFU_ROUNDS, eval_every=MFU_EVAL_EVERY,
        )

    try:
        train_path = _train_path_probe(device_kind, model, x, y, matmul_params)
    except Exception as e:  # noqa: BLE001 — the probe must not kill the MFU row
        traceback.print_exc(file=sys.stderr)
        train_path = {"error": f"{type(e).__name__}: {e}"}

    steps_per_epoch = MFU_SAMPLES_PER_NODE // MFU_BATCH
    steps_per_round = steps_per_epoch * MFU_EPOCHS
    train_flops_per_step = 6.0 * MFU_BATCH * matmul_params  # fwd 2x + bwd 4x
    # Eval runs every MFU_EVAL_EVERY rounds; amortize it per round.
    eval_flops = 2.0 * MFU_TEST_SAMPLES * matmul_params / MFU_EVAL_EVERY
    flops_per_round = COMMITTEE * steps_per_round * train_flops_per_step + eval_flops
    achieved = flops_per_round / res.seconds_per_round
    peak = PEAK_FLOPS.get(device_kind)

    # Roofline: is this config MXU-bound or HBM-bound on this chip? Per
    # step per member: fwd+bwd touch the f32 params twice (bf16 casts fuse
    # into the matmul reads, so traffic stays 4B/param), grads write once,
    # and adam reads+writes both f32 moments and the params. Activations
    # ([B, hidden] bf16, fwd save + bwd read) are B-proportional.
    p_bytes = 4.0 * matmul_params
    act_bytes = 2.0 * 2 * MFU_BATCH * (MFU_HIDDEN[0] + MFU_HIDDEN[1])
    step_bytes = (
        2 * p_bytes        # params read: fwd + bwd
        + p_bytes          # grads write
        + 6 * p_bytes      # adam: read m, v, params; write m, v, params
        + act_bytes
    )
    round_bytes = COMMITTEE * steps_per_round * step_bytes + (
        # committee gather (read K models) + diffusion broadcast (write N)
        (COMMITTEE + MFU_NODES) * p_bytes
    )
    bw = HBM_BW.get(device_kind)
    roofline = None
    if peak and bw:
        t_flops = flops_per_round / peak
        t_hbm = round_bytes / bw
        # Achievable MFU if compute and HBM overlap perfectly: the round
        # cannot finish faster than max(t_flops, t_hbm).
        roofline = {
            "flops_per_round": flops_per_round,
            "hbm_bytes_per_round": round_bytes,
            "arithmetic_intensity_flop_per_byte": round(flops_per_round / round_bytes, 1),
            "ridge_flop_per_byte": round(peak / bw, 1),
            "t_mxu_ms": round(t_flops * 1e3, 2),
            "t_hbm_ms": round(t_hbm * 1e3, 2),
            "mfu_ceiling": round(t_flops / max(t_flops, t_hbm), 3),
            "note": "ceiling assumes perfect compute/HBM overlap; the "
            "optimizer (9x f32 param traffic/step) is the dominant HBM term",
        }
    return {
        "model": f"MLP-784x{MFU_HIDDEN[0]}x{MFU_HIDDEN[1]}x10",
        "params": int(matmul_params),
        "batch": MFU_BATCH,
        "local_epochs": MFU_EPOCHS,
        "sec_per_round": round(res.seconds_per_round, 6),
        "flops_per_step": train_flops_per_step,
        "flops_per_round": flops_per_round,
        "achieved_tflops": round(achieved / 1e12, 3),
        "assumed_peak_tflops": round(peak / 1e12, 1) if peak else None,
        "mfu": round(achieved / peak, 4) if peak else None,
        "roofline": roofline,
        "train_path_probe": train_path,
        "note": "utilization probe (random labels); parity metrics come from the 100-node config",
    }


def _mh_cfg() -> dict:
    """Multi-host shape, env-overridable (the slow test shrinks it)."""
    g = lambda k, d: int(os.environ.get(f"P2PFL_TPU_MH_{k}", d))  # noqa: E731
    return {
        "procs": g("PROCS", MH_PROCS),
        "devices_per_proc": g("DEVICES", MH_DEVICES_PER_PROC),
        "nodes": g("NODES", MH_NODES),
        "samples": g("SAMPLES", MH_SAMPLES),
        "rounds": g("ROUNDS", MH_ROUNDS),
        "rpc": g("RPC", MH_RPC),
        # Cohort fraction for seeded sampling across the process-spanning
        # mesh (0 = full participation, the classic bench shape).
        "cohort": float(os.environ.get("P2PFL_TPU_MH_COHORT", "0")),
    }


def run_multihost() -> None:
    """Orchestrator for ``--multihost``: spawn N worker processes that join
    one jax.distributed deployment (N x 4 virtual CPU devices -> one
    process-spanning ``nodes`` mesh axis) and run the FULL bench path —
    MeshSimulation with fused rounds_per_call, warmup, eval — as a single
    SPMD program across processes. Process 0's JSON line is reprinted here.

    This is the runnable counterpart of the reference's Ray-cluster
    scale-out (actor_pool.py:69): same launch shape as a real pod slice
    (per-host processes + a coordinator), CPU devices standing in for
    chips. Launch: ``python bench.py --multihost``.
    """
    import socket

    cfg = _mh_cfg()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.join(REPO, "bench.py"),
                "--multihost-worker", str(port), str(pid),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in range(cfg["procs"])
    ]
    # Drain all worker pipes CONCURRENTLY: the workers run one lockstep
    # SPMD program, so a worker blocked writing >64KB of unread stdout
    # (jax warnings + _phase lines) inside a collective would deadlock the
    # whole deployment if we drained sequentially.
    outs: list[str] = [""] * len(procs)
    # Worker cap derives from the soft budget like every other subprocess
    # cap (measure_reference_baseline, main's metric_cap) instead of a
    # hard-coded 1800 s: most of the budget, minus a reporting reserve.
    try:
        soft_budget = float(os.environ.get("P2PFL_TPU_BENCH_BUDGET", "3000"))
    except ValueError:
        soft_budget = 3000.0
    worker_cap = max(120.0, soft_budget - 120.0)

    def _drain(i: int, p) -> None:
        try:
            outs[i], _ = p.communicate(timeout=worker_cap)
        except subprocess.TimeoutExpired:
            p.kill()
            outs[i], _ = p.communicate()

    drains = [
        threading.Thread(target=_drain, args=(i, p), daemon=True)
        for i, p in enumerate(procs)
    ]
    for t in drains:
        t.start()
    for t in drains:
        t.join()
    line = None
    for pid, (p, out) in enumerate(zip(procs, outs)):
        tail = out[-2500:]
        if p.returncode != 0:
            print(json.dumps({"error": f"multihost worker {pid} rc={p.returncode}: {tail}"}))
            os._exit(1)
        if pid == 0:
            for ln in reversed(out.strip().splitlines()):
                if ln.startswith("{"):
                    line = ln
                    break
    if line is None:
        print(json.dumps({"error": f"worker 0 printed no JSON: {outs[0][-2500:]}"}))
        os._exit(1)
    print(line, flush=True)
    os._exit(0)


def run_multihost_worker(port: int, pid: int) -> None:
    """Worker body for ``--multihost``: join the deployment, build the
    process-spanning mesh, run the metric simulation, report (pid 0)."""
    cfg = _mh_cfg()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={cfg['devices_per_proc']}"
    ).strip()
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.mesh import initialize_multihost, make_mesh
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    initialize_multihost(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=cfg["procs"], process_id=pid,
    )
    n_global = cfg["procs"] * cfg["devices_per_proc"]
    assert len(jax.devices()) == n_global, (len(jax.devices()), n_global)
    mesh = make_mesh()
    _phase(f"multihost worker {pid}: mesh over {n_global} devices, "
           f"{jax.process_count()} processes")

    # Host-side numpy data with identical seeds in every process (SPMD
    # requires all processes to feed the same logical arrays); semantics
    # mirror _make_data (class templates + noise + label flip).
    n, s = cfg["nodes"], cfg["samples"]
    rng = np.random.default_rng(42)
    templates = rng.uniform(size=(10, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, size=(n, s)).astype(np.int32)
    x = np.clip(
        templates[y] + NOISE * rng.normal(size=(n, s, 28, 28)), 0.0, 1.0
    ).astype(np.float32)
    flip = rng.uniform(size=y.shape) < LABEL_FLIP
    y[flip] = rng.integers(0, 10, size=int(flip.sum()))
    yt = rng.integers(0, 10, size=TEST_SAMPLES).astype(np.int32)
    xt = np.clip(
        templates[yt] + NOISE * rng.normal(size=(TEST_SAMPLES, 28, 28)), 0.0, 1.0
    ).astype(np.float32)
    flip_t = rng.uniform(size=yt.shape) < LABEL_FLIP
    yt[flip_t] = rng.integers(0, 10, size=int(flip_t.sum()))
    mask = np.ones((n, s), np.float32)

    # Optional seeded cohort sampling over the process-spanning mesh: the
    # population sampler's schedule is deterministic per (seed, round, name),
    # so every process compiles the identical committee rows — no collective
    # needed to agree on who trains.
    run_kw: dict = {}
    committee = COMMITTEE
    if cfg["cohort"] > 0:
        from p2pfl_tpu.population.cohort import CohortPlan, committee_schedule

        plan = CohortPlan(
            seed=1, fraction=cfg["cohort"],
            names=tuple(f"node-{i}" for i in range(n)),
        )
        sched = committee_schedule(plan, plan.names, cfg["rounds"])
        run_kw["committee_schedule"] = sched
        committee = int(sched.shape[1])
        _phase(f"multihost worker {pid}: cohort {cfg['cohort']:.2f} -> "
               f"K={committee} of {n} nodes per round")

    with MeshSimulation(
        mlp_model(seed=0), (x, y, mask), test_data=(xt, yt),
        train_set_size=committee, batch_size=BATCH, seed=1, mesh=mesh,
    ) as sim:
        res = sim.run(
            rounds=cfg["rounds"], epochs=EPOCHS, warmup=True,
            rounds_per_call=cfg["rpc"], **run_kw,
        )
    out = {
        "metric": f"sec_per_round_{n}node_mnist_fedavg_multihost_cpu",
        "value": round(res.seconds_per_round, 6),
        "unit": "s/round",
        "extra": {
            "processes": cfg["procs"],
            "devices_per_process": cfg["devices_per_proc"],
            "global_devices": n_global,
            "nodes": n, "rounds": cfg["rounds"], "rounds_per_call": cfg["rpc"],
            "samples_per_node": s, "committee": committee,
            "cohort_fraction": cfg["cohort"] or None,
            "final_test_acc": round(float(res.test_acc[-1]), 4),
            "note": "bench path over a 2-process jax.distributed mesh (CPU "
            "devices standing in for chips); launch: python bench.py --multihost",
        },
    }
    if pid == 0:
        out["meta"] = _bench_meta(seed=1, backend="cpu")
        print(json.dumps(out), flush=True)
    else:
        print(f"MULTIHOST_WORKER_OK pid={pid} acc={res.test_acc[-1]:.4f}", flush=True)
    os._exit(0)


def run_tpu_metric(budget: float) -> None:
    """Subprocess body: the full on-chip measurement — backend init, the
    rounds_per_call metric sweep, and the MFU probe — in a FRESH process.

    The orchestrating parent never imports jax, so a backend wedge here
    (tunnel flapping mid-init) dies with this subprocess instead of
    poisoning the parent's later options. Prints ONE JSON line:
    {"tpu": {...}, "mfu": {...}, "kind": "..."} or {"error": "..."}.
    """
    out: dict = {}
    t0 = time.monotonic()
    try:
        kind = probe_backend()
        tpu = bench_tpu(budget_deadline=t0 + budget * 0.6)
        if time.monotonic() - t0 > budget * 0.7:
            _phase("tpu-metric: soft budget tight, skipping MFU probe")
            mfu: dict = {"skipped": "soft time budget"}
        else:
            try:
                mfu = bench_mfu(kind)
            except Exception as e:  # noqa: BLE001 — MFU must not kill the metric
                traceback.print_exc(file=sys.stderr)
                mfu = {"error": f"{type(e).__name__}: {e}"}
        out = {"tpu": tpu, "mfu": mfu, "kind": kind}
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out), flush=True)
    os._exit(1 if "error" in out else 0)


def scale_bench_body(kind: str, n: int = SCALE_NODES, s: int = SCALE_SAMPLES,
                     rounds: int = SCALE_ROUNDS, committee: int = SCALE_COMMITTEE) -> dict:
    """The measurable body of the --scale-500 mode (probe-free, so the CPU
    mesh can rehearse it at reduced scale): Dirichlet non-IID data generated
    on device, FedProx, 10% committee sampling, eval every 5 rounds."""
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    @jax.jit
    def make(key):
        kt, kd, ky, kn, kyt, knt = jax.random.split(key, 6)
        templates = jax.random.uniform(kt, (10, 28, 28), jnp.float32)
        # Per-node class mixture ~ Dir(alpha): the FEMNIST-style
        # writer-skew each node sees a few classes mostly.
        probs = jax.random.dirichlet(kd, jnp.full((10,), SCALE_ALPHA), (n,))
        logits = jnp.broadcast_to(jnp.log(probs + 1e-9)[:, None, :], (n, s, 10))
        y = jax.random.categorical(ky, logits, axis=-1).astype(jnp.int32)
        x = jnp.clip(
            templates[y] + NOISE * jax.random.normal(kn, (n, s, 28, 28)), 0.0, 1.0
        )
        yt = jax.random.randint(kyt, (TEST_SAMPLES,), 0, 10).astype(jnp.int32)
        xt = jnp.clip(
            templates[yt] + NOISE * jax.random.normal(knt, (TEST_SAMPLES, 28, 28)),
            0.0, 1.0,
        )
        return x, y, jnp.ones((n, s), jnp.float32), xt, yt

    _phase(f"scale bench: generating {n}-node Dirichlet data on device")
    x, y, mask, xt, yt = make(jax.random.key(11))
    jax.block_until_ready(x)
    with MeshSimulation(
        mlp_model(seed=0), (x, y, mask), test_data=(xt, yt),
        train_set_size=committee, batch_size=BATCH, seed=1,
        fedprox_mu=SCALE_FEDPROX_MU,
    ) as sim:
        _phase("scale bench: warmup compile + timed run")
        res = sim.run(
            rounds=rounds, epochs=1, warmup=True,
            rounds_per_call=rounds, eval_every=5,
        )
    return {
        # "synthetic" in the metric name: the accuracy column is on
        # template+noise Dirichlet blobs and must not read as a real-CIFAR
        # parity claim (VERDICT r4 weak #5); the THROUGHPUT is the point.
        "metric": f"sec_per_round_{n}node_dirichlet_fedprox_synthetic",
        "value": round(res.seconds_per_round, 6),
        "unit": "s/round",
        "extra": {
            "nodes": n, "committee": committee, "rounds": rounds,
            "samples_per_node": s, "alpha": SCALE_ALPHA,
            "fedprox_mu": SCALE_FEDPROX_MU,
            "final_test_acc": round(res.test_acc[-1], 4),
            "accuracy_data": "synthetic template+noise blobs (class-template "
            "MNIST-shaped); throughput is the comparison, accuracy is sanity",
            "device_kind": kind,
            "note": "reference collapses at 100 in-process nodes "
            f"(BASELINE.md: heartbeat convergence fails); this is {n} nodes "
            f"with {100.0 * committee / max(n, 1):.1f}% committee sampling",
        },
    }


def run_scale_500() -> None:
    """Subprocess-style mode: config #5 shape at 5x the reference's collapse
    point — 512 nodes, Dirichlet non-IID, FedProx, 10% committee sampling.
    Prints ONE JSON line. Data is generated on device so startup is not
    dominated by a ~180MB host upload over the tunnel."""
    out: dict = {}
    try:
        kind = probe_backend()
        out = scale_bench_body(kind)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=11)


def attn_bench_body(kind: str, seqs=(1024, 2048, 4096, 8192), iters_cap: int = 65536) -> dict:
    """Kernel-level microbench: Pallas flash attention vs the XLA dense
    softmax path vs the lax.scan blockwise path, forward and forward+
    backward, at growing sequence length (bf16, causal, B=1 H=8 D=128).

    Timing is tunnel-honest: each timed region is ONE compiled call that
    chains ``iters`` data-dependent iterations (inputs differ every step,
    so nothing can be replay-served) and is closed by fetching a scalar
    that data-depends on the last iteration.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from p2pfl_tpu.ops.attention import (
        blockwise_attention, dense_attention, flash_attention,
    )

    B, H, D = 1, 8, 128
    variants = {
        "dense": lambda q, k, v: dense_attention(q, k, v, causal=True),
        "blockwise": lambda q, k, v: blockwise_attention(q, k, v, causal=True),
        "flash": lambda q, k, v: flash_attention(q, k, v, causal=True),
        # Same forward kernel, old remat-through-blockwise backward: its
        # fwdbwd row quantifies what the Pallas backward kernels buy.
        "flashremat": lambda q, k, v: flash_attention(
            q, k, v, True, 512, 512, None, "remat"
        ),
    }

    def timed_call(fn, s: int, iters: int, grad: bool) -> float:
        if grad:
            loss = lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum()
            # All three grads: argnums=0 alone would let XLA dead-code the
            # dk/dv matmuls for the XLA paths while flash's custom VJP
            # always computes them — biasing the comparison against flash.
            body = jax.grad(loss, argnums=(0, 1, 2))
        else:
            body = fn

        @jax.jit
        def chained(q, k, v):
            def step(carry, _):
                q, k, v = carry
                if grad:
                    dq, dk, dv = body(q, k, v)
                    # Fold every grad back in: keeps dk/dv live and makes
                    # each iteration's inputs distinct (replay-proof). The
                    # 1e-2 scale sits above bf16 ulp at |x|~1 (~4e-3), so
                    # the change is structural, not just in rare tiny
                    # elements; softmax saturation from the slow drift
                    # changes no FLOPs.
                    q = q + (1e-2 * dq).astype(q.dtype)
                    k = k + (1e-2 * dk).astype(k.dtype)
                    v = v + (1e-2 * dv).astype(v.dtype)
                    probe = dq.reshape(-1)[0]
                else:
                    out = body(q, k, v)
                    q = q + (1e-2 * out).astype(q.dtype)  # data-dependence
                    probe = out.reshape(-1)[0]
                return (q, k, v), probe
            (q, k, v), last = lax.scan(step, (q, k, v), None, length=iters)
            return q, last

        key = jax.random.key(s)
        kq, kk, kv = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, s, H, D), jnp.bfloat16)
        k = jax.random.normal(kk, (B, s, H, D), jnp.bfloat16)
        v = jax.random.normal(kv, (B, s, H, D), jnp.bfloat16)
        qc, last = chained(q, k, v)  # compile + warmup
        np.asarray(last)  # retire the warmup for real
        t0 = time.monotonic()
        qc, last = chained(qc, k, v)  # warmed inputs differ from warmup's
        np.asarray(last)
        return (time.monotonic() - t0) / iters

    results: dict = {}
    for s in seqs:
        # Causal-convention FLOPs: QK^T + PV over the lower triangle.
        fwd_flops = 2.0 * B * H * s * s * D
        # FLOP-proportional iteration count: ~1e14 FLOP (~1 s at 100
        # TFLOP/s) of fwd work per timed region, so the ONE ~77 ms tunnel
        # dispatch each compiled call pays is <10% of the measurement at
        # every S. The cap never binds at the defaults; it exists so smoke
        # tests can pass a small iters_cap and finish in interpret mode.
        iters = max(8, min(iters_cap, int(1.0e14 / fwd_flops)))
        row: dict = {"iters": iters}
        for name, fn in variants.items():
            for grad, suffix, factor in ((False, "fwd", 1.0), (True, "fwdbwd", 3.5)):
                if name == "flashremat" and not grad:
                    continue  # its forward is byte-identical to "flash"
                try:
                    dt = timed_call(fn, s, iters, grad)
                    row[f"{suffix}_{name}_ms"] = round(dt * 1e3, 3)
                    row[f"{suffix}_{name}_tflops"] = round(
                        factor * fwd_flops / dt / 1e12, 2
                    )
                except Exception as e:  # noqa: BLE001 — e.g. dense OOM at 8k
                    traceback.print_exc(file=sys.stderr)
                    row[f"{suffix}_{name}_ms"] = (
                        f"error: {type(e).__name__}: {str(e)[:200]}"
                    )
        for suffix in ("fwd", "fwdbwd"):
            d, f = row.get(f"{suffix}_dense_ms"), row.get(f"{suffix}_flash_ms")
            if isinstance(d, float) and isinstance(f, float) and f > 0:
                row[f"{suffix}_flash_vs_dense"] = round(d / f, 2)
        results[str(s)] = row
        _phase(f"attn S={s}: {json.dumps(row)}")
    # Headline: flash fwd throughput at the largest seq that measured; a
    # null value with rc=0 would read as a successful run downstream.
    headline = next(
        (
            results[str(s)]["fwd_flash_tflops"]
            for s in reversed(seqs)
            if isinstance(results[str(s)].get("fwd_flash_tflops"), float)
        ),
        None,
    )
    if headline is None:
        raise RuntimeError(f"flash variant failed at every seq: {results}")
    return {
        "metric": "attention_kernel_microbench",
        "value": headline,
        "unit": "TFLOP/s",
        "extra": {
            "shape": f"B{B} H{H} D{D} bf16 causal",
            "device_kind": kind,
            "per_seq": results,
            "note": "causal-convention FLOPs (lower triangle); fwd+bwd "
            "counted at 3.5x fwd; flash bwd is the FlashAttention-2 "
            "Pallas kernel pair (ops/attention.py), flashremat rows show "
            "the old remat-through-blockwise backward for contrast",
        },
    }


def run_attn_bench() -> None:
    """Subprocess-style mode: the attention kernel microbench on the real
    chip. Prints ONE JSON line; per-seq rows echo to stderr as they finish."""
    out: dict = {}
    try:
        kind = probe_backend()
        out = attn_bench_body(kind)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out)


def _production_mfu_row(model: str, kind: str, cost: dict, sec_per_round: float) -> dict:
    """MFU + roofline for a production model's federated round, from XLA's
    own cost analysis of the compiled program (VERDICT r4 #6: no more
    purpose-built-MLP-only utilization numbers)."""
    flops_per_round = cost["flops_per_round"]
    bytes_per_round = cost.get("bytes_accessed_per_round", 0.0)
    achieved = flops_per_round / sec_per_round
    peak = PEAK_FLOPS.get(kind)
    bw = HBM_BW.get(kind)
    row = {
        "model": model,
        "flops_per_round": flops_per_round,
        "bytes_accessed_per_round": bytes_per_round,
        "sec_per_round": round(sec_per_round, 6),
        "achieved_tflops": round(achieved / 1e12, 3),
        "assumed_peak_tflops": round(peak / 1e12, 1) if peak else None,
        "mfu": round(achieved / peak, 4) if peak else None,
        "source": "XLA cost_analysis of the compiled round program",
    }
    if peak and bw and bytes_per_round:
        t_flops = flops_per_round / peak
        t_hbm = bytes_per_round / bw
        row["roofline"] = {
            "arithmetic_intensity_flop_per_byte": round(
                flops_per_round / bytes_per_round, 1
            ),
            "ridge_flop_per_byte": round(peak / bw, 1),
            "t_mxu_ms": round(t_flops * 1e3, 2),
            "t_hbm_ms": round(t_hbm * 1e3, 2),
            "mfu_ceiling": round(t_flops / max(t_flops, t_hbm), 3),
            "note": "XLA 'bytes accessed' counts logical operand traffic; "
            "fusion makes real HBM traffic lower, so t_hbm is pessimistic",
        }
    return row


# --- transformer-LM MFU config (--lm-mfu) ------------------------------------
# A production-shaped causal-LM federated round: 8 nodes, committee 4, flash
# attention, bf16. Sized so one fused 5-round call is compute-dominated on
# the tunnel (~1s+ of device work) without a long compile.
LM_NODES = 8
LM_COMMITTEE = 4
LM_SEQS_PER_NODE = 64
LM_SEQ_LEN = 1024
LM_VOCAB = 8192
LM_LAYERS = 4
LM_HEADS = 8
LM_EMBED = 512
LM_BATCH = 8
LM_ROUNDS = 5


def lm_mfu_body(kind: str, nodes: int = LM_NODES, seqs: int = LM_SEQS_PER_NODE,
                seq_len: int = LM_SEQ_LEN, rounds: int = LM_ROUNDS,
                vocab: int = LM_VOCAB, layers: int = LM_LAYERS,
                heads: int = LM_HEADS, embed: int = LM_EMBED,
                batch: int = LM_BATCH, attention: str = "flash") -> dict:
    """Federated transformer-LM round (MeshSimulation task='lm', flash
    attention) with XLA-cost-analysis MFU — the measurable body, probe-free
    and fully parameterized so the CPU mesh can rehearse it at tiny scale."""
    import numpy as np

    from p2pfl_tpu.models import transformer_lm_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    rng = np.random.default_rng(5)
    starts = rng.integers(0, vocab, size=(nodes, seqs, 1))
    x = ((starts + np.arange(seq_len)[None, None, :]) % vocab).astype(np.int32)
    y = np.zeros((nodes, seqs), np.int32)  # unused for task="lm"
    mask = np.ones((nodes, seqs), np.float32)
    xt = (
        (rng.integers(0, vocab, size=(16, 1)) + np.arange(seq_len)) % vocab
    ).astype(np.int32)

    model = transformer_lm_model(
        seed=0, seq_len=seq_len, vocab_size=vocab, num_layers=layers,
        num_heads=heads, embed_dim=embed, attention_kind=attention,
    )
    _phase(f"lm-mfu: {layers}L/{embed}d/{heads}h seq={seq_len} "
           f"vocab={vocab} nodes={nodes}")
    with MeshSimulation(
        model, (x, y, mask), test_data=(xt, None),
        train_set_size=min(LM_COMMITTEE, nodes), batch_size=batch,
        lr=3e-4, seed=1, task="lm",
    ) as sim:
        res = sim.run(rounds=rounds, epochs=1, warmup=True, rounds_per_call=rounds)
        cost = sim.round_cost_analysis(rounds_per_call=rounds)
    out = {
        "metric": "transformer_lm_federated_round_mfu",
        "value": None,
        "unit": "mfu",
        "extra": {
            "device_kind": kind,
            "nodes": nodes, "committee": min(LM_COMMITTEE, nodes),
            "seq_len": seq_len, "layers": layers, "embed": embed,
            "heads": heads, "vocab": vocab, "batch": batch,
            "rounds": rounds, "attention": attention,
            "sec_per_round": round(res.seconds_per_round, 6),
            "final_token_loss": round(res.test_loss[-1], 4),
        },
    }
    if cost:
        row = _production_mfu_row(
            f"transformer-lm-{layers}L-{embed}d-federated-round",
            kind, cost, res.seconds_per_round,
        )
        out["value"] = row.get("mfu")
        out["extra"]["mfu_row"] = row
    else:
        out["extra"]["mfu_row"] = {"error": "backend exposes no cost analysis"}
        out["value"] = 0.0
    return out


def run_lm_mfu() -> None:
    """Subprocess-style mode: transformer-LM federated-round MFU on the
    real chip. Prints ONE JSON line."""
    out: dict = {}
    try:
        kind = probe_backend()
        out = lm_mfu_body(kind)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=1)


def run_cifar_bench() -> None:
    """Subprocess-style mode: configs #3/#4 — federated GroupNorm ResNet-18
    on synthetic CIFAR at 56 nodes. Three points: SCAFFOLD (clean, config
    #3), Multi-Krum with 10% of nodes mounting the 10x-scaled-delta
    model-poisoning attack, and FedAvg under the same attack (the
    undefended contrast). Prints ONE JSON line; each completed leg is also
    echoed to stderr immediately (the tunnel can wedge a later leg for
    hours — a stall must not destroy the legs already measured)."""
    out: dict = {}
    try:
        kind = probe_backend()
        from p2pfl_tpu.examples.cifar import build_parser, run as cifar_run

        common = [
            "--nodes", str(CIFAR_NODES), "--rounds", str(CIFAR_ROUNDS),
            "--train-set-size", str(CIFAR_COMMITTEE),
            "--samples-per-node", str(CIFAR_SAMPLES), "--batch-size", "32",
            "--rounds-per-call", str(CIFAR_ROUNDS_PER_CALL),
            "--eval-every", str(CIFAR_EVAL_EVERY),
            "--seed", "1",
        ]
        runs = {}
        mfu_row = None
        poison = [
            "--poison-frac", str(CIFAR_POISON), "--attack", CIFAR_ATTACK,
        ]
        for label, extra in (
            # Cost analysis on the first leg only: the program's FLOPs are
            # identical across legs modulo the aggregation rule's epsilon.
            ("scaffold_clean", ["--aggregator", "scaffold", "--cost-analysis"]),
            ("krum_poisoned", ["--aggregator", "krum", *poison]),
            ("fedavg_poisoned", ["--aggregator", "fedavg", *poison]),
        ):
            _phase(f"cifar resnet18: {label}")
            r = cifar_run(build_parser().parse_args(common + extra))
            runs[label] = {
                "sec_per_round": round(r["sec_per_round"], 4),
                "final_test_acc": round(r["final_test_acc"], 4),
                "acc_curve": [round(a, 3) for a in r["test_acc"]],
                "poisoned_nodes": len(r["poisoned_nodes"]),
            }
            if r.get("cost_analysis"):
                mfu_row = _production_mfu_row(
                    "resnet18-groupnorm-federated-round", kind,
                    r["cost_analysis"], r["sec_per_round"],
                )
            _phase(f"cifar leg done: {json.dumps({label: runs[label]})}")
        out = {
            "metric": "cifar_resnet18_federated",
            "value": runs["krum_poisoned"]["sec_per_round"],
            "unit": "s/round",
            "extra": {
                "model": "resnet18-groupnorm", "nodes": CIFAR_NODES,
                "committee": CIFAR_COMMITTEE, "rounds": CIFAR_ROUNDS,
                "rounds_per_call": CIFAR_ROUNDS_PER_CALL,
                "eval_every": CIFAR_EVAL_EVERY,
                "samples_per_node": CIFAR_SAMPLES,
                "poison_frac": CIFAR_POISON, "attack": CIFAR_ATTACK,
                "device_kind": kind,
                "runs": runs,
                "mfu": mfu_row,
                "note": "BASELINE configs #3/#4: reference has no runnable "
                "CIFAR/robust composition to compare against",
            },
        }
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=1)


def run_wire_bench() -> None:
    """Subprocess-style mode ``--wire``: sparse delta gossip wire-bytes
    benchmark. Runs the same in-memory MNIST FedAvg federation three times —
    dense frames (``WIRE_COMPRESSION="none"``), the PR 1 sparse baseline
    (``"topk"`` with bf16 values, per-tensor frames, serialized stage
    machine) and the quantized fast path (int4 values, coalesced+DEFLATEd
    multi-tensor body, train<->diffuse overlap) — over the real
    Node/gossip/aggregator stack, and reports the bytes-per-round counter
    (model-plane TX, counted at the gossip send point, attributed per wire
    codec) next to final accuracy and the PR 6 overlap report. Prints ONE
    JSON line and stamps ``artifacts/WIRE_BENCH.json`` with the shared
    versioned meta block so ``scripts/perf_diff.py`` can gate regressions.

    Shape overrides: P2PFL_TPU_WIRE_NODES (default 8), P2PFL_TPU_WIRE_ROUNDS
    (default 3), P2PFL_TPU_WIRE_TOPK_RATIO (default 0.1),
    P2PFL_TPU_WIRE_QUANT (default "int4").
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU is the venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY, TRACER, CriticalPathAnalyzer
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_WIRE_NODES", "8"))
        rounds = int(os.environ.get("P2PFL_TPU_WIRE_ROUNDS", "3"))
        ratio = float(os.environ.get("P2PFL_TPU_WIRE_TOPK_RATIO", "0.1"))
        quant = os.environ.get("P2PFL_TPU_WIRE_QUANT", "int4")
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        # full committee: every node trains, so the dominant traffic is the
        # partial-model gossip the sparse path compresses
        Settings.TRAIN_SET_SIZE = n_nodes
        Settings.WIRE_TOPK_RATIO = ratio
        # Liveness bounds for a contended host (the critical-path bench's
        # rationale): 8 concurrent fits on few cores starve daemon threads
        # for seconds — the 1.5 s test heartbeat timeout then declares
        # healthy peers dead mid-round and the write-off/heal cycle thrashes
        # the byte counts this bench exists to measure.
        Settings.HEARTBEAT_TIMEOUT = 10.0
        Settings.VOTE_TIMEOUT = 30.0
        Settings.AGGREGATION_TIMEOUT = 120.0
        Settings.AGGREGATION_STALL_PATIENCE = 60.0

        # One SHARED apply_fn across the fleet (per-node params via
        # build_copy): one XLA program per process instead of 8
        # identity-distinct compiles whose serialized first-fit cost
        # desynchronizes round 0 into heartbeat write-offs.
        from p2pfl_tpu.learning.learner import JaxLearner

        template = mlp_model(seed=0)
        _phase("wire bench: pre-warming the shared XLA programs")
        warm_data = synthetic_mnist(n_train=256, n_test=64)
        warm_parts = warm_data.generate_partitions(1, RandomIIDPartitionStrategy)
        warm = JaxLearner(
            template.build_copy(), warm_parts[0], self_addr="mem://warmup",
            batch_size=32, seed=0,
        )
        warm.set_epochs(1)
        warm.fit()
        warm.evaluate()
        del warm

        # (scheme label, WIRE_COMPRESSION, values, coalesce, overlap)
        arms = (
            ("none", "none", "bf16", False, False),
            ("topk", "topk", "bf16", False, False),  # the PR 1 baseline, verbatim
            (f"topk-{quant}", "topk", quant, True, True),  # quant+coalesce+overlap
        )
        runs: dict = {}
        overlap_reports: dict = {}
        for label, scheme, values, coalesce, overlap in arms:
            Settings.WIRE_COMPRESSION = scheme
            Settings.WIRE_TOPK_VALUES = values
            Settings.COALESCE_ENABLED = coalesce
            Settings.OVERLAP_TRAIN_DIFFUSE = overlap
            REGISTRY.reset()
            TRACER.reset()
            _phase(f"wire bench: {n_nodes}-node federation, arm={label}")
            data = synthetic_mnist(n_train=256 * n_nodes, n_test=256)
            parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
            nodes = [
                Node(
                    template.build_copy(params=mlp_model(seed=i).get_parameters()),
                    parts[i], batch_size=32,
                )
                for i in range(n_nodes)
            ]
            t0 = time.monotonic()
            for nd in nodes:
                nd.start()
            try:
                for i in range(1, n_nodes):
                    nodes[i].connect(nodes[0].addr)
                wait_convergence(nodes, n_nodes - 1, wait=30)
                nodes[0].set_start_learning(rounds=rounds, epochs=1)
                deadline = time.time() + 900
                while time.time() < deadline:
                    if all(
                        not nd.learning_in_progress()
                        and nd.learning_workflow is not None
                        for nd in nodes
                    ):
                        break
                    time.sleep(0.25)
                else:
                    raise TimeoutError(f"{label} federation did not finish")
                wall_s = time.monotonic() - t0
                tx_bytes = sum(
                    nd.protocol.gossiper.total_tx_bytes() for nd in nodes
                )
                tx_frames = sum(
                    sum(f for f, _ in nd.protocol.gossiper.wire_stats().values())
                    for nd in nodes
                )
                by_codec: dict = {}
                for nd in nodes:
                    for codec, b in nd.protocol.gossiper.bytes_by_codec().items():
                        by_codec[codec] = by_codec.get(codec, 0) + b
                accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in nodes]
                runs[label] = {
                    "model_tx_bytes_total": int(tx_bytes),
                    "model_tx_frames": int(tx_frames),
                    "bytes_per_round": round(tx_bytes / rounds, 1),
                    "bytes_by_codec": {k: int(v) for k, v in sorted(by_codec.items())},
                    "final_test_acc_mean": round(sum(accs) / len(accs), 4),
                    "final_test_acc_min": round(min(accs), 4),
                    "wall_s": round(wall_s, 2),
                }
                _phase(f"wire bench {label}: {json.dumps(runs[label])}")
            finally:
                for nd in nodes:
                    nd.stop()
                InMemoryRegistry.reset()
            if scheme == "topk":
                try:
                    ov = CriticalPathAnalyzer.from_tracer(TRACER).overlap_report()
                    overlap_reports[label] = {
                        "train_diffuse_overlap_fraction": ov[
                            "train_diffuse_overlap_fraction"
                        ],
                        "train_diffuse_overlap_s": ov["train_diffuse_overlap_s"],
                        "serialized_diffuse_s": ov["serialized_diffuse_s"],
                        "diffuse_under_any_fit_fraction": ov.get(
                            "diffuse_under_any_fit_fraction"
                        ),
                    }
                except Exception as exc:  # noqa: BLE001 — report is advisory here
                    overlap_reports[label] = {"error": repr(exc)}
        quant_label = f"topk-{quant}"
        vs_dense = runs["none"]["bytes_per_round"] / max(
            runs[quant_label]["bytes_per_round"], 1.0
        )
        # The acceptance ratio: FURTHER reduction of the quantized+coalesced
        # arm vs the PR 1 topk baseline, on the sparse-codec bytes the new
        # encoders actually own (dense init/fallback frames ride both arms
        # identically and would otherwise floor the ratio).
        base_sparse = sum(
            b for c, b in runs["topk"]["bytes_by_codec"].items()
            if c.startswith("topk")
        )
        quant_sparse = sum(
            b for c, b in runs[quant_label]["bytes_by_codec"].items()
            if c.startswith("topk")
        )
        further_sparse = base_sparse / max(quant_sparse, 1)
        further_total = runs["topk"]["bytes_per_round"] / max(
            runs[quant_label]["bytes_per_round"], 1.0
        )
        out = {
            "metric": "wire_bytes_per_round_8node_mnist_fedavg",
            "value": runs[quant_label]["bytes_per_round"],
            "unit": "bytes/round",
            "vs_baseline": round(vs_dense, 2),
            "meta": _bench_meta(seed=0, backend="cpu"),
            "extra": {
                "nodes": n_nodes,
                "rounds": rounds,
                "topk_ratio": ratio,
                "quant": quant,
                "runs": runs,
                "further_vs_topk_sparse_bytes": round(further_sparse, 2),
                "further_vs_topk_total_bytes": round(further_total, 2),
                "overlap": overlap_reports,
                "acc_delta_pp_vs_dense": round(
                    100.0
                    * (
                        runs["none"]["final_test_acc_mean"]
                        - runs[quant_label]["final_test_acc_mean"]
                    ),
                    2,
                ),
                "acc_delta_pp_vs_topk": round(
                    100.0
                    * (
                        runs["topk"]["final_test_acc_mean"]
                        - runs[quant_label]["final_test_acc_mean"]
                    ),
                    2,
                ),
                "note": "vs_baseline = dense bytes/round over quantized "
                "bytes/round; further_vs_topk_sparse_bytes = PR 1 topk "
                "sparse-codec bytes over the int-quantized coalesced codec's "
                "(the >=3x acceptance ratio — dense init frames ride every "
                "arm identically and are excluded by the codec attribution)",
            },
        }
        os.makedirs("artifacts", exist_ok=True)
        with open(os.path.join("artifacts", "WIRE_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, backend="cpu")


def run_privacy_bench() -> None:
    """Subprocess-style mode ``--privacy``: privacy-plane acceptance run.

    Four arms over the real 8-node Node/gossip/aggregator stack (pinned
    learner seeds so the lattice pipeline is replay-comparable):

    * ``plaintext-int8`` — the PR 12 topk+quant codec (int8 + coalesce),
      the wire-overhead comparator;
    * ``masked`` — ``PRIVACY_SECAGG``: pairwise-masked lattice frames on
      the shared rand-k support;
    * ``masked-nomask`` — the IDENTICAL lattice pipeline with the pairwise
      masks zeroed (bench-local patch): the bit-exactness comparator. The
      masks cancel in modular integer arithmetic, so this arm must land at
      EXACTLY the masked arm's accuracy — the asserted 0.0 pp delta;
    * ``masked-crash`` — DP-SGD on, one committee member (seeded
      ``plan_masker_dropout`` trace) crashed mid-round-1: survivors repair
      the uncancelled mask shares and must finish sane, with a nonzero
      epsilon through the budget ledger.

    Writes ``artifacts/PRIVACY_BENCH.json`` with the shared meta block.
    Shape overrides: P2PFL_TPU_PRIVACY_NODES (default 8),
    P2PFL_TPU_PRIVACY_ROUNDS (default 4).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.chaos import CHAOS
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.learning.learner import JaxLearner
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.privacy import BUDGETS, wire_epsilon
        from p2pfl_tpu.privacy.secagg import PrivacyPlane
        from p2pfl_tpu.telemetry import REGISTRY, TRACER
        from p2pfl_tpu.telemetry.ledger import canonical_params_hash
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_PRIVACY_NODES", "8"))
        rounds = int(os.environ.get("P2PFL_TPU_PRIVACY_ROUNDS", "4"))
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        Settings.TRAIN_SET_SIZE = n_nodes  # full committee: every node masks
        Settings.PRIVACY_KEY_WAIT_S = 15.0
        # Liveness bounds for a contended host (the wire bench's rationale).
        Settings.HEARTBEAT_TIMEOUT = 10.0
        Settings.VOTE_TIMEOUT = 30.0
        Settings.AGGREGATION_TIMEOUT = 120.0
        Settings.AGGREGATION_STALL_PATIENCE = 60.0

        template = mlp_model(seed=0)
        _phase("privacy bench: pre-warming the shared XLA programs")
        warm_data = synthetic_mnist(n_train=256, n_test=64)
        warm_parts = warm_data.generate_partitions(1, RandomIIDPartitionStrategy)
        warm = JaxLearner(
            template.build_copy(), warm_parts[0], self_addr="mem://warmup",
            batch_size=32, seed=0,
        )
        warm.set_epochs(1)
        warm.fit()
        warm.evaluate()
        del warm

        data = synthetic_mnist(n_train=256 * n_nodes, n_test=256)
        parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)

        # Bench-local bit-exactness comparator: run the EXACT masked lattice
        # pipeline with the pairwise masks zeroed. Patch scope = one arm.
        _orig_mask_own = PrivacyPlane.mask_own

        def _nomask_own(self, model, anchor_leaves, round, committee, *, mask=True):
            return _orig_mask_own(
                self, model, anchor_leaves, round, committee, mask=False
            )

        runs: dict = {}

        def run_arm(label, *, secagg, nomask=False, dp=False, crash=False):
            Settings.WIRE_COMPRESSION = "topk"
            Settings.WIRE_TOPK_RATIO = 0.1
            Settings.WIRE_TOPK_VALUES = "int8"
            Settings.COALESCE_ENABLED = True
            Settings.PRIVACY_SECAGG = secagg
            Settings.PRIVACY_DP_CLIP = 8.0 if dp else 0.0
            Settings.PRIVACY_DP_SIGMA = 0.005 if dp else 0.0
            REGISTRY.reset()
            TRACER.reset()
            BUDGETS.reset()
            CHAOS.reset()
            if nomask:
                PrivacyPlane.mask_own = _nomask_own
            _phase(f"privacy bench: {n_nodes}-node federation, arm={label}")
            nodes = [
                Node(
                    template.build_copy(params=mlp_model(seed=i).get_parameters()),
                    parts[i], batch_size=32, seed=i,
                )
                for i in range(n_nodes)
            ]
            victim = None
            killed = False
            t0 = time.monotonic()
            try:
                for nd in nodes:
                    nd.start()
                for i in range(1, n_nodes):
                    nodes[i].connect(nodes[0].addr)
                wait_convergence(nodes, n_nodes - 1, wait=30)
                if crash:
                    trace = CHAOS.plan_masker_dropout(
                        rounds, [nd.addr for nd in nodes], seed=11, drop_round=1
                    )
                    victim = next(nd for nd in nodes if nd.addr == trace[0].node)
                nodes[0].set_start_learning(rounds=rounds, epochs=1)
                deadline = time.time() + 900
                while time.time() < deadline:
                    if victim is not None and not killed:
                        if (victim.state.round or 0) >= 1:
                            time.sleep(0.5)
                            victim.crash()
                            CHAOS.recovery(victim.addr, "crash")
                            killed = True
                    live = [nd for nd in nodes if nd is not victim or not killed]
                    if all(
                        not nd.learning_in_progress()
                        and nd.learning_workflow is not None
                        for nd in live
                    ):
                        break
                    time.sleep(0.25)
                else:
                    raise TimeoutError(f"{label} federation did not finish")
                wall_s = time.monotonic() - t0
                live = [nd for nd in nodes if nd is not victim or not killed]
                by_codec: dict = {}
                for nd in nodes:
                    for codec, b in nd.protocol.gossiper.bytes_by_codec().items():
                        by_codec[codec] = by_codec.get(codec, 0) + b
                accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in live]
                repairs = 0
                fam = REGISTRY.get("p2pfl_privacy_repairs_total")
                if fam is not None:
                    repairs = sum(
                        int(c.value) for lbl, c in fam.samples()
                        if lbl.get("role") == "applied"
                    )
                masked_ok = 0
                fam = REGISTRY.get("p2pfl_privacy_masked_rounds_total")
                if fam is not None:
                    masked_ok = sum(
                        int(c.value) for lbl, c in fam.samples()
                        if lbl.get("outcome") == "ok"
                    )
                runs[label] = {
                    "bytes_by_codec": {k: int(v) for k, v in sorted(by_codec.items())},
                    "final_test_acc_mean": round(sum(accs) / len(accs), 6),
                    "final_test_acc_min": round(min(accs), 6),
                    "params_hash_node0": canonical_params_hash(
                        live[0].learner.get_model().get_parameters()
                    ),
                    "masked_rounds_ok": masked_ok,
                    "mask_repairs_applied": repairs,
                    "dp_epsilon": wire_epsilon(
                        max(BUDGETS.epsilon(nd.addr) for nd in live)
                    ) if dp else None,
                    "killed": bool(killed),
                    "wall_s": round(wall_s, 2),
                }
                _phase(f"privacy bench {label}: {json.dumps(runs[label])}")
            finally:
                PrivacyPlane.mask_own = _orig_mask_own
                for nd in nodes:
                    try:
                        nd.stop()
                    except Exception:  # noqa: BLE001 — crashed victim
                        pass
                InMemoryRegistry.reset()
                CHAOS.reset()

        run_arm("plaintext-int8", secagg=False)
        run_arm("masked", secagg=True)
        run_arm("masked-nomask", secagg=True, nomask=True)
        run_arm("masked-crash", secagg=True, dp=True, crash=True)

        # Acceptance 1: bit-exact masked FedAvg at zero dropout — 0.0 pp
        # accuracy delta between the masked arm and its maskless twin.
        bitexact_pp = 100.0 * abs(
            runs["masked"]["final_test_acc_mean"]
            - runs["masked-nomask"]["final_test_acc_mean"]
        )
        if bitexact_pp != 0.0:
            raise AssertionError(
                f"masked vs maskless accuracy delta {bitexact_pp} pp != 0.0"
            )
        # Acceptance 2: <=15% wire overhead on top of the topk+quant codec.
        topk_sparse = sum(
            b for c, b in runs["plaintext-int8"]["bytes_by_codec"].items()
            if c.startswith("topk")
        )
        masked_sparse = runs["masked"]["bytes_by_codec"].get("masked", 0)
        overhead = masked_sparse / max(topk_sparse, 1)
        if overhead > 1.15:
            raise AssertionError(
                f"masked wire bytes {masked_sparse} are {overhead:.2f}x the "
                f"topk+quant codec's {topk_sparse} (bound 1.15x)"
            )
        # Acceptance 3: the crash arm survived with a live DP budget. The
        # crash is a wall-clock race (the victim may die before OR after its
        # round-1 frame lands anywhere), so survivor accuracy is bounded
        # against the plaintext arm rather than asserted equal.
        crash = runs["masked-crash"]
        if not crash["killed"]:
            raise AssertionError("crash arm never killed its masker")
        if crash["final_test_acc_mean"] < runs["plaintext-int8"][
            "final_test_acc_mean"
        ] - 0.25:
            raise AssertionError(
                f"crash-arm accuracy {crash['final_test_acc_mean']} collapsed"
            )
        if not (crash["dp_epsilon"] or 0) > 0:
            raise AssertionError(f"crash arm epsilon {crash['dp_epsilon']}")
        out = {
            "metric": "privacy_masked_wire_overhead_vs_topk_quant",
            "value": round(overhead, 4),
            "unit": "x",
            "vs_baseline": round(overhead, 4),
            "meta": _bench_meta(seed=0, backend="cpu"),
            "extra": {
                "nodes": n_nodes,
                "rounds": rounds,
                "runs": runs,
                "bitexact_acc_delta_pp": bitexact_pp,
                "bitexact_params_hash_match": (
                    runs["masked"]["params_hash_node0"]
                    == runs["masked-nomask"]["params_hash_node0"]
                ),
                "masked_sparse_bytes": int(masked_sparse),
                "topk_quant_sparse_bytes": int(topk_sparse),
                "acc_delta_pp_vs_plaintext": round(
                    100.0
                    * (
                        runs["plaintext-int8"]["final_test_acc_mean"]
                        - runs["masked"]["final_test_acc_mean"]
                    ),
                    2,
                ),
                "note": "value = masked lattice frame bytes over the PR 12 "
                "topk-int8+coalesce sparse bytes at the same ratio (<=1.15 "
                "acceptance); bitexact_acc_delta_pp compares the masked arm "
                "against the identical pipeline with masks zeroed (must be "
                "exactly 0.0 — modular mask cancellation is exact, not "
                "float-approximate)",
            },
        }
        os.makedirs("artifacts", exist_ok=True)
        with open(os.path.join("artifacts", "PRIVACY_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, backend="cpu")


def run_parity_bench() -> None:
    """Subprocess-style mode ``--parity``: sim↔real parity acceptance run.

    One seeded scenario — a 5% chaos drop trace, one 1s-straggler, one
    signflip adversary — runs on BOTH execution backends at n=8: the real
    wire (in-memory transport, full Node/gossip/admission stack, the shared
    parity learner kernel) and the fused mesh (MeshSimulation,
    ``canonical_committee=True``). Both emit the canonical trajectory
    ledger; the gate asserts

    * every wire node's per-round aggregate hashes agree (intra-backend),
    * ``parity_diff`` aligns the wire ledger against the mesh ledger with
      ZERO divergence and bit-exact aggregate hashes (cross-backend),
    * a single perturbed event in a copied mesh ledger is localized by
      ``parity_diff`` to exactly that event (negative control).

    Writes ``artifacts/ledger_*.jsonl`` (all nine ledgers),
    ``artifacts/parity_diff.json`` (the OK report ``fed_top`` banners), and
    ``artifacts/PARITY_BENCH.json`` with both backends' ledger digests.
    Prints ONE JSON line. Shape overrides: P2PFL_TPU_PARITY_SEED (config-
    validated); nodes/rounds are pinned at 8/3 for this acceptance arm.
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import hashlib
        import importlib.util

        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.parity import ParityScenario, run_fused, run_wire

        spec = importlib.util.spec_from_file_location(
            "parity_diff", os.path.join(REPO, "scripts", "parity_diff.py")
        )
        parity_diff = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(parity_diff)

        seed = Settings.PARITY_SEED
        scn = ParityScenario(
            seed=seed, n_nodes=8, rounds=3, samples_per_node=64,
            batch_size=16, hidden=(32,),
            byzantine={6: "signflip"}, straggler={5: 1.0}, drop_rate=0.05,
        )
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)

        _phase(f"parity bench: wire arm (n=8, drop 5%, straggler, signflip; seed {seed})")
        t0 = time.monotonic()
        wire = run_wire(scn, ledger_dir=art)
        wire_s = time.monotonic() - t0
        _phase(f"parity bench: wire arm done in {wire_s:.1f}s; fused arm")
        t0 = time.monotonic()
        fused = run_fused(scn, ledger_dir=art)
        fused_s = time.monotonic() - t0

        names = scn.node_names
        # Intra-backend: every wire node committed the same bits per round.
        ref_hashes = wire["hashes"][names[0]]
        assert len(ref_hashes) == scn.rounds, (
            f"wire node0 committed {sorted(ref_hashes)} of {scn.rounds} rounds"
        )
        for n in names:
            assert wire["hashes"][n] == ref_hashes, (
                f"wire nodes disagree: {n} committed {wire['hashes'][n]}, "
                f"{names[0]} committed {ref_hashes}"
            )

        # Cross-backend: ledger alignment + bit-exact hashes.
        wire_path = wire["ledgers"][names[0]]
        mesh_path = fused["ledger"]
        rc = parity_diff.main(
            [wire_path, mesh_path, "--out", os.path.join(art, "parity_diff.json")]
        )
        with open(os.path.join(art, "parity_diff.json")) as f:
            report = json.load(f)
        assert rc == 0 and report["status"] == "OK", (
            f"parity DIVERGED: {json.dumps(report.get('first_divergence'))}"
        )
        assert report["hashes_compared"] == scn.rounds, (
            f"only {report['hashes_compared']} of {scn.rounds} aggregate "
            "hashes were bit-compared"
        )

        # Negative control: a single perturbed event must be localized
        # exactly (not merely "something differs somewhere").
        perturb_round = 1
        perturbed = os.path.join(art, "ledger_mesh-sim.perturbed.jsonl")
        with open(mesh_path) as f, open(perturbed, "w") as g:
            for line in f:
                doc = json.loads(line)
                if (
                    doc.get("kind") == "aggregate_committed"
                    and doc.get("round") == perturb_round
                ):
                    doc["hash"] = "sha256:" + "0" * 64
                g.write(json.dumps(doc, sort_keys=True, separators=(",", ":")) + "\n")
        neg = parity_diff.compare_ledgers(
            parity_diff.read_ledger(wire_path)[1],
            parity_diff.read_ledger(perturbed)[1],
        )
        fd = neg["first_divergence"]
        assert neg["status"] == "DIVERGED" and fd is not None, (
            "negative control not detected"
        )
        assert (
            fd["a"]["kind"] == "aggregate_committed"
            and fd["a"]["round"] == perturb_round
            and "hash differs" in fd["problem"]
        ), f"negative control localized wrong event: {json.dumps(fd)}"

        def _digest(path: str) -> str:
            with open(path, "rb") as f:
                return "sha256:" + hashlib.sha256(f.read()).hexdigest()

        out = {
            "metric": "parity_events_aligned_8node_wire_vs_fused",
            "value": report["compared_events"],
            "unit": "events",
            "vs_baseline": None,
            "extra": {
                "nodes": scn.n_nodes,
                "rounds": scn.rounds,
                "scenario": {
                    "seed": seed, "drop_rate": scn.drop_rate,
                    "byzantine": {str(k): v for k, v in scn.byzantine.items()},
                    "straggler": {str(k): v for k, v in scn.straggler.items()},
                },
                "aggregate_hashes": {str(r): h for r, h in sorted(ref_hashes.items())},
                "hashes_bit_exact": True,
                "ledger_digests": {
                    "wire_node0": _digest(wire_path),
                    "mesh": _digest(mesh_path),
                },
                "negative_control": {
                    "perturbed_round": perturb_round,
                    "localized_kind": fd["a"]["kind"],
                    "localized_round": fd["a"]["round"],
                },
                "wall_s": {"wire": round(wire_s, 1), "fused": round(fused_s, 1)},
                "note": "same seeded scenario on the real wire (n=8) and the "
                "fused mesh (n=8): trajectories align event-for-event and "
                "round aggregates are bit-exact (canonical kernel + "
                "reduction order; docs/components/parity.md)",
            },
        }
        with open(os.path.join(art, "PARITY_BENCH.json"), "w") as f:
            json.dump(
                {**out, "meta": _bench_meta(seed=seed, backend="cpu")},
                f, indent=1,
            )
        _phase("parity bench: PASS")
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=Settings.PARITY_SEED if "Settings" in dir() else None, backend="cpu")


def run_chaos_bench() -> None:
    """Subprocess-style mode ``--chaos``: round-survival acceptance run.

    Runs the same 8-node in-memory MNIST FedAvg federation twice over the
    real Node/gossip/aggregator stack — a fault-free baseline, then a chaos
    run with 10% seeded message drop plus ONE trainset member crashed
    mid-round — and asserts the hardening contract:

    * the survivors complete every round (no stage sleeps out its fixed
      timeout waiting on the dead peer),
    * final mean accuracy lands within 2pp of the fault-free run,
    * no stage wait exceeds its configured deadline (vote_rtt vs
      VOTE_TIMEOUT, aggregation_wait / full_model_wait vs
      AGGREGATION_TIMEOUT — measured from the round tracer's spans),
    * fault injection is deterministic: the same seed replayed through a
      fresh chaos plane yields identical injected-fault counts.

    Shape overrides: P2PFL_TPU_CHAOS_BENCH_NODES (default 8),
    P2PFL_TPU_CHAOS_BENCH_ROUNDS (default 3), P2PFL_TPU_CHAOS_BENCH_DROP
    (default 0.1), P2PFL_TPU_CHAOS_BENCH_SEED (default 42).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.chaos import CHAOS, ChaosPlane
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY, TRACER
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_CHAOS_BENCH_NODES", "8"))
        rounds = int(os.environ.get("P2PFL_TPU_CHAOS_BENCH_ROUNDS", "3"))
        drop = float(os.environ.get("P2PFL_TPU_CHAOS_BENCH_DROP", "0.1"))
        seed = int(os.environ.get("P2PFL_TPU_CHAOS_BENCH_SEED", "42"))
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        Settings.TRAIN_SET_SIZE = max(2, n_nodes // 2)  # crash stays survivable

        # Stage-wait deadlines asserted against the tracer's span durations.
        wait_deadlines = {
            # vote_rtt spans cast + ballot wait; its wait loop overshoots the
            # vote deadline by at most one 0.5s slice + tally work.
            "vote_rtt": Settings.VOTE_TIMEOUT + 3.0,
            "aggregation_wait": Settings.AGGREGATION_TIMEOUT,
            "full_model_wait": Settings.AGGREGATION_TIMEOUT,
        }

        def run_leg(chaotic: bool) -> dict:
            REGISTRY.reset()
            TRACER.reset()
            CHAOS.reset()
            data = synthetic_mnist(n_train=256 * n_nodes, n_test=256)
            parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
            nodes = [
                Node(mlp_model(seed=i), parts[i], batch_size=32)
                for i in range(n_nodes)
            ]
            by_addr = {nd.addr: nd for nd in nodes}
            for nd in nodes:
                nd.start()
            victim = None
            try:
                import contextlib

                scope = (
                    CHAOS.overridden(drop_rate=drop, seed=seed)
                    if chaotic
                    else contextlib.nullcontext()
                )
                with scope:
                    for i in range(1, n_nodes):
                        nodes[i].connect(nodes[0].addr)
                    wait_convergence(nodes, n_nodes - 1, wait=30)
                    t0 = time.monotonic()
                    nodes[0].set_start_learning(rounds=rounds, epochs=1)
                    deadline = time.time() + 900
                    while time.time() < deadline:
                        state0 = nodes[0].state
                        if (
                            chaotic
                            and victim is None
                            and state0.round == 1
                            and state0.train_set
                        ):
                            # Crash one NON-initiator trainset member while
                            # round 1 is mid-flight.
                            for addr in state0.train_set:
                                if addr != nodes[0].addr and addr in by_addr:
                                    victim = by_addr[addr]
                                    break
                            victim = victim or nodes[-1]
                            _phase(f"chaos: crashing {victim.addr} mid-round 1")
                            victim.crash()
                        survivors = [nd for nd in nodes if nd is not victim]
                        if all(
                            not nd.learning_in_progress()
                            and nd.learning_workflow is not None
                            for nd in survivors
                        ):
                            break
                        time.sleep(0.25)
                    else:
                        raise TimeoutError(
                            f"{'chaos' if chaotic else 'baseline'} federation "
                            "did not finish"
                        )
                    wall_s = time.monotonic() - t0
                    faults = CHAOS.fault_counts()  # before scope exit resets
                survivors = [nd for nd in nodes if nd is not victim]
                incomplete = {
                    nd.addr: nd.learning_workflow.history.count("RoundFinishedStage")
                    for nd in survivors
                    if nd.learning_workflow.history.count("RoundFinishedStage")
                    != rounds
                }
                if incomplete:
                    raise AssertionError(
                        f"survivors did not complete all {rounds} rounds: "
                        f"{incomplete}"
                    )
                accs = [
                    nd.learner.evaluate().get("test_acc", 0.0) for nd in survivors
                ]
                wait_max = {name: 0.0 for name in wait_deadlines}
                for s in TRACER.spans():
                    if s.name in wait_max:
                        wait_max[s.name] = max(wait_max[s.name], s.dur_s)
                over = {
                    name: (m, wait_deadlines[name])
                    for name, m in wait_max.items()
                    if m >= wait_deadlines[name]
                }
                if over:
                    raise AssertionError(
                        f"stage wait exceeded its deadline: {over}"
                    )
                return {
                    "wall_s": round(wall_s, 2),
                    "final_test_acc_mean": round(sum(accs) / len(accs), 4),
                    "final_test_acc_min": round(min(accs), 4),
                    "survivors": len(survivors),
                    "crashed": victim.addr if victim is not None else None,
                    "max_wait_s": {k: round(v, 3) for k, v in wait_max.items()},
                    "injected_faults": faults if chaotic else {},
                }
            finally:
                for nd in nodes:
                    nd.stop()
                InMemoryRegistry.reset()

        _phase(f"chaos bench: {n_nodes}-node baseline (fault-free)")
        baseline = run_leg(chaotic=False)
        _phase(f"baseline done: {json.dumps(baseline)}")
        _phase(
            f"chaos bench: {n_nodes}-node chaos leg "
            f"(drop={drop}, 1 mid-round crash, seed={seed})"
        )
        chaos = run_leg(chaotic=True)
        _phase(f"chaos leg done: {json.dumps(chaos)}")

        acc_delta_pp = round(
            100.0 * (baseline["final_test_acc_mean"] - chaos["final_test_acc_mean"]),
            2,
        )
        if acc_delta_pp > 2.0:
            raise AssertionError(
                f"chaos accuracy degraded {acc_delta_pp}pp > 2pp tolerance "
                f"(baseline {baseline['final_test_acc_mean']}, "
                f"chaos {chaos['final_test_acc_mean']})"
            )

        # Determinism: the same seed replayed through fresh planes must give
        # identical injected-fault counts (per-pair decision streams are pure
        # functions of (seed, pair, sequence index)).
        from p2pfl_tpu.config import Settings as S

        replay_pairs = [(f"n{i}", f"n{j}") for i in range(4) for j in range(4) if i != j]
        counts = []
        for _ in range(2):
            plane = ChaosPlane()
            with S.overridden(
                CHAOS_ENABLED=True, CHAOS_SEED=seed, CHAOS_DROP_RATE=drop
            ):
                for _ in range(500):
                    for pair in replay_pairs:
                        plane.intercept(*pair)
            counts.append(plane.fault_counts())
        if counts[0] != counts[1]:
            raise AssertionError(f"fault injection not deterministic: {counts}")

        out = {
            "metric": "chaos_round_survival_8node_mnist_fedavg",
            "value": acc_delta_pp,
            "unit": "pp_acc_delta_vs_fault_free",
            "vs_baseline": None,
            "extra": {
                "nodes": n_nodes,
                "rounds": rounds,
                "drop_rate": drop,
                "seed": seed,
                "baseline": baseline,
                "chaos": chaos,
                "deterministic_replay_counts": counts[0],
                "wait_deadlines_s": wait_deadlines,
                "note": "chaos leg: seeded 10% message drop + 1 trainset "
                "member crashed mid-round; survivors must finish all rounds "
                "with every stage wait under its deadline",
            },
        }
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_recovery_bench() -> None:
    """Subprocess-style mode ``--recovery``: durable-recovery acceptance run.

    Five arms over the real Node/gossip/aggregator stack (8-node in-memory
    MNIST FedAvg, full committees, per-node write-ahead journals):

    * **baseline** — fault-free run (the accuracy/wall yardstick);
    * **crash_restart** — one seeded trainset member crashed mid-round, then
      RESUMED from its journal as the same address: it must re-enter the
      stage machine, contribute within 2 rounds of the resume, and the
      federation must finish at 0.0 pp accuracy delta vs baseline;
    * **partition_heal** — a seeded 4|4 partition held for ~2 rounds, then
      healed: the halves must re-discover each other (heal probes), exchange
      reconcile pings, catch the behind half up (dense round-anchor
      catch-up when a half leads), and converge to ONE federation at 0.0 pp;
    * **quorum_park** — the same 4|4 split with RECOVERY_QUORUM_FRACTION
      set so neither half has quorum: every node must PARK (no vote
      progress, state journaled) instead of burning vote timeouts, unpark on
      heal, and still finish all rounds at 0.0 pp;
    * **async_partition_heal** — the 4|4 split under the async scheduler:
      windows keep closing in both halves and the heal merges both halves'
      contributions through the staleness-weighted buffer.

    Determinism: the seeded recovery trace replays identically
    (plan_recovery is a pure function of the seed) and a fresh chaos plane
    replaying the same intercept+recovery sequence yields identical fault
    counts. Artifact: ``artifacts/RECOVERY_BENCH.json``.

    Shape overrides: P2PFL_TPU_RECOVERY_BENCH_NODES (default 8),
    P2PFL_TPU_RECOVERY_BENCH_ROUNDS (default 5),
    P2PFL_TPU_RECOVERY_BENCH_SEED (default 42).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        import contextlib
        import tempfile

        from p2pfl_tpu.chaos import CHAOS, ChaosPlane
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.management.checkpoint import NodeJournal, attach_node_journal
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_RECOVERY_BENCH_NODES", "8"))
        rounds = int(os.environ.get("P2PFL_TPU_RECOVERY_BENCH_ROUNDS", "5"))
        seed = int(os.environ.get("P2PFL_TPU_RECOVERY_BENCH_SEED", "42"))
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        Settings.TRAIN_SET_SIZE = n_nodes  # full committee: victims train

        def metric_sum(name: str) -> float:
            fam = REGISTRY.get(name)
            return sum(c.value for _, c in fam.samples()) if fam else 0.0

        def metric_by_label(name: str) -> dict:
            fam = REGISTRY.get(name)
            if fam is None:
                return {}
            agg: dict = {}
            for labels, child in fam.samples():
                key = labels.get("role") or labels.get("fault") or labels.get("node")
                agg[key] = agg.get(key, 0.0) + child.value
            return agg

        def run_leg(kind: str, mode: str = "sync", quorum: float = 0.0) -> dict:
            REGISTRY.reset()
            CHAOS.reset()
            data = synthetic_mnist(n_train=256 * n_nodes, n_test=256)
            parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
            nodes = [
                Node(mlp_model(seed=i), parts[i], batch_size=32)
                for i in range(n_nodes)
            ]
            tmpdir = tempfile.mkdtemp(prefix=f"recovery-bench-{kind}-")
            journals = [
                NodeJournal(os.path.join(tmpdir, f"j{i}")) for i in range(n_nodes)
            ]
            by_addr = {nd.addr: nd for nd in nodes}
            for nd, journal in zip(nodes, journals):
                attach_node_journal(nd, journal)
                nd.start()
            addrs = [nd.addr for nd in nodes]
            plan = CHAOS.plan_recovery(
                rounds, addrs, seed=seed,
                crash_round=(1 if kind == "crash_restart" else None),
                restart_after=1,
                partition_round=(1 if kind != "crash_restart" else None),
                heal_after=2,
            ) if kind != "baseline" else ()
            victim_addr = next((e.node for e in plan if e.kind == "crash"), None)
            part_groups = next(
                (e.groups for e in plan if e.kind == "partition"), None
            )
            crashed = healed = False
            part_base = None
            full_park_at = None
            resumed_node = None
            resume_round = None
            contributed_round = [None]
            quorum_scope = (
                Settings.overridden(RECOVERY_QUORUM_FRACTION=quorum)
                if quorum > 0.0
                else contextlib.nullcontext()
            )
            try:
                with quorum_scope:
                    for i in range(1, n_nodes):
                        nodes[i].connect(nodes[0].addr)
                    wait_convergence(nodes, n_nodes - 1, wait=30)
                    t0 = time.monotonic()
                    nodes[0].set_start_learning(rounds=rounds, epochs=1, mode=mode)
                    observer = nodes[0]
                    deadline = time.time() + 900
                    while time.time() < deadline:
                        r0 = observer.state.round or 0
                        if (
                            victim_addr is not None
                            and not crashed
                            and by_addr[victim_addr].recovery_journal is not None
                            and journals[addrs.index(victim_addr)].all_steps()
                        ):
                            victim = by_addr[victim_addr]
                            _phase(f"recovery: crashing {victim_addr} mid-round {victim.state.round}")
                            victim.crash()
                            CHAOS.recovery(victim_addr, "crash")
                            journal = journals[addrs.index(victim_addr)]
                            journal.wait()
                            resumed_node = Node.resume(
                                mlp_model(seed=1000),
                                parts[addrs.index(victim_addr)],
                                journal, batch_size=32,
                            )
                            assert resumed_node.addr == victim_addr
                            resumed_node.start()
                            resumed_node.resume_learning()
                            resume_round = resumed_node.state.round or 0
                            CHAOS.recovery(victim_addr, "restart")
                            nodes[addrs.index(victim_addr)] = resumed_node
                            by_addr[victim_addr] = resumed_node
                            _phase(
                                f"recovery: resumed {victim_addr} at round {resume_round}"
                            )
                            crashed = True
                        if part_groups is not None and not healed:
                            if part_base is None:
                                if r0 >= 1 and observer.learning_in_progress():
                                    _phase(
                                        f"recovery: partitioning "
                                        f"{len(part_groups[0])}|{len(part_groups[1])} "
                                        f"at round {r0}"
                                    )
                                    CHAOS.partition(*part_groups)
                                    CHAOS.recovery("fleet", "partition")
                                    part_base = r0
                            else:
                                # quorum arm: rounds stop advancing once the
                                # fleet parks — heal a beat after everyone is
                                # parked rather than on round progress.
                                parked_now = sum(
                                    1 for nd in nodes if nd.state.parked
                                )
                                if quorum > 0.0 and parked_now >= n_nodes - 1:
                                    full_park_at = full_park_at or time.monotonic()
                                heal_due = (
                                    r0 >= part_base + 2
                                    or (
                                        full_park_at is not None
                                        and time.monotonic() - full_park_at > 2.0
                                    )
                                    or not observer.learning_in_progress()
                                )
                                if heal_due:
                                    _phase(f"recovery: healing at round {r0}")
                                    CHAOS.heal()
                                    CHAOS.recovery("fleet", "heal")
                                    healed = True
                        # track the resumed identity's first post-resume
                        # appearance in a SURVIVOR's aggregation progress
                        if resumed_node is not None and contributed_round[0] is None:
                            watcher = next(
                                nd for nd in nodes if nd.addr != victim_addr
                            )
                            for peer, merged in list(
                                watcher.state.models_aggregated.items()
                            ):
                                if peer != victim_addr and victim_addr in merged:
                                    contributed_round[0] = watcher.state.round
                                    break
                        if all(
                            not nd.learning_in_progress()
                            and nd.learning_workflow is not None
                            for nd in nodes
                        ):
                            break
                        time.sleep(0.1)
                    else:
                        raise TimeoutError(
                            f"{kind} federation did not finish "
                            f"(stages: {({nd.addr: nd.state.current_stage for nd in nodes})})"
                        )
                    wall_s = time.monotonic() - t0
                    if part_groups is not None and not healed:
                        CHAOS.heal()
                    faults = CHAOS.fault_counts()
                accs = [
                    nd.learner.evaluate().get("test_acc", 0.0) for nd in nodes
                ]
                leg = {
                    "wall_s": round(wall_s, 2),
                    "final_test_acc_mean": round(sum(accs) / len(accs), 4),
                    "final_test_acc_min": round(min(accs), 4),
                    "final_test_acc_max": round(max(accs), 4),
                    "rounds_finished": [
                        nd.learning_workflow.history.count("RoundFinishedStage")
                        + nd.learning_workflow.history.count("AsyncWindowFinishedStage")
                        for nd in nodes
                    ],
                    "journal_saves": metric_sum("p2pfl_recovery_journal_saves_total"),
                    "injected_faults": faults,
                    "recovery_events_executed": int(faults.get("recovery", 0)),
                    "planned_events": [
                        {"when": e.when, "kind": e.kind, "node": e.node}
                        for e in plan
                    ],
                }
                if kind == "crash_restart":
                    leg.update(
                        {
                            "victim": victim_addr,
                            "resumed_same_identity": resumed_node is not None
                            and resumed_node.addr == victim_addr,
                            "resume_round": resume_round,
                            "contributed_round": contributed_round[0],
                            "resumes": metric_sum("p2pfl_recovery_resumes_total"),
                            "resumed_history_head": (
                                resumed_node.learning_workflow.history[:6]
                                if resumed_node is not None
                                and resumed_node.learning_workflow is not None
                                else []
                            ),
                        }
                    )
                if part_groups is not None:
                    leg.update(
                        {
                            "heals_detected": metric_sum("p2pfl_recovery_heals_total"),
                            "reconcile": metric_by_label(
                                "p2pfl_recovery_reconcile_total"
                            ),
                        }
                    )
                if quorum > 0.0:
                    leg.update(
                        {
                            "parks": metric_sum("p2pfl_recovery_parks_total"),
                            "parked_seconds": round(
                                metric_sum("p2pfl_recovery_parked_seconds_total"), 2
                            ),
                        }
                    )
                return leg
            finally:
                for nd in nodes:
                    nd.stop()
                if resumed_node is not None:
                    resumed_node.stop()
                for journal in journals:
                    try:
                        journal.close()
                    except Exception:  # noqa: BLE001
                        pass
                InMemoryRegistry.reset()
                CHAOS.reset()

        _phase(f"recovery bench: {n_nodes}-node baseline (fault-free)")
        baseline = run_leg("baseline")
        _phase(f"baseline done: {json.dumps(baseline)}")

        _phase("recovery bench: crash_restart arm")
        crash_leg = run_leg("crash_restart")
        _phase(f"crash_restart done: {json.dumps(crash_leg)}")

        _phase("recovery bench: partition_heal arm (4|4, split-brain)")
        part_leg = run_leg("partition_heal")
        _phase(f"partition_heal done: {json.dumps(part_leg)}")

        _phase("recovery bench: quorum_park arm (4|4 below quorum)")
        quorum_leg = run_leg("quorum_park", quorum=0.6)
        _phase(f"quorum_park done: {json.dumps(quorum_leg)}")

        _phase("recovery bench: async partition_heal arm")
        async_leg = run_leg("async_partition_heal", mode="async")
        _phase(f"async_partition_heal done: {json.dumps(async_leg)}")

        # --- acceptance assertions ---------------------------------------
        base_acc = baseline["final_test_acc_mean"]
        deltas = {
            name: round(100.0 * (base_acc - leg["final_test_acc_mean"]), 2)
            for name, leg in (
                ("crash_restart", crash_leg),
                ("partition_heal", part_leg),
                ("quorum_park", quorum_leg),
                ("async_partition_heal", async_leg),
            )
        }
        worst_delta = max(deltas.values())
        if worst_delta > 0.0:
            raise AssertionError(
                f"recovery arm degraded accuracy vs fault-free baseline: "
                f"{deltas} (baseline {base_acc})"
            )
        if not crash_leg["resumed_same_identity"]:
            raise AssertionError("crash_restart: identity not restored from journal")
        if crash_leg["contributed_round"] is None or (
            crash_leg["contributed_round"] - crash_leg["resume_round"] > 2
        ):
            raise AssertionError(
                f"crash_restart: resumed node did not contribute within 2 "
                f"rounds (resumed at {crash_leg['resume_round']}, first seen "
                f"at {crash_leg['contributed_round']})"
            )
        if part_leg["heals_detected"] < 2:
            raise AssertionError(
                f"partition_heal: heal detections missing: {part_leg}"
            )
        if part_leg["final_test_acc_min"] != part_leg["final_test_acc_max"]:
            raise AssertionError(
                f"partition_heal: halves did not converge to one model: "
                f"{part_leg}"
            )
        if quorum_leg["parks"] < n_nodes:
            raise AssertionError(
                f"quorum_park: expected every node to park below quorum, got "
                f"{quorum_leg['parks']}"
            )

        # --- determinism ---------------------------------------------------
        plan_a = ChaosPlane().plan_recovery(
            rounds, [f"n{i}" for i in range(n_nodes)], seed=seed,
            crash_round=1, partition_round=1, heal_after=2,
        )
        plan_b = ChaosPlane().plan_recovery(
            rounds, [f"n{i}" for i in range(n_nodes)], seed=seed,
            crash_round=1, partition_round=1, heal_after=2,
        )
        if plan_a != plan_b:
            raise AssertionError("plan_recovery is not deterministic")
        replay_counts = []
        for _ in range(2):
            plane = ChaosPlane()
            with Settings.overridden(CHAOS_ENABLED=True, CHAOS_SEED=seed):
                plane.partition([f"n{i}" for i in range(4)],
                                [f"n{i}" for i in range(4, 8)])
                for e in plan_a:
                    plane.recovery(e.node or "fleet", e.kind)
                for i in range(4):
                    for j in range(4, 8):
                        plane.intercept(f"n{i}", f"n{j}")
            replay_counts.append(plane.fault_counts())
        if replay_counts[0] != replay_counts[1]:
            raise AssertionError(
                f"recovery fault replay not deterministic: {replay_counts}"
            )

        out = {
            "metric": "recovery_durable_8node_mnist_fedavg",
            "value": worst_delta,
            "unit": "worst_pp_acc_delta_vs_fault_free",
            "vs_baseline": None,
            "extra": {
                "nodes": n_nodes,
                "rounds": rounds,
                "seed": seed,
                "baseline": baseline,
                "crash_restart": crash_leg,
                "partition_heal": part_leg,
                "quorum_park": quorum_leg,
                "async_partition_heal": async_leg,
                "acc_delta_pp": deltas,
                "deterministic_replay_counts": replay_counts[0],
                "note": "crash-restarted node resumes its own identity from "
                "the write-ahead journal and contributes within 2 rounds; a "
                "healed 4|4 partition reconciles to one model; below-quorum "
                "halves park instead of burning vote timeouts; the seeded "
                "recovery trace replays deterministically",
            },
        }
        os.makedirs("artifacts", exist_ok=True)
        with open(os.path.join("artifacts", "RECOVERY_BENCH.json"), "w") as f:
            json.dump({**out, "meta": _bench_meta(seed=seed, backend="cpu")}, f, indent=1)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_async_bench() -> None:
    """Subprocess-style mode ``--async``: elastic async federation acceptance.

    Four arms over the real Node/gossip/aggregator stack (8-node in-memory
    MNIST FedAvg, full-participation committees so the sync barrier is set
    by the slowest trainer — the fair comparison):

    * **straggler throughput** — one 5x-slow peer (fit stretched to 5x the
      fast floor). Sync rounds block on it; async windows close on the
      buffer fill target. Contract: fleet round/window throughput (completed
      rounds-or-windows across all nodes per wall second) of async >= 3x
      sync, at equal final accuracy (<= 0.5 pp delta on the fast nodes).
    * **churn** — a seeded per-window join/leave trace from the chaos plane
      (``CHAOS.plan_churn``; executed events counted as fault "churn"):
      async finishes every window on all surviving original nodes and every
      joiner (cold full-model catch-up bootstrap) contributes within 2
      windows; the SAME trace under sync demonstrably stalls — joiners have
      no entry path, win committee votes, and burn the vote timeout every
      round (or rounds are abandoned outright within the wall budget).
    * **Byzantine** — 2 signflip adversaries under async: admission control
      screens every async contribution exactly as it screens sync partials;
      honest accuracy holds 0.0 pp vs the clean async leg.

    Results + the shared versioned meta block + structured perf section land
    in ``artifacts/ASYNC_BENCH.json``.

    Shape overrides: P2PFL_TPU_ASYNC_BENCH_NODES (default 8),
    P2PFL_TPU_ASYNC_BENCH_ROUNDS (default 3), P2PFL_TPU_ASYNC_BENCH_SLOW
    (default 5.0), P2PFL_TPU_ASYNC_BENCH_SEED (default 42).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.chaos import CHAOS
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.management.profiler import perf_section
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY, TRACER
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_ASYNC_BENCH_NODES", "8"))
        # 5 units amortize the one-time window-0 alignment ramp (nodes enter
        # window 0 staggered by the init-model diffusion) over the steady
        # state the contrast is about: steady async windows run at the fit
        # floor + epsilon, sync rounds at the straggler floor + overhead.
        rounds = int(os.environ.get("P2PFL_TPU_ASYNC_BENCH_ROUNDS", "5"))
        slow_x = float(os.environ.get("P2PFL_TPU_ASYNC_BENCH_SLOW", "5.0"))
        seed = int(os.environ.get("P2PFL_TPU_ASYNC_BENCH_SEED", "42"))
        fast_floor_s = 4.0  # deterministic fit floor; straggler = slow_x * this

        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        # Full participation: every node trains every round, so the sync
        # barrier is set by the slowest trainer in EVERY round (not only the
        # rounds that elect it) — apples-to-apples with async, where every
        # node trains every window.
        Settings.TRAIN_SET_SIZE = n_nodes
        Settings.ASYNC_WINDOW_TIMEOUT = 20.0
        # Inline fits: the shared learner executor sizes itself from
        # cpu_count (2 workers on the 1-core CI box) and would serialize the
        # sleep-floor fits in pairs — pacing BOTH schedulers with pool
        # capacity instead of the straggle being measured. The floors are
        # sleeps; inline fits on the stage threads overlap them fully.
        Settings.EXECUTOR_MAX_WORKERS = 0

        def stretch_fit(node, floor_s: float) -> None:
            orig = node.learner.fit

            def fit(*a, **kw):
                t0 = time.monotonic()
                r = orig(*a, **kw)
                extra = floor_s - (time.monotonic() - t0)
                if extra > 0:
                    time.sleep(extra)
                return r

            node.learner.fit = fit

        # One SHARED apply_fn across every leg's fleet (per-node params still
        # differ via build_copy) + a one-time pre-warm of the train/eval XLA
        # programs on a throwaway learner: on a contended 1-core host, 8
        # identity-distinct compiles serialized inside window/round 0 would
        # drown the straggle being measured (same rationale and pattern as
        # the critical-path bench). Fits are tiny (128 samples -> 4 steps);
        # the deterministic sleep FLOOR carries the slowdown contrast.
        # Small MLP: the bench measures SCHEDULING (barrier vs buffered
        # windows), and on a 1-core host the async all-to-all contribution
        # decode+screen cost scales with param bytes — a full-size model
        # would measure serialization throughput instead of the barrier.
        hidden = (128,)
        template = mlp_model(seed=0, hidden_sizes=hidden)
        _phase("async bench: pre-warming the shared XLA programs")
        from p2pfl_tpu.learning.learner import JaxLearner

        warm_data = synthetic_mnist(n_train=128, n_test=128)
        warm_parts = warm_data.generate_partitions(1, RandomIIDPartitionStrategy)
        warm = JaxLearner(
            template.build_copy(), warm_parts[0], self_addr="mem://warmup",
            batch_size=32, seed=0,
        )
        warm.set_epochs(1)
        warm.fit()
        warm.evaluate()
        del warm

        def build_fed(n, extra_parts=0, slow_idx=None):
            data = synthetic_mnist(n_train=128 * (n + extra_parts), n_test=128)
            parts = data.generate_partitions(n + extra_parts, RandomIIDPartitionStrategy)
            nodes = [
                Node(
                    template.build_copy(
                        params=mlp_model(seed=i, hidden_sizes=hidden).get_parameters()
                    ),
                    parts[i], batch_size=32,
                )
                for i in range(n)
            ]
            for i, nd in enumerate(nodes):
                stretch_fit(
                    nd,
                    fast_floor_s * slow_x if i == slow_idx else fast_floor_s,
                )
                nd.start()
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n - 1, wait=30)
            return nodes, parts

        def teardown(nodes):
            for nd in nodes:
                try:
                    nd.stop()
                except Exception:  # noqa: BLE001
                    pass
            InMemoryRegistry.reset()

        def finished(nd, target, stage):
            return (
                not nd.learning_in_progress()
                and nd.learning_workflow is not None
                and nd.learning_workflow.history.count(stage) >= target
            )

        # --- arm 1: one 5x-slow peer, sync vs async -------------------------
        def straggler_leg(mode: str) -> dict:
            REGISTRY.reset()
            TRACER.reset()
            CHAOS.reset()
            nodes, _ = build_fed(n_nodes, slow_idx=n_nodes - 1)
            slow = nodes[-1]
            fast = nodes[:-1]
            stage = (
                "AsyncWindowFinishedStage" if mode == "async" else "RoundFinishedStage"
            )
            try:
                t0 = time.monotonic()
                nodes[0].set_start_learning(rounds=rounds, epochs=1, mode=mode)
                waiting_on = fast if mode == "async" else nodes
                deadline = time.time() + 600
                while time.time() < deadline:
                    if all(finished(nd, rounds, stage) for nd in waiting_on):
                        break
                    time.sleep(0.2)
                else:
                    raise TimeoutError(
                        f"{mode} straggler leg did not finish: "
                        f"{ {nd.addr: nd.learning_workflow.history.count(stage) for nd in waiting_on if nd.learning_workflow} }"
                    )
                wall = time.monotonic() - t0
                completed = sum(
                    nd.learning_workflow.history.count(stage)
                    for nd in nodes
                    if nd.learning_workflow is not None
                )
                if mode == "async":
                    # The straggler keeps its remaining windows on its own
                    # time — the fleet is NOT waiting on it. Stop it so the
                    # leg tears down promptly.
                    nodes[0].set_stop_learning()
                accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in fast]
                return {
                    "wall_s": round(wall, 2),
                    "completed_units": completed,
                    "throughput_units_per_s": round(completed / wall, 4),
                    "final_test_acc_mean_fast": round(sum(accs) / len(accs), 4),
                    "slow_peer": slow.addr,
                }
            finally:
                teardown(nodes)

        _phase(f"async bench: sync leg ({n_nodes} nodes, 1 x{slow_x} straggler)")
        sync_leg = straggler_leg("sync")
        _phase(f"sync leg done: {json.dumps(sync_leg)}")
        _phase(f"async bench: async leg ({n_nodes} nodes, 1 x{slow_x} straggler)")
        async_leg = straggler_leg("async")
        _phase(f"async leg done: {json.dumps(async_leg)}")

        throughput_x = round(
            async_leg["throughput_units_per_s"] / sync_leg["throughput_units_per_s"],
            2,
        )
        acc_delta_pp = round(
            100.0
            * (
                sync_leg["final_test_acc_mean_fast"]
                - async_leg["final_test_acc_mean_fast"]
            ),
            2,
        )
        if throughput_x < 3.0:
            raise AssertionError(
                f"async throughput only {throughput_x}x sync (need >= 3x): "
                f"async {async_leg}, sync {sync_leg}"
            )
        if abs(acc_delta_pp) > 0.5:
            raise AssertionError(
                f"async accuracy delta {acc_delta_pp}pp exceeds 0.5pp "
                f"(sync {sync_leg['final_test_acc_mean_fast']}, "
                f"async {async_leg['final_test_acc_mean_fast']})"
            )

        # --- arm 2: seeded churn trace, async finishes / sync stalls --------
        # Fixed 4-unit trace: long enough for 3 leaves + 3 joins, short
        # enough that the sync leg's vote-timeout-burning rounds stay inside
        # the wall budget.
        churn_rounds = int(os.environ.get("P2PFL_TPU_ASYNC_BENCH_CHURN_ROUNDS", "4"))
        # Joins stop 2 windows before the end: the contract is "contributes
        # within 2 windows of joining", which needs that much runway — a
        # join at the final window has no experiment left to contribute to.
        n_joiners = max(1, churn_rounds - 2)

        def churn_leg(mode: str, budget_s: float) -> dict:
            REGISTRY.reset()
            TRACER.reset()
            CHAOS.reset()
            nodes, parts = build_fed(n_nodes, extra_parts=n_joiners)
            by_addr = {nd.addr: nd for nd in nodes}
            # Victims: non-initiator originals; joiners are cold nodes.
            trace = CHAOS.plan_churn(
                churn_rounds,
                [nd.addr for nd in nodes[1:]],
                [f"joiner-{i}" for i in range(n_joiners)],
                seed=seed,
            )
            joiners: dict = {}
            crashed: list = []
            pending = list(trace)
            stage = (
                "AsyncWindowFinishedStage" if mode == "async" else "RoundFinishedStage"
            )
            join_windows: dict = {}
            try:
                t0 = time.monotonic()
                nodes[0].set_start_learning(rounds=churn_rounds, epochs=1, mode=mode)
                deadline = time.monotonic() + budget_s
                while time.monotonic() < deadline:
                    w = nodes[0].state.round
                    if w is not None:
                        due = [e for e in pending if e.when <= w]
                        for ev in due:
                            pending.remove(ev)
                            if ev.kind == "leave":
                                victim = by_addr.get(ev.node)
                                if victim is not None and victim not in crashed:
                                    victim.crash()
                                    crashed.append(victim)
                                    CHAOS.churn(ev.node, "leave")
                            else:  # join
                                j = Node(
                                    template.build_copy(
                                        params=mlp_model(
                                            seed=100 + len(joiners),
                                            hidden_sizes=hidden,
                                        ).get_parameters()
                                    ),
                                    parts[n_nodes + len(joiners)],
                                    batch_size=32,
                                )
                                stretch_fit(j, fast_floor_s)
                                j.start()
                                j.connect(nodes[0].addr)
                                if mode == "async":
                                    # Elastic membership: first-class join.
                                    time.sleep(0.3)
                                    j.request_async_join()
                                # Sync has NO join path: the node is a live
                                # neighbor (it wins votes!) but can never
                                # enter the experiment.
                                joiners[ev.node] = j
                                join_windows[ev.node] = w
                                CHAOS.churn(j.addr, "join")
                    survivors = [nd for nd in nodes if nd not in crashed]
                    watch = survivors + (
                        list(joiners.values()) if mode == "async" else []
                    )
                    if not pending and all(
                        not nd.learning_in_progress()
                        and nd.learning_workflow is not None
                        for nd in watch
                    ):
                        break
                    time.sleep(0.2)
                wall = time.monotonic() - t0
                survivors = [nd for nd in nodes if nd not in crashed]
                completed = {
                    nd.addr: (
                        nd.learning_workflow.history.count(stage)
                        if nd.learning_workflow
                        else 0
                    )
                    for nd in survivors
                }
                all_done = not pending and all(
                    c >= churn_rounds for c in completed.values()
                )
                joiner_first_fold = {}
                for sym, j in joiners.items():
                    first = nodes[0].async_agg.seen_contributors.get(j.addr) if nodes[0].async_agg else None
                    joiner_first_fold[j.addr] = {
                        "joined_at": join_windows.get(sym),
                        "first_folded_window": first,
                    }
                # The sync stall signature: joiners are live neighbors, so
                # they win committee votes — but they never received the
                # kickoff and can never cast a ballot, so every election
                # after the first join burns the full VOTE_TIMEOUT.
                vote_rtt_max = 0.0
                vote_timeout_spans = 0
                if mode != "async":
                    for s in TRACER.spans():
                        if s.name == "vote_rtt":
                            vote_rtt_max = max(vote_rtt_max, s.dur_s)
                            if s.dur_s >= Settings.VOTE_TIMEOUT - 0.5:
                                vote_timeout_spans += 1
                faults = CHAOS.fault_counts()
                if mode != "async":
                    # make teardown quick: abort whatever is still limping
                    try:
                        nodes[0].set_stop_learning()
                    except Exception:  # noqa: BLE001
                        pass
                return {
                    "wall_s": round(wall, 2),
                    "completed_by_survivor": completed,
                    "all_survivors_finished": all_done,
                    "mean_unit_wall_s": round(
                        wall / max(1, min(completed.values() or [1])), 2
                    ),
                    "crashed": [nd.addr for nd in crashed],
                    "joiners": joiner_first_fold,
                    "churn_faults": faults.get("churn", 0),
                    "injected_faults": faults,
                    "vote_rtt_max_s": round(vote_rtt_max, 2),
                    "vote_timeout_rounds": vote_timeout_spans,
                }
            finally:
                teardown(list(nodes) + list(joiners.values()))

        _phase("async bench: churn arm (async leg)")
        churn_async = churn_leg("async", budget_s=300.0)
        _phase(f"churn async done: {json.dumps(churn_async)}")
        _phase("async bench: churn arm (sync leg, same seeded trace)")
        churn_sync = churn_leg("sync", budget_s=300.0)
        _phase(f"churn sync done: {json.dumps(churn_sync)}")

        if not churn_async["all_survivors_finished"]:
            raise AssertionError(
                f"async churn leg did not finish all windows: {churn_async}"
            )
        for addr, info in churn_async["joiners"].items():
            first, joined = info["first_folded_window"], info["joined_at"]
            if first is None or joined is None or first - joined > 2:
                raise AssertionError(
                    f"joiner {addr} did not contribute within 2 windows: {info}"
                )
        # The SAME trace must demonstrably stall (or abandon) sync rounds.
        # PR 3's death callbacks make leaves survivable even in sync — the
        # stall the barrier cannot escape is the JOIN side: a joiner is a
        # live neighbor (it wins committee votes) with no entry path into
        # the experiment, so every election after the first join burns the
        # full VOTE_TIMEOUT, the joiner never contributes a sample, and the
        # per-round wall stretches well past the async per-window wall.
        sync_abandoned = not churn_sync["all_survivors_finished"]
        stall_ratio = round(
            churn_sync["mean_unit_wall_s"]
            / max(1e-9, churn_async["mean_unit_wall_s"]),
            2,
        )
        sync_joiners_dark = all(
            info["first_folded_window"] is None
            for info in churn_sync["joiners"].values()
        )
        if not sync_abandoned:
            if churn_sync["vote_timeout_rounds"] == 0:
                raise AssertionError(
                    "sync churn leg finished without a single vote-timeout "
                    f"round — the trace did not stall the barrier: {churn_sync}"
                )
            if not sync_joiners_dark:
                raise AssertionError(
                    f"sync mode integrated a joiner it has no path for: {churn_sync}"
                )
            if stall_ratio < 2.0:
                raise AssertionError(
                    f"sync churn rounds only {stall_ratio}x async windows "
                    f"(expected >= 2x): sync {churn_sync}, async {churn_async}"
                )

        # --- arm 3: Byzantine signflip under async --------------------------
        byz_rounds = 3  # accuracy saturates by 3 windows; keep the arm short

        def byzantine_leg(n_adversaries: int) -> dict:
            REGISTRY.reset()
            TRACER.reset()
            CHAOS.reset()
            nodes, _ = build_fed(n_nodes)
            adversaries = [nd.addr for nd in nodes[-n_adversaries:]] if n_adversaries else []
            for addr in adversaries:
                CHAOS.set_byzantine(addr, "signflip")
            honest = [nd for nd in nodes if nd.addr not in adversaries]
            try:
                t0 = time.monotonic()
                nodes[0].set_start_learning(rounds=byz_rounds, epochs=1, mode="async")
                deadline = time.time() + 300
                while time.time() < deadline:
                    if all(
                        finished(nd, byz_rounds, "AsyncWindowFinishedStage")
                        for nd in honest
                    ):
                        break
                    time.sleep(0.2)
                else:
                    raise TimeoutError("async byzantine leg did not finish")
                wall = time.monotonic() - t0
                nodes[0].set_stop_learning()
                accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in honest]
                rej = REGISTRY.get("p2pfl_updates_rejected_total")
                rejections = (
                    sum(c.value for _, c in rej.samples()) if rej is not None else 0
                )
                return {
                    "wall_s": round(wall, 2),
                    "final_test_acc_mean_honest": round(sum(accs) / len(accs), 4),
                    "adversaries": adversaries,
                    "rejections_total": int(rejections),
                }
            finally:
                CHAOS.reset()
                teardown(nodes)

        _phase("async bench: byzantine arm (clean async baseline)")
        byz_clean = byzantine_leg(0)
        _phase(f"clean baseline done: {json.dumps(byz_clean)}")
        _phase("async bench: byzantine arm (2 signflip adversaries)")
        byz = byzantine_leg(2)
        _phase(f"byzantine leg done: {json.dumps(byz)}")

        byz_delta_pp = round(
            100.0
            * (
                byz_clean["final_test_acc_mean_honest"]
                - byz["final_test_acc_mean_honest"]
            ),
            2,
        )
        if byz_delta_pp > 0.0:
            raise AssertionError(
                f"async Byzantine arm lost {byz_delta_pp}pp "
                f"(clean {byz_clean}, signflip {byz})"
            )
        if byz["rejections_total"] == 0:
            raise AssertionError(
                "admission control rejected nothing under async signflip — "
                "contributions are not being screened"
            )

        perf = perf_section(REGISTRY)
        out = {
            "metric": f"async_vs_sync_throughput_{n_nodes}node_1x{slow_x:g}_straggler",
            "value": throughput_x,
            "unit": "x_fleet_round_window_throughput",
            "vs_baseline": None,
            "meta": _bench_meta(seed=seed, backend="cpu"),
            "perf": perf,
            "extra": {
                "nodes": n_nodes,
                "rounds_or_windows": rounds,
                "seed": seed,
                "slowdown_x": slow_x,
                "fast_fit_floor_s": fast_floor_s,
                "acc_delta_pp": acc_delta_pp,
                "sync": sync_leg,
                "async": async_leg,
                "churn": {
                    "trace_rounds": churn_rounds,
                    "async": churn_async,
                    "sync": churn_sync,
                    "sync_stalled_or_abandoned": bool(
                        sync_abandoned or churn_sync["vote_timeout_rounds"] > 0
                    ),
                    "sync_vote_timeout_rounds": churn_sync["vote_timeout_rounds"],
                    "sync_joiners_never_contributed": bool(sync_joiners_dark),
                    "sync_vs_async_unit_wall_x": stall_ratio,
                },
                "byzantine": {
                    "clean": byz_clean,
                    "signflip": byz,
                    "acc_delta_pp": byz_delta_pp,
                },
                "note": "throughput = completed rounds (sync) or windows "
                "(async) across the whole fleet per wall second; full-"
                "participation committees so the sync barrier is set by the "
                "straggler every round; async windows close on the buffer "
                "fill target (ASYNC_BUFFER_K) with staleness-weighted folds",
            },
        }
        os.makedirs("artifacts", exist_ok=True)
        with open(os.path.join("artifacts", "ASYNC_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_byzantine_bench() -> None:
    """Subprocess-style mode ``--byzantine``: Byzantine defense acceptance.

    Runs the same in-memory MNIST federation (8 nodes, 2 seeded adversaries
    by default) over the real Node/gossip/aggregator stack under a
    model-poisoning attack injected at the chaos plane's send choke point,
    across six legs:

    * ``clean`` — fault-free FedAvg (the accuracy yardstick),
    * ``fedavg_attacked`` — FedAvg with wire admission DISABLED: the
      undefended contrast (must degrade >= 10pp),
    * ``krum`` / ``trimmed_mean`` / ``geometric_median`` — the same attack
      against the full defense plane (admission screening + robust rule;
      must finish every round within the PR 3 stage-wait deadlines and land
      within 2pp of clean),
    * ``labelflip_fedavg`` — the DATA-poisoning arm: the same adversary set
      trains on label-flipped partitions (learning/dataset/poison.py)
      instead of corrupting frames; reported for the attack-family contrast
      (low-rate label flip is survivable by plain FedAvg — the reason the
      wire attack is the headline).

    Also embeds: a per-leg rejection-counter breakdown
    (``p2pfl_updates_rejected_total`` by reason), a deterministic-replay
    check (the same seed corrupting the same frame sequence through two
    fresh chaos planes must produce identical fault counts AND identical
    corrupted payloads), and an aggregator-only probe (krum_select on a
    synthetic attacked stack — layer-2 evidence independent of admission).

    Shape overrides: P2PFL_TPU_BYZ_NODES (default 8),
    P2PFL_TPU_BYZ_ADVERSARIES (2), P2PFL_TPU_BYZ_ROUNDS (3),
    P2PFL_TPU_BYZ_SEED (42), P2PFL_TPU_BYZ_ATTACK (scaled).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import contextlib

        import jax

        jax.config.update("jax_platforms", "cpu")
        import numpy as np

        from p2pfl_tpu.chaos import CHAOS, ChaosPlane
        from p2pfl_tpu.comm.envelope import Envelope
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.aggregators import (
            FedAvg,
            GeometricMedian,
            MultiKrum,
            TrimmedMean,
        )
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.learning.dataset.poison import poison_partitions, select_poisoned
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY, TRACER
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_BYZ_NODES", "8"))
        n_adv = int(os.environ.get("P2PFL_TPU_BYZ_ADVERSARIES", "2"))
        rounds = int(os.environ.get("P2PFL_TPU_BYZ_ROUNDS", "3"))
        seed = int(os.environ.get("P2PFL_TPU_BYZ_SEED", "42"))
        attack = os.environ.get("P2PFL_TPU_BYZ_ATTACK", "scaled")
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        # Full committee: adversaries are always trainers, so the attack
        # actually enters every round's aggregation.
        Settings.TRAIN_SET_SIZE = n_nodes

        adv_idx = set(int(i) for i in select_poisoned(n_nodes, n_adv / n_nodes, seed))
        assert len(adv_idx) == n_adv, (adv_idx, n_adv)

        wait_deadlines = {
            "vote_rtt": Settings.VOTE_TIMEOUT + 3.0,
            "aggregation_wait": Settings.AGGREGATION_TIMEOUT,
            "full_model_wait": Settings.AGGREGATION_TIMEOUT,
        }

        def rejected_by_reason() -> dict:
            fam = REGISTRY.get("p2pfl_updates_rejected_total")
            agg: dict = {}
            if fam is not None:
                for labels, child in fam.samples():
                    r = labels.get("reason", "?")
                    agg[r] = agg.get(r, 0) + int(child.value)
            return agg

        def run_leg(
            label: str,
            make_aggregator,
            *,
            wire_attack: bool = False,
            admission: bool = True,
            labelflip: bool = False,
        ) -> dict:
            REGISTRY.reset()
            TRACER.reset()
            CHAOS.reset()
            _phase(f"byzantine leg {label}: attack={attack if wire_attack else ('labelflip' if labelflip else 'none')}, admission={admission}")
            data = synthetic_mnist(n_train=256 * n_nodes, n_test=256)
            parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
            if labelflip:
                parts, poisoned = poison_partitions(
                    parts, n_adv / n_nodes, num_classes=10, seed=seed
                )
                assert set(int(i) for i in poisoned) == adv_idx
            nodes = [
                Node(mlp_model(seed=i), parts[i], batch_size=32,
                     aggregator=make_aggregator())
                for i in range(n_nodes)
            ]
            honest = [nd for i, nd in enumerate(nodes) if i not in adv_idx]
            # The experiment is launched from an HONEST node: the initiator's
            # init_model weights seed round 0 for the whole federation, and
            # the protocol must trust the operator who starts the experiment
            # (a Byzantine initiator's scaled init is screened out by
            # admission — screen_init — which would correctly leave peers
            # unseeded rather than poisoned, stalling round 0 by design).
            initiator = honest[0]
            scope = (
                CHAOS.overridden(seed=seed) if wire_attack else contextlib.nullcontext()
            )
            faults: dict = {}
            with Settings.overridden(ADMISSION_ENABLED=admission):
                with scope:
                    if wire_attack:
                        for i in adv_idx:
                            CHAOS.set_byzantine(nodes[i].addr, attack)
                    for nd in nodes:
                        nd.start()
                    try:
                        for i in range(1, n_nodes):
                            nodes[i].connect(nodes[0].addr)
                        wait_convergence(nodes, n_nodes - 1, wait=30)
                        t0 = time.monotonic()
                        initiator.set_start_learning(rounds=rounds, epochs=1)
                        deadline = time.time() + 900
                        while time.time() < deadline:
                            if all(
                                not nd.learning_in_progress()
                                and nd.learning_workflow is not None
                                for nd in nodes
                            ):
                                break
                            time.sleep(0.25)
                        else:
                            raise TimeoutError(f"{label} federation did not finish")
                        wall_s = time.monotonic() - t0
                        faults = CHAOS.fault_counts()
                        incomplete = {
                            nd.addr: nd.learning_workflow.history.count(
                                "RoundFinishedStage"
                            )
                            for nd in honest
                            if nd.learning_workflow.history.count("RoundFinishedStage")
                            != rounds
                        }
                        if incomplete:
                            raise AssertionError(
                                f"{label}: honest nodes did not complete all "
                                f"{rounds} rounds: {incomplete}"
                            )
                        accs = [
                            nd.learner.evaluate().get("test_acc", 0.0)
                            for nd in honest
                        ]
                        wait_max = {name: 0.0 for name in wait_deadlines}
                        for s in TRACER.spans():
                            if s.name in wait_max:
                                wait_max[s.name] = max(wait_max[s.name], s.dur_s)
                        over = {
                            name: (m, wait_deadlines[name])
                            for name, m in wait_max.items()
                            if m >= wait_deadlines[name]
                        }
                        if over:
                            raise AssertionError(
                                f"{label}: stage wait exceeded its deadline: {over}"
                            )
                        rej = rejected_by_reason()
                    finally:
                        for nd in nodes:
                            nd.stop()
                        InMemoryRegistry.reset()
            leg = {
                "wall_s": round(wall_s, 2),
                "final_test_acc_mean": round(sum(accs) / len(accs), 4),
                "final_test_acc_min": round(min(accs), 4),
                "rejected_by_reason": rej,
                "rejected_total": sum(rej.values()),
                "injected_faults": faults,
                "max_wait_s": {k: round(v, 3) for k, v in wait_max.items()},
            }
            _phase(f"byzantine leg {label} done: {json.dumps(leg)}")
            return leg

        legs = {
            "clean": run_leg("clean", FedAvg),
            "fedavg_attacked": run_leg(
                "fedavg_attacked", FedAvg, wire_attack=True, admission=False
            ),
            "krum": run_leg(
                "krum", lambda: MultiKrum(num_byzantine=n_adv), wire_attack=True
            ),
            "trimmed_mean": run_leg(
                "trimmed_mean",
                lambda: TrimmedMean(trim_ratio=n_adv / n_nodes),
                wire_attack=True,
            ),
            "geometric_median": run_leg(
                "geometric_median", GeometricMedian, wire_attack=True
            ),
            "labelflip_fedavg": run_leg("labelflip_fedavg", FedAvg, labelflip=True),
        }

        clean_acc = legs["clean"]["final_test_acc_mean"]
        degradation_pp = round(
            100.0 * (clean_acc - legs["fedavg_attacked"]["final_test_acc_mean"]), 2
        )
        if degradation_pp < 10.0:
            raise AssertionError(
                f"undefended FedAvg only degraded {degradation_pp}pp under the "
                f"{attack} attack (need >= 10pp for a meaningful contrast)"
            )
        for name in ("krum", "trimmed_mean", "geometric_median"):
            delta_pp = round(
                100.0 * (clean_acc - legs[name]["final_test_acc_mean"]), 2
            )
            legs[name]["acc_delta_vs_clean_pp"] = delta_pp
            if delta_pp > 2.0:
                raise AssertionError(
                    f"{name} degraded {delta_pp}pp > 2pp under the defended run"
                )
            if legs[name]["rejected_total"] == 0:
                raise AssertionError(
                    f"{name}: admission rejected nothing — the attack never "
                    "hit the screen"
                )

        # Deterministic corruption replay: same seed + same frame sequence
        # through two fresh planes => identical fault counts AND payloads.
        frame = mlp_model(seed=0).encode_parameters()
        replays = []
        for _ in range(2):
            plane = ChaosPlane()
            with Settings.overridden(CHAOS_ENABLED=True, CHAOS_SEED=seed):
                plane.set_byzantine("adv", attack)
                payloads = []
                for k in range(50):
                    env = Envelope.weights("adv", "partial_model", k, frame, ["adv"], 1)
                    payloads.append(plane.corrupt_weights("adv", env).payload)
            replays.append((plane.fault_counts(), payloads))
        if replays[0] != replays[1]:
            raise AssertionError("byzantine corruption is not deterministic")

        # Aggregator-only probe: Krum's distance filter must exclude the
        # attackers even with admission out of the picture.
        from p2pfl_tpu.ops import aggregation as agg_ops

        probe_model = mlp_model(seed=0, hidden_sizes=(16,))
        base = probe_model.get_parameters()
        stack = agg_ops.tree_stack(
            [[p + 0.01 * i for p in base] for i in range(n_nodes - n_adv)]
            + [
                [-10.0 * p if attack in ("signflip", "scaled") else p for p in base]
                for _ in range(n_adv)
            ]
        )
        sel = agg_ops.krum_select(
            stack, num_byzantine=n_adv, num_selected=n_nodes - n_adv - 2
        )
        attacker_rows = set(range(n_nodes - n_adv, n_nodes))
        krum_excludes_attackers = not (set(int(i) for i in np.asarray(sel)) & attacker_rows)
        if not krum_excludes_attackers:
            raise AssertionError(f"krum_select picked an attacker row: {sel}")

        out = {
            "metric": f"byzantine_defense_{n_nodes}node_mnist",
            "value": degradation_pp,
            "unit": "pp_fedavg_degradation_undefended",
            "vs_baseline": None,
            "extra": {
                "nodes": n_nodes,
                "adversaries": n_adv,
                "adversary_indices": sorted(adv_idx),
                "attack": attack,
                "rounds": rounds,
                "seed": seed,
                "legs": legs,
                "defended_rules": {
                    "krum": f"MultiKrum(f={n_adv}, m=n-f-2)",
                    "trimmed_mean": f"TrimmedMean(trim_ratio={n_adv / n_nodes})",
                    "geometric_median": "GeometricMedian(iters=8)",
                },
                "deterministic_replay_counts": replays[0][0],
                "krum_select_excludes_attackers": krum_excludes_attackers,
                "wait_deadlines_s": wait_deadlines,
                "note": "defended legs run admission screening + robust "
                "aggregation; fedavg_attacked runs with admission disabled "
                "(the undefended contrast); labelflip_fedavg is the "
                "data-poisoning arm (poison.py flip_labels)",
            },
        }
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_observatory_bench() -> None:
    """Subprocess-style mode ``--observatory``: federation-observatory
    acceptance run.

    One 8-node in-memory MNIST chaos federation (5% seeded message drop)
    with three seeded anomalies over the real Node/gossip stack:

    * a **straggler** — one honest node whose fits take longer than the
      fleet's JIT stall patience, so the fleet aggregates without it each
      round and it genuinely falls behind in round index (the lag its
      gossiped digests expose),
    * a **Byzantine peer** — signflip model poisoning at the chaos plane's
      send choke point (its frames are rejected by wire admission,
      attributed per sender),
    * a **digest-free node** — emission disabled, proving digest-bearing
      and digest-free nodes interoperate on the same wire,

    plus one bystander node killed mid-run (``Node.crash()``). Asserts the
    observatory contract: every surviving honest digest-bearing node flags
    the straggler as its top straggler AND the Byzantine peer as its top
    suspect within 2 rounds (scores derived purely from gossiped digests),
    and the killed node's flight-recorder dump lands in ``artifacts/``.

    Shape overrides: P2PFL_TPU_OBS_BENCH_NODES (default 8),
    P2PFL_TPU_OBS_BENCH_ROUNDS (default 3), P2PFL_TPU_OBS_BENCH_DROP
    (default 0.05), P2PFL_TPU_OBS_BENCH_SEED (default 42),
    P2PFL_TPU_OBS_BENCH_STRAGGLE_S (default 12.0 — must exceed
    AGGREGATION_STALL_PATIENCE, else the fleet waits for the straggler and
    no round lag can develop).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.chaos import CHAOS
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY, TRACER
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_OBS_BENCH_NODES", "8"))
        rounds = int(os.environ.get("P2PFL_TPU_OBS_BENCH_ROUNDS", "3"))
        drop = float(os.environ.get("P2PFL_TPU_OBS_BENCH_DROP", "0.05"))
        seed = int(os.environ.get("P2PFL_TPU_OBS_BENCH_SEED", "42"))
        straggle_s = float(os.environ.get("P2PFL_TPU_OBS_BENCH_STRAGGLE_S", "12.0"))
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        # Everyone trains: the Byzantine node's poisoned partials must flow
        # every round for per-sender rejection attribution to accumulate.
        Settings.TRAIN_SET_SIZE = n_nodes
        REGISTRY.reset()
        TRACER.reset()
        CHAOS.reset()

        _phase(
            f"observatory bench: {n_nodes} nodes, {rounds} rounds, "
            f"drop={drop}, straggler +{straggle_s}s/fit, 1 signflip adversary"
        )
        data = synthetic_mnist(n_train=256 * n_nodes, n_test=256)
        parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
        nodes = [
            Node(mlp_model(seed=i), parts[i], batch_size=32)
            for i in range(n_nodes)
        ]
        # Role cast (all by index, deterministic): 0 = initiator, 1 = seeded
        # straggler, 2 = Byzantine, 3 = digest-free, 4 = mid-run kill victim.
        straggler, byzantine, digest_free, victim = (
            nodes[1], nodes[2], nodes[3], nodes[4],
        )
        honest_observers = [
            nd for nd in nodes
            if nd not in (straggler, byzantine, victim)
        ]

        # Straggler: stretch every fit past the stall patience. The fleet
        # JIT-aggregates without it each round (PR 3 machinery) and the
        # straggler's round index falls behind — the lag its gossiped
        # digests expose, and the signal the straggler score is built from.
        inner_fit = straggler.learner.fit

        def slow_fit(*a, **kw):
            time.sleep(straggle_s)
            return inner_fit(*a, **kw)

        straggler.learner.fit = slow_fit
        # Digest-free node: beats stay in the pre-digest wire format.
        digest_free.protocol.set_digest_source(None)

        flag_round: dict = {}  # observer addr -> round when both flagged
        victim_killed = False
        try:
            with CHAOS.overridden(drop_rate=drop, seed=seed):
                CHAOS.set_byzantine(byzantine.addr, "signflip")
                for nd in nodes:
                    nd.start()
                for i in range(1, n_nodes):
                    nodes[i].connect(nodes[0].addr)
                wait_convergence(nodes, n_nodes - 1, wait=30)
                t0 = time.monotonic()
                nodes[0].set_start_learning(rounds=rounds, epochs=1)
                deadline = time.time() + 900
                while time.time() < deadline:
                    for nd in honest_observers:
                        if nd.addr in flag_round:
                            continue
                        obs = nd.observatory
                        if (
                            obs.top("straggler") == straggler.addr
                            and obs.top("suspect") == byzantine.addr
                        ):
                            r = nd.state.round
                            flag_round[nd.addr] = -1 if r is None else int(r)
                    if (
                        not victim_killed
                        and nodes[0].state.round is not None
                        and nodes[0].state.round >= 1
                    ):
                        _phase(f"killing bystander {victim.addr} mid-round 1")
                        victim.crash()
                        victim_killed = True
                    alive = [nd for nd in nodes if nd is not victim or not victim_killed]
                    if all(
                        not nd.learning_in_progress()
                        and nd.learning_workflow is not None
                        for nd in alive
                    ):
                        break
                    time.sleep(0.25)
                else:
                    raise TimeoutError("observatory federation did not finish")
                wall_s = time.monotonic() - t0
                faults = CHAOS.fault_counts()
                # Final flag sweep (scores persist after the run ends).
                for nd in honest_observers:
                    if nd.addr not in flag_round:
                        obs = nd.observatory
                        if (
                            obs.top("straggler") == straggler.addr
                            and obs.top("suspect") == byzantine.addr
                        ):
                            r = nd.state.round
                            flag_round[nd.addr] = rounds if r is None else int(r)
                # Federation snapshot for fed_top (from the initiator's view).
                os.makedirs("artifacts", exist_ok=True)
                snap_path = nodes[0].observatory.write_snapshot(
                    os.path.join("artifacts", "federation_snapshot.json")
                )
                # Fleet-view facts must be read BEFORE stop(): teardown
                # clears neighbor tables, which forgets observatory peers.
                df_known = len(digest_free.observatory.scores())
                df_rounds = digest_free.learning_workflow.history.count(
                    "RoundFinishedStage"
                )
        finally:
            for nd in nodes:
                try:
                    nd.stop()
                except Exception:
                    pass
            InMemoryRegistry.reset()

        # --- the acceptance assertions --------------------------------------
        unflagged = [nd.addr for nd in honest_observers if nd.addr not in flag_round]
        if unflagged:
            raise AssertionError(
                f"honest nodes never flagged both anomalies: {unflagged}"
            )
        late = {a: r for a, r in flag_round.items() if r > 2}
        if late:
            raise AssertionError(
                f"anomalies flagged later than round 2 on: {late}"
            )
        dump = victim.protocol.flight_recorder.dump_path("artifacts")
        if not os.path.exists(dump):
            raise AssertionError(f"killed node's flight-recorder dump missing: {dump}")
        with open(dump) as f:
            dump_doc = json.load(f)
        if dump_doc.get("trigger") != "crash" or not dump_doc.get("events"):
            raise AssertionError(f"flight-recorder dump malformed: {dump}")
        # Digest-free interop: the opted-out node finished every round AND
        # its observatory still assembled the fleet (ingest-only works).
        if df_rounds != rounds:
            raise AssertionError(
                f"digest-free node finished {df_rounds}/{rounds} rounds"
            )
        if df_known < n_nodes - 2:  # fleet minus itself and the dead victim
            raise AssertionError(
                f"digest-free node assembled only {df_known} peers' digests"
            )

        out = {
            "metric": "observatory_flag_latency_8node_chaos",
            "value": max(flag_round.values()),
            "unit": "rounds_to_flag_both_anomalies_worst_node",
            "vs_baseline": None,
            "extra": {
                "nodes": n_nodes,
                "rounds": rounds,
                "drop_rate": drop,
                "seed": seed,
                "wall_s": round(wall_s, 2),
                "straggler": straggler.addr,
                "byzantine": byzantine.addr,
                "digest_free": digest_free.addr,
                "killed": victim.addr,
                "flag_round_by_observer": flag_round,
                "injected_faults": faults,
                "flightrec_dump": dump,
                "flightrec_events": len(dump_doc.get("events", [])),
                "federation_snapshot": snap_path,
                "digest_free_peers_known": df_known,
                "note": "flag = observer's top straggler AND top suspect "
                "match the seeded anomalies, derived ONLY from gossiped "
                "health digests; digest-free node proves wire compat",
            },
        }
        out["meta"] = _bench_meta(seed=seed, backend="cpu")
        with open(os.path.join("artifacts", "OBSERVATORY_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
        _phase(
            f"observatory bench done: worst flag round "
            f"{max(flag_round.values())}, {len(flag_round)} observers"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_fleetobs_bench() -> None:
    """Subprocess-style mode ``--fleetobs``: sketch-native fleet
    observability acceptance run, two arms.

    **Fused-mesh arm** (8 → 512 → 10k virtual nodes): a MeshSimulation per
    fleet size with seeded 5x-slow device tiers (``node_speed``), 3 rounds
    each. The 10k run's jit-computed fleet summary is folded into sketches
    host-side and written as a fed_top-renderable observatory snapshot
    (``artifacts/federation_snapshot.json``); every seeded straggler must
    appear in the top-N straggler table. At each size the arm also measures
    (a) the encoded bytes of a v2 health digest summarizing the whole
    fleet's step-time/staleness distributions and (b) the estimated memory
    of an observatory ingesting one digest per node — both must grow
    SUBLINEARLY (digest bytes flat-to-logarithmic, per-node observatory
    memory strictly shrinking as the population outgrows OBS_MAX_TRACKED).

    **Async-attribution arm** (8 real nodes, ``mode="async"``): one seeded
    5x-slow contributor, 5 windows. The window-level critical path must
    attribute the slow contributor as gating in >= 4/5 windows, and the
    digest-carried staleness sketch p90 of a fast observer must match its
    buffer's exact measured staleness p90 within sketch error.

    Shape overrides: P2PFL_TPU_FLEETOBS_SIZES (comma list, default
    "8,512,10000"), P2PFL_TPU_FLEETOBS_WINDOWS (default 5),
    P2PFL_TPU_FLEETOBS_SLOW_X (default 5.0).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol/scale bench: CPU venue
        import numpy as np
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.parallel.simulation import MeshSimulation
        from p2pfl_tpu.telemetry import REGISTRY, TRACER
        from p2pfl_tpu.telemetry import digest as digest_mod
        from p2pfl_tpu.telemetry.observatory import Observatory
        from p2pfl_tpu.telemetry.sketches import (
            DistinctEstimator,
            QuantileSketch,
            SKETCHES,
        )

        sizes = [
            int(s)
            for s in os.environ.get(
                "P2PFL_TPU_FLEETOBS_SIZES", "8,512,10000"
            ).split(",")
        ]
        windows = int(os.environ.get("P2PFL_TPU_FLEETOBS_WINDOWS", "5"))
        slow_x = float(os.environ.get("P2PFL_TPU_FLEETOBS_SLOW_X", "5.0"))
        seed = 42
        top_n = 16
        REGISTRY.reset()
        TRACER.reset()
        SKETCHES.reset()

        # --- arm A: fused-mesh fleet observability at scale ------------------
        rng = np.random.default_rng(seed)
        scale_points = []
        snap_path = os.path.join("artifacts", "federation_snapshot.json")
        for n in sizes:
            n_stragglers = max(1, min(12, n // 64))
            straggler_idx = sorted(
                rng.choice(n, size=n_stragglers, replace=False).tolist()
            )
            speed = np.ones(n, np.float32)
            speed[straggler_idx] = slow_x
            _phase(
                f"fleetobs mesh arm: n={n}, {n_stragglers} seeded "
                f"{slow_x:g}x stragglers"
            )
            samples, feat, classes = 16, 16, 4
            x = rng.normal(size=(n, samples, feat)).astype(np.float32)
            y = rng.integers(0, classes, size=(n, samples)).astype(np.int32)
            mask = np.ones((n, samples), np.float32)
            model = mlp_model(
                input_shape=(feat,), hidden_sizes=(8,), out_channels=classes,
                seed=seed,
            )
            sim = MeshSimulation(
                model, (x, y, mask), test_data=(x[0], y[0]),
                train_set_size=min(64, n), batch_size=8, seed=seed,
                node_speed=speed,
            )
            res = sim.run(rounds=3, warmup=False)
            snap = sim.fleet_snapshot(
                res, top_n=top_n, path=snap_path if n == max(sizes) else None
            )
            top_names = list(snap["peers"])
            seeded_names = [f"vnode/{i:05d}" for i in straggler_idx]
            missing = [s for s in seeded_names if s not in top_names]
            health = sim.fleet_health(res)
            sim.close()

            # Digest bytes: a v2 digest whose sketches summarize the WHOLE
            # fleet's distributions (the observatory's merged view re-
            # gossiped) — the wire cost that must stay flat-to-log in n.
            sk_steps = QuantileSketch(
                rel_err=Settings.SKETCH_REL_ERR, max_bins=Settings.SKETCH_MAX_BINS
            )
            sk_steps.add_many(health["step_time"])
            sk_lag = QuantileSketch(
                rel_err=Settings.SKETCH_REL_ERR, max_bins=Settings.SKETCH_MAX_BINS
            )
            sk_lag.add_many(health["round_lag"])
            est = DistinctEstimator()
            for i in range(n):
                est.add(f"vnode/{i:05d}")
            fleet_dig = digest_mod.HealthDigest(
                node="fleet-summary", ts=time.time(), round=3,
                sketches={
                    "step_time": sk_steps.to_wire(
                        max_bins=digest_mod.DIGEST_SKETCH_BINS
                    ),
                    "staleness": sk_lag.to_wire(
                        max_bins=digest_mod.DIGEST_SKETCH_BINS
                    ),
                    "__distinct__": est.to_wire(),
                },
            )
            digest_bytes = len(fleet_dig.encode())
            if digest_bytes > digest_mod.MAX_DIGEST_BYTES:
                raise AssertionError(
                    f"fleet digest at n={n} is {digest_bytes}B > "
                    f"MAX_DIGEST_BYTES — the sketch bound failed"
                )

            # Observatory memory: ingest one (small, sketch-bearing) digest
            # per virtual node; beyond OBS_MAX_TRACKED the overflow folds
            # into merged sketches, so memory must plateau.
            prev_refresh = Settings.OBS_REFRESH_MIN_S
            Settings.OBS_REFRESH_MIN_S = 1.0
            try:
                obs = Observatory(f"bench-obs-{n}")
                peer_sketch = QuantileSketch(rel_err=Settings.SKETCH_REL_ERR)
                peer_sketch.add(0.01)
                peer_wire = peer_sketch.to_wire()
                now_ts = time.time()
                for i in range(n):
                    obs.ingest(
                        digest_mod.HealthDigest(
                            node=f"vnode/{i:05d}", ts=now_ts, round=3,
                            steps_per_s=float(1.0 / max(1e-6, health["step_time"][i])),
                            sketches={"staleness": peer_wire},
                        )
                    )
                obs_mem = obs.estimated_memory_bytes()
                fleet_view = obs.fleet_quantiles()
            finally:
                Settings.OBS_REFRESH_MIN_S = prev_refresh
            scale_points.append(
                {
                    "fleet_size": n,
                    "rounds": res.rounds,
                    "sec_per_round": round(res.seconds_per_round, 6),
                    "seeded_stragglers": seeded_names,
                    "top_n": top_names,
                    "stragglers_missing_from_top": missing,
                    "digest_bytes": digest_bytes,
                    "obs_memory_bytes": obs_mem,
                    "obs_memory_bytes_per_node": round(obs_mem / n, 2),
                    "obs_fleet_staleness_count": fleet_view.get(
                        "staleness", {}
                    ).get("count", 0),
                }
            )
            _phase(
                f"  n={n}: digest {digest_bytes}B, obs mem {obs_mem}B "
                f"({obs_mem / n:.0f}B/node), top-{top_n} misses: {missing}"
            )

        big = scale_points[-1]
        small = scale_points[0]
        if big["stragglers_missing_from_top"]:
            raise AssertionError(
                f"seeded stragglers missing from the {big['fleet_size']}-node "
                f"top-{top_n}: {big['stragglers_missing_from_top']}"
            )
        size_ratio = big["fleet_size"] / small["fleet_size"]
        if big["digest_bytes"] > small["digest_bytes"] * 4:
            raise AssertionError(
                f"digest bytes grew {big['digest_bytes'] / small['digest_bytes']:.1f}x "
                f"over a {size_ratio:.0f}x fleet — not flat-to-logarithmic"
            )
        # The sublinear-memory claim: total observatory memory PLATEAUS at
        # ~the tracking cap's worth of digests (overflow folds into fixed-
        # size sketches), so past the cap it must stay within 1.5x of
        # cap * per-digest cost no matter how large the fleet grows.
        if big["fleet_size"] > Settings.OBS_MAX_TRACKED:
            plateau = (
                small["obs_memory_bytes_per_node"]
                * Settings.OBS_MAX_TRACKED
                * 1.5
            )
            if big["obs_memory_bytes"] > plateau:
                raise AssertionError(
                    f"observatory memory {big['obs_memory_bytes']}B at "
                    f"n={big['fleet_size']} exceeds the tracking-cap plateau "
                    f"({plateau:.0f}B) — overflow folding is not bounding it"
                )

        # --- arm B: async window attribution over the real wire ---------------
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry.critical_path import CriticalPathAnalyzer
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = 8
        fit_floor = 0.6
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        Settings.EXECUTOR_MAX_WORKERS = 0  # inline fits: sleep floors overlap
        Settings.ASYNC_BUFFER_K = n_nodes // 2
        Settings.ASYNC_WINDOW_TIMEOUT = 20.0
        REGISTRY.reset()
        TRACER.reset()
        SKETCHES.reset()
        _phase(
            f"fleetobs async arm: {n_nodes} nodes, {windows} windows, one "
            f"{slow_x:g}x-slow contributor"
        )
        data = synthetic_mnist(n_train=128 * n_nodes, n_test=64)
        parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
        # Shared apply_fn + throwaway-learner prewarm (the --async bench
        # pattern): serialized per-node XLA compiles inside window 0 would
        # drown the seeded slowdown the attribution assertions measure.
        from p2pfl_tpu.learning.learner import JaxLearner

        template = mlp_model(seed=0)
        warm = JaxLearner(
            template.build_copy(), parts[0], self_addr="mem://warmup",
            batch_size=32, seed=0,
        )
        warm.set_epochs(1)
        warm.fit()
        warm.evaluate()
        del warm
        SKETCHES.reset()  # the warmup learner's step times are not a node's
        nodes = [
            Node(
                template.build_copy(params=mlp_model(seed=i).get_parameters()),
                parts[i], batch_size=32,
            )
            for i in range(n_nodes)
        ]
        slow = nodes[-1]

        def stretch(node, floor_s):
            orig = node.learner.fit

            def fit(*a, **kw):
                t0 = time.monotonic()
                r = orig(*a, **kw)
                extra = floor_s - (time.monotonic() - t0)
                if extra > 0:
                    time.sleep(extra)
                return r

            node.learner.fit = fit

        for i, nd in enumerate(nodes):
            stretch(nd, fit_floor * (slow_x if nd is slow else 1.0))
        try:
            for nd in nodes:
                nd.start()
            for i in range(1, n_nodes):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n_nodes - 1, wait=30)
            t0 = time.monotonic()
            nodes[0].set_start_learning(rounds=windows, epochs=1, mode="async")
            deadline = time.monotonic() + 600
            while time.monotonic() < deadline:
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    and nd.learning_workflow.history.count(
                        "AsyncWindowFinishedStage"
                    )
                    >= windows
                    for nd in nodes
                ):
                    break
                time.sleep(0.25)
            else:
                raise TimeoutError("async arm did not finish")
            async_wall = time.monotonic() - t0

            # Window-level attribution from the shared in-process tracer.
            analyzer = CriticalPathAnalyzer.from_tracer(TRACER)
            wreport = analyzer.window_report()
            gated = sum(
                1
                for w in range(windows)
                if wreport["windows"].get(str(w), {}).get("gating_contributor")
                == slow.addr
            )

            # Digest-carried staleness p90 vs the buffer's exact measure, on
            # a fast observer that folded the slow peer's stale frames.
            observer = nodes[0]
            exact_lags = sorted(observer.async_agg.lag_log)
            dig = digest_mod.collect(observer.addr)
            sk = dig.sketch("staleness")
            if sk is None or not exact_lags:
                raise AssertionError(
                    "staleness sketch missing from the digest "
                    f"(sketch={sk}, lags={len(exact_lags)})"
                )
            # Same nearest-rank (floor) convention as the sketch's walk.
            exact_p90 = float(exact_lags[int(0.9 * (len(exact_lags) - 1))])
            sketch_p90 = sk.quantile(0.9)
            tol = max(0.5, 2.0 * sk.rel_err * max(1.0, exact_p90))
            digest_bytes_total = sum(
                c.value
                for lbl, c in REGISTRY.get("p2pfl_digest_bytes_total").samples()
            )
        finally:
            for nd in nodes:
                try:
                    nd.stop()
                except Exception:
                    pass
            InMemoryRegistry.reset()

        if gated < windows - 1:
            raise AssertionError(
                f"slow contributor gated only {gated}/{windows} windows "
                f"(report: {wreport['gating_counts']})"
            )
        if abs(sketch_p90 - exact_p90) > tol:
            raise AssertionError(
                f"digest staleness p90 {sketch_p90:.3f} vs exact "
                f"{exact_p90:.3f} exceeds sketch tolerance {tol:.3f}"
            )

        out = {
            "metric": "fleetobs_sublinear_observability",
            "value": big["digest_bytes"] / small["digest_bytes"],
            "unit": "digest_bytes_growth_8_to_10k",
            "vs_baseline": None,
            "extra": {
                "scale_points": scale_points,
                "federation_snapshot": snap_path,
                "top_n": top_n,
                "async": {
                    "nodes": n_nodes,
                    "windows": windows,
                    "slow_x": slow_x,
                    "slow_contributor": slow.addr,
                    "wall_s": round(async_wall, 2),
                    "gated_windows": gated,
                    "close_reason_counts": wreport["close_reason_counts"],
                    "mean_staleness_discount": wreport["mean_staleness_discount"],
                    "wait_wall_s_total": wreport["wait_wall_s_total"],
                    "staleness_p90_exact": exact_p90,
                    "staleness_p90_sketch": round(sketch_p90, 4),
                    "sketch_tolerance": round(tol, 4),
                    "digest_bytes_total_emitted": digest_bytes_total,
                },
                "note": "digest bytes and per-node observatory memory are "
                "measured at each fleet size; the snapshot renders via "
                "scripts/fed_top.py",
            },
        }
        out["meta"] = _bench_meta(seed=seed, backend="cpu")
        os.makedirs("artifacts", exist_ok=True)
        with open(os.path.join("artifacts", "FLEETOBS_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
        _phase(
            f"fleetobs bench done: digest {small['digest_bytes']}B -> "
            f"{big['digest_bytes']}B over {size_ratio:.0f}x fleet; slow peer "
            f"gated {gated}/{windows} windows"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_population_bench() -> None:
    """Subprocess-style mode ``--population``: population-scale engine
    acceptance run, three arms.

    **Engine arm** (``P2PFL_TPU_POP_BENCH_NODES`` virtual nodes — default
    the 100k north-star shape): one :class:`PopulationEngine` run of
    ``P2PFL_TPU_POP_BENCH_ROUNDS`` cohort-sampled rounds at
    ``P2PFL_TPU_POP_BENCH_COHORT`` fraction over the sharded fused mesh
    (the engine builds the same mesh ``--multihost`` workers join; this
    arm runs it on the local device set), trajectory ledger attached and
    seeded device-class speed tiers on. Reports s/round + final accuracy,
    writes the ``population_snapshot`` to
    ``artifacts/federation_snapshot.json``, and renders it through
    ``scripts/fed_top.py --once`` — the COHORT column must be populated
    and the mean realized cohort fill must equal K/n exactly.

    **Recovery arm** (scaled-down population, same engine code): a control
    engine runs R rounds uninterrupted; a second engine runs R/2 rounds,
    checkpoints (``FLCheckpointer``), and is destroyed — the killed host.
    A THIRD engine built fresh from the same spec restores the checkpoint
    and finishes the schedule. Final accuracy must match the control to
    0.0 pp, the node-0 canonical params hash must be bit-identical, and
    the replayed cohort-fill accounting must match the control's.

    **Scenario parity arm** (n=8 real wire nodes): one seeded
    :class:`PopulationScenario` (Dirichlet label skew + 50% cohort)
    executed by BOTH backends; the wire's rotating-observer stream must
    align with the fused ledger (``compare_ledgers``: status OK, every
    round's aggregate hash bit-exact, all wire nodes agreeing). Ledgers
    land under ``artifacts/population_scenario/`` and the report at
    ``artifacts/population_parity_diff.json`` — separate paths from the
    ``--parity`` arm's published artifacts, which this bench must not
    clobber.

    Shape overrides: the ``P2PFL_TPU_POP_BENCH_*`` Settings knobs — CI
    runs a small population; the default is the acceptance shape.
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol/scale bench: CPU venue
        import importlib.util

        import numpy as np
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.management.checkpoint import FLCheckpointer
        from p2pfl_tpu.population import PopulationEngine, PopulationScenario
        from p2pfl_tpu.population.scenarios import (
            run_scenario_fused,
            run_scenario_wire,
        )
        from p2pfl_tpu.telemetry.ledger import LEDGERS, canonical_params_hash

        spec = importlib.util.spec_from_file_location(
            "parity_diff", os.path.join(REPO, "scripts", "parity_diff.py")
        )
        parity_diff = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(parity_diff)

        n = int(Settings.POP_BENCH_NODES)
        rounds = int(Settings.POP_BENCH_ROUNDS)
        fraction = float(Settings.POP_BENCH_COHORT)
        seed = 42
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)
        snap_path = os.path.join(art, "federation_snapshot.json")

        # --- arm A: cohort-sampled engine run at population scale -------------
        _phase(
            f"population engine arm: n={n}, {rounds} rounds, "
            f"cohort {fraction:g}"
        )
        Settings.LEDGER_ENABLED = True
        LEDGERS.reset()
        LEDGERS.configure(f"population-bench-n{n}")
        t0 = time.monotonic()
        eng = PopulationEngine(
            n,
            cohort_fraction=fraction,
            seed=seed,
            speed_tiers=(1.0, 1.0, 1.0, 2.0, 5.0),  # device classes
        )
        build_s = time.monotonic() - t0
        try:
            cohort_k = eng.cohort_k
            led = eng.attach_ledger(run_id=f"population-bench-n{n}")
            res = eng.run(rounds, epochs=1)
            snap = eng.snapshot(res, path=snap_path)
            fill = eng.cohort_fill()
            # Exactly K of n nodes are solicited every round, so the mean
            # realized fill is K/n to fp precision — anything else means the
            # schedule and the accounting disagree.
            if abs(float(fill.mean()) * n - cohort_k) > 1e-6:
                raise AssertionError(
                    f"mean cohort fill {fill.mean():.6g} != K/n "
                    f"{cohort_k / n:.6g} at n={n}"
                )
            ledger_rounds = sum(
                1 for ev in led.canonical_events()
                if ev["kind"] == "aggregate_committed"
            )
            engine_hash = canonical_params_hash(eng.gather_params(0))
        finally:
            eng.close()
        shown_fill = [
            p.get("cohort_fill") for p in snap["peers"].values()
        ]
        if not shown_fill or any(v is None for v in shown_fill):
            raise AssertionError(
                "population_snapshot peers missing cohort_fill "
                f"(got {shown_fill[:4]}…)"
            )
        # The acceptance surface is the rendered view, not just the JSON:
        # the snapshot must round-trip through fed_top with the COHORT
        # column populated.
        top = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fed_top.py"),
             snap_path, "--once"],
            capture_output=True, text=True, timeout=60,
        )
        if top.returncode != 0 or "COHORT" not in top.stdout:
            raise AssertionError(
                f"fed_top render failed (rc={top.returncode}): "
                f"{top.stderr[-500:]}"
            )
        fed_top_head = top.stdout.splitlines()[:6]
        _phase(
            f"  n={n}: {res.seconds_per_round:.3f}s/round, final acc "
            f"{res.test_acc[-1]:.3f}, {ledger_rounds} ledger commits"
        )

        # --- arm B: kill-one-host recovery ------------------------------------
        n_rec = min(n, 256)
        rec_rounds, kill_after = 6, 3
        rec_kw = dict(cohort_fraction=0.25, seed=seed + 1)
        _phase(
            f"population recovery arm: n={n_rec}, kill after "
            f"{kill_after}/{rec_rounds} rounds"
        )
        with PopulationEngine(n_rec, **rec_kw) as ref:
            ref_res = ref.run(rec_rounds)
            ref_acc = float(ref_res.test_acc[-1])
            ref_hash = canonical_params_hash(ref.gather_params(0))
            ref_fill = ref.cohort_fill()
        with tempfile.TemporaryDirectory(prefix="pop_ckpt_") as ckpt_dir:
            ckpt = FLCheckpointer(ckpt_dir)
            with PopulationEngine(n_rec, **rec_kw) as victim:
                victim.run(kill_after)
                if not victim.save_to(ckpt):
                    raise AssertionError("population checkpoint save failed")
            # victim.close() == the host is gone; a FRESH engine (same spec,
            # new process in production) restores and finishes the schedule.
            with PopulationEngine(n_rec, **rec_kw) as healed:
                restored = healed.load_from(ckpt)
                if restored != kill_after:
                    raise AssertionError(
                        f"restored {restored} rounds, expected {kill_after}"
                    )
                rec_res = healed.run(rec_rounds - kill_after)
                rec_acc = float(rec_res.test_acc[-1])
                rec_hash = canonical_params_hash(healed.gather_params(0))
                rec_fill = healed.cohort_fill()
        acc_delta_pp = abs(rec_acc - ref_acc) * 100.0
        if rec_hash != ref_hash:
            raise AssertionError(
                f"recovery diverged: resumed hash {rec_hash[:16]}… != "
                f"control {ref_hash[:16]}…"
            )
        if acc_delta_pp != 0.0:
            raise AssertionError(
                f"recovery accuracy delta {acc_delta_pp:.4f} pp != 0.0 "
                f"(resumed {rec_acc:.4f} vs control {ref_acc:.4f})"
            )
        if not np.allclose(rec_fill, ref_fill):
            raise AssertionError(
                "replayed cohort-fill accounting diverged from control"
            )
        _phase(
            f"  recovery holds: acc {rec_acc:.3f} == control, hash "
            f"{rec_hash[:16]}… bit-identical"
        )

        # --- arm C: one scenario, two backends, parity-gated ------------------
        scn = PopulationScenario(
            seed=77, n_nodes=8, rounds=3, samples_per_node=32,
            batch_size=16, hidden=(16,), cohort_fraction=0.5,
            dirichlet_alpha=0.3,
        )
        _phase(
            f"population scenario arm: wire n={scn.n_nodes}, cohort "
            f"K={scn.cohort_k}, Dirichlet alpha={scn.dirichlet_alpha}"
        )
        pop_art = os.path.join(art, "population_scenario")
        os.makedirs(pop_art, exist_ok=True)
        t0 = time.monotonic()
        wire = run_scenario_wire(scn, ledger_dir=pop_art)
        wire_s = time.monotonic() - t0
        # Intra-backend: every node (member or not) committed the same bits.
        names = scn.node_names
        ref_hashes = wire["hashes"][names[0]]
        if len(ref_hashes) != scn.rounds:
            raise AssertionError(
                f"wire node0 committed rounds {sorted(ref_hashes)} of "
                f"{scn.rounds}"
            )
        for name in names:
            if wire["hashes"][name] != ref_hashes:
                raise AssertionError(
                    f"wire nodes disagree under cohort sampling: {name} "
                    f"committed {wire['hashes'][name]}, expected {ref_hashes}"
                )
        t0 = time.monotonic()
        fused = run_scenario_fused(scn, ledger_dir=pop_art)
        fused_s = time.monotonic() - t0
        report = parity_diff.compare_ledgers(wire["stitched"], fused["events"])
        with open(os.path.join(art, "population_parity_diff.json"), "w") as f:
            json.dump(report, f, indent=1)
        if report["status"] != "OK":
            raise AssertionError(
                "scenario parity DIVERGED: "
                f"{json.dumps(report.get('first_divergence'))}"
            )
        if report["hashes_compared"] != scn.rounds:
            raise AssertionError(
                f"only {report['hashes_compared']} of {scn.rounds} scenario "
                "aggregate hashes were bit-compared"
            )
        _phase(
            f"  scenario parity OK: {report['compared_events']} events "
            f"aligned, {report['hashes_compared']} hashes bit-exact "
            f"(wire {wire_s:.1f}s, fused {fused_s:.1f}s)"
        )

        out = {
            "metric": "population_sec_per_round",
            "value": round(res.seconds_per_round, 6),
            "unit": f"s/round at n={n}, cohort K={cohort_k}",
            "vs_baseline": None,
            "extra": {
                "nodes": n,
                "rounds": rounds,
                "cohort_fraction": fraction,
                "cohort_k": cohort_k,
                "engine_build_s": round(build_s, 2),
                "final_test_acc": round(float(res.test_acc[-1]), 4),
                "mean_cohort_fill": round(float(fill.mean()), 6),
                "ledger_commits": ledger_rounds,
                "engine_params_hash": engine_hash,
                "federation_snapshot": snap_path,
                "fed_top_head": fed_top_head,
                "recovery": {
                    "nodes": n_rec,
                    "rounds": rec_rounds,
                    "killed_after": kill_after,
                    "acc_delta_pp": acc_delta_pp,
                    "control_acc": round(ref_acc, 4),
                    "params_hash_match": True,
                },
                "scenario_parity": {
                    "nodes": scn.n_nodes,
                    "rounds": scn.rounds,
                    "cohort_k": scn.cohort_k,
                    "dirichlet_alpha": scn.dirichlet_alpha,
                    "status": report["status"],
                    "compared_events": report["compared_events"],
                    "hashes_compared": report["hashes_compared"],
                    "wire_s": round(wire_s, 2),
                    "fused_s": round(fused_s, 2),
                },
            },
        }
        out["meta"] = _bench_meta(seed=seed, backend="cpu")
        with open(os.path.join(art, "POPULATION_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
        _phase(
            f"population bench done: {res.seconds_per_round:.3f}s/round at "
            f"n={n}, recovery 0.0 pp, scenario parity OK"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_campaign_bench() -> None:
    """Subprocess-style mode ``--campaign``: the adversarial campaign
    universe (CPU venue — a protocol/robustness bench).

    Samples ``P2PFL_TPU_CAMPAIGN_SCENARIOS`` seeded scenarios (default 20,
    all distinct by construction — the sampler raises otherwise) from the
    declarative matrix in :mod:`p2pfl_tpu.campaigns.matrix`, executes each
    on BOTH backends (real wire + fused mesh), runs every pair under the
    ledger parity differ, and grades each against its scenario family's
    invariant catalog (:mod:`p2pfl_tpu.campaigns.invariants`).

    Acceptance, enforced here:

    * zero graded violations across the whole campaign;
    * at least one ADAPTIVE-adversary scenario whose realized decision
      stream flipped attacks mid-campaign (the ladder escalated off real
      admission rejections, not a prewritten script);
    * per-round aggregate hashes bit-exact wire-vs-fused for every family
      under the exact-parity contract (the privacy family instead proves
      the masked-vs-plain hash negative control).

    Ledgers land under ``artifacts/campaign_ledgers/<family>-<i>/``; the
    graded report (per-family arms for ``scripts/perf_diff.py``) is
    stamped with the bench meta block at ``artifacts/CAMPAIGN_BENCH.json``.
    ``make campaign-check`` replays the committed baseline subset of the
    same campaign (``tests/campaign_fixtures/campaign_baseline.json``).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.campaigns import run_campaign
        from p2pfl_tpu.config import Settings

        seed = int(Settings.CAMPAIGN_SEED)
        n = int(Settings.CAMPAIGN_SCENARIOS)
        art = os.path.join(REPO, "artifacts")
        ledger_dir = os.path.join(art, "campaign_ledgers")
        os.makedirs(ledger_dir, exist_ok=True)
        _phase(f"campaign: seed={seed}, {n} scenarios, both backends each")
        t0 = time.monotonic()
        rep = run_campaign(seed, n, ledger_dir=ledger_dir, emit=_phase)
        total_s = time.monotonic() - t0

        adaptive = [s for s in rep["scenarios"] if s["family"] == "adaptive"]
        if not adaptive:
            raise AssertionError("campaign sampled no adaptive-adversary scenario")
        switched = [
            s for s in adaptive
            if len({
                d["attack"] for d in s.get("adaptive", {}).get("decisions", ())
            }) >= 2
        ]
        if not switched:
            raise AssertionError(
                "no adaptive adversary flipped attacks mid-campaign "
                f"(decisions: {[s.get('adaptive') for s in adaptive]})"
            )
        if rep["violations_total"]:
            worst = [
                v for s in rep["scenarios"]
                for v in s.get("violations", [s.get("error", "")])
            ][:5]
            raise AssertionError(
                f"campaign graded {rep['violations_total']} violation(s): "
                f"{worst}"
            )
        ok = sum(1 for s in rep["scenarios"] if s["verdict"] == "ok")
        _phase(
            f"campaign done: {ok}/{n} scenarios ok across "
            f"{len(rep['families'])} families, {len(switched)} adaptive "
            f"ladder(s) escalated, {total_s:.0f}s total"
        )
        out = {
            "metric": "campaign_scenarios_ok",
            "value": ok,
            "unit": f"of {n} scenarios at seed {seed}",
            "vs_baseline": None,
            "extra": {
                "campaign": rep["campaign"],
                "campaign_seed": seed,
                "n_scenarios": n,
                "families": rep["families"],
                "adaptive_escalations": [
                    s["adaptive"]["decisions"] for s in switched
                ],
                "total_s": round(total_s, 2),
                "scenarios": [
                    {
                        k: s.get(k)
                        for k in (
                            "family", "index", "run_id", "seed", "verdict",
                            "parity_status", "wire_hashes", "fused_hashes",
                            "baseline_hashes", "seconds",
                        )
                    }
                    for s in rep["scenarios"]
                ],
            },
        }
        out["meta"] = _bench_meta(seed=seed, backend="cpu")
        with open(os.path.join(art, "CAMPAIGN_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_soak_bench() -> None:
    """Subprocess-style mode ``--soak``: supervisor overhead + healing
    acceptance (CPU venue — a robustness bench).

    **Overhead arm** (the ``--population`` 100k north-star shape): the same
    seeded engine schedule runs unsupervised (control) and under the
    :class:`~p2pfl_tpu.population.supervisor.EngineSupervisor` with
    per-cadence journaling, both timed AFTER a warmup chunk paid compile —
    the supervised/unsupervised wall ratio must stay ≤ 1.05× (journaling
    is write-ahead + async orbax; the scan loop must not feel it).

    **Healing arm** (64 vnodes): a seeded ``plan_host_faults`` trace
    (kill + OOM + SIGTERM) injected mid-schedule; the supervisor must heal
    every fault and land on a final canonical params hash bit-identical to
    a fault-free control.

    Stamps ``perf.supervisor`` (journal seconds/chunk, restarts, degrade
    steps, overhead ratio) — ``scripts/perf_diff.py`` gates those keys and
    REFUSES (exit 3) when exactly one side of a diff ran supervised.
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # robustness/protocol bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.chaos.plane import ChaosPlane
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.management.checkpoint import FLCheckpointer
        from p2pfl_tpu.management.profiler import perf_section
        from p2pfl_tpu.population import EngineSupervisor, PopulationEngine
        from p2pfl_tpu.telemetry import REGISTRY
        from p2pfl_tpu.telemetry.ledger import canonical_params_hash

        seed = 42
        n = int(Settings.POP_BENCH_NODES)
        fraction = float(Settings.POP_BENCH_COHORT)
        warm_rounds, timed_rounds, chunk = 2, 12, 2
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)

        def factory(**kw):
            args = dict(num_nodes=n, cohort_fraction=fraction, seed=seed)
            args.update(kw)
            return PopulationEngine(**args)

        # --- arm A: supervision overhead at the 100k shape --------------------
        _phase(
            f"soak overhead arm: n={n}, cohort {fraction:g}, "
            f"{timed_rounds} timed rounds (chunk={chunk})"
        )
        with factory() as ctrl:
            ctrl.run(warm_rounds)  # compile paid outside the timed window
            t0 = time.monotonic()
            for _ in range(timed_rounds // chunk):
                ctrl.run(chunk)
            control_s = time.monotonic() - t0
        with tempfile.TemporaryDirectory(prefix="soak_bench_") as ckdir:
            with FLCheckpointer(ckdir, max_to_keep=2) as ck:
                with EngineSupervisor(
                    factory, ck, node="soak-bench", journal_every=2,
                ) as sup:
                    sup.run(warm_rounds, chunk=chunk)  # compile + orbax setup
                    t0 = time.monotonic()
                    rep = sup.run(timed_rounds, chunk=chunk)
                    supervised_s = time.monotonic() - t0
        overhead_ratio = supervised_s / control_s
        journal_s_per_chunk = rep.journal_s / max(1, rep.chunks)
        _phase(
            f"  control {control_s:.2f}s, supervised {supervised_s:.2f}s "
            f"({rep.journals} journal(s), {rep.journal_s:.2f}s) -> "
            f"ratio {overhead_ratio:.3f}x"
        )
        if rep.parked or rep.total_restarts:
            raise AssertionError(
                f"overhead arm was not clean: parked={rep.parked} "
                f"restarts={rep.restarts}"
            )
        if overhead_ratio > 1.05:
            raise AssertionError(
                f"supervisor overhead {overhead_ratio:.3f}x > 1.05x budget "
                f"(journals cost {rep.journal_s:.2f}s of {supervised_s:.2f}s)"
            )

        # --- arm B: heal kill/OOM/SIGTERM to bit-identity ---------------------
        n_soak, chunks_soak = 64, 5
        faults = ChaosPlane().plan_host_faults(
            chunks_soak, seed=seed, kinds=("kill", "oom", "sigterm")
        )
        _phase(
            f"soak healing arm: n={n_soak}, faults "
            f"{[(ev.when, ev.kind) for ev in faults]}"
        )

        def soak_factory(**kw):
            args = dict(
                num_nodes=n_soak, cohort_fraction=0.25, cohort_min=4,
                samples_per_node=8, feature_dim=8, hidden=(8,), batch_size=4,
                seed=seed,
            )
            args.update(kw)
            return PopulationEngine(**args)

        with soak_factory() as clean:
            clean.run(chunks_soak)
            clean_hash = canonical_params_hash(clean.gather_params(0))
        with tempfile.TemporaryDirectory(prefix="soak_bench_heal_") as ckdir:
            with FLCheckpointer(ckdir, max_to_keep=2) as ck:
                with EngineSupervisor(
                    soak_factory, ck, node="soak-bench-heal", faults=faults,
                    backoff_s=0.0,
                ) as healer:
                    heal_rep = healer.run(chunks_soak, chunk=1)
                    healed_hash = (
                        None if heal_rep.parked
                        else canonical_params_hash(healer.engine.gather_params(0))
                    )
        if heal_rep.parked or heal_rep.completed != chunks_soak:
            raise AssertionError(
                f"healing arm parked={heal_rep.parked} completed="
                f"{heal_rep.completed}/{chunks_soak}"
            )
        if healed_hash != clean_hash:
            raise AssertionError(
                f"healed hash {healed_hash} != fault-free control {clean_hash}"
            )
        _phase(
            f"  healed {len(heal_rep.faults_executed)} fault(s), "
            f"{heal_rep.total_restarts} restart(s), hash bit-identical"
        )

        out = {
            "metric": "soak_overhead_ratio",
            "value": round(overhead_ratio, 4),
            "unit": f"x vs unsupervised at n={n}",
            "vs_baseline": None,
            "extra": {
                "nodes": n,
                "timed_rounds": timed_rounds,
                "chunk": chunk,
                "control_wall_s": round(control_s, 3),
                "supervised_wall_s": round(supervised_s, 3),
                "healing": {
                    "nodes": n_soak,
                    "chunks": chunks_soak,
                    "faults": [[ev.when, ev.kind] for ev in faults],
                    "restarts": dict(heal_rep.restarts),
                    "events": list(heal_rep.events),
                    "params_hash_match": True,
                },
            },
        }
        out["perf"] = perf_section(
            REGISTRY,
            extra={
                "supervisor": {
                    "journal_s_per_chunk": round(journal_s_per_chunk, 4),
                    "journals": int(rep.journals),
                    "overhead_ratio": round(overhead_ratio, 4),
                    "restarts": int(heal_rep.total_restarts),
                    "degrade_steps": len(heal_rep.degrade_steps),
                }
            },
        )
        out["meta"] = _bench_meta(seed=seed, backend="cpu")
        with open(os.path.join(art, "SOAK_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
        _phase(
            f"soak bench done: overhead {overhead_ratio:.3f}x <= 1.05x, "
            f"{heal_rep.total_restarts} fault(s) healed to bit-identity"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_asyncpop_bench() -> None:
    """Subprocess-style mode ``--asyncpop``: async-window population
    acceptance run, four arms, all on the CPU venue (protocol/scale bench).

    **Throughput arm** (``P2PFL_TPU_ASYNCPOP_BENCH_NODES`` vnodes, seeded
    slow tier ``(1,1,1,2,5)``): one :class:`AsyncPopulationEngine` run of
    ``P2PFL_TPU_ASYNCPOP_BENCH_WINDOWS`` windows; per-contribution
    simulated-time throughput must be ≥2x the sync barrier's over the SAME
    cohort stream at equal participation (``simulated_barrier_time`` over
    the matching committee schedule — the sync engine pays the slowest
    committee member every round; async windows close on fill).

    **IID control arm**: same engine vs the sync fused baseline at zero
    delay — final accuracy delta must be exactly 0.0 pp AND the global
    params hash bit-identical (the zero-lag windows ARE the sync rounds).

    **Flash-crowd arm**: the ``flash`` arrival trace (10x spike) must
    sustain window throughput with bounded staleness: fold lag is capped by
    ``ASYNCPOP_MAX_LAG`` by construction, and the scheduler's
    stall-patience backpressure must keep the pending queue bounded.

    **Ceiling arm**: doubling vnode loop (donation on, bf16 state, lean
    per-vnode data) toward ``P2PFL_TPU_ASYNCPOP_BENCH_CEILING``; records
    the max vnode count that completed windows and the limiting resource
    if below 1M. Writes ``artifacts/ASYNCPOP_BENCH.json``.
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol/scale bench: CPU venue
        import numpy as np
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.parallel.simulation import simulated_barrier_time
        from p2pfl_tpu.population import AsyncPopulationEngine, PopulationEngine
        from p2pfl_tpu.population.cohort import committee_schedule
        from p2pfl_tpu.telemetry.ledger import canonical_params_hash

        n = int(Settings.ASYNCPOP_BENCH_NODES)
        windows = int(Settings.ASYNCPOP_BENCH_WINDOWS)
        fraction = float(Settings.ASYNCPOP_BENCH_COHORT)
        seed = 42
        tiers = (1.0, 1.0, 1.0, 2.0, 5.0)
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)

        # --- arm A: simulated-time throughput vs the sync barrier -------------
        _phase(f"asyncpop throughput arm: n={n}, {windows} windows, cohort {fraction:g}")
        t0 = time.monotonic()
        eng = AsyncPopulationEngine(
            n, cohort_fraction=fraction, seed=seed, speed_tiers=tiers,
        )
        build_s = time.monotonic() - t0
        try:
            cohort_k = eng.cohort_k
            res = eng.run(windows, eval_every=max(1, windows // 2), warmup=True)
            summ = res.summary()
            snap_path = os.path.join(art, "asyncpop_snapshot.json")
            eng.snapshot(res, path=snap_path)
            node_speed = eng.node_speed
            sync_plan = eng.plan.cohort_plan
            names = eng.names
        finally:
            eng.close()
        contribs = summ["contributions"]
        async_ticks = summ["sim_time_ticks"]
        # Equal participation: enough sync rounds to solicit the same number
        # of contributions, each round paying its slowest member's tier.
        sync_rounds = max(1, int(np.ceil(contribs / cohort_k)))
        sync_comm = committee_schedule(sync_plan, names, sync_rounds, start_round=0)
        sync_ticks = simulated_barrier_time(sync_comm, node_speed)
        async_tpt = contribs / max(async_ticks, 1e-12)
        sync_tpt = (sync_rounds * cohort_k) / max(sync_ticks, 1e-12)
        speedup = async_tpt / max(sync_tpt, 1e-12)
        if speedup < 2.0:
            raise AssertionError(
                f"async simulated-time throughput {async_tpt:.2f} contrib/tick "
                f"is only {speedup:.2f}x the sync barrier's {sync_tpt:.2f} "
                "(acceptance floor: 2x)"
            )
        _phase(
            f"  n={n}: {res.seconds_per_window:.3f}s/window wall, "
            f"{speedup:.1f}x sync simulated throughput "
            f"({async_tpt:.1f} vs {sync_tpt:.1f} contrib/tick), "
            f"mean lag {summ['mean_lag']:.2f}"
        )

        # --- arm B: IID zero-delay control vs the sync fused baseline ---------
        n_ctl, r_ctl = 256, 5
        ctl_kw = dict(
            cohort_fraction=0.25, seed=seed + 1, samples_per_node=16,
            hidden=(16,),
        )
        _phase(f"asyncpop IID control arm: n={n_ctl}, {r_ctl} rounds")
        with PopulationEngine(n_ctl, **ctl_kw) as sync_eng:
            sync_res = sync_eng.run(r_ctl)
            sync_acc = float(sync_res.test_acc[-1])
            sync_hash = canonical_params_hash(sync_eng.gather_params(0))
        with AsyncPopulationEngine(n_ctl, **ctl_kw) as async_eng:
            async_res = async_eng.run(r_ctl)
            async_acc = float(async_res.test_acc[-1])
            async_hash = canonical_params_hash(async_eng.global_params())
        acc_delta_pp = abs(async_acc - sync_acc) * 100.0
        if async_hash != sync_hash:
            raise AssertionError(
                f"IID control diverged: async hash {async_hash[:16]}… != "
                f"sync {sync_hash[:16]}… — zero-lag windows must BE the "
                "sync rounds"
            )
        if acc_delta_pp != 0.0:
            raise AssertionError(
                f"IID control accuracy delta {acc_delta_pp:.4f} pp != 0.0"
            )
        _phase(f"  IID control holds: acc {async_acc:.3f}, hash bit-identical")

        # --- arm C: flash crowd sustains throughput, staleness bounded --------
        n_fc, period = 4096, 8
        fc_windows = 3 * period
        _phase(f"asyncpop flash-crowd arm: n={n_fc}, {fc_windows} windows, 10x spike")
        with AsyncPopulationEngine(
            n_fc, cohort_fraction=0.05, seed=seed + 2, speed_tiers=tiers,
            trace="flash", trace_period=period,
        ) as fc_eng:
            fc_k = fc_eng.cohort_k
            fc_res = fc_eng.run(fc_windows, eval_every=fc_windows)
            fc_sched = fc_res.schedule
            fc_patience = fc_eng.plan.resolved()[2]
        fc_summ = fc_res.summary()
        fc_max_lag = int(fc_sched.lag[fc_sched.present].max()) if fc_sched.present.any() else 0
        max_queue = int(fc_sched.queue_depth.max())
        queue_bound = (fc_patience + 1) * fc_k
        if fc_summ["contributions"] == 0:
            raise AssertionError("flash-crowd arm folded zero contributions")
        stalled = fc_summ["close_reasons"]["stall"]
        if stalled > fc_windows // 2:
            raise AssertionError(
                f"flash-crowd arm stalled {stalled}/{fc_windows} windows — "
                "throughput not sustained"
            )
        if fc_max_lag > int(Settings.ASYNCPOP_MAX_LAG):
            raise AssertionError(
                f"flash-crowd fold lag {fc_max_lag} exceeded the "
                f"ASYNCPOP_MAX_LAG={Settings.ASYNCPOP_MAX_LAG} bound"
            )
        if max_queue > queue_bound:
            raise AssertionError(
                f"flash-crowd pending queue {max_queue} blew past the "
                f"stall-patience backpressure bound {queue_bound}"
            )
        _phase(
            f"  flash crowd holds: {fc_summ['contributions']} contribs, "
            f"max lag {fc_max_lag} <= {Settings.ASYNCPOP_MAX_LAG}, "
            f"max queue {max_queue} <= {queue_bound}, "
            f"{fc_sched.dropped.sum()} dropped"
        )

        # --- arm D: vnode ceiling with donation + bf16 state ------------------
        ceiling_target = int(Settings.ASYNCPOP_BENCH_CEILING)
        probe_n = min(max(n, 125_000), ceiling_target)
        max_ok, ceiling_log, limit_reason = 0, [], None
        _phase(f"asyncpop ceiling arm: doubling from {probe_n} toward {ceiling_target}")
        while probe_n <= ceiling_target:
            try:
                t0 = time.monotonic()
                with AsyncPopulationEngine(
                    probe_n, cohort_fraction=min(fraction, 2048 / probe_n),
                    seed=seed + 3, speed_tiers=tiers,
                    samples_per_node=8, feature_dim=16,
                    state_dtype="bfloat16",
                ) as ceil_eng:
                    ceil_res = ceil_eng.run(2, eval_every=4)
                dt = time.monotonic() - t0
                max_ok = probe_n
                ceiling_log.append(
                    {"nodes": probe_n, "sec_per_window": round(ceil_res.seconds_per_window, 3),
                     "total_s": round(dt, 1)}
                )
                _phase(f"  ceiling: n={probe_n} OK ({ceil_res.seconds_per_window:.2f}s/window)")
            except (MemoryError, Exception) as e:  # noqa: BLE001 — record, stop
                limit_reason = (
                    f"{type(e).__name__} at n={probe_n}: {str(e)[:300]}"
                )
                _phase(f"  ceiling: n={probe_n} FAILED — {limit_reason}")
                break
            if probe_n == ceiling_target:
                break
            probe_n = min(probe_n * 2, ceiling_target)
        if max_ok >= ceiling_target:
            limiting_resource = None
        elif limit_reason is None:
            limiting_resource = "wall-clock budget (doubling loop ended early)"
        else:
            limiting_resource = (
                "host RAM for the [N]-stacked per-vnode data arrays — the "
                "history-ring engine carries no per-vnode params, so data "
                f"rows dominate ({limit_reason})"
            )

        out = {
            "metric": "asyncpop_speedup_vs_sync_barrier",
            "value": round(speedup, 3),
            "unit": f"x sim-time throughput at n={n}, cohort K={cohort_k}",
            "vs_baseline": None,
            "extra": {
                "nodes": n,
                "windows": windows,
                "cohort_k": cohort_k,
                "engine_build_s": round(build_s, 2),
                "sec_per_window_wall": round(res.seconds_per_window, 4),
                "contributions": contribs,
                "async_sim_ticks": round(async_ticks, 1),
                "sync_sim_ticks": round(sync_ticks, 1),
                "async_contribs_per_tick": round(async_tpt, 2),
                "sync_contribs_per_tick": round(sync_tpt, 2),
                "mean_fold_lag": round(summ["mean_lag"], 3),
                "close_reasons": summ["close_reasons"],
                "snapshot": snap_path,
                "iid_control": {
                    "nodes": n_ctl,
                    "rounds": r_ctl,
                    "acc_delta_pp": acc_delta_pp,
                    "params_hash_match": True,
                    "final_acc": round(async_acc, 4),
                },
                "flash_crowd": {
                    "nodes": n_fc,
                    "windows": fc_windows,
                    "period": period,
                    "contributions": fc_summ["contributions"],
                    "max_fold_lag": fc_max_lag,
                    "max_lag_bound": int(Settings.ASYNCPOP_MAX_LAG),
                    "max_queue_depth": max_queue,
                    "queue_bound": queue_bound,
                    "dropped": int(fc_sched.dropped.sum()),
                    "close_reasons": fc_summ["close_reasons"],
                },
                "ceiling": {
                    "target": ceiling_target,
                    "max_vnodes_ok": max_ok,
                    "donation": True,
                    "state_dtype": "bfloat16",
                    "limiting_resource": limiting_resource,
                    "log": ceiling_log,
                },
            },
        }
        out["meta"] = _bench_meta(seed=seed, backend="cpu")
        with open(os.path.join(art, "ASYNCPOP_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
        _phase(
            f"asyncpop bench done: {speedup:.1f}x sync, IID 0.0 pp, "
            f"flash crowd bounded, ceiling {max_ok}"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_devobs_bench() -> None:
    """Subprocess-style mode ``--devobs``: device-observatory acceptance
    run, three arms, all on the CPU venue (protocol/scale bench).

    **Overhead arm** (``P2PFL_TPU_DEVOBS_BENCH_NODES`` vnodes, default the
    100k north-star shape): the SAME seeded cohort-sampled population runs
    twice — in-scan telemetry on, then off — warmup first, best-of-two
    timed calls each. Gates: wall ratio on/off under
    ``DEVOBS_BENCH_MAX_OVERHEAD`` (default 1.05 — the aux stream rides the
    scan's ys side, so <5% is the contract, not a hope) AND the node-0
    canonical params hash BIT-IDENTICAL between the two arms (telemetry
    must never touch the math). The on-arm's sketch stream
    (``update_norm`` / ``train_loss``), the ``p2pfl_mesh_*`` Prometheus
    family, and a fed_top render with the LOSS/GNORM columns populated are
    all asserted, and the ``perf.devobs`` block (device peak bytes,
    compile seconds, AOT scan FLOPs/bytes) is stamped for
    ``scripts/perf_diff.py``'s devobs gate.

    **Tripwire arm** (small population, seeded NaN injection via
    ``DEVOBS_NAN_INJECT_ROUND``): with ``park`` the run must stop within
    the injected round's chunk, return a partial result carrying the trip
    record, and dump the flight recorder; with ``abort`` the same trip
    must raise with state parked (params still readable).

    Shape overrides: the ``P2PFL_TPU_DEVOBS_BENCH_*`` Settings knobs — CI
    runs a small population; the default is the acceptance shape.
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol/scale bench: CPU venue
        import numpy as np  # noqa: F401

        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.management.profiler import (
            device_memory_watermark,
            perf_section,
        )
        from p2pfl_tpu.population import PopulationEngine
        from p2pfl_tpu.telemetry import REGISTRY
        from p2pfl_tpu.telemetry.export import render_prometheus
        from p2pfl_tpu.telemetry.ledger import canonical_params_hash
        from p2pfl_tpu.telemetry.sketches import SKETCHES

        n = int(Settings.DEVOBS_BENCH_NODES)
        rounds = int(Settings.DEVOBS_BENCH_ROUNDS)
        fraction = float(Settings.DEVOBS_BENCH_COHORT)
        max_overhead = float(Settings.DEVOBS_BENCH_MAX_OVERHEAD)
        rpc = max(1, rounds // 2)
        seed = 42
        art = os.path.join(REPO, "artifacts")
        os.makedirs(art, exist_ok=True)
        snap_path = os.path.join(art, "federation_snapshot.json")

        def _timed_arm(devobs_on: bool, snapshot: bool):
            """(best wall, params hash, compile wall, engine extras)."""
            Settings.DEVOBS_ENABLED = devobs_on
            Settings.DEVOBS_NAN_INJECT_ROUND = -1
            eng = PopulationEngine(
                n, cohort_fraction=fraction, seed=seed,
                speed_tiers=(1.0, 1.0, 1.0, 2.0, 5.0),
            )
            try:
                t0 = time.monotonic()
                res = eng.run(rounds, warmup=True, rounds_per_call=rpc)
                compile_s = (time.monotonic() - t0) - res.seconds_total
                walls = [res.seconds_total]
                res2 = eng.run(rounds, rounds_per_call=rpc)
                walls.append(res2.seconds_total)
                h = canonical_params_hash(eng.gather_params(0))
                extra: dict = {}
                if snapshot:
                    eng.snapshot(res2, path=snap_path)
                    # AOT cost analysis of the exact scanned program (the
                    # perf.devobs gate's FLOPs/bytes source).
                    extra["cost"] = eng.sim.round_cost_analysis(
                        rounds_per_call=rpc, devobs=devobs_on
                    )
                return min(walls), h, compile_s, extra
            finally:
                eng.close()

        _phase(
            f"devobs overhead arm: n={n}, {rounds} rounds x2 calls, "
            f"cohort {fraction:g}, telemetry ON"
        )
        REGISTRY.reset()
        SKETCHES.reset()
        on_wall, on_hash, compile_s, on_extra = _timed_arm(True, snapshot=True)
        for metric in ("update_norm", "train_loss"):
            sk = SKETCHES.get(metric, "mesh-sim")
            if sk is None or sk.count <= 0:
                raise AssertionError(
                    f"devobs on-arm streamed no {metric} sketch buckets"
                )
        prom = render_prometheus(REGISTRY)
        if "p2pfl_mesh_train_loss" not in prom or "p2pfl_mesh_round" not in prom:
            raise AssertionError(
                "p2pfl_mesh_* family missing from the Prometheus exposition"
            )
        wm = device_memory_watermark()
        _phase(f"devobs overhead arm: telemetry OFF (same seed/shape)")
        off_wall, off_hash, _, _ = _timed_arm(False, snapshot=False)
        overhead = on_wall / max(off_wall, 1e-9)
        if on_hash != off_hash:
            raise AssertionError(
                f"telemetry changed the math: on-hash {on_hash} != "
                f"off-hash {off_hash}"
            )
        if overhead > max_overhead:
            raise AssertionError(
                f"devobs overhead {overhead:.3f}x exceeds the "
                f"{max_overhead:g}x gate (on {on_wall:.2f}s / off "
                f"{off_wall:.2f}s)"
            )
        # Acceptance surface is the rendered view: LOSS/GNORM must be
        # populated (not '-') for the tracked virtual rows.
        top = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "fed_top.py"),
             snap_path, "--once"],
            capture_output=True, text=True, timeout=60,
        )
        if top.returncode != 0 or "LOSS" not in top.stdout:
            raise AssertionError(
                f"fed_top render failed (rc={top.returncode}): "
                f"{top.stderr[-500:]}"
            )
        with open(snap_path) as f:
            snap_doc = json.load(f)
        grafted = [
            p for p in snap_doc["peers"].values()
            if p.get("stage") == "virtual" and p.get("loss") is not None
        ]
        if not grafted:
            raise AssertionError(
                "no virtual peer row carries the in-scan loss graft"
            )
        _phase(
            f"devobs overhead: {overhead:.3f}x (on {on_wall:.2f}s / off "
            f"{off_wall:.2f}s), params hash identical"
        )

        # --- tripwire arm ----------------------------------------------------
        n_trip = min(n, 256)
        inject_at = 3  # chunk 1 with rounds_per_call=2
        trip_rpc = 2
        _phase(
            f"devobs tripwire arm: n={n_trip}, NaN injected at round "
            f"{inject_at}, park then abort"
        )
        Settings.DEVOBS_ENABLED = True
        Settings.DEVOBS_NAN_INJECT_ROUND = inject_at
        Settings.DEVOBS_TRIP_ACTION = "park"
        with PopulationEngine(n_trip, cohort_fraction=0.25, seed=seed + 1) as eng:
            res = eng.run(8, rounds_per_call=trip_rpc)
            trip = res.tripped
            if trip is None or trip["kind"] != "nonfinite":
                raise AssertionError(f"park arm did not trip: {trip}")
            if trip["round"] != inject_at:
                raise AssertionError(
                    f"tripped at round {trip['round']}, injected {inject_at}"
                )
            # Within one chunk: the run stopped at the tripping chunk's
            # boundary, not after the full schedule.
            tripped_chunk_end = (inject_at // trip_rpc + 1) * trip_rpc
            if res.rounds != tripped_chunk_end:
                raise AssertionError(
                    f"park arm ran {res.rounds} rounds; expected to stop at "
                    f"the tripping chunk boundary {tripped_chunk_end}"
                )
            flightrec = trip.get("flightrec")
            if not flightrec or not os.path.exists(flightrec):
                raise AssertionError(
                    f"tripwire flight-recorder dump missing: {flightrec}"
                )
        Settings.DEVOBS_TRIP_ACTION = "abort"
        abort_raised = False
        eng = PopulationEngine(n_trip, cohort_fraction=0.25, seed=seed + 2)
        try:
            try:
                eng.run(8, rounds_per_call=trip_rpc)
            except RuntimeError as err:
                abort_raised = "devobs tripwire" in str(err)
            if not abort_raised:
                raise AssertionError("abort arm did not raise the trip contract")
            if eng.sim.params_stack is None:
                raise AssertionError("abort arm nuked state; expected it parked")
            canonical_params_hash(eng.gather_params(0))  # parked == readable
        finally:
            eng.close()
        Settings.DEVOBS_NAN_INJECT_ROUND = -1
        Settings.DEVOBS_TRIP_ACTION = "abort"
        _phase("devobs tripwire arm: park partial + abort raise both honored")

        cost = on_extra.get("cost") or {}
        out = {
            "bench": "p2pfl_tpu",
            "mode": "devobs",
            "metric": "devobs_overhead_ratio",
            "value": round(overhead, 4),
            "unit": "x_on_over_off",
            "extra": {
                "nodes": n,
                "rounds_per_call": rpc,
                "rounds_per_arm": rounds,
                "wall_s_on": round(on_wall, 4),
                "wall_s_off": round(off_wall, 4),
                "max_overhead": max_overhead,
                "params_hash_match": True,
                "snapshot": snap_path,
                "tripwire": {
                    "nodes": n_trip,
                    "inject_round": inject_at,
                    "park_rounds_run": tripped_chunk_end,
                    "flightrec": flightrec,
                    "abort_raised": True,
                },
            },
        }
        out["perf"] = perf_section(
            REGISTRY,
            cost=cost or None,
            extra={
                "devobs": {
                    "device_peak_bytes": wm["peak_bytes_in_use"],
                    "compile_seconds": round(max(0.0, compile_s), 4),
                    "scan_flops": cost.get("flops"),
                    "scan_bytes": cost.get("bytes_accessed"),
                }
            },
        )
        out["meta"] = _bench_meta(seed=seed, backend="cpu")
        with open(os.path.join(art, "DEVOBS_BENCH.json"), "w") as f:
            json.dump(out, f, indent=1)
        _phase(
            f"devobs bench done: {overhead:.3f}x overhead, hash identical, "
            f"NaN tripped in-chunk at round {inject_at}"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_critical_path_bench() -> None:
    """Subprocess-style mode ``--critical-path``: performance-attribution
    acceptance run.

    One 8-node in-memory MNIST federation over the real Node/gossip stack
    with ONE seeded 3x-slow straggler (its ``fit`` is stretched to ~3x by
    sleeping twice the measured fit duration, capped below the aggregation
    deadlines; stall patience is raised so the fleet WAITS for it — the
    straggler gates rounds instead of being abandoned). After the run the
    federation-wide span DAG is fed to the critical-path analyzer and the
    bench asserts the attribution contract:

    * every round yields a critical path with an identified gating node,
    * the seeded straggler is the gating node on >= 80% of round paths,
    * the report carries per-stage wall-clock shares and the
      train<->diffuse overlap fraction (ROADMAP item 4's before-number),
    * the structured ``perf`` section (XLA FLOPs/bytes from the learner's
      compiled train-epoch, compile + recompile events, windowed device
      trace) lands in ``artifacts/CRITICAL_PATH_BENCH.json``, and
      ``scripts/perf_diff.py`` exits 0 diffing that file against itself
      and NONZERO against an injected 2x regression.

    Shape overrides: P2PFL_TPU_CP_NODES (default 8), P2PFL_TPU_CP_ROUNDS
    (default 5), P2PFL_TPU_CP_SEED (42), P2PFL_TPU_CP_SLOWDOWN (3.0).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.management.profiler import perf_section
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY, TRACER, CriticalPathAnalyzer
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_CP_NODES", "8"))
        rounds = int(os.environ.get("P2PFL_TPU_CP_ROUNDS", "5"))
        seed = int(os.environ.get("P2PFL_TPU_CP_SEED", "42"))
        slowdown = float(os.environ.get("P2PFL_TPU_CP_SLOWDOWN", "3.0"))
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        # Everyone trains, so the straggler is in every committee and its
        # slow fit is load-bearing for every aggregation.
        Settings.TRAIN_SET_SIZE = n_nodes
        # The fleet must WAIT for the straggler (gating, not abandonment):
        # stall patience sits ABOVE the stretched fit (capped at 8 s below)
        # but below the aggregation timeout, so a genuine stall still
        # unblocks. The observatory bench exercises the opposite regime
        # (straggler beyond patience -> abandoned and lagging).
        Settings.AGGREGATION_STALL_PATIENCE = 35.0
        # Deadlines widened to match: the straggle below is up to 20 s, and
        # a 1-core host can smear honest fits by ~10 s of scheduler noise
        # on a bad round — gating must come from the SEEDED straggler, not
        # from a timeout artifact.
        Settings.VOTE_TIMEOUT = 30.0
        Settings.AGGREGATION_TIMEOUT = 90.0
        # A pegged 1-core host starves daemon threads for seconds at a
        # time: the test-default 1.5 s heartbeat timeout then declares
        # healthy peers dead mid-round (observed: a partitioned node
        # soloing the experiment), and the 2 s gossip stall-abandon window
        # gives up on peers that are merely descheduled. Both bounds are
        # liveness tunables, not correctness ones — widen them so the only
        # seeded anomaly in this bench is the straggler itself.
        Settings.HEARTBEAT_TIMEOUT = 10.0
        Settings.GOSSIP_EXIT_ON_X_EQUAL_ROUNDS = 400
        # Every node gets its own executor slot: with the cpu_count-derived
        # default (2 on this host) fits QUEUE behind each other, so the
        # straggler's sleep holds a slot and serializes into whichever
        # honest node queued behind it — that node's "fit" span then
        # inherits the straggle and steals the gating attribution.
        Settings.EXECUTOR_MAX_WORKERS = n_nodes
        # This bench measures the ATTRIBUTION contract (the seeded straggler
        # must gate >= 80% of round critical paths) against the serialized
        # reference stage machine — pin train<->diffuse overlap OFF so
        # background drains and vote-RTT prefit threads don't smear the
        # early rounds' gating on a contended 1-core host. The overlap
        # measurement itself is owned by bench --wire (overlap section in
        # WIRE_BENCH.json) and the make wire-check gate.
        Settings.OVERLAP_TRAIN_DIFFUSE = False
        # Continuous profiling: the windowed device trace is captured
        # around the WARMUP fit below, not inside the measured federation
        # (PERF_TRACE_DIR stays unset) — an open jax.profiler window traces
        # the whole process, and on a 1-core host that overhead distorts
        # the very round timings this bench attributes (observed: honest
        # fits inflated ~10x while the window stayed open across the
        # straggler's stretched fit).
        REGISTRY.reset()
        TRACER.reset()

        _phase(
            f"critical-path bench: {n_nodes} nodes, {rounds} rounds, "
            f"{slowdown:.1f}x straggler"
        )
        # Tiny fits (128 samples -> 8 steps at 2 epochs): on a 1-core
        # host, 8 concurrent heavy fits smear each round across many
        # seconds of scheduler noise, which both desynchronizes the leaky
        # vote barrier and drowns the straggle being measured. The straggle
        # FLOOR below guarantees the margin; the fit only needs to be real.
        data = synthetic_mnist(n_train=128 * n_nodes, n_test=128)
        parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
        # One SHARED apply_fn across the fleet (per-node params still differ
        # via build_copy): nodes in one process then share one XLA program —
        # one compile total, like a real per-process deployment — instead of
        # 8 identity-distinct compiles whose serialized first-fit costs
        # desynchronize round 0 by more than the straggle being measured.
        template = mlp_model(seed=0)
        # Pre-warm the shared train/eval programs on a THROWAWAY learner:
        # round 0 must measure federation dynamics, not one ~10 s XLA
        # compile amplified by 8-way CPU contention (which can push the
        # stretched straggler fit past stall patience and flip the fleet
        # into the abandon regime this bench is not about).
        from p2pfl_tpu.learning.learner import JaxLearner

        from p2pfl_tpu.management.profiler import device_trace_window

        _phase("critical-path bench: pre-warming the shared XLA programs")
        warm = JaxLearner(
            template.build_copy(), parts[0], self_addr="mem://warmup",
            batch_size=32, seed=0,
        )
        warm.set_epochs(1)
        with device_trace_window(
            os.path.join("artifacts", "perf_traces"), label="warmup_fit"
        ):
            warm.fit()
        warm.evaluate()
        del warm
        nodes = [
            Node(
                template.build_copy(params=mlp_model(seed=i).get_parameters()),
                parts[i], batch_size=32,
            )
            for i in range(n_nodes)
        ]
        straggler = nodes[1]
        inner_fit = straggler.learner.fit
        measured_factor: list = []

        def slow_fit(*a, **kw):
            t0 = time.monotonic()
            m = inner_fit(*a, **kw)
            dt = time.monotonic() - t0
            # Stretch to ~slowdown x. The 15 s floor keeps the straggle
            # decisive on a contended 1-core host, where concurrent fits
            # inflate any node's wall-clock by up to ~10 s of scheduler
            # luck on a bad round (a sleeping straggler yields its core, so
            # a purely relative stretch can vanish into that noise); the
            # 20 s cap stays below stall patience (35 s) and the
            # aggregation deadline (90 s) so the fleet waits for the
            # straggler rather than abandoning it.
            extra = min(max(dt * (slowdown - 1.0), 15.0), 20.0)
            measured_factor.append((dt + extra) / max(dt, 1e-9))
            time.sleep(extra)
            return m

        straggler.learner.fit = slow_fit

        for nd in nodes:
            nd.start()
        try:
            for i in range(1, n_nodes):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n_nodes - 1, wait=30)
            t0 = time.monotonic()
            nodes[0].set_start_learning(rounds=rounds, epochs=2)
            deadline = time.time() + 900
            while time.time() < deadline:
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in nodes
                ):
                    break
                time.sleep(0.25)
            else:
                raise TimeoutError("critical-path federation did not finish")
            wall_s = time.monotonic() - t0
        finally:
            for nd in nodes:
                nd.stop()
            InMemoryRegistry.reset()

        # --- attribution ----------------------------------------------------
        analyzer = CriticalPathAnalyzer.from_tracer(TRACER)
        report = analyzer.report()
        seen_rounds = analyzer.rounds()
        missing = [r for r in range(rounds) if r not in seen_rounds]
        if missing:
            raise AssertionError(f"no spans for rounds {missing}")
        gating_by_round = {
            r: report["rounds"][str(r)]["gating_node"] for r in range(rounds)
        }
        unattributed = [r for r, g in gating_by_round.items() if not g]
        if unattributed:
            raise AssertionError(
                f"rounds without a gating node: {unattributed}"
            )
        gated = sum(1 for g in gating_by_round.values() if g == straggler.addr)
        frac = gated / rounds
        _phase(
            f"critical-path: straggler gates {gated}/{rounds} rounds "
            f"({frac:.0%}); per-round {gating_by_round}"
        )
        if frac < 0.8:
            # Diagnosable failure: dump every round's walk before raising.
            for r in range(rounds):
                rp = report["rounds"][str(r)]
                _phase(
                    f"  round {r}: gating={rp['gating_node']} "
                    f"wall={rp['wall_s']:.2f} attr={rp['attributed_by_node']}"
                )
                for h in rp["path"]:
                    _phase(
                        f"    {h['start_s']:9.3f}..{h['end_s']:9.3f} "
                        f"attr={h['attributed_s']:6.3f} {h['node'][-7:]:8s} "
                        f"{h['name']} [{h['kind']}]"
                    )
            os.makedirs("artifacts", exist_ok=True)
            with open(
                os.path.join("artifacts", "CRITICAL_PATH_BENCH.failed.json"), "w"
            ) as f:
                json.dump(report, f, indent=1)
            raise AssertionError(
                f"straggler {straggler.addr} gates only {frac:.0%} of round "
                f"critical paths (< 80%): {gating_by_round}"
            )
        overlap = report["overlap"]

        # --- structured perf section ---------------------------------------
        cost = nodes[0].learner.cost_analysis()
        perf = perf_section(REGISTRY, cost=cost)
        if not cost or not cost.get("flops_per_epoch"):
            raise AssertionError(
                f"XLA cost analysis missing from the perf section: {cost}"
            )

        mean_wall = sum(
            report["rounds"][str(r)]["wall_s"] for r in range(rounds)
        ) / rounds
        out = {
            "metric": f"critical_path_{n_nodes}node_mnist_3x_straggler",
            "value": round(frac, 4),
            "unit": "fraction_rounds_gated_by_straggler",
            "vs_baseline": None,
            "meta": _bench_meta(seed=seed, backend="cpu"),
            "perf": perf,
            "extra": {
                "nodes": n_nodes,
                "rounds": rounds,
                "seed": seed,
                "straggler": straggler.addr,
                "target_slowdown_x": slowdown,
                "measured_slowdown_x": round(
                    sum(measured_factor) / len(measured_factor), 2
                )
                if measured_factor
                else None,
                "wall_s": round(wall_s, 2),
                "mean_round_wall_s": round(mean_wall, 4),
                "gating_by_round": {str(r): g for r, g in gating_by_round.items()},
                "stage_shares": report["stage_shares"],
                "train_diffuse_overlap_fraction": overlap[
                    "train_diffuse_overlap_fraction"
                ],
                "serialized_diffuse_s": overlap["serialized_diffuse_s"],
                "critical_path_report": report,
                "note": "gating node = node with the largest attributed share "
                "of each round's critical path (telemetry/critical_path.py); "
                "overlap fraction ~0 quantifies the serialized train->gossip "
                "headroom ROADMAP item 4 will reclaim",
            },
        }

        # --- artifact + perf_diff exit-code demonstration -------------------
        os.makedirs("artifacts", exist_ok=True)
        bench_path = os.path.join("artifacts", "CRITICAL_PATH_BENCH.json")
        with open(bench_path, "w") as f:
            json.dump(out, f, indent=1)
        regressed = json.loads(json.dumps(out))
        regressed["extra"]["mean_round_wall_s"] *= 2.0
        for node_label in regressed["perf"]["steady_state"]["step_s"]:
            regressed["perf"]["steady_state"]["step_s"][node_label] *= 2.0
        reg_path = os.path.join("artifacts", "CRITICAL_PATH_BENCH.regressed.json")
        with open(reg_path, "w") as f:
            json.dump(regressed, f, indent=1)
        diff = os.path.join(REPO, "scripts", "perf_diff.py")
        rc_self = subprocess.run(
            [sys.executable, diff, bench_path, bench_path],
            capture_output=True, text=True, cwd=REPO,
        ).returncode
        rc_reg = subprocess.run(
            [sys.executable, diff, bench_path, reg_path],
            capture_output=True, text=True, cwd=REPO,
        ).returncode
        if rc_self != 0:
            raise AssertionError(f"perf_diff flagged a self-diff (rc={rc_self})")
        if rc_reg == 0:
            raise AssertionError("perf_diff missed an injected 2x regression")
        out["extra"]["perf_diff_self_rc"] = rc_self
        out["extra"]["perf_diff_regressed_rc"] = rc_reg
        with open(bench_path, "w") as f:
            json.dump(out, f, indent=1)
        _phase(
            f"critical-path bench done: {frac:.0%} gated, report at {bench_path}"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, seed=locals().get("seed"), backend="cpu")


def run_telemetry_bench() -> None:
    """Subprocess-style mode ``--telemetry``: run an 8-node in-memory MNIST
    federation (sparse delta wire path, so codec metrics engage) with the
    telemetry plane on, then emit ONE JSON line embedding (a) the metrics
    registry snapshot (gossip bytes, compression ratio, aggregation wait,
    per-stage durations, learner timings), (b) a per-round stage breakdown
    computed from the round trace, and (c) pointers to the Prometheus text
    snapshot + Perfetto-loadable Chrome trace written under artifacts/.

    Shape overrides: P2PFL_TPU_TELEMETRY_NODES (default 8),
    P2PFL_TPU_TELEMETRY_ROUNDS (default 2).
    """
    out: dict = {}
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"  # protocol-stack bench: CPU venue
        import jax

        jax.config.update("jax_platforms", "cpu")
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
        from p2pfl_tpu.config import Settings
        from p2pfl_tpu.learning.dataset import (
            RandomIIDPartitionStrategy,
            synthetic_mnist,
        )
        from p2pfl_tpu.models import mlp_model
        from p2pfl_tpu.node import Node
        from p2pfl_tpu.telemetry import REGISTRY, TRACER
        from p2pfl_tpu.telemetry.export import render_prometheus, snapshot
        from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

        n_nodes = int(os.environ.get("P2PFL_TPU_TELEMETRY_NODES", "8"))
        rounds = int(os.environ.get("P2PFL_TPU_TELEMETRY_ROUNDS", "2"))
        set_test_settings()
        Settings.RESOURCE_MONITOR_PERIOD = 0
        Settings.LOG_LEVEL = "WARNING"
        Settings.TRAIN_SET_SIZE = n_nodes
        Settings.WIRE_COMPRESSION = "topk"  # engage the delta codec metrics

        REGISTRY.reset()
        TRACER.reset()
        _phase(f"telemetry bench: {n_nodes}-node federation, {rounds} rounds")
        data = synthetic_mnist(n_train=256 * n_nodes, n_test=256)
        parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
        nodes = [
            Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n_nodes)
        ]
        for nd in nodes:
            nd.start()
        try:
            for i in range(1, n_nodes):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n_nodes - 1, wait=30)
            nodes[0].set_start_learning(rounds=rounds, epochs=1)
            deadline = time.time() + 900
            while time.time() < deadline:
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in nodes
                ):
                    break
                time.sleep(0.25)
            else:
                raise TimeoutError("telemetry federation did not finish")
        finally:
            for nd in nodes:
                nd.stop()
            InMemoryRegistry.reset()

        # --- export surfaces ------------------------------------------------
        prom_text = render_prometheus(REGISTRY)
        snap = snapshot(REGISTRY)
        trace = TRACER.export_chrome_trace()
        os.makedirs("artifacts", exist_ok=True)
        prom_path = os.path.join("artifacts", "telemetry_snapshot.prom")
        trace_path = os.path.join("artifacts", "telemetry_trace.json")
        with open(prom_path, "w") as f:
            f.write(prom_text)
        with open(trace_path, "w") as f:
            json.dump(trace, f)

        core_families = [
            "p2pfl_gossip_tx_bytes_total",
            "p2pfl_gossip_rx_bytes_total",
            "p2pfl_wire_compression_ratio",
            "p2pfl_aggregation_wait_seconds",
            "p2pfl_stage_duration_seconds",
            "p2pfl_learner_jit_compile_seconds",
        ]
        missing = [
            fam
            for fam in core_families
            if fam not in snap or not snap[fam]["samples"]
        ]
        if missing:
            raise AssertionError(f"metric families missing from snapshot: {missing}")

        # --- per-round stage breakdown from the trace -----------------------
        spans = TRACER.spans()
        stage_breakdown: dict = {}
        for s in spans:
            r = s.args.get("round")
            if r is None or s.name.startswith("recv:"):
                continue
            row = stage_breakdown.setdefault(str(r), {}).setdefault(
                s.name, {"total_s": 0.0, "count": 0}
            )
            row["total_s"] = round(row["total_s"] + s.dur_s, 4)
            row["count"] += 1

        # --- cross-node trace assertion -------------------------------------
        exp_traces = {s.trace_id for s in spans if s.name == "experiment"}
        recv_traces = {s.trace_id for s in spans if s.name.startswith("recv:")}
        cross_node_ok = len(exp_traces) == 1 and recv_traces <= exp_traces
        if not cross_node_ok:
            raise AssertionError(
                f"cross-node spans do not share one trace id: "
                f"experiments={exp_traces}, recv={recv_traces}"
            )

        # --- hot-path overhead (the acceptance sanity number) ---------------
        child = REGISTRY.counter(
            "p2pfl_bench_overhead_probe_total", "overhead probe", labels=("node",)
        ).labels("bench")
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(20000):
                child.inc()
            best = min(best, (time.perf_counter() - t0) / 20000)

        def _series(fam: str) -> list:
            return snap.get(fam, {}).get("samples", [])

        tx_bytes_total = sum(s["value"] for s in _series("p2pfl_gossip_tx_bytes_total"))
        ratios = [s["value"] for s in _series("p2pfl_wire_compression_ratio")]
        agg_wait = _series("p2pfl_aggregation_wait_seconds")
        out = {
            "metric": "telemetry_plane_8node_mnist_fedavg",
            "value": round(best * 1e6, 3),
            "unit": "us/counter_increment",
            "vs_baseline": None,
            "extra": {
                "nodes": n_nodes,
                "rounds": rounds,
                "span_count": len(spans),
                "trace_id": sorted(exp_traces)[0],
                "cross_node_trace_ok": cross_node_ok,
                "gossip_tx_bytes_total": int(tx_bytes_total),
                "compression_ratio_mean": round(sum(ratios) / len(ratios), 2)
                if ratios
                else None,
                "aggregation_wait_total_s": round(
                    sum(s["sum"] for s in agg_wait), 3
                ),
                "stage_breakdown_by_round": stage_breakdown,
                "prometheus_snapshot": prom_path,
                "chrome_trace": trace_path,
                "metric_families": sorted(snap.keys()),
            },
        }
        _phase(
            f"telemetry bench done: {len(spans)} spans, "
            f"{len(snap)} metric families, increment {best*1e6:.2f}us"
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out["error"] = f"{type(e).__name__}: {e}"
    _emit(out, backend="cpu")


def measure_reference_baseline(
    remaining: float = float("inf"), ladder=None
) -> dict:
    """Measure the actual reference federation via the attempt ladder: run
    THIS file with --baseline-ref in a CPU-pinned subprocess (the reference
    import must never touch the TPU backend) and parse its single JSON
    line. Returns the largest completing configuration. Each rung's
    subprocess timeout is capped by the caller's ``remaining`` soft budget
    (minus a reserve for the fallback path), so the whole bench cannot
    overshoot its budget chasing a slow rung.

    ``ladder`` overrides BASELINE_LADDER — the degraded CPU-fallback path
    passes a same-node-count ladder so the ratio stays apples-to-apples
    (the reference's per-round cost grows with node count, so dividing an
    8-node measurement by a 20-node baseline would overstate the speedup).
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    last_err = "ladder empty"
    deadline = time.monotonic() + remaining
    for nodes, rounds, budget in (ladder if ladder is not None else BASELINE_LADDER):
        budget = min(budget, deadline - time.monotonic() - 60.0)  # 60s reserve
        if budget < 90.0:
            last_err = "soft budget exhausted before this rung"
            break
        _phase(f"reference baseline attempt: {nodes} nodes x {rounds} round(s), cap {budget:.0f}s")
        try:
            return _json_subprocess(
                ["--baseline-ref", str(nodes), str(rounds)], budget, env
            )
        except Exception as e:  # noqa: BLE001 — try the next rung
            last_err = str(e)  # includes the subprocess stderr tail
            _phase(f"reference baseline at {nodes} nodes failed: {last_err}")
    raise RuntimeError(f"reference baseline failed at every ladder rung: {last_err}")


def run_reference_baseline(n: int, rounds: int) -> None:
    """Subprocess body: measure the actual reference federation on CPU."""
    out: dict = {}
    try:
        sys.path.insert(0, "/root/reference")
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import numpy as np

        from p2pfl.utils.utils import set_test_settings, wait_convergence, wait_to_finish
        set_test_settings()  # the reference's own fast pacing (conservative for us)

        import datasets as hfds
        from p2pfl.communication.protocols.memory.memory_communication_protocol import (
            InMemoryCommunicationProtocol,
        )
        from p2pfl.learning.dataset.p2pfl_dataset import P2PFLDataset
        from p2pfl.learning.dataset.partition_strategies import RandomIIDPartitionStrategy
        from p2pfl.learning.frameworks.flax.flax_model import MLP as FlaxMLP
        from p2pfl.learning.frameworks.flax.flax_model import FlaxModel
        from p2pfl.node import Node
        rng = np.random.default_rng(42)
        templates = rng.uniform(size=(10, 28, 28)).astype(np.float32)
        # Generate held-out test samples BEYOND the training pool so the
        # reported baseline accuracy is test accuracy, not memorization.
        n_test = 256
        total = n * BASELINE_SAMPLES + n_test
        y = rng.integers(0, 10, size=total).astype(np.int32)
        x = np.clip(
            templates[y] + NOISE * rng.normal(size=(total, 28, 28)), 0, 1
        ).astype(np.float32)
        flip = rng.uniform(size=total) < LABEL_FLIP
        y[flip] = rng.integers(0, 10, size=int(flip.sum()))
        ds = hfds.Dataset.from_dict(
            {"image": list(x[:-n_test]), "label": y[:-n_test].tolist()}
        )
        ds_test = hfds.Dataset.from_dict(
            {"image": list(x[-n_test:]), "label": y[-n_test:].tolist()}
        )
        data = P2PFLDataset(hfds.DatasetDict({"train": ds, "test": ds_test}))
        parts = data.generate_partitions(n, RandomIIDPartitionStrategy)

        def make_model():
            m = FlaxMLP()
            params = m.init(jax.random.PRNGKey(0), np.zeros((1, 28, 28)))["params"]
            return FlaxModel(m, params)

        t_setup = time.monotonic()
        nodes = []
        for i in range(n):
            node = Node(
                make_model(), parts[i], address=f"refnode-{i}",
                protocol=InMemoryCommunicationProtocol,
            )
            node.start()
            nodes.append(node)
        for i in range(1, n):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, n - 1, only_direct=False, wait=120)
        setup_s = time.monotonic() - t_setup

        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=rounds, epochs=EPOCHS)
        wait_to_finish(nodes, timeout=3600)  # parent enforces the real budget
        dt = time.monotonic() - t0

        # Final test accuracy: evaluate node 0's final model on the FULL
        # held-out split ourselves — the reference partitions the test split
        # across nodes, so its per-node logged "accuracy" is a high-variance
        # few-sample number (max over nodes trivially hits 1.0).
        final_acc = None
        try:
            fm = nodes[0].learner.get_model()  # FlaxModel
            # The reference MLP is written for single samples (batch size 1,
            # flax_model.py:171-195) — vmap it over the held-out split.
            logits = jax.vmap(
                lambda xi: fm.model.apply({"params": fm.model_params}, xi)
            )(jnp.asarray(x[-n_test:]))
            # The reference MLP flattens each sample to one row -> logits
            # arrive [n, 1, 10]; collapse before comparing.
            pred = np.argmax(np.asarray(logits), axis=-1).reshape(-1)
            final_acc = float(np.mean(pred == y[-n_test:]))
        except Exception:
            traceback.print_exc(file=sys.stderr)
        for node in nodes:
            node.stop()
        out = {
            "baseline": "reference-p2pfl-flax-inmemory",
            "nodes": n,
            "rounds": rounds,
            "sec_per_round": dt / rounds,
            "setup_s": setup_s,
            "final_test_acc": final_acc,
            # The reference's FlaxLearner.fit never writes the trained
            # TrainState params back into the model it returns
            # (flax_learner.py:106-137: self.state is trained, but
            # flax_model.model_params stays at init), so its federation
            # gossips/aggregates INITIAL weights and the aggregated model's
            # held-out accuracy stays ~random. Timing is unaffected (all
            # the local compute still runs); accuracy parity should be read
            # as "ours ~0.9 ceiling vs the reference's broken flax path".
            "note": "reference flax bug: trained params never sync into the gossiped model",
        }
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        out = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out), flush=True)
    os._exit(0)  # lingering reference threads must not block exit


def bench_torch_cpu_fallback() -> dict:
    """Fallback baseline if the reference run fails: one federated round of
    committee compute, eager PyTorch CPU (conservative: no gossip/protocol
    overhead counted)."""
    import numpy as np
    import torch
    from torch import nn

    torch.manual_seed(0)
    rng = np.random.default_rng(0)
    x = torch.from_numpy(rng.normal(size=(SAMPLES_PER_NODE, 784)).astype(np.float32))
    y = torch.from_numpy(rng.integers(0, 10, size=SAMPLES_PER_NODE).astype(np.int64))

    def one_node_epoch() -> None:
        model = nn.Sequential(
            nn.Flatten(), nn.Linear(784, 256), nn.ReLU(), nn.Linear(256, 128),
            nn.ReLU(), nn.Linear(128, 10),
        )
        opt = torch.optim.Adam(model.parameters(), lr=1e-3)
        loss_fn = nn.CrossEntropyLoss()
        for _ in range(EPOCHS):
            for i in range(0, SAMPLES_PER_NODE, BATCH):
                opt.zero_grad()
                loss = loss_fn(model(x[i : i + BATCH]), y[i : i + BATCH])
                loss.backward()
                opt.step()

    one_node_epoch()  # warmup
    t0 = time.monotonic()
    for _ in range(COMMITTEE):
        one_node_epoch()
    return {
        "baseline": "torch-cpu-committee-loop (fallback)",
        "sec_per_round": time.monotonic() - t0,
        "final_test_acc": None,
    }


#: Auxiliary on-chip captures appended to a successful TPU-path run when
#: budget remains: the VERDICT r4 evidence items (CIFAR robust trio,
#: attention microbench, transformer-LM MFU) that rounds 3-5 could not
#: land because the tunnel was down whenever a builder session looked.
#: Each runs as a hard-capped subprocess; failures/skips are recorded,
#: never fatal to the main metric line.
AUX_CAPTURES = [
    ("cifar_resnet_trio", "--cifar", 1500.0),
    ("attention_microbench", "--attn", 1500.0),
    ("lm_mfu", "--lm-mfu", 900.0),
]


def _run_aux_captures(
    t_start: float, soft_budget: float, env: dict, specs=None, into: dict = None
) -> dict:
    """Run the aux capture queue with whatever budget remains (90s margin
    per leg); returns {name: result | {"error"/"skipped": ...}}. Results
    are written into ``into`` AS EACH LEG COMPLETES — the caller attaches
    that dict to the output line first, so a SIGTERM mid-queue still
    prints every leg already measured (the invariant run_cifar_bench
    states for its own legs)."""
    aux: dict = {} if into is None else into
    for name, flag, cap in (specs if specs is not None else AUX_CAPTURES):
        remaining = soft_budget - (time.monotonic() - t_start)
        cap = min(cap, remaining - 90.0)
        if cap < 240.0:
            aux[name] = {"skipped": "soft budget exhausted"}
            continue
        _phase(f"aux capture {name} (cap {cap:.0f}s)")
        try:
            aux[name] = _json_subprocess([flag], cap, env)
            _phase(f"aux capture {name} done")
        except Exception as e:  # noqa: BLE001 — aux must never kill the metric
            traceback.print_exc(file=sys.stderr)
            # Keep the TAIL: _json_subprocess appends the child's stderr
            # tail there, which is the diagnosable part.
            aux[name] = {"error": f"{type(e).__name__}: {str(e)[-800:]}"}
    return aux


def _assemble(out: dict, tpu: dict, base: dict, kind: str, mfu: dict) -> None:
    """Fill the output line from a measurement + baseline pair. ONE
    assembler for the TPU and degraded paths so their JSON shapes can
    never drift apart."""
    value = tpu["sec_per_round"]
    out["value"] = round(value, 6)
    out["vs_baseline"] = round(base["sec_per_round"] / value, 3)
    out["extra"] = {
        "rounds_per_sec": round(tpu["rounds_per_sec"], 3),
        "final_test_acc": round(tpu["final_test_acc"], 4),
        "label_flip": LABEL_FLIP,
        "rounds_per_call": tpu["rounds_per_call"],
        "rounds_per_call_sweep": tpu.get("rounds_per_call_sweep"),
        "est_dispatch_s_per_call": tpu.get("est_dispatch_s_per_call"),
        "baseline": base.get("baseline"),
        "baseline_sec_per_round": round(base["sec_per_round"], 4),
        # Baseline's own shape: makes a ladder fall-through (e.g. the
        # matched-count rung failing in degraded mode) visible in the
        # JSON rather than silently skewing vs_baseline.
        "baseline_nodes": base.get("nodes"),
        "baseline_rounds": base.get("rounds"),
        "baseline_final_test_acc": base.get("final_test_acc"),
        "baseline_note": base.get("note"),
        "device_kind": kind,
        "mfu_probe": mfu,
        "rounds": tpu.get("rounds", ROUNDS),
        "nodes": tpu.get("nodes", NUM_NODES),
        "committee": COMMITTEE,
    }


def _measure_degraded(out_template: dict, soft_budget: float = 3000.0) -> dict:
    """The honest tunnel-down answer: reduced-scale CPU-mesh measurement
    plus a matched-node-count reference baseline (apples-to-apples ratio),
    assembled into a fully-labeled degraded output line. Typically ~4 min;
    the orchestrator runs it BEFORE settling into the wait ladder so a
    numeric line is on hand the moment anything (deadline, SIGTERM) ends
    the wait. Caps scale with the soft budget so a slow fallback cannot
    starve the ladder of the patience the budget implies."""
    # Caps scale down with the budget but keep FLOORS that fit the measured
    # costs (~25 s CPU fallback, ~190 s 8-node baseline): a tiny budget must
    # not push the baseline down to the torch loop, whose different shape
    # makes vs_baseline meaningless.
    tpu = measure_cpu_fallback(min(450.0, max(150.0, soft_budget * 0.15)))
    try:
        base = measure_reference_baseline(
            min(900.0, max(520.0, soft_budget * 0.3)),
            ladder=[
                # The 8-node rung measures ~260 s wall on this box; the
                # floor must cover it or tiny budgets fall through to the
                # torch loop (observed: vs_baseline 0.13 nonsense).
                (tpu["nodes"], 1, min(700.0, max(420.0, soft_budget * 0.25))),
                (4, 1, 240.0),
            ],
        )
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        _phase(f"degraded baseline failed ({e}); falling back to torch loop")
        base = bench_torch_cpu_fallback()
    d = json.loads(json.dumps(out_template))
    _assemble(
        d, tpu, base, "cpu (TPU unavailable)",
        {"skipped": "TPU unavailable (reduced-scale CPU fallback)"},
    )
    # Relabel the metric and flag degradation at TOP level: a consumer
    # parsing only {metric, value, vs_baseline} must never mistake the
    # reduced-scale CPU number for the 100-node result.
    d["metric"] = f"sec_per_round_{tpu['nodes']}node_mnist_fedavg_cpu_fallback"
    d["degraded"] = True
    # WHY this run degraded rides the meta block (probe timeout vs absent
    # platform vs pre-pinned env): BENCH_r03–r05 degraded silently and the
    # trajectory doc had to reverse-engineer the cause from timestamps.
    d["meta"] = _bench_meta(
        seed=None, backend="cpu", fallback_reason=_fallback_reason() or "unknown"
    )
    d["extra"]["scale_note"] = (
        f"TPU tunnel down: measured at {tpu['nodes']} nodes x "
        f"{tpu['rounds']} rounds on the 8-device virtual CPU mesh "
        f"(metric shape is {NUM_NODES} nodes x {ROUNDS} rounds)"
    )
    return d


def main() -> None:
    out = {
        "metric": "sec_per_round_100node_mnist_fedavg",
        "value": None,
        "unit": "s/round",
        "vs_baseline": None,
        "extra": {},
        "meta": _bench_meta(),
    }
    best: dict = {}  # best-available complete line (the degraded fallback)

    def _bail(signum, _frame):
        # An impatient driver sends TERM: a degraded-but-numeric line (if
        # the fallback finished measuring) still beats an empty capture.
        # Kill in-flight measurement children first — an orphaned
        # --baseline-ref subprocess would keep saturating the single core
        # and skew whatever the driver runs next.
        for child in list(_live_children):
            try:
                child.kill()
            except Exception:  # noqa: BLE001
                pass
        line = best or {
            **out,
            "degraded": True,
            "error": f"terminated by signal {signum} while waiting for TPU",
        }
        print(json.dumps(line), flush=True)
        os._exit(1 if "error" in line else 0)

    signal.signal(signal.SIGTERM, _bail)
    signal.signal(signal.SIGINT, _bail)

    t_start = time.monotonic()
    try:
        try:
            soft_budget = float(os.environ.get("P2PFL_TPU_BENCH_BUDGET", "3000"))
        except ValueError:
            soft_budget = 3000.0
        # Reserve: TPU-metric subprocess (~300-500s: 3 sweep compiles + MFU)
        # + 20-node reference baseline (~350s) + margin.
        reserve = min(900.0, soft_budget * 0.5)

        # First probe gets one retry: a single timed-out probe must not be
        # what sends a whole bench run down the degraded path (BENCH_r03–r05).
        kind = _subprocess_tpu_probe(retries=1)
        if kind is None:
            _phase(
                "tunnel down at first probe: pre-computing the degraded "
                "fallback, then holding the wait ladder until the reserve"
            )
            try:
                best = _measure_degraded(out, soft_budget)
                _phase(f"degraded fallback ready: {best['metric']} = {best['value']}")
            except Exception as e:  # noqa: BLE001 — waiting is still worthwhile
                traceback.print_exc(file=sys.stderr)
                _phase(f"degraded fallback failed ({e}); wait ladder anyway")
            kind = wait_for_tpu(deadline=t_start + soft_budget - reserve)
        if kind is None:
            if best:
                print(json.dumps(best), flush=True)
                os._exit(0)
            raise RuntimeError(
                "TPU unavailable for the whole wait budget and the degraded "
                "fallback also failed"
            )

        # --- tunnel is up: full measurement, subprocess-contained ---------
        # Self-propagate the settled verdict: every arm subprocess below
        # inherits it through the knob and skips its own probe ladder (one
        # probe, all arms). setdefault — an operator assertion wins.
        os.environ.setdefault("P2PFL_TPU_BENCH_ASSUME_BACKEND", "tpu")
        remaining = soft_budget - (time.monotonic() - t_start)
        metric_cap = max(420.0, remaining - 420.0)  # keep ~7 min for baseline
        _phase(f"TPU up ({kind}): metric subprocess (cap {metric_cap:.0f}s)")
        # Sanitize like the probe does: a leftover JAX_PLATFORMS=cpu (e.g.
        # from a documented CPU smoke run) must not make the metric child
        # measure the host CPU after the probe found a real chip.
        tpu_env = dict(os.environ)
        tpu_env.pop("JAX_PLATFORMS", None)
        tm = _json_subprocess(
            ["--tpu-metric", str(metric_cap * 0.9)], metric_cap, tpu_env
        )
        _phase("measuring reference baseline (subprocess, CPU)")
        try:
            remaining = soft_budget - (time.monotonic() - t_start)
            if remaining < 240.0:
                _phase("soft budget tight: using torch-loop fallback baseline")
                base = bench_torch_cpu_fallback()
            else:
                base = measure_reference_baseline(remaining)
        except Exception as e:  # noqa: BLE001
            traceback.print_exc(file=sys.stderr)
            _phase(f"reference baseline failed ({e}); falling back to torch loop")
            base = bench_torch_cpu_fallback()
        _phase("baseline done")
        _assemble(out, tm["tpu"], base, tm["kind"], tm["mfu"])
        # From here the REAL metric line exists: a SIGTERM during the aux
        # captures below must print it, not the degraded fallback — and
        # the aux dict is attached BEFORE the legs run so completed legs
        # survive a mid-queue TERM.
        best = out
        aux: dict = {}
        out["extra"]["aux_captures"] = aux
        _run_aux_captures(t_start, soft_budget, tpu_env, into=aux)
    except Exception as e:  # noqa: BLE001
        traceback.print_exc(file=sys.stderr)
        if not best:
            # Degraded-beats-empty applies on EVERY path: when the first
            # probe succeeded and the tunnel flapped mid-measurement, the
            # fallback was never pre-computed — measure it now (late but
            # numeric beats punctual but empty).
            try:
                _phase(f"TPU path failed ({e}); measuring degraded fallback now")
                best = _measure_degraded(out, soft_budget)
            except Exception:  # noqa: BLE001
                traceback.print_exc(file=sys.stderr)
        if best:
            # The TPU path died after recovery (e.g. the tunnel flapped
            # mid-measurement): the degraded line is still a real answer.
            best["extra"]["tpu_attempt_error"] = f"{type(e).__name__}: {e}"
            print(json.dumps(best), flush=True)
            os._exit(0)
        out["error"] = f"{type(e).__name__}: {e}"
    print(json.dumps(out), flush=True)
    # _exit (not sys.exit): a wedged backend thread must not turn success
    # into a hang; nonzero when the run failed so CI gates see it.
    os._exit(1 if "error" in out else 0)


if __name__ == "__main__":
    if "--baseline-ref" in sys.argv:
        i = sys.argv.index("--baseline-ref")
        run_reference_baseline(int(sys.argv[i + 1]), int(sys.argv[i + 2]))
    elif "--cpu-fallback" in sys.argv:
        run_cpu_fallback()
    elif "--multihost-worker" in sys.argv:
        i = sys.argv.index("--multihost-worker")
        run_multihost_worker(int(sys.argv[i + 1]), int(sys.argv[i + 2]))
    elif "--multihost" in sys.argv:
        run_multihost()
    elif "--tpu-metric" in sys.argv:
        i = sys.argv.index("--tpu-metric")
        run_tpu_metric(float(sys.argv[i + 1]) if len(sys.argv) > i + 1 else 900.0)
    elif "--scale-500" in sys.argv:
        run_scale_500()
    elif "--cifar" in sys.argv:
        run_cifar_bench()
    elif "--wire" in sys.argv:
        run_wire_bench()
    elif "--privacy" in sys.argv:
        run_privacy_bench()
    elif "--telemetry" in sys.argv:
        run_telemetry_bench()
    elif "--observatory" in sys.argv:
        run_observatory_bench()
    elif "--fleetobs" in sys.argv:
        run_fleetobs_bench()
    elif "--asyncpop" in sys.argv:
        run_asyncpop_bench()
    elif "--devobs" in sys.argv:
        run_devobs_bench()
    elif "--population" in sys.argv:
        run_population_bench()
    elif "--campaign" in sys.argv:
        run_campaign_bench()
    elif "--soak" in sys.argv:
        run_soak_bench()
    elif "--critical-path" in sys.argv:
        run_critical_path_bench()
    elif "--parity" in sys.argv:
        run_parity_bench()
    elif "--chaos" in sys.argv:
        run_chaos_bench()
    elif "--recovery" in sys.argv:
        run_recovery_bench()
    elif "--byzantine" in sys.argv:
        run_byzantine_bench()
    elif "--async" in sys.argv:
        run_async_bench()
    elif "--attn" in sys.argv:
        run_attn_bench()
    elif "--lm-mfu" in sys.argv:
        run_lm_mfu()
    else:
        main()
