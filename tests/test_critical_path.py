"""Performance attribution plane: critical-path analyzer (synthetic DAGs,
two-process merge with wall-clock anchors + skew correction), continuous
profiling (perf section, recompile counter, device-trace windows), the
flight-recorder clock fix, and perf_diff's exit-code semantics."""

import importlib.util
import json
import os
import time

import pytest

from p2pfl_tpu.telemetry import REGISTRY, TRACER
from p2pfl_tpu.telemetry import tracing
from p2pfl_tpu.telemetry.critical_path import (
    CriticalPathAnalyzer,
    Seg,
    skew_from_registry,
)
from p2pfl_tpu.telemetry.tracing import Tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _seg(name, node, start, end, span_id="", parent_id="", rnd=0):
    return Seg(
        name=name, node=node, start_s=start, end_s=end,
        span_id=span_id or f"{node}-{name}-{start}", parent_id=parent_id,
        trace_id="t", round=rnd,
    )


# --- synthetic-DAG critical path --------------------------------------------


def _straggler_round():
    """Two trainers; B's fit is 5x A's, so A waits on B's partial. The
    cross-node edge: A's recv is parented onto B's diffuse span."""
    return [
        _seg("fit", "A", 0.0, 1.0, span_id="a-fit"),
        _seg("diffuse:partial_model", "A", 1.0, 1.3, span_id="a-diff"),
        _seg("aggregation_wait", "A", 1.3, 5.5, span_id="a-wait"),
        _seg("fit", "B", 0.0, 5.0, span_id="b-fit"),
        _seg("diffuse:partial_model", "B", 5.0, 5.45, span_id="b-diff"),
        _seg("recv:partial_model", "A", 5.4, 5.41, span_id="a-recv",
             parent_id="b-diff"),
    ]


def test_straggler_gates_the_round():
    a = CriticalPathAnalyzer(_straggler_round(), slack_s=0.5)
    path = a.round_path(0)
    assert path.gating_node == "B"
    # B's slow fit dominates the attribution; A's post-arrival tail is tiny.
    assert path.attributed_by_node["B"] == pytest.approx(5.4, abs=0.5)
    names = [h.name for h in path.hops]
    assert "fit" in names and "aggregation_wait" in names
    # Path is ordered earliest-first and attribution is within the round.
    assert path.hops[0].start_s <= path.hops[-1].start_s
    assert 0.5 < path.coverage <= 1.01


def test_wait_without_arrival_falls_back_to_predecessor():
    segs = [
        _seg("fit", "A", 0.0, 1.0, span_id="a-fit"),
        _seg("aggregation_wait", "A", 1.0, 4.0, span_id="a-wait"),
    ]
    path = CriticalPathAnalyzer(segs, slack_s=0.5).round_path(0)
    assert path.gating_node == "A"
    assert [h.name for h in path.hops] == ["fit", "aggregation_wait"]
    assert sum(h.attributed_s for h in path.hops) == pytest.approx(4.0, abs=0.01)


def test_ack_cycle_does_not_truncate_the_walk():
    """A diffuse wait resolved by an ack whose parent chain loops back onto
    the diffuse span itself must fall through, not end the walk."""
    segs = [
        _seg("fit", "A", 0.0, 3.0, span_id="a-fit"),
        _seg("diffuse:full_model", "A", 3.0, 4.0, span_id="a-diff"),
        # Ack arrives on A, parented (via B's recv) onto A's own diffuse.
        _seg("recv:full_model", "B", 3.2, 3.21, span_id="b-recv",
             parent_id="a-diff"),
        _seg("recv:models_ready", "A", 3.9, 3.91, span_id="a-ack",
             parent_id="b-recv"),
    ]
    path = CriticalPathAnalyzer(segs, slack_s=0.5).round_path(0)
    assert path.gating_node == "A"
    # The walk reached the fit despite the cycle.
    assert any(h.name == "fit" for h in path.hops)
    assert path.attributed_by_node["A"] == pytest.approx(4.0, abs=0.2)


def test_stage_shares_and_rounds():
    a = CriticalPathAnalyzer(_straggler_round(), slack_s=0.5)
    assert a.rounds() == [0]
    shares = a.stage_shares(0)
    assert shares["by_stage_s"]["fit"] == pytest.approx(6.0)
    assert sum(shares["shares"].values()) == pytest.approx(1.0, abs=0.01)


def test_overlap_report_serialized_vs_overlapped():
    serialized = CriticalPathAnalyzer(
        [
            _seg("fit", "A", 0.0, 2.0),
            _seg("diffuse:partial_model", "A", 2.0, 3.0),
        ]
    ).overlap_report()
    assert serialized["train_diffuse_overlap_fraction"] == 0.0
    assert serialized["serialized_diffuse_s"] == pytest.approx(1.0)

    overlapped = CriticalPathAnalyzer(
        [
            _seg("fit", "A", 0.0, 2.0),
            _seg("diffuse:partial_model", "A", 1.0, 2.0),  # fully under fit
            _seg("fit", "B", 0.0, 1.0),
            _seg("diffuse:partial_model", "B", 1.5, 2.5),  # under A's fit only
        ]
    ).overlap_report()
    assert overlapped["train_diffuse_overlap_fraction"] == pytest.approx(0.5)
    assert overlapped["diffuse_under_any_fit_fraction"] == pytest.approx(0.75)


def test_report_counts_gating_nodes():
    segs = _straggler_round() + [
        _seg("fit", "A", 10.0, 11.0, span_id="a-fit-1", rnd=1),
        _seg("fit", "B", 10.0, 15.0, span_id="b-fit-1", rnd=1),
    ]
    rep = CriticalPathAnalyzer(segs, slack_s=0.5).report()
    assert rep["top_gating_node"] == "B"
    assert rep["gating_node_counts"]["B"] == 2
    assert rep["top_gating_fraction"] == 1.0
    assert "overlap" in rep and "stage_shares" in rep


# --- chrome-trace export: Perfetto contract + wall anchor ---------------------


def test_chrome_trace_perfetto_fields_and_stable_ordering():
    t = Tracer(max_spans=64)
    before_wall = time.time()
    with t.span("fit", node="mem://n0", round=2):
        time.sleep(0.01)
    with t.span("diffuse:partial_model", node="mem://n1", round=2):
        pass
    doc = t.export_chrome_trace()
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    metas = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
    assert len(spans) == 2 and len(metas) == 2
    for ev in spans:
        assert ev["ph"] == "X"
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        for key in ("trace_id", "span_id", "parent_id", "round"):
            assert key in ev["args"]
    fit = next(e for e in spans if e["name"] == "fit")
    assert fit["dur"] >= 10_000  # ts/dur are MICROseconds
    # Wall anchor: ts + wall_epoch_s lands at the real recording time.
    meta = doc["metadata"]
    wall_start = fit["ts"] / 1e6 + meta["wall_epoch_s"]
    assert abs(wall_start - before_wall) < 5.0
    assert meta["exported_at_s"] >= meta["wall_epoch_at_init_s"] - 1.0
    # Deterministic ordering: same spans export byte-identically, sorted.
    doc2 = t.export_chrome_trace()
    doc["metadata"].pop("wall_epoch_s"), doc["metadata"].pop("exported_at_s")
    doc2["metadata"].pop("wall_epoch_s"), doc2["metadata"].pop("exported_at_s")
    assert json.dumps(doc) == json.dumps(doc2)
    ts_list = [e["ts"] for e in spans]
    assert ts_list == sorted(ts_list)


def _two_process_docs(offset_s: float):
    """Fixture: a sender tracer ("process" A) and a receiver tracer (B)
    linked through the wire context, exported separately; B's wall anchor
    is then shifted by ``offset_s`` to simulate NTP skew."""
    t_a, t_b = Tracer(max_spans=64), Tracer(max_spans=64)
    with t_a.span("fit", node="procA", round=0):
        time.sleep(0.05)
    with t_b.span("aggregation_wait", node="procB", round=0):
        with t_a.span("diffuse:partial_model", node="procA", round=0) as ctx:
            wire = ctx.wire()
            time.sleep(0.01)
        with tracing.attach_wire(wire):
            with t_b.span("recv:partial_model", node="procB", round=0):
                time.sleep(0.005)
        time.sleep(0.005)
    doc_a, doc_b = t_a.export_chrome_trace(), t_b.export_chrome_trace()
    doc_a["metadata"]["node"] = "procA"
    doc_b["metadata"]["node"] = "procB"
    doc_b["metadata"]["wall_epoch_s"] += offset_s
    return doc_a, doc_b


def test_two_process_merge_aligns_without_skew():
    doc_a, doc_b = _two_process_docs(offset_s=0.0)
    a = CriticalPathAnalyzer.from_chrome_traces([doc_a, doc_b], slack_s=0.5)
    assert set(a.nodes()) == {"procA", "procB"}
    path = a.round_path(0)
    # B's wait resolves through the recv onto A's diffuse -> A's fit gates.
    assert path.gating_node == "procA"
    assert any(h.name == "fit" and h.node == "procA" for h in path.hops)


def test_two_process_merge_corrects_measured_skew():
    # B's clock is 5 s ahead; A measured that skew on B's heartbeats.
    doc_a, doc_b = _two_process_docs(offset_s=5.0)
    doc_a["metadata"]["peer_clock_skew_s"] = {"procB": -5.0}
    merged = CriticalPathAnalyzer.from_chrome_traces([doc_a, doc_b], slack_s=0.5)
    assert merged.round_path(0).gating_node == "procA"
    # Explicit skew_s wins the same way.
    doc_a["metadata"].pop("peer_clock_skew_s")
    explicit = CriticalPathAnalyzer.from_chrome_traces(
        [doc_a, doc_b], skew_s={"procB": -5.0}, slack_s=0.5
    )
    assert explicit.round_path(0).gating_node == "procA"
    # Uncorrected, B's spans land 5 s in the future and the merged round
    # timeline inflates by the skew — the correction is load-bearing.
    broken = CriticalPathAnalyzer.from_chrome_traces(
        [doc_a, doc_b], auto_skew=False, slack_s=0.5
    )
    assert broken.round_path(0).wall_s > 4.0
    assert merged.round_path(0).wall_s < 2.0


def test_skew_from_registry_reads_reference_rows():
    g = REGISTRY.gauge(
        "p2pfl_heartbeat_clock_skew_seconds",
        "Receiver wall-clock minus the sender-stamped beat timestamp",
        labels=("node", "peer"),
    )
    g.labels("mem://ref", "mem://peer1").set(0.25)
    g.labels("mem://ref", "mem://peer2").set(-1.5)
    g.labels("mem://other", "mem://peer1").set(99.0)
    skews = skew_from_registry("mem://ref")
    assert skews["mem://peer1"] == 0.25
    assert skews["mem://peer2"] == -1.5
    assert 99.0 not in skews.values()


# --- continuous profiling -----------------------------------------------------


def _tiny_learner(addr: str, batch_size: int = 16):
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp_model

    data = synthetic_mnist(n_train=64, n_test=16)
    part = data.generate_partitions(1, RandomIIDPartitionStrategy)[0]
    return JaxLearner(
        mlp_model(seed=0, hidden_sizes=(8,)), part,
        self_addr=addr, batch_size=batch_size, seed=0,
    )


def test_recompile_counter_counts_shape_driven_retraces():
    learner = _tiny_learner("mem://recompile-test")
    fam = REGISTRY.get("p2pfl_learner_recompiles_total")
    assert fam is not None

    def count():
        return sum(
            c.value
            for labels, c in fam.samples()
            if labels.get("node") == "mem://recompile-test"
        )

    learner.fit()  # first compile: gauged, NOT counted as a recompile
    learner.fit()  # cache hit: still no recompile
    base = count()
    assert base == 0
    learner.batch_size = 8  # shape change -> silent retrace, now visible
    learner.fit()
    assert count() >= base + 1
    comp = REGISTRY.get("p2pfl_learner_jit_compile_seconds")
    assert any(
        labels.get("node") == "mem://recompile-test" and c.value > 0
        for labels, c in comp.samples()
    )


def test_learner_cost_analysis_reports_flops():
    learner = _tiny_learner("mem://cost-test")
    cost = learner.cost_analysis()
    assert cost is not None
    assert cost["flops_per_epoch"] > 0
    assert cost["steps_per_epoch"] >= 1
    assert cost["flops_per_step"] == pytest.approx(
        cost["flops_per_epoch"] / cost["steps_per_epoch"]
    )


def test_perf_section_structure():
    from p2pfl_tpu.management.profiler import PERF_SCHEMA_VERSION, perf_section

    sec = perf_section(REGISTRY, cost={"flops_per_epoch": 1.0})
    assert sec["schema_version"] == PERF_SCHEMA_VERSION
    assert set(sec["compile"]) == {
        "first_compile_s", "recompiles_total", "last_recompile_s"
    }
    assert set(sec["steady_state"]) == {"step_s", "steps_per_s"}
    assert sec["xla_cost"] == {"flops_per_epoch": 1.0}
    assert isinstance(sec["device_traces"], list)
    json.dumps(sec)  # must be bench-JSON-embeddable


def test_device_trace_window_noop_and_capture_once(tmp_path):
    from p2pfl_tpu.management import profiler

    with profiler.device_trace_window(None) as captured:
        assert captured is None
    with profiler.device_trace_window("", label="x") as captured:
        assert captured is None
    label = f"once-{time.time_ns()}"  # process-global registry: unique label
    with profiler.device_trace_window(str(tmp_path), label=label) as captured:
        assert captured is not None
        import jax.numpy as jnp

        (jnp.ones((4,)) * 2).block_until_ready()
    assert os.path.isdir(captured)
    assert captured in profiler.captured_device_traces()
    with profiler.device_trace_window(str(tmp_path), label=label) as again:
        assert again is None  # capture-once per label per process


# --- flight recorder clocks ---------------------------------------------------


def test_flight_recorder_maps_mono_to_wall_at_read_time(tmp_path):
    from p2pfl_tpu.telemetry.flight_recorder import FlightRecorder

    rec = FlightRecorder("mem://clock-test", capacity=8)
    rec.record("tick", i=1)
    ev = rec.events()[0]
    assert abs(ev["t"] - time.time()) < 5.0  # wall, derived at read time
    assert abs(ev["t_mono"] - time.monotonic()) < 5.0
    path = rec.dump("test", directory=str(tmp_path))
    assert path is not None
    with open(path) as f:
        doc = json.load(f)
    # Both clocks + the mapping in the header; events carry both stamps.
    assert {"dumped_at", "dumped_at_mono", "mono_to_wall_epoch"} <= set(doc)
    assert doc["events"][0]["t"] == pytest.approx(
        doc["events"][0]["t_mono"] + doc["mono_to_wall_epoch"], abs=1.0
    )


# --- protocol trace export ----------------------------------------------------


def test_protocol_export_trace_annotates_node_and_skews(tmp_path):
    from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol

    proto = InMemoryCommunicationProtocol("mem://trace-export-test")
    try:
        proto.heartbeater.beat("mem://peer", time.time() - 2.0)
        path = proto.export_trace(str(tmp_path / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["metadata"]["node"] == "mem://trace-export-test"
        skews = doc["metadata"]["peer_clock_skew_s"]
        assert skews["mem://peer"] == pytest.approx(2.0, abs=1.0)
        assert "wall_epoch_s" in doc["metadata"]
    finally:
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry

        try:
            proto.stop()
        except Exception:
            pass
        InMemoryRegistry.reset()


# --- perf_diff exit-code semantics --------------------------------------------


def _perf_diff():
    spec = importlib.util.spec_from_file_location(
        "perf_diff", os.path.join(REPO, "scripts", "perf_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_doc(step=0.01, wall=2.0):
    return {
        "metric": "unit_test_arm",
        "value": wall,
        "unit": "s/round",
        "meta": {"schema_version": 1, "git_sha": "x", "backend": "cpu", "seed": 0},
        "perf": {
            "schema_version": 1,
            "compile": {"recompiles_total": {"n0": 0}},
            "steady_state": {"step_s": {"n0": step}},
        },
        "extra": {"mean_round_wall_s": wall},
    }


def test_perf_diff_exit_codes(tmp_path):
    pd = _perf_diff()
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_bench_doc()))

    same = tmp_path / "same.json"
    same.write_text(json.dumps(_bench_doc(step=0.0105, wall=2.1)))  # in noise
    assert pd.main([str(base), str(same)]) == 0

    reg = tmp_path / "reg.json"
    reg.write_text(json.dumps(_bench_doc(step=0.02, wall=4.0)))  # 2x
    assert pd.main([str(base), str(reg)]) == 1

    improved = tmp_path / "improved.json"
    improved.write_text(json.dumps(_bench_doc(step=0.005, wall=1.0)))
    assert pd.main([str(base), str(improved)]) == 0

    alien_doc = _bench_doc()
    alien_doc["meta"]["schema_version"] = 2
    alien = tmp_path / "alien.json"
    alien.write_text(json.dumps(alien_doc))
    assert pd.main([str(base), str(alien)]) == 3

    other_metric = _bench_doc()
    other_metric["metric"] = "different_arm"
    om = tmp_path / "om.json"
    om.write_text(json.dumps(other_metric))
    assert pd.main([str(base), str(om)]) == 3
    assert pd.main([str(base), str(om), "--allow-metric-mismatch"]) == 0

    assert pd.main([str(base), str(tmp_path / "missing.json")]) == 2


def test_perf_diff_noise_aware_list_baselines(tmp_path):
    pd = _perf_diff()
    base_doc = _bench_doc()
    # Noisy baseline samples: cv ~0.3 widens the band beyond the default.
    base_doc["extra"]["mean_round_wall_s"] = [2.0, 1.4, 2.6]
    base = tmp_path / "base.json"
    base.write_text(json.dumps(base_doc))
    cand = _bench_doc(wall=3.0)  # +50%: outside 0.25 but inside 2*cv (~0.49)...
    cand["extra"]["mean_round_wall_s"] = 2.9
    cp = tmp_path / "cand.json"
    cp.write_text(json.dumps(cand))
    summary = pd.compare(base_doc, cand)
    row = next(
        r for r in summary["rows"] if r["key"] == "extra.mean_round_wall_s"
    )
    assert row["allowed_rel"] > 0.25  # band widened by measured noise
    assert not row["regressed"]


def test_perf_diff_recompile_counts_regress(tmp_path):
    """Recompile counts gate on the FLEET SUM: a storm fails, but the same
    total landing on different nodes (scheduler luck run to run) does not."""
    pd = _perf_diff()
    base_doc = _bench_doc()
    cand_doc = _bench_doc()
    cand_doc["perf"]["compile"]["recompiles_total"]["n0"] = 3
    summary = pd.compare(base_doc, cand_doc)
    assert "perf.compile.recompiles_total.sum" in summary["regressions"]

    # Same fleet total redistributed across nodes: NOT a regression.
    base_doc["perf"]["compile"]["recompiles_total"] = {"n0": 3, "n1": 1}
    cand_doc["perf"]["compile"]["recompiles_total"] = {"n0": 1, "n1": 3}
    summary = pd.compare(base_doc, cand_doc)
    assert not [r for r in summary["regressions"] if "recompiles" in r]
