"""Management layer: async logger (reference decorators/async_logger.py:
29-70), file logging through the listener, metric routing, flush-on-exit."""

import logging
import threading

from p2pfl_tpu.experiment import Experiment
from p2pfl_tpu.management.logger import logger


class _ThreadRecordingHandler(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []
        self.threads = set()

    def emit(self, record):
        self.records.append(record.getMessage())
        self.threads.add(threading.current_thread().name)


def test_log_calls_are_async():
    """Handlers run on the QueueListener thread, never the caller thread —
    the hot path (gossip/heartbeat) must not block on handler IO."""
    h = _ThreadRecordingHandler()
    orig = logger._listener.handlers
    logger._listener.handlers = orig + (h,)
    try:
        logger.info("async-test-node", "hello-async")
        logger.flush()
        assert any("hello-async" in r for r in h.records)
        assert threading.current_thread().name not in h.threads
    finally:
        logger._listener.handlers = orig


def test_file_logging_flush(tmp_path):
    path = logger.enable_file_logging(str(tmp_path))
    logger.info("file-test-node", "to-disk-and-flushed")
    logger.flush()
    with open(path) as f:
        content = f.read()
    assert "to-disk-and-flushed" in content
    # detach the file handler again so other tests don't write here
    logger._listener.handlers = tuple(
        h for h in logger._listener.handlers if h is not logger._file_handler
    )
    logger._file_handler = None


def test_metric_routing_step_vs_round():
    """Step-wise metrics land in local storage, round-wise in global
    (reference logger.py:266-305)."""
    node = "metrics-test-node"
    logger.register_node(node)
    try:
        logger.experiment_started(node, Experiment("routing-exp", 3))
        logger.log_metric(node, "train_loss", 0.5, step=2)
        logger.log_metric(node, "test_acc", 0.9)
        local = logger.get_local_logs()
        assert "routing-exp" in local
        assert local["routing-exp"][0][node]["train_loss"] == [(2, 0.5)]
        glob = logger.get_global_logs()
        assert glob["routing-exp"][node]["test_acc"] == [(0, 0.9)]
    finally:
        logger.unregister_node(node)


def test_profile_run_host_and_device_trace(tmp_path):
    """profile_run writes a host .pstat and an XLA device trace
    (TPU-first upgrade over the reference's yappi hook,
    examples/mnist.py:264-297)."""
    import jax.numpy as jnp

    from p2pfl_tpu.management.profiler import profile_run

    host_dir = tmp_path / "host"
    trace_dir = tmp_path / "trace"
    with profile_run(str(host_dir), str(trace_dir), label="t") as info:
        jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
    assert info["elapsed_s"] >= 0
    assert list(host_dir.glob("t-*.pstat"))
    # jax.profiler.trace writes plugins/profile/<ts>/*.xplane.pb
    assert list(trace_dir.rglob("*.xplane.pb"))


def test_profile_run_noop_paths():
    from p2pfl_tpu.management.profiler import profile_run

    with profile_run() as info:
        pass
    assert "host_profile" not in info and "device_trace" not in info
