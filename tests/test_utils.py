"""Utility helpers (parity with reference utils/utils.py:39-145): the e2e
assertions lean on these, so their FAILURE paths matter — a
check_equal_models that cannot fail would make every model-equality e2e
assertion vacuous."""

import types

import numpy as np
import pytest

from p2pfl_tpu.utils.utils import check_equal_models, wait_convergence


def _fake_node(params):
    """Duck-typed node.learner.get_model().get_parameters() chain."""
    model = types.SimpleNamespace(get_parameters=lambda: params)
    learner = types.SimpleNamespace(get_model=lambda: model)
    return types.SimpleNamespace(learner=learner)


def test_check_equal_models_accepts_close_models():
    a = [np.ones((3, 3), np.float32), np.zeros((2,), np.float32)]
    b = [p + 0.05 for p in a]  # inside the reference's atol=1e-1
    check_equal_models([_fake_node(a), _fake_node(b)])


def test_check_equal_models_detects_divergence():
    a = [np.ones((3, 3), np.float32)]
    b = [np.ones((3, 3), np.float32) + 1.0]  # far outside atol
    with pytest.raises(AssertionError):
        check_equal_models([_fake_node(a), _fake_node(b)])


def test_check_equal_models_detects_shape_mismatch():
    a = [np.ones((3, 3), np.float32)]
    b = [np.ones((3, 2), np.float32)]
    with pytest.raises(AssertionError, match="shape mismatch"):
        check_equal_models([_fake_node(a), _fake_node(b)])


def test_check_equal_models_detects_layer_count_mismatch():
    a = [np.ones((3,), np.float32)]
    b = [np.ones((3,), np.float32), np.ones((2,), np.float32)]
    with pytest.raises(AssertionError, match="layer count"):
        check_equal_models([_fake_node(a), _fake_node(b)])


def test_wait_convergence_times_out():
    node = types.SimpleNamespace(
        addr="fake-0", get_neighbors=lambda only_direct=False: []
    )
    with pytest.raises(TimeoutError):
        wait_convergence([node], 1, wait=0.2)
