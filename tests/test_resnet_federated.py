"""Federated ResNet-18 training (BASELINE.json configs #3/#4 at test scale).

The reference never composes its CIFAR configs: robust aggregators and
ResNet exist but no test trains them together. Here full-depth ResNet-18
(reduced input resolution for the 1-core CPU mesh) actually TRAINS
federated under Multi-Krum with label-flipping Byzantine nodes (config
#4). The bar is honest at this scale — 24 total member-steps — so the
assertion is a decreasing test loss plus above-chance accuracy, not
convergence. The converged full-resolution runs (SCAFFOLD config #3 and
the 56-node robust trio) are the TPU bench points (`bench.py --cifar`).

Cost note: ~18 s per ResNet member-step on this 1-core box — the test
runs ~10 min even with the persistent compile cache warm; it is the
heaviest single test in the suite and exists because the round-3 verdict
required ResNet-18 to be *trained* federated, not just shape-checked.
"""

import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import (
    RandomIIDPartitionStrategy,
    poison_partitions,
    synthetic_cifar10,
)
from p2pfl_tpu.models.resnet import resnet18_model
from p2pfl_tpu.ops import aggregation as agg_ops
from p2pfl_tpu.parallel.simulation import MeshSimulation

IMG = 12  # full ResNet-18 depth/width; reduced resolution for CPU compile


@pytest.mark.slow
def test_resnet18_federated_krum_under_poisoning():
    """2/8 nodes label-flipped; Multi-Krum-aggregated federation still
    learns (test split is clean, so the metrics measure true performance)."""
    data = synthetic_cifar10(n_train=8 * 24, n_test=96, image_size=IMG, seed=42)
    parts = data.generate_partitions(8, RandomIIDPartitionStrategy)
    parts, poisoned = poison_partitions(parts, 0.25, num_classes=10, seed=7)
    assert len(poisoned) == 2
    sim = MeshSimulation(
        resnet18_model(seed=0, input_shape=(IMG, IMG, 3)),
        parts,
        train_set_size=3,
        batch_size=12,
        seed=1,
        lr=1e-3,
        aggregate_fn=lambda stacked, w: agg_ops.krum(
            stacked, w, num_byzantine=1, num_selected=2
        )[0],
    )
    res = sim.run(rounds=4, epochs=1, warmup=False)
    assert np.isfinite(res.test_loss[-1])
    # Trains: the aggregated model's held-out loss drops substantially
    # (observed 6.55 -> 3.52 deterministic under the pinned seed). Accuracy
    # at 24 member-steps on 96 test samples is pure noise — the converged
    # accuracy demonstration is the TPU bench point (bench.py --cifar).
    assert res.test_loss[-1] < 0.75 * res.test_loss[0], (res.test_loss, res.test_acc)
