"""Federated ResNet-18 training (BASELINE.json configs #3/#4 at test scale).

The reference never composes its CIFAR configs: robust aggregators and
ResNet exist but no test trains them together. Here full-depth ResNet-18
(reduced input resolution for the 1-core CPU mesh) actually TRAINS
federated under Multi-Krum with label-flipping Byzantine nodes (config
#4). The task is narrowed to a 4-class subset and the resolution lowered
to 8x8 (conv cost ~ H*W, so the saved per-step time buys 144 member-steps
where 12x12 afforded 24) — enough training that the assertion can be a
decreasing test loss AND clearly-above-chance accuracy, not convergence.
The converged full-resolution 10-class runs (SCAFFOLD config #3 and the
56-node robust trio) are the TPU bench points (`bench.py --cifar`).

Cost note: still the heaviest single test in the suite (~10-15 min on
this 1-core box even with the persistent compile cache warm); it exists
because the round-3 verdict required ResNet-18 to be *trained* federated,
not just shape-checked, and round 4's required it to clear chance.
"""

import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import (
    RandomIIDPartitionStrategy,
    poison_partitions,
    synthetic_cifar10,
)
from p2pfl_tpu.models.resnet import resnet18_model
from p2pfl_tpu.ops import aggregation as agg_ops
from p2pfl_tpu.parallel.simulation import MeshSimulation

IMG = 8  # full ResNet-18 depth/width; reduced resolution for CPU step cost


@pytest.mark.slow
def test_resnet18_federated_krum_under_poisoning():
    """1/8 nodes label-flipped; Multi-Krum-aggregated federation still
    learns (test split is clean, so the metrics measure true performance).

    Config note (learned the expensive way): with 2/8 poisoned and a
    committee of 3, both attackers land in one committee ~11% of rounds
    and Krum's 2-closest rule then selects the COLLUDING PAIR — the
    honest-majority precondition (n - f - 2 >= f headroom within the
    committee) must hold for the defense story to be meaningful. One
    poisoned node keeps every committee honest-majority. Two local epochs
    matter too: 1-epoch member deltas are noise-dominated and Krum's
    distance geometry picks noise (probe: stuck at chance for 8 rounds).
    """
    data = synthetic_cifar10(
        n_train=8 * 48, n_test=96, num_classes=4, image_size=IMG, seed=42
    )
    parts = data.generate_partitions(8, RandomIIDPartitionStrategy)
    parts, poisoned = poison_partitions(parts, 0.125, num_classes=4, seed=7)
    assert len(poisoned) == 1
    sim = MeshSimulation(
        resnet18_model(seed=0, input_shape=(IMG, IMG, 3)),
        parts,
        train_set_size=3,
        batch_size=12,
        seed=1,
        lr=3e-3,
        aggregate_fn=lambda stacked, w: agg_ops.krum(
            stacked, w, num_byzantine=1, num_selected=2
        )[0],
    )
    res = sim.run(rounds=6, epochs=2, warmup=False)
    assert np.isfinite(res.test_loss[-1])
    # Trains: the aggregated model's held-out loss drops substantially.
    assert res.test_loss[-1] < 0.75 * res.test_loss[0], (res.test_loss, res.test_acc)
    # And learns above chance on the 4-class subset (chance = 0.25;
    # deterministic under the pinned seeds — see observed curve in the
    # assertion message if this ever trips).
    assert res.test_acc[-1] >= 0.40, (res.test_loss, res.test_acc)
