"""Torch interop: handle round-trips, learner training, federation with
torch nodes, and exact torch<->flax weight translation (reference framework
matrix tests: test/learning/frameworks_test.py:63-385)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from p2pfl_tpu.exceptions import ModelNotMatchingError
from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
from p2pfl_tpu.learning.interop import (
    TorchLearner,
    TorchModelHandle,
    jax_mlp_params_to_torch,
    torch_mlp_model,
    torch_state_dict_to_jax_mlp,
)
from p2pfl_tpu.learning.learner import JaxLearner, LearnerFactory
from p2pfl_tpu.models import mlp_model

# torch learners train real epochs -> excluded from the fast subset
pytestmark = pytest.mark.slow



def test_handle_roundtrip_and_shape_check():
    m = torch_mlp_model(seed=0)
    params = m.get_parameters()
    wire = m.encode_parameters()
    m2 = torch_mlp_model(seed=1)
    m2.set_parameters(bytes(wire))
    for a, b in zip(params, m2.get_parameters()):
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ModelNotMatchingError):
        m2.set_parameters([p[:1] for p in params])


def test_learner_factory_picks_torch():
    assert LearnerFactory.create_learner(torch_mlp_model()) is TorchLearner
    assert LearnerFactory.create_learner(mlp_model()) is JaxLearner


def test_torch_learner_trains():
    data = synthetic_mnist(n_train=512, n_test=128)
    learner = TorchLearner(torch_mlp_model(seed=0), data, "t0", batch_size=32)
    learner.set_epochs(2)
    learner.fit()
    metrics = learner.evaluate()
    assert metrics["test_acc"] > 0.5, metrics
    assert learner.get_model().get_contributors() == ["t0"]


def test_torch_nodes_federate():
    """Two torch-backed nodes converge over the in-memory transport — the
    reference's multi-framework federation (node_test.py:79-135) with the
    torch backend."""
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils.utils import check_equal_models, wait_convergence, wait_to_finish

    parts = synthetic_mnist(n_train=256, n_test=64).generate_partitions(
        2, RandomIIDPartitionStrategy
    )
    nodes = [
        Node(torch_mlp_model(seed=i), parts[i], learner=TorchLearner, batch_size=32)
        for i in range(2)
    ]
    try:
        for n in nodes:
            n.start()
        nodes[1].connect(nodes[0].addr)
        wait_convergence(nodes, 1, wait=5)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=120)
        check_equal_models(nodes)
    finally:
        for n in nodes:
            n.stop()


def test_torch_scaffold_emits_deltas():
    """SCAFFOLD contract on the torch path: delta_y_i / delta_c_i ride in
    additional_info (same payload JaxLearner.fit emits), with delta_y equal
    to the actual parameter movement."""
    data = synthetic_mnist(n_train=256, n_test=64)
    model = torch_mlp_model(seed=0)
    before = [a.copy() for a in model.get_parameters()]
    learner = TorchLearner(model, data, "t0", batch_size=32, callbacks=["scaffold"])
    learner.set_epochs(1)
    learner.fit()
    info = model.get_info("scaffold")
    assert info is not None
    n_leaves = len(model.get_parameters())
    assert len(info["delta_y_i"]) == n_leaves
    assert len(info["delta_c_i"]) == n_leaves
    after = model.get_parameters()
    # leaves are emitted in jax-tree (sorted-key) order, same as get_parameters
    for dy, a, b in zip(info["delta_y_i"], after, before):
        np.testing.assert_allclose(dy, a.astype(np.float32) - b.astype(np.float32), atol=1e-5)
    assert any(np.abs(dc).max() > 0 for dc in info["delta_c_i"])


def test_torch_nodes_scaffold_convergence():
    """Torch-node federation under the Scaffold aggregator (VERDICT round-2
    ask #4): converges and keeps the scaffold server round-trip alive."""
    from p2pfl_tpu.learning.aggregators import Scaffold
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils.utils import check_equal_models, wait_convergence, wait_to_finish

    parts = synthetic_mnist(n_train=512, n_test=128).generate_partitions(
        2, RandomIIDPartitionStrategy
    )
    nodes = [
        Node(
            torch_mlp_model(seed=i),
            parts[i],
            learner=TorchLearner,
            aggregator=Scaffold(),
            batch_size=32,
        )
        for i in range(2)
    ]
    try:
        for n in nodes:
            n.start()
        nodes[1].connect(nodes[0].addr)
        wait_convergence(nodes, 1, wait=5)
        nodes[0].set_start_learning(rounds=2, epochs=2)
        wait_to_finish(nodes, timeout=120)
        check_equal_models(nodes)
        # scaffold requires the callback to have been auto-wired by Node
        assert all(n.learner._scaffold for n in nodes)
        metrics = [n.learner.evaluate() for n in nodes]
        assert all(m["test_acc"] > 0.5 for m in metrics), metrics
    finally:
        for n in nodes:
            n.stop()


def test_torch_to_jax_weight_translation_exact():
    """Same weights -> same logits across frameworks (atol covers the
    f32 matmul-order difference only)."""
    tm = torch_mlp_model(seed=3)
    jm = mlp_model(seed=0)
    jax_params = torch_state_dict_to_jax_mlp(tm.params)
    x = np.random.default_rng(0).normal(size=(8, 28, 28)).astype(np.float32)
    out_t = tm.apply_fn(tm.params, x.reshape(8, -1))
    jm.set_parameters(jax_params)
    out_j = np.asarray(jm.apply_fn(jm.params, x))
    # flax MLP computes in bfloat16 -> tolerance is bf16 rounding
    np.testing.assert_allclose(out_t, out_j, atol=0.1)

    back = jax_mlp_params_to_torch(jax_params)
    for k, v in tm.params.items():
        np.testing.assert_array_equal(back[k], v)


def test_canonical_wire_with_compression():
    """A torch handle's canonical-wire frame compressed with bf16 decodes on
    a jax handle with default settings (codec spec rides in the frame)."""
    import numpy as np

    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.models import mlp_model

    tm = torch_mlp_model(seed=3, canonical=True)
    tm.set_contribution(["t-addr"], 77)
    assert len(tm.encode_parameters(compression="int8")) < len(tm.encode_parameters())
    with Settings.overridden(WIRE_COMPRESSION="bf16"):
        blob = tm.encode_parameters()
    jm = mlp_model(seed=0)
    jm.set_parameters(bytes(blob))
    assert jm.contributors == ["t-addr"] and jm.num_samples == 77
    want = torch_state_dict_to_jax_mlp(tm.params)
    import jax

    for got, ref in zip(jax.tree.leaves(jm.params), jax.tree.leaves(want)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2**-7, atol=1e-6
        )
