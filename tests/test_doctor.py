"""Diagnosis plane (fed_doctor): run ids, evidence bundles, rule catalog.

Covers: the federation-wide run id (seeded-deterministic mint, first-
establish-wins, the gRPC ``__run__:`` reserved control arg, LEDGERS-pin
adoption); bundle COMPLETENESS on every dump-on-failure path — workflow
exception on both wire schedulers, supervisor park (runtime and trip
kinds), devobs tripwire in park and abort action on both fused engines,
campaign invariant violation — each asserting the manifest lists the
expected members under the matching run id; the end-to-end correlation
contract (one run id stamped across ledger dumps, flight-recorder dumps,
observatory snapshots, supervisor reports, bench meta in an 8-node run);
manifest determinism; the happy-path zero-cost contract (no bundle unless
triggered); and diagnosis rule units on synthesized evidence.
"""

from __future__ import annotations

import glob
import json
import os
import time

import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry import REGISTRY, bundle, diagnosis
from p2pfl_tpu.telemetry.bundle import (
    WIRE_ARG_PREFIX,
    artifact_header,
    comparable_manifest,
    current_run_id,
    establish_run,
    load_manifest,
    write_bundle,
)
from p2pfl_tpu.telemetry.diagnosis import Evidence, diagnose
from p2pfl_tpu.telemetry.ledger import LEDGERS

_SHAPE = dict(
    cohort_fraction=0.5, cohort_min=2, seed=11,
    samples_per_node=8, feature_dim=8, hidden=(4,), batch_size=4,
)


@pytest.fixture(autouse=True)
def _clean_ledgers():
    LEDGERS.reset()
    yield
    LEDGERS.reset()


def _member_names(bundle_dir):
    man = load_manifest(bundle_dir)
    assert man is not None, f"no manifest in {bundle_dir}"
    return man, sorted(m["name"] for m in man["members"])


def _one_bundle(root):
    dirs = [d for d in glob.glob(os.path.join(root, "bundle_*")) if os.path.isdir(d)]
    assert dirs, f"no bundle under {root}"
    assert len(dirs) == 1, dirs
    return dirs[0]


# --- run-id plane -------------------------------------------------------------


def test_seeded_mint_is_deterministic_with_host_suffix():
    a = bundle.mint_run_id(seed=42, name="engine")
    b = bundle.mint_run_id(seed=42, name="engine")
    assert a == b and len(a) == 17 and a[12] == "-"
    assert bundle.mint_run_id(seed=43, name="engine") != a
    # unseeded mints are unique
    assert bundle.mint_run_id() != bundle.mint_run_id()


def test_establish_first_wins_and_fresh_overrides():
    rid = establish_run(seed=5, name="engine")
    assert establish_run(seed=999, name="other") == rid  # first wins
    assert current_run_id() == rid
    rid2 = establish_run(fresh=True)
    assert rid2 != rid and current_run_id() == rid2


def test_settings_pin_beats_everything():
    with Settings.overridden(RUN_ID="pinned-by-ci"):
        assert establish_run(seed=1) == "pinned-by-ci"
        assert current_run_id() == "pinned-by-ci"


def test_ledgers_pin_adopted_by_engine_establish():
    LEDGERS.configure("campaign-pinned")
    assert establish_run(seed=3, name="engine") == "campaign-pinned"


def test_adopt_requires_force_unless_unset():
    rid = establish_run(seed=7, name="engine")
    bundle.adopt_run_id("other-federation", force=False)
    assert current_run_id() == rid  # non-start_learning frames can't steal it
    bundle.adopt_run_id("other-federation", force=True)
    assert current_run_id() == "other-federation"


def test_run_id_rides_grpc_reserved_control_arg():
    pytest.importorskip("grpc")
    from p2pfl_tpu.comm.envelope import Envelope
    from p2pfl_tpu.comm.grpc.grpc_protocol import _env_to_pb, _pb_to_env

    rid = establish_run(seed=9, name="engine")
    env = Envelope.message("127.0.0.1:1", "vote_train_set", args=["a", "5"], round=1)
    assert env.run_id == rid
    pb = _env_to_pb(env)
    assert any(a == WIRE_ARG_PREFIX + rid for a in pb.control.args)
    back = _pb_to_env(pb)
    assert back.run_id == rid
    assert back.args == ["a", "5"]  # sentinel stripped before dispatch

    # absence-tolerant: a pre-run-id peer's frame decodes with run_id == ""
    bare = Envelope(source="n1", cmd="beat", args=["1.0"], ttl=3, msg_id=7)
    assert _pb_to_env(_env_to_pb(bare)).run_id == ""


def test_artifact_header_shape():
    establish_run(seed=4, name="engine")
    h = artifact_header(node="n0", kind="flightrec", schema_version=2)
    assert h["run_id"] == current_run_id()
    assert h["schema_version"] == 2 and h["kind"] == "flightrec"
    assert set(h["clock"]) == {"wall", "mono", "mono_to_wall_epoch"}


# --- bundle completeness on every dump-on-failure path ------------------------


def _crash_workflow(tmp_path, mode):
    """Run a real 2-node in-memory federation whose scheduler entry stage
    raises, then return the bundle its crash hook captured."""
    from p2pfl_tpu.learning.dataset import (
        RandomIIDPartitionStrategy,
        synthetic_mnist,
    )
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    if mode == "sync":
        from p2pfl_tpu.stages.base_node import StartLearningStage as Entry
    else:
        from p2pfl_tpu.stages.async_node import AsyncStartStage as Entry

    from p2pfl_tpu.utils.utils import wait_convergence

    def boom(node):
        raise RuntimeError(f"synthetic {mode} scheduler crash")

    orig = Entry.execute
    Entry.execute = staticmethod(boom)
    data = synthetic_mnist(n_train=64, n_test=16)
    parts = data.generate_partitions(2, RandomIIDPartitionStrategy)
    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=8) for i in range(2)]
    try:
        with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path)):
            for n in nodes:
                n.start()
            nodes[1].connect(nodes[0].addr)
            wait_convergence(nodes, 1, only_direct=False, wait=8.0)
            nodes[0].set_start_learning(rounds=1, epochs=1, mode=mode)
            deadline = time.time() + 30.0
            while time.time() < deadline:
                dirs = glob.glob(os.path.join(str(tmp_path), "bundle_*"))
                if dirs and os.path.exists(os.path.join(dirs[0], "manifest.json")):
                    return dirs[0]
                time.sleep(0.2)
            raise AssertionError("workflow crash produced no bundle")
    finally:
        Entry.execute = orig
        for n in nodes:
            n.stop()


@pytest.mark.parametrize("mode", ["sync", "async"])
def test_workflow_crash_bundles_complete(tmp_path, mode):
    out = _crash_workflow(tmp_path, mode)
    man, names = _member_names(out)
    assert man["trigger"] == "workflow_crash"
    assert man["run_id"]  # the initiator minted one at set_start_learning
    assert "context.json" in names
    assert "metrics.json" in names and "metrics.prom" in names
    assert any(n.startswith("flightrec_") for n in names)
    ctx = json.load(open(os.path.join(out, "context.json")))
    assert ctx["header"]["run_id"] == man["run_id"]
    assert ctx["error"]["type"] == "RuntimeError"
    assert f"synthetic {mode} scheduler crash" in ctx["error"]["message"]
    # the flight recorder rings rode along under the same run id
    for fr in glob.glob(os.path.join(out, "flightrec_*.json")):
        doc = json.load(open(fr))
        assert doc["header"]["run_id"] == man["run_id"]
        assert any(e.get("kind") == "workflow_crash" for e in doc["events"])


def test_supervisor_park_bundle_complete(tmp_path):
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.population import EngineSupervisor, PopulationEngine

    class _FailingEngine(PopulationEngine):
        def run(self, *a, **kw):
            raise RuntimeError("synthetic chunk failure")

    def factory(**kw):
        args = dict(num_nodes=6, **_SHAPE)
        args.update(kw)
        return _FailingEngine(**args)

    with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path / "bundles")):
        ck = FLCheckpointer(str(tmp_path / "ck"))
        with EngineSupervisor(
            factory, ck, node="sup-park", max_retries=0,
            backoff_s=0.0, degrade="off",
        ) as sup:
            report = sup.run(2, chunk=1)
    assert report.parked and report.park_reason == "runtime"
    assert report.run_id  # report carries the run id
    out = _one_bundle(str(tmp_path / "bundles"))
    man, names = _member_names(out)
    assert man["trigger"] == "supervisor_park"
    assert man["run_id"] == report.run_id
    assert "context.json" in names and "metrics.json" in names
    assert any(n.startswith("flightrec_") for n in names)
    ctx = json.load(open(os.path.join(out, "context.json")))
    assert ctx["context"]["reason"] == "runtime"


def test_supervisor_trip_park_bundle(tmp_path):
    """Supervised devobs trip: the engine's devobs_trip hook fires first,
    then the supervisor's trip-kind park captures its own evidence —
    both land in the run's (shared) bundle directory."""
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.population import EngineSupervisor, PopulationEngine

    def factory(**kw):
        args = dict(num_nodes=6, **_SHAPE)
        args.update(kw)
        return PopulationEngine(**args)

    with Settings.overridden(
        DOCTOR_BUNDLE_DIR=str(tmp_path / "bundles"),
        DEVOBS_ENABLED=True,
        DEVOBS_NAN_INJECT_ROUND=1,
        DEVOBS_TRIP_ACTION="park",
    ):
        ck = FLCheckpointer(str(tmp_path / "ck"))
        with EngineSupervisor(
            factory, ck, node="sup-trip", max_retries=0,
            backoff_s=0.0, degrade="off",
        ) as sup:
            report = sup.run(2, chunk=2)
    assert report.parked and report.park_reason.startswith("trip")
    out = _one_bundle(str(tmp_path / "bundles"))
    man, names = _member_names(out)
    assert man["run_id"] == report.run_id
    # last writer wins on the shared per-run dir: either trigger is
    # acceptable, both must have left a complete member set
    assert man["trigger"] in ("devobs_trip", "supervisor_park")
    assert "context.json" in names and "metrics.json" in names
    triggers = {
        labels.get("trigger")
        for labels, _child in REGISTRY.get("p2pfl_doctor_bundles_total").samples()
    }
    assert {"devobs_trip", "supervisor_park"} <= triggers


@pytest.mark.parametrize("engine_kind", ["sync", "async"])
@pytest.mark.parametrize("action", ["park", "abort"])
def test_devobs_trip_bundle_both_engines_both_actions(
    tmp_path, engine_kind, action
):
    from p2pfl_tpu.population import AsyncPopulationEngine, PopulationEngine

    with Settings.overridden(
        DOCTOR_BUNDLE_DIR=str(tmp_path),
        DEVOBS_ENABLED=True,
        DEVOBS_NAN_INJECT_ROUND=2,
        DEVOBS_TRIP_ACTION=action,
    ):
        if engine_kind == "sync":
            with PopulationEngine(6, **_SHAPE) as eng:
                rid = current_run_id()
                if action == "abort":
                    with pytest.raises(RuntimeError, match="devobs tripwire"):
                        eng.run(6, rounds_per_call=2)
                else:
                    res = eng.run(6, rounds_per_call=2)
                    assert res.tripped is not None
        else:
            with AsyncPopulationEngine(6, **_SHAPE) as eng:
                rid = current_run_id()
                if action == "abort":
                    with pytest.raises(RuntimeError, match="devobs tripwire"):
                        eng.run(6, eval_every=6, windows_per_call=2)
                else:
                    res = eng.run(6, eval_every=6, windows_per_call=2)
                    assert res.tripped is not None
    out = _one_bundle(str(tmp_path))
    man, names = _member_names(out)
    assert man["trigger"] == "devobs_trip"
    assert man["run_id"] == rid
    assert "context.json" in names and "metrics.json" in names
    ctx = json.load(open(os.path.join(out, "context.json")))
    assert ctx["context"]["kind"] == "nonfinite"
    # the diagnosis engine attributed the trip
    inc = json.load(open(os.path.join(out, "incident.json")))
    assert inc["top"] == "device_tripwire"


def test_campaign_violation_bundle(tmp_path, monkeypatch):
    from p2pfl_tpu.campaigns import engine as campaign_engine
    from p2pfl_tpu.population import scenarios as scn_mod

    def explode(scn, ledger_dir=None):
        raise RuntimeError("synthetic scenario failure")

    monkeypatch.setattr(scn_mod, "run_scenario_wire", explode)
    with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path)):
        report = campaign_engine.run_campaign(seed=3, n_scenarios=1)
    assert report["violations_total"] >= 1
    entry = report["scenarios"][0]
    assert entry["verdict"] == "error"
    assert entry["bundle"] and os.path.isdir(entry["bundle"])
    man, names = _member_names(entry["bundle"])
    assert man["trigger"] == "campaign_violation"
    assert man["run_id"] == entry["run_id"]  # scenario's pinned run id
    assert "context.json" in names
    ctx = json.load(open(os.path.join(entry["bundle"], "context.json")))
    assert ctx["error"]["type"] == "RuntimeError"


def _load_bench(alias):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        alias,
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py"),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_bench_meta_carries_run_id():
    bench = _load_bench("bench_for_doctor")
    establish_run(seed=12, name="engine")
    assert bench._bench_meta(seed=12)["run_id"] == current_run_id()


# --- end-to-end correlation (acceptance: one run id across everything) --------


def test_8node_run_one_run_id_across_all_artifacts(tmp_path):
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.population import EngineSupervisor, PopulationEngine
    from p2pfl_tpu.telemetry.flight_recorder import FlightRecorder

    def factory(**kw):
        args = dict(num_nodes=8, **_SHAPE)
        args.update(kw)
        return PopulationEngine(**args)

    ck = FLCheckpointer(str(tmp_path / "ck"))
    snap_path = os.path.join(str(tmp_path), "federation_snapshot.json")
    with EngineSupervisor(factory, ck, node="sup-corr", backoff_s=0.0) as sup:
        report = sup.run(2, chunk=1)
        snap = sup.snapshot(report.results[-1], top_n=4, path=snap_path)
    rid = report.run_id
    assert rid, "supervised run must establish a run id"
    assert current_run_id() == rid

    # 1. supervisor report + snapshot
    assert snap["supervisor"]["run_id"] == rid
    # 2. observatory snapshot doc header (write_snapshot_doc choke point)
    doc = json.load(open(snap_path))
    assert doc["header"]["run_id"] == rid
    # 3. trajectory ledger dump headers
    paths = LEDGERS.dump_all(str(tmp_path / "ledgers"))
    assert paths
    for p in paths:
        head = json.loads(open(p).readline())
        assert head["run_id"] == rid, p
    # 4. flight-recorder dump header
    rec = FlightRecorder("corr-node")
    rec.record("stage", stage="x")
    fr_path = rec.dump("manual", directory=str(tmp_path))
    assert json.load(open(fr_path))["header"]["run_id"] == rid
    # 5. bench meta block
    assert _load_bench("bench_for_corr")._bench_meta()["run_id"] == rid
    # 6. an explicitly-requested bundle joins them all under that id
    with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path / "bundles")):
        out = write_bundle("manual")
    man, _ = _member_names(out)
    assert man["run_id"] == rid


# --- manifest & happy-path contracts ------------------------------------------


def test_manifest_determinism_and_excluded_isolation(tmp_path):
    establish_run(run_id="det-run")
    LEDGERS.emit("n0", "round_open", round=1)
    with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path / "a")):
        out_a = write_bundle("manual")
    with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path / "b")):
        out_b = write_bundle("manual")
    man_a, man_b = load_manifest(out_a), load_manifest(out_b)
    # wall-clock lives ONLY in the excluded section
    assert "written_at" in man_a["excluded"]
    assert comparable_manifest(man_a) == comparable_manifest(man_b)
    # canonical ledger members carry sha256 in the comparable part, and the
    # bytes really are identical
    led = [m for m in man_a["members"] if m["kind"] == "ledger"]
    assert led and all("sha256" in m for m in led)


def test_happy_path_writes_no_bundle(tmp_path):
    """No failure, no bundle: a clean engine run must not create bundle
    dirs (the <= 1.02x overhead acceptance is 'zero artifacts unless
    triggered')."""
    from p2pfl_tpu.population import PopulationEngine

    with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path)):
        with PopulationEngine(6, **_SHAPE) as eng:
            eng.run(2, rounds_per_call=2)
    assert not glob.glob(os.path.join(str(tmp_path), "bundle_*"))


def test_bundle_disabled_master_switch(tmp_path):
    with Settings.overridden(
        DOCTOR_BUNDLE_DIR=str(tmp_path), DOCTOR_BUNDLE_ENABLED=False
    ):
        assert write_bundle("manual") is None
    assert not glob.glob(os.path.join(str(tmp_path), "bundle_*"))


# --- diagnosis rule units -----------------------------------------------------


def test_clean_evidence_yields_no_findings():
    assert diagnose(Evidence()) == []


def test_codec_storm_routes_away_from_byzantine():
    ev = Evidence()
    ev.ledgers["n0"] = [
        {"kind": "admission_rejected", "round": r, "sender": f"n{r}",
         "reason": "decode_error"}
        for r in (1, 2, 3)
    ]
    fs = diagnose(ev)
    assert [f.rule for f in fs] == ["codec_corruption_storm"]


def test_byzantine_burst_with_corroboration():
    ev = Evidence()
    ev.ledgers["n0"] = [
        {"kind": "admission_rejected", "round": r, "sender": "adv",
         "reason": "norm_screen"}
        for r in (1, 2, 3)
    ]
    ev.snapshot = {"peers": {"adv": {"scores": {"suspect": 3.0}}}}
    fs = diagnose(ev)
    assert fs[0].rule == "byzantine_active"
    assert fs[0].confidence > 0.6
    assert any("suspect" in e for e in fs[0].evidence)
    assert fs[0].exonerated  # the checks that came back clean are on record


def test_under_rejection_fires_only_with_zero_rejections():
    ev = Evidence()
    ev.metrics = {
        "p2pfl_chaos_faults_total": {
            "samples": [{"labels": {"fault": "byzantine_zero"}, "value": 2.0}]
        }
    }
    assert diagnose(ev)[0].rule == "adversary_under_rejection"
    ev.ledgers["n0"] = [
        {"kind": "admission_rejected", "round": 1, "sender": "adv",
         "reason": "norm_screen"},
        {"kind": "admission_rejected", "round": 2, "sender": "adv",
         "reason": "norm_screen"},
    ]
    rules = [f.rule for f in diagnose(ev)]
    assert "adversary_under_rejection" not in rules
    assert "byzantine_active" in rules


def test_heartbeat_false_death_requires_no_chaos():
    ev = Evidence()
    ev.flightrecs["n0"] = {"node": "n0", "events": [
        {"kind": "peer_lost", "peer": "n2"},
        {"kind": "peer_recovered", "peer": "n2"},
    ]}
    assert diagnose(ev)[0].rule == "heartbeat_false_death"
    ev.metrics = {
        "p2pfl_chaos_faults_total": {
            "samples": [{"labels": {"fault": "partition"}, "value": 1.0}]
        }
    }
    rules = [f.rule for f in diagnose(ev)]
    assert "heartbeat_false_death" not in rules  # the flap has a cause


def test_parity_divergence_localizes_first_event():
    ev = Evidence()
    ev.parity = {
        "status": "DIVERGED",
        "compared_events": 17,
        "first_divergence": {"round": 3, "kind": "aggregate_committed"},
    }
    f = diagnose(ev)[0]
    assert f.rule == "parity_divergence"
    assert f.data["first_divergence"]["round"] == 3


def test_oom_from_context_error():
    ev = Evidence()
    ev.context = {"trigger": "supervisor_park",
                  "error": {"message": "RESOURCE_EXHAUSTED: out of memory"}}
    assert diagnose(ev)[0].rule == "oom_degrade_ladder"


def test_min_confidence_floor_filters():
    ev = Evidence()
    ev.flightrecs["n0"] = {"node": "n0", "events": [
        {"kind": "peer_lost", "peer": "n2"},
        {"kind": "peer_recovered", "peer": "n2"},
    ]}
    assert diagnose(ev)  # 0.6 confidence passes the default 0.5 floor
    with Settings.overridden(DOCTOR_MIN_CONFIDENCE=0.9):
        assert diagnose(ev) == []


def test_incident_doc_and_render():
    ev = Evidence(run_id="r7")
    ev.parity = {"status": "DIVERGED", "first_divergence": {"round": 1}}
    findings = diagnose(ev)
    doc = diagnosis.incident_doc(findings, run_id="r7", source="here")
    assert doc["top"] == "parity_divergence" and doc["run_id"] == "r7"
    text = diagnosis.render_report(doc)
    assert "parity_divergence" in text and "run r7" in text
