"""ModelHandle contract tests — get/set/encode round-trips and wrong-shape
errors, mirroring the reference framework matrix tests
(test/learning/frameworks_test.py:63-206)."""

import numpy as np
import pytest

from p2pfl_tpu.exceptions import ModelNotMatchingError
from p2pfl_tpu.models import ModelHandle, cnn_model, mlp_model, resnet18_model


def test_mlp_forward_shape():
    m = mlp_model(seed=0)
    x = np.random.default_rng(0).normal(size=(4, 28, 28)).astype(np.float32)
    logits = m.apply_fn(m.params, x)
    assert logits.shape == (4, 10)
    assert str(logits.dtype) == "float32"


def test_cnn_forward_shape():
    m = cnn_model(seed=0)
    x = np.zeros((2, 28, 28, 1), np.float32)
    assert m.apply_fn(m.params, x).shape == (2, 10)


@pytest.mark.slow
def test_resnet_forward_shape():
    m = resnet18_model(seed=0)
    x = np.zeros((2, 32, 32, 3), np.float32)
    assert m.apply_fn(m.params, x).shape == (2, 10)


def test_get_set_roundtrip():
    m = mlp_model(seed=0)
    m2 = mlp_model(seed=1)
    params = m.get_parameters()
    m2.set_parameters(params)
    for a, b in zip(params, m2.get_parameters()):
        np.testing.assert_array_equal(a, b)


def test_encode_decode_roundtrip_with_metadata():
    m = mlp_model(seed=0)
    m.set_contribution(["node-a"], 321)
    m.add_info("scaffold", {"lr": 0.1})
    blob = m.encode_parameters()
    m2 = mlp_model(seed=1)
    m2.set_parameters(blob)
    for a, b in zip(m.get_parameters(), m2.get_parameters()):
        np.testing.assert_array_equal(a, b)
    assert m2.get_contributors() == ["node-a"]
    assert m2.get_num_samples() == 321
    assert m2.get_info("scaffold") == {"lr": 0.1}


def test_wrong_shape_raises():
    m = mlp_model(seed=0)
    bad = [np.zeros((1, 1), np.float32)] * len(m.get_parameters())
    with pytest.raises(ModelNotMatchingError):
        m.set_parameters(bad)


def test_wrong_count_raises():
    m = mlp_model(seed=0)
    with pytest.raises(ModelNotMatchingError):
        m.set_parameters(m.get_parameters()[:-1])


def test_build_copy_independent():
    m = mlp_model(seed=0)
    copy = m.build_copy(contributors=["x"], num_samples=5)
    assert copy.get_contributors() == ["x"]
    zeroed = [np.zeros_like(p) for p in copy.get_parameters()]
    copy.set_parameters(zeroed)
    # original untouched (leaf 0 is a zero-init bias; check across all leaves)
    assert any(np.abs(p).sum() > 0 for p in m.get_parameters())
    assert all(np.abs(p).sum() == 0 for p in copy.get_parameters())


def test_handle_is_pure_container_for_any_pytree():
    h = ModelHandle({"a": np.ones((2, 2), np.float32)})
    h.set_parameters([np.zeros((2, 2), np.float32)])
    assert np.asarray(h.get_tree()["a"]).sum() == 0
