"""Transformer model family + sequence parallelism.

Checks: (a) the ring-attention transformer applied under shard_map over a
'seq' mesh axis produces the same logits as the single-device blockwise
variant, (b) LM loss + train step work under sequence parallelism and reduce
the loss, (c) the classifier variant plugs into JaxLearner and the mesh
simulation (federated transformer fine-tuning).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh

from p2pfl_tpu.models.transformer import (
    TransformerLM,
    causal_lm_loss,
    transformer_classifier_model,
    transformer_lm_model,
)
from p2pfl_tpu.parallel.sequence import (

    make_sequence_parallel_train_step,
    sequence_parallel_apply,
    sequence_parallel_lm_loss,
    shard_tokens,
)

# LM train steps compile ~5-12s each -> excluded from the fast subset
pytestmark = pytest.mark.slow

VOCAB, SEQ, B = 64, 32, 2


def _tokens(seed=0, b=B, s=SEQ):
    return jax.random.randint(jax.random.key(seed), (b, s), 0, VOCAB)


def _tiny_lm(attention_kind="blockwise", axis_name=None):
    return transformer_lm_model(
        seed=0,
        seq_len=SEQ,
        vocab_size=VOCAB,
        num_layers=2,
        num_heads=2,
        embed_dim=32,
        attention_kind=attention_kind,
        axis_name=axis_name,
    )


def test_lm_forward_shapes_and_determinism():
    model = _tiny_lm()
    toks = _tokens()
    out1 = model.apply_fn(model.params, toks)
    out2 = model.apply_fn(model.params, toks)
    assert out1.shape == (B, SEQ, VOCAB)
    assert out1.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


@pytest.mark.parametrize("kind", ["dense", "flash"])
def test_attention_kinds_agree(kind):
    ref = _tiny_lm("blockwise")
    alt = _tiny_lm(kind)
    toks = _tokens()
    out_ref = ref.apply_fn(ref.params, toks)
    out_alt = alt.apply_fn(alt.params, toks)  # same seed -> same params
    # bf16 blocks: per-path rounding differs by a few ulps of the ~O(1) logits
    np.testing.assert_allclose(np.asarray(out_alt), np.asarray(out_ref), atol=6e-2)


def test_ring_transformer_matches_blockwise_on_mesh():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    ref = _tiny_lm("blockwise")
    ring = _tiny_lm("ring", axis_name="seq")
    toks = _tokens()
    out_ref = ref.apply_fn(ref.params, toks)
    sp_apply = jax.jit(sequence_parallel_apply(ring.apply_fn, mesh, "seq"))
    out_ring = sp_apply(ring.params, shard_tokens(toks, mesh, "seq"))
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref), atol=6e-2)


def test_sequence_parallel_lm_loss_matches_local():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    ref = _tiny_lm("blockwise")
    ring = _tiny_lm("ring", axis_name="seq")
    toks = _tokens()
    local = causal_lm_loss(ref.apply_fn(ref.params, toks), toks)
    sp_loss = jax.jit(sequence_parallel_lm_loss(ring.apply_fn, mesh, "seq"))
    dist = sp_loss(ring.params, shard_tokens(toks, mesh, "seq"))
    np.testing.assert_allclose(float(dist), float(local), atol=2e-2)


def test_sequence_parallel_train_step_reduces_loss():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    ring = _tiny_lm("ring", axis_name="seq")
    opt = optax.adam(1e-2)
    step = make_sequence_parallel_train_step(ring.apply_fn, opt, mesh, "seq")
    params, opt_state = ring.params, opt.init(ring.params)
    toks = shard_tokens(_tokens(), mesh, "seq")
    losses = []
    for _ in range(10):
        params, opt_state, loss = step(params, opt_state, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_long_context_ring_runs():
    """8-way sequence parallelism on a longer context than any single test
    above; smoke-checks memory-bounded exact attention end to end."""
    mesh = Mesh(np.array(jax.devices()[:8]), ("seq",))
    model = transformer_lm_model(
        seed=0, seq_len=512, vocab_size=VOCAB, num_layers=1, num_heads=2,
        embed_dim=32, attention_kind="ring", axis_name="seq",
    )
    toks = _tokens(s=512)
    sp_apply = jax.jit(sequence_parallel_apply(model.apply_fn, mesh, "seq"))
    out = sp_apply(model.params, shard_tokens(toks, mesh, "seq"))
    assert out.shape == (B, 512, VOCAB)
    assert np.isfinite(np.asarray(out)).all()


def test_non_ring_kind_with_axis_name_rejected():
    with pytest.raises(ValueError, match="requires attention_kind='ring'"):
        _tiny_lm("blockwise", axis_name="seq").apply_fn(
            _tiny_lm("blockwise").params, _tokens()
        )


def test_ring_classifier_pools_globally():
    from p2pfl_tpu.models.transformer import TransformerClassifier

    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    ref_mod = TransformerClassifier(
        num_classes=4, vocab_size=VOCAB, num_layers=1, num_heads=2, embed_dim=32
    )
    ring_mod = ref_mod.copy(attention_kind="ring", axis_name="seq")
    params = ref_mod.init(jax.random.key(0), jnp.zeros((1, SEQ), jnp.int32))
    toks = _tokens()
    out_ref = ref_mod.apply(params, toks)
    sp = jax.jit(
        jax.shard_map(
            ring_mod.apply,
            mesh=mesh,
            in_specs=(jax.sharding.PartitionSpec(), jax.sharding.PartitionSpec(None, "seq")),
            out_specs=jax.sharding.PartitionSpec(),
            check_vma=False,
        )
    )
    out_ring = sp(params, shard_tokens(toks, mesh, "seq"))
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref), atol=6e-2)


def test_federated_lm_finetuning_mesh_simulation():
    """Federated causal-LM fine-tuning as one sharded XLA program: 16 nodes,
    committee of 4, transformer LM, token-level eval improving."""
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    rng = np.random.default_rng(0)
    N, S, L = 16, 8, 32
    # learnable corpus: arithmetic token sequences mod VOCAB
    starts = rng.integers(0, VOCAB, size=(N, S, 1))
    x = ((starts + np.arange(L)[None, None, :]) % VOCAB).astype(np.int32)
    y = np.zeros((N, S), np.int32)  # unused for lm
    mask = np.ones((N, S), np.float32)
    xt = ((rng.integers(0, VOCAB, size=(16, 1)) + np.arange(L)) % VOCAB).astype(np.int32)

    model = transformer_lm_model(
        seed=0, seq_len=L, vocab_size=VOCAB, num_layers=1, num_heads=2, embed_dim=32
    )
    sim = MeshSimulation(
        model, (x, y, mask), test_data=(xt, None), train_set_size=4,
        batch_size=4, lr=5e-3, seed=0, task="lm",
    )
    res = sim.run(rounds=6, epochs=1, warmup=False)
    assert res.test_loss[-1] < res.test_loss[0] * 0.7, res.test_loss
    assert res.test_acc[-1] > res.test_acc[0], res.test_acc


# --- classifier: federated fine-tuning path ----------------------------------


def test_classifier_with_jax_learner():
    from p2pfl_tpu.learning.dataset.dataset import FederatedDataset
    from p2pfl_tpu.learning.learner import JaxLearner

    rng = np.random.default_rng(0)
    # class-conditional token distributions: class c draws from its half of
    # the vocab with 30% noise — learnable through the mean-pool head
    y = rng.integers(0, 2, size=96).astype(np.int32)
    base = rng.integers(0, VOCAB // 2, size=(96, 16))
    x = (base + (VOCAB // 2) * y[:, None]).astype(np.int32)
    noise = rng.random((96, 16)) < 0.3
    x[noise] = rng.integers(0, VOCAB, size=int(noise.sum()))
    data = FederatedDataset.from_arrays(x, y)
    data.generate_train_test_split(test_size=0.25, seed=0)
    model = transformer_classifier_model(
        seed=0, seq_len=16, num_classes=2, vocab_size=VOCAB,
        num_layers=1, num_heads=2, embed_dim=32,
    )
    learner = JaxLearner(model, data, "node0", lr=5e-3, batch_size=16, seed=0)
    learner.set_epochs(6)
    learner.fit()
    metrics = learner.evaluate()
    assert metrics["test_acc"] > 0.6, metrics


def test_ring_flash_transformer_matches_blockwise_on_mesh():
    """attention_kind='ring_flash' (Pallas flash-carry fold per ring
    rotation) produces the same logits as the local blockwise reference —
    the model-level proof that the faster ring forward is still exact."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    ref = _tiny_lm("blockwise")
    ring = _tiny_lm("ring_flash", axis_name="seq")
    toks = _tokens()
    out_ref = ref.apply_fn(ref.params, toks)
    sp_apply = jax.jit(sequence_parallel_apply(ring.apply_fn, mesh, "seq"))
    out_ring = sp_apply(ring.params, shard_tokens(toks, mesh, "seq"))
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref), atol=6e-2)
