"""SEEDED DEFECT (C2): blocking operations while a lock is held.

``announce`` performs a transport send and a ``time.sleep`` inside the
table lock; every other thread touching the table stalls behind the
network. ``reap`` joins a worker thread under the same lock.
"""

from __future__ import annotations

import threading
import time


class PeerTable:
    def __init__(self, protocol) -> None:
        self._table_lock = threading.Lock()
        self._peers: dict = {}
        self._worker_thread = None
        self.protocol = protocol

    def announce(self, env) -> None:
        with self._table_lock:
            for peer in self._peers:
                self.protocol.send(peer, env)  # network I/O under the lock
            time.sleep(0.05)  # pacing sleep under the lock

    def reap(self) -> None:
        with self._table_lock:
            if self._worker_thread is not None:
                self._worker_thread.join(timeout=1.0)  # join under the lock
