"""Seeded-defect fixtures for the static analysis suite.

Each module plants ONE class of bug the checkers exist to catch; the
analyzer regression tests (tests/test_analysis.py) run the checkers over
this directory and assert every seed is flagged by the intended checker —
so a refactor of the AST machinery that quietly blinds a checker fails CI.

These modules are parsed, never imported (the analysis is pure-AST); keep
them import-free of heavy deps anyway so an accidental import stays cheap.
"""
