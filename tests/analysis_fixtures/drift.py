"""SEEDED DEFECT (C5): config / wire drift — a raw P2PFL_TPU_* env read
bypassing config.py, an emitted metric documented nowhere, and a command
sent that no Command class defines (so both transports drop it)."""

from __future__ import annotations

import os

from p2pfl_tpu.telemetry import REGISTRY

# bypasses the validated fail-fast env layer in config.py
_TURBO = os.environ.get("P2PFL_TPU_FIXTURE_TURBO", "0") == "1"

# appears in neither docs/ nor tests/ (the fixtures dir is excluded from
# the reference corpus precisely so this stays undocumented)
_GHOST = REGISTRY.counter(
    "p2pfl_fixture_ghost_total", "seeded undocumented metric", labels=("node",)
)


class GhostAnnouncer:
    def __init__(self, protocol) -> None:
        self.protocol = protocol

    def announce(self) -> None:
        # no Command subclass anywhere defines "ghost_announce": receivers
        # on either transport drop it as unknown
        self.protocol.broadcast(self.protocol.build_msg("ghost_announce"))
