"""SEEDED DEFECT (C1): a lock-order inversion across two code paths.

``transfer_ab`` nests B inside A; ``transfer_ba`` nests A inside B. Two
threads running one each can deadlock — the acquisition-order graph has the
cycle A -> B -> A. Also seeds a guaranteed self-deadlock: re-entering a
non-reentrant ``threading.Lock`` through a same-class call.
"""

from __future__ import annotations

import threading


class Ledger:
    def __init__(self) -> None:
        self._alpha_lock = threading.Lock()
        self._beta_lock = threading.Lock()
        self._guard = threading.Lock()
        self.alpha = 0
        self.beta = 0

    def transfer_ab(self, amount: int) -> None:
        with self._alpha_lock:
            with self._beta_lock:  # order: alpha -> beta
                self.alpha -= amount
                self.beta += amount

    def transfer_ba(self, amount: int) -> None:
        with self._beta_lock:
            with self._alpha_lock:  # order: beta -> alpha — INVERSION
                self.beta -= amount
                self.alpha += amount

    def _audit(self) -> int:
        with self._guard:
            return self.alpha + self.beta

    def audited_total(self) -> int:
        with self._guard:
            # same-class call that re-acquires the non-reentrant lock we
            # already hold: guaranteed deadlock, not just a potential one
            return self._audit()
