"""SEEDED DEFECT (C4): side effects inside jit-compiled functions — they
run once at trace time, then silently freeze: the metric stops counting,
the timestamp is baked into the compiled program as a constant."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from p2pfl_tpu.telemetry import REGISTRY

_STEPS = REGISTRY.counter("p2pfl_fixture_steps_total", "seeded", labels=("node",))


@jax.jit
def noisy_step(params, grads):
    _STEPS.labels("fixture").inc()  # traced once, never counts again
    lr = 0.1 + 0.01 * np.random.random()  # baked in at trace time
    return jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)


def _scaled_loss_impl(x):
    started = time.time()  # trace-time constant, not a clock
    return jnp.sum(x * x) + (started - started)


scaled_loss = jax.jit(_scaled_loss_impl)
