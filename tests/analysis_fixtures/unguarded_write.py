"""SEEDED DEFECT (C3): shared attributes written from a daemon-thread entry
point with no guarding lock (and no ``# unguarded-ok:`` annotation), racing
the main-thread writer of the same attributes."""

from __future__ import annotations

import threading


class ProgressBoard:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.rounds_done = 0
        self.best_score = 0.0

    def start(self) -> None:
        threading.Thread(target=self._poll, daemon=True).start()

    def _poll(self) -> None:
        # daemon-thread entry point: read-modify-write with no lock
        self.rounds_done = self.rounds_done + 1
        self.best_score = max(self.best_score, 1.0)

    def reset(self) -> None:
        with self._lock:
            self.rounds_done = 0
            self.best_score = 0.0
