"""Privacy plane tests: mask algebra, bit-exactness, dropout repair,
hostile frames, the RDP accountant, and the wire-overhead bound.

The load-bearing claims:

* pairwise masks cancel EXACTLY (modular integer arithmetic) in any merge
  order — masked FedAvg is bit-exact with the identical pipeline run
  maskless at zero dropout;
* a dead masker's uncancelled shares are reconstructible from journaled /
  revealed pair secrets, so a crash mid-round cannot poison the sum;
* hostile masked frames die as counted structural rejections BEFORE any
  lattice value reaches the aggregator or the anchor;
* the (previously dead) accountant in ``learning/privacy.py`` is wired,
  monotone, and honest about voided guarantees;
* a masked frame costs at most 1.15x the PR 12 topk+quant frame bytes for
  the same tensors (the shared support ships zero index bytes).
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from p2pfl_tpu.comm.admission import AdmissionController
from p2pfl_tpu.config import Settings
from p2pfl_tpu.learning.aggregators.masked import MaskedFedAvg
from p2pfl_tpu.learning.privacy import (
    dp_sgd_privacy_spent,
    gaussian_rdp_epsilon,
    resolve_seed,
)
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.privacy import (
    BUDGETS,
    PairwiseMasker,
    PrivacyPlane,
    lattice_qmax,
    ring_dtype,
    round_secret,
    shared_support,
    signed_share,
    wire_epsilon,
)
from p2pfl_tpu.telemetry import REGISTRY


@pytest.fixture(autouse=True)
def _fresh_registry():
    REGISTRY.reset()
    BUDGETS.reset()
    yield
    REGISTRY.reset()
    BUDGETS.reset()


def _federation(n=3, round=2, seed=0):
    """n planes with exchanged keys + n toy models around a shared anchor."""
    addrs = [f"n{i}" for i in range(n)]
    planes = {a: PrivacyPlane(a) for a in addrs}
    for a in addrs:
        for b in addrs:
            if a != b:
                assert planes[a].learn_key(b, planes[b].masker.public_key_hex())
    rng = np.random.default_rng(seed)
    anchor = [
        rng.normal(size=(24, 6)).astype(np.float32),
        rng.normal(size=(11,)).astype(np.float32),
    ]
    models = {
        a: ModelHandle(
            params=[
                x + rng.normal(scale=1e-3, size=x.shape).astype(np.float32)
                for x in anchor
            ],
            contributors=[a],
            num_samples=10 + i,
        )
        for i, a in enumerate(addrs)
    }
    return addrs, planes, anchor, models, round


# --- mask algebra -------------------------------------------------------------


def test_pair_secrets_symmetric_and_distinct():
    a, b, c = PairwiseMasker("a"), PairwiseMasker("b"), PairwiseMasker("c")
    for x, y in ((a, b), (a, c), (b, c)):
        assert x.learn_key(y.addr, y.public_key_hex())
        assert y.learn_key(x.addr, x.public_key_hex())
    assert a.pair_secret("b") == b.pair_secret("a")
    assert a.pair_secret("c") == c.pair_secret("a")
    assert a.pair_secret("b") != a.pair_secret("c")


def test_hostile_pubkeys_rejected():
    m = PairwiseMasker("a")
    assert not m.learn_key("b", "zz-not-hex")
    assert not m.learn_key("b", "0")  # out of group range
    assert not m.learn_key("b", "1")
    assert not m.learn_key("a", PairwiseMasker("x").public_key_hex())  # self


def test_total_masks_cancel_over_committee():
    addrs, planes, _, _, r = _federation(4)
    bits = Settings.PRIVACY_RING_BITS
    for tensor_idx, k in ((0, 31), (1, 7)):
        acc = np.zeros(k, ring_dtype(bits))
        for a in addrs:
            acc = acc + planes[a].masker.total_mask(addrs, r, tensor_idx, k, bits)
        assert not acc.any()


def test_signed_share_pair_sums_to_zero():
    a, b = PairwiseMasker("a"), PairwiseMasker("b")
    a.learn_key("b", b.public_key_hex())
    b.learn_key("a", a.public_key_hex())
    rs = a.pair_round_secret("b", 5)
    assert rs == b.pair_round_secret("a", 5)  # both ends derive it
    bits = Settings.PRIVACY_RING_BITS
    s_ab = signed_share(rs, "a", "b", 0, 16, bits)
    s_ba = signed_share(rs, "b", "a", 0, 16, bits)
    assert not (s_ab + s_ba).any()
    # distinct streams per round and tensor
    rs6 = a.pair_round_secret("b", 6)
    assert not np.array_equal(s_ab, signed_share(rs6, "a", "b", 0, 16, bits))
    assert not np.array_equal(s_ab, signed_share(rs, "a", "b", 1, 16, bits))


def test_repair_reveal_is_round_scoped():
    """The wire form of a repair is H(pair_secret, round) — NOT the pair
    secret. A captured round-r reveal must not regenerate any other round's
    mask streams, even across a journaled crash-restart with the same
    keypair (the exact leak of revealing the raw pair secret)."""
    a, b = PairwiseMasker("a"), PairwiseMasker("b")
    a.learn_key("b", b.public_key_hex())
    b.learn_key("a", a.public_key_hex())
    r, bits = 5, Settings.PRIVACY_RING_BITS
    reveal = round_secret(a.pair_secret("b"), r)
    assert reveal != a.pair_secret("b")
    # the reveal reconstructs round r's stream exactly...
    assert np.array_equal(
        PairwiseMasker.stream(reveal, 0, 16, bits),
        PairwiseMasker.stream(a.pair_round_secret("b", r), 0, 16, bits),
    )
    # ...but feeding it back through the KDF in the observer's only possible
    # roles (as a pair secret, or as a later round's secret) yields streams
    # unrelated to what the pair actually masks with in round r+1 — the
    # per-round scoping holds even though the keypair is unchanged.
    true_next = PairwiseMasker.stream(a.pair_round_secret("b", r + 1), 0, 16, bits)
    assert not np.array_equal(
        PairwiseMasker.stream(round_secret(reveal, r + 1), 0, 16, bits), true_next
    )
    assert not np.array_equal(
        PairwiseMasker.stream(reveal, 0, 16, bits), true_next
    )


def test_shared_support_deterministic_sorted_bounded():
    idx = shared_support(3, 0, 1000, 0.1)
    assert np.array_equal(idx, shared_support(3, 0, 1000, 0.1))
    assert idx.size == 100 and (np.diff(idx) > 0).all()
    assert 0 <= idx[0] and idx[-1] < 1000
    assert not np.array_equal(idx, shared_support(4, 0, 1000, 0.1))
    assert shared_support(3, 0, 3, 0.1).size == 1  # floor of one value


def test_lattice_qmax_bounds():
    from p2pfl_tpu.privacy.masking import LATTICE_HEADROOM

    assert lattice_qmax(16, 3) == 32767 // (3 * LATTICE_HEADROOM)
    # honest worst-case sum stays range-checkable inside the signed half
    assert 3 * lattice_qmax(16, 3) * LATTICE_HEADROOM <= (1 << 15) - 1
    with pytest.raises(ValueError):
        lattice_qmax(16, 40000)  # qmax < 1


def test_pack_ring_roundtrip_all_widths():
    from p2pfl_tpu.privacy.masking import pack_ring, unpack_ring

    rng = np.random.default_rng(3)
    for bits in (12, 16, 32):
        for k in (1, 2, 7, 64):
            v = rng.integers(0, 1 << bits, size=k, dtype=np.uint64).astype(
                ring_dtype(bits)
            )
            packed = pack_ring(v, bits)
            assert packed.dtype == np.uint8
            if bits == 12:
                assert packed.size == 3 * ((k + 1) // 2)  # 1.5 B/value
            assert np.array_equal(unpack_ring(packed, k, bits), v)
    # unreduced mod-2**16 carrier reduces on pack (ring consistency)
    v = np.array([4096 + 5, 65535], np.uint16)
    assert np.array_equal(
        unpack_ring(pack_ring(v, 12), 2, 12), np.array([5, 4095], np.uint16)
    )
    with pytest.raises(ValueError):
        unpack_ring(np.zeros(4, np.uint8), 2, 12)  # wrong plane length
    with pytest.raises(ValueError):
        unpack_ring(np.zeros(3, np.uint8), 4, 12)


def test_hostile_packed_frame_dies_as_value_error():
    """A frame whose packed planes disagree with the declared ks must raise
    in parse_frame — the command handler surfaces that as a counted
    ``corrupt`` rejection before any value enters a lattice sum."""
    addrs, planes, anchor, models, r = _federation(2)
    handle = planes[addrs[0]].mask_own(models[addrs[0]], anchor, r, addrs)
    blob = PrivacyPlane.encode_frame(handle)
    from p2pfl_tpu.ops.serialization import deserialize_arrays

    arrays, meta = deserialize_arrays(bytes(blob))
    assert PrivacyPlane.is_masked_frame(meta)
    lat = PrivacyPlane.parse_frame(arrays, meta)
    for x, y in zip(lat, handle.get_parameters()):
        ring = 1 << Settings.PRIVACY_RING_BITS
        assert np.array_equal(x, (np.asarray(y).astype(np.uint32) % ring).astype(x.dtype))
    with pytest.raises(ValueError):
        PrivacyPlane.parse_frame(arrays[:-1], meta)  # tensor count
    with pytest.raises(ValueError):
        PrivacyPlane.parse_frame(
            [np.zeros(2, np.uint8)] * len(arrays), meta
        )  # plane length
    bad_meta = {**meta, "__masked__": {**meta["__masked__"], "bits": 13}}
    with pytest.raises(ValueError):
        PrivacyPlane.parse_frame(arrays, bad_meta)  # unknown ring


# --- bit-exactness & merge-order independence ---------------------------------


def _encode_all(planes, models, anchor, addrs, r, mask):
    handles = []
    for a in addrs:
        planes[a].reset()
        handles.append(planes[a].mask_own(models[a], anchor, r, addrs, mask=mask))
    return handles


def test_masked_bitexact_with_maskless_and_merge_order_independent():
    addrs, planes, anchor, models, r = _federation(3)
    agg = MaskedFedAvg()
    agg.set_addr(addrs[0])

    def finalized(mask, order):
        handles = _encode_all(planes, models, anchor, addrs, r, mask)
        merged = agg.aggregate([handles[i] for i in order])
        out, outcome = planes[addrs[0]].finalize(merged, addrs, anchor)
        assert outcome == "ok"
        return out

    base = finalized(True, [0, 1, 2])
    for order in ([2, 1, 0], [1, 0, 2]):
        again = finalized(True, order)
        for x, y in zip(base, again):
            assert np.array_equal(x, y)
    plain = finalized(False, [0, 1, 2])
    for x, y in zip(base, plain):
        assert np.array_equal(x, y)  # bit-exact, not allclose


def test_masked_aggregate_tracks_true_mean():
    addrs, planes, anchor, models, r = _federation(3)
    agg = MaskedFedAvg()
    agg.set_addr(addrs[0])
    handles = _encode_all(planes, models, anchor, addrs, r, True)
    out, outcome = planes[addrs[0]].finalize(agg.aggregate(handles), addrs, anchor)
    assert outcome == "ok"
    true_mean = [
        anchor[i]
        + np.mean(
            [np.asarray(models[a].params[i]) - anchor[i] for a in addrs], axis=0
        )
        for i in range(len(anchor))
    ]
    # rand-k support covers ~10% per round; ON the support the lattice is
    # within a quantization step of the true mean, OFF it the anchor holds.
    for i, (got, want) in enumerate(zip(out, true_mean)):
        idx = shared_support(r, i, got.size, Settings.PRIVACY_MASK_RATIO)
        _, qmax, scale = PrivacyPlane.lattice_params(len(addrs))
        got_f, want_f, anc_f = (
            got.reshape(-1), want.reshape(-1), anchor[i].reshape(-1)
        )
        assert np.abs(got_f[idx] - want_f[idx]).max() <= scale
        off = np.setdiff1d(np.arange(got_f.size), idx)
        assert np.array_equal(got_f[off], anc_f[off])


def test_error_feedback_carries_untransmitted_mass():
    addrs, planes, anchor, models, r = _federation(2)
    p = planes[addrs[0]]
    p.mask_own(models[addrs[0]], anchor, r, addrs)
    delta0 = np.asarray(models[addrs[0]].params[0]).reshape(-1) - anchor[
        0
    ].reshape(-1)
    resid = p._residual[0]
    idx = shared_support(r, 0, delta0.size, Settings.PRIVACY_MASK_RATIO)
    off = np.setdiff1d(np.arange(delta0.size), idx)
    # off-support: the full delta is retained for a later round
    assert np.allclose(resid[off], delta0[off])
    # on-support: only the (bounded) lattice error remains
    _, qmax, scale = PrivacyPlane.lattice_params(len(addrs))
    assert np.abs(resid[idx]).max() <= 0.5 * scale + 1e-7


# --- dropout recovery ---------------------------------------------------------


def test_dropout_repair_via_revealed_secrets():
    addrs, planes, anchor, models, r = _federation(3)
    agg = MaskedFedAvg()
    agg.set_addr(addrs[0])
    handles = _encode_all(planes, models, anchor, addrs, r, True)
    dead = addrs[2]
    merged = agg.aggregate(handles[:2])  # dead masker's frame never arrived
    # Unrepaired: the observer knows its OWN pair with the dead peer but not
    # the other survivor's — finalize must refuse, not emit ring noise.
    out, outcome = planes[addrs[0]].finalize(merged, addrs, anchor)
    assert out is None and outcome == "unrepaired"
    # The other survivor reveals; finalize succeeds and equals the maskless
    # 2-contributor sum under the SAME declared committee of 3.
    sec = planes[addrs[1]].repair_secrets_for(dead, r)
    assert sec is not None
    assert planes[addrs[0]].note_repair(r, addrs[1], dead, sec)
    # A hostile overwrite of the stored genuine reveal is refused (first
    # write wins) — finalize keeps subtracting the real share below.
    assert not planes[addrs[0]].note_repair(r, addrs[1], dead, "ab" * 32)
    out, outcome = planes[addrs[0]].finalize(merged, addrs, anchor)
    assert outcome == "ok"
    # Reference: the maskless 2-contributor lattice sum decoded with the
    # SAME float ops finalize uses (the maskless frames' lattices ARE the
    # raw q grids, so this is the ground truth the repair must recover).
    from p2pfl_tpu.privacy.masking import center_ring

    plain = _encode_all(planes, models, anchor, addrs, r, False)
    plain_merged = agg.aggregate(plain[:2])
    bits = Settings.PRIVACY_RING_BITS
    _, _, scale = PrivacyPlane.lattice_params(len(addrs))
    for i, (got, anc) in enumerate(zip(out, anchor)):
        idx = shared_support(r, i, anc.size, Settings.PRIVACY_MASK_RATIO)
        t = center_ring(np.asarray(plain_merged.get_parameters()[i]), bits)
        vbar = (t.astype(np.float64) * float(scale) / 2).astype(np.float32)
        ref = anc.reshape(-1).astype(np.float32, copy=True)
        ref[idx] = ref[idx] + vbar
        assert np.array_equal(got.reshape(-1), ref)


def test_dropout_repair_via_journaled_seeds():
    """A crash-RESTARTED masker re-derives identical masks from journaled
    key material (export/import round-trip) — its re-sent frame cancels
    exactly like the lost one."""
    addrs, planes, anchor, models, r = _federation(3)
    p = planes[addrs[0]]
    resurrected = PrivacyPlane(addrs[0])
    resurrected.import_state(p.export_state())
    bits = Settings.PRIVACY_RING_BITS
    before = p.masker.total_mask(addrs, r, 0, 17, bits)
    after = resurrected.masker.total_mask(addrs, r, 0, 17, bits)
    assert np.array_equal(before, after)
    assert resurrected.masker.pair_secret(addrs[1]) == p.masker.pair_secret(addrs[1])


def test_repair_reveal_once_and_hostile_repairs_dropped():
    addrs, planes, _, _, r = _federation(3)
    p = planes[addrs[0]]
    assert p.repair_secrets_for("ghost", r) is None  # unknown peer: nothing
    sec = p.repair_secrets_for(addrs[1], r)
    assert sec is not None
    assert p.repair_secrets_for(addrs[1], r) is None  # dedup per (round, dead)
    q = planes[addrs[1]]
    q.note_committee(r, addrs)
    assert not q.note_repair(r, addrs[0], addrs[0], "ab" * 32)  # survivor == dead
    assert not q.note_repair(r, addrs[0], addrs[2], "zz")  # not hex
    assert not q.note_repair(r, addrs[0], addrs[2], "ab" * 8)  # wrong length
    # committee validation: a claimed survivor or dead peer outside the
    # round's registered committee is rejected, as is any claim for a round
    # whose committee was never registered here.
    assert not q.note_repair(r, "outsider", addrs[2], "ab" * 32)
    assert not q.note_repair(r, addrs[0], "outsider", "ab" * 32)
    assert not q.note_repair(r + 1, addrs[0], addrs[2], "ab" * 32)
    # first write wins: the genuine claim sticks, an overwrite is refused
    assert q.note_repair(r, addrs[0], addrs[2], sec)
    assert not q.note_repair(r, addrs[0], addrs[2], "ab" * 32)
    assert q._repairs[(r, addrs[0], addrs[2])] == bytes.fromhex(sec)


# --- hostile masked frames ----------------------------------------------------


def _masked_meta(r=2, n=3, bits=None, ks=(10,)):
    return {
        "round": r,
        "bits": Settings.PRIVACY_RING_BITS if bits is None else bits,
        "n": n,
        "ks": list(ks),
    }


def test_hostile_masked_frames_rejected_and_counted():
    adm = AdmissionController("t0")
    committee = ["a", "b", "c"]
    dt = ring_dtype(Settings.PRIVACY_RING_BITS)
    good = [np.zeros(10, dt)]

    def rejected(reason, **kw):
        before = adm.rejected_count(reason)
        args = {
            "arrays": good,
            "info": _masked_meta(),
            "committee": committee,
            "contributors": ["a"],
            "expected_ks": [10],
            "source": "evil",
        }
        args.update(kw)
        got = adm.screen_masked(**args)
        assert got == reason
        assert adm.rejected_count(reason) == before + 1

    rejected("masked_structure", info=None)
    rejected("masked_structure", info={"round": "x"})
    rejected("masked_structure", info=_masked_meta(bits=8))  # wrong ring
    rejected("masked_structure", info=_masked_meta(n=2))  # committee mismatch
    rejected("masked_member", contributors=["outsider"])
    rejected("masked_member", contributors=[])
    rejected("masked_structure", arrays=[np.zeros(9, dt)])  # short plane
    rejected("masked_structure", arrays=[np.zeros(10, np.float32)])  # not ring
    rejected("masked_structure", arrays=[])  # tensor count
    # the clean frame passes
    assert (
        adm.screen_masked(
            good,
            _masked_meta(),
            committee=committee,
            contributors=["a"],
            expected_ks=[10],
            source="honest",
        )
        is None
    )


def test_range_check_rejects_wrapped_sum_before_model():
    """An unrepaired/hostile mask share is uniform ring noise — the
    committee-side range check must reject it before any value reaches
    model-shaped output."""
    addrs, planes, anchor, models, r = _federation(2)
    agg = MaskedFedAvg()
    agg.set_addr(addrs[0])
    handles = _encode_all(planes, models, anchor, addrs, r, True)
    # corrupt one lattice plane with a huge constant (survives merge)
    bad = handles[1]
    params = [np.asarray(a).copy() for a in bad.get_parameters()]
    params[0] = params[0] + ring_dtype(Settings.PRIVACY_RING_BITS).type(
        3 << (Settings.PRIVACY_RING_BITS - 3)
    )
    hostile = ModelHandle(
        params=params,
        contributors=bad.contributors,
        num_samples=bad.num_samples,
        additional_info=dict(bad.additional_info),
    )
    out, outcome = planes[addrs[0]].finalize(
        agg.aggregate([handles[0], hostile]), addrs, anchor
    )
    assert out is None and outcome == "range"


def test_finalize_refuses_mismatched_anchor_round():
    """A stale (or advanced) anchor at finalize would scatter the committee
    mean onto the wrong base — finalize must refuse it as a counted
    structure outcome, mirroring mask_own's encode-time anchor check."""
    addrs, planes, anchor, models, r = _federation(3)
    agg = MaskedFedAvg()
    agg.set_addr(addrs[0])
    merged = agg.aggregate(_encode_all(planes, models, anchor, addrs, r, True))
    out, outcome = planes[addrs[0]].finalize(
        merged, addrs, anchor, anchor_round=r + 1
    )
    assert out is None and outcome == "structure"
    out, outcome = planes[addrs[0]].finalize(
        merged, addrs, anchor, anchor_round=r
    )
    assert outcome == "ok" and out is not None


def test_masked_merge_drops_plaintext_and_foreign_lattices():
    addrs, planes, anchor, models, r = _federation(3)
    agg = MaskedFedAvg()
    agg.set_addr(addrs[0])
    handles = _encode_all(planes, models, anchor, addrs, r, True)
    merged = agg.aggregate([handles[0], models[addrs[1]], handles[2]])
    assert sorted(merged.contributors) == [addrs[0], addrs[2]]
    # a frame from another lattice generation (different round) is dropped
    other = _encode_all(planes, models, anchor, addrs, r + 1, True)
    merged2 = agg.aggregate([handles[0], other[1]])
    assert merged2.contributors == [addrs[0]]


# --- accountant (learning/privacy.py, now live) -------------------------------


def test_accountant_monotonicity():
    eps = [gaussian_rdp_epsilon(1.0, t, 1e-5) for t in (1, 10, 100, 1000)]
    assert all(b > a for a, b in zip(eps, eps[1:]))  # more steps, more spend
    sig = [gaussian_rdp_epsilon(s, 100, 1e-5) for s in (0.5, 1.0, 2.0, 4.0)]
    assert all(b < a for a, b in zip(sig, sig[1:]))  # more noise, less spend
    assert gaussian_rdp_epsilon(1.0, 100, 1e-5) < gaussian_rdp_epsilon(
        1.0, 100, 1e-7
    )  # tighter delta costs epsilon
    assert gaussian_rdp_epsilon(0.0, 10, 1e-5) == math.inf
    assert gaussian_rdp_epsilon(1.0, 0, 1e-5) == 0.0
    with pytest.raises(ValueError):
        gaussian_rdp_epsilon(1.0, 10, 1.5)


def test_privacy_spent_honest_about_voided_guarantee():
    ok = dp_sgd_privacy_spent(1.0, 1.0, 100)
    assert 0 < ok["epsilon"] < math.inf
    voided = dp_sgd_privacy_spent(1.0, 1.0, 100, nonprivate_steps=1)
    assert voided["epsilon"] == math.inf
    nothing = dp_sgd_privacy_spent(1.0, 1.0, 0)
    assert nothing["epsilon"] == 0.0


def test_resolve_seed_entropy_and_pinned_warning():
    a, b = resolve_seed(None), resolve_seed(None)
    assert a != b  # OS entropy (collision odds 2^-31)
    assert resolve_seed(42) == 42
    with pytest.warns(UserWarning):
        resolve_seed(42, dp_noise_multiplier=1.0)


def test_budget_ledger_rides_gauge_and_wire_sentinel():
    BUDGETS.record("nA", clip_norm=1.0, noise_multiplier=1.0, dp_steps=50)
    eps1 = BUDGETS.epsilon("nA")
    assert 0 < eps1 < math.inf
    BUDGETS.record("nA", clip_norm=1.0, noise_multiplier=1.0, dp_steps=50)
    assert BUDGETS.epsilon("nA") > eps1  # composition is monotone
    fam = REGISTRY.get("p2pfl_privacy_epsilon")
    vals = {lbl["node"]: c.value for lbl, c in fam.samples()}
    assert vals["nA"] == pytest.approx(BUDGETS.epsilon("nA"))
    # non-private steps void the claim -> wire sentinel -1
    BUDGETS.record("nA", clip_norm=0.0, noise_multiplier=0.0, nonprivate_steps=1)
    assert BUDGETS.epsilon("nA") == math.inf
    assert wire_epsilon(BUDGETS.epsilon("nA")) == -1.0
    assert wire_epsilon(0.0) == 0.0 and wire_epsilon(2.5) == 2.5


def test_digest_carries_epsilon():
    from p2pfl_tpu.telemetry import digest as dig

    BUDGETS.record("nB", clip_norm=1.0, noise_multiplier=2.0, dp_steps=10)
    d = dig.collect("nB")
    assert d.dp_epsilon == pytest.approx(wire_epsilon(BUDGETS.epsilon("nB")))
    rt = dig.decode(d.encode())
    assert rt.dp_epsilon == pytest.approx(d.dp_epsilon)
    # absent field (older peer / DP never reported) decodes to None — NOT
    # 0.0, which would read as an active zero-spend DP claim in fed_top
    legacy = dig.decode('{"node":"old","v":1}')
    assert legacy is not None and legacy.dp_epsilon is None
    # a node with no budget entry omits the field on the wire entirely
    silent = dig.collect("never-reported-dp")
    assert silent.dp_epsilon is None
    assert '"dp_epsilon"' not in silent.encode()
    assert dig.decode(silent.encode()).dp_epsilon is None


def test_fed_top_eps_column_distinguishes_absent_from_zero():
    """fed_top's EPS column: '-' means the peer never reported a budget;
    '0.00' is a genuine zero-spend DP claim; 'inf' is the -1 voided-claim
    sentinel. Conflating absent with 0.0 would render missing telemetry as
    an active privacy guarantee."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "fed_top", os.path.join(os.path.dirname(__file__), "..", "scripts", "fed_top.py")
    )
    ft = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ft)

    def peer(**kw):
        return {"round": 1, "total_rounds": 2, "stage": "s", "scores": {}, **kw}

    snap = {
        "observer": "obs",
        "peers": {
            "mem://silent": peer(),  # no dp_epsilon key at all
            "mem://null": peer(dp_epsilon=None),  # digest never reported
            "mem://zero": peer(dp_epsilon=0.0),  # DP on, nothing released
            "mem://void": peer(dp_epsilon=-1.0),  # guarantee voided
            "mem://live": peer(dp_epsilon=2.5),
        },
    }
    out = ft.render(snap, color=False)
    rows = {
        line.split()[0]: line for line in out.splitlines() if "mem://" in line
    }
    assert " - " in rows["mem://silent"] and " - " in rows["mem://null"]
    assert "0.00" in rows["mem://zero"]
    assert "inf" in rows["mem://void"]
    assert "2.50" in rows["mem://live"]


# --- wire overhead ------------------------------------------------------------


def test_masked_wire_overhead_within_bound():
    """A masked frame must cost <= 1.15x the PR 12 topk+quant frame for the
    same model at the same ratio (acceptance: <=15% overhead). The shared
    support ships no index bytes, which is what pays for the wider values."""
    from p2pfl_tpu.comm.delta import DeltaWireCodec

    rng = np.random.default_rng(1)
    anchor = [
        rng.normal(size=(128, 64)).astype(np.float32),
        rng.normal(size=(64, 10)).astype(np.float32),
        rng.normal(size=(10,)).astype(np.float32),
    ]
    model = ModelHandle(
        params=[
            x + rng.normal(scale=1e-3, size=x.shape).astype(np.float32)
            for x in anchor
        ],
        contributors=["n0"],
        num_samples=8,
    )
    addrs, planes, _, _, _ = _federation(3)
    with Settings.overridden(
        WIRE_COMPRESSION="topk",
        WIRE_TOPK_RATIO=Settings.PRIVACY_MASK_RATIO,
        WIRE_TOPK_VALUES="int8",
        COALESCE_ENABLED=True,
    ):
        codec = DeltaWireCodec("n0")
        codec.set_anchor(anchor, 2)
        tagged = codec.encode_tagged(model, 2)
        assert tagged is not None
        topk_bytes = len(tagged[0])
        masked = planes[addrs[0]].mask_own(model, anchor, 2, addrs)
        masked_bytes = len(PrivacyPlane.encode_frame(masked))
    assert masked_bytes <= 1.15 * topk_bytes, (masked_bytes, topk_bytes)


# --- chaos scenario -----------------------------------------------------------


def test_plan_masker_dropout_deterministic():
    from p2pfl_tpu.chaos import CHAOS

    nodes = [f"mem://n{i}" for i in range(5)]
    a = CHAOS.plan_masker_dropout(4, nodes, seed=9, drop_round=1)
    b = CHAOS.plan_masker_dropout(4, nodes, seed=9, drop_round=1)
    assert a == b and len(a) == 1
    assert a[0].kind == "crash" and a[0].node in nodes and a[0].when == 1
    c = CHAOS.plan_masker_dropout(4, nodes, seed=10, drop_round=1)
    assert c[0].node in nodes  # other seeds still pick from the committee
    assert CHAOS.plan_masker_dropout(4, [], seed=9) == ()
    assert CHAOS.plan_masker_dropout(2, nodes, seed=9, drop_round=5) == ()


# --- ledger / parity exemption ------------------------------------------------


def test_privacy_masked_kind_ranked_and_not_in_trajectory():
    from p2pfl_tpu.telemetry.ledger import KIND_RANK, TRAJECTORY_KINDS

    assert "privacy_masked" in KIND_RANK
    # masked rounds are a wire-only fact: the fused mesh has no masks, so
    # the kind must stay OUT of the cross-backend trajectory comparison
    # (the codec-scoped parity exemption, docs/components/parity.md).
    assert "privacy_masked" not in TRAJECTORY_KINDS


def test_parity_negative_control_masked_vs_plain_hashes_differ():
    """Negative control for the masked-aggregate parity exemption: the
    masked pipeline's aggregate is NOT bit-identical to plaintext FedAvg
    (unit weights + lattice), so comparing their ledgers MUST diverge —
    which is exactly why masked runs are exempt from the parity gate."""
    from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    addrs, planes, anchor, models, r = _federation(3)
    agg = MaskedFedAvg()
    agg.set_addr(addrs[0])
    handles = _encode_all(planes, models, anchor, addrs, r, True)
    out, outcome = planes[addrs[0]].finalize(agg.aggregate(handles), addrs, anchor)
    assert outcome == "ok"
    plain = FedAvg()
    plain.set_addr(addrs[0])
    ref = plain.aggregate([models[a] for a in addrs])
    assert canonical_params_hash(out) != canonical_params_hash(
        [np.asarray(p) for p in ref.get_parameters()]
    )
