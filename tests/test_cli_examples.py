"""CLI + examples tests (reference has no CLI tests; example coverage via
the e2e node tests — this adds direct coverage for the registry and both
execution modes of the mnist example)."""

from __future__ import annotations

import pytest

from p2pfl_tpu.cli import build_parser
from p2pfl_tpu.examples import EXAMPLES
from p2pfl_tpu.examples.mnist import build_parser as mnist_parser, run_mesh, run_nodes


def test_examples_registry():
    assert {"mnist", "node1", "node2"} <= set(EXAMPLES)


def test_cli_parser_subcommands():
    p = build_parser()
    args = p.parse_args(["experiment", "run", "mnist", "--nodes", "2"])
    assert args.command == "experiment" and args.name == "mnist"
    assert args.extra == ["--nodes", "2"]
    args = p.parse_args(["experiment", "list"])
    assert args.action == "list"
    for stub in ("login", "remote", "launch"):
        assert build_parser().parse_args([stub]).command == stub


def test_cli_experiment_list(capsys):
    from p2pfl_tpu.cli import main

    assert main(["experiment", "list"]) == 0
    out = capsys.readouterr().out
    assert "mnist" in out and "node1" in out


def test_cli_unknown_experiment(capsys):
    from p2pfl_tpu.cli import main

    assert main(["experiment", "help", "nope"]) == 2


@pytest.mark.parametrize("name", ["mnist", "node1", "node2"])
def test_cli_help_subprocess_dispatch(name):
    """`experiment help <name>` must exit cleanly for EVERY registered
    example (the examples parse args before touching any jax backend)."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-m", "p2pfl_tpu", "experiment", "help", name],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "usage:" in out.stdout


def test_mnist_example_mesh_mode():
    args = mnist_parser().parse_args(
        ["--nodes", "4", "--rounds", "1", "--samples-per-node", "32", "--batch-size", "16"]
    )
    res = run_mesh(args)
    assert res["mode"] == "mesh"
    assert res["sec_per_round"] > 0


@pytest.mark.parametrize(
    "aggregator",
    [
        "fedavg",  # one aggregator stays in the fast subset as the smoke path
        pytest.param("fedmedian", marks=pytest.mark.slow),
        pytest.param("scaffold", marks=pytest.mark.slow),
        pytest.param("krum", marks=pytest.mark.slow),
        pytest.param("trimmed_mean", marks=pytest.mark.slow),
    ],
)
def test_mnist_example_nodes_mode(aggregator):
    args = mnist_parser().parse_args(
        [
            "--mode", "nodes",
            "--nodes", "2",
            "--rounds", "1",
            "--samples-per-node", "48",
            "--batch-size", "16",
            "--topology", "full",
            "--aggregator", aggregator,
        ]
    )
    res = run_nodes(args)
    assert res["mode"] == "nodes"
    assert res["final_test_acc"] is not None
