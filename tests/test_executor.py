"""Nodes-mode learner executor (replaces the reference's Ray actor-pool
tests, test/simulation/actor_pool_test.py:183-232 and
virtual_node_learner_test.py:32-126): capacity bounds, queueing, crash
isolation, wrapper delegation, and a 20-node in-memory federation."""

import threading
import time

import numpy as np
import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
from p2pfl_tpu.learning.learner import JaxLearner, Learner
from p2pfl_tpu.models import mlp_model
from p2pfl_tpu.parallel.executor import LearnerExecutor, VirtualNodeLearner

# 20-node federation + pool crash scenarios -> excluded from the fast subset
pytestmark = pytest.mark.slow



class SlowLearner(Learner):
    """Test double: fit sleeps; records concurrency."""

    active = 0
    peak = 0
    _class_lock = threading.Lock()

    def __init__(self, delay=0.3, fail=False):
        super().__init__()
        self.delay = delay
        self.fail = fail
        self.fits = 0

    def fit(self):
        with SlowLearner._class_lock:
            SlowLearner.active += 1
            SlowLearner.peak = max(SlowLearner.peak, SlowLearner.active)
        try:
            if self.fail:
                raise RuntimeError("boom")
            time.sleep(self.delay)
            self.fits += 1
            return None
        finally:
            with SlowLearner._class_lock:
                SlowLearner.active -= 1

    def interrupt_fit(self):
        pass

    def evaluate(self):
        return {"test_acc": 1.0}

    def get_framework(self):
        return "test"


def test_capacity_bound_and_queueing():
    SlowLearner.active = SlowLearner.peak = 0
    ex = LearnerExecutor(max_workers=2)
    try:
        learners = [SlowLearner(delay=0.2) for _ in range(6)]
        for i, ln in enumerate(learners):
            ex.submit("fit", f"n{i}", ln)
        for i in range(6):
            ex.get_result(f"n{i}", timeout=10)
        assert SlowLearner.peak <= 2  # capacity bound held
        assert all(ln.fits == 1 for ln in learners)
        assert ex.stats()["jobs_done"] == 6
    finally:
        ex.shutdown()


def test_crash_isolation():
    """A raising learner fails only its own future; the pool keeps serving."""
    ex = LearnerExecutor(max_workers=2)
    try:
        ex.submit("fit", "bad", SlowLearner(fail=True))
        with pytest.raises(RuntimeError, match="boom"):
            ex.get_result("bad", timeout=10)
        ok = SlowLearner(delay=0.05)
        ex.submit("fit", "good", ok)
        ex.get_result("good", timeout=10)
        assert ok.fits == 1
        stats = ex.stats()
        assert stats["jobs_failed"] == 1 and stats["jobs_done"] == 2
    finally:
        ex.shutdown()


def test_virtual_learner_delegates_and_executes():
    data = synthetic_mnist(n_train=256, n_test=64)
    ex = LearnerExecutor(max_workers=2)
    try:
        inner = JaxLearner(mlp_model(seed=0), data, "v0", batch_size=32)
        virt = VirtualNodeLearner(inner, ex, addr="v0")
        virt.set_epochs(1)
        assert virt.epochs == 1 and inner.epochs == 1
        virt.fit()
        assert virt.get_model() is inner.get_model()
        assert virt.get_model().get_contributors() == ["v0"]
        metrics = virt.evaluate()
        assert "test_acc" in metrics
        assert virt.get_framework() == "jax"
        virt.interrupt_fit()  # must not raise (upgrade over reference)
    finally:
        ex.shutdown()


def test_device_placement_round_robin():
    """Jobs are pinned round-robin onto JAX devices (TPU-native analogue of
    per-actor device fractions)."""
    import jax

    devices = jax.devices()[:4]
    ex = LearnerExecutor(max_workers=4, devices=devices)
    try:
        data = synthetic_mnist(n_train=128, n_test=32)
        learners = [JaxLearner(mlp_model(seed=i), data, f"d{i}", batch_size=32) for i in range(4)]
        for i, ln in enumerate(learners):
            ex.submit("fit", f"d{i}", ln)
        for i in range(4):
            ex.get_result(f"d{i}", timeout=60)
        for ln in learners:
            assert ln.get_model().get_contributors()
    finally:
        ex.shutdown()


def test_20_node_federation_bounded_and_crash_tolerant():
    """20 nodes share one capacity-8 executor; per-round wall-clock stays
    bounded and the federation survives a learner raising mid-fit
    (VERDICT round-2 ask #2 done-condition)."""
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils.utils import wait_convergence, wait_to_finish

    n_nodes = 20
    Settings.RESOURCE_MONITOR_PERIOD = 0
    data = synthetic_mnist(n_train=64 * n_nodes, n_test=64)
    parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
    ex = LearnerExecutor(max_workers=8)

    crashed = {"done": False}
    crash_lock = threading.Lock()

    class CrashingLearner(JaxLearner):
        """First fit in the whole federation raises; everyone else trains."""

        def fit(self):
            with crash_lock:
                first = not crashed["done"]
                crashed["done"] = True
            if first:
                raise RuntimeError("injected mid-fit crash")
            return super().fit()

    nodes = []
    try:
        with Settings.overridden(TRAIN_SET_SIZE=6):
            for i in range(n_nodes):
                nodes.append(
                    Node(
                        mlp_model(seed=i),
                        parts[i],
                        learner=CrashingLearner,
                        executor=ex,
                        batch_size=32,
                    )
                )
            for n in nodes:
                n.start()
            for i in range(1, n_nodes):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n_nodes - 1, wait=15)
            t0 = time.monotonic()
            nodes[0].set_start_learning(rounds=1, epochs=1)
            wait_to_finish(nodes, timeout=180)
            elapsed = time.monotonic() - t0
            assert crashed["done"]
            # capacity-8 pool, committee of 6: one fit wave + the 30s
            # aggregation-timeout worst case for peers of the crashed node
            assert elapsed < 120, f"round took {elapsed}s"
            stats = ex.stats()
            assert stats["peak_active"] <= 8
            assert stats["jobs_done"] >= 6
            assert stats["jobs_failed"] == 1  # pool survived the crash
    finally:
        for n in nodes:
            n.stop()
        ex.shutdown()
