"""Device observatory (PR 17): in-scan telemetry, tripwires, profiling.

Covers: the device-side bucket sketch (device_bucket_stats folds into the
host QuantileSketch with the sketch's own error bound); the aux stream's
contracts — chunking invariance (rounds_per_call must not change what the
host sees) and params-path neutrality (devobs on/off node-0 hash is
bit-identical); the ``p2pfl_mesh_*`` Prometheus family; the NaN tripwire
in both park and abort actions on the sync engine and park on the async
engine; and ``perf_diff``'s devobs refusal (exit 3 when exactly one side
carries a ``perf.devobs`` section).
"""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np
import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry import REGISTRY
from p2pfl_tpu.telemetry.export import render_prometheus
from p2pfl_tpu.telemetry.ledger import canonical_params_hash
from p2pfl_tpu.telemetry.sketches import (
    SKETCHES,
    QuantileSketch,
    device_bucket_spec,
    device_bucket_stats,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENG_KW = dict(
    cohort_fraction=0.5, seed=7, samples_per_node=8, feature_dim=8,
    num_classes=4, hidden=(8,), batch_size=4, lr=0.05,
)


@pytest.fixture(autouse=True)
def _clean_sketches():
    SKETCHES.reset()
    yield
    SKETCHES.reset()


# --- device bucket sketch -----------------------------------------------------


def test_device_bucket_stats_fold_matches_host_sketch():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    vals = rng.lognormal(mean=-2.0, sigma=2.0, size=256).astype(np.float32)
    vals[:13] = 0.0  # exact zeros land in the zeros counter, not a bucket
    gamma_log, lo_idx, nbins = device_bucket_spec()
    st = device_bucket_stats(
        jnp.asarray(vals), gamma_log=gamma_log, lo_idx=lo_idx, nbins=nbins
    )
    assert int(np.asarray(st["zeros"])) == 13
    assert int(np.asarray(st["counts"]).sum()) + 13 == 256

    folded = QuantileSketch()
    folded.fold_device_buckets(
        gamma_log,
        lo_idx,
        np.asarray(st["counts"]),
        zeros=float(np.asarray(st["zeros"])),
        vsum=float(np.asarray(st["sum"])),
        vmin=float(np.asarray(st["min"])),
        vmax=float(np.asarray(st["max"])),
    )
    direct = QuantileSketch()
    direct.add_many(vals.tolist())
    assert folded.count == direct.count == 256
    for q in (0.5, 0.9, 0.99):
        assert folded.quantile(q) == pytest.approx(
            direct.quantile(q), rel=3 * 0.02 + 1e-6
        )


# --- aux-stream contracts on the sync engine ----------------------------------


def _run_sync(rounds=4, rpc=2, **settings):
    from p2pfl_tpu.population import PopulationEngine

    with Settings.overridden(**settings):
        with PopulationEngine(8, **ENG_KW) as eng:
            res = eng.run(rounds, rounds_per_call=rpc)
            return res, canonical_params_hash(eng.gather_params(0))


def test_devobs_on_off_params_hash_identical():
    _, h_on = _run_sync(DEVOBS_ENABLED=True)
    on_counts = _sketch_counts("mesh-sim")
    SKETCHES.reset()
    _, h_off = _run_sync(DEVOBS_ENABLED=False)
    assert h_on == h_off
    assert on_counts[0] > 0 and on_counts[1] > 0
    assert _sketch_counts("mesh-sim") == (0, 0)  # off arm folds nothing


def _sketch_counts(node):
    un = SKETCHES.get("update_norm", node)
    tl = SKETCHES.get("train_loss", node)
    return (
        0 if un is None else un.count,
        0 if tl is None else tl.count,
    )


def test_aux_stream_is_chunking_invariant():
    _run_sync(rounds=4, rpc=2, DEVOBS_ENABLED=True)
    by_two = _sketch_counts("mesh-sim")
    SKETCHES.reset()
    _run_sync(rounds=4, rpc=4, DEVOBS_ENABLED=True)
    assert _sketch_counts("mesh-sim") == by_two
    assert by_two[0] == 4 * 4  # rounds x cohort_k (8 nodes at 50%)


def test_mesh_prometheus_family_exported():
    _run_sync(DEVOBS_ENABLED=True)
    prom = render_prometheus(REGISTRY)
    for metric in (
        "p2pfl_mesh_round",
        "p2pfl_mesh_train_loss",
        "p2pfl_mesh_weight_mass",
        "p2pfl_mesh_participants_total",
        "p2pfl_mesh_chunk_seconds",
    ):
        assert metric in prom, metric


# --- tripwires ----------------------------------------------------------------


def test_nan_tripwire_park_stops_at_chunk_boundary(tmp_path):
    res, _ = _run_sync(
        rounds=6,
        rpc=2,
        DEVOBS_ENABLED=True,
        DEVOBS_NAN_INJECT_ROUND=2,
        DEVOBS_TRIP_ACTION="park",
    )
    trip = res.tripped
    assert trip is not None
    assert trip["kind"] == "nonfinite" and trip["round"] == 2
    assert res.rounds == 4  # injected mid-chunk-1, parked at its boundary
    assert trip.get("flightrec") and os.path.exists(trip["flightrec"])
    trips = REGISTRY.get("p2pfl_mesh_trips_total")
    assert any(
        lbl.get("kind") == "nonfinite" and c.value > 0
        for lbl, c in trips.samples()
    )


def test_nan_tripwire_abort_raises_with_state_parked():
    from p2pfl_tpu.population import PopulationEngine

    with Settings.overridden(
        DEVOBS_ENABLED=True,
        DEVOBS_NAN_INJECT_ROUND=1,
        DEVOBS_TRIP_ACTION="abort",
    ):
        with PopulationEngine(8, **ENG_KW) as eng:
            with pytest.raises(RuntimeError, match="devobs tripwire"):
                eng.run(6, rounds_per_call=2)
            # Abort parks the state before raising: readable, not poisoned.
            assert eng.sim.params_stack is not None
            canonical_params_hash(eng.gather_params(0))


def test_async_engine_aux_stream_and_park_trip():
    from p2pfl_tpu.population import AsyncPopulationEngine

    with Settings.overridden(DEVOBS_ENABLED=True):
        with AsyncPopulationEngine(8, **ENG_KW) as eng:
            eng.run(4, eval_every=4, windows_per_call=2)
            h_on = canonical_params_hash(eng.global_params())
    on_counts = _sketch_counts("asyncpop-engine")
    assert on_counts[0] > 0 and on_counts[1] > 0
    SKETCHES.reset()
    with Settings.overridden(DEVOBS_ENABLED=False):
        with AsyncPopulationEngine(8, **ENG_KW) as eng:
            eng.run(4, eval_every=4, windows_per_call=2)
            h_off = canonical_params_hash(eng.global_params())
    assert h_on == h_off

    with Settings.overridden(
        DEVOBS_ENABLED=True,
        DEVOBS_NAN_INJECT_ROUND=2,
        DEVOBS_TRIP_ACTION="park",
    ):
        with AsyncPopulationEngine(8, **ENG_KW) as eng:
            res = eng.run(6, eval_every=6, windows_per_call=2)
    assert res.tripped is not None and res.tripped["kind"] == "nonfinite"
    assert res.tripped["round"] == 2 and res.windows == 4


# --- perf_diff devobs gating --------------------------------------------------


def _perf_diff():
    spec = importlib.util.spec_from_file_location(
        "perf_diff", os.path.join(REPO, "scripts", "perf_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_doc(wall=2.0, devobs=None):
    doc = {
        "metric": "unit_test_arm",
        "value": wall,
        "unit": "s/round",
        "meta": {"schema_version": 1, "git_sha": "x", "backend": "cpu", "seed": 0},
        "perf": {
            "schema_version": 1,
            "compile": {"recompiles_total": {"n0": 0}},
            "steady_state": {"step_s": {"n0": 0.01}},
        },
        "extra": {"mean_round_wall_s": wall},
    }
    if devobs is not None:
        doc["perf"]["devobs"] = devobs
    return doc


def test_perf_diff_refuses_one_sided_devobs(tmp_path):
    pd = _perf_diff()
    dev = {"device_peak_bytes": 1 << 20, "compile_seconds": 1.0,
           "scan_flops": 1e6, "scan_bytes": 1e6}
    with_dev = tmp_path / "with.json"
    with_dev.write_text(json.dumps(_bench_doc(devobs=dev)))
    without = tmp_path / "without.json"
    without.write_text(json.dumps(_bench_doc()))
    # Exactly one side profiled -> refusal, either direction.
    assert pd.main([str(with_dev), str(without)]) == 3
    assert pd.main([str(without), str(with_dev)]) == 3
    # Both sides bare or both profiled -> normal comparison.
    assert pd.main([str(without), str(without)]) == 0
    assert pd.main([str(with_dev), str(with_dev)]) == 0
    # Devobs keys gate: a blown-up device watermark regresses (exit 1).
    worse = tmp_path / "worse.json"
    worse.write_text(
        json.dumps(_bench_doc(devobs={**dev, "device_peak_bytes": 1 << 24}))
    )
    assert pd.main([str(with_dev), str(worse)]) == 1
