"""Pipeline parallelism (GPipe over a `stage` mesh axis): exact forward
equivalence vs sequential stage application, gradient equivalence, and a
pipelined training loop that learns. No reference analogue — part of the
full dp/tp/sp/ep/pp parallelism matrix."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from p2pfl_tpu.parallel.mesh import make_mesh
from p2pfl_tpu.parallel.pipeline import (

    make_pipeline_train_step,
    pipeline_apply,
    sequential_apply,
    stack_stage_params,
)

# GPipe programs compile ~10-70s each on the 1-core CPU mesh -> excluded from the fast subset
pytestmark = pytest.mark.slow

D = 16


def _block_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stage_params(seed, n_stages):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.normal(scale=0.5, size=(D, D)), jnp.float32),
            "b": jnp.asarray(rng.normal(scale=0.1, size=(D,)), jnp.float32),
        }
        for _ in range(n_stages)
    ]


@pytest.fixture(scope="module")
def stage_mesh():
    return make_mesh((4,), ("stage",), devices=jax.devices()[:4])


def test_pipeline_matches_sequential_forward(stage_mesh):
    n_stages, batch, micro = 4, 16, 4
    params = _stage_params(0, n_stages)
    stacked = stack_stage_params(params, stage_mesh)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(batch, D)), jnp.float32)

    piped = pipeline_apply(stacked, x, _block_fn, stage_mesh, micro)
    seq = sequential_apply(stacked, x, _block_fn, n_stages)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq), atol=1e-6)


def test_pipeline_stage_params_actually_sharded(stage_mesh):
    stacked = stack_stage_params(_stage_params(0, 4), stage_mesh)
    w = stacked["w"]
    assert "stage" in w.sharding.spec
    assert w.addressable_shards[0].data.shape[0] == 1  # one stage per device


def test_pipeline_gradients_match_sequential(stage_mesh):
    n_stages, batch, micro = 4, 16, 4
    stacked = stack_stage_params(_stage_params(2, n_stages), stage_mesh)
    x = jnp.asarray(np.random.default_rng(3).normal(size=(batch, D)), jnp.float32)
    y = jnp.asarray(np.random.default_rng(4).normal(size=(batch, D)), jnp.float32)

    def loss_piped(p):
        return jnp.mean((pipeline_apply(p, x, _block_fn, stage_mesh, micro) - y) ** 2)

    def loss_seq(p):
        return jnp.mean((sequential_apply(p, x, _block_fn, n_stages) - y) ** 2)

    g_piped = jax.grad(loss_piped)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree.leaves(g_piped), jax.tree.leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_pipeline_train_step_learns(stage_mesh):
    n_stages, batch, micro = 4, 32, 4
    stacked = stack_stage_params(_stage_params(5, n_stages), stage_mesh)
    opt = optax.adam(1e-2)
    opt_state = opt.init(stacked)
    x = jnp.asarray(np.random.default_rng(6).normal(size=(batch, D)), jnp.float32)
    y = jnp.tanh(x @ jnp.ones((D, D), jnp.float32) * 0.1)

    step = make_pipeline_train_step(
        _block_fn, lambda out, tgt: jnp.mean((out - tgt) ** 2), opt, stage_mesh, micro
    )
    params, losses = stacked, []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses[:3] + losses[-3:]
    # stage sharding preserved through updates
    assert "stage" in params["w"].sharding.spec


def test_pipelined_transformer_lm_matches_plain(stage_mesh):
    """Staged TransformerLM through the GPipe schedule produces the same
    logits as the plain model (embed/ln_f/lm_head replicated; blocks
    stage-stacked). bf16 compute -> bf16-rounding tolerance."""
    from p2pfl_tpu.models import transformer_lm_model
    from p2pfl_tpu.parallel.pipeline import make_pipelined_transformer_lm

    model = transformer_lm_model(
        seed=0, seq_len=32, vocab_size=64, num_layers=4, num_heads=2, embed_dim=32
    )
    params, apply_fn = make_pipelined_transformer_lm(
        model, stage_mesh, n_microbatches=2
    )
    assert "stage" in params["stages"]["b0"]["attn"]["qkv"]["kernel"].sharding.spec

    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(8, 32)), jnp.int32
    )
    piped = apply_fn(params, toks)
    plain = model.apply_fn(model.params, toks)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(plain), atol=0.15)

    # gradient equivalence with the plain model (a mis-scaled replicated-
    # param gradient, e.g. an extra psum over the stage axis, must fail)
    def loss_piped(p):
        return jnp.mean(apply_fn(p, toks) ** 2)

    def loss_plain(p):
        return jnp.mean(model.apply_fn(p, toks) ** 2)

    g_piped = jax.grad(loss_piped)(params)
    g_plain = jax.grad(loss_plain)(model.params)["params"]

    def close(a, b):
        a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
        np.testing.assert_allclose(a, b, atol=2e-3 + 0.05 * np.abs(b).max())

    for name in ("embed", "ln_f", "lm_head"):
        for a, b in zip(jax.tree.leaves(g_piped[name]), jax.tree.leaves(g_plain[name])):
            close(a, b)
    for s in range(4):  # stage-stacked block grads vs per-block plain grads
        for a, b in zip(
            jax.tree.leaves(jax.tree.map(lambda x, s=s: x[s], g_piped["stages"]["b0"])),
            jax.tree.leaves(g_plain[f"block{s}"]),
        ):
            close(a, b)


def test_pipelined_transformer_rejects_ring(stage_mesh):
    from p2pfl_tpu.models import transformer_lm_model
    from p2pfl_tpu.parallel.pipeline import make_pipelined_transformer_lm

    model = transformer_lm_model(
        seed=0, seq_len=32, vocab_size=64, num_layers=4, num_heads=2,
        embed_dim=32, attention_kind="ring", axis_name="seq",
    )
    with pytest.raises(ValueError, match="ring"):
        make_pipelined_transformer_lm(model, stage_mesh, n_microbatches=2)
