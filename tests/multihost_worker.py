"""Worker for the 2-process multi-host mesh test (run via subprocess by
test_multihost.py — not collected by pytest).

Each process contributes 4 virtual CPU devices; after
``initialize_multihost`` the global mesh spans both processes (8 devices on
the ``nodes`` axis) and one FedAvg round of the MeshSimulation runs as a
process-spanning SPMD program — the CI-runnable stand-in for a DCN-spanning
TPU pod slice (BASELINE.json north-star).

Usage: python multihost_worker.py <coordinator_port> <process_id>
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

port, pid = int(sys.argv[1]), int(sys.argv[2])

from p2pfl_tpu.parallel.mesh import initialize_multihost, make_mesh  # noqa: E402

initialize_multihost(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8, len(jax.devices())
assert len(jax.local_devices()) == 4

from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist  # noqa: E402
from p2pfl_tpu.models import mlp_model  # noqa: E402
from p2pfl_tpu.parallel.simulation import MeshSimulation  # noqa: E402

mesh = make_mesh()  # all 8 global devices on the "nodes" axis
assert set(d.process_index for d in mesh.devices.flat) == {0, 1}

# Same seeds in both processes -> identical host data, as SPMD requires.
data = synthetic_mnist(n_train=512, n_test=128)
parts = data.generate_partitions(8, RandomIIDPartitionStrategy)
sim = MeshSimulation(
    mlp_model(seed=0), parts, train_set_size=4, batch_size=32, seed=1, mesh=mesh
)
res = sim.run(rounds=1, epochs=1, warmup=False)
acc = res.test_acc[-1]
assert 0.0 <= acc <= 1.0
print(f"MULTIHOST_OK pid={pid} acc={acc:.4f}", flush=True)
