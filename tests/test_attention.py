"""Attention ops: blockwise/flash/ring vs the dense reference.

Strategy mirrors the repo's test approach (SURVEY.md §4): exact-math kernels
are unit-tested against a materialized reference on the virtual 8-device CPU
mesh from conftest.py; ring attention runs under a real shard_map so the
ppermute path is exercised (sharding semantics identical to TPU ICI).
"""

import jax
import jax.numpy as jnp

from p2pfl_tpu.utils.compat import shard_map
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from p2pfl_tpu.ops.attention import (
    blockwise_attention,
    dense_attention,
    flash_attention,
    flash_chunk_update,
)
from p2pfl_tpu.ops.ring_attention import ring_attention

B, S, H, D = 2, 64, 2, 16


def _qkv(seed=0, s=S, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (B, s, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_blockwise_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_ragged_tail_block():
    q, k, v = _qkv(s=48)  # 48 % 32 != 0 exercises the tail-block path
    ref = dense_attention(q, k, v, causal=True)
    out = blockwise_attention(q, k, v, causal=True, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal, 16, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_non_divisible_block_sizes():
    q, k, v = _qkv(s=48)  # 48 isn't a multiple of the requested 32
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, True, 32, 32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_grads_match_dense():
    q, k, v = _qkv()

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_block(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, causal=True, block_k=16) ** 2)

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_grads_match_dense():
    q, k, v = _qkv()

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 16, 16) ** 2)

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


# --- ring attention over a real mesh -----------------------------------------


def _ring_fn(mesh, causal, n_shards):
    spec = P(None, "seq", None, None)
    return shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal, block_k=8),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_shards", [4, 8])
def test_ring_matches_dense(causal, n_shards):
    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("seq",))
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = jax.jit(_ring_fn(mesh, causal, n_shards))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_grads_match_dense():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = _qkv()
    ring = _ring_fn(mesh, True, 4)

    g_ref = jax.grad(lambda *a: jnp.sum(dense_attention(*a, causal=True) ** 2), (0, 1, 2))(
        q, k, v
    )
    g_out = jax.jit(
        jax.grad(lambda *a: jnp.sum(ring(*a) ** 2), (0, 1, 2))
    )(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ring_bfloat16_runs():
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = jax.jit(_ring_fn(mesh, True, 4))(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=5e-2
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_pallas_bwd_matches_remat_bwd(causal):
    """The FlashAttention-2 Pallas backward and the independently-derived
    remat-through-blockwise backward must agree (and both match dense —
    covered above for the default). Ragged 48-long sequences exercise the
    non-power-of-two block picker in all three backward kernels."""
    q, k, v = _qkv(seed=3, s=48)

    def loss(kind):
        return lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal, 16, 16, None, kind) ** 2
        )

    g_pallas = jax.grad(loss("pallas"), argnums=(0, 1, 2))(q, k, v)
    g_remat = jax.grad(loss("remat"), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_pallas, g_remat):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_pallas_bwd_bf16_runs():
    """bf16 inputs (the bench dtype): pallas backward produces finite bf16
    grads of the right shape."""
    q, k, v = _qkv(seed=4, dtype=jnp.bfloat16)
    g = jax.grad(
        lambda q, k, v: flash_attention(q, k, v, True, 16, 16)
        .astype(jnp.float32)
        .sum(),
        argnums=(0, 1, 2),
    )(q, k, v)
    for t, ref in zip(g, (q, k, v)):
        assert t.shape == ref.shape and t.dtype == ref.dtype
        assert np.isfinite(np.asarray(t, dtype=np.float32)).all()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_shards", [4, 8])
def test_ring_flash_impl_matches_dense(causal, n_shards):
    """impl='flash' (Pallas flash-carry fold per rotation) is exact: matches
    dense attention across shard counts, causal and not. check_vma=False:
    the Pallas interpreter (CPU test path) cannot trace varying-axis values
    through a kernel call; sequence_parallel_attention does the same."""
    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("seq",))
    q, k, v = _qkv(seed=5)
    spec = P(None, "seq", None, None)
    ring = shard_map(
        lambda q, k, v: ring_attention(
            q, k, v, "seq", causal=causal, block_k=8, impl="flash"
        ),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    out = jax.jit(ring)(q, k, v)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-3)


def test_ring_flash_grads_match_dense():
    """impl='flash' backward (remat through the blockwise ring) matches
    dense gradients."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    q, k, v = _qkv(seed=6)
    spec = P(None, "seq", None, None)
    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "seq", causal=True, block_k=8, impl="flash"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    g_ref = jax.grad(lambda *a: jnp.sum(dense_attention(*a, causal=True) ** 2), (0, 1, 2))(
        q, k, v
    )
    g_out = jax.grad(lambda *a: jnp.sum(jax.jit(ring)(*a) ** 2), (0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3)


def test_flash_chunk_update_matches_flash_forward():
    """One whole-sequence fold through the carry kernel, finalized, equals
    the plain flash forward — pins the two kernels to each other (the ring
    is built from the carry kernel; the bench measures the forward one)."""
    q, k, v = _qkv(seed=7)
    qt, kt, vt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v))
    b, h, s, d = qt.shape
    m0 = jnp.full((b, h, s, 128), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, s, 128), jnp.float32)
    acc0 = jnp.zeros((b, h, s, d), jnp.float32)
    _, l, acc = flash_chunk_update(
        (m0, l0, acc0), qt, kt, vt, 0, 0, causal=True, block_q=16, block_k=16
    )
    out = jnp.moveaxis(
        (acc / jnp.maximum(l[..., :1], 1e-30)).astype(q.dtype), 1, 2
    )
    ref = flash_attention(q, k, v, True, 16, 16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
