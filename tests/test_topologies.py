"""Topology construction (parity with reference utils/topologies.py:30-93
plus the GRID / ERDOS_RENYI extensions): adjacency-matrix invariants per
type, and real in-memory nodes wired per the matrix."""

import numpy as np
import pytest

from p2pfl_tpu.utils.topologies import TopologyFactory, TopologyType


def _connected(adj: np.ndarray) -> bool:
    n = adj.shape[0]
    seen = {0}
    frontier = [0]
    while frontier:
        i = frontier.pop()
        for j in TopologyFactory.neighbors_of(adj, i):
            if j not in seen:
                seen.add(j)
                frontier.append(j)
    return len(seen) == n


@pytest.mark.parametrize("topology", list(TopologyType))
@pytest.mark.parametrize("n", [1, 2, 5, 9])
def test_matrix_invariants(topology, n):
    """Every topology yields a symmetric, hollow, connected 0/1 matrix."""
    adj = TopologyFactory.generate_matrix(topology, n, seed=3)
    assert adj.shape == (n, n)
    assert set(np.unique(adj)) <= {0, 1}
    np.testing.assert_array_equal(adj, adj.T)
    assert np.diagonal(adj).sum() == 0
    if n > 1:
        assert _connected(adj), topology


def test_exact_structures():
    star = TopologyFactory.generate_matrix(TopologyType.STAR, 5)
    assert star[0].sum() == 4 and all(star[i].sum() == 1 for i in range(1, 5))
    line = TopologyFactory.generate_matrix(TopologyType.LINE, 5)
    assert line.sum() == 2 * 4  # n-1 undirected edges
    assert line[0].sum() == 1 and line[2].sum() == 2
    ring = TopologyFactory.generate_matrix(TopologyType.RING, 5)
    assert (ring.sum(axis=0) == 2).all()
    full = TopologyFactory.generate_matrix(TopologyType.FULL, 5)
    assert (full.sum(axis=0) == 4).all()


def test_grid_degrees():
    """3x3 grid: corners degree 2, edges 3, center 4."""
    adj = TopologyFactory.generate_matrix(TopologyType.GRID, 9)
    degrees = sorted(adj.sum(axis=0).tolist())
    assert degrees == [2, 2, 2, 2, 3, 3, 3, 3, 4]


def test_erdos_renyi_seeded_and_connected():
    a = TopologyFactory.generate_matrix(TopologyType.ERDOS_RENYI, 12, p=0.2, seed=7)
    b = TopologyFactory.generate_matrix(TopologyType.ERDOS_RENYI, 12, p=0.2, seed=7)
    np.testing.assert_array_equal(a, b)  # deterministic under a seed
    c = TopologyFactory.generate_matrix(TopologyType.ERDOS_RENYI, 12, p=0.2, seed=8)
    assert not np.array_equal(a, c)  # and varies with it
    # Even at p=0 the ring backbone guarantees connectivity.
    z = TopologyFactory.generate_matrix(TopologyType.ERDOS_RENYI, 12, p=0.0, seed=1)
    assert _connected(z)


def test_connect_nodes_wires_real_federation():
    """connect_nodes on in-memory nodes: direct-neighbor sets match the
    matrix (STAR: the hub sees all spokes, spokes see the hub)."""
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import (
        RandomIIDPartitionStrategy,
        synthetic_mnist,
    )
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    parts = synthetic_mnist(n_train=128, n_test=32).generate_partitions(
        4, RandomIIDPartitionStrategy
    )
    with Settings.overridden(RESOURCE_MONITOR_PERIOD=0):
        nodes = [Node(mlp_model(seed=i), parts[i]) for i in range(4)]
        for node in nodes:
            node.start()
        try:
            adj = TopologyFactory.generate_matrix(TopologyType.STAR, 4)
            TopologyFactory.connect_nodes(adj, nodes)
            hub_direct = set(nodes[0].get_neighbors(only_direct=True))
            assert hub_direct == {nodes[i].addr for i in (1, 2, 3)}
            for i in (1, 2, 3):
                assert set(nodes[i].get_neighbors(only_direct=True)) == {
                    nodes[0].addr
                }
        finally:
            for node in nodes:
                node.stop()
