"""Unit tests for bench.py's round-5 orchestration logic (wait ladder,
output assembly, MFU row math, multihost config) — the pure-Python pieces
that must be right for BENCH_r05.json to be trustworthy, testable without
a TPU or a jit."""

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import bench


def test_wait_for_tpu_respects_deadline(monkeypatch):
    """A deadline closer than one probe timeout must return None without
    probing (the reserve is sacred: it funds the measurement itself)."""
    calls = []
    monkeypatch.setattr(bench, "_subprocess_tpu_probe", lambda t=90.0: calls.append(t))
    out = bench.wait_for_tpu(deadline=time.monotonic() + 10.0, probe_timeout=90.0)
    assert out is None
    assert calls == []


def test_wait_for_tpu_returns_kind_on_recovery(monkeypatch):
    seq = iter([None, None, "TPU v5 lite"])
    monkeypatch.setattr(bench, "_subprocess_tpu_probe", lambda t=90.0: next(seq))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    out = bench.wait_for_tpu(deadline=time.monotonic() + 3600.0, probe_timeout=1.0)
    assert out == "TPU v5 lite"


def test_assemble_shapes_and_ratio():
    out = {"metric": "m", "value": None, "unit": "s/round", "vs_baseline": None, "extra": {}}
    tpu = {
        "sec_per_round": 0.02, "rounds_per_sec": 50.0, "final_test_acc": 0.9,
        "rounds_per_call": 10, "nodes": 100, "rounds": 10,
        "rounds_per_call_sweep": {"10": 0.02},
    }
    base = {"sec_per_round": 200.0, "baseline": "ref", "nodes": 20, "rounds": 1}
    bench._assemble(out, tpu, base, "TPU v5 lite", {"mfu": 0.4})
    assert out["value"] == 0.02
    assert out["vs_baseline"] == pytest.approx(10000.0)
    ex = out["extra"]
    # The degraded and TPU paths share this assembler; these keys are the
    # contract BENCH_r0N.json consumers read.
    for key in (
        "rounds_per_call_sweep", "baseline_sec_per_round", "baseline_nodes",
        "device_kind", "mfu_probe", "final_test_acc", "nodes", "rounds",
    ):
        assert key in ex, key
    assert ex["device_kind"] == "TPU v5 lite"


def test_production_mfu_row_math():
    cost = {"flops_per_round": 1e12, "bytes_accessed_per_round": 1e9}
    row = bench._production_mfu_row("m", "TPU v5 lite", cost, sec_per_round=0.01)
    # 1e12 flops / 0.01 s = 100 TFLOP/s; v5 lite peak 197.
    assert row["achieved_tflops"] == pytest.approx(100.0)
    assert row["mfu"] == pytest.approx(100.0 / 197.0, abs=1e-3)
    rl = row["roofline"]
    assert rl["arithmetic_intensity_flop_per_byte"] == pytest.approx(1000.0)
    assert 0.0 < rl["mfu_ceiling"] <= 1.0


def test_production_mfu_row_unknown_device():
    cost = {"flops_per_round": 1e12, "bytes_accessed_per_round": 1e9}
    row = bench._production_mfu_row("m", "cpu-rehearsal", cost, sec_per_round=0.01)
    assert row["mfu"] is None
    assert "roofline" not in row


def test_mh_cfg_env_overrides(monkeypatch):
    monkeypatch.setenv("P2PFL_TPU_MH_NODES", "32")
    monkeypatch.setenv("P2PFL_TPU_MH_RPC", "3")
    cfg = bench._mh_cfg()
    assert cfg["nodes"] == 32
    assert cfg["rpc"] == 3
    assert cfg["procs"] == bench.MH_PROCS  # untouched knobs keep defaults


def test_aux_captures_success_order_and_error_isolation(monkeypatch):
    """Aux legs run in order with per-leg caps; a failing leg records its
    error and later legs still run (evidence capture must never be
    all-or-nothing)."""
    calls = []

    def fake_subprocess(args, timeout, env):
        calls.append((args[0], timeout))
        if args[0] == "--attn":
            raise RuntimeError("tunnel wedged mid-leg")
        return {"metric": args[0], "value": 1}

    monkeypatch.setattr(bench, "_json_subprocess", fake_subprocess)
    aux = bench._run_aux_captures(time.monotonic(), 10_000.0, {})
    assert [c[0] for c in calls] == ["--cifar", "--attn", "--lm-mfu"]
    assert aux["cifar_resnet_trio"] == {"metric": "--cifar", "value": 1}
    assert "tunnel wedged" in aux["attention_microbench"]["error"]
    assert aux["lm_mfu"]["metric"] == "--lm-mfu"


def test_aux_captures_skip_on_exhausted_budget(monkeypatch):
    """With the budget spent, every leg is skipped without any subprocess."""
    monkeypatch.setattr(
        bench, "_json_subprocess",
        lambda *a, **k: (_ for _ in ()).throw(AssertionError("must not run")),
    )
    aux = bench._run_aux_captures(time.monotonic() - 5_000.0, 5_000.0, {})
    assert all(v == {"skipped": "soft budget exhausted"} for v in aux.values())


def test_aux_captures_partial_budget(monkeypatch):
    """A budget that only funds the first leg skips the rest (caps shrink
    with elapsed time)."""
    specs = [("a", "--a", 1500.0), ("b", "--b", 1500.0)]
    t0 = time.monotonic()
    # 400s of budget: leg a gets min(1500, 400-90)=310 >= 240 and runs; a
    # consuming fake then advances the clock so leg b sees the shrink.
    consumed = []

    def consuming(args, timeout, env):
        consumed.append(timeout)
        monkeypatch.setattr(
            bench.time, "monotonic", lambda: t0 + 200.0
        )  # leg took 200s
        return {"ok": args[0]}

    monkeypatch.setattr(bench, "_json_subprocess", consuming)
    aux = bench._run_aux_captures(t0, 400.0, {}, specs=specs)
    assert "ok" in aux["a"]
    assert aux["b"] == {"skipped": "soft budget exhausted"}


def test_aux_captures_mutate_attached_dict_in_place(monkeypatch):
    """The caller attaches `into` to the output line BEFORE the legs run;
    each completed leg must be visible in that same dict (the SIGTERM
    mid-queue survival property)."""
    seen_at_leg2 = {}

    def fake_subprocess(args, timeout, env):
        if args[0] == "--attn":
            seen_at_leg2.update(attached)  # snapshot mid-queue
        return {"metric": args[0]}

    monkeypatch.setattr(bench, "_json_subprocess", fake_subprocess)
    attached = {}
    out = bench._run_aux_captures(time.monotonic(), 10_000.0, {}, into=attached)
    assert out is attached
    # By the time leg 2 ran, leg 1's completed result was already attached.
    assert seen_at_leg2.get("cifar_resnet_trio") == {"metric": "--cifar"}


# --- probe verdict cache + the assume-backend knob (PR 16) --------------------


@pytest.fixture
def _clean_probe_state():
    """Probe verdicts are per-invocation module state; isolate each test."""
    bench._PROBE_CACHE[0] = None
    bench._TPU_FAIL_REASON[0] = None
    yield
    bench._PROBE_CACHE[0] = None
    bench._TPU_FAIL_REASON[0] = None


def test_probe_cache_up_verdict_reused(_clean_probe_state):
    """A found chip is definitive for the whole invocation: later probes
    must answer from the cache without spawning a subprocess (timeout so
    small a real probe could never succeed)."""
    bench._PROBE_CACHE[0] = ("up", "TPU v5 lite")
    assert bench._subprocess_tpu_probe(timeout=0.001) == "TPU v5 lite"
    assert bench._TPU_FAIL_REASON[0] is None


def test_probe_cache_down_verdict_stops_wait_ladder(monkeypatch, _clean_probe_state):
    """A clean negative ("tpu_absent") is definitive — the wait ladder must
    stop after ONE probe instead of sleeping its budget against it (the
    r03+ burn the cache exists to stop)."""
    calls = []

    def fake_probe(t=90.0):
        calls.append(t)
        bench._TPU_FAIL_REASON[0] = "tpu_absent"
        bench._PROBE_CACHE[0] = ("down", "tpu_absent")
        return None

    sleeps = []
    real_probe = bench._subprocess_tpu_probe
    monkeypatch.setattr(bench, "_subprocess_tpu_probe", fake_probe)
    monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
    out = bench.wait_for_tpu(deadline=time.monotonic() + 3600.0, probe_timeout=1.0)
    assert out is None
    assert len(calls) == 1 and sleeps == []
    # And a later direct probe answers from the cache, stamping the reason.
    bench._TPU_FAIL_REASON[0] = None
    assert real_probe(timeout=0.001) is None
    assert bench._TPU_FAIL_REASON[0] == "tpu_absent"


def test_probe_timeout_is_never_cached(_clean_probe_state):
    """A timeout is a transient non-answer: the ladder must keep re-asking,
    so it must NOT settle the verdict cache."""
    assert bench._subprocess_tpu_probe(timeout=0.05) is None
    assert bench._TPU_FAIL_REASON[0] == "tpu_probe_timeout"
    assert bench._PROBE_CACHE[0] is None


def test_assume_cpu_skips_probe_and_ladder(monkeypatch, _clean_probe_state):
    """P2PFL_TPU_BENCH_ASSUME_BACKEND=cpu skips every probe AND the whole
    wait ladder, while fallback_reason still records how the arm degraded."""
    from p2pfl_tpu.config import Settings

    monkeypatch.setattr(Settings, "BENCH_ASSUME_BACKEND", "cpu")
    assert bench._subprocess_tpu_probe(timeout=0.001) is None
    assert bench._TPU_FAIL_REASON[0] == "assumed_backend"
    calls = []
    monkeypatch.setattr(bench, "_subprocess_tpu_probe", lambda t=90.0: calls.append(t))
    t0 = time.monotonic()
    out = bench.wait_for_tpu(deadline=time.monotonic() + 3600.0, probe_timeout=30.0)
    assert out is None and calls == []
    assert time.monotonic() - t0 < 1.0


def test_assume_tpu_short_circuits_probe(monkeypatch, _clean_probe_state):
    """The settled-verdict self-propagation path: an arm subprocess spawned
    with ASSUME_BACKEND=tpu answers instantly without re-probing."""
    from p2pfl_tpu.config import Settings

    monkeypatch.setattr(Settings, "BENCH_ASSUME_BACKEND", "tpu")
    assert bench._subprocess_tpu_probe(timeout=0.001) == "TPU (assumed)"
    assert bench._TPU_FAIL_REASON[0] is None
    assert bench._PROBE_CACHE[0] is None  # an assumption is not a verdict
