"""Trajectory ledger & sim↔real parity tests.

Covers the PR's determinism contract (same seed ⇒ byte-identical canonical
ledgers across wire reruns, including under a seeded chaos drop trace), the
cross-backend parity gate at small n (wire vs fused mesh, bit-exact
aggregate hashes), the hash canonicalization rules, and parity_diff's
hostile-input tolerance (truncated ledger, unknown event version, missing
hash)."""

from __future__ import annotations

import importlib.util
import json
import os

import numpy as np
import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry.ledger import (
    KIND_RANK,
    LEDGERS,
    TrajectoryLedger,
    canonical_params_hash,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_parity_diff():
    spec = importlib.util.spec_from_file_location(
        "parity_diff", os.path.join(REPO, "scripts", "parity_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_ledgers():
    LEDGERS.reset()
    yield
    LEDGERS.reset()


# --- ledger mechanics ---------------------------------------------------------


def test_emit_sequences_and_tail():
    led = TrajectoryLedger("n0", run_id="r")
    assert led.emit("round_open", round=0, members=["a"])
    assert led.emit("contribution_folded", round=0, sender="a", lag=0, num_samples=4)
    assert led.emit("round_close", round=0)
    evs = led.events()
    assert [e["seq"] for e in evs] == [0, 1, 2]
    assert all(e["v"] == 1 for e in evs)
    assert [e["kind"] for e in led.tail(2)] == ["contribution_folded", "round_close"]


def test_dedup_key_one_commit_per_round():
    led = TrajectoryLedger("n0")
    assert led.emit(
        "aggregate_committed", round=1, dedup_key=("commit", 1), hash="h1"
    )
    # The redundant-delivery race: a second commit for the same round is one
    # trajectory fact, first wins.
    assert not led.emit(
        "aggregate_committed", round=1, dedup_key=("commit", 1), hash="h2"
    )
    commits = [e for e in led.events() if e["kind"] == "aggregate_committed"]
    assert len(commits) == 1 and commits[0]["hash"] == "h1"


def test_capacity_bound():
    with Settings.overridden(LEDGER_CAPACITY=16):
        led = TrajectoryLedger("n0")
        for i in range(40):
            led.emit("round_close", round=i)
        assert len(led.events()) == 16
        # oldest evicted, newest kept, seq keeps counting
        assert led.events()[-1]["round"] == 39
        assert led.events()[-1]["seq"] == 39


def test_canonical_dump_is_append_order_independent(tmp_path):
    """Two ledgers holding the same event SET in different arrival orders
    dump byte-identically — the property the cross-run determinism and the
    cross-backend diff both stand on."""
    events = [
        ("round_open", dict(round=0, members=["a", "b"])),
        ("contribution_folded", dict(round=0, sender="b", lag=0, num_samples=4)),
        ("contribution_folded", dict(round=0, sender="a", lag=0, num_samples=4)),
        ("aggregate_committed", dict(round=0, hash="sha256:x", contributors=["a", "b"], num_samples=8)),
        ("round_close", dict(round=0)),
    ]
    led_fwd = TrajectoryLedger("n0", run_id="r")
    for kind, fields in events:
        led_fwd.emit(kind, **fields)
    led_rev = TrajectoryLedger("n0", run_id="r")
    for kind, fields in reversed(events):
        led_rev.emit(kind, **fields)
    a = led_fwd.dump(str(tmp_path / "a.jsonl"))
    b = led_rev.dump(str(tmp_path / "b.jsonl"))
    assert open(a, "rb").read() == open(b, "rb").read()
    # provenance fields are stripped from the canonical view
    led_fwd.emit("aggregate_committed", round=1, hash="h", origin="train", reason="fill")
    canon = [e for e in led_fwd.canonical_events() if e.get("round") == 1][0]
    assert "origin" not in canon and "reason" not in canon


def test_hub_emit_respects_enabled():
    with Settings.overridden(LEDGER_ENABLED=False):
        assert not LEDGERS.emit("n0", "round_open", round=0, members=[])
        assert LEDGERS.peek("n0") is None
    with Settings.overridden(LEDGER_ENABLED=True):
        assert LEDGERS.emit("n0", "round_open", round=0, members=[])
        assert LEDGERS.peek("n0") is not None


# --- hash canonicalization ----------------------------------------------------


def test_hash_float_canonicalization():
    h = canonical_params_hash
    # -0.0 and +0.0 collapse
    assert h([np.float32([-0.0, 1.0])]) == h([np.float32([0.0, 1.0])])
    # every NaN payload collapses to one canonical NaN
    weird_nan = np.array([np.float32(np.nan)]).view(np.uint32)
    weird_nan = (weird_nan | 1).view(np.float32)  # non-default payload
    assert h([weird_nan]) == h([np.float32([np.nan])])
    # a value change changes the hash
    assert h([np.float32([1.0])]) != h([np.float32([1.0000001])])
    # a reshape changes the hash (shape is part of the identity)
    assert h([np.ones((2, 3), np.float32)]) != h([np.ones((3, 2), np.float32)])
    # pytree and its flat-leaves list agree (ModelHandle.get_parameters path)
    tree = {"a": np.ones((2,), np.float32), "b": np.zeros((3,), np.float32)}
    import jax

    assert h(tree) == h([np.asarray(x) for x in jax.tree.leaves(tree)])
    # float64 and float32 of the same values agree (canonical cast)
    assert h([np.float64([0.5, 0.25])]) == h([np.float32([0.5, 0.25])])


# --- parity_diff hostile inputs ----------------------------------------------


def _write_ledger(path, events, header=None):
    with open(path, "w") as f:
        f.write(json.dumps(header or {"ledger": "trajectory", "v": 1, "node": "x", "run_id": "r"}) + "\n")
        for ev in events:
            f.write(json.dumps(ev) + "\n")
    return str(path)


def _ev(kind, rnd, **fields):
    return {"v": 1, "seq": 0, "kind": kind, "round": rnd, **fields}


def test_parity_diff_ok_and_localization(tmp_path):
    pd = _load_parity_diff()
    base = [
        _ev("round_open", 0, members=["a", "b"]),
        _ev("contribution_folded", 0, sender="a", lag=0, num_samples=4),
        _ev("contribution_folded", 0, sender="b", lag=0, num_samples=4),
        _ev("aggregate_committed", 0, hash="sha256:aa", contributors=["a", "b"], num_samples=8),
        _ev("round_close", 0),
        _ev("round_open", 1, members=["a", "b"]),
        _ev("aggregate_committed", 1, hash="sha256:bb", contributors=["a", "b"], num_samples=8),
        _ev("round_close", 1),
    ]
    a = _write_ledger(tmp_path / "a.jsonl", base)
    ok = pd.compare_ledgers(pd.read_ledger(a)[1], pd.read_ledger(a)[1])
    assert ok["status"] == "OK" and ok["hashes_compared"] == 2

    # single-event perturbation localized exactly
    mutated = [dict(e) for e in base]
    mutated[6]["hash"] = "sha256:cc"
    b = _write_ledger(tmp_path / "b.jsonl", mutated)
    bad = pd.compare_ledgers(pd.read_ledger(a)[1], pd.read_ledger(b)[1])
    fd = bad["first_divergence"]
    assert bad["status"] == "DIVERGED"
    assert fd["a"]["kind"] == "aggregate_committed" and fd["a"]["round"] == 1
    assert "hash differs" in fd["problem"]
    # CLI contract: exit 1 + report written
    out = tmp_path / "report.json"
    assert pd.main([a, b, "--out", str(out)]) == 1
    assert json.load(open(out))["status"] == "DIVERGED"
    assert pd.main([a, a]) == 0


def test_parity_diff_truncated_ledger(tmp_path):
    pd = _load_parity_diff()
    a = _write_ledger(tmp_path / "a.jsonl", [
        _ev("round_open", 0, members=["a"]),
        _ev("round_close", 0),
    ])
    # crash-truncated copy: torn final line
    full = open(a).read().splitlines()
    torn = tmp_path / "torn.jsonl"
    torn.write_text("\n".join(full[:-1]) + "\n" + full[-1][: len(full[-1]) // 2])
    header, events, notes = pd.read_ledger(str(torn))
    assert len(events) == 1 and any("truncated" in n for n in notes)
    # the differ reports the missing tail as the divergence, not a crash
    res = pd.compare_ledgers(pd.read_ledger(a)[1], events)
    assert res["status"] == "DIVERGED"
    assert "missing in B" in res["first_divergence"]["problem"]


def test_parity_diff_unknown_version_and_missing_hash(tmp_path):
    pd = _load_parity_diff()
    events = [
        _ev("round_open", 0, members=["a"]),
        {"v": 99, "kind": "hologram", "round": 0},  # future schema: skipped
        {"kind": "no_version", "round": 0},  # unversioned: skipped
        "not even an object",
        _ev("aggregate_committed", 0, contributors=["a"], num_samples=4),  # no hash
        _ev("round_close", 0),
    ]
    a = _write_ledger(tmp_path / "a.jsonl", events)
    header, evs, notes = pd.read_ledger(a)
    assert [e["kind"] for e in evs] == ["round_open", "aggregate_committed", "round_close"]
    assert any("unknown event version" in n for n in notes)
    res = pd.compare_ledgers(evs, evs)
    assert res["status"] == "OK"
    assert res["hashes_compared"] == 0
    assert any("neither commit carries a hash" in n for n in res["notes"])


def test_perf_diff_refuses_cross_backend_comparisons(tmp_path):
    """A TPU baseline diffed against a CPU-fallback candidate must REFUSE
    (exit 3) with the fallback reason named — not report a 100x
    'regression' that is actually a platform change."""
    spec = importlib.util.spec_from_file_location(
        "perf_diff", os.path.join(REPO, "scripts", "perf_diff.py")
    )
    pd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pd)

    def doc(backend, why=None, value=1.0):
        return {
            "metric": "m", "value": value, "unit": "s",
            "meta": {
                "schema_version": 1, "backend": backend,
                "fallback_reason": why,
            },
        }

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(doc("TPU v5 lite")))
    b.write_text(json.dumps(doc("cpu", why="tpu_probe_timeout", value=100.0)))
    assert pd.main([str(a), str(b)]) == 3
    # explicit override compares anyway (and then flags the regression)
    assert pd.main([str(a), str(b), "--allow-backend-mismatch"]) == 1
    # same backend on both sides: no refusal
    b.write_text(json.dumps(doc("TPU v5 lite", value=1.0)))
    assert pd.main([str(a), str(b)]) == 0


def test_bench_meta_carries_fallback_reason():
    import bench

    meta = bench._bench_meta(seed=1, backend="cpu")
    assert "fallback_reason" in meta and meta["fallback_reason"] is None
    meta = bench._bench_meta(backend="cpu", fallback_reason="tpu_probe_timeout")
    assert meta["fallback_reason"] == "tpu_probe_timeout"


def test_parity_diff_kind_rank_in_sync():
    """The differ duplicates KIND_RANK to stay stdlib-only — drift between
    the copies would silently misalign ledgers."""
    pd = _load_parity_diff()
    assert pd.KIND_RANK == KIND_RANK


# --- emission points ----------------------------------------------------------


def test_async_fold_emits_contribution_event():
    from p2pfl_tpu.learning.aggregators import AsyncBufferedAggregator
    from p2pfl_tpu.models.model_handle import ModelHandle

    with Settings.overridden(LEDGER_ENABLED=True):
        agg = AsyncBufferedAggregator("async-node")
        agg.open_window(3)
        m = ModelHandle(
            params=[np.zeros(2, np.float32)], contributors=["peer"], num_samples=5
        )
        agg.fold(m, origin_window=1, sender="peer")
    evs = LEDGERS.get("async-node").events()
    folds = [e for e in evs if e["kind"] == "contribution_folded"]
    assert folds == [
        {
            "v": 1, "seq": folds[0]["seq"], "kind": "contribution_folded",
            "round": 3, "sender": "peer", "lag": 2, "num_samples": 5,
        }
    ]


def test_chaos_byzantine_activation_enters_ledger():
    from p2pfl_tpu.chaos import CHAOS

    with Settings.overridden(LEDGER_ENABLED=True):
        try:
            CHAOS.set_byzantine("evil-node", "signflip")
        finally:
            CHAOS.clear_byzantine()
    evs = LEDGERS.get("evil-node").events()
    assert any(
        e["kind"] == "chaos_fault" and e["fault"] == "byzantine"
        and e["attack"] == "signflip" and e["round"] is None
        for e in evs
    )


def test_observatory_membership_enters_ledger_and_snapshot():
    from p2pfl_tpu.telemetry.digest import HealthDigest
    from p2pfl_tpu.telemetry.observatory import Observatory

    with Settings.overridden(LEDGER_ENABLED=True):
        obs = Observatory("obs-node")
        obs.ingest(HealthDigest(node="peer-1", round=2))
        evs = LEDGERS.get("obs-node").events()
        assert any(
            e["kind"] == "membership" and e["event"] == "join"
            and e["peer"] == "peer-1" and e["round"] is None
            for e in evs
        )
        snap = obs.snapshot()
        assert snap["ledger"]["events"], "snapshot should carry the ledger tail"
    with Settings.overridden(LEDGER_SNAPSHOT_TAIL=0):
        assert "ledger" not in obs.snapshot()


# --- mesh emission ------------------------------------------------------------


def test_mesh_ledger_emission():
    import optax

    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.mesh import make_mesh
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    import jax

    n, s = 4, 32
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, s, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, size=(n, s)).astype(np.int32)
    w = np.ones((n, s), np.float32)
    with Settings.overridden(LEDGER_ENABLED=True):
        sim = MeshSimulation(
            model=mlp_model(seed=0, hidden_sizes=(16,)),
            partitions=(x, y, w),
            test_data=None,
            train_set_size=2,
            batch_size=16,
            optimizer=optax.sgd(0.1),
            seed=0,
            canonical_committee=True,
            mesh=make_mesh(devices=jax.devices()[:1]),
        )
        led = sim.attach_ledger(node="mesh-test", run_id="mesh-run")
        sim.run(2, warmup=False, rounds_per_call=1)
    evs = led.events()
    opens = [e for e in evs if e["kind"] == "round_open"]
    assert [e["round"] for e in opens] == [0, 1]
    assert all(len(e["members"]) == 2 for e in opens)
    folds = [e for e in evs if e["kind"] == "contribution_folded"]
    assert len(folds) == 4 and all(e["num_samples"] == s and e["lag"] == 0 for e in folds)
    commits = [e for e in evs if e["kind"] == "aggregate_committed"]
    # rounds_per_call=1: every round's commit carries a content hash
    assert len(commits) == 2 and all(e["hash"].startswith("sha256:") for e in commits)
    # canonical committee: members are drawn from the vnode names, sorted
    assert opens[0]["members"] == sorted(opens[0]["members"])


def test_mesh_ledger_node_names_validated():
    import optax

    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    x = np.zeros((2, 16, 28, 28), np.float32)
    y = np.zeros((2, 16), np.int32)
    w = np.ones((2, 16), np.float32)
    sim = MeshSimulation(
        model=mlp_model(seed=0, hidden_sizes=(8,)),
        partitions=(x, y, w), test_data=None,
        optimizer=optax.sgd(0.1), seed=0,
    )
    with pytest.raises(ValueError, match="node_names"):
        sim.attach_ledger(node_names=["only-one"])


# --- the determinism + parity contracts (wire runs; slower) -------------------


def _tiny_scenario(**kw):
    from p2pfl_tpu.parity import ParityScenario

    base = dict(
        seed=77, n_nodes=2, rounds=2, samples_per_node=32, batch_size=16,
        hidden=(16,),
    )
    base.update(kw)
    return ParityScenario(**base)


def test_wire_ledgers_byte_identical_across_runs(tmp_path):
    """Same seed ⇒ byte-identical canonical ledgers across two wire runs."""
    from p2pfl_tpu.parity import run_wire

    scn = _tiny_scenario()
    run_wire(scn, ledger_dir=str(tmp_path / "a"))
    run_wire(scn, ledger_dir=str(tmp_path / "b"))
    for name in scn.node_names:
        da = open(tmp_path / "a" / f"ledger_{name}.jsonl", "rb").read()
        db = open(tmp_path / "b" / f"ledger_{name}.jsonl", "rb").read()
        assert da == db, f"{name}: ledgers differ across identical runs"


def test_wire_ledgers_byte_identical_under_chaos_replay(tmp_path):
    """The chaos drop trace is seeded and recoverable: replaying the same
    chaos'd scenario yields byte-identical trajectory ledgers (per-frame
    drops are environment noise and deliberately NOT trajectory events)."""
    from p2pfl_tpu.parity import run_wire

    scn = _tiny_scenario(seed=78, drop_rate=0.1)
    run_wire(scn, ledger_dir=str(tmp_path / "a"))
    run_wire(scn, ledger_dir=str(tmp_path / "b"))
    for name in scn.node_names:
        da = open(tmp_path / "a" / f"ledger_{name}.jsonl", "rb").read()
        db = open(tmp_path / "b" / f"ledger_{name}.jsonl", "rb").read()
        assert da == db, f"{name}: chaos replay broke ledger determinism"


def test_parity_wire_vs_fused_bit_exact(tmp_path):
    """The gate's core claim at small n: the real wire federation and the
    fused mesh emit ALIGNED trajectories with bit-exact aggregate hashes."""
    import jax

    from p2pfl_tpu.parallel.mesh import make_mesh
    from p2pfl_tpu.parity import run_fused, run_wire

    pd = _load_parity_diff()
    scn = _tiny_scenario(seed=79)
    wire = run_wire(scn, ledger_dir=str(tmp_path))
    fused = run_fused(
        scn, ledger_dir=str(tmp_path),
        mesh=make_mesh(devices=jax.devices()[:1]),
    )
    names = scn.node_names
    assert wire["hashes"][names[0]] == wire["hashes"][names[1]]
    assert wire["hashes"][names[0]] == fused["hashes"]
    report = pd.compare_ledgers(wire["events"][names[0]], fused["events"])
    assert report["status"] == "OK", json.dumps(report["first_divergence"])
    assert report["hashes_compared"] == scn.rounds
