"""MoE / expert parallelism: routing math vs a per-token reference, LM
training, and expert-sharded execution on a mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P

from p2pfl_tpu.models.moe import (
    MoEMLP,
    moe_lm_apply_with_aux,
    moe_lm_model,
    shard_moe_params,
)
import pytest

# expert-parallel programs compile ~5-20s each on the 1-core CPU mesh -> excluded from the fast subset
pytestmark = pytest.mark.slow


def test_moe_mlp_matches_per_token_reference():
    """With ample capacity and f32 compute, the dispatch/combine einsums must
    equal routing each token through its argmax expert individually."""
    b, s, e, nx = 2, 8, 16, 4
    layer = MoEMLP(
        num_experts=nx, mlp_ratio=2, capacity_factor=float(b * s),
        compute_dtype=jnp.float32,
    )
    x = jax.random.normal(jax.random.key(0), (b, s, e), jnp.float32)
    params = layer.init(jax.random.key(1), x)
    out, _ = layer.apply(params, x, mutable=["losses"])

    p = params["params"]
    router_w = np.asarray(p["router"]["kernel"])  # [E, X]
    wi = np.asarray(p["wi"])  # [X, E, M]
    wo = np.asarray(p["wo"])  # [X, M, E]
    toks = np.asarray(x).reshape(-1, e)
    expect = np.zeros_like(toks)
    for t in range(toks.shape[0]):
        logits = toks[t] @ router_w
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        xi = int(np.argmax(probs))
        h = toks[t] @ wi[xi]
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        expect[t] = float(probs[xi]) * (h @ wo[xi])
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, e), expect, atol=1e-4
    )


def test_moe_capacity_overflow_drops_to_residual():
    """Tokens past an expert's capacity must contribute zero (the block's
    residual carries them), never garbage."""
    b, s, e, nx = 1, 8, 8, 2
    layer = MoEMLP(num_experts=nx, mlp_ratio=1, capacity_factor=0.25,
                   compute_dtype=jnp.float32)  # cap = 1 token per expert
    x = jax.random.normal(jax.random.key(0), (b, s, e), jnp.float32)
    params = layer.init(jax.random.key(1), x)
    out, _ = layer.apply(params, x, mutable=["losses"])
    # at most `cap * nx` = 2 rows may be nonzero
    nonzero_rows = np.count_nonzero(
        np.abs(np.asarray(out).reshape(-1, e)).sum(axis=1) > 1e-9
    )
    assert nonzero_rows <= 2, nonzero_rows


def test_moe_lm_trains_with_aux_loss():
    model = moe_lm_model(
        seed=0, seq_len=32, vocab_size=64, num_layers=2, num_heads=2,
        embed_dim=32, num_experts=4,
    )
    apply_aux = moe_lm_apply_with_aux(model.model_def)
    toks = jnp.asarray(np.arange(4 * 32, dtype=np.int32).reshape(4, 32) % 64)
    opt = optax.adam(5e-3)

    @jax.jit
    def step(p, s):
        def loss_fn(pp):
            logits, aux = apply_aux(pp, toks)
            logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
            nll = -jnp.take_along_axis(
                logp, toks[:, 1:, None].astype(jnp.int32), axis=-1
            )[..., 0]
            return jnp.mean(nll) + 0.01 * aux
        loss, g = jax.value_and_grad(loss_fn)(p)
        u, s = opt.update(g, s, p)
        return optax.apply_updates(p, u), s, loss

    p, s = model.params, opt.init(model.params)
    first = None
    for _ in range(20):
        p, s, loss = step(p, s)
        first = first if first is not None else float(loss)
    assert float(loss) < first * 0.7, (first, float(loss))


def test_expert_parallel_matches_unsharded():
    mesh = Mesh(np.array(jax.devices()[:4]), ("expert",))
    model = moe_lm_model(
        seed=0, seq_len=16, vocab_size=32, num_layers=2, num_heads=2,
        embed_dim=32, num_experts=4,
    )
    toks = jnp.asarray(np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % 32)
    ref = model.apply_fn(model.params, toks)
    sharded = shard_moe_params(model.params, mesh)
    # expert-stacked FFN weights actually landed on the expert axis
    wi = sharded["params"]["block1"]["moe"]["wi"]
    assert wi.sharding.spec == P("expert"), wi.sharding
    out = jax.jit(model.apply_fn)(sharded, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-2)
