"""Multi-host mesh groundwork (VERDICT round-2 ask #5): two OS processes,
each with 4 virtual CPU devices, join via jax.distributed and run one
FedAvg round of the MeshSimulation over a process-spanning mesh — the
CI-runnable analogue of a DCN-spanning pod slice."""

import os
import socket
import subprocess
import sys
import pytest

# spawns a 2-process jax.distributed mesh -> excluded from the fast subset
pytestmark = pytest.mark.slow



def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_fedavg_round():
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(worker))
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out[-3000:]}"
        assert "MULTIHOST_OK" in out, out[-2000:]
    # Both processes computed the same (replicated) accuracy.
    accs = {line.split("acc=")[1] for out in outs for line in out.splitlines() if "MULTIHOST_OK" in line}
    assert len(accs) == 1, accs


def test_multihost_bench_mode():
    """`python bench.py --multihost` (VERDICT r4 ask #5): the FULL bench
    path — MeshSimulation with warmup, fused rounds_per_call, eval cadence,
    committee sampling — composes over a 2-process jax.distributed mesh,
    not just one FedAvg round. Tiny shape via env so CI stays affordable;
    the documented launch command (no env) runs the 96-node shape."""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env.update(
        P2PFL_TPU_MH_NODES="16", P2PFL_TPU_MH_SAMPLES="64",
        P2PFL_TPU_MH_ROUNDS="4", P2PFL_TPU_MH_RPC="2",
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--multihost"],
        capture_output=True, text=True, timeout=600, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    import json

    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["metric"] == "sec_per_round_16node_mnist_fedavg_multihost_cpu"
    assert out["value"] > 0
    ex = out["extra"]
    assert ex["processes"] == 2 and ex["global_devices"] == 8
    # 4 rounds x 64 samples on the template task already clears chance.
    assert ex["final_test_acc"] > 0.3, out
