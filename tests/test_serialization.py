"""Wire-format round-trip and safety tests (no pickle anywhere)."""

import numpy as np
import pytest

from p2pfl_tpu.exceptions import DecodingParamsError
from p2pfl_tpu.ops.serialization import deserialize_arrays, serialize_arrays


def test_roundtrip_basic():
    arrays = [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.ones((2, 2, 2), dtype=np.float16),
        np.array(7, dtype=np.int64),
        np.zeros((0, 5), dtype=np.float32),
    ]
    meta = {"contributors": ["a", "b"], "num_samples": 128, "nested": {"x": [1, 2.5]}}
    buf = serialize_arrays(arrays, meta)
    out, meta2 = deserialize_arrays(buf)
    assert len(out) == len(arrays)
    for a, b in zip(arrays, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    assert meta2 == meta


def test_roundtrip_bfloat16_via_ml_dtypes():
    import jax.numpy as jnp

    a = np.asarray(jnp.ones((4, 4), dtype=jnp.bfloat16))
    buf = serialize_arrays([a], {})
    out, _ = deserialize_arrays(buf)
    assert out[0].dtype == a.dtype
    np.testing.assert_array_equal(np.asarray(a, np.float32), np.asarray(out[0], np.float32))


def test_metadata_ndarray():
    c = np.random.default_rng(0).normal(size=(3, 3)).astype(np.float32)
    buf = serialize_arrays([], {"global_c": c})
    _, meta = deserialize_arrays(buf)
    np.testing.assert_array_equal(meta["global_c"], c)


def test_bad_magic_raises():
    with pytest.raises(DecodingParamsError):
        deserialize_arrays(b"NOPE" + b"\0" * 64)


def test_truncated_raises():
    buf = serialize_arrays([np.ones((10, 10), np.float32)], {})
    with pytest.raises(DecodingParamsError):
        deserialize_arrays(buf[: len(buf) // 2])


def test_rejects_unserializable_metadata():
    with pytest.raises(TypeError):
        serialize_arrays([], {"fn": lambda: None})


# --- wire compression (ops/compression.py) -----------------------------------


def test_compress_bf16_roundtrip_bound():
    from p2pfl_tpu.ops.compression import compress_arrays, decompress_arrays

    rng = np.random.default_rng(0)
    arrays = [rng.normal(size=(64, 32)).astype(np.float32), np.arange(5, dtype=np.int32)]
    enc, spec = compress_arrays(arrays, "bf16")
    assert enc[0].dtype.name == "bfloat16"
    assert enc[1].dtype == np.int32 and spec[1]["codec"] == "raw"  # ints pass through
    dec = decompress_arrays(enc, spec)
    assert dec[0].dtype == np.float32
    # bf16 keeps ~8 mantissa bits: relative error < 2^-8
    np.testing.assert_allclose(dec[0], arrays[0], rtol=2**-8)
    np.testing.assert_array_equal(dec[1], arrays[1])


def test_compress_int8_error_bound_and_size():
    from p2pfl_tpu.ops.compression import compress_arrays, decompress_arrays

    rng = np.random.default_rng(1)
    a = rng.normal(scale=0.1, size=(128, 128)).astype(np.float32)
    enc, spec = compress_arrays([a], "int8")
    assert enc[0].dtype == np.int8 and enc[0].nbytes == a.nbytes // 4
    dec = decompress_arrays(enc, spec)[0]
    scale = spec[0]["scale"]
    assert np.max(np.abs(dec - a)) <= scale / 2 + 1e-7
    # zero tensors and 0-d arrays survive
    enc, spec = compress_arrays([np.zeros((3,), np.float32), np.float32(2.5)], "int8")
    dec = decompress_arrays(enc, spec)
    np.testing.assert_array_equal(dec[0], np.zeros((3,)))
    np.testing.assert_allclose(dec[1], 2.5, atol=2.5 / 127)


def test_compress_unknown_scheme_and_spec_mismatch():
    from p2pfl_tpu.ops.compression import compress_arrays, decompress_arrays

    with pytest.raises(ValueError, match="unknown compression scheme"):
        compress_arrays([np.zeros(2, np.float32)], "zstd")
    with pytest.raises(ValueError, match="does not match"):
        decompress_arrays([np.zeros(2, np.int8)], [])


def test_model_handle_wire_compression_transparent():
    """A compressed frame decodes on a receiver with default settings: the
    codec spec rides in the frame (sender-local setting)."""
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.models import mlp_model

    sender = mlp_model(seed=0)
    sender.set_contribution(["addr-a"], 321)
    raw = len(sender.encode_parameters())
    blob = sender.encode_parameters(compression="int8")
    assert len(blob) < raw / 3  # ~4x smaller minus header
    receiver = mlp_model(seed=1)
    receiver.set_parameters(bytes(blob))
    assert receiver.contributors == ["addr-a"] and receiver.num_samples == 321
    for got, want in zip(receiver.get_parameters(), sender.get_parameters()):
        assert got.dtype == want.dtype
        absmax = np.max(np.abs(want)) if want.size else 0.0
        np.testing.assert_allclose(got, want, atol=absmax / 127 + 1e-7)

    # Settings-driven default path
    with Settings.overridden(WIRE_COMPRESSION="bf16"):
        blob = sender.encode_parameters()
    assert len(blob) < raw * 0.6
    receiver.set_parameters(bytes(blob))


def test_int8_nonfinite_tensors_ship_raw():
    """A diverged (NaN/inf) tensor must not be laundered into finite int8
    weights — it passes through raw so receivers still see the divergence."""
    from p2pfl_tpu.ops.compression import compress_arrays, decompress_arrays

    bad = np.array([np.nan, 1.0, np.inf], np.float32)
    good = np.ones((4,), np.float32)
    enc, spec = compress_arrays([bad, good], "int8")
    assert spec[0]["codec"] == "raw" and spec[1]["codec"] == "int8"
    dec = decompress_arrays(enc, spec)
    assert np.isnan(dec[0][0]) and np.isinf(dec[0][2])


def test_malformed_codec_spec_raises_decoding_error():
    from p2pfl_tpu.exceptions import DecodingParamsError
    from p2pfl_tpu.models.model_handle import decode_wire_frame
    from p2pfl_tpu.ops.compression import CODEC_META_KEY

    blob = serialize_arrays(
        [np.zeros((2,), np.int8)], {CODEC_META_KEY: [{"codec": "int8"}]}  # no scale
    )
    with pytest.raises(DecodingParamsError, match="codec spec"):
        decode_wire_frame(bytes(blob))
    blob = serialize_arrays([np.zeros((2,), np.int8)], {CODEC_META_KEY: "bf16"})
    with pytest.raises(DecodingParamsError):
        decode_wire_frame(bytes(blob))


# --- hypothesis fuzz: the wire format faces untrusted peers -------------------
# Optional dependency: without it only the fuzz cases vanish — a missing
# hypothesis must not take the whole module's deterministic tests down with
# a collection error.

try:
    from hypothesis import given, settings as hyp_settings, strategies as st

    _HAVE_HYPOTHESIS = True
except ModuleNotFoundError:
    _HAVE_HYPOTHESIS = False

if _HAVE_HYPOTHESIS:
    _DTYPES = [np.float32, np.float16, np.int32, np.int64, np.uint8, np.bool_]

    @st.composite
    def _array(draw):
        dtype = draw(st.sampled_from(_DTYPES))
        shape = tuple(draw(st.lists(st.integers(0, 5), min_size=0, max_size=4)))
        if dtype == np.bool_:
            return (draw(st.integers(0, 1)) * np.ones(shape)).astype(dtype)
        return np.full(shape, draw(st.integers(-100, 100)), dtype=dtype)

    @hyp_settings(max_examples=40, deadline=None)
    @given(st.lists(_array(), min_size=0, max_size=6), st.integers(0, 2**31 - 1))
    def test_fuzz_roundtrip_any_shapes_dtypes(arrays, sample_count):
        """Any list of ndarrays (0-d, empty, bool, unsigned...) survives the
        PFLT frame byte-exactly with its metadata."""
        meta = {"num_samples": sample_count}
        out, meta2 = deserialize_arrays(serialize_arrays(arrays, meta))
        assert meta2 == meta and len(out) == len(arrays)
        for a, b in zip(arrays, out):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)

    @hyp_settings(max_examples=60, deadline=None)
    @given(st.data())
    def test_fuzz_single_byte_flip_never_crashes(data):
        """Flipping any single byte of a frame either still decodes (flip
        landed in tensor payload — CRC32 verification is the checksummed
        path's job; see test_tensor_corruption_detected) or raises
        DecodingParamsError. It must NEVER raise anything else — malformed
        frames from a malicious peer cannot crash the node loop with an
        unexpected exception type."""
        buf = bytearray(
            serialize_arrays(
                [np.arange(6, dtype=np.float32).reshape(2, 3)], {"contributors": ["n0"]}
            )
        )
        pos = data.draw(st.integers(0, len(buf) - 1))
        bit = data.draw(st.integers(0, 7))
        buf[pos] ^= 1 << bit
        try:
            deserialize_arrays(bytes(buf))
        except DecodingParamsError:
            pass  # the contract: corrupt frames fail loudly with THIS error
