"""Wire-format round-trip and safety tests (no pickle anywhere)."""

import numpy as np
import pytest

from p2pfl_tpu.exceptions import DecodingParamsError
from p2pfl_tpu.ops.serialization import deserialize_arrays, serialize_arrays


def test_roundtrip_basic():
    arrays = [
        np.arange(12, dtype=np.float32).reshape(3, 4),
        np.ones((2, 2, 2), dtype=np.float16),
        np.array(7, dtype=np.int64),
        np.zeros((0, 5), dtype=np.float32),
    ]
    meta = {"contributors": ["a", "b"], "num_samples": 128, "nested": {"x": [1, 2.5]}}
    buf = serialize_arrays(arrays, meta)
    out, meta2 = deserialize_arrays(buf)
    assert len(out) == len(arrays)
    for a, b in zip(arrays, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)
    assert meta2 == meta


def test_roundtrip_bfloat16_via_ml_dtypes():
    import jax.numpy as jnp

    a = np.asarray(jnp.ones((4, 4), dtype=jnp.bfloat16))
    buf = serialize_arrays([a], {})
    out, _ = deserialize_arrays(buf)
    assert out[0].dtype == a.dtype
    np.testing.assert_array_equal(np.asarray(a, np.float32), np.asarray(out[0], np.float32))


def test_metadata_ndarray():
    c = np.random.default_rng(0).normal(size=(3, 3)).astype(np.float32)
    buf = serialize_arrays([], {"global_c": c})
    _, meta = deserialize_arrays(buf)
    np.testing.assert_array_equal(meta["global_c"], c)


def test_bad_magic_raises():
    with pytest.raises(DecodingParamsError):
        deserialize_arrays(b"NOPE" + b"\0" * 64)


def test_truncated_raises():
    buf = serialize_arrays([np.ones((10, 10), np.float32)], {})
    with pytest.raises(DecodingParamsError):
        deserialize_arrays(buf[: len(buf) // 2])


def test_rejects_unserializable_metadata():
    with pytest.raises(TypeError):
        serialize_arrays([], {"fn": lambda: None})
