"""Sketch-native fleet observability (PR 8).

Covers: the quantile sketch's merge algebra (associativity, commutativity,
idempotent re-merge at the observatory) and relative-error bound against
exact quantiles on adversarial distributions; the HyperLogLog distinct
estimator; digest v1<->v2 cross-version round-trips; observatory TTL
eviction and bounded population-overflow tracking; Prometheus summary/
quantile exposition (with escaping/NaN regressions extended to the new
form); window-DAG attribution on synthetic async traces; and the fused-mesh
population snapshot.
"""

from __future__ import annotations

import json
import math
import random
import time

import numpy as np
import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry import REGISTRY
from p2pfl_tpu.telemetry import digest as digest_mod
from p2pfl_tpu.telemetry.critical_path import CriticalPathAnalyzer, Seg
from p2pfl_tpu.telemetry.export import hist_quantile, render_prometheus
from p2pfl_tpu.telemetry.observatory import Observatory, population_snapshot
from p2pfl_tpu.telemetry.sketches import (
    SKETCHES,
    DistinctEstimator,
    QuantileSketch,
)


@pytest.fixture(autouse=True)
def _clean_sketches():
    SKETCHES.reset()
    yield
    SKETCHES.reset()


# --- quantile sketch ----------------------------------------------------------


def _adversarial_streams():
    rng = random.Random(7)
    return {
        "constant": [3.14] * 500,
        "bimodal_extreme": [1e-6] * 300 + [1e6] * 300,
        "lognormal": [rng.lognormvariate(0.0, 2.0) for _ in range(2000)],
        "with_zeros_and_negatives": (
            [0.0] * 50
            + [-rng.lognormvariate(0.0, 1.0) for _ in range(200)]
            + [rng.lognormvariate(0.0, 1.0) for _ in range(200)]
        ),
        "heavy_duplicates": [float(rng.choice([1, 1, 1, 2, 50])) for _ in range(1000)],
    }


def _exact_quantile(values, q):
    """Nearest-rank (floor) — the sketch walk's convention."""
    s = sorted(values)
    return s[int(q * (len(s) - 1))]


def test_sketch_relative_error_bound_on_adversarial_distributions():
    for name, stream in _adversarial_streams().items():
        sk = QuantileSketch(rel_err=0.02, max_bins=1024)  # no collapse
        for v in stream:
            sk.add(v)
        for q in (0.1, 0.5, 0.9, 0.99):
            exact = _exact_quantile(stream, q)
            est = sk.quantile(q)
            if abs(exact) < 1e-9:
                assert abs(est) < 1e-9, (name, q, est)
            else:
                rel = abs(est - exact) / abs(exact)
                assert rel <= sk.rel_err + 1e-9, (name, q, exact, est, rel)


def test_sketch_collapse_bounds_bins_and_tracks_degraded_error():
    sk = QuantileSketch(rel_err=0.02, max_bins=32)
    rng = random.Random(3)
    stream = [rng.lognormvariate(0.0, 3.0) for _ in range(5000)]
    for v in stream:
        sk.add(v)
    assert len(sk._bins) <= 32
    assert sk.rel_err > 0.02  # collapse degraded (and TRACKED) the guarantee
    for q in (0.5, 0.9, 0.99):
        exact = _exact_quantile(stream, q)
        est = sk.quantile(q)
        assert abs(est - exact) / exact <= sk.rel_err + 1e-9, (q, exact, est)


def test_sketch_merge_associative_commutative():
    rng = random.Random(11)
    streams = [
        [rng.lognormvariate(0.0, 1.5) for _ in range(200)] for _ in range(3)
    ]
    a, b, c = (QuantileSketch(rel_err=0.02) for _ in range(3))
    for sk, vals in zip((a, b, c), streams):
        for v in vals:
            sk.add(v)
    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    swapped = c.merge(a.merge(b))
    for q in (0.25, 0.5, 0.9, 0.99):
        assert left.quantile(q) == right.quantile(q) == swapped.quantile(q)
    assert left.count == right.count == swapped.count == 600
    # Merged quantiles keep the bound vs the pooled stream.
    pooled = streams[0] + streams[1] + streams[2]
    for q in (0.5, 0.9):
        exact = _exact_quantile(pooled, q)
        assert abs(left.quantile(q) - exact) / exact <= left.rel_err + 1e-9


def test_sketch_add_many_matches_scalar_adds():
    vals = np.array([0.0, 0.001, 0.5, -2.0, 7.25, 7.25, 1e4], np.float64)
    a = QuantileSketch(rel_err=0.02)
    a.add_many(vals)
    b = QuantileSketch(rel_err=0.02)
    for v in vals:
        b.add(float(v))
    assert a.count == b.count and a.sum == pytest.approx(b.sum)
    assert a._bins == b._bins and a._neg == b._neg
    assert a.zero_count == b.zero_count


def test_sketch_wire_roundtrip_and_hostile_payloads():
    sk = QuantileSketch(rel_err=0.02)
    rng = random.Random(5)
    for _ in range(500):
        sk.add(rng.lognormvariate(0.0, 1.0))
    wire = sk.to_wire()
    assert len(json.dumps(wire)) < 2048
    back = QuantileSketch.from_wire(wire)
    assert back is not None
    assert back.count == sk.count
    for q in (0.5, 0.9, 0.99):
        assert back.quantile(q) == pytest.approx(sk.quantile(q), rel=back.rel_err + 0.01)
    # Wire-bin bounding survives the round trip (digest beat budget).
    small = QuantileSketch.from_wire(sk.to_wire(max_bins=16))
    assert small is not None and len(small._bins) <= 16
    # Hostile/garbage payloads decode to None, never raise.
    for garbage in (
        None, "x", 42, [], {"v": 99}, {"v": 1, "b": "nope"},
        {"v": 1, "b": [[0, "NaN"]]},
        {"v": 1, "c": 1, "b": [[0, 1e9]]},  # fabricated mass > count
        {"v": 1, "c": float("inf"), "b": []},
    ):
        assert QuantileSketch.from_wire(garbage) is None, garbage


def test_distinct_estimator_accuracy_merge_idempotence_and_wire():
    a = DistinctEstimator()
    for i in range(2000):
        a.add(f"node-{i}")
    est = a.estimate()
    assert abs(est - 2000) / 2000 < 0.25  # HLL m=128: ~9% typical
    # Idempotent re-merge: gossip redelivery must not inflate the count.
    assert a.merge(a).estimate() == est
    b = DistinctEstimator()
    for i in range(1500, 2500):
        b.add(f"node-{i}")
    merged = a.merge(b)
    assert merged.estimate() >= est  # union can only grow
    assert a.merge(b).estimate() == b.merge(a).estimate()
    back = DistinctEstimator.from_wire(a.to_wire())
    assert back is not None and back.estimate() == est
    for garbage in (None, 7, "!!!notb64!!!", "QUJD", ""):  # wrong size/format
        assert DistinctEstimator.from_wire(garbage) is None, garbage


# --- digest v1 <-> v2 ---------------------------------------------------------


def _v2_digest(node="mem://peer", lags=(0, 0, 1, 2)):
    sk = QuantileSketch(rel_err=0.02)
    for lag in lags:
        sk.add(float(lag))
    est = DistinctEstimator()
    est.add("a")
    est.add("b")
    return digest_mod.HealthDigest(
        node=node, ts=time.time(), round=3, stage="AsyncWindowStage",
        mode="async", steps_per_s=25.0,
        sketches={"staleness": sk.to_wire(), "__distinct__": est.to_wire()},
    )


def test_digest_v2_roundtrip_carries_sketches():
    dig = _v2_digest()
    back = digest_mod.decode(dig.encode())
    assert back is not None and back.version == digest_mod.DIGEST_VERSION
    sk = back.sketch("staleness")
    assert sk is not None and sk.count == 4
    # Nearest-rank (floor) p90 of (0, 0, 1, 2) is 1.
    assert sk.quantile(0.9) == pytest.approx(1.0, rel=0.05)
    est = back.distinct()
    assert est is not None and est.estimate() == pytest.approx(2.0, abs=0.5)
    # v1 scalar fields survive alongside.
    assert back.round == 3 and back.mode == "async" and back.steps_per_s == 25.0


def test_digest_v1_payload_decodes_with_empty_sketches():
    # A v1 sender's payload: no "sk" key at all.
    v1 = digest_mod.HealthDigest(node="mem://old", ts=1.0, round=2)
    v1.version = 1
    v1.sketches = {}
    payload = v1.encode()
    assert '"sk"' not in payload
    back = digest_mod.decode(payload)
    assert back is not None and back.version == 1
    assert back.sketches == {}
    assert back.sketch("staleness") is None and back.distinct() is None
    assert back.round == 2


def test_digest_v2_readable_by_v1_field_set():
    """An old decoder keeps every recognized field and ignores the rest —
    simulate by checking the v2 payload is a strict superset of the v1
    field set (the contract the old decode loop relies on)."""
    raw = json.loads(_v2_digest().encode())
    v1_fields = {
        "node", "ts", "round", "total_rounds", "stage", "mode", "staleness",
        "steps_per_s", "jit_compile_s", "tx_bytes", "rx_bytes", "queue_depth",
        "agg_waits", "agg_wait_s", "contributors", "rejections",
        "rejected_by_source", "faults_seen", "mem_bytes", "v",
    }
    assert v1_fields <= set(raw)
    # Malformed sketch table degrades to absent, not to a dead digest.
    raw["sk"] = {"staleness": "not-a-dict", "__distinct__": 42}
    back = digest_mod.decode(json.dumps(raw))
    assert back is not None and back.sketch("staleness") is None


def test_digest_collect_includes_observed_sketches():
    REGISTRY.reset()
    SKETCHES.observe("step_time", "mem://me", 0.02)
    SKETCHES.observe("staleness", "mem://me", 1.0)
    SKETCHES.distinct_add("mem://me", "mem://peer")
    dig = digest_mod.collect("mem://me")
    assert dig.version == 2
    assert dig.sketch("step_time") is not None
    assert dig.sketch("staleness").count == 1
    assert dig.distinct() is not None
    assert len(dig.encode()) <= digest_mod.MAX_DIGEST_BYTES


# --- observatory: idempotent re-merge, TTL eviction, overflow ----------------


def test_observatory_remerge_is_idempotent():
    obs = Observatory("mem://obs")
    dig = _v2_digest(node="mem://peer")
    obs.ingest(dig)
    once = obs.fleet_quantiles()
    obs.ingest(dig)  # gossip redelivery: latest-per-peer, not accumulation
    twice = obs.fleet_quantiles()
    assert once["staleness"]["count"] == twice["staleness"]["count"] == 4
    assert once == twice


def test_observatory_ttl_eviction_drops_dead_peer_from_scoring():
    evicted = REGISTRY.get("p2pfl_fed_evicted_total")
    with Settings.overridden(OBS_PEER_TTL=5.0):
        obs = Observatory("mem://obs-ttl")
        before = sum(
            c.value for lbl, c in evicted.samples()
            if lbl.get("node") == "mem://obs-ttl"
        )
        dead = _v2_digest(node="mem://dead")
        obs.ingest(dead)
        assert "mem://dead" in obs.scores()
        # Age the dead peer's arrival past the TTL, then any ingest sweeps.
        with obs._lock:
            d, seen = obs._peers["mem://dead"]
            obs._peers["mem://dead"] = (d, seen - 10.0)
        obs._last_evict = 0.0
        obs.ingest(_v2_digest(node="mem://alive"))
        assert "mem://dead" not in obs.scores()
        assert "mem://alive" in obs.scores()
        after = sum(
            c.value for lbl, c in evicted.samples()
            if lbl.get("node") == "mem://obs-ttl"
        )
        assert after == before + 1
        events = [e["event"] for e in obs.snapshot()["membership_events"]]
        assert "evict" in events


def test_observatory_overflow_stays_bounded_and_folds_fleet_sketches():
    with Settings.overridden(OBS_MAX_TRACKED=8):
        obs = Observatory("mem://obs-big")
        for i in range(40):
            obs.ingest(_v2_digest(node=f"mem://p{i:03d}", lags=(1,)))
        assert len(obs.scores()) <= 8
        snap = obs.snapshot()
        assert snap["fleet"]["overflow_peers"] == 40 - 8
        assert snap["fleet"]["size"] == 40
        # Every peer's staleness fold is in the merged fleet view, tracked
        # or not — the quantile plane is population-complete.
        assert obs.fleet_quantiles()["staleness"]["count"] == 40
        # Memory plateaus: ingesting more overflow peers barely moves it.
        m1 = obs.estimated_memory_bytes()
        for i in range(40, 80):
            obs.ingest(_v2_digest(node=f"mem://p{i:03d}", lags=(1,)))
        m2 = obs.estimated_memory_bytes()
        assert m2 < m1 * 1.5


def test_observatory_snapshot_surfaces_staleness_p90():
    obs = Observatory("mem://obs-stale")
    obs.ingest(_v2_digest(node="mem://peer", lags=(0, 0, 0, 0, 0, 0, 0, 0, 3, 3)))
    entry = obs.snapshot()["peers"]["mem://peer"]
    assert entry["staleness_p90"] == pytest.approx(3.0, rel=0.05)
    v1 = digest_mod.HealthDigest(node="mem://old", ts=time.time(), round=1)
    v1.version = 1
    obs.ingest(v1)
    assert obs.snapshot()["peers"]["mem://old"]["staleness_p90"] is None


# --- Prometheus summary/quantile exposition ----------------------------------


def test_prometheus_histogram_quantile_family():
    REGISTRY.reset()
    h = REGISTRY.histogram(
        "t_fleetobs_demo_seconds", "demo", labels=("node",)
    )
    for v in (0.01, 0.02, 0.3, 1.2, 4.0):
        h.labels("n1").observe(v)
    text = render_prometheus()
    assert "# TYPE t_fleetobs_demo_seconds_quantile gauge" in text
    for q in ("0.5", "0.9", "0.99"):
        assert f't_fleetobs_demo_seconds_quantile{{node="n1",quantile="{q}"}}' in text
    # hist_quantile interpolates inside the covering bucket.
    assert hist_quantile((1.0, 2.0, 4.0), (0, 2, 2), 0.5) == pytest.approx(2.0)
    assert math.isnan(hist_quantile((1.0,), (0,), 0.5))


def test_prometheus_sketch_quantiles_with_escaping_and_nan_regression():
    REGISTRY.reset()
    evil = 'no"de\\with\nnasties'
    SKETCHES.observe("step_time", evil, 0.5)
    text = render_prometheus()
    assert "# TYPE p2pfl_sketch_step_time gauge" in text
    # The node label is escaped exactly like every other label value.
    assert 'node="no\\"de\\\\with\\nnasties"' in text
    assert 'quantile="0.5"' in text
    # Empty-histogram series emit NO quantile lines (no NaN noise): an
    # empty histogram family renders buckets but no _quantile family.
    REGISTRY.reset()
    SKETCHES.reset()
    REGISTRY.histogram("t_fleetobs_empty_seconds", "empty", labels=("node",)).labels("a")
    text = render_prometheus()
    assert "t_fleetobs_empty_seconds_bucket" in text
    assert "t_fleetobs_empty_seconds_quantile" not in text
    assert "NaN" not in text.split("t_fleetobs_empty_seconds")[-1][:200]


# --- window-DAG attribution on synthetic async traces ------------------------


def _win_seg(name, node, start, end, rnd, span_id="", parent_id="", **extra):
    return Seg(
        name=name, node=node, start_s=start, end_s=end, span_id=span_id,
        parent_id=parent_id, trace_id="t", round=rnd, extra=extra,
    )


def _synthetic_async_trace(windows=3, slow="slow", fast="fast", slow_fit=3.0):
    """Two contributors; ``slow``'s fit is slow_fit per window. The fast
    node closes each window when the slow contribution arrives (the recv's
    parent crosses the wire to the slow sender's diffuse span)."""
    segs = []
    t_fast = 0.0
    t_slow = 0.0
    for w in range(windows):
        # Fast node: quick fit, diffuse, then a long wait for the slow frame.
        segs.append(_win_seg("fit", fast, t_fast, t_fast + 0.5, w))
        segs.append(
            _win_seg("diffuse:async_model", fast, t_fast + 0.5, t_fast + 0.6, w)
        )
        # Slow node: long fit, then diffuse (the frame that closes the wait).
        segs.append(
            _win_seg("fit", slow, t_slow, t_slow + slow_fit, w, span_id=f"sf{w}")
        )
        segs.append(
            _win_seg(
                "diffuse:async_model", slow, t_slow + slow_fit,
                t_slow + slow_fit + 0.1, w, span_id=f"sd{w}",
            )
        )
        arrive = t_slow + slow_fit + 0.05
        segs.append(
            _win_seg(
                "recv:async_model", fast, arrive, arrive + 0.02, w,
                span_id=f"r{w}", parent_id=f"sd{w}",
            )
        )
        segs.append(
            _win_seg("async_window_wait", fast, t_fast + 0.6, arrive + 0.05, w)
        )
        segs.append(
            _win_seg(
                "window_close", fast, arrive + 0.05, arrive + 0.05, w,
                reason="fill" if w < windows - 1 else "timeout",
                mean_lag=1.0, fill=2,
            )
        )
        t_fast = arrive + 0.1
        t_slow += slow_fit + 0.2
    return segs


def test_window_report_attributes_slow_contributor_and_reasons():
    an = CriticalPathAnalyzer(_synthetic_async_trace(windows=3), slack_s=0.5)
    assert an.has_windows()
    rep = an.window_report(staleness_alpha=0.5)
    assert rep["top_gating_contributor"] == "slow"
    assert rep["gating_counts"]["slow"] == 3
    assert rep["top_gating_fraction"] == 1.0
    assert rep["close_reason_counts"] == {"fill": 2, "timeout": 1}
    for w in ("0", "1", "2"):
        win = rep["windows"][w]
        assert win["gating_contributor"] == "slow"
        assert win["fill"] == 2
        assert win["mean_lag"] == 1.0
        # discount = 1 - (1+1)^-0.5
        assert win["staleness_discount"] == pytest.approx(
            1.0 - 2.0 ** -0.5, abs=1e-3
        )
    assert rep["wait_wall_s_total"] > 0
    # The full report nests the window view for async traces.
    assert "window_report" in an.report()


def test_window_report_absent_for_sync_traces():
    segs = [
        _win_seg("fit", "a", 0.0, 1.0, 0),
        _win_seg("diffuse:partial_model", "a", 1.0, 1.5, 0),
    ]
    an = CriticalPathAnalyzer(segs)
    assert not an.has_windows()
    assert "window_report" not in an.report()


# --- population snapshot (fused-mesh path) -----------------------------------


def test_population_snapshot_top_n_and_quantiles():
    n = 200
    rng = np.random.default_rng(0)
    lag = np.zeros(n)
    step = np.full(n, 0.01) + rng.normal(0, 1e-4, n)
    seeded = [7, 50, 199]
    lag[seeded] = 3.0
    step[seeded] = 0.05
    snap = population_snapshot(
        "mesh-sim",
        [f"vnode/{i:05d}" for i in range(n)],
        {"round_lag": lag, "step_time": step, "round": np.full(n, 5.0)},
        top_n=5,
    )
    top = list(snap["peers"])
    assert {f"vnode/{i:05d}" for i in seeded} <= set(top)
    # The observer rides the doc as its own row (wire parity: an
    # Observatory snapshot always includes self), so size = n + 1 and the
    # tracked set = top_n stragglers + the self row.
    assert "mesh-sim" in top and len(top) == 5 + 1
    assert snap["top_straggler"] in {f"vnode/{i:05d}" for i in seeded}
    assert snap["virtual"] is True
    assert snap["fleet"]["size"] == n + 1
    assert snap["fleet"]["overflow_peers"] == n - 5
    q = snap["fleet"]["quantiles"]["round_lag"]
    assert q["count"] == n and q["p99"] == pytest.approx(3.0, rel=0.1)
    with pytest.raises(ValueError):
        population_snapshot("x", ["a", "b"], {"round_lag": np.zeros(3)})


def test_mesh_simulation_validates_node_speed_shape():
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    n = 8
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 8, 4)).astype(np.float32)
    y = rng.integers(0, 2, size=(n, 8)).astype(np.int32)
    m = np.ones((n, 8), np.float32)
    model = mlp_model(input_shape=(4,), hidden_sizes=(4,), out_channels=2)
    with pytest.raises(ValueError, match="node_speed"):
        MeshSimulation(
            model, (x, y, m), test_data=(x[0], y[0]), batch_size=4,
            node_speed=np.ones(5, np.float32),
        )
    with pytest.raises(ValueError, match="> 0"):
        MeshSimulation(
            model, (x, y, m), test_data=(x[0], y[0]), batch_size=4,
            node_speed=np.zeros(n, np.float32),
        )


@pytest.mark.slow
def test_mesh_fleet_snapshot_flags_seeded_stragglers(tmp_path):
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    n = 16
    rng = np.random.default_rng(1)
    x = rng.normal(size=(n, 8, 4)).astype(np.float32)
    y = rng.integers(0, 2, size=(n, 8)).astype(np.int32)
    m = np.ones((n, 8), np.float32)
    speed = np.ones(n, np.float32)
    speed[[2, 9]] = 5.0
    model = mlp_model(input_shape=(4,), hidden_sizes=(4,), out_channels=2)
    sim = MeshSimulation(
        model, (x, y, m), test_data=(x[0], y[0]), train_set_size=4,
        batch_size=4, node_speed=speed, seed=0,
    )
    res = sim.run(rounds=2, warmup=False)
    path = str(tmp_path / "snap.json")
    snap = sim.fleet_snapshot(res, top_n=4, path=path)
    sim.close()
    assert {"vnode/00002", "vnode/00009"} <= set(snap["peers"])
    assert snap["top_straggler"] in ("vnode/00002", "vnode/00009")
    with open(path) as f:
        assert json.load(f)["fleet"]["size"] == n
