"""Native (C++) wire codec: build, byte-identity with the Python path,
CRC32 integrity (weights AND metadata), and graceful fallback."""

import ctypes

import numpy as np
import pytest

from p2pfl_tpu import native
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import DecodingParamsError
from p2pfl_tpu.ops.serialization import deserialize_arrays, serialize_arrays


def _arrays():
    rng = np.random.default_rng(0)
    return [
        rng.normal(size=(17, 33)).astype(np.float32),
        rng.integers(0, 255, size=(5,)).astype(np.uint8),
        np.float32(3.25),  # 0-d leaf
        rng.normal(size=(128, 64)).astype(np.float16),
    ]


def test_native_builds_and_loads():
    lib = native.get_lib()
    assert lib is not None, "g++ is in the image; the codec must build"


def test_native_and_python_paths_byte_identical():
    arrays = _arrays()
    meta = {"contributors": ["a", "b"], "num_samples": 7}
    assert native.get_lib() is not None
    buf_native = serialize_arrays(arrays, meta)
    assert isinstance(buf_native, bytearray)  # single-copy native path
    # NO_NATIVE now rides the validated Settings layer (env read at config
    # load), so runtime disabling goes through Settings.overridden.
    with Settings.overridden(NO_NATIVE=True):
        buf_python = serialize_arrays(arrays, meta)
    assert isinstance(buf_python, bytes)
    assert bytes(buf_native) == buf_python


def test_roundtrip_with_checksum():
    arrays = _arrays()
    buf = serialize_arrays(arrays, {"k": 1})
    out, meta = deserialize_arrays(buf)
    assert meta == {"k": 1}
    for a, b in zip(arrays, out):
        np.testing.assert_array_equal(np.asarray(a), b)


def test_tensor_corruption_detected():
    buf = bytearray(serialize_arrays(_arrays(), {}))
    buf[-3] ^= 0xFF  # flip a bit in the last tensor's bytes
    with pytest.raises(DecodingParamsError, match="CRC32"):
        deserialize_arrays(bytes(buf))


def test_metadata_corruption_detected():
    buf = bytearray(serialize_arrays(_arrays(), {"num_samples": 7}))
    # flip a bit inside the msgpack header region (right after the prefix)
    buf[20] ^= 0x01
    with pytest.raises(DecodingParamsError):
        deserialize_arrays(bytes(buf))


def test_checksum_optional():
    buf = bytearray(serialize_arrays(_arrays(), {}, checksum=False))
    buf[-3] ^= 0xFF
    out, _ = deserialize_arrays(bytes(buf))  # crc=0 -> unchecked
    assert len(out) == 4


def test_packed_size_matches_python_framing():
    lib = native.get_lib()
    assert lib is not None
    sizes = [17 * 33 * 4, 5, 4, 128 * 64 * 2]
    n = len(sizes)
    c_sizes = (ctypes.c_size_t * n)(*sizes)
    header_len = 123
    total = lib.pflt_packed_size(c_sizes, n, header_len)
    off = 14 + header_len  # magic + version + header_len + crc32
    off += (-off) % 64
    for s in sizes:
        off += s
        off += (-off) % 64
    assert total == off


def test_python_fallback_when_disabled():
    arrays = _arrays()
    with Settings.overridden(NO_NATIVE=True):
        buf = serialize_arrays(arrays, {"x": [1, 2]})
        out, meta = deserialize_arrays(buf)
    assert meta == {"x": [1, 2]}
    for a, b in zip(arrays, out):
        np.testing.assert_array_equal(np.asarray(a), b)
