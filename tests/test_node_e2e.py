"""End-to-end convergence tests (mirrors reference test/node_test.py:79-135):
multi-node training over the in-memory transport asserting (a) exact stage
history per round, (b) equal models across nodes, (c) final accuracy > 0.5
(reference asserts the same bar on real MNIST; we use the synthetic learnable
MNIST stand-in — zero egress)."""

import time

import pytest

from p2pfl_tpu.comm.grpc import GrpcCommunicationProtocol
from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol
from p2pfl_tpu.config import Settings
from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
from p2pfl_tpu.management.logger import logger
from p2pfl_tpu.models import mlp_model
from p2pfl_tpu.node import Node
from p2pfl_tpu.utils.utils import check_equal_models, wait_convergence

# The heavy scenarios run over BOTH transports (reference runs its whole e2e
# matrix over each protocol, node_test.py:79); "memory" is the in-process
# registry, "grpc" real localhost sockets.
PROTOCOLS = {
    "memory": InMemoryCommunicationProtocol,
    "grpc": GrpcCommunicationProtocol,
}


def _spawn(n, batch_size=32, protocol=None, **node_kw):
    data = synthetic_mnist(n_train=256 * n, n_test=128)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    kw = dict(batch_size=batch_size, **node_kw)
    if protocol is not None:
        kw["protocol"] = PROTOCOLS[protocol]
    nodes = [Node(mlp_model(seed=i), parts[i], **kw) for i in range(n)]
    for node in nodes:
        node.start()
    return nodes


def _wait_finished(nodes, timeout=240.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(
            not n.learning_in_progress() and n.learning_workflow is not None
            for n in nodes
        ):
            return
        time.sleep(0.2)
    raise TimeoutError("learning did not finish")


def _expected_history(rounds, trained_flags):
    hist = ["StartLearningStage"]
    for r in range(rounds):
        hist.append("VoteTrainSetStage")
        hist.append("TrainStage" if trained_flags[r] else "WaitAggregatedModelsStage")
        hist.append("GossipModelStage")
        hist.append("RoundFinishedStage")
    return hist


@pytest.mark.parametrize("n_nodes,rounds", [(2, 2)])
def test_e2e_convergence_small(n_nodes, rounds):
    Settings.RESOURCE_MONITOR_PERIOD = 0
    nodes = _spawn(n_nodes)
    try:
        nodes[1].connect(nodes[0].addr)
        wait_convergence(nodes, n_nodes - 1, wait=5)
        nodes[0].set_start_learning(rounds=rounds, epochs=1)
        _wait_finished(nodes)
        for node in nodes:
            hist = node.learning_workflow.history
            # every round is Vote -> (Train|WaitAgg) -> Gossip -> RoundFinished
            trained = [h == "TrainStage" for h in hist if h in ("TrainStage", "WaitAggregatedModelsStage")]
            assert hist == _expected_history(rounds, trained)
        check_equal_models(nodes)
        # Per-node FINAL accuracy (reference node_test.py:126-132 asserts the
        # last round's accuracy for every node, not a max over history).
        # Scope to this run's node addresses — the singleton logger
        # accumulates across tests, and each node logs under its own
        # experiment name.
        addrs = {n.addr for n in nodes}
        final_accs = {}
        for exp in logger.get_global_logs().values():
            for node_addr, node_metrics in exp.items():
                if node_addr not in addrs:
                    continue
                for name, vals in node_metrics.items():
                    if name == "test_acc" and vals:
                        rnd, acc = sorted(vals)[-1]
                        prev = final_accs.get(node_addr)
                        if prev is None or rnd >= prev[0]:
                            final_accs[node_addr] = (rnd, acc)
        assert set(final_accs) == addrs, final_accs
        for addr, (_, acc) in final_accs.items():
            assert acc > 0.5, f"node {addr} final test_acc {acc} <= 0.5"
    finally:
        for node in nodes:
            node.stop()


@pytest.mark.slow
def test_e2e_line_topology_with_non_trainers():
    """4 nodes, line connection, committee of 2 — some nodes must take the
    WaitAggregatedModelsStage path and still converge (fast variant of the
    reference 6x3 case; the full shape runs in
    test_e2e_six_node_line_three_rounds)."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    n_nodes, rounds = 4, 2
    with Settings.overridden(TRAIN_SET_SIZE=2):
        nodes = _spawn(n_nodes)
        try:
            for i in range(1, n_nodes):
                nodes[i].connect(nodes[i - 1].addr)
            wait_convergence(nodes, n_nodes - 1, wait=8)
            nodes[0].set_start_learning(rounds=rounds, epochs=1)
            _wait_finished(nodes)
            waiters = sum(
                "WaitAggregatedModelsStage" in n.learning_workflow.history for n in nodes
            )
            assert waiters >= 1  # committee smaller than population
            check_equal_models(nodes)
        finally:
            for node in nodes:
                node.stop()


@pytest.mark.slow
def test_e2e_six_node_line_three_rounds():
    """The reference's heavy parity case for real: 6 nodes in a line,
    committee of 4, 3 rounds (node_test.py's 6x3 matrix point). Non-trainers
    take WaitAggregatedModelsStage in every round, gossip crosses multi-hop
    non-direct neighbors, and all six models converge equal."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    n_nodes, rounds = 6, 3
    with Settings.overridden(TRAIN_SET_SIZE=4):
        nodes = _spawn(n_nodes)
        try:
            for i in range(1, n_nodes):
                nodes[i].connect(nodes[i - 1].addr)
            wait_convergence(nodes, n_nodes - 1, wait=10)
            nodes[0].set_start_learning(rounds=rounds, epochs=1)
            _wait_finished(nodes, timeout=240)  # reference budget (:105)
            waiters = sum(
                "WaitAggregatedModelsStage" in n.learning_workflow.history
                for n in nodes
            )
            assert waiters >= 1
            for n in nodes:
                hist = n.learning_workflow.history
                trained = [
                    h == "TrainStage"
                    for h in hist
                    if h in ("TrainStage", "WaitAggregatedModelsStage")
                ]
                assert hist == _expected_history(rounds, trained)
            check_equal_models(nodes)
        finally:
            for node in nodes:
                node.stop()


@pytest.mark.slow
def test_stop_learning_mid_run():
    Settings.RESOURCE_MONITOR_PERIOD = 0
    nodes = _spawn(2)
    try:
        nodes[1].connect(nodes[0].addr)
        wait_convergence(nodes, 1, wait=5)
        nodes[0].set_start_learning(rounds=50, epochs=1)
        time.sleep(1.0)
        nodes[0].set_stop_learning()
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(not n.learning_in_progress() for n in nodes):
                break
            time.sleep(0.2)
        assert all(not n.learning_in_progress() for n in nodes)
    finally:
        for node in nodes:
            node.stop()


@pytest.mark.slow
def test_e2e_over_grpc_transport():
    """Full convergence over the real gRPC transport (reference runs its e2e
    matrix over both transports, node_test.py:79)."""
    from p2pfl_tpu.comm.grpc import GrpcCommunicationProtocol

    Settings.RESOURCE_MONITOR_PERIOD = 0
    data = synthetic_mnist(n_train=256, n_test=64)
    parts = data.generate_partitions(2, RandomIIDPartitionStrategy)
    nodes = [
        Node(
            mlp_model(seed=i),
            parts[i],
            batch_size=32,
            protocol=GrpcCommunicationProtocol,
        )
        for i in range(2)
    ]
    for node in nodes:
        node.start()
    try:
        nodes[1].connect(nodes[0].addr)
        wait_convergence(nodes, 1, wait=5)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        _wait_finished(nodes, timeout=120)
        check_equal_models(nodes)
    finally:
        for node in nodes:
            node.stop()


@pytest.mark.parametrize("protocol", ["memory", "grpc"])
@pytest.mark.slow
def test_e2e_with_int8_wire_compression(protocol):
    """Federation converges with int8-quantized gossip (4x smaller weight
    frames; no reference analogue — it always gossips full-precision
    pickle, p2pfl_model.py:71-86). Over gRPC the quantized frames really
    cross protobuf serialization + sockets."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    with Settings.overridden(WIRE_COMPRESSION="int8"):
        nodes = _spawn(2, protocol=protocol)
        try:
            nodes[1].connect(nodes[0].addr)
            wait_convergence(nodes, 1, wait=5)
            nodes[0].set_start_learning(rounds=2, epochs=1)
            _wait_finished(nodes)
            check_equal_models(nodes)
            for node in nodes:
                acc = node.learner.evaluate().get("test_acc")
                assert acc is not None and acc > 0.5, acc
        finally:
            for node in nodes:
                node.stop()


@pytest.mark.parametrize("protocol", ["memory", "grpc"])
@pytest.mark.slow
def test_node_down_during_learning(protocol):
    """Kill a node mid-experiment: survivors detect the death via heartbeats
    and finish the remaining rounds through vote/aggregation timeouts with
    equal models. The reference ships this scenario DISABLED
    (_test_node_down_on_learning, node_test.py:160-180); here it runs — over
    both transports (a gRPC crash leaves a dead socket, the harder case)."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    nodes = _spawn(3, protocol=protocol)
    try:
        nodes[1].connect(nodes[0].addr)
        nodes[2].connect(nodes[0].addr)
        wait_convergence(nodes, 2, wait=5)
        nodes[0].set_start_learning(rounds=3, epochs=1)
        time.sleep(1.5)  # let round 0 get going, then crash a participant
        # Simulate an UNANNOUNCED crash: silence the node's threads and
        # server without the graceful disconnect notification that
        # Node.stop() sends — the survivors must notice via the heartbeat
        # staleness sweep, which is exactly what's under test.
        crashed = nodes[2].protocol
        crashed._running = False
        crashed.heartbeater.stop()
        crashed.gossiper.stop()
        crashed._server_stop()
        survivors = nodes[:2]
        _wait_finished(survivors, timeout=150)
        # the dead node is gone from every survivor's view
        for n in survivors:
            assert nodes[2].addr not in n.protocol.get_neighbors(only_direct=False)
        check_equal_models(survivors)
        for n in survivors:
            acc = n.learner.evaluate().get("test_acc")
            assert acc is not None and acc > 0.5, acc
    finally:
        for node in nodes:
            node.stop()


@pytest.mark.slow
def test_e2e_scaffold_with_wire_compression():
    """SCAFFOLD federation under bf16 wire compression: the weight tensors
    compress but the control-variate deltas ride the frame METADATA
    (ndarray-tagged, never compressed), so the scaffold server math stays
    full precision. Proves no interaction bug between the codec and the
    additional_info side channel."""
    from p2pfl_tpu.learning.aggregators import Scaffold

    Settings.RESOURCE_MONITOR_PERIOD = 0
    with Settings.overridden(WIRE_COMPRESSION="bf16"):
        data = synthetic_mnist(n_train=512, n_test=128)
        parts = data.generate_partitions(2, RandomIIDPartitionStrategy)
        nodes = [
            Node(mlp_model(seed=i), parts[i], aggregator=Scaffold(), batch_size=32)
            for i in range(2)
        ]
        for node in nodes:
            node.start()
        try:
            nodes[1].connect(nodes[0].addr)
            wait_convergence(nodes, 1, wait=5)
            nodes[0].set_start_learning(rounds=2, epochs=2)
            _wait_finished(nodes)
            check_equal_models(nodes)
            for node in nodes:
                acc = node.learner.evaluate().get("test_acc")
                assert acc is not None and acc > 0.5, acc
        finally:
            for node in nodes:
                node.stop()


@pytest.mark.slow
def test_e2e_krum_excludes_poisoned_node():
    """Nodes-mode robust aggregation composition (BASELINE config #4 over
    the real protocol): one of four nodes trains on label-flipped data;
    Krum-aggregating nodes converge to a model that still learns. The
    reference ships Krum only as an unrunnable stub — here the rule runs
    inside a live gossip federation."""
    from p2pfl_tpu.learning.aggregators import Krum
    from p2pfl_tpu.learning.dataset import flip_labels

    Settings.RESOURCE_MONITOR_PERIOD = 0
    data = synthetic_mnist(n_train=1024, n_test=128)
    parts = data.generate_partitions(4, RandomIIDPartitionStrategy)
    parts[3] = flip_labels(parts[3], num_classes=10)  # the Byzantine node
    nodes = [
        Node(
            mlp_model(seed=i),
            parts[i],
            aggregator=Krum(num_byzantine=1, num_selected=2),
            batch_size=32,
        )
        for i in range(4)
    ]
    for node in nodes:
        node.start()
    try:
        for i in range(1, 4):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, 3, wait=8)
        nodes[0].set_start_learning(rounds=2, epochs=1)
        _wait_finished(nodes)
        check_equal_models(nodes)
        # The point of the rule: the Byzantine node's model was EXCLUDED —
        # provenance on the aggregated model (robust.py stamps only the
        # selected contributors) must not contain its address. Accuracy
        # alone can't catch Krum degrading to average-everything (3 clean +
        # 1 flipped still clears 0.5).
        # (raw attribute: get_contributors() raises on empty, which would
        # mask the crafted message below)
        contributors = nodes[0].learner.get_model().contributors
        assert contributors, "aggregated model lost provenance"
        assert nodes[3].addr not in contributors, contributors
        # test split is clean: accuracy measures true performance
        acc = nodes[0].learner.evaluate()["test_acc"]
        assert acc > 0.5, acc
    finally:
        for node in nodes:
            node.stop()
