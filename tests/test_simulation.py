"""Mesh simulation backend tests (replaces the reference's Ray simulation
tests, test/simulation/*): committee election semantics, convergence,
determinism, sharding over the 8-device CPU mesh."""

import numpy as np
import pytest

import jax

from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
from p2pfl_tpu.models import mlp_model
from p2pfl_tpu.ops import aggregation as agg_ops
from p2pfl_tpu.parallel.mesh import make_mesh
from p2pfl_tpu.parallel.simulation import MeshSimulation, vote_committee


@pytest.fixture(scope="module")
def parts16():
    data = synthetic_mnist(n_train=1600, n_test=256)
    return data.generate_partitions(16, RandomIIDPartitionStrategy)


def test_vote_committee_size_and_range():
    committee = np.asarray(vote_committee(jax.random.key(0), 20, 4))
    assert committee.shape == (4,)
    assert len(set(committee.tolist())) == 4
    assert committee.min() >= 0 and committee.max() < 20


def test_vote_committee_varies_with_key():
    a = np.asarray(vote_committee(jax.random.key(1), 20, 4))
    b = np.asarray(vote_committee(jax.random.key(2), 20, 4))
    assert a.tolist() != b.tolist()


def test_vote_committee_deterministic():
    a = np.asarray(vote_committee(jax.random.key(3), 20, 4))
    b = np.asarray(vote_committee(jax.random.key(3), 20, 4))
    np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_simulation_converges(parts16):
    sim = MeshSimulation(
        mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=1
    )
    res = sim.run(rounds=3, epochs=1)
    assert res.rounds == 3
    assert len(res.test_acc) == 3
    assert res.test_acc[-1] > 0.5
    assert res.committees.shape == (3, 4)
    # committees rotate between rounds (with overwhelming probability)
    assert len({tuple(c) for c in res.committees.tolist()}) > 1


@pytest.mark.slow
def test_simulation_rounds_chunking_equivalent(parts16):
    """rounds_per_call must not change the math, only the dispatch."""
    sim1 = MeshSimulation(mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=7)
    r1 = sim1.run(rounds=2, epochs=1, rounds_per_call=1)
    sim2 = MeshSimulation(mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=7)
    r2 = sim2.run(rounds=2, epochs=1, rounds_per_call=2)
    # NOTE: key-splitting differs between chunkings (split per call), so
    # committees may differ; what must hold is shape/finite metrics and that
    # both learn.
    assert r1.rounds == r2.rounds == 2
    assert np.isfinite(r1.test_loss).all() and np.isfinite(r2.test_loss).all()


@pytest.mark.slow
def test_simulation_on_explicit_tp_mesh(parts16):
    """nodes x model mesh: population DP + tensor parallelism compile+run,
    with the kernels *actually* partitioned over the ``model`` axis (a silent
    fallback to full replication must fail this test)."""
    mesh = make_mesh((4, 2), ("nodes", "model"))
    sim = MeshSimulation(
        mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=1, mesh=mesh
    )
    # At least one dense kernel must be sharded over the model axis: its
    # addressable shards must cover only 1/tp of the output dim.
    tp_leaves = [
        leaf
        for leaf in jax.tree.leaves(sim.params_stack)
        if leaf.ndim >= 3 and "model" in leaf.sharding.spec
    ]
    assert tp_leaves, "no parameter leaf is partitioned over the model axis"
    for leaf in tp_leaves:
        shard_shape = leaf.addressable_shards[0].data.shape
        assert shard_shape[-1] == leaf.shape[-1] // 2, (
            f"leaf {leaf.shape} shard {shard_shape}: output dim not split over model axis"
        )
        assert shard_shape[0] == leaf.shape[0] // 4  # nodes axis split too

    res = sim.run(rounds=1, epochs=1, warmup=False)
    assert np.isfinite(res.test_loss[-1])
    # Population state must still be TP-sharded after the round (the round
    # body must not have gathered everything onto every device).
    post = [
        leaf
        for leaf in jax.tree.leaves(sim.params_stack)
        if leaf.ndim >= 3 and "model" in leaf.sharding.spec
    ]
    assert post, "round body dropped the model-axis sharding"


@pytest.mark.slow
def test_simulation_all_nodes_equal_after_diffusion(parts16):
    sim = MeshSimulation(mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=1)
    sim.run(rounds=1, epochs=1, warmup=False)
    m0 = sim.final_model(node=0).get_parameters()
    m7 = sim.final_model(node=7).get_parameters()
    for a, b in zip(m0, m7):
        np.testing.assert_allclose(a, b, atol=1e-6)


@pytest.mark.slow
def test_simulation_median_aggregation(parts16):
    sim = MeshSimulation(
        mlp_model(seed=0),
        parts16,
        train_set_size=4,
        batch_size=32,
        seed=1,
        aggregate_fn=lambda stacked, w: agg_ops.fedmedian(stacked),
    )
    res = sim.run(rounds=2, epochs=1, warmup=False)
    assert res.test_acc[-1] > 0.3


@pytest.mark.slow
def test_simulation_dirichlet_noniid():
    """BASELINE.json config #2 shape (non-IID leg): Dirichlet(0.1)
    partitions still converge under FedAvg on the mesh. (The CNN leg is
    covered by test_cnn_learner_convergence in test_learner.py — bf16 convs
    under vmap+scan compile for minutes on the virtual CPU mesh, so the
    model family and the partition skew are tested through separate
    cheap paths.)"""
    from p2pfl_tpu.learning.dataset import DirichletPartitionStrategy

    data = synthetic_mnist(n_train=1600, n_test=256)
    parts = data.generate_partitions(8, DirichletPartitionStrategy, alpha=0.1)
    sim = MeshSimulation(mlp_model(seed=0), parts, train_set_size=4, batch_size=32, seed=2)
    res = sim.run(rounds=3, epochs=1, warmup=False)
    assert res.test_acc[-1] > 0.5, res.test_acc


@pytest.mark.slow
def test_simulation_krum_tolerates_poisoned_nodes():
    """BASELINE.json config #4 shape: label-poisoned (Byzantine) nodes;
    Krum aggregation keeps the federation learning."""
    import jax.numpy as jnp

    from p2pfl_tpu.parallel.simulation import _stack_partitions

    data = synthetic_mnist(n_train=1600, n_test=256)
    parts = data.generate_partitions(16, RandomIIDPartitionStrategy)
    x, y, mask = _stack_partitions(parts)
    rng = np.random.default_rng(0)
    for bad in (0, 1):  # 2/16 adversarial: random labels
        y[bad] = rng.integers(0, 10, size=y[bad].shape)

    sim = MeshSimulation(
        mlp_model(seed=0),
        (x, y, mask),
        test_data=parts[0].export_arrays(train=False),
        train_set_size=4,
        batch_size=32,
        seed=3,
        aggregate_fn=lambda stacked, w: agg_ops.krum(stacked, w, num_byzantine=1)[0],
    )
    res = sim.run(rounds=4, epochs=1, warmup=False)
    assert res.test_acc[-1] > 0.5, res.test_acc


@pytest.mark.slow
def test_simulation_fedprox(parts16):
    """BASELINE.json config #5 shape: FedProx proximal term in the jitted
    local step — converges, and a huge mu visibly constrains movement."""
    sim = MeshSimulation(
        mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=1,
        fedprox_mu=0.01,
    )
    res = sim.run(rounds=2, epochs=1, warmup=False)
    assert res.test_acc[-1] > 0.5

    import jax

    before = jax.tree.leaves(MeshSimulation(
        mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=1
    ).params_stack)[0]

    def movement(mu):
        s = MeshSimulation(
            mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=1,
            fedprox_mu=mu,
        )
        s.run(rounds=1, epochs=1, warmup=False)
        after = jax.tree.leaves(s.params_stack)[0]
        return float(np.abs(np.asarray(after) - np.asarray(before)).max())

    assert movement(100.0) < movement(0.0)


@pytest.mark.slow
def test_simulation_scaffold(parts16):
    """Sim-mode SCAFFOLD (BASELINE.json config #3's aggregator leg): control
    variates ride the scan carry, the federation converges, and the
    variates actually move."""
    sim = MeshSimulation(
        mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=1,
        algorithm="scaffold", lr=0.05,  # scaffold defaults to SGD (option-II variate math)
    )
    res = sim.run(rounds=3, epochs=1, warmup=False)
    assert res.test_acc[-1] > 0.5, res.test_acc
    # committee members' control variates are nonzero after training
    c_leaf = np.asarray(jax.tree.leaves(sim.c_stack)[0])
    assert np.abs(c_leaf).max() > 0
    cg_leaf = np.asarray(jax.tree.leaves(sim.c_global)[0])
    assert np.abs(cg_leaf).max() > 0
    # all nodes still hold the same model after diffusion
    m0 = sim.final_model(node=0).get_parameters()
    m9 = sim.final_model(node=9).get_parameters()
    for a, b in zip(m0, m9):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_simulation_scaffold_rejects_bad_combos(parts16):
    with pytest.raises(ValueError):
        MeshSimulation(
            mlp_model(seed=0), parts16, algorithm="scaffold",
            aggregate_fn=lambda s, w: s,
        )
    with pytest.raises(ValueError):
        MeshSimulation(
            mlp_model(seed=0), parts16, algorithm="scaffold", per_node_init=True
        )
    with pytest.raises(ValueError):
        MeshSimulation(mlp_model(seed=0), parts16, algorithm="fedscram")
    with pytest.raises(ValueError):
        import optax

        MeshSimulation(
            mlp_model(seed=0), parts16, algorithm="scaffold",
            optimizer=optax.sgd(0.1),
        )


@pytest.mark.slow
def test_simulation_with_dp_sgd():
    """Mesh simulation with DP-SGD local training (per-example clip +
    Gaussian noise inside the jitted round) still learns; no reference
    analogue — p2pfl has no privacy machinery."""
    from p2pfl_tpu.models import mlp_model

    data = synthetic_mnist(n_train=512, n_test=128)
    parts = data.generate_partitions(4, RandomIIDPartitionStrategy)
    sim = MeshSimulation(
        mlp_model(seed=0), parts, train_set_size=4, batch_size=32, seed=0,
        lr=3e-3, dp_clip_norm=1.0, dp_noise_multiplier=0.2,
    )
    res = sim.run(rounds=5, epochs=2, warmup=False)
    assert np.isfinite(res.test_loss[-1])
    assert res.test_acc[-1] > 0.5, res.test_acc


@pytest.mark.slow
def test_simulation_lm_with_dp_sgd():
    """DP-SGD on the federated causal-LM path: the privacy unit is one
    sequence (each batch row clipped as a whole)."""
    from p2pfl_tpu.models import transformer_lm_model

    rng = np.random.default_rng(0)
    seqs = (np.arange(16 * 32).reshape(16, 32) + rng.integers(0, 3, (16, 1))) % 64
    x = seqs.reshape(4, 4, 32).astype(np.int32)  # [nodes, seqs, L]
    y = np.zeros((4, 4), np.int32)  # unused for lm
    m = np.ones((4, 4), np.float32)
    lm = transformer_lm_model(
        seed=0, seq_len=32, vocab_size=64, num_layers=1, num_heads=2, embed_dim=32
    )
    sim = MeshSimulation(
        lm, (x, y, m), test_data=(x[0], None), train_set_size=2, batch_size=2,
        seed=0, task="lm", dp_clip_norm=1.0, dp_noise_multiplier=0.1, lr=5e-3,
    )
    res = sim.run(rounds=3, epochs=1, warmup=False)
    assert np.isfinite(res.test_loss[-1])
    assert res.test_loss[-1] < res.test_loss[0]  # it learns under DP
    assert sim.privacy_spent()["epsilon"] > 0


@pytest.mark.slow
def test_eval_every_reports_only_evaluated_rounds():
    parts8 = synthetic_mnist(n_train=512, n_test=64).generate_partitions(
        8, RandomIIDPartitionStrategy
    )
    sim = MeshSimulation(mlp_model(seed=0), parts8, train_set_size=4, batch_size=32, seed=3)
    res = sim.run(rounds=5, epochs=1, warmup=False, eval_every=2)
    # evaluated at absolute rounds 1, 3, 4(final): 3 entries, all finite
    assert len(res.test_acc) == 3
    assert all(np.isfinite(a) for a in res.test_acc)
    assert res.rounds == 5

    # chunk-invariant: same cadence when rounds are split across calls
    sim2 = MeshSimulation(mlp_model(seed=0), parts8, train_set_size=4, batch_size=32, seed=3)
    res2 = sim2.run(rounds=5, epochs=1, warmup=False, eval_every=2, rounds_per_call=2)
    assert len(res2.test_acc) == 3
    np.testing.assert_allclose(res.test_acc, res2.test_acc, atol=1e-5)


def test_indivisible_population_pads_and_stays_sharded():
    """N % mesh-nodes != 0 used to de-shard every population buffer
    (replication, with a loud warning — round-3 verdict). Auto-padding
    replaced that fallback: the population is padded to the mesh axis with
    zero-weight fillers and every stacked buffer stays node-sharded."""
    parts6 = synthetic_mnist(n_train=384, n_test=64).generate_partitions(
        6, RandomIIDPartitionStrategy
    )
    sim = MeshSimulation(
        mlp_model(seed=0), parts6, train_set_size=2, batch_size=32, seed=0
    )
    assert sim.logical_num_nodes == 6
    assert sim.num_nodes % sim.mesh.shape["nodes"] == 0
    # Stacked leaves are sharded over the (padded) nodes axis, not replicated.
    leaf = jax.tree.leaves(sim.params_stack)[0]
    assert leaf.shape[0] == sim.num_nodes
    assert "nodes" in leaf.sharding.spec
    # Fillers carry zero samples: they cannot contribute aggregate weight.
    assert float(np.asarray(sim.sample_mask[6:]).sum()) == 0.0


@pytest.mark.slow
def test_krum_defends_model_poisoning(parts16):
    """4/16 nodes corrupt their model update in-program (10x-scaled delta —
    an overshoot attack that actively diverges the mean); Multi-Krum keeps
    learning while undefended FedAvg is wrecked by the same attack."""
    byz = np.zeros(16, np.float32)
    byz[[3, 7, 11, 15]] = 1.0

    def run(agg_fn, attack):
        sim = MeshSimulation(
            mlp_model(seed=0), parts16, train_set_size=4, batch_size=32,
            seed=5, aggregate_fn=agg_fn, byzantine_mask=byz,
            byzantine_attack=attack,
        )
        return sim.run(rounds=4, epochs=1, warmup=False).test_acc[-1]

    # f=2 Byzantine budget: with 4/16 poisoned nodes, a committee of 4
    # draws >=2 attackers in ~24% of rounds — f=1 would average a poisoned
    # update into those rounds (observed: acc collapses to ~0.3).
    krum = lambda s, w: agg_ops.krum(s, w, num_byzantine=2, num_selected=2)[0]  # noqa: E731
    krum_scaled = run(krum, "scaled")
    fedavg_scaled = run(agg_ops.fedavg, "scaled")
    krum_signflip = run(krum, "signflip")
    assert krum_scaled > 0.5, (krum_scaled, fedavg_scaled)
    assert krum_signflip > 0.5, krum_signflip
    assert krum_scaled > fedavg_scaled + 0.1, (krum_scaled, fedavg_scaled)


def test_byzantine_mask_rejects_scaffold(parts16):
    with pytest.raises(ValueError, match="robust"):
        MeshSimulation(
            mlp_model(seed=0), parts16, algorithm="scaffold",
            byzantine_mask=np.ones(16, np.float32),
        )


@pytest.mark.slow
def test_scale_bench_body_rehearsal():
    """bench.py --scale-500's measurable body (probe-free) runs end-to-end
    at reduced scale on the CPU mesh: on-device Dirichlet data generation,
    FedProx, 12.5% committee sampling, eval_every cadence. De-risks the
    real-TPU mode so its first contact with hardware can't be a crash."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    out = bench.scale_bench_body("cpu-rehearsal", n=64, s=64, rounds=4, committee=8)
    assert out["metric"] == "sec_per_round_64node_dirichlet_fedprox_synthetic"
    assert out["value"] > 0
    assert out["extra"]["final_test_acc"] > 0.3  # observed 0.57
    assert "64 nodes" in out["extra"]["note"]


@pytest.mark.slow
def test_attn_bench_body_rehearsal():
    """bench.py --attn's measurable body runs end-to-end at tiny scale on
    the CPU mesh (flash falls back to Pallas interpret mode): all three
    variants produce timings, the fwd+bwd path computes full q/k/v grads,
    and the headline reflects the flash fwd throughput."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    out = bench.attn_bench_body("cpu-rehearsal", seqs=(128,), iters_cap=8)
    assert out["metric"] == "attention_kernel_microbench"
    row = out["extra"]["per_seq"]["128"]
    for variant in ("dense", "blockwise", "flash"):
        assert isinstance(row[f"fwd_{variant}_ms"], float)
        assert isinstance(row[f"fwdbwd_{variant}_ms"], float)
    assert out["value"] == row["fwd_flash_tflops"]


def _tiny_stacked(n=8, s=64):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, s, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, size=(n, s)).astype(np.int32)
    return x, y, np.ones((n, s), np.float32)


@pytest.mark.slow
def test_pristine_warmup_donate_reinit_is_bit_identical():
    """run(warmup=True) on a fresh simulation donates the real state to the
    warmup execution (peak HBM ~1x state instead of the copies path's ~2x —
    the difference between ResNet-18 at 56 nodes fitting a 16 GB chip or
    OOMing) and rebuilds the identical initial population, so results match
    a warmup-free run bit for bit."""
    x, y, m = _tiny_stacked()
    sim1 = MeshSimulation(
        mlp_model(seed=0), (x, y, m), test_data=(x[0], y[0]),
        train_set_size=4, batch_size=16, seed=1,
    )
    assert sim1._pristine
    sim1.run(rounds=2, epochs=1, warmup=True, rounds_per_call=2)
    assert not sim1._pristine  # trained state: next warmup must copy
    sim2 = MeshSimulation(
        mlp_model(seed=0), (x, y, m), test_data=(x[0], y[0]),
        train_set_size=4, batch_size=16, seed=1,
    )
    sim2.run(rounds=2, epochs=1, warmup=False, rounds_per_call=2)
    for a, b in zip(jax.tree.leaves(sim1.params_stack), jax.tree.leaves(sim2.params_stack)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_closed_simulation_raises_everywhere():
    """close() releases buffers AND data; every later entry point must say
    'closed', not crash deep in tracing or point at load_from (checkpoints
    do not carry the training data close() dropped)."""
    x, y, m = _tiny_stacked()
    with MeshSimulation(
        mlp_model(seed=0), (x, y, m), train_set_size=4, batch_size=16, seed=1
    ) as sim:
        pass  # context exit closes
    assert sim.params_stack is None and sim.x is None
    with pytest.raises(RuntimeError, match="closed"):
        sim.run(rounds=1)
    with pytest.raises(RuntimeError, match="closed"):
        sim.final_model()
    with pytest.raises(RuntimeError, match="closed"):
        sim.load_from(checkpointer=None)


@pytest.mark.slow
def test_round_cost_analysis_and_lm_mfu_rehearsal():
    """VERDICT r4 #6 groundwork: XLA cost analysis of the compiled round
    program (the production-model MFU source) works on the CPU mesh, and
    bench.py --lm-mfu's measurable body runs end-to-end at tiny scale."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    out = bench.lm_mfu_body(
        "cpu-rehearsal", nodes=4, seqs=8, seq_len=128, rounds=2,
        vocab=256, layers=2, heads=2, embed=64, batch=4,
    )
    assert out["metric"] == "transformer_lm_federated_round_mfu"
    row = out["extra"]["mfu_row"]
    # CPU backends expose cost analysis too; if this ever regresses the
    # bench degrades gracefully, but the rehearsal should catch it.
    assert "error" not in row, row
    assert row["flops_per_round"] > 0
    assert out["extra"]["sec_per_round"] > 0


@pytest.mark.slow
def test_train_path_probe_rehearsal():
    """bench.py's isolated fit-path probe (the '66-83%' artifact row) runs
    end-to-end at tiny scale: vmapped member steps under one scan, loss
    finite, throughput positive."""
    import os
    import sys

    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench
    from p2pfl_tpu.models import mlp_model

    x = np.random.default_rng(0).random((4, 256, 28, 28), dtype=np.float32)
    y = np.random.default_rng(1).integers(0, 10, (4, 256)).astype(np.int32)
    model = mlp_model(seed=0)
    out = bench._train_path_probe(
        "cpu-rehearsal", model, jnp.asarray(x), jnp.asarray(y),
        matmul_params=784 * 256 + 256 * 128 + 128 * 10,
        members=4, batch=64, steps=4,
    )
    assert "error" not in out, out
    assert out["achieved_tflops"] > 0
    assert out["seconds"] > 0


@pytest.mark.slow
def test_simulation_geometric_median_tolerates_poisoned_nodes():
    """The geomedian rule composes with the mesh simulation's in-program
    model poisoning: 2/16 nodes mount the 10x-scaled-delta attack and the
    federation still learns (rotation-invariant robustness, no committee
    subset selection)."""
    data = synthetic_mnist(n_train=1600, n_test=256)
    parts = data.generate_partitions(16, RandomIIDPartitionStrategy)
    mask = np.zeros(16, np.float32)
    mask[[0, 1]] = 1.0
    sim = MeshSimulation(
        mlp_model(seed=0),
        parts,
        train_set_size=4,
        batch_size=32,
        seed=3,
        byzantine_mask=mask,
        byzantine_attack="scaled",
        aggregate_fn=agg_ops.geometric_median,
    )
    res = sim.run(rounds=4, epochs=1, warmup=False)
    assert res.test_acc[-1] > 0.5, res.test_acc


def test_server_optimizer_validations():
    """FedOpt composition rules: no scaffold, no per-node init, known names."""
    import optax

    data = synthetic_mnist(n_train=256, n_test=64)
    parts = data.generate_partitions(4, RandomIIDPartitionStrategy)
    with pytest.raises(ValueError, match="scaffold"):
        MeshSimulation(
            mlp_model(seed=0), parts, algorithm="scaffold",
            server_optimizer=optax.sgd(1.0),
        )
    with pytest.raises(ValueError, match="per_node_init"):
        MeshSimulation(
            mlp_model(seed=0), parts, per_node_init=True,
            server_optimizer="fedadam",
        )
    with pytest.raises(ValueError, match="unknown server_optimizer"):
        MeshSimulation(mlp_model(seed=0), parts, server_optimizer="fedsgd")


@pytest.mark.slow
def test_server_sgd_unit_lr_equals_plain_fedavg(parts16):
    """FedOpt with server sgd(1.0) must reduce exactly to plain FedAvg
    (updates = -(x - agg), so x + updates == agg) — the identity that
    anchors the pseudo-gradient sign convention."""
    import optax

    kw = dict(train_set_size=4, batch_size=32, seed=9)
    plain = MeshSimulation(mlp_model(seed=0), parts16, **kw)
    r_plain = plain.run(rounds=2, epochs=1, warmup=False)
    srv = MeshSimulation(
        mlp_model(seed=0), parts16, server_optimizer=optax.sgd(1.0), **kw
    )
    r_srv = srv.run(rounds=2, epochs=1, warmup=False)
    assert r_srv.test_acc == pytest.approx(r_plain.test_acc, abs=1e-5)
    for a, b in zip(
        jax.tree.leaves(plain.params_stack), jax.tree.leaves(srv.params_stack)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.slow
@pytest.mark.parametrize(
    "name,server_lr",
    [("fedavgm", 1.0), ("fedadam", 0.003), ("fedyogi", 0.01)],
)
def test_fedopt_variants_converge(name, server_lr, parts16):
    """Reddi et al. server optimizers train on the mesh (server state rides
    the c_global carry through the fused-round scan). Server lrs are the
    probed sweet spots for this task — adaptive variants normalize the
    tiny pseudo-gradient, so lrs near 1.0 overshoot (observed divergence
    at 0.1)."""
    sim = MeshSimulation(
        mlp_model(seed=0), parts16, train_set_size=4, batch_size=32, seed=2,
        server_optimizer=name, server_lr=server_lr,
    )
    res = sim.run(rounds=4, epochs=1, warmup=False, rounds_per_call=4)
    assert res.test_acc[-1] > 0.5, (name, res.test_acc)


@pytest.mark.slow
def test_fedopt_composes_with_robust_aggregation():
    """Server momentum over a robust aggregate: geomedian filters the
    10x-scaled-delta attackers, fedavgm's server momentum then smooths the
    filtered update — the federation learns under attack."""
    data = synthetic_mnist(n_train=1600, n_test=256)
    parts = data.generate_partitions(16, RandomIIDPartitionStrategy)
    mask = np.zeros(16, np.float32)
    mask[[3, 11]] = 1.0
    sim = MeshSimulation(
        mlp_model(seed=0), parts, train_set_size=4, batch_size=32, seed=4,
        byzantine_mask=mask, byzantine_attack="scaled",
        aggregate_fn=agg_ops.geometric_median,
        server_optimizer="fedavgm", server_lr=1.0,
    )
    res = sim.run(rounds=4, epochs=1, warmup=False, rounds_per_call=2)
    assert res.test_acc[-1] > 0.5, res.test_acc


@pytest.mark.slow
def test_clip_update_norm_bounds_deltas_and_learns_under_attack():
    """Norm bounding: plain FedAvg with clip_update_norm still learns under
    the 10x-scaled-delta attack, and the clip provably binds — a clip far
    below the honest delta norm visibly throttles training. (At this MLP/
    MNIST scale undefended FedAvg eventually recovers too, so the defense
    contrast lives in the CIFAR bench; here we pin the mechanism.)"""
    data = synthetic_mnist(n_train=1600, n_test=256)
    parts = data.generate_partitions(16, RandomIIDPartitionStrategy)
    mask = np.zeros(16, np.float32)
    mask[[2, 9]] = 1.0
    kw = dict(
        train_set_size=4, batch_size=32, seed=6,
        byzantine_mask=mask, byzantine_attack="scaled",
    )
    clipped = MeshSimulation(
        mlp_model(seed=0), parts, clip_update_norm=5.0, **kw
    )
    r_ok = clipped.run(rounds=2, epochs=1, warmup=False, rounds_per_call=2)
    assert r_ok.test_acc[-1] > 0.5, r_ok.test_acc
    throttled = MeshSimulation(
        mlp_model(seed=0), parts, clip_update_norm=0.01, **kw
    )
    r_slow = throttled.run(rounds=2, epochs=1, warmup=False, rounds_per_call=2)
    # A clip two orders below the honest delta norm must visibly slow
    # training — proves the clip actually binds inside the jitted round.
    assert r_slow.test_acc[-1] < r_ok.test_acc[-1] - 0.2, (
        r_slow.test_acc, r_ok.test_acc,
    )


def test_clip_update_norm_validations():
    data = synthetic_mnist(n_train=256, n_test=64)
    parts = data.generate_partitions(4, RandomIIDPartitionStrategy)
    with pytest.raises(ValueError, match="clip_update_norm"):
        MeshSimulation(mlp_model(seed=0), parts, clip_update_norm=-1.0)
    with pytest.raises(ValueError, match="scaffold"):
        MeshSimulation(
            mlp_model(seed=0), parts, algorithm="scaffold", clip_update_norm=1.0
        )
