"""Chaos plane + round-survival hardening tests.

Covers: deterministic fault injection (same seed => same decision stream),
config fail-fast validation, drop/duplicate/partition behavior through the
real protocol send path, bounded retry + backoff before write-off,
death-callback propagation (heartbeat-declared and send-failure), the
aggregation wait completing via the death callback in well under
AGGREGATION_TIMEOUT, in-memory transport teardown hygiene, the gossip
abandon metric, and the dense-frame round-anchor resync for rejoin.
"""

import os
import subprocess
import sys
import threading
import time
from typing import Any

import numpy as np
import pytest

from p2pfl_tpu.chaos import CHAOS, ChaosPlane
from p2pfl_tpu.comm.commands.command import Command
from p2pfl_tpu.comm.gossiper import Gossiper
from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol
from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import CommunicationError
from p2pfl_tpu.learning.aggregators.fedavg import FedAvg
from p2pfl_tpu.telemetry import REGISTRY


class MockCommand(Command):
    def __init__(self):
        self.calls = []

    @staticmethod
    def get_name() -> str:
        return "mock"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        self.calls.append((source, round, args))


def _mk(n):
    protos = [InMemoryCommunicationProtocol() for _ in range(n)]
    for p in protos:
        p.start()
    return protos


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


# --- the plane itself --------------------------------------------------------


def test_chaos_deterministic_same_seed():
    """Same seed + same intercept sequence => identical decisions AND
    identical fault counts (the acceptance determinism property)."""
    p1, p2 = ChaosPlane(), ChaosPlane()
    pairs = [("a", "b"), ("b", "a"), ("a", "c"), ("c", "a")]
    with Settings.overridden(
        CHAOS_ENABLED=True, CHAOS_SEED=7, CHAOS_DROP_RATE=0.25,
        CHAOS_DUPLICATE_RATE=0.1, CHAOS_DELAY_JITTER_S=0.0,
    ):
        d1 = [p1.intercept(s, d) for _ in range(400) for s, d in pairs]
        d2 = [p2.intercept(s, d) for _ in range(400) for s, d in pairs]
    assert d1 == d2
    assert p1.fault_counts() == p2.fault_counts()
    assert p1.fault_counts().get("drop", 0) > 0  # faults actually fired


def test_chaos_different_seed_differs():
    p1, p2 = ChaosPlane(), ChaosPlane()
    with Settings.overridden(CHAOS_ENABLED=True, CHAOS_DROP_RATE=0.5):
        with Settings.overridden(CHAOS_SEED=1):
            d1 = [p1.intercept("a", "b").drop for _ in range(200)]
        with Settings.overridden(CHAOS_SEED=2):
            d2 = [p2.intercept("a", "b").drop for _ in range(200)]
    assert d1 != d2


def test_chaos_inactive_is_clean():
    p = ChaosPlane()
    assert not p.active
    d = p.intercept("a", "b")  # callable even when inactive: clean decision
    assert not d.drop and d.blocked is None and d.delay_s == 0.0


def test_chaos_env_validation_fails_fast():
    """A typo'd chaos env value must fail at config IMPORT (the
    WIRE_COMPRESSION pattern), not mid-round in a gossip thread."""
    for var, bad in (
        ("P2PFL_TPU_CHAOS_SEED", "not-an-int"),
        ("P2PFL_TPU_CHAOS_DROP_RATE", "nope"),
        ("P2PFL_TPU_CHAOS_DROP_RATE", "1.5"),
        ("P2PFL_TPU_CHAOS_DUPLICATE_RATE", "-0.1"),
        ("P2PFL_TPU_CHAOS_DELAY_S", "99"),
    ):
        env = dict(os.environ)
        env[var] = bad
        proc = subprocess.run(
            [sys.executable, "-c", "import p2pfl_tpu.config"],
            capture_output=True, text=True, env=env,
        )
        assert proc.returncode != 0, (var, bad)
        assert "ValueError" in proc.stderr and var in proc.stderr, proc.stderr


# --- through the real send path ----------------------------------------------


def test_drop_injection_loses_message_silently():
    a, b = _mk(2)
    cmd = MockCommand()
    b.add_command(cmd)
    try:
        a.connect(b.addr)
        with CHAOS.overridden(drop_rate=1.0, seed=3):
            a.send(b.addr, a.build_msg("mock"))  # must NOT raise
            time.sleep(0.3)
            assert cmd.calls == []
            assert CHAOS.fault_counts().get("drop", 0) >= 1
        # healed: delivery works again
        a.send(b.addr, a.build_msg("mock", args=["after"]))
        assert _wait(lambda: cmd.calls)
    finally:
        a.stop()
        b.stop()


def test_duplicate_injection_is_deduped():
    """Duplicated control frames must execute exactly once (msg_id dedup)."""
    a, b = _mk(2)
    cmd = MockCommand()
    b.add_command(cmd)
    try:
        a.connect(b.addr)
        with CHAOS.overridden(duplicate_rate=1.0, seed=3):
            a.send(b.addr, a.build_msg("mock", args=["dup"]))
            assert _wait(lambda: cmd.calls)
            time.sleep(0.3)
            assert len(cmd.calls) == 1
            assert CHAOS.fault_counts().get("duplicate", 0) >= 1
    finally:
        a.stop()
        b.stop()


def test_partition_writes_peer_off_and_fires_death_callback():
    a, b = _mk(2)
    deaths = []
    a.on_neighbor_removed(deaths.append)
    try:
        a.connect(b.addr)
        CHAOS.partition([a.addr], [b.addr])
        try:
            with pytest.raises(CommunicationError):
                a.send(b.addr, a.build_msg("mock"), retries=1)
        finally:
            CHAOS.reset()
        assert deaths == [b.addr]
        assert b.addr not in a.get_neighbors()
        # heal + reconnect works (the link was never really down)
        assert a.connect(b.addr)
    finally:
        a.stop()
        b.stop()


def test_send_retry_succeeds_after_transient_failure():
    """A transient blip must NOT write the peer off: bounded retry with
    backoff recovers the send and keeps the neighbor."""

    class Flaky(InMemoryCommunicationProtocol):
        def __init__(self):
            self.failures_left = 2
            super().__init__()

        def _transport_send(self, nei, env):
            if self.failures_left > 0:
                self.failures_left -= 1
                raise CommunicationError("transient blip")
            super()._transport_send(nei, env)

    a, b = Flaky(), InMemoryCommunicationProtocol()
    a.start()
    b.start()
    cmd = MockCommand()
    b.add_command(cmd)
    retries_before = sum(
        c.value for _, c in REGISTRY.get("p2pfl_send_retries_total").samples()
    )
    try:
        a.connect(b.addr)
        a.send(b.addr, a.build_msg("mock"), retries=3)
        assert _wait(lambda: cmd.calls)
        assert b.addr in a.get_neighbors()  # never written off
        retries_after = sum(
            c.value for _, c in REGISTRY.get("p2pfl_send_retries_total").samples()
        )
        assert retries_after - retries_before >= 2
    finally:
        a.stop()
        b.stop()


# --- round survival ----------------------------------------------------------


def test_aggregation_wait_completes_via_death_callback():
    """ACCEPTANCE (fast, non-slow): with one trainset member dead, the
    aggregation wait finishes via remove_node in well under the timeout."""
    from p2pfl_tpu.models import mlp_model

    agg = FedAvg()
    agg.set_addr("n1")
    agg.set_nodes_to_aggregate(["n1", "n2", "n3"])
    m = mlp_model(seed=0, hidden_sizes=(8,))
    from p2pfl_tpu.models.model_handle import ModelHandle

    agg.add_model(ModelHandle(m.params, m.apply_fn, contributors=["n1"]))
    agg.add_model(ModelHandle(m.params, m.apply_fn, contributors=["n2"]))

    result = {}

    def waiter():
        t0 = time.monotonic()
        result["model"] = agg.wait_and_get_aggregation(timeout=30.0)
        result["waited"] = time.monotonic() - t0

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.3)
    assert t.is_alive()  # still blocked on the missing n3
    assert agg.remove_node("n3") is True
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert result["waited"] < 5.0, result  # well under the 30s timeout
    assert sorted(result["model"].get_contributors()) == ["n1", "n2"]


def test_aggregator_remove_node_keeps_arrived_contribution():
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.models.model_handle import ModelHandle

    agg = FedAvg()
    agg.set_nodes_to_aggregate(["n1", "n2"])
    m = mlp_model(seed=0, hidden_sizes=(8,))
    agg.add_model(ModelHandle(m.params, m.apply_fn, contributors=["n1"]))
    # n1 already contributed: its death must not drop the model
    assert agg.remove_node("n1") is False
    assert "n1" in agg.get_aggregated_models()
    # unknown node: no-op
    assert agg.remove_node("stranger") is False


def test_heartbeat_death_during_round_unblocks_survivors():
    """SATELLITE: heartbeat-declared removal (notify=False) during an active
    round — a 3-node full-committee federation where one member crashes
    abruptly after learning starts must still finish all rounds, in well
    under VOTE_TIMEOUT + AGGREGATION_TIMEOUT."""
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    Settings.RESOURCE_MONITOR_PERIOD = 0
    n = 3
    with Settings.overridden(TRAIN_SET_SIZE=3):
        data = synthetic_mnist(n_train=128 * n, n_test=64)
        parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        for nd in nodes:
            nd.start()
        try:
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            from p2pfl_tpu.utils.utils import wait_convergence

            wait_convergence(nodes, n - 1, wait=8)
            t0 = time.monotonic()
            nodes[0].set_start_learning(rounds=1, epochs=1)
            # Crash the victim while round 0 is in flight (vote or train).
            assert _wait(lambda: nodes[0].state.round == 0, timeout=10.0)
            victim = nodes[2]
            victim.crash()
            survivors = nodes[:2]
            assert _wait(
                lambda: all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in survivors
                ),
                timeout=Settings.VOTE_TIMEOUT + Settings.AGGREGATION_TIMEOUT,
            ), "survivors did not finish the round"
            elapsed = time.monotonic() - t0
            # "well under": no stage slept out its full fixed timeout.
            assert elapsed < Settings.AGGREGATION_TIMEOUT, elapsed
            for nd in survivors:
                assert nd.learning_workflow.history.count("RoundFinishedStage") == 1
            # the victim left the survivors' membership
            for nd in survivors:
                assert victim.addr not in nd.get_neighbors()
        finally:
            for nd in nodes:
                nd.stop()


# --- in-memory teardown hygiene (satellite) ----------------------------------


def test_inmemory_stop_with_handlers_in_flight_leaks_nothing():
    a, b = _mk(2)

    class Slow(Command):
        @staticmethod
        def get_name() -> str:
            return "slow"

        def execute(self, source, round, *args, **kwargs):
            time.sleep(0.5)

    b.add_command(Slow())
    a.connect(b.addr)
    for _ in range(8):  # more work than the 4 executor workers
        a.send(b.addr, a.build_msg("slow"))
    b_addr = b.addr
    b.stop()  # handlers still in flight
    a.stop()
    # registry entry released, address immediately reusable
    assert InMemoryRegistry.lookup(b_addr) is None
    fresh = InMemoryCommunicationProtocol(b_addr)
    fresh.start()
    fresh.stop()
    # executor worker threads are gone (bounded join in _server_stop)
    assert _wait(
        lambda: not any(
            t.name.startswith(f"memsrv-{b_addr}") and t.is_alive()
            for t in threading.enumerate()
        ),
        timeout=5.0,
    ), [t.name for t in threading.enumerate()]


def test_inmemory_restart_same_addr_not_unregistered_by_old_instance():
    """Identity-guarded unregister: the OLD instance's late stop must not
    tear a restarted node out of the registry."""
    old = InMemoryCommunicationProtocol()
    old.start()
    addr = old.addr
    old.crash()  # unregisters old
    fresh = InMemoryCommunicationProtocol(addr)
    fresh.start()
    old.stop()  # late stop of the dead instance — must be a no-op
    try:
        assert InMemoryRegistry.lookup(addr) is fresh
    finally:
        fresh.stop()


# --- gossip abandon metric (satellite) ----------------------------------------


def test_gossip_abandon_logs_and_counts(caplog):
    import logging

    sent = []
    g = Gossiper("mem://abandoner", send_fn=lambda n, e: sent.append(n),
                 get_direct_neighbors_fn=lambda: [])
    fam = REGISTRY.get("p2pfl_gossip_abandoned_total")
    before = sum(c.value for _, c in fam.samples())
    with Settings.overridden(GOSSIP_EXIT_ON_X_EQUAL_ROUNDS=3):
        with caplog.at_level(logging.WARNING, logger="p2pfl_tpu"):
            g.gossip_weights(
                early_stopping_fn=lambda: False,
                get_candidates_fn=lambda: ["mem://dead-peer"],
                status_fn=lambda: "stuck",  # never changes -> stall exit
                model_fn=lambda nei: None,
                period=0.01,
            )
    after = sum(c.value for _, c in fam.samples())
    assert after - before == 1
    assert any("ABANDONED" in r.message for r in caplog.records)


# --- rejoin: round-anchor resync ----------------------------------------------


def test_dense_full_model_resyncs_round_anchor():
    """A crashed-and-restarted node that adopts a DENSE full model for round
    r fast-forwards its delta anchor to r+1, so sparse top-k frames for the
    next round decode instead of being dropped forever."""
    from p2pfl_tpu.comm.commands.impl import FullModelCommand
    from p2pfl_tpu.exceptions import DeltaAnchorError
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    with Settings.overridden(WIRE_COMPRESSION="topk", EXECUTOR_MAX_WORKERS=0):
        data = synthetic_mnist(n_train=128, n_test=32)
        parts = data.generate_partitions(1, RandomIIDPartitionStrategy)
        # The "restarted" node: fresh state, experiment resumed at round 2,
        # anchor round -1 (it crashed; its codec state is gone).
        node = Node(mlp_model(seed=0), parts[0], batch_size=32)
        node.state.set_experiment("rejoin-test", 5)
        node.state.experiment.round = 2
        assert node.state.wire.anchor_round == -1

        # An in-phase sender: its anchor for round 3 is the round-2 aggregate.
        from p2pfl_tpu.comm.delta import DeltaWireCodec

        sender_model = mlp_model(seed=1)
        sender_model.contributors = ["s"]
        sender_codec = DeltaWireCodec("sender")

        # 1) restarted node receives the DENSE round-2 full model
        dense_payload = sender_model.encode_parameters()
        FullModelCommand(node).execute("sender", 2, weights=dense_payload)
        assert node.state.last_full_model_round == 2
        assert node.state.wire.anchor_round == 3  # resynced

        # 2) sender anchors round 3 on the same aggregate and ships sparse
        sender_codec.set_anchor(sender_model.get_parameters(), 3)
        perturbed = sender_model.build_copy(
            params=[np.asarray(p) + 0.01 for p in sender_model.get_parameters()],
            contributors=["s"], num_samples=1,
        )
        sparse = sender_codec.encode_model(perturbed, 3)
        assert sparse is not None
        arrays, meta = node.state.wire.decode_frame(sparse)  # must NOT raise
        assert len(arrays) == len(sender_model.get_parameters())

        # 3) a sparse frame for an UN-anchored round still rejects
        sender_codec.set_anchor(sender_model.get_parameters(), 7)
        stale = sender_codec.encode_model(perturbed, 7)
        with pytest.raises(DeltaAnchorError):
            node.state.wire.decode_frame(stale)
