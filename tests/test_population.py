"""Population subsystem: cohort sampler, scenario partitioner, padding,
engine accounting.

The statistical assertions (coverage fairness, Dirichlet skew) run on FIXED
seeds — the sampler is a pure function of its inputs, so these are exact
regression pins, not flaky tolerance tests.
"""

from __future__ import annotations

import numpy as np
import pytest

from p2pfl_tpu.population.cohort import (
    CohortPlan,
    clear_plan,
    cohort_for_round,
    cohort_size,
    committee_schedule,
    install_plan,
    wire_cohort_filter,
)
from p2pfl_tpu.population.scenarios import dirichlet_label_counts


def _names(n: int) -> list:
    return [f"vnode/{i:05d}" for i in range(n)]


# --- sampler determinism ------------------------------------------------------


def test_cohort_stream_seeded_and_order_independent():
    names = _names(24)
    plan = CohortPlan(seed=9, fraction=0.25, names=tuple(names))
    stream = [plan.cohort(r, names) for r in range(20)]
    # Same seed: the identical stream, even from a shuffled name order (the
    # wire's discovery order is arbitrary; the fused backend's is indexed).
    shuffled = list(names)
    np.random.default_rng(0).shuffle(shuffled)
    again = [plan.cohort(r, shuffled) for r in range(20)]
    assert again == stream
    assert all(c == sorted(c) and len(c) == 6 for c in stream)
    # Different seed: a different stream (the sampler can disagree).
    other = CohortPlan(seed=10, fraction=0.25, names=tuple(names))
    assert [other.cohort(r, names) for r in range(20)] != stream


def test_wire_filter_matches_fused_schedule():
    """The two backends' cohort derivations are the same function: the wire
    filter (ambient plan + live candidate list) must select exactly the
    names the fused committee schedule indexes, every round."""
    names = _names(12)
    plan = CohortPlan(seed=3, fraction=0.5, churn_rate=0.1, names=tuple(names))
    sched = committee_schedule(plan, names, rounds=8)
    install_plan(plan)
    try:
        for r in range(8):
            got = wire_cohort_filter(r, names)
            assert sorted(got) == [names[i] for i in sched[r]]
    finally:
        clear_plan()


def test_wire_filter_semantics():
    # No plan installed: identity (as a list), any candidate order.
    clear_plan()
    cands = ["c", "a", "b"]
    assert wire_cohort_filter(0, cands) == cands
    plan = CohortPlan(seed=1, fraction=0.5)
    install_plan(plan)
    try:
        got = wire_cohort_filter(2, cands)
        # Subset of the candidates, preserved in CANDIDATE order (the vote
        # stage's tie-breaks are positional).
        assert [c for c in cands if c in got] == got
        assert len(got) == 2
    finally:
        clear_plan()


def test_committee_schedule_static_k_and_churn_exhaustion():
    names = _names(10)
    plan = CohortPlan(seed=5, fraction=0.4, names=tuple(names))
    sched = committee_schedule(plan, names, rounds=6)
    assert sched.shape == (6, 4) and sched.dtype == np.int32
    assert all(list(row) == sorted(row) for row in sched)
    # A churn trace that can leave < K nodes up must raise, not shrink the
    # committee (the fused scan's shapes are static).
    drowned = CohortPlan(
        seed=5, fraction=0.4, churn_rate=0.95, names=tuple(names)
    )
    with pytest.raises(ValueError, match="churn left"):
        committee_schedule(drowned, names, rounds=50)


def test_cohort_size_clamps():
    assert cohort_size(100, 0.01) == 1
    assert cohort_size(100, 0.01, min_size=8) == 8
    assert cohort_size(4, 0.9) == 4
    assert cohort_size(100, 1.0) == 100


# --- statistics ---------------------------------------------------------------


def test_cohort_coverage_fairness():
    """Per-round reshuffle ⇒ long-run participation concentrates at the
    cohort fraction for EVERY node (no node starved or pinned)."""
    n, rounds, fraction = 40, 300, 0.2
    names = _names(n)
    k = cohort_size(n, fraction)
    counts = np.zeros(n)
    for r in range(rounds):
        for name in cohort_for_round(7, r, names, fraction):
            counts[names.index(name)] += 1
    expected = rounds * k / n
    assert counts.sum() == rounds * k  # exactly K solicited per round
    assert counts.min() > 0.5 * expected
    assert counts.max() < 1.5 * expected


def test_dirichlet_label_counts_exact_sizes_and_skew():
    rng = np.random.default_rng(11)
    n, s, c = 64, 40, 10
    # Extreme concentration: every node nearly single-class.
    skewed = dirichlet_label_counts(rng, n, s, c, alpha=0.05)
    assert skewed.shape == (n, c)
    assert (skewed.sum(axis=1) == s).all()  # fixed per-node sizes, any alpha
    assert (skewed.max(axis=1) / s).mean() > 0.7
    # Near-uniform concentration: no dominant class anywhere.
    flat = dirichlet_label_counts(rng, n, s, c, alpha=1000.0)
    assert (flat.sum(axis=1) == s).all()
    assert (flat.max(axis=1) / s).mean() < 0.25


# --- padding invariance (satellite: auto-pad to the mesh axis) ----------------


def _tiny_sim(pad_to_multiple):
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    rng = np.random.default_rng(0)
    n, s, feat, classes = 6, 8, 4, 3
    x = rng.normal(size=(n, s, feat)).astype(np.float32)
    y = rng.integers(0, classes, size=(n, s)).astype(np.int32)
    w = np.ones((n, s), np.float32)
    model = mlp_model(input_shape=(feat,), hidden_sizes=(4,), out_channels=classes, seed=0)
    return MeshSimulation(
        model, (x, y, w), train_set_size=3, batch_size=4, seed=0,
        canonical_committee=True, pad_to_multiple=pad_to_multiple,
    )


def test_padded_population_matches_unpadded():
    """Zero-weight fillers must be invisible: same committees, same node-0
    trajectory, bit for bit."""
    import jax

    sim_a = _tiny_sim(pad_to_multiple=1)   # 6 stays 6
    sim_b = _tiny_sim(pad_to_multiple=4)   # 6 pads to 8
    try:
        assert sim_b.num_nodes == 8 and sim_b.logical_num_nodes == 6
        res_a = sim_a.run(rounds=2, warmup=False)
        res_b = sim_b.run(rounds=2, warmup=False)
        assert np.array_equal(res_a.committees, res_b.committees)
        pa = jax.tree.map(lambda a: np.asarray(a[0]), sim_a.params_stack)
        pb = jax.tree.map(lambda a: np.asarray(a[0]), sim_b.params_stack)
        for la, lb in zip(jax.tree.leaves(pa), jax.tree.leaves(pb)):
            np.testing.assert_array_equal(la, lb)
    finally:
        sim_a.close()
        sim_b.close()


# --- engine accounting --------------------------------------------------------


def test_engine_cohort_fill_and_snapshot():
    from p2pfl_tpu.population import PopulationEngine

    with PopulationEngine(
        16, cohort_fraction=0.25, seed=2, samples_per_node=8, hidden=(4,)
    ) as eng:
        res = eng.run(4)
        fill = eng.cohort_fill()
        assert np.isclose(fill.mean() * 16, eng.cohort_k)
        # Fill is participation/rounds: each node's value is a multiple of
        # 1/4 and the schedule rows are what was counted.
        assert fill.sum() * 4 == np.asarray(res.committees).size
        snap = eng.snapshot(res, top_n=4)
        # top_n virtual rows + the observer's own row (wire doc-shape parity).
        assert len(snap["peers"]) == 4 + 1
        assert all(
            p["cohort_fill"] is not None
            for name, p in snap["peers"].items()
            if name != "population-engine"
        )


def test_engine_checkpoint_resume_replays_cohort_accounting(tmp_path):
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.population import PopulationEngine
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    kw = dict(cohort_fraction=0.5, seed=4, samples_per_node=8, hidden=(4,))
    with PopulationEngine(8, **kw) as ref:
        ref.run(3)
        ref_fill = ref.cohort_fill()
        ref_hash = canonical_params_hash(ref.gather_params(0))
    ckpt = FLCheckpointer(str(tmp_path))
    with PopulationEngine(8, **kw) as victim:
        victim.run(2)
        assert victim.save_to(ckpt)
    with PopulationEngine(8, **kw) as healed:
        assert healed.load_from(ckpt) == 2
        healed.run(1)
        assert canonical_params_hash(healed.gather_params(0)) == ref_hash
        np.testing.assert_allclose(healed.cohort_fill(), ref_fill)


# --- both backends, end to end ------------------------------------------------


@pytest.mark.slow
def test_scenario_parity_under_cohort_sampling(tmp_path):
    """One seeded scenario (Dirichlet skew, 50% cohort), both backends:
    the rotating-observer wire stream must align with the fused ledger and
    every round's aggregate hash must be bit-exact."""
    import importlib.util
    import os

    from p2pfl_tpu.population.scenarios import (
        PopulationScenario,
        run_scenario_fused,
        run_scenario_wire,
    )

    spec = importlib.util.spec_from_file_location(
        "parity_diff",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "parity_diff.py"),
    )
    parity_diff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(parity_diff)

    scn = PopulationScenario(
        seed=77, n_nodes=4, rounds=2, samples_per_node=16, batch_size=8,
        hidden=(8,), cohort_fraction=0.5, dirichlet_alpha=0.3,
    )
    wire = run_scenario_wire(scn, ledger_dir=str(tmp_path), timeout_s=180.0)
    # Every node — member or not — committed the same bits each round.
    ref = wire["hashes"][scn.node_names[0]]
    assert len(ref) == scn.rounds
    assert all(wire["hashes"][n] == ref for n in scn.node_names)
    fused = run_scenario_fused(scn, ledger_dir=str(tmp_path))
    report = parity_diff.compare_ledgers(wire["stitched"], fused["events"])
    assert report["status"] == "OK", report.get("first_divergence")
    assert report["hashes_compared"] == scn.rounds
