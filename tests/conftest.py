"""Test harness config: run JAX on a virtual 8-device CPU mesh.

The container may auto-register a TPU platform plugin at interpreter startup
(sitecustomize) and pin ``jax_platforms`` to it; unit tests must run on a
virtual 8-device CPU mesh instead, so we (a) set the XLA host-device-count
flag before any backend initializes and (b) force the platform config back to
cpu. Mirrors the reference's ``set_test_settings()`` pattern
(p2pfl/utils/utils.py:24-40) of shrinking timeouts for in-process multi-node
tests.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The sitecustomize plugin calls jax.config.update("jax_platforms", "axon,cpu")
# at startup; the env var alone no longer wins. No backend is initialized yet
# at conftest-import time, so this is safe.
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (verified to work on the CPU backend):
# heavy programs — the ResNet-18 federated round compiles ~14 min on this
# 1-core box — are compiled once and reloaded on every later suite run.
# Only slow compiles are persisted so the cache stays small.
jax.config.update(
    "jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__), ".jax_cache")
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fast_settings():
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.utils.utils import set_test_settings

    snap = Settings.snapshot()
    set_test_settings()
    yield
    Settings.restore(snap)


@pytest.fixture(autouse=True)
def _reset_memory_transport():
    """Each test gets a clean in-memory transport registry."""
    yield
    try:
        from p2pfl_tpu.comm.memory.registry import InMemoryRegistry

        InMemoryRegistry.reset()
    except ImportError:
        pass


@pytest.fixture(autouse=True)
def _reset_run_context(tmp_path):
    """Each test starts without an ambient run id or stale live flight
    recorders: a run id established (or a recorder created) by one test
    must not correlate — or leak into the evidence bundles of — the next.
    Evidence bundles default into the test's tmp dir so failure-path
    tests (parks, trips, campaign errors) never litter ``artifacts/``."""
    from p2pfl_tpu.config import Settings

    with Settings.overridden(DOCTOR_BUNDLE_DIR=str(tmp_path / "bundles")):
        yield
    try:
        from p2pfl_tpu.telemetry.bundle import reset_run
        from p2pfl_tpu.telemetry.flight_recorder import reset_live_recorders

        reset_run()
        reset_live_recorders()
    except ImportError:
        pass
