"""REST telemetry client + resource monitor (parity with reference
management/p2pfl_web_services.py:58-268 and node_monitor.py:31-86):
payload shapes against a real local HTTP server, the fail-safe breaker,
and the monitor's periodic system-metric reporting."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.management.node_monitor import NodeMonitor
from p2pfl_tpu.management.web_services import WebServices


@pytest.fixture()
def web_server():
    """A real localhost HTTP sink recording (path, headers, body) tuples."""
    received = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802 (stdlib naming)
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            received.append(
                (self.path, self.headers.get("x-api-key"), json.loads(body))
            )
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):  # keep pytest output clean
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_port}", received
    finally:
        srv.shutdown()
        srv.server_close()


def test_payload_shapes_and_api_key(web_server):
    url, received = web_server
    ws = WebServices(url, key="sekrit")
    ws.register_node("node-a")
    ws.send_log("node-a", "INFO", "hello")
    ws.send_local_metric("node-a", "exp1", "loss", 0.5, round=2, step=7)
    ws.send_global_metric("node-a", "exp1", "test_acc", 0.9, round=2)
    ws.send_system_metric("node-a", "cpu_percent", 12.5)
    ws.unregister_node("node-a")
    paths = [p for p, _, _ in received]
    assert paths == [
        "/node", "/node-log", "/node-metric/local", "/node-metric/global",
        "/node-metric/system", "/node-remove",
    ]
    assert all(key == "sekrit" for _, key, _ in received)
    local = received[2][2]
    assert local == {
        "address": "node-a", "experiment": "exp1", "metric": "loss",
        "value": 0.5, "round": 2, "step": 7,
    }


def test_breaker_opens_on_unreachable_sink():
    """Telemetry failures must never take a node down: after the failure
    threshold the breaker opens, later calls return instantly without IO."""
    ws = WebServices(
        "http://127.0.0.1:1", key="k", timeout=0.5, fail_threshold=2,
        backoff_base=30.0,
    )
    ws.register_node("node-a")  # fails (connection refused), swallowed
    assert not ws.broken  # one transient failure must NOT disable telemetry
    ws.register_node("node-a")  # second consecutive failure trips it
    assert ws.broken
    t0 = time.monotonic()
    for _ in range(50):
        ws.send_log("node-a", "INFO", "dropped")
    assert time.monotonic() - t0 < 0.2  # no network attempts while open


def test_breaker_reprobes_after_backoff_window():
    """The breaker is a window, not a latch: once the backoff expires the
    client re-probes, and a healthy sink closes the breaker for good."""
    state = {"fail": True}
    ok_posts = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if state["fail"]:
                self.send_response(500)
            else:
                ok_posts.append(self.path)
                self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    ws = WebServices(
        f"http://127.0.0.1:{srv.server_port}", key="k", timeout=2.0,
        fail_threshold=1, backoff_base=0.1,
    )
    try:
        ws.register_node("node-a")  # 500 -> trips the breaker
        assert ws.broken
        state["fail"] = False  # sink recovers
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not ok_posts:
            ws.send_log("node-a", "INFO", "probe")  # dropped until window expires
            time.sleep(0.05)
        assert ok_posts, "breaker never re-probed after the backoff window"
        assert not ws.broken  # the successful re-probe closed it
    finally:
        srv.shutdown()
        srv.server_close()


def test_node_monitor_exposes_availability():
    """Callers can tell whether system monitoring is actually on."""
    mon = NodeMonitor("node-a", lambda n, m, v: None)
    try:
        import psutil  # noqa: F401

        assert mon.available
    except ImportError:
        assert not mon.available
        mon.start()  # must be a silent-safe no-op (plus a one-time warning)
        assert mon._thread is None


def test_node_monitor_reports_system_metrics():
    psutil = pytest.importorskip("psutil")  # noqa: F841 — monitor needs it
    reported = []
    with Settings.overridden(RESOURCE_MONITOR_PERIOD=0.05):
        mon = NodeMonitor("node-a", lambda n, m, v: reported.append((n, m, v)))
        mon.start()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and len(reported) < 4:
            time.sleep(0.05)
        mon.stop()
    metrics = {m for _, m, _ in reported}
    assert {"cpu_percent", "ram_percent", "net_in_mbps", "net_out_mbps"} <= metrics
    assert all(n == "node-a" for n, _, _ in reported)
    n_before = len(reported)
    time.sleep(0.2)  # stop() must actually stop the thread
    assert len(reported) == n_before


def test_logger_connect_web_routes_registration(web_server):
    url, received = web_server
    from p2pfl_tpu.management.logger import logger

    logger.connect_web(url, "k2")
    try:
        logger.register_node("node-w")
        logger.unregister_node("node-w")
    finally:
        logger._web_services = None  # detach so other tests stay offline
    paths = [p for p, _, _ in received]
    assert "/node" in paths
