"""REST telemetry client + resource monitor (parity with reference
management/p2pfl_web_services.py:58-268 and node_monitor.py:31-86):
payload shapes against a real local HTTP server, the fail-safe breaker,
and the monitor's periodic system-metric reporting."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.management.node_monitor import NodeMonitor
from p2pfl_tpu.management.web_services import WebServices


@pytest.fixture()
def web_server():
    """A real localhost HTTP sink recording (path, headers, body) tuples."""
    received = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):  # noqa: N802 (stdlib naming)
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            received.append(
                (self.path, self.headers.get("x-api-key"), json.loads(body))
            )
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):  # keep pytest output clean
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_port}", received
    finally:
        srv.shutdown()
        srv.server_close()


def test_payload_shapes_and_api_key(web_server):
    url, received = web_server
    ws = WebServices(url, key="sekrit")
    ws.register_node("node-a")
    ws.send_log("node-a", "INFO", "hello")
    ws.send_local_metric("node-a", "exp1", "loss", 0.5, round=2, step=7)
    ws.send_global_metric("node-a", "exp1", "test_acc", 0.9, round=2)
    ws.send_system_metric("node-a", "cpu_percent", 12.5)
    ws.unregister_node("node-a")
    paths = [p for p, _, _ in received]
    assert paths == [
        "/node", "/node-log", "/node-metric/local", "/node-metric/global",
        "/node-metric/system", "/node-remove",
    ]
    assert all(key == "sekrit" for _, key, _ in received)
    local = received[2][2]
    assert local == {
        "address": "node-a", "experiment": "exp1", "metric": "loss",
        "value": 0.5, "round": 2, "step": 7,
    }


def test_breaker_opens_on_unreachable_sink():
    """Telemetry failures must never take a node down: the first failed
    POST trips the breaker, later calls return instantly without IO."""
    ws = WebServices("http://127.0.0.1:1", key="k", timeout=0.5)
    ws.register_node("node-a")  # fails, trips the breaker, swallowed
    assert ws._broken
    t0 = time.monotonic()
    for _ in range(50):
        ws.send_log("node-a", "INFO", "dropped")
    assert time.monotonic() - t0 < 0.2  # no network attempts after the trip


def test_node_monitor_reports_system_metrics():
    psutil = pytest.importorskip("psutil")  # noqa: F841 — monitor needs it
    reported = []
    with Settings.overridden(RESOURCE_MONITOR_PERIOD=0.05):
        mon = NodeMonitor("node-a", lambda n, m, v: reported.append((n, m, v)))
        mon.start()
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline and len(reported) < 4:
            time.sleep(0.05)
        mon.stop()
    metrics = {m for _, m, _ in reported}
    assert {"cpu_percent", "ram_percent", "net_in_mbps", "net_out_mbps"} <= metrics
    assert all(n == "node-a" for n, _, _ in reported)
    n_before = len(reported)
    time.sleep(0.2)  # stop() must actually stop the thread
    assert len(reported) == n_before


def test_logger_connect_web_routes_registration(web_server):
    url, received = web_server
    from p2pfl_tpu.management.logger import logger

    logger.connect_web(url, "k2")
    try:
        logger.register_node("node-w")
        logger.unregister_node("node-w")
    finally:
        logger._web_services = None  # detach so other tests stay offline
    paths = [p for p, _, _ in received]
    assert "/node" in paths
