"""Byzantine defense plane tests.

Covers: wire admission control (structural/dtype/NaN/norm screening, the
adaptive bound, the enable flag, corrupt-frame accounting, the num_samples
clamp + its fail-fast env validation), Krum/Multi-Krum selection against
signflip and scaled attackers (kernel and node-mode aggregator), the chaos
plane's Byzantine peer behaviors (determinism, each attack's effect, the
real send choke point), screening after sparse-delta reconstruction (a
poisoned top-k frame never corrupts the anchor), full-model first-wins
adoption, and a non-slow 3-node e2e where one adversarial trainer is
screened out and the round still completes within the PR 3 wait bounds.
"""

import os
import subprocess
import sys
import time
from typing import Any

import numpy as np
import pytest

from p2pfl_tpu.chaos import BYZANTINE_ATTACKS, CHAOS, ChaosPlane
from p2pfl_tpu.comm.admission import MIN_NORM_HISTORY, AdmissionController
from p2pfl_tpu.comm.envelope import Envelope
from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol
from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
from p2pfl_tpu.config import Settings
from p2pfl_tpu.learning.aggregators import FedAvg, Krum, MultiKrum
from p2pfl_tpu.models import mlp_model
from p2pfl_tpu.models.model_handle import ModelHandle
from p2pfl_tpu.ops import aggregation as agg_ops
from p2pfl_tpu.ops.serialization import deserialize_arrays
from p2pfl_tpu.telemetry import REGISTRY


def _small_model() -> ModelHandle:
    return mlp_model(seed=0, hidden_sizes=(16,))


def _rejected(reason=None) -> int:
    fam = REGISTRY.get("p2pfl_updates_rejected_total")
    total = 0
    if fam is not None:
        for labels, child in fam.samples():
            if reason is None or labels.get("reason") == reason:
                total += int(child.value)
    return total


# --- admission control: the screen -------------------------------------------


def test_admission_legit_frame_passes_and_builds_history():
    m = _small_model()
    adm = AdmissionController("adm-legit")
    for i in range(MIN_NORM_HISTORY + 1):
        frame = [p + 0.01 * (i + 1) for p in m.get_parameters()]
        assert adm.screen(frame, m, source="peer") is None
    assert adm.rejected_count() == 0


def test_admission_structural_rejections():
    m = _small_model()
    params = m.get_parameters()
    adm = AdmissionController("adm-struct")
    # wrong leaf count
    assert adm.screen(params[:-1], m) == "tree"
    # wrong shape (transpose a 2D leaf)
    bad = [p.copy() for p in params]
    i2d = next(i for i, p in enumerate(bad) if p.ndim == 2)
    bad[i2d] = bad[i2d].T.copy()
    assert adm.screen(bad, m) == "shape"
    # wrong dtype class (int where float expected)
    bad = [p.copy() for p in params]
    bad[0] = bad[0].astype(np.int32)
    assert adm.screen(bad, m) == "dtype"
    assert adm.rejected_count("tree") == 1
    assert adm.rejected_count("shape") == 1
    assert adm.rejected_count("dtype") == 1


def test_admission_rejects_nonfinite():
    m = _small_model()
    adm = AdmissionController("adm-nan")
    nan_frame = [p.copy() for p in m.get_parameters()]
    nan_frame[0][0] = np.nan
    assert adm.screen(nan_frame, m) == "nonfinite"
    inf_frame = [p.copy() for p in m.get_parameters()]
    inf_frame[-1][...] = np.inf
    assert adm.screen(inf_frame, m) == "nonfinite"
    assert adm.rejected_count("nonfinite") == 2


def test_admission_norm_bound_bootstrap_and_adaptive():
    m = _small_model()
    params = m.get_parameters()
    adm = AdmissionController("adm-norm")
    # Bootstrap (no history yet): an update at least as large as the whole
    # model is rejected outright — signflip (2||w||) and scaled (9||w||)
    # both trip before any honest norms have been observed.
    assert adm.screen([-p for p in params], m) == "norm"
    assert adm.screen([10.0 * p for p in params], m) == "norm"
    # Build honest history: small perturbations around the local model.
    for i in range(MIN_NORM_HISTORY):
        assert adm.screen([p + 0.01 * (i + 1) for p in params], m) is None
    # Adaptive bound: an outlier far beyond median * ADMISSION_NORM_MULT
    # rejects (+1.0 per element ~ 25x the largest honest perturbation).
    assert adm.screen([p + 1.0 for p in params], m) == "norm"
    # ...and honest frames keep passing after the rejection.
    assert adm.screen([p + 0.02 for p in params], m) is None


def test_admission_disabled_admits_everything():
    m = _small_model()
    adm = AdmissionController("adm-off")
    nan_frame = [np.full_like(p, np.nan) for p in m.get_parameters()]
    with Settings.overridden(ADMISSION_ENABLED=False):
        assert adm.screen(nan_frame, m) is None
        assert adm.screen(nan_frame[:-1], m) is None
    assert adm.rejected_count() == 0


def test_admission_skips_norm_check_when_asked():
    """The full-model path screens structure+finiteness but not distance —
    a rejoining node must be able to adopt a far-away aggregate."""
    m = _small_model()
    adm = AdmissionController("adm-rejoin")
    far = [p + 100.0 for p in m.get_parameters()]
    assert adm.screen(far, m, check_norm=False) is None
    nan_frame = [np.full_like(p, np.nan) for p in m.get_parameters()]
    assert adm.screen(nan_frame, m, check_norm=False) == "nonfinite"


def test_num_samples_clamp():
    adm = AdmissionController("adm-clamp")
    cap = Settings.MAX_CLAIMED_SAMPLES
    assert adm.clamp_num_samples(17, "peer") == 17
    assert adm.clamp_num_samples(cap, "peer") == cap
    assert adm.clamp_num_samples(cap * 1000, "peer") == cap
    assert adm.clamp_num_samples(-3, "peer") == 0
    fam = REGISTRY.get("p2pfl_claimed_samples_clamped_total")
    clamped = sum(
        int(c.value) for labels, c in fam.samples()
        if labels.get("node") == "adm-clamp"
    )
    assert clamped == 1


def test_admission_env_validation_fails_fast():
    """A typo'd admission/clamp env value must fail at config import (the
    CHAOS_*/WIRE_COMPRESSION fail-fast pattern)."""
    for var, bad in (
        ("P2PFL_TPU_MAX_CLAIMED_SAMPLES", "lots"),
        ("P2PFL_TPU_MAX_CLAIMED_SAMPLES", "0"),
        ("P2PFL_TPU_ADMISSION_NORM_MULT", "0.5"),
        ("P2PFL_TPU_ADMISSION_NORM_WINDOW", "2"),
    ):
        env = dict(os.environ)
        env[var] = bad
        proc = subprocess.run(
            [sys.executable, "-c", "import p2pfl_tpu.config"],
            capture_output=True, text=True, env=env,
        )
        assert proc.returncode != 0, (var, bad)
        assert "ValueError" in proc.stderr and var in proc.stderr, proc.stderr


def test_admission_init_screen():
    """Init frames: honest fresh-init weights pass (ratio ~1 to the local
    init), a x10-scaled init rejects as init_norm, NaN rejects, and a
    signflip init passes (valid-scale — the documented trust boundary)."""
    m = _small_model()
    other = mlp_model(seed=7, hidden_sizes=(16,))
    adm = AdmissionController("adm-init")
    assert adm.screen_init(other.get_parameters(), m) is None
    assert adm.screen_init([10.0 * p for p in other.get_parameters()], m) == "init_norm"
    nan_init = [np.full_like(p, np.nan) for p in other.get_parameters()]
    assert adm.screen_init(nan_init, m) == "nonfinite"
    assert adm.screen_init([-p for p in other.get_parameters()], m) is None


def test_init_model_command_rejects_scaled_init():
    """A Byzantine initiator's scaled init frame must not seed the node."""
    from p2pfl_tpu.comm.commands.impl import InitModelCommand

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _make_node()
        before = [p.copy() for p in node.learner.get_model().get_parameters()]
        evil = mlp_model(seed=3)
        evil_frame = evil.build_copy(
            params=[10.0 * p for p in evil.get_parameters()]
        ).encode_parameters()
        InitModelCommand(node).execute("evil", 0, weights=evil_frame)
        assert not node.state.model_initialized_event.is_set()
        for a, b in zip(before, node.learner.get_model().get_parameters()):
            np.testing.assert_array_equal(a, b)
        assert _rejected("init_norm") >= 1


# --- admission on the command path --------------------------------------------


def _make_node(seed=0, aggregator=None):
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.node import Node

    data = synthetic_mnist(n_train=64, n_test=32)
    parts = data.generate_partitions(1, RandomIIDPartitionStrategy)
    return Node(mlp_model(seed=seed), parts[0], batch_size=32,
                aggregator=aggregator or FedAvg())


def test_corrupt_frame_counted_not_raised():
    """A truncated/garbage frame must become a reason="corrupt" rejection on
    the handler, never an exception on the transport thread."""
    from p2pfl_tpu.comm.commands.impl import FullModelCommand, PartialModelCommand

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _make_node()
        node.state.set_experiment("corrupt-test", 3)
        before = _rejected("corrupt")
        PartialModelCommand(node).execute("evil", 0, weights=b"PFLTgarbage")
        FullModelCommand(node).execute("evil", 0, weights=b"\x00\x01\x02")
        assert _rejected("corrupt") - before == 2


def test_partial_model_rejected_before_aggregator():
    """A poisoned partial model must never reach aggregator.add_model."""
    from p2pfl_tpu.comm.commands.impl import PartialModelCommand

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _make_node()
        node.state.set_experiment("screen-test", 3)
        node.state.train_set = [node.addr, "evil"]
        node.aggregator.set_nodes_to_aggregate([node.addr, "evil"])
        evil = node.learner.get_model().build_copy(
            params=[10.0 * p for p in node.learner.get_model().get_parameters()],
            contributors=["evil"], num_samples=1,
        )
        before = _rejected("norm")
        PartialModelCommand(node).execute(
            "evil", 0, weights=evil.encode_parameters(),
            contributors=["evil"], num_samples=1,
        )
        assert _rejected("norm") - before == 1
        assert node.aggregator.get_aggregated_models() == []


def test_inflated_num_samples_clamped_on_partial_path():
    from p2pfl_tpu.comm.commands.impl import PartialModelCommand

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _make_node()
        node.start()  # the admitted model triggers a models_aggregated broadcast
        try:
            node.state.set_experiment("clamp-test", 3)
            node.state.train_set = [node.addr, "evil"]
            node.aggregator.set_nodes_to_aggregate([node.addr, "evil"])
            # In-norm (honest-looking) frame with an absurd num_samples claim.
            m = node.learner.get_model()
            frame = m.build_copy(
                params=[p + 0.001 for p in m.get_parameters()],
                contributors=["evil"], num_samples=1,
            )
            PartialModelCommand(node).execute(
                "evil", 0, weights=frame.encode_parameters(),
                contributors=["evil"], num_samples=10**15,
            )
            stored = [
                mm for mm in node.aggregator._models if "evil" in mm.contributors
            ]
            assert stored
            assert stored[0].get_num_samples() == Settings.MAX_CLAIMED_SAMPLES
        finally:
            node.stop()
            InMemoryRegistry.reset()


def test_full_model_first_wins_blocks_overwrite():
    """Once a round's full model is held (adopted or own aggregate), a later
    full_model frame for the same round must NOT overwrite it — only
    re-announce models_ready (ack repair)."""
    from p2pfl_tpu.comm.commands.impl import FullModelCommand

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _make_node()
        node.start()
        try:
            node.state.set_experiment("firstwins-test", 3)
            node.state.last_full_model_round = 0  # round 0 already held
            before = [p.copy() for p in node.learner.get_model().get_parameters()]
            other = mlp_model(seed=9)
            other.contributors = ["evil"]
            FullModelCommand(node).execute(
                "evil", 0, weights=other.encode_parameters()
            )
            after = node.learner.get_model().get_parameters()
            for a, b in zip(before, after):
                np.testing.assert_array_equal(a, b)
        finally:
            node.stop()


# --- screening after sparse-delta reconstruction -------------------------------


def test_poisoned_sparse_frame_rejected_and_anchor_survives():
    """A NaN-poisoned top-k frame must be screened AFTER reconstruction and
    must never corrupt the receiver's round anchor: a subsequent honest
    sparse frame still decodes cleanly."""
    from p2pfl_tpu.comm.commands.impl import PartialModelCommand
    from p2pfl_tpu.comm.delta import DeltaWireCodec

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0, WIRE_COMPRESSION="topk"):
        node = _make_node()
        node.state.set_experiment("sparse-poison", 3)
        node.state.train_set = [node.addr, "evil"]
        node.aggregator.set_nodes_to_aggregate([node.addr, "evil"])
        anchor = node.learner.get_model().get_parameters()
        node.state.wire.set_anchor(anchor, 0)

        sender = DeltaWireCodec("evil")
        sender.set_anchor(anchor, 0)
        honest_update = node.learner.get_model().build_copy(
            params=[p + 0.01 for p in anchor], contributors=["evil"], num_samples=1,
        )
        sparse = sender.encode_model(honest_update, 0)
        assert sparse is not None

        # Poison the sparse frame's float (value) tensors with NaN, exactly
        # like the chaos plane's "nan" byzantine behavior does on the wire.
        plane = ChaosPlane()
        plane.set_byzantine("evil", "nan")
        env = Envelope.weights("evil", "partial_model", 0, sparse, ["evil"], 1)
        poisoned = plane.corrupt_weights("evil", env).payload

        before = _rejected("nonfinite")
        PartialModelCommand(node).execute(
            "evil", 0, weights=poisoned, contributors=["evil"], num_samples=1
        )
        assert _rejected("nonfinite") - before == 1
        assert node.aggregator.get_aggregated_models() == []

        # Anchor unpoisoned: a later HONEST sparse frame decodes to finite
        # arrays that match the sender's update.
        sender2 = DeltaWireCodec("evil2")
        sender2.set_anchor(anchor, 0)
        sparse2 = sender2.encode_model(honest_update, 0)
        arrays, _ = node.state.wire.decode_frame(sparse2)
        for a in arrays:
            assert np.isfinite(np.asarray(a, dtype=np.float32)).all()


def test_hostile_quantized_frame_rejected_as_corrupt_before_anchor():
    """Pre-dequantize sanity screen (wire-speed plane): a quantized top-k
    frame with a hostile scale / zero-point / int range dies as a counted
    ``reason="corrupt"`` rejection BEFORE any value touches the round anchor
    — and the anchor keeps decoding honest frames afterwards."""
    from p2pfl_tpu.comm.commands.impl import PartialModelCommand
    from p2pfl_tpu.comm.delta import DeltaWireCodec
    from p2pfl_tpu.ops.compression import CODEC_META_KEY
    from p2pfl_tpu.ops.serialization import deserialize_arrays, serialize_arrays

    with Settings.overridden(
        EXECUTOR_MAX_WORKERS=0, WIRE_COMPRESSION="topk",
        WIRE_TOPK_VALUES="int8", COALESCE_ENABLED=True,
    ):
        node = _make_node()
        node.state.set_experiment("quant-poison", 3)
        node.state.train_set = [node.addr, "evil"]
        node.aggregator.set_nodes_to_aggregate([node.addr, "evil"], round=0)
        anchor = node.learner.get_model().get_parameters()
        node.state.wire.set_anchor(anchor, 0)

        sender = DeltaWireCodec("evil")
        sender.set_anchor(anchor, 0)
        update = node.learner.get_model().build_copy(
            params=[np.asarray(p) + 0.01 for p in anchor],
            contributors=["evil"], num_samples=1,
        )
        blob, label = sender.encode_tagged(update, 0)
        assert label == "topk-int8"

        # Hostile sender: rewrite every per-tensor scale to NaN (valid CRC —
        # this is a malicious frame, not line noise).
        arrays, meta = deserialize_arrays(bytes(blob))
        poisoned_any = False
        for s in meta[CODEC_META_KEY]:
            if s.get("values") in ("int8", "int4"):
                s["scale"] = float("nan")
                poisoned_any = True
        assert poisoned_any
        hostile = bytes(serialize_arrays([np.asarray(a) for a in arrays], meta))

        before = _rejected("corrupt")
        anchor_before = node.state.wire.export_state()
        PartialModelCommand(node).execute(
            "evil", 0, weights=hostile, contributors=["evil"], num_samples=1
        )
        assert _rejected("corrupt") - before == 1
        assert node.aggregator.get_aggregated_models() == []
        after = node.state.wire.export_state()
        assert after["anchor_round"] == anchor_before["anchor_round"]
        for a, b in zip(anchor_before["anchor"], after["anchor"]):
            np.testing.assert_array_equal(a, b)

        # honest frame still decodes against the untouched anchor
        arrays2, _ = node.state.wire.decode_frame(bytes(blob))
        for a in arrays2:
            assert np.isfinite(np.asarray(a, dtype=np.float32)).all()


# --- Krum / Multi-Krum ---------------------------------------------------------


def _attacked_stack(n_honest=6, n_adv=2, attack="signflip"):
    base = _small_model().get_parameters()
    honest = [[p + 0.01 * (i + 1) for p in base] for i in range(n_honest)]
    if attack == "signflip":
        adv = [[-p for p in base] for _ in range(n_adv)]
    else:  # scaled
        adv = [[10.0 * p for p in base] for _ in range(n_adv)]
    return agg_ops.tree_stack(honest + adv), n_honest, n_adv


@pytest.mark.parametrize("attack", ["signflip", "scaled"])
def test_krum_select_excludes_attackers(attack):
    stacked, n_honest, n_adv = _attacked_stack(attack=attack)
    idx = agg_ops.krum_select(stacked, num_byzantine=n_adv, num_selected=1)
    assert int(np.asarray(idx)[0]) < n_honest
    idx_multi = agg_ops.krum_select(
        stacked, num_byzantine=n_adv,
        num_selected=n_honest + n_adv - n_adv - 2,
    )
    assert set(int(i) for i in np.asarray(idx_multi)) <= set(range(n_honest))


@pytest.mark.parametrize("attack", ["signflip", "scaled"])
def test_krum_aggregator_contributors_exclude_attackers(attack):
    base = _small_model().get_parameters()
    models = [
        ModelHandle([p + 0.01 * (i + 1) for p in base], contributors=[f"h{i}"])
        for i in range(6)
    ]
    mult = -1.0 if attack == "signflip" else 10.0
    models += [
        ModelHandle([mult * p for p in base], contributors=[f"adv{i}"])
        for i in range(2)
    ]
    out = MultiKrum(num_byzantine=2).aggregate(models)
    assert out.contributors
    assert not any(c.startswith("adv") for c in out.contributors)
    single = Krum(num_byzantine=2, num_selected=1).aggregate(models)
    assert len(single.contributors) == 1
    assert single.contributors[0].startswith("h")


def test_multikrum_auto_selection_size():
    mk = MultiKrum(num_byzantine=2)
    assert mk._select_count(8) == 4  # n - f - 2
    assert mk._select_count(3) == 1  # floors at 1
    assert MultiKrum(num_byzantine=2, num_selected=3)._select_count(8) == 3
    assert mk.partial_aggregation is False  # raw models only — never pre-averaged


def test_krum_remove_node_wakes_wait():
    """PR 3 interplay: a dead trainset member shrinks Krum's wait too."""
    import threading

    agg = Krum(num_byzantine=1)
    agg.set_addr("n1")
    agg.set_nodes_to_aggregate(["n1", "n2", "n3"])
    base = _small_model().get_parameters()
    agg.add_model(ModelHandle(base, contributors=["n1"]))
    agg.add_model(ModelHandle([p + 0.01 for p in base], contributors=["n2"]))
    result = {}

    def waiter():
        t0 = time.monotonic()
        result["model"] = agg.wait_and_get_aggregation(timeout=30.0)
        result["waited"] = time.monotonic() - t0

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.3)
    assert t.is_alive()
    assert agg.remove_node("n3") is True
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert result["waited"] < 5.0, result


# --- chaos plane: byzantine behaviors ------------------------------------------


def test_byzantine_attack_validation_and_active_flag():
    plane = ChaosPlane()
    with pytest.raises(ValueError, match="attack"):
        plane.set_byzantine("x", "meteor")
    assert not plane.active
    plane.set_byzantine("x", "signflip")
    assert plane.active
    assert plane.byzantine_peers() == {"x": "signflip"}
    plane.clear_byzantine("x")
    assert not plane.active
    plane.set_byzantine("x", "nan")
    plane.reset()
    assert not plane.active and plane.byzantine_peers() == {}


def test_byzantine_corruption_effects():
    m = _small_model()
    params = m.get_parameters()
    payload = m.encode_parameters()
    env = Envelope.weights("adv", "partial_model", 0, payload, ["adv"], 128)
    plane = ChaosPlane()

    plane.set_byzantine("adv", "signflip")
    arrays, _ = deserialize_arrays(plane.corrupt_weights("adv", env).payload)
    np.testing.assert_allclose(np.asarray(arrays[0]), -params[0])

    plane.set_byzantine("adv", "scaled", scale=10.0)
    arrays, _ = deserialize_arrays(plane.corrupt_weights("adv", env).payload)
    np.testing.assert_allclose(
        np.asarray(arrays[0]), 10.0 * params[0], rtol=1e-6
    )

    plane.set_byzantine("adv", "nan")
    arrays, _ = deserialize_arrays(plane.corrupt_weights("adv", env).payload)
    assert not np.isfinite(np.asarray(arrays[0]).astype(np.float32)).any()

    plane.set_byzantine("adv", "inflate", inflate_factor=1000)
    out = plane.corrupt_weights("adv", env)
    assert out.num_samples == 128 * 1000
    assert out.payload == env.payload  # weights untouched by inflation

    # honest source / control frames are identity
    assert plane.corrupt_weights("honest", env) is env
    ctrl = Envelope.message("adv", "vote_train_set", args=["a", "1"])
    assert plane.corrupt_weights("adv", ctrl) is ctrl

    counts = plane.fault_counts()
    for attack in BYZANTINE_ATTACKS:
        assert counts.get(f"byzantine_{attack}", 0) >= 1, counts


def test_byzantine_corruption_deterministic():
    """Same attack + same frame sequence through two fresh planes =>
    identical corrupted payloads AND identical fault counts."""
    m = _small_model()
    frame = m.encode_parameters()
    outs = []
    for _ in range(2):
        plane = ChaosPlane()
        plane.set_byzantine("adv", "scaled")
        payloads = []
        for k in range(20):
            env = Envelope.weights("adv", "partial_model", k, frame, ["adv"], 1)
            payloads.append(plane.corrupt_weights("adv", env).payload)
        outs.append((payloads, plane.fault_counts()))
    assert outs[0] == outs[1]


def test_byzantine_through_real_send_path():
    """Corruption happens at the shared send choke point: a weights frame
    from a byzantine protocol arrives corrupted at the receiver."""
    from p2pfl_tpu.comm.commands.command import Command

    received = []

    class Capture(Command):
        @staticmethod
        def get_name() -> str:
            return "partial_model"

        def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
            received.append(kwargs["weights"])

    a, b = InMemoryCommunicationProtocol(), InMemoryCommunicationProtocol()
    a.start()
    b.start()
    b.add_command(Capture())
    try:
        a.connect(b.addr)
        m = _small_model()
        CHAOS.set_byzantine(a.addr, "signflip")
        try:
            env = a.build_weights("partial_model", 0, m.encode_parameters(), ["a"], 1)
            a.send(b.addr, env)
            deadline = time.time() + 5.0
            while time.time() < deadline and not received:
                time.sleep(0.05)
            assert received, "frame never arrived"
            arrays, _ = deserialize_arrays(received[0])
            np.testing.assert_allclose(
                np.asarray(arrays[0]), -m.get_parameters()[0]
            )
            assert CHAOS.fault_counts().get("byzantine_signflip", 0) >= 1
        finally:
            CHAOS.reset()
    finally:
        a.stop()
        b.stop()
        InMemoryRegistry.reset()


# --- e2e: adversary screened out, round survives -------------------------------


def test_e2e_adversary_screened_round_completes():
    """3-node full-committee federation with one scaled adversary: the honest
    nodes reject its frames at admission, JIT-aggregate what arrived (PR 3
    stall patience), and finish the round well inside the fixed timeouts."""
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils.utils import wait_convergence

    Settings.RESOURCE_MONITOR_PERIOD = 0
    n = 3
    with Settings.overridden(TRAIN_SET_SIZE=3):
        data = synthetic_mnist(n_train=128 * n, n_test=64)
        parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
        nodes = [
            Node(mlp_model(seed=i), parts[i], batch_size=32,
                 aggregator=Krum(num_byzantine=1))
            for i in range(n)
        ]
        adversary, honest = nodes[2], nodes[:2]
        for nd in nodes:
            nd.start()
        try:
            CHAOS.set_byzantine(adversary.addr, "scaled")
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n - 1, wait=8)
            rejected_before = _rejected()
            t0 = time.monotonic()
            nodes[0].set_start_learning(rounds=1, epochs=1)
            deadline = time.time() + Settings.VOTE_TIMEOUT + Settings.AGGREGATION_TIMEOUT
            while time.time() < deadline:
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in honest
                ):
                    break
                time.sleep(0.2)
            else:
                pytest.fail("honest nodes did not finish under the adversary")
            elapsed = time.monotonic() - t0
            # "well under": no stage slept out its full fixed timeout.
            assert elapsed < Settings.AGGREGATION_TIMEOUT, elapsed
            for nd in honest:
                assert nd.learning_workflow.history.count("RoundFinishedStage") == 1
            assert _rejected() > rejected_before, "no poisoned frame was screened"
        finally:
            CHAOS.reset()
            for nd in nodes:
                nd.stop()
            InMemoryRegistry.reset()
