"""Elastic async federation: staleness-weighted buffered aggregation, the
window scheduler, elastic membership (join/rejoin under the sparse wire),
churn traces, and the observatory-driven participation gate.

Strategy mirrors the chaos/Byzantine test planes: pure-math units first
(staleness weights, bit-exact FedAvg equivalence, anchor history), then the
command handlers against crafted frames, then small in-memory federations
through the real Node/gossip/aggregator stack.
"""

import threading
import time
from typing import Any

import numpy as np
import pytest

from p2pfl_tpu.config import Settings


# --- staleness-weight math ----------------------------------------------------


def test_staleness_weight_identity_and_monotonicity():
    from p2pfl_tpu.learning.aggregators import staleness_weight

    # lag 0 weighs exactly 1.0 for EVERY alpha — the bit-exact-FedAvg hinge.
    for alpha in (0.0, 0.25, 0.5, 1.0, 4.0):
        assert staleness_weight(0, alpha) == 1.0
    # monotone non-increasing in lag; strictly decreasing when alpha > 0
    for alpha in (0.25, 0.5, 1.0):
        ws = [staleness_weight(lag, alpha) for lag in range(8)]
        assert all(a > b for a, b in zip(ws, ws[1:]))
    # alpha = 0 disables the discount entirely
    assert [staleness_weight(lag, 0.0) for lag in range(5)] == [1.0] * 5
    # negative lag (a faster peer's contribution) is clamped to fresh
    assert staleness_weight(-3, 1.0) == 1.0


def _handles(n=3, dim=5, samples=(10, 20, 30)):
    from p2pfl_tpu.models.model_handle import ModelHandle

    rng = np.random.default_rng(0)
    out = []
    for i in range(n):
        params = [rng.normal(size=(dim,)).astype(np.float32),
                  rng.normal(size=(dim, 2)).astype(np.float32)]
        out.append(
            ModelHandle(params, contributors=[f"n{i}"], num_samples=samples[i])
        )
    return out


def test_zero_staleness_window_is_bit_exact_fedavg():
    from p2pfl_tpu.learning.aggregators import FedAvg
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator

    models = _handles()
    ref = FedAvg().aggregate(list(models))
    out = AsyncBufferedAggregator.aggregate_weighted(list(models), [0, 0, 0])
    for a, b in zip(out.get_parameters(), ref.get_parameters()):
        # bit-exact: same kernel, same weights — not just allclose
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert out.contributors == ref.contributors
    assert out.get_num_samples() == ref.get_num_samples()


def test_stale_contribution_weighs_less():
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator

    models = _handles(n=2, samples=(10, 10))
    fresh_only = models[0].get_parameters()
    even = AsyncBufferedAggregator.aggregate_weighted(list(models), [0, 0], alpha=1.0)
    discounted = AsyncBufferedAggregator.aggregate_weighted(
        list(models), [0, 9], alpha=1.0
    )
    # Discounting the second model must pull the aggregate TOWARD the fresh one.
    def dist(agg):
        return sum(
            float(np.linalg.norm(np.asarray(a) - np.asarray(f)))
            for a, f in zip(agg.get_parameters(), fresh_only)
        )

    assert dist(discounted) < dist(even)


# --- buffered window mechanics ------------------------------------------------


def test_window_completes_on_own_contribution_when_all_trainers_dead():
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator

    agg = AsyncBufferedAggregator("me")
    agg.open_window(0)
    own = _handles(n=1, samples=(10,))[0]
    agg.fold(own, 0, "me")
    t0 = time.monotonic()
    # Target re-evaluates to 1 (everyone else is dead) -> immediate close.
    out = agg.wait_window(lambda: 1, timeout=30.0)
    assert time.monotonic() - t0 < 1.0
    assert out is not None and out.get_num_samples() == 10


def test_window_target_shrinks_live_via_notify():
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator

    agg = AsyncBufferedAggregator("me")
    agg.open_window(0)
    agg.fold(_handles(n=1)[0], 0, "me")
    target = {"n": 2}
    done = threading.Event()
    result = {}

    def waiter():
        result["model"] = agg.wait_window(lambda: target["n"], timeout=20.0)
        done.set()

    threading.Thread(target=waiter, daemon=True).start()
    time.sleep(0.6)
    assert not done.is_set()  # still waiting on the (dead) second contributor
    target["n"] = 1  # the death callback's effect...
    agg.notify()  # ...and its wake
    assert done.wait(timeout=2.0)
    assert result["model"] is not None


def test_stale_limit_drops_contribution():
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator

    with Settings.overridden(ASYNC_MAX_STALENESS=2):
        agg = AsyncBufferedAggregator("me")
        agg.open_window(10)
        ok = agg.fold(_handles(n=1)[0], 7, "laggard")  # lag 3 > 2
        assert not ok
        assert agg.fill() == 0
        assert agg.fold(_handles(n=1)[0], 8, "laggard")  # lag 2 == limit


def test_window_early_stop_returns_none():
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator

    agg = AsyncBufferedAggregator("me")
    agg.open_window(0)
    assert agg.wait_window(lambda: 5, timeout=10.0, early_stop_fn=lambda: True) is None


# --- sparse-delta anchor history ---------------------------------------------


def test_anchor_history_decodes_lagging_sparse_frames():
    from p2pfl_tpu.comm.delta import DeltaWireCodec
    from p2pfl_tpu.exceptions import DeltaAnchorError
    from p2pfl_tpu.models import mlp_model

    with Settings.overridden(WIRE_COMPRESSION="topk"):
        model = mlp_model(seed=0, hidden_sizes=(8,))
        model.contributors = ["s"]
        params = model.get_parameters()

        # The lagging SENDER is anchored at window 1.
        sender = DeltaWireCodec("sender")
        sender.set_anchor(params, 1)
        perturbed = model.build_copy(
            params=[np.asarray(p) + 0.01 for p in params],
            contributors=["s"], num_samples=1,
        )
        frame_w1 = sender.encode_model(perturbed, 1)
        assert frame_w1 is not None

        # The receiver advanced through windows 1..3 with history depth 3.
        recv = DeltaWireCodec("recv")
        recv.anchor_history = 3
        for w in (1, 2, 3):
            recv.set_anchor(params, w)
        arrays, meta = recv.decode_frame(frame_w1)  # decodes via the history
        assert len(arrays) == len(params)

        # Depth-1 (sync) behavior rejects the same lagging frame.
        sync_recv = DeltaWireCodec("sync-recv")
        for w in (1, 2, 3):
            sync_recv.set_anchor(params, w)
        with pytest.raises(DeltaAnchorError):
            sync_recv.decode_frame(frame_w1)

        # Eviction: a frame anchored before the kept history rejects too.
        deep = DeltaWireCodec("deep")
        deep.anchor_history = 2
        for w in (1, 2, 3, 4):
            deep.set_anchor(params, w)
        with pytest.raises(DeltaAnchorError):
            deep.decode_frame(frame_w1)

        # resync (the rejoin path) drops the history with the residuals.
        recv.resync(params, 9)
        with pytest.raises(DeltaAnchorError):
            recv.decode_frame(frame_w1)


# --- churn trace --------------------------------------------------------------


def test_plan_churn_deterministic_and_counted():
    from p2pfl_tpu.chaos import CHAOS, ChaosPlane

    leavers = [f"n{i}" for i in range(6)]
    joiners = [f"j{i}" for i in range(3)]
    a = ChaosPlane().plan_churn(5, leavers, joiners, seed=7)
    b = ChaosPlane().plan_churn(5, leavers, joiners, seed=7)
    assert a == b
    c = ChaosPlane().plan_churn(5, leavers, joiners, seed=8)
    assert a != c
    # one leave + one join per round from round 1 (joiners run out at 3)
    assert sum(1 for e in a if e.kind == "leave") == 4
    assert sum(1 for e in a if e.kind == "join") == 3
    assert all(e.when >= 1 for e in a)
    # executed events land in the shared fault table under "churn"
    CHAOS.reset()
    try:
        CHAOS.churn("n0", "leave")
        CHAOS.churn("j0", "join")
        assert CHAOS.fault_counts().get("churn") == 2
    finally:
        CHAOS.reset()


# --- command handlers ---------------------------------------------------------


def _node_pair():
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    data = synthetic_mnist(n_train=128, n_test=32)
    parts = data.generate_partitions(1, RandomIIDPartitionStrategy)
    return Node(mlp_model(seed=0), parts[0], batch_size=32)


def test_async_contribution_ignored_outside_async_session():
    from p2pfl_tpu.comm.commands.impl import AsyncContributionCommand

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _node_pair()
        payload = node.learner.get_model().encode_parameters()
        # No experiment at all, then a SYNC experiment: both must no-op.
        AsyncContributionCommand(node).execute("peer", 0, weights=payload)
        node.state.set_experiment("sync-exp", 3)
        node.state.fed_mode = "sync"
        AsyncContributionCommand(node).execute("peer", 0, weights=payload)
        assert node.async_agg is None


def test_async_contribution_folds_and_screens():
    from p2pfl_tpu.comm.commands.impl import AsyncContributionCommand
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _node_pair()
        node.state.set_experiment("async-exp", 3)
        node.state.fed_mode = "async"
        node.async_agg = AsyncBufferedAggregator(node.addr)
        node.async_agg.open_window(1)
        payload = node.learner.get_model().encode_parameters()
        AsyncContributionCommand(node).execute(
            "peer", 1, weights=payload, contributors=["peer"], num_samples=17
        )
        assert node.async_agg.fill() == 1
        assert node.async_agg.seen_contributors.get("peer") == 1
        # A corrupt frame is a counted rejection, not an exception storm.
        AsyncContributionCommand(node).execute("peer2", 1, weights=b"garbage")
        assert node.async_agg.fill() == 1


def test_suspect_gate_blocks_contribution():
    from p2pfl_tpu.comm.commands.impl import AsyncContributionCommand
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.telemetry.digest import HealthDigest

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0, ASYNC_SUSPECT_GATE=1.0):
        node = _node_pair()
        node.state.set_experiment("async-exp", 3)
        node.state.fed_mode = "async"
        node.async_agg = AsyncBufferedAggregator(node.addr)
        node.async_agg.open_window(0)
        # The fleet attributes admission rejections to "evil" via digests —
        # note "evil" itself reports NO digest; the gate must still fire.
        node.observatory.ingest(
            HealthDigest(
                node="reporter", ts=time.time(),
                rejected_by_source={"evil": 5.0},
            )
        )
        assert node.observatory.suspect_score("evil") == 5.0
        payload = node.learner.get_model().encode_parameters()
        AsyncContributionCommand(node).execute(
            "evil", 0, weights=payload, contributors=["evil"], num_samples=1
        )
        assert node.async_agg.fill() == 0  # gated before decode
        fam = REGISTRY.get("p2pfl_async_dropped_total")
        dropped = {
            labels["reason"]: child.value
            for labels, child in fam.samples()
            if labels.get("node") == node.addr
        }
        assert dropped.get("suspect", 0) >= 1


def test_async_done_removes_peer_from_fill_target():
    from p2pfl_tpu.comm.commands.impl import AsyncDoneCommand
    from p2pfl_tpu.learning.aggregators.async_buffer import AsyncBufferedAggregator
    from p2pfl_tpu.stages.async_node import select_participants

    with Settings.overridden(EXECUTOR_MAX_WORKERS=0):
        node = _node_pair()
        node.state.set_experiment("async-exp", 3)
        node.state.fed_mode = "async"
        node.async_agg = AsyncBufferedAggregator(node.addr)

        node.protocol.get_neighbors = lambda only_direct=False: ["p1", "p2"]
        solicit, countable = select_participants(node)
        assert solicit == ["p1", "p2"] and countable == ["p1", "p2"]
        AsyncDoneCommand(node).execute("p1", 3)
        solicit, countable = select_participants(node)
        # A finished peer produces nothing further: never shipped to,
        # never waited on.
        assert solicit == ["p2"] and countable == ["p2"]
        # ...and a fresh experiment forgets the done set.
        node.state.set_experiment("async-exp-2", 3)
        assert node.state.async_done_peers == set()


def test_start_learning_command_mode_backcompat():
    from p2pfl_tpu.comm.commands.impl import StartLearningCommand

    calls = []

    class FakeNode:
        def start_learning_thread(self, rounds, epochs, mode="sync"):
            calls.append((rounds, epochs, mode))

    cmd = StartLearningCommand(FakeNode())
    cmd.execute("src", 0, "3", "2")  # old two-arg frame
    cmd.execute("src", 0, "3", "2", "async")
    assert calls == [(3, 2, "sync"), (3, 2, "async")]


def test_scheduler_registry():
    from p2pfl_tpu.stages.async_node import AsyncStartStage
    from p2pfl_tpu.stages.base_node import StartLearningStage
    from p2pfl_tpu.stages.workflow import scheduler_start_stage

    assert scheduler_start_stage("sync") is StartLearningStage
    assert scheduler_start_stage("async") is AsyncStartStage
    with pytest.raises(ValueError):
        scheduler_start_stage("semi-sync")


# --- observability ------------------------------------------------------------


def test_digest_carries_mode_and_staleness():
    from p2pfl_tpu.telemetry.digest import HealthDigest, decode

    dig = HealthDigest(node="n1", mode="async", staleness=1.5, round=4)
    back = decode(dig.encode())
    assert back.mode == "async" and back.staleness == 1.5 and back.round == 4
    # absent fields (older peer) degrade to defaults, not failures
    old = decode('{"node": "n2", "round": 1, "v": 1}')
    assert old is not None and old.mode == "" and old.staleness == 0.0


def test_observatory_membership_events():
    from p2pfl_tpu.telemetry.digest import HealthDigest
    from p2pfl_tpu.telemetry.observatory import Observatory

    class Rec:
        def __init__(self):
            self.events = []

        def record(self, kind, **detail):
            self.events.append((kind, detail))

    rec = Rec()
    obs = Observatory("me", recorder=rec)
    obs.ingest(HealthDigest(node="p1", ts=time.time()))
    obs.forget("p1")
    obs.ingest(HealthDigest(node="p1", ts=time.time() + 1))
    snap = obs.snapshot()
    kinds = [e["event"] for e in snap["membership_events"] if e["peer"] == "p1"]
    # Reappearance after suspected death is a HEAL (durable recovery plane):
    # the peer's scoring state starts fresh and the event says "recover".
    assert kinds == ["join", "leave", "recover"]
    recorded = [d["event"] for k, d in rec.events if k == "membership"]
    assert recorded == ["join", "leave", "recover"]


# --- e2e: mid-run join under the sparse wire ---------------------------------


def _wait(cond, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return False


def test_async_join_bootstraps_and_decodes_sparse_wire():
    """A cold node joins a running async federation mid-experiment over the
    topk sparse wire: the dense catch-up + anchor resync must leave it able
    to decode peers' sparse frames, and its contributions must be folded by
    the established nodes within 2 windows of the join."""
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    n, windows = 2, 4
    with Settings.overridden(
        WIRE_COMPRESSION="topk", ASYNC_WINDOW_TIMEOUT=8.0, LOG_LEVEL="WARNING"
    ):
        data = synthetic_mnist(n_train=128 * (n + 1), n_test=32)
        parts = data.generate_partitions(n + 1, RandomIIDPartitionStrategy)
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        for nd in nodes:
            # pace the windows so the join lands mid-run
            orig = nd.learner.fit

            def slow_fit(orig=orig):
                time.sleep(0.8)
                return orig()

            nd.learner.fit = slow_fit
            nd.start()
        joiner = None
        try:
            nodes[1].connect(nodes[0].addr)
            assert _wait(lambda: len(nodes[0].get_neighbors()) == 1, 10)
            nodes[0].set_start_learning(rounds=windows, epochs=1, mode="async")
            assert _wait(lambda: (nodes[0].state.round or 0) >= 1, 30)

            joiner = Node(mlp_model(seed=9), parts[n], batch_size=32)
            joiner.start()
            joiner.connect(nodes[0].addr)
            time.sleep(0.3)
            joiner.request_async_join()
            join_window = nodes[0].state.round or 0

            alln = nodes + [joiner]
            assert _wait(
                lambda: all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in alln
                ),
                90,
            ), {nd.addr: (nd.learning_workflow.history if nd.learning_workflow else None) for nd in alln}
            # the joiner ran real windows
            jh = joiner.learning_workflow.history
            assert jh.count("AsyncWindowFinishedStage") >= 1, jh
            # sparse frames were actually on the wire...
            assert nodes[0].state.wire.sparse_frames > 0
            # ...and the established nodes folded the joiner soon after entry
            for nd in nodes:
                first = nd.async_agg.seen_contributors.get(joiner.addr)
                assert first is not None, nd.async_agg.seen_contributors
                assert first - join_window <= 2, (first, join_window)
        finally:
            for nd in nodes:
                nd.stop()
            if joiner is not None:
                joiner.stop()
            InMemoryRegistry.reset()
