"""JaxLearner behavior: single-learner train smoke tests (mirrors reference
test/learning/frameworks_test.py:322-385) plus scaffold/fedprox specifics."""

import numpy as np

from p2pfl_tpu.learning.dataset import synthetic_mnist
from p2pfl_tpu.learning.learner import JaxLearner, LearnerFactory
from p2pfl_tpu.models import mlp_model


def _learner(**kw):
    model = mlp_model(seed=0)
    data = synthetic_mnist(n_train=512, n_test=256)
    return JaxLearner(model=model, data=data, self_addr="n0", batch_size=64, **kw)


def test_fit_improves_accuracy():
    lrn = _learner(lr=3e-3)
    lrn.set_epochs(2)
    before = lrn.evaluate()["test_acc"]
    lrn.fit()
    after = lrn.evaluate()["test_acc"]
    assert after > max(before, 0.5), (before, after)


def test_fit_sets_contribution():
    lrn = _learner()
    lrn.set_epochs(1)
    model = lrn.fit()
    assert model.get_contributors() == ["n0"]
    assert model.get_num_samples() == 512


def test_interrupt_before_fit_skips_training():
    lrn = _learner()
    lrn.set_epochs(1)
    before = lrn.get_model().get_parameters()
    lrn.fit()  # warms things up
    p_after_first = lrn.get_model().get_parameters()
    assert any(np.abs(a - b).max() > 0 for a, b in zip(before, p_after_first))


def test_scaffold_callback_produces_deltas():
    lrn = _learner(callbacks=["scaffold"])
    lrn.set_epochs(1)
    model = lrn.fit()
    info = model.get_info("scaffold")
    assert info is not None
    n_leaves = len(model.get_parameters())
    assert len(info["delta_y_i"]) == n_leaves
    assert len(info["delta_c_i"]) == n_leaves
    # delta_y must equal final - initial params
    assert any(np.abs(d).max() > 0 for d in info["delta_y_i"])


def test_fedprox_keeps_params_closer_to_anchor():
    lrn_plain = _learner(lr=1e-2, seed=7)
    lrn_prox = _learner(lr=1e-2, fedprox_mu=1.0, seed=7)
    start = [p.copy() for p in lrn_plain.get_model().get_parameters()]
    lrn_plain.set_epochs(1)
    lrn_prox.set_epochs(1)
    lrn_plain.fit()
    lrn_prox.fit()

    def drift(lrn):
        return sum(
            float(np.abs(a - b).sum())
            for a, b in zip(lrn.get_model().get_parameters(), start)
        )

    assert drift(lrn_prox) < drift(lrn_plain)


def test_metric_reporter_called():
    lrn = _learner()
    seen = []
    lrn.metric_reporter = lambda name, value, step=None: seen.append(name)
    lrn.set_epochs(1)
    lrn.fit()
    lrn.evaluate()
    assert "train_loss" in seen and "test_acc" in seen


def test_learner_factory():
    model = mlp_model(seed=0)
    assert LearnerFactory.create_learner(model) is JaxLearner


def test_callback_registry_hooks_and_errors():
    """Open CallbackFactory (reference callback_factory.py:16-101): custom
    host-side callbacks resolve by name, hook around the jitted fit, and
    unknown names raise listing what's available."""
    import pytest

    from p2pfl_tpu.learning.callbacks import CallbackFactory, P2PFLCallback
    from p2pfl_tpu.learning.dataset import synthetic_mnist
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp_model

    calls = []

    @CallbackFactory.decorator("jax", "recorder")
    class Recorder(P2PFLCallback):
        name = "recorder"

        def on_fit_start(self, learner):
            calls.append("start")

        def on_fit_end(self, learner):
            calls.append("end")
            learner.get_model().add_info("recorder", {"fits": calls.count("end")})

    data = synthetic_mnist(n_train=128, n_test=32)
    learner = JaxLearner(
        mlp_model(seed=0), data, "cb0", batch_size=32, callbacks=["recorder"]
    )
    learner.set_epochs(1)
    model = learner.fit()
    assert calls == ["start", "end"]
    assert model.get_info("recorder") == {"fits": 1}

    with pytest.raises(ValueError, match="recorder"):
        JaxLearner(mlp_model(seed=0), data, "cb1", callbacks=["nope"])
    assert "recorder" in CallbackFactory.registered("jax")


def test_cnn_learner_convergence():
    """CNN model family trains through the jitted learner (BASELINE.json
    config #2's model leg; the sim-mode leg uses the MLP because bf16 convs
    under vmap+scan compile for minutes on the virtual CPU mesh)."""
    from p2pfl_tpu.learning.dataset import synthetic_mnist
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import cnn_model

    data = synthetic_mnist(n_train=512, n_test=64)
    learner = JaxLearner(cnn_model(seed=0), data, "cnn0", batch_size=32, lr=3e-3)
    learner.set_epochs(4)
    learner.fit()
    metrics = learner.evaluate()
    assert metrics["test_acc"] > 0.5, metrics
