"""JaxLearner behavior: single-learner train smoke tests (mirrors reference
test/learning/frameworks_test.py:322-385) plus scaffold/fedprox specifics."""

import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import synthetic_mnist
from p2pfl_tpu.learning.learner import JaxLearner, LearnerFactory
from p2pfl_tpu.models import mlp_model


def _learner(**kw):
    model = mlp_model(seed=0)
    data = synthetic_mnist(n_train=512, n_test=256)
    return JaxLearner(model=model, data=data, self_addr="n0", batch_size=64, **kw)


@pytest.mark.slow
def test_fit_improves_accuracy():
    lrn = _learner(lr=3e-3)
    lrn.set_epochs(2)
    before = lrn.evaluate()["test_acc"]
    lrn.fit()
    after = lrn.evaluate()["test_acc"]
    assert after > max(before, 0.5), (before, after)


def test_fit_sets_contribution():
    lrn = _learner()
    lrn.set_epochs(1)
    model = lrn.fit()
    assert model.get_contributors() == ["n0"]
    assert model.get_num_samples() == 512


def test_interrupt_before_fit_skips_training():
    lrn = _learner()
    lrn.set_epochs(1)
    before = lrn.get_model().get_parameters()
    lrn.fit()  # warms things up
    p_after_first = lrn.get_model().get_parameters()
    assert any(np.abs(a - b).max() > 0 for a, b in zip(before, p_after_first))


@pytest.mark.slow
def test_scaffold_callback_produces_deltas():
    lrn = _learner(callbacks=["scaffold"])
    lrn.set_epochs(1)
    model = lrn.fit()
    info = model.get_info("scaffold")
    assert info is not None
    n_leaves = len(model.get_parameters())
    assert len(info["delta_y_i"]) == n_leaves
    assert len(info["delta_c_i"]) == n_leaves
    # delta_y must equal final - initial params
    assert any(np.abs(d).max() > 0 for d in info["delta_y_i"])


@pytest.mark.slow
def test_fedprox_keeps_params_closer_to_anchor():
    lrn_plain = _learner(lr=1e-2, seed=7)
    lrn_prox = _learner(lr=1e-2, fedprox_mu=1.0, seed=7)
    start = [p.copy() for p in lrn_plain.get_model().get_parameters()]
    lrn_plain.set_epochs(1)
    lrn_prox.set_epochs(1)
    lrn_plain.fit()
    lrn_prox.fit()

    def drift(lrn):
        return sum(
            float(np.abs(a - b).sum())
            for a, b in zip(lrn.get_model().get_parameters(), start)
        )

    assert drift(lrn_prox) < drift(lrn_plain)


def test_metric_reporter_called():
    lrn = _learner()
    seen = []
    lrn.metric_reporter = lambda name, value, step=None: seen.append(name)
    lrn.set_epochs(1)
    lrn.fit()
    lrn.evaluate()
    assert "train_loss" in seen and "test_acc" in seen


def test_learner_factory():
    model = mlp_model(seed=0)
    assert LearnerFactory.create_learner(model) is JaxLearner


def test_callback_registry_hooks_and_errors():
    """Open CallbackFactory (reference callback_factory.py:16-101): custom
    host-side callbacks resolve by name, hook around the jitted fit, and
    unknown names raise listing what's available."""
    import pytest

    from p2pfl_tpu.learning.callbacks import CallbackFactory, P2PFLCallback
    from p2pfl_tpu.learning.dataset import synthetic_mnist
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import mlp_model

    calls = []

    @CallbackFactory.decorator("jax", "recorder")
    class Recorder(P2PFLCallback):
        name = "recorder"

        def on_fit_start(self, learner):
            calls.append("start")

        def on_fit_end(self, learner):
            calls.append("end")
            learner.get_model().add_info("recorder", {"fits": calls.count("end")})

    data = synthetic_mnist(n_train=128, n_test=32)
    learner = JaxLearner(
        mlp_model(seed=0), data, "cb0", batch_size=32, callbacks=["recorder"]
    )
    learner.set_epochs(1)
    model = learner.fit()
    assert calls == ["start", "end"]
    assert model.get_info("recorder") == {"fits": 1}

    with pytest.raises(ValueError, match="recorder"):
        JaxLearner(mlp_model(seed=0), data, "cb1", callbacks=["nope"])
    assert "recorder" in CallbackFactory.registered("jax")


@pytest.mark.slow
def test_cnn_learner_convergence():
    """CNN model family trains through the jitted learner (BASELINE.json
    config #2's model leg; the sim-mode leg uses the MLP because bf16 convs
    under vmap+scan compile for minutes on the virtual CPU mesh)."""
    from p2pfl_tpu.learning.dataset import synthetic_mnist
    from p2pfl_tpu.learning.learner import JaxLearner
    from p2pfl_tpu.models import cnn_model

    data = synthetic_mnist(n_train=512, n_test=64)
    learner = JaxLearner(cnn_model(seed=0), data, "cnn0", batch_size=32, lr=3e-3)
    learner.set_epochs(4)
    learner.fit()
    metrics = learner.evaluate()
    assert metrics["test_acc"] > 0.5, metrics


# --- DP-SGD (no reference analogue) ------------------------------------------


@pytest.mark.slow
def test_dp_grads_matches_plain_mean_when_unclipped():
    """With a huge clip bound and zero noise, the DP estimate equals the
    plain masked mean gradient."""
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.learner import dp_grads, softmax_cross_entropy

    with Settings.overridden(COMPUTE_DTYPE="float32"):
        model = mlp_model(seed=0)  # f32 compute: batched == per-example exactly
    x = jnp.asarray(np.random.default_rng(0).uniform(size=(8, 28, 28)), jnp.float32)
    y = jnp.asarray(np.arange(8) % 10, jnp.int32)
    w = jnp.ones((8,), jnp.float32)

    def batch_loss(p, bx, by, bw):
        return softmax_cross_entropy(model.apply_fn(p, bx), by, bw)

    loss, got = dp_grads(
        batch_loss, model.params, x, y, w, jax.random.key(0),
        clip_norm=1e9, noise_multiplier=0.0,
    )
    want_loss, want = jax.value_and_grad(
        lambda p: softmax_cross_entropy(model.apply_fn(p, x), y, w)
    )(model.params)
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=1e-5)
    for g, r in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_dp_grads_clips_per_example_norm():
    """With clip C and no noise, the mean gradient's norm is <= C (each
    example contributes at most C / B)."""
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.learning.learner import dp_grads, softmax_cross_entropy

    model = mlp_model(seed=0)
    x = jnp.asarray(np.random.default_rng(1).uniform(size=(4, 28, 28)), jnp.float32)
    y = jnp.asarray([0, 1, 2, 3], jnp.int32)
    w = jnp.ones((4,), jnp.float32)
    clip = 0.01

    def batch_loss(p, bx, by, bw):
        return softmax_cross_entropy(model.apply_fn(p, bx), by, bw)

    _, got = dp_grads(
        batch_loss, model.params, x, y, w, jax.random.key(0),
        clip_norm=clip, noise_multiplier=0.0,
    )
    total = float(
        jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in jax.tree.leaves(got)))
    )
    assert total <= clip + 1e-6


@pytest.mark.slow
def test_dp_learner_still_learns():
    """DP-SGD with a moderate clip and noise still reaches >0.5 accuracy on
    the synthetic MNIST (privacy costs accuracy, not learnability)."""
    data = synthetic_mnist(n_train=512, n_test=128)
    learner = JaxLearner(
        mlp_model(seed=0), data, "dp-node", batch_size=64,
        dp_clip_norm=1.0, dp_noise_multiplier=0.3, lr=3e-3,
    )
    learner.set_epochs(3)
    learner.fit()
    metrics = learner.evaluate()
    assert metrics["test_acc"] > 0.5, metrics


def test_dp_noise_without_clip_rejected():
    import pytest

    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    with pytest.raises(ValueError, match="dp_clip_norm"):
        JaxLearner(mlp_model(seed=0), dp_noise_multiplier=0.5)
    data = synthetic_mnist(n_train=64, n_test=16)
    with pytest.raises(ValueError, match="dp_clip_norm"):
        MeshSimulation(
            mlp_model(seed=0),
            data.generate_partitions(2, RandomIIDPartitionStrategy),
            train_set_size=2,
            dp_noise_multiplier=0.5,
        )


@pytest.mark.slow
def test_dp_noise_differs_across_nodes_with_same_seed():
    """Two nodes with identical seeds must not inject identical DP noise
    (the node address is folded into the noise key)."""
    import jax

    data = synthetic_mnist(n_train=64, n_test=16)
    out = []
    for addr in ("node-a", "node-b"):
        learner = JaxLearner(
            mlp_model(seed=0), data, addr, batch_size=32,
            dp_clip_norm=1.0, dp_noise_multiplier=1.0, seed=0,
        )
        learner.set_epochs(1)
        out.append(learner.fit().get_parameters())
    diffs = [float(np.max(np.abs(a - b))) for a, b in zip(out[0], out[1])]
    assert max(diffs) > 1e-6, diffs


def test_privacy_accountant_closed_form_and_monotonicity():
    """The conservative Gaussian-RDP bound has a closed-form optimum:
    eps = T/(2 sigma^2) + sqrt(2 T log(1/delta)) / sigma."""
    import math

    from p2pfl_tpu.learning.privacy import gaussian_rdp_epsilon

    for sigma, steps, delta in [(1.0, 100, 1e-5), (2.0, 1000, 1e-6), (0.5, 10, 1e-3)]:
        want = steps / (2 * sigma**2) + math.sqrt(2 * steps * math.log(1 / delta)) / sigma
        got = gaussian_rdp_epsilon(sigma, steps, delta)
        assert abs(got - want) < 1e-9 * max(1.0, want), (got, want)
    # properties: more noise -> less epsilon; more steps -> more epsilon
    assert gaussian_rdp_epsilon(2.0, 100, 1e-5) < gaussian_rdp_epsilon(1.0, 100, 1e-5)
    assert gaussian_rdp_epsilon(1.0, 200, 1e-5) > gaussian_rdp_epsilon(1.0, 100, 1e-5)
    assert gaussian_rdp_epsilon(0.0, 100, 1e-5) == float("inf")
    assert gaussian_rdp_epsilon(1.0, 0, 1e-5) == 0.0


@pytest.mark.slow
def test_dp_learner_reports_privacy_spent():
    data = synthetic_mnist(n_train=128, n_test=32)
    learner = JaxLearner(
        mlp_model(seed=0), data, "dp-acct", batch_size=32,
        dp_clip_norm=1.0, dp_noise_multiplier=1.0,
    )
    metrics = []
    learner.metric_reporter = lambda name, value, step=None: metrics.append((name, value))
    learner.set_epochs(2)
    model = learner.fit()
    info = learner.privacy_spent()
    assert info["steps"] == 8  # 4 steps/epoch x 2
    assert 0 < info["epsilon"] < float("inf")
    assert ("dp_epsilon", info["epsilon"]) in metrics
    # epsilon must be a LOCAL claim: never stamped into the gossiped model's
    # additional_info (aggregation merges peers' info and could overwrite it)
    assert model.get_info("dp") is None
    # epsilon accumulates across fits
    learner.fit()
    assert learner.privacy_spent()["steps"] == 16
    assert learner.privacy_spent()["epsilon"] > info["epsilon"]


@pytest.mark.slow
def test_privacy_spent_is_inf_after_nonprivate_training():
    """A model trained without DP must never read as epsilon=0 — any
    non-private step voids the claim."""
    data = synthetic_mnist(n_train=64, n_test=16)
    learner = JaxLearner(mlp_model(seed=0), data, "plain", batch_size=32)
    assert learner.privacy_spent()["epsilon"] == 0.0  # nothing released yet
    learner.set_epochs(1)
    learner.fit()
    spent = learner.privacy_spent()
    assert spent["epsilon"] == float("inf")
    assert spent["nonprivate_steps"] > 0


def test_interrupt_fit_lands_mid_epoch(monkeypatch):
    """With interrupt_every=k the epoch scan is segmented and an interrupt
    raised during segment 1 stops before segment 2 — the reference torch
    path's per-batch ``should_stop`` granularity (lightning_learner.py:98-137)
    on the jitted path."""
    lrn = _learner(interrupt_every=2, seed=0)
    lrn.set_epochs(1)  # 512/64 = 8 steps -> 4 segments of 2
    calls = []
    orig = JaxLearner._train_epoch

    def spy(*args, **kw):
        calls.append(1)
        lrn.interrupt_fit()  # fires while the segment is "running"
        return orig(*args, **kw)

    monkeypatch.setattr(JaxLearner, "_train_epoch", staticmethod(spy))
    lrn.fit()
    assert len(calls) == 1  # stopped after the first 2-step segment


def test_interrupt_every_full_epoch_unsegmented(monkeypatch):
    lrn = _learner(seed=0)  # default: one compiled call per epoch
    lrn.set_epochs(1)
    calls = []
    orig = JaxLearner._train_epoch

    def spy(*args, **kw):
        calls.append(args[2].shape[0])
        return orig(*args, **kw)

    monkeypatch.setattr(JaxLearner, "_train_epoch", staticmethod(spy))
    lrn.fit()
    assert calls == [8]  # 512/64 steps in a single scan


def test_interrupt_every_validation():
    with pytest.raises(ValueError, match="interrupt_every"):
        _learner(interrupt_every=0)
