"""Aggregation kernel semantics vs. straightforward numpy references."""

import numpy as np
import jax.numpy as jnp

from p2pfl_tpu.ops import aggregation as agg


def _stack(n, seed=0):
    rng = np.random.default_rng(seed)
    trees = [
        {
            "w": rng.normal(size=(5, 3)).astype(np.float32),
            "b": rng.normal(size=(3,)).astype(np.float32),
        }
        for _ in range(n)
    ]
    return trees, agg.tree_stack(trees)


def test_fedavg_weighted_mean():
    trees, stacked = _stack(4)
    w = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
    out = agg.fedavg(stacked, w)
    expect = sum(wi * t["w"] for wi, t in zip(w, trees)) / w.sum()
    np.testing.assert_allclose(np.asarray(out["w"]), expect, rtol=1e-5)


def test_fedavg_masked_matches_subset():
    trees, stacked = _stack(6)
    w = np.full((6,), 10.0, np.float32)
    mask = np.array([1, 0, 1, 0, 0, 1], np.float32)
    out = agg.fedavg_masked(stacked, w, mask)
    subset = agg.tree_stack([trees[0], trees[2], trees[5]])
    expect = agg.fedavg(subset, np.full((3,), 10.0, np.float32))
    np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(expect["w"]), rtol=1e-5)


def test_fedmedian():
    trees, stacked = _stack(5)
    out = agg.fedmedian(stacked)
    expect = np.median(np.stack([t["b"] for t in trees]), axis=0)
    np.testing.assert_allclose(np.asarray(out["b"]), expect, rtol=1e-6)


def test_trimmed_mean_drops_outliers():
    trees, stacked = _stack(5)
    # poison model 0 with huge values
    poisoned = [dict(t) for t in trees]
    poisoned[0] = {"w": trees[0]["w"] + 1e6, "b": trees[0]["b"] - 1e6}
    stacked_p = agg.tree_stack(poisoned)
    out = agg.trimmed_mean(stacked_p, trim=1)
    vals = np.stack([t["w"] for t in poisoned])
    svals = np.sort(vals, axis=0)[1:-1]
    np.testing.assert_allclose(np.asarray(out["w"]), svals.mean(axis=0), rtol=1e-5)
    assert np.abs(np.asarray(out["w"])).max() < 1e3


def test_krum_excludes_byzantine():
    rng = np.random.default_rng(1)
    base = rng.normal(size=(8,)).astype(np.float32)
    # 5 honest models near base, 2 byzantine far away
    models = [{"p": base + 0.01 * rng.normal(size=(8,)).astype(np.float32)} for _ in range(5)]
    models += [{"p": base + 100.0} for _ in range(2)]
    stacked = agg.tree_stack(models)
    idx = np.asarray(agg.krum_select(stacked, num_byzantine=2, num_selected=3))
    assert set(idx.tolist()) <= {0, 1, 2, 3, 4}
    out, sel = agg.krum(stacked, np.ones((7,), np.float32), num_byzantine=2, num_selected=3)
    assert np.abs(np.asarray(out["p"]) - base).max() < 1.0
    np.testing.assert_array_equal(np.sort(np.asarray(sel)), np.sort(idx))


def test_scaffold_update():
    gp = {"w": np.zeros((2, 2), np.float32)}
    gc = {"w": np.zeros((2, 2), np.float32)}
    dy = agg.tree_stack([{"w": np.ones((2, 2), np.float32)}, {"w": 3 * np.ones((2, 2), np.float32)}])
    dc = agg.tree_stack([{"w": np.ones((2, 2), np.float32)}, {"w": np.ones((2, 2), np.float32)}])
    new_p, new_c = agg.scaffold_update(gp, gc, dy, dc, jnp.float32(1.0), jnp.float32(4.0))
    np.testing.assert_allclose(np.asarray(new_p["w"]), 2 * np.ones((2, 2)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_c["w"]), 0.5 * np.ones((2, 2)), rtol=1e-6)


def test_stack_unstack_roundtrip():
    trees, stacked = _stack(3)
    out = agg.tree_unstack(stacked, 3)
    for a, b in zip(trees, out):
        np.testing.assert_array_equal(a["w"], np.asarray(b["w"]))


def test_geometric_median_resists_outliers():
    """Weiszfeld iterations land near the honest cluster even with 2/7 of
    the weight placed far away (the mean would be dragged ~28 units)."""
    rng = np.random.default_rng(2)
    base = rng.normal(size=(3, 4)).astype(np.float32)
    models = [
        {"a": base + 0.01 * rng.normal(size=(3, 4)).astype(np.float32),
         "b": np.float32(1.0) + 0.01 * rng.normal()}
        for _ in range(5)
    ]
    models += [{"a": base + 100.0, "b": np.float32(101.0)} for _ in range(2)]
    stacked = agg.tree_stack(models)
    out = agg.geometric_median(stacked, np.ones((7,), np.float32), iters=16)
    assert np.abs(np.asarray(out["a"]) - base).max() < 1.0
    assert abs(float(out["b"]) - 1.0) < 1.0
    # Structure and dtypes preserved through the flatten/unflatten.
    assert out["a"].shape == base.shape and out["a"].dtype == base.dtype


def test_geometric_median_matches_mean_when_symmetric():
    """With two symmetric points and equal weights the geometric median is
    their midpoint (= the mean), so the kernel agrees with fedavg there."""
    models = [{"p": np.full((4,), -1.0, np.float32)}, {"p": np.full((4,), 3.0, np.float32)}]
    stacked = agg.tree_stack(models)
    w = np.ones((2,), np.float32)
    gm = np.asarray(agg.geometric_median(stacked, w, iters=32)["p"])
    fa = np.asarray(agg.fedavg(stacked, w)["p"])
    np.testing.assert_allclose(gm, fa, atol=1e-3)
