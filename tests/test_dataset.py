"""Dataset wrapper + partition strategy tests (mirrors reference
test/learning/p2pfl_dataset_test.py:93-124)."""

import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import (
    DirichletPartitionStrategy,
    FederatedDataset,
    LabelSkewedPartitionStrategy,
    PercentageBasedNonIIDPartitionStrategy,
    RandomIIDPartitionStrategy,
    synthetic_mnist,
)


@pytest.fixture
def labels():
    return np.random.default_rng(0).integers(0, 10, size=1000)


def _check_partition(parts, n_total):
    all_idx = np.concatenate(parts)
    assert len(all_idx) == len(np.unique(all_idx))  # disjoint
    assert all_idx.max() < n_total


def test_iid_partition(labels):
    parts = RandomIIDPartitionStrategy.generate(labels, 7, seed=1)
    _check_partition(parts, len(labels))
    sizes = [len(p) for p in parts]
    assert max(sizes) - min(sizes) <= 1
    assert sum(sizes) == len(labels)


def test_iid_deterministic(labels):
    a = RandomIIDPartitionStrategy.generate(labels, 4, seed=3)
    b = RandomIIDPartitionStrategy.generate(labels, 4, seed=3)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_dirichlet_partition(labels):
    parts = DirichletPartitionStrategy.generate(labels, 10, seed=1, alpha=0.1)
    _check_partition(parts, len(labels))
    assert sum(len(p) for p in parts) == len(labels)
    assert min(len(p) for p in parts) >= 2
    # alpha=0.1 should produce visibly skewed class distributions
    dists = []
    for p in parts:
        hist = np.bincount(labels[p], minlength=10) / max(len(p), 1)
        dists.append(hist)
    assert np.std([d.max() for d in dists]) > 0.01


def test_label_skewed_partition(labels):
    parts = LabelSkewedPartitionStrategy.generate(labels, 5, seed=1, classes_per_partition=2)
    _check_partition(parts, len(labels))
    for p in parts:
        assert len(np.unique(labels[p])) <= 2


def test_percentage_noniid_partition(labels):
    parts = PercentageBasedNonIIDPartitionStrategy.generate(labels, 5, seed=1, percentage=0.8)
    _check_partition(parts, len(labels))
    # home classes (the top few) should dominate each partition: ~80% of rows
    # come from the home budget, which may span 2 classes when classes are
    # smaller than the budget.
    for p in parts:
        hist = np.sort(np.bincount(labels[p], minlength=10))[::-1]
        assert hist[:2].sum() / len(p) > 0.6


def test_generate_partitions_end_to_end():
    ds = synthetic_mnist(n_train=256, n_test=64)
    parts = ds.generate_partitions(4, RandomIIDPartitionStrategy, seed=0)
    assert len(parts) == 4
    assert sum(p.get_num_samples(True) for p in parts) == 256
    for p in parts:
        assert p.get_num_samples(False) == 64  # shared test split


def test_export_batches_shapes_and_mask():
    ds = synthetic_mnist(n_train=100, n_test=10)
    xb, yb, wb = ds.export_batches(32, train=True, seed=0)
    assert xb.shape == (4, 32, 28, 28)
    assert yb.shape == (4, 32)
    assert wb.sum() == 100  # mask covers padding
    xb, yb, wb = ds.export_batches(32, train=True, drop_remainder=True)
    assert xb.shape == (3, 32, 28, 28)
    assert wb.sum() == 96


def test_train_test_split_from_arrays():
    x = np.zeros((100, 4), np.float32)
    y = np.arange(100) % 3
    ds = FederatedDataset.from_arrays(x, y)
    ds.generate_train_test_split(test_size=0.25, seed=0)
    assert ds.get_num_samples(True) == 75
    assert ds.get_num_samples(False) == 25


class _FakeVisionDataset:
    """Map-style (image, label) dataset, shaped like torchvision's."""

    def __init__(self, n, uint8=True, seed=0):
        rng = np.random.default_rng(seed)
        if uint8:
            self.images = rng.integers(0, 256, size=(n, 28, 28), dtype=np.uint8)
        else:
            self.images = rng.uniform(size=(n, 28, 28)).astype(np.float32)
        self.labels = rng.integers(0, 10, size=n)

    def __iter__(self):
        return zip(self.images, self.labels)


def test_vision_pairs_to_arrays_uint8_rescale():
    from p2pfl_tpu.learning.dataset import vision_pairs_to_arrays

    x, y = vision_pairs_to_arrays(_FakeVisionDataset(16))
    assert x.shape == (16, 28, 28) and x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() <= 1.0
    assert y.shape == (16,) and y.dtype == np.int32


def test_from_vision_datasets_end_to_end():
    from p2pfl_tpu.learning.dataset import from_vision_datasets

    ds = from_vision_datasets(_FakeVisionDataset(64), _FakeVisionDataset(16, seed=1))
    assert ds.get_num_samples(True) == 64
    assert ds.get_num_samples(False) == 16
    parts = ds.generate_partitions(4, RandomIIDPartitionStrategy, seed=0)
    xb, yb, wb = parts[0].export_batches(8)
    assert xb.shape == (2, 8, 28, 28)


def test_load_torchvision_gated(tmp_path):
    from p2pfl_tpu.learning.dataset import load_torchvision

    try:
        import torchvision  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="synthetic_mnist"):
            load_torchvision("MNIST", cache_dir=str(tmp_path))
    else:  # pragma: no cover - torchvision present: no-egress environment,
        # so only assert the no-download path fails cleanly, never fetch
        with pytest.raises(RuntimeError):
            load_torchvision("MNIST", cache_dir=str(tmp_path), download=False)


def test_vision_dense_fast_path_and_int_rescale():
    from p2pfl_tpu.learning.dataset import vision_pairs_to_arrays

    class DenseStyle:  # torchvision-like: whole split as .data/.targets
        data = np.arange(4 * 28 * 28, dtype=np.uint16).reshape(4, 28, 28)
        targets = [0, 1, 2, 3]

        def __iter__(self):  # pragma: no cover - fast path must win
            raise AssertionError("fast path not taken")

    x, y = vision_pairs_to_arrays(DenseStyle())
    assert x.dtype == np.float32 and x.max() <= 1.0
    np.testing.assert_array_equal(y, [0, 1, 2, 3])


def test_vision_fast_path_respects_transforms_and_empty():
    from p2pfl_tpu.learning.dataset import vision_pairs_to_arrays

    class WithTransform:
        data = np.zeros((2, 4, 4), dtype=np.uint8)
        targets = [0, 1]
        transform = staticmethod(lambda img: np.asarray(img) + 1.0)

        def __iter__(self):
            for img, t in zip(self.data, self.targets):
                yield self.transform(img), t

    x, _ = vision_pairs_to_arrays(WithTransform())
    assert x.min() == 1.0  # transform applied -> per-item path was taken

    class Empty:
        data = np.zeros((0, 4, 4), dtype=np.uint8)
        targets = []

        def __iter__(self):
            return iter(())

    with pytest.raises(ValueError, match="empty"):
        vision_pairs_to_arrays(Empty())


# --- export strategies (reference p2pfl_dataset.py:224-248) -------------------


def test_export_numpy_and_batched_strategies_match_legacy():
    from p2pfl_tpu.learning.dataset import (
        BatchedArraysExportStrategy,
        NumpyExportStrategy,
        synthetic_mnist,
    )

    ds = synthetic_mnist(n_train=130, n_test=32)
    x, y = ds.export(NumpyExportStrategy)
    assert x.shape == (130, 28, 28) and y.shape == (130,)

    xb, yb, wb = ds.export(BatchedArraysExportStrategy, batch_size=64, seed=5)
    xb2, yb2, wb2 = ds.export_batches(64, train=True, seed=5)
    np.testing.assert_array_equal(xb, xb2)
    np.testing.assert_array_equal(yb, yb2)
    np.testing.assert_array_equal(wb, wb2)
    assert xb.shape == (3, 64, 28, 28) and wb[-1].sum() == 130 - 2 * 64

    # drop_remainder slices the ragged tail instead of padding it
    xb3, _, wb3 = ds.export(
        BatchedArraysExportStrategy, batch_size=64, drop_remainder=True
    )
    assert xb3.shape == (2, 64, 28, 28) and wb3.sum() == 128


def test_export_torch_dataloader_roundtrip():
    import torch

    from p2pfl_tpu.learning.dataset import TorchExportStrategy, synthetic_mnist

    ds = synthetic_mnist(n_train=100, n_test=16)
    loader = ds.export(TorchExportStrategy, batch_size=32, seed=(1, 2, 3))
    batches = list(loader)
    assert sum(len(b[1]) for b in batches) == 100  # ragged tail kept
    assert batches[0][0].dtype == torch.float32
    assert batches[0][1].dtype == torch.int64
    assert batches[0][0].shape == (32, 28, 28)

    # seeded: same tuple seed -> same order; different seed -> different
    a = torch.cat([b[1] for b in ds.export(TorchExportStrategy, batch_size=32, seed=(1, 2, 3))])
    b = torch.cat([b[1] for b in ds.export(TorchExportStrategy, batch_size=32, seed=(1, 2, 3))])
    c = torch.cat([b[1] for b in ds.export(TorchExportStrategy, batch_size=32, seed=(9, 9, 9))])
    assert torch.equal(a, b)
    assert not torch.equal(a, c)


def test_export_tf_data_roundtrip():
    pytest.importorskip("tensorflow")
    import numpy as _np

    from p2pfl_tpu.learning.dataset import TensorFlowExportStrategy, synthetic_mnist

    ds = synthetic_mnist(n_train=100, n_test=16)
    tfds = ds.export(TensorFlowExportStrategy, batch_size=32, seed=(4, 5))
    batches = [( _np.asarray(x), _np.asarray(y)) for x, y in tfds]
    assert sum(len(y) for _, y in batches) == 100
    assert batches[0][0].shape == (32, 28, 28)
    # eval export is un-shuffled and label-complete
    te = ds.export(TensorFlowExportStrategy, train=False, batch_size=7)
    ys = _np.concatenate([_np.asarray(y) for _, y in te])
    _, y_test = ds.export_arrays(train=False)
    np.testing.assert_array_equal(ys, y_test)


# --- byzantine poisoning ------------------------------------------------------


def test_poison_partitions_label_flip():
    from p2pfl_tpu.learning.dataset import (
        RandomIIDPartitionStrategy,
        poison_partitions,
        synthetic_mnist,
    )

    parts = synthetic_mnist(n_train=200, n_test=32).generate_partitions(
        10, RandomIIDPartitionStrategy
    )
    poisoned_parts, idx = poison_partitions(parts, 0.2, num_classes=10, seed=1)
    assert len(idx) == 2
    for i, (orig, pois) in enumerate(zip(parts, poisoned_parts)):
        xo, yo = orig.export_arrays(True)
        xp, yp = pois.export_arrays(True)
        np.testing.assert_array_equal(xo, xp)  # inputs untouched
        if i in idx:
            np.testing.assert_array_equal(yp, (yo + 1) % 10)
            # test split stays clean: evaluation measures true accuracy
            _, yt_o = orig.export_arrays(False)
            _, yt_p = pois.export_arrays(False)
            np.testing.assert_array_equal(yt_o, yt_p)
        else:
            assert pois is orig


def test_synthetic_cifar10_shape_and_learnability_proxy():
    from p2pfl_tpu.learning.dataset import synthetic_cifar10

    ds = synthetic_cifar10(n_train=64, n_test=32, image_size=16)
    x, y = ds.export_arrays(True)
    assert x.shape == (64, 16, 16, 3) and x.dtype == np.float32
    assert x.min() >= 0.0 and x.max() <= 1.0
    # Learnability: a sample sits closer to its OWN class mean than to other
    # class means (the class template structure survives the noise).
    means = np.stack([x[y == c].mean(axis=0) for c in np.unique(y)])
    classes = list(np.unique(y))
    d = np.sqrt(((x[:, None] - means[None]) ** 2).sum(axis=(2, 3, 4)))  # [n, C]
    nearest = np.array(classes)[np.argmin(d, axis=1)]
    assert (nearest == y).mean() > 0.9, (nearest == y).mean()
    ds2 = synthetic_cifar10(n_train=64, n_test=32, image_size=16)
    x2, y2 = ds2.export_arrays(True)
    np.testing.assert_array_equal(y, y2)  # deterministic
