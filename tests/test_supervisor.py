"""Engine supervisor: write-ahead journaling, self-healing bit-exact
resume, the degrade ladder, torn-checkpoint tolerance, and the digest's
optional supervisor fields.

Every assertion here is deterministic: host-fault traces are seeded pure
functions, the supervisor's ``events`` tuple is timestamp-free by
construction, and healed params hashes are compared bit-for-bit against
fault-free controls (`make soak-check` runs the same contract at the
64-node shape on both engines).
"""

from __future__ import annotations

import os
import shutil

import pytest

from p2pfl_tpu.chaos.plane import ChaosPlane, HostFaultEvent
from p2pfl_tpu.management.checkpoint import FLCheckpointer
from p2pfl_tpu.population import EngineSupervisor, PopulationEngine
from p2pfl_tpu.telemetry.ledger import canonical_params_hash

_SHAPE = dict(
    cohort_fraction=0.5, cohort_min=2, seed=11,
    samples_per_node=8, feature_dim=8, hidden=(4,), batch_size=4,
)


def _factory(**kw):
    args = dict(num_nodes=6, **_SHAPE)
    args.update(kw)
    return PopulationEngine(**args)


# --- seeded fault traces ------------------------------------------------------


def test_plan_host_faults_seeded_one_slot_per_kind():
    plane = ChaosPlane()
    trace = plane.plan_host_faults(10, seed=7)
    assert trace == plane.plan_host_faults(10, seed=7)  # pure in the seed
    assert trace != plane.plan_host_faults(10, seed=8)
    assert len(trace) == 3  # one slot per default kind
    assert {ev.kind for ev in trace} == {"kill", "oom", "sigterm"}
    whens = [ev.when for ev in trace]
    assert len(set(whens)) == len(whens)  # drawn without replacement
    assert all(1 <= w < 10 for w in whens)  # start=1: never before chunk 1
    assert list(trace) == sorted(trace, key=lambda ev: (ev.when, ev.kind))


def test_supervisor_rejects_bad_config():
    with pytest.raises(ValueError, match="degrade"):
        EngineSupervisor(_factory, None, degrade="bogus")
    with pytest.raises(ValueError, match="fault kind"):
        EngineSupervisor(_factory, None, faults=(HostFaultEvent(1, "meteor"),))
    with pytest.raises(ValueError, match="two host faults"):
        EngineSupervisor(
            _factory, None,
            faults=(HostFaultEvent(1, "kill"), HostFaultEvent(1, "oom")),
        )


# --- healing to bit identity --------------------------------------------------


def test_supervised_run_heals_every_fault_kind_bit_exact(tmp_path):
    """kill / OOM / SIGTERM / slow injected across one supervised run: the
    final params hash must equal a fault-free control's, every planned kind
    must actually fire, and the snapshot grafts the RESTARTS / DEGRADE
    columns onto every peer."""
    with _factory() as ctrl:
        ctrl.run(5)
        control_hash = canonical_params_hash(ctrl.gather_params(0))

    faults = (
        HostFaultEvent(1, "kill"),
        HostFaultEvent(2, "oom"),
        HostFaultEvent(3, "sigterm"),
        HostFaultEvent(4, "slow"),
    )
    ck = FLCheckpointer(str(tmp_path))
    with EngineSupervisor(
        _factory, ck, node="sup-test", faults=faults, backoff_s=0.0
    ) as sup:
        report = sup.run(5, chunk=1)
        healed_hash = canonical_params_hash(sup.engine.gather_params(0))
        snap = sup.snapshot(report.results[-1], top_n=4)

    assert not report.parked
    assert report.completed == 5
    assert healed_hash == control_hash  # bit-exact seeded-stream replay
    assert report.faults_executed == faults  # trace fully consumed, in order
    # kill and oom roll back + replay; sigterm journals first (zero rollback
    # window) then restarts; slow only journals defensively.
    assert report.restarts == {"kill": 1, "oom": 1, "sigterm": 1}
    assert report.retries == 2  # kill + oom (sigterm restarts inline)
    assert report.degrade_steps == ()
    assert "fault:kill@1" in report.events
    assert "journal:defensive@4" in report.events
    # the events log is timestamp-free: only action tags with cursor anchors
    assert all("@" in ev and ":" in ev for ev in report.events)
    # fed_top surface: every peer row carries the supervisor columns
    assert snap["supervisor"]["restarts"] == 3
    assert snap["supervisor"]["parked"] is False
    assert all(
        p["restarts"] == 3 and p["degrade"] == 0
        for p in snap["peers"].values()
    )


# --- degrade ladder -----------------------------------------------------------


class _FailingEngine(PopulationEngine):
    """An engine whose chunk launch always dies — drives the full ladder."""

    def run(self, *a, **kw):  # noqa: D102 - synthetic failure
        raise RuntimeError("synthetic chunk failure")


def _failing_factory(**kw):
    args = dict(num_nodes=8, **_SHAPE)
    args.update(kw)
    return _FailingEngine(**args)


def test_degrade_ladder_deterministic_then_park(tmp_path):
    """Retry exhaustion climbs chunk-halving then cohort-halving to the
    plan's min_size floor, then parks — and the whole action sequence is
    replay-identical across supervisors."""
    def run_once(sub):
        ck = FLCheckpointer(str(tmp_path / sub))
        with EngineSupervisor(
            _failing_factory, ck, node=f"sup-degrade-{sub}",
            max_retries=0, backoff_s=0.0, degrade="cohort",
        ) as sup:
            return sup.run(5, chunk=4)

    first = run_once("a")
    assert first.parked and first.park_reason == "runtime"
    assert first.completed == 0
    actions = [a for a, _ in first.degrade_steps]
    assert actions == ["chunks", "chunks", "cohort"]  # 4 -> 2 -> 1, K 4 -> 2
    assert first.chunk_final == 1
    assert first.cohort_final == 2  # halted at the plan's min_size floor
    assert first.events[-1].startswith("park:runtime@")
    assert first.events == run_once("b").events  # deterministic ladder


def test_degrade_off_parks_after_retry_budget(tmp_path):
    ck = FLCheckpointer(str(tmp_path))
    with EngineSupervisor(
        _failing_factory, ck, node="sup-off",
        max_retries=1, backoff_s=0.0, degrade="off",
    ) as sup:
        report = sup.run(2, chunk=1)
    assert report.parked
    assert report.degrade_steps == ()
    assert report.retries == 1  # the budgeted retry, then straight to park


# --- torn-checkpoint tolerance ------------------------------------------------


def _tear_state(ck_dir: str, step: int) -> None:
    """Simulate a kill mid-save: the step's small meta record and commit
    marker survive, but the state files are gone — exactly the incoherent
    shape restore_coherent must skip wholesale."""
    state_dir = os.path.join(ck_dir, str(step), "state")
    assert os.path.isdir(state_dir)
    shutil.rmtree(state_dir)


def test_sync_engine_load_from_skips_torn_newest_step(tmp_path):
    with _factory() as ctrl:
        ctrl.run(3)
        control_hash = canonical_params_hash(ctrl.gather_params(0))

    ck = FLCheckpointer(str(tmp_path))
    with _factory() as victim:
        victim.run(1)
        assert victim.save_to(ck)
        victim.run(1)
        assert victim.save_to(ck)
        ck.wait()
    _tear_state(ck.directory, 2)

    healed_ck = FLCheckpointer(str(tmp_path))  # fresh manager: reads disk
    with _factory() as healed:
        # meta@2 still reads — a per-record walk would hand back cursor 2
        # with state from step 1. The coherent walk falls back wholesale.
        assert healed.load_from(healed_ck) == 1
        healed.run(2)
        assert canonical_params_hash(healed.gather_params(0)) == control_hash


def test_async_engine_load_from_skips_torn_newest_step(tmp_path):
    from p2pfl_tpu.population import AsyncPopulationEngine

    kw = dict(
        num_nodes=6, cohort_fraction=0.5, cohort_min=2, seed=13,
        samples_per_node=8, feature_dim=8, hidden=(4,), batch_size=4,
    )
    with AsyncPopulationEngine(**kw) as ctrl:
        ctrl.run(3)
        control_hash = canonical_params_hash(ctrl.global_params())

    ck = FLCheckpointer(str(tmp_path))
    with AsyncPopulationEngine(**kw) as victim:
        victim.run(1)
        assert victim.save_to(ck)
        victim.run(1)
        assert victim.save_to(ck)
        ck.wait()
    _tear_state(ck.directory, 2)

    healed_ck = FLCheckpointer(str(tmp_path))
    with AsyncPopulationEngine(**kw) as healed:
        assert healed.load_from(healed_ck) == 1
        healed.run(2)
        assert canonical_params_hash(healed.global_params()) == control_hash


# --- digest optional fields (cross-version wire) ------------------------------


def test_digest_supervisor_fields_cross_version_round_trip():
    from p2pfl_tpu.telemetry import digest as digest_mod

    sup = digest_mod.HealthDigest(node="mem://sup", ts=1.0, restarts=3, degrade=1)
    payload = sup.encode()
    assert '"restarts":3' in payload and '"degrade":1' in payload
    back = digest_mod.decode(payload)
    assert back.restarts == 3 and back.degrade == 1
    # A genuine zero survives the wire — distinct from "never supervised".
    zero = digest_mod.decode(
        digest_mod.HealthDigest(node="mem://z", restarts=0, degrade=0).encode()
    )
    assert zero.restarts == 0 and zero.degrade == 0
    # Unsupervised node: fields omitted entirely, old wire shape preserved.
    plain = digest_mod.HealthDigest(node="mem://old", ts=1.0)
    wire = plain.encode()
    assert "restarts" not in wire and "degrade" not in wire
    old = digest_mod.decode(wire)
    assert old.restarts is None and old.degrade is None
