"""Checkpoint/resume tests.

The reference has no checkpointing (SURVEY.md §5 — Lightning checkpoints
disabled, lightning_learner.py:66); this subsystem is the TPU build's
upgrade, so these tests define its contract: round-trip fidelity, retention,
bit-identical simulation resume, and federation-mode per-round snapshots.
"""

from __future__ import annotations

import numpy as np
import pytest

from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
from p2pfl_tpu.management.checkpoint import FLCheckpointer, attach_node_checkpointing
from p2pfl_tpu.models import mlp_model
from p2pfl_tpu.parallel.simulation import MeshSimulation

# resume tests run multi-round sims repeatedly -> excluded from the fast subset
pytestmark = pytest.mark.slow



@pytest.fixture
def parts8():
    data = synthetic_mnist(n_train=8 * 32, n_test=64)
    return data.generate_partitions(8, RandomIIDPartitionStrategy)


def _trees_equal(a, b):
    import jax

    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_model_roundtrip(tmp_path):
    model = mlp_model(seed=3)
    model.contributors = ["a", "b"]
    model.num_samples = 17
    model.additional_info = {"tag": "x", "vec": np.arange(3.0)}
    with FLCheckpointer(str(tmp_path / "ck")) as ck:
        assert ck.save_model(0, model)
        ck.wait()
        restored = ck.restore_model(mlp_model(seed=0))
    _trees_equal(restored.params, model.params)
    assert restored.contributors == ["a", "b"]
    assert restored.num_samples == 17
    assert restored.additional_info["tag"] == "x"
    assert restored.additional_info["vec"] == [0.0, 1.0, 2.0]


def test_retention_and_interval(tmp_path):
    model = mlp_model(seed=0)
    with FLCheckpointer(str(tmp_path / "ck"), max_to_keep=2, save_interval=2) as ck:
        for step in range(5):
            saved = ck.save_model(step, model)
            assert saved == (step % 2 == 0)
        ck.wait()
        assert ck.latest_step() == 4
        assert len(ck.all_steps()) <= 2


def test_restore_missing_raises(tmp_path):
    with FLCheckpointer(str(tmp_path / "empty")) as ck:
        with pytest.raises(FileNotFoundError):
            ck.restore_model(mlp_model(seed=0))


def test_simulation_resume_bit_identical(tmp_path, parts8):
    """4 straight rounds == 2 rounds + checkpoint + restore + 2 rounds."""
    kw = dict(train_set_size=4, batch_size=16, seed=5)

    sim_full = MeshSimulation(mlp_model(seed=0), parts8, **kw)
    res_full = sim_full.run(rounds=4, epochs=1, warmup=False)

    sim_a = MeshSimulation(mlp_model(seed=0), parts8, **kw)
    sim_a.run(rounds=2, epochs=1, warmup=False)
    with FLCheckpointer(str(tmp_path / "sim")) as ck:
        sim_a.save_to(ck)
        ck.wait()

        sim_b = MeshSimulation(mlp_model(seed=0), parts8, **kw)
        assert sim_b.load_from(ck) == 2
    res_b = sim_b.run(rounds=2, epochs=1, warmup=False)

    _trees_equal(sim_full.params_stack, sim_b.params_stack)
    assert res_full.test_acc[2:] == pytest.approx(res_b.test_acc, abs=1e-6)
    assert sim_b.completed_rounds == 4


def test_simulation_run_with_checkpointer(tmp_path, parts8):
    sim = MeshSimulation(mlp_model(seed=0), parts8, train_set_size=4, batch_size=16, seed=1)
    with FLCheckpointer(str(tmp_path / "auto")) as ck:
        sim.run(rounds=3, epochs=1, warmup=False, checkpointer=ck)
        ck.wait()
        assert ck.latest_step() == 3
        assert len(ck.all_steps()) >= 1


def test_simulation_final_round_always_saved(tmp_path, parts8):
    """Off-cadence final chunk still lands on disk (and checkpoint_every=0
    must not crash — it's clamped)."""
    sim = MeshSimulation(mlp_model(seed=0), parts8, train_set_size=4, batch_size=16, seed=1)
    with FLCheckpointer(str(tmp_path / "cad")) as ck:
        sim.run(rounds=3, epochs=1, warmup=False, checkpointer=ck, checkpoint_every=2)
        ck.wait()
        assert ck.latest_step() == 3  # 2 (cadence) and 3 (final)
    sim2 = MeshSimulation(mlp_model(seed=0), parts8, train_set_size=4, batch_size=16, seed=1)
    with FLCheckpointer(str(tmp_path / "zero")) as ck:
        sim2.run(rounds=2, epochs=1, warmup=False, checkpointer=ck, checkpoint_every=0)
        ck.wait()
        assert ck.latest_step() == 2


def test_simulation_resume_adopts_checkpoint_seed(tmp_path, parts8):
    """Resuming under a different constructor seed must not diverge: the
    checkpointed seed wins (round keys are fold_in(key(seed), round))."""
    kw = dict(train_set_size=4, batch_size=16)
    sim_full = MeshSimulation(mlp_model(seed=0), parts8, seed=5, **kw)
    sim_full.run(rounds=3, epochs=1, warmup=False)

    sim_a = MeshSimulation(mlp_model(seed=0), parts8, seed=5, **kw)
    sim_a.run(rounds=1, epochs=1, warmup=False)
    with FLCheckpointer(str(tmp_path / "seed")) as ck:
        sim_a.save_to(ck)
        ck.wait()
        sim_b = MeshSimulation(mlp_model(seed=0), parts8, seed=999, **kw)
        sim_b.load_from(ck)
    assert sim_b.seed == 5
    sim_b.run(rounds=2, epochs=1, warmup=False)
    _trees_equal(sim_full.params_stack, sim_b.params_stack)


def test_jsonable_numpy_scalars(tmp_path):
    model = mlp_model(seed=0)
    model.additional_info = {"acc": np.float32(0.91), "n": np.int64(7)}
    with FLCheckpointer(str(tmp_path / "scal")) as ck:
        ck.save_model(0, model)
        ck.wait()
        restored = ck.restore_model(mlp_model(seed=0))
    assert restored.additional_info["acc"] == pytest.approx(0.91)
    assert restored.additional_info["n"] == 7


def test_orbax_not_imported_by_core():
    """Core import paths (Node/logger/CLI) must not pull in orbax."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import p2pfl_tpu.node, p2pfl_tpu.cli, p2pfl_tpu.management\n"
        "assert not any(m.startswith('orbax') for m in sys.modules), 'orbax imported'\n"
        "print('ok')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120
    )
    assert out.returncode == 0, out.stderr


def test_node_round_end_checkpointing(tmp_path):
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils.utils import wait_convergence, wait_to_finish

    parts = synthetic_mnist(n_train=256, n_test=64).generate_partitions(
        2, RandomIIDPartitionStrategy
    )
    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=16) for i in range(2)]
    with FLCheckpointer(str(tmp_path / "node0"), max_to_keep=5) as ck:
        attach_node_checkpointing(nodes[0], ck)
        for n in nodes:
            n.start()
        try:
            nodes[1].connect(nodes[0].addr)
            wait_convergence(nodes, 1, wait=10)
            nodes[0].set_start_learning(rounds=2, epochs=1)
            wait_to_finish(nodes, timeout=120)
        finally:
            for n in nodes:
                n.stop()
        ck.wait()
        steps = ck.all_steps()
        assert len(steps) >= 2  # one snapshot per finished round
        restored = ck.restore_model(mlp_model(seed=0))
    _trees_equal(restored.params, nodes[0].learner.get_model().params)


def test_node_journal_restores_anchors_and_residuals_bit_exact(tmp_path):
    """The write-ahead journal's contract: a restored node holds the exact
    model params, sparse-delta anchor AND error-feedback residuals it
    journaled — bit-exact, so sparse frames for the journaled round keep
    decoding and no transmitted mass is lost across the restart."""
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.management.checkpoint import NodeJournal
    from p2pfl_tpu.node import Node

    parts = synthetic_mnist(n_train=128, n_test=32).generate_partitions(
        2, RandomIIDPartitionStrategy
    )
    node = Node(mlp_model(seed=3), parts[0], batch_size=16, executor=False)
    node.state.set_experiment("journal", 5)
    node.state.experiment.round = 2
    with Settings.overridden(WIRE_COMPRESSION="topk"):
        model = node.learner.get_model()
        node.state.wire.set_anchor(model.get_parameters(), 2)
        # a real encode populates nonzero EF residuals
        moved = model.build_copy(
            params=[np.asarray(p) + 0.01 for p in model.get_parameters()]
        )
        assert node.state.wire.encode_model(moved, 2) is not None
    before = node.state.wire.export_state()
    assert before["anchor"] is not None and before["residual"] is not None

    with NodeJournal(str(tmp_path / "journal")) as journal:
        assert journal.snapshot(node)
        journal.wait()
        assert not journal.snapshot(node)  # same round: already durable

        restored = Node.resume(
            mlp_model(seed=0), parts[1], journal, batch_size=16, executor=False
        )
    assert restored.addr == node.addr
    after = restored.state.wire.export_state()
    assert after["anchor_round"] == 2
    assert after["anchor_crc"] == before["anchor_crc"]
    for a, b in zip(before["anchor"], after["anchor"]):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(before["residual"], after["residual"]):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(
        node.learner.get_model().get_parameters(),
        restored.learner.get_model().get_parameters(),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    meta = restored._resume_meta
    assert meta["round"] == 2 and meta["fed_mode"] == "sync"


def test_node_crash_restart_resume_roundtrip(tmp_path):
    """Full crash→restart→resume: a 3-node federation loses one journaled
    node mid-round; Node.resume rebuilds it AS ITSELF (same address), it
    re-enters the stage machine mid-experiment, trains real rounds, and the
    federation finishes with the resumed identity contributing."""
    import time

    from p2pfl_tpu.management.checkpoint import NodeJournal, attach_node_journal
    from p2pfl_tpu.node import Node

    n, rounds = 3, 5
    parts = synthetic_mnist(n_train=128 * n, n_test=64).generate_partitions(
        n, RandomIIDPartitionStrategy
    )
    from p2pfl_tpu.config import Settings

    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
    journals = [NodeJournal(str(tmp_path / f"j{i}")) for i in range(n)]
    with Settings.overridden(LOG_LEVEL="WARNING", TRAIN_SET_SIZE=3):
        for nd, journal in zip(nodes, journals):
            attach_node_journal(nd, journal)
            nd.start()
        try:
            from p2pfl_tpu.utils.utils import wait_convergence

            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n - 1, wait=15)
            nodes[0].set_start_learning(rounds=rounds, epochs=1)
            victim = nodes[2]
            victim_addr = victim.addr
            # crash only after the victim's first snapshot is durable — a
            # node that dies before EVER journaling has nothing to resume
            # from (that is cold join territory, not crash-restart)
            deadline = time.time() + 60
            while time.time() < deadline and not journals[2].all_steps():
                time.sleep(0.05)
            assert journals[2].all_steps(), "victim never journaled"
            victim.crash()
            journals[2].wait()

            resumed = Node.resume(
                mlp_model(seed=99), parts[2], journals[2], batch_size=32
            )
            assert resumed.addr == victim_addr  # identity restored from disk
            resumed.start()
            resumed.resume_learning()
            assert resumed.learning_in_progress()
            nodes[2] = resumed

            fin = time.time() + 150
            while time.time() < fin:
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in nodes
                ):
                    break
                time.sleep(0.25)
            else:
                raise AssertionError(
                    {nd.addr: nd.state.current_stage for nd in nodes}
                )
            history = resumed.learning_workflow.history
            assert history[0] == "ResumeStage"
            # the resumed identity ran REAL training rounds after re-entry
            assert history.count("TrainStage") >= 1, history
            assert history.count("RoundFinishedStage") >= 1, history
            accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in nodes]
            assert min(accs) == 1.0, accs
        finally:
            for nd in nodes:
                nd.stop()
            for journal in journals:
                journal.close()


def test_dp_step_counter_survives_resume(tmp_path):
    """Privacy spend must survive checkpoint resume: a fresh object that
    restored N DP rounds and runs more must count ALL noise injected."""
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    data = synthetic_mnist(n_train=128, n_test=32)
    parts = data.generate_partitions(2, RandomIIDPartitionStrategy)

    def make():
        return MeshSimulation(
            mlp_model(seed=0), parts, train_set_size=2, batch_size=32, seed=0,
            dp_clip_norm=1.0, dp_noise_multiplier=0.5,
        )

    ckpt = FLCheckpointer(str(tmp_path / "dp-ckpt"))
    sim = make()
    sim.run(rounds=2, epochs=1, warmup=False, checkpointer=ckpt)
    spent_first = sim.privacy_spent()
    assert spent_first["steps"] == 2 * (64 // 32)

    resumed = make()
    resumed.load_from(ckpt)
    assert resumed.privacy_spent()["steps"] == spent_first["steps"]
    resumed.run(rounds=2, epochs=1, warmup=False)
    assert resumed.privacy_spent()["steps"] == 2 * spent_first["steps"]
    assert resumed.privacy_spent()["epsilon"] > spent_first["epsilon"]
    ckpt.close()


def test_dp_resume_rejects_changed_noise_parameters(tmp_path):
    """Resuming a DP checkpoint under a different sigma would re-price the
    restored steps; load_from must refuse."""
    import pytest

    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.parallel.simulation import MeshSimulation

    data = synthetic_mnist(n_train=128, n_test=32)
    parts = data.generate_partitions(2, RandomIIDPartitionStrategy)

    def make(sigma):
        return MeshSimulation(
            mlp_model(seed=0), parts, train_set_size=2, batch_size=32, seed=0,
            dp_clip_norm=1.0, dp_noise_multiplier=sigma,
        )

    ckpt = FLCheckpointer(str(tmp_path / "dp-mismatch"))
    sim = make(0.5)
    sim.run(rounds=1, epochs=1, warmup=False, checkpointer=ckpt)
    with pytest.raises(ValueError, match="re-price"):
        make(2.0).load_from(ckpt)
    # matching parameters restore fine
    ok = make(0.5)
    ok.load_from(ckpt)
    assert ok.privacy_spent()["steps"] == sim.privacy_spent()["steps"]
    ckpt.close()


def test_simulation_fedopt_resume_bit_identical(tmp_path, parts8):
    """FedOpt server state (adam moments in the c_global slot) must survive
    checkpoint + restore: 4 straight rounds == 2 + save/restore + 2. A
    resume that silently re-initialized the server moments would diverge."""
    kw = dict(
        train_set_size=4, batch_size=16, seed=5,
        server_optimizer="fedadam", server_lr=0.003,
    )

    sim_full = MeshSimulation(mlp_model(seed=0), parts8, **kw)
    res_full = sim_full.run(rounds=4, epochs=1, warmup=False)

    sim_a = MeshSimulation(mlp_model(seed=0), parts8, **kw)
    sim_a.run(rounds=2, epochs=1, warmup=False)
    with FLCheckpointer(str(tmp_path / "fedopt")) as ck:
        sim_a.save_to(ck)
        ck.wait()

        sim_b = MeshSimulation(mlp_model(seed=0), parts8, **kw)
        assert sim_b.load_from(ck) == 2
    res_b = sim_b.run(rounds=2, epochs=1, warmup=False)

    _trees_equal(sim_full.params_stack, sim_b.params_stack)
    _trees_equal(sim_full.c_global, sim_b.c_global)
    assert res_full.test_acc[2:] == pytest.approx(res_b.test_acc, abs=1e-6)


def test_fedopt_resume_rejects_changed_server_optimizer(tmp_path, parts8):
    """adam and yogi share a state structure, so a mismatched resume would
    restore cleanly and silently diverge — the meta pin must reject it."""
    kw = dict(train_set_size=4, batch_size=16, seed=5)
    sim_a = MeshSimulation(
        mlp_model(seed=0), parts8, server_optimizer="fedadam",
        server_lr=0.003, **kw,
    )
    sim_a.run(rounds=1, epochs=1, warmup=False)
    with FLCheckpointer(str(tmp_path / "pin")) as ck:
        sim_a.save_to(ck)
        ck.wait()
        for bad in (
            dict(server_optimizer="fedyogi", server_lr=0.003),  # rule swap
            dict(server_optimizer="fedadam", server_lr=0.1),    # lr swap
            dict(),                                             # dropped entirely
        ):
            sim_b = MeshSimulation(mlp_model(seed=0), parts8, **kw, **bad)
            # Only the meta-pin rejection counts: a broad except here once
            # masked unrelated restore crashes as "passing".
            with pytest.raises(ValueError, match="server"):
                sim_b.load_from(ck)
        # The matching config still restores.
        sim_ok = MeshSimulation(
            mlp_model(seed=0), parts8, server_optimizer="fedadam",
            server_lr=0.003, **kw,
        )
        assert sim_ok.load_from(ck) == 1
