"""Communication-layer tests (mirrors reference
test/communication/communication_test.py): protocol guards, command
dispatch, neighbor discovery via heartbeats, disconnect reconvergence, and
abrupt-death detection — over the in-memory transport."""

import time
from typing import Any

import pytest

from p2pfl_tpu.comm.commands.command import Command
from p2pfl_tpu.comm.grpc import GrpcCommunicationProtocol
from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol
from p2pfl_tpu.exceptions import (
    CommunicationError,
    NeighborNotConnectedError,
    ProtocolNotStartedError,
)


class MockCommand(Command):
    def __init__(self):
        self.calls = []

    @staticmethod
    def get_name() -> str:
        return "mock"

    def execute(self, source: str, round: int, *args: str, **kwargs: Any) -> None:
        self.calls.append((source, round, args))


@pytest.fixture(params=[InMemoryCommunicationProtocol, GrpcCommunicationProtocol])
def protocol_class(request):
    """Both transports must satisfy the same behavioral contract (the
    reference parametrizes identically, communication_test.py:57-195)."""
    return request.param


def _mk(n, cls=InMemoryCommunicationProtocol):
    protos = [cls() for _ in range(n)]
    for p in protos:
        p.start()
    return protos


def _wait(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_not_started_raises():
    p = InMemoryCommunicationProtocol()
    with pytest.raises(ProtocolNotStartedError):
        p.connect("mem://nowhere")
    with pytest.raises(ProtocolNotStartedError):
        p.broadcast(p.build_msg("mock"))


def test_invalid_connect_raises(protocol_class):
    (p,) = _mk(1, protocol_class)
    try:
        with pytest.raises(CommunicationError):
            p.connect("mem://does-not-exist" if protocol_class is InMemoryCommunicationProtocol else "127.0.0.1:1")
    finally:
        p.stop()


def test_send_to_unconnected_raises(protocol_class):
    a, b = _mk(2, protocol_class)
    try:
        with pytest.raises(NeighborNotConnectedError):
            a.send(b.addr, a.build_msg("mock"))
    finally:
        a.stop()
        b.stop()


def test_command_dispatch_and_ttl_gossip(protocol_class):
    a, b, c = _mk(3, protocol_class)
    cmds = {}
    for p in (a, b, c):
        cmd = MockCommand()
        cmds[p.addr] = cmd
        p.add_command(cmd)
    try:
        # line: a - b - c
        a.connect(b.addr)
        b.connect(c.addr)
        a.broadcast(a.build_msg("mock", args=["x", "y"], round=3))
        # direct delivery to b, TTL re-gossip to c
        assert _wait(lambda: cmds[b.addr].calls and cmds[c.addr].calls)
        src, rnd, args = cmds[c.addr].calls[0]
        assert src == a.addr and rnd == 3 and args == ("x", "y")
        # dedup: the same message must be executed exactly once per node
        time.sleep(0.5)
        assert len(cmds[b.addr].calls) == 1
        assert len(cmds[c.addr].calls) == 1
    finally:
        for p in (a, b, c):
            p.stop()


def test_neighbor_discovery_via_heartbeats(protocol_class):
    protos = _mk(5, protocol_class)
    try:
        for p in protos[1:]:
            p.connect(protos[0].addr)
        # star topology: heartbeat TTL-gossip should reveal everyone
        assert _wait(
            lambda: all(len(p.get_neighbors(only_direct=False)) == 4 for p in protos),
            timeout=8.0,
        ), {p.addr: p.get_neighbors() for p in protos}
        # direct neighbors stay as-connected
        assert len(protos[0].get_neighbors(only_direct=True)) == 4
        assert all(len(p.get_neighbors(only_direct=True)) == 1 for p in protos[1:])
    finally:
        for p in protos:
            p.stop()


def test_disconnect_reconvergence(protocol_class):
    a, b, c = _mk(3, protocol_class)
    try:
        b.connect(a.addr)
        c.connect(a.addr)
        assert _wait(lambda: len(a.get_neighbors()) == 2)
        c.stop()  # abrupt death
        assert _wait(lambda: c.addr not in a.get_neighbors(), timeout=8.0)
        assert _wait(lambda: c.addr not in b.get_neighbors(only_direct=False), timeout=8.0)
    finally:
        a.stop()
        b.stop()


def test_weights_envelope_roundtrip(protocol_class):
    a, b = _mk(2, protocol_class)
    received = {}

    class WeightsCmd(Command):
        @staticmethod
        def get_name() -> str:
            return "weights_test"

        def execute(self, source, round, *args, **kwargs):
            received.update(kwargs, source=source, round=round)

    b.add_command(WeightsCmd())
    try:
        a.connect(b.addr)
        env = a.build_weights("weights_test", 2, b"PAYLOAD", ["a", "b"], 17)
        a.send(b.addr, env)
        assert _wait(lambda: received)
        assert received["weights"] == b"PAYLOAD"
        assert received["contributors"] == ["a", "b"]
        assert received["num_samples"] == 17
        assert received["round"] == 2
    finally:
        a.stop()
        b.stop()


def test_grpc_mtls_end_to_end(tmp_path):
    """Mutual-TLS gRPC transport with ephemeral CA-signed certs (reference
    ships gen-certs.sh + USE_SSL settings; here the cert tooling is
    programmatic — utils/certificates.py). Covers: secure handshake, command
    dispatch, weights payload."""
    pytest.importorskip(
        "cryptography",
        reason="cert generation needs the cryptography package (absent from "
        "the CI image) — mTLS coverage runs where it is installed",
    )
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.utils.certificates import generate_certificates

    paths = generate_certificates(str(tmp_path))
    received = {}

    class WeightsCmd(Command):
        @staticmethod
        def get_name() -> str:
            return "weights_test"

        def execute(self, source, round, *args, **kwargs):
            received.update(kwargs, source=source, round=round)

    with Settings.overridden(
        USE_SSL=True,
        SSL_CA_CRT=paths["ca_crt"],
        SSL_SERVER_KEY=paths["server_key"],
        SSL_SERVER_CRT=paths["server_crt"],
        SSL_CLIENT_KEY=paths["client_key"],
        SSL_CLIENT_CRT=paths["client_crt"],
    ):
        a, b = _mk(2, GrpcCommunicationProtocol)
        cmd = MockCommand()
        b.add_command(cmd)
        b.add_command(WeightsCmd())
        try:
            a.connect(b.addr)
            assert _wait(lambda: b.addr in a.get_neighbors())
            a.send(b.addr, a.build_msg("mock", args=["secure"], round=1))
            assert _wait(lambda: cmd.calls)
            assert cmd.calls[0][2] == ("secure",)
            a.send(b.addr, a.build_weights("weights_test", 1, b"TLS-PAYLOAD", ["a"], 3))
            assert _wait(lambda: received.get("weights") == b"TLS-PAYLOAD")
        finally:
            a.stop()
            b.stop()


def test_grpc_mtls_rejects_unauthenticated_client(tmp_path):
    """A client without the CA-signed cert must not be able to connect
    (require_client_auth=True on the server)."""
    pytest.importorskip(
        "cryptography",
        reason="cert generation needs the cryptography package (absent from "
        "the CI image) — mTLS coverage runs where it is installed",
    )
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.utils.certificates import generate_certificates

    paths = generate_certificates(str(tmp_path / "good"))
    rogue = generate_certificates(str(tmp_path / "rogue"))  # different CA

    with Settings.overridden(
        USE_SSL=True,
        SSL_CA_CRT=paths["ca_crt"],
        SSL_SERVER_KEY=paths["server_key"],
        SSL_SERVER_CRT=paths["server_crt"],
        SSL_CLIENT_KEY=paths["client_key"],
        SSL_CLIENT_CRT=paths["client_crt"],
    ):
        (server,) = _mk(1, GrpcCommunicationProtocol)
    try:
        # rogue client: trusts the right CA but presents a cert signed by
        # ANOTHER CA -> server-side client-auth must refuse it
        with Settings.overridden(
            USE_SSL=True,
            SSL_CA_CRT=paths["ca_crt"],
            SSL_SERVER_KEY=rogue["server_key"],
            SSL_SERVER_CRT=rogue["server_crt"],
            SSL_CLIENT_KEY=rogue["client_key"],
            SSL_CLIENT_CRT=rogue["client_crt"],
        ):
            (client,) = _mk(1, GrpcCommunicationProtocol)
            try:
                with pytest.raises(CommunicationError):
                    client.connect(server.addr)
            finally:
                client.stop()
    finally:
        server.stop()


def test_unknown_command_is_contained(protocol_class):
    """A version-skewed or malicious peer sending an unregistered command
    must not crash the receiver OR tear down the link: dispatch errors are
    contained at the receiving node (CommunicationProtocol's
    _dispatch_contained logs them), so the gRPC Ack stays CLEAN — an error
    Ack would make the sender treat the link as dead and remove the
    neighbor (the bug this test caught). Registered commands keep working."""
    a, b = _mk(2, protocol_class)
    try:
        cmd = MockCommand()
        b.add_command(cmd)
        a.connect(b.addr)
        assert _wait(lambda: b.addr in a.get_neighbors(only_direct=True))
        # Unknown command: delivery must not raise on the sender and must
        # not kill the receiver.
        a.broadcast(a.build_msg("no-such-command", args=["x"]))
        time.sleep(0.3)
        # The receiver still dispatches registered commands afterwards.
        a.broadcast(a.build_msg("mock", args=["after"]))
        assert _wait(lambda: any(args == ("after",) for _, _, args in cmd.calls))
    finally:
        for p in (a, b):
            p.stop()


def test_proto_schema_not_stale():
    """The committed node_pb2.py must match what protoc generates from
    node.proto (parity with the reference's generate_proto.py tooling,
    reference grpc/proto/generate_proto.py). Skips when protoc is absent;
    when the byte-compare fails but the embedded serialized DESCRIPTOR is
    identical, the diff is protoc codegen drift, not a schema change —
    skip rather than fail."""
    import shutil
    import subprocess
    import sys
    import tempfile
    from pathlib import Path

    if shutil.which("protoc") is None:
        pytest.skip("protoc not on PATH")
    proc = subprocess.run(
        [sys.executable, "-m", "p2pfl_tpu.comm.grpc.generate_proto", "--check"],
        capture_output=True, text=True,
    )
    if proc.returncode != 0:
        from p2pfl_tpu.comm.grpc import generate_proto

        with tempfile.TemporaryDirectory() as td:
            fresh = generate_proto.generate(Path(td)).read_text()
        committed = (
            Path(generate_proto.__file__).parent / "node_pb2.py"
        ).read_text()

        def descriptor_literal(src: str) -> str:
            # The serialized-descriptor bytes literal may itself contain
            # ')' bytes, so a non-greedy regex would truncate it and mask
            # real schema drift; slice from the call to the end of its
            # statement instead (the generated file always follows the
            # AddSerializedFile line with a _builder.Build* call).
            body = src.split("AddSerializedFile(", 1)[1]
            return body.split("_builder.Build", 1)[0].rsplit(")", 1)[0]

        try:
            same = descriptor_literal(fresh) == descriptor_literal(committed)
        except IndexError:
            same = False
        if same:
            pytest.skip("protoc codegen drift with identical schema descriptor")
        pytest.fail(f"node.proto schema drifted from node_pb2.py: {proc.stderr}")
