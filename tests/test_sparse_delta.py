"""Sparse delta wire path tests: jitted top-k kernels, index+values frame
layout, error-feedback residual conservation, codec round trips, corruption
detection, and live federations gossiping sparse deltas end to end."""

import time

import numpy as np
import pytest

from p2pfl_tpu.comm.delta import (
    COALESCE_META_KEY,
    DELTA_META_KEY,
    DeltaWireCodec,
    codec_label,
)
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import DecodingParamsError, DeltaAnchorError
from p2pfl_tpu.ops.compression import (
    CODEC_META_KEY,
    compress_arrays,
    decompress_arrays,
    ef_topk_encode,
    ef_topk_quant_encode,
    pack_nibbles,
    scatter_dense,
    topk_count,
    topk_select,
    unpack_nibbles,
)
from p2pfl_tpu.ops.serialization import (
    decode_sparse_indices,
    encode_sparse_indices,
    serialize_arrays,
)


# --- kernels ------------------------------------------------------------------


def test_topk_select_scatter_roundtrip():
    rng = np.random.default_rng(0)
    flat = rng.normal(size=(4096,)).astype(np.float32)
    k = 409
    idx, vals = topk_select(flat, k)
    assert idx.shape == (k,) and vals.shape == (k,)
    assert (np.diff(idx) > 0).all()  # sorted ascending, unique
    # selected values are exactly the k largest magnitudes
    thresh = np.sort(np.abs(flat))[-k]
    assert (np.abs(vals) >= thresh - 1e-7).all()
    dense = scatter_dense(idx, vals, flat.size)
    np.testing.assert_array_equal(dense[idx], flat[idx])
    mask = np.ones(flat.size, bool)
    mask[idx] = False
    assert (dense[mask] == 0).all()


def test_sparse_index_codecs():
    # dense-ish indices pack as u16 gaps
    idx = np.array([0, 3, 4, 100, 65535 + 90], np.int64)
    packed, codec = encode_sparse_indices(idx)
    assert codec == "gap16" and packed.dtype == np.uint16
    np.testing.assert_array_equal(decode_sparse_indices(packed, codec), idx)
    # a >u16 gap falls back to absolute u32
    idx = np.array([5, 200_000], np.int64)
    packed, codec = encode_sparse_indices(idx)
    assert codec == "abs32" and packed.dtype == np.uint32
    np.testing.assert_array_equal(decode_sparse_indices(packed, codec), idx)
    # unsorted input is a caller bug, loudly
    with pytest.raises(ValueError, match="sorted"):
        encode_sparse_indices(np.array([5, 3], np.int64))


def test_topk_count_bounds():
    assert topk_count(100, 0.1) == 10
    assert topk_count(3, 0.1) == 1  # never zero
    assert topk_count(10, 1.0) == 10
    assert topk_count(7, 0.999) == 7  # never exceeds size


# --- stateless codec ----------------------------------------------------------


def test_topk_full_ratio_float32_is_exact():
    """dense == decode(encode) at k=100% with float32 values — the lossless
    corner pins the layout (selection covers everything, scatter inverts)."""
    rng = np.random.default_rng(1)
    arrays = [
        rng.normal(size=(64, 32)).astype(np.float32),
        rng.normal(size=(7,)).astype(np.float32),
        np.arange(5, dtype=np.int32),  # ints pass through raw
    ]
    enc, spec = compress_arrays(arrays, "topk", ratio=1.0, value_dtype="float32")
    assert [s["codec"] for s in spec] == ["topk", "topk", "raw"]
    assert len(enc) == 5  # 2 parts per sparse tensor + 1 raw
    dec = decompress_arrays(enc, spec)
    for a, b in zip(arrays, dec):
        assert b.dtype == a.dtype and b.shape == a.shape
        np.testing.assert_array_equal(a, b)


def test_topk_partial_ratio_keeps_largest_and_shrinks():
    rng = np.random.default_rng(2)
    a = rng.normal(size=(256, 128)).astype(np.float32)
    enc, spec = compress_arrays([a], "topk", ratio=0.1)
    wire = sum(e.nbytes for e in enc)
    assert wire < a.nbytes / 8  # >=8x smaller (u16 gaps + bf16 values -> ~10x)
    dec = decompress_arrays(enc, spec)[0]
    k = topk_count(a.size, 0.1)
    kept = np.flatnonzero(dec.reshape(-1))
    assert kept.size == k
    # kept values match the original to bf16 precision; dropped decode to 0
    np.testing.assert_allclose(
        dec.reshape(-1)[kept], a.reshape(-1)[kept], rtol=2**-8
    )


def test_topk_nonfinite_ships_raw():
    bad = np.array([np.nan, 1.0, np.inf], np.float32)
    enc, spec = compress_arrays([bad], "topk", ratio=0.5)
    assert spec[0]["codec"] == "raw"
    dec = decompress_arrays(enc, spec)[0]
    assert np.isnan(dec[0]) and np.isinf(dec[2])


# --- error feedback -----------------------------------------------------------


def test_error_feedback_residual_conservation():
    """scatter(sent) + new_residual == delta + old_residual EXACTLY (float32
    values): transmitted and untransmitted positions are disjoint, so no
    floating-point resummation is involved."""
    rng = np.random.default_rng(3)
    delta = rng.normal(size=(2048,)).astype(np.float32)
    residual = rng.normal(scale=0.1, size=(2048,)).astype(np.float32)
    k = 204
    idx, vals, new_resid = ef_topk_encode(delta, residual, k, value_dtype="float32")
    idx, vals, new_resid = np.asarray(idx), np.asarray(vals), np.asarray(new_resid)
    np.testing.assert_array_equal(
        scatter_dense(idx, vals, delta.size) + new_resid, delta + residual
    )
    # transmitted positions are fully drained from the residual
    assert (new_resid[idx] == 0).all()


def test_error_feedback_recovers_tail_over_rounds():
    """What top-k drops is not lost: with a CONSTANT per-round delta, the
    residual grows until every coordinate eventually ships — total
    transmitted mass approaches rounds * delta."""
    rng = np.random.default_rng(4)
    delta = rng.normal(size=(1000,)).astype(np.float32)
    k = 100
    residual = np.zeros_like(delta)
    received = np.zeros_like(delta)
    for _ in range(30):
        idx, vals, residual = ef_topk_encode(delta, residual, k, "float32")
        received += scatter_dense(np.asarray(idx), np.asarray(vals), delta.size)
        residual = np.asarray(residual)
    total = 30.0 * delta
    # conservation: received + residual == total; and the residual is small
    # relative to total (everything but the last few rounds' tail shipped)
    np.testing.assert_allclose(received + residual, total, rtol=1e-5, atol=1e-4)
    assert np.linalg.norm(residual) < 0.2 * np.linalg.norm(total)


def test_ef_bf16_quantization_error_lands_in_residual():
    rng = np.random.default_rng(5)
    delta = rng.normal(size=(512,)).astype(np.float32)
    idx, vals, resid = ef_topk_encode(delta, np.zeros_like(delta), 64, "bf16")
    idx, resid = np.asarray(idx), np.asarray(resid)
    dequant = np.asarray(vals).astype(np.float32)
    # residual at transmitted positions == exact quantization error
    np.testing.assert_array_equal(resid[idx], delta[idx] - dequant)


# --- value quantization (int8 / int4) ----------------------------------------


def test_nibble_pack_roundtrip_and_hostile_ranges():
    rng = np.random.default_rng(7)
    q = rng.integers(-7, 8, size=(33,)).astype(np.int8)  # odd length: padded
    packed = pack_nibbles(q)
    assert packed.dtype == np.uint8 and packed.size == 17
    np.testing.assert_array_equal(unpack_nibbles(packed, q.size), q)
    # reserved 0 nibble (a zero-filled hostile plane) fails the range check
    with pytest.raises(ValueError, match="nibble"):
        unpack_nibbles(np.zeros(4, np.uint8), 8)
    # short buffer fails instead of silently truncating
    with pytest.raises(ValueError, match="shorter"):
        unpack_nibbles(packed[:2], q.size)
    with pytest.raises(ValueError, match="range"):
        pack_nibbles(np.array([9], np.int8))


@pytest.mark.parametrize("bits,qmax", [(8, 127), (4, 7)])
def test_ef_quant_residual_absorbs_quantization_error_exactly(bits, qmax):
    """The EF-conservation contract under integer quantization: the residual
    at transmitted positions is EXACTLY acc - q*scale (one f32 subtraction),
    so encode(x) + residual' == x in the error-feedback sense — quantization
    noise is never lost, it ships in a later round."""
    rng = np.random.default_rng(8)
    delta = rng.normal(size=(2048,)).astype(np.float32)
    residual = rng.normal(scale=0.1, size=(2048,)).astype(np.float32)
    k = 204
    idx, q, scale, new_resid = ef_topk_quant_encode(delta, residual, k, bits)
    idx, q, new_resid = np.asarray(idx), np.asarray(q), np.asarray(new_resid)
    acc = delta + residual
    assert q.dtype == np.int8 and (np.abs(q.astype(np.int16)) <= qmax).all()
    dequant = q.astype(np.float32) * np.float32(scale)
    np.testing.assert_array_equal(new_resid[idx], acc[idx] - dequant)
    # untransmitted positions keep their accumulated mass untouched
    mask = np.ones(acc.size, bool)
    mask[idx] = False
    np.testing.assert_array_equal(new_resid[mask], acc[mask])
    # per-value quantization error bounded by scale/2 (+ rounding epsilon)
    assert float(np.max(np.abs(acc[idx] - dequant))) <= float(scale) * 0.5 + 1e-6


@pytest.mark.parametrize("values", ["int8", "int4"])
@pytest.mark.parametrize("coalesce", [False, True])
def test_quantized_codec_roundtrip(values, coalesce):
    """int8/int4 frames (coalesced and per-tensor) reconstruct the model to
    within the per-tensor quantization grid; codec labels attribute them."""
    from p2pfl_tpu.models import mlp_model

    rng = np.random.default_rng(9)
    sender = mlp_model(seed=0)
    anchor = sender.get_parameters()
    cs, cr = DeltaWireCodec("s"), DeltaWireCodec("r")
    cs.set_anchor(anchor, 1)
    cr.set_anchor(anchor, 1)
    sender.set_parameters(
        [np.asarray(p) + 0.01 * rng.standard_normal(np.asarray(p).shape).astype(np.float32) for p in anchor]
    )
    sender.set_contribution(["s"], 7)
    with Settings.overridden(
        WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=1.0, WIRE_TOPK_VALUES=values,
        COALESCE_ENABLED=coalesce,
    ):
        tagged = cs.encode_tagged(sender, 1)
    assert tagged is not None
    blob, label = tagged
    assert label == codec_label(values) == f"topk-{values}"
    arrays, meta = cr.decode_frame(blob)
    assert meta["contributors"] == ["s"] and meta["num_samples"] == 7
    assert (meta.get(COALESCE_META_KEY) is not None) == coalesce
    for got, want, anc in zip(arrays, sender.get_parameters(), anchor):
        got32 = np.asarray(got, dtype=np.float32)
        want32 = np.asarray(want, dtype=np.float32)
        # worst case = half a grid step of the per-tensor scale
        delta = want32 - np.asarray(anc, dtype=np.float32)
        qmax = 127 if values == "int8" else 7
        bound = float(np.max(np.abs(delta))) / qmax + 1e-6
        assert float(np.max(np.abs(got32 - want32))) <= bound


def test_quant_min_values_floor_keeps_small_tensors_bf16():
    """Tensors whose top-k keeps fewer than QUANT_MIN_VALUES values ship
    bf16 — a scale header on a 3-value bias costs more than it saves."""
    from p2pfl_tpu.ops.serialization import deserialize_arrays

    codec = DeltaWireCodec("s")
    big = np.zeros((4096,), np.float32)
    small = np.zeros((4,), np.float32)

    class _M:
        contributors = ["s"]
        num_samples = 1
        additional_info: dict = {}

        def get_parameters(self):
            return [big + 0.5, small + 0.5]

    codec.set_anchor([big, small], 0)
    with Settings.overridden(
        WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=0.1, WIRE_TOPK_VALUES="int8",
        COALESCE_ENABLED=True, QUANT_MIN_VALUES=16,
    ):
        blob, label = codec.encode_tagged(_M(), 0)
    assert label == "topk-int8"  # frame label follows the requested codec
    _, meta = deserialize_arrays(bytes(blob))
    kinds = [s.get("values") for s in meta[CODEC_META_KEY]]
    assert kinds == ["int8", "bf16"]  # 409 values quantize; 1 value stays bf16


def test_encode_against_anchor_history_is_stateless():
    """A drain serving a retired round (or an async laggard window) encodes
    against the anchor HISTORY without touching the live EF residuals."""
    from p2pfl_tpu.models import mlp_model

    sender = mlp_model(seed=0)
    anchor0 = sender.get_parameters()
    cs, cr = DeltaWireCodec("s"), DeltaWireCodec("r")
    cs.anchor_history = 2
    cs.set_anchor(anchor0, 0)
    sender.set_parameters([np.asarray(p) + 0.01 for p in anchor0])
    sender.set_contribution(["s"], 1)
    with Settings.overridden(
        WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=1.0, WIRE_TOPK_VALUES="float32",
        COALESCE_ENABLED=False,
    ):
        # advance to round 1: round 0's anchor retires into the history
        anchor1 = sender.get_parameters()
        cs.set_anchor(anchor1, 1)
        resid_before = cs.export_state()["residual"]
        blob = cs.encode_model(sender, 0)  # retired round still encodes
        assert blob is not None
        assert cs.export_state()["residual"] == resid_before  # EF untouched
        assert cs.encode_model(sender, 7) is None  # unknown round: dense
    cr.set_anchor(anchor0, 0)
    arrays, meta = cr.decode_frame(blob)
    assert meta[DELTA_META_KEY]["round"] == 0
    for got, want in zip(arrays, sender.get_parameters()):
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), atol=1e-6
        )


# --- coalesced frames ---------------------------------------------------------


def _coalesced_frame(values="int8"):
    from p2pfl_tpu.models import mlp_model

    rng = np.random.default_rng(10)
    sender = mlp_model(seed=0)
    anchor = sender.get_parameters()
    cs = DeltaWireCodec("s")
    cs.set_anchor(anchor, 1)
    sender.set_parameters(
        [np.asarray(p) + 0.01 * rng.standard_normal(np.asarray(p).shape).astype(np.float32) for p in anchor]
    )
    sender.set_contribution(["s"], 1)
    with Settings.overridden(
        WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=0.1, WIRE_TOPK_VALUES=values,
        COALESCE_ENABLED=True,
    ):
        blob, _ = cs.encode_tagged(sender, 1)
    receiver = DeltaWireCodec("r")
    receiver.set_anchor(anchor, 1)
    return bytes(blob), receiver


def _tampered(blob, mutate):
    """Re-serialize ``blob`` with ``mutate(arrays, meta)`` applied (the CRC
    is recomputed — this simulates a HOSTILE sender, not line corruption)."""
    from p2pfl_tpu.ops.serialization import deserialize_arrays, serialize_arrays

    arrays, meta = deserialize_arrays(blob)
    arrays = [np.asarray(a) for a in arrays]
    out = mutate(arrays, meta)
    if out is not None:
        arrays = out
    return bytes(serialize_arrays(arrays, meta))


def test_coalesced_frame_shrinks_and_roundtrips():
    blob, receiver = _coalesced_frame("int4")
    arrays, meta = receiver.decode_frame(blob)
    assert meta.get(COALESCE_META_KEY) is not None
    assert all(np.isfinite(np.asarray(a, np.float32)).all() for a in arrays)
    # the coalesced int4 body beats the PR 1 per-tensor bf16 layout by >2x
    from p2pfl_tpu.models import mlp_model

    sender = mlp_model(seed=0)
    anchor = sender.get_parameters()
    cs = DeltaWireCodec("s2")
    cs.set_anchor(anchor, 1)
    rng = np.random.default_rng(10)
    sender.set_parameters(
        [np.asarray(p) + 0.01 * rng.standard_normal(np.asarray(p).shape).astype(np.float32) for p in anchor]
    )
    sender.set_contribution(["s"], 1)
    with Settings.overridden(
        WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=0.1, WIRE_TOPK_VALUES="bf16",
        COALESCE_ENABLED=False,
    ):
        baseline, _ = cs.encode_tagged(sender, 1)
    assert len(baseline) > 2 * len(blob), (len(baseline), len(blob))


@pytest.mark.parametrize(
    "name,mutate",
    [
        (
            "nan_scale",
            lambda arrays, meta: [
                s.__setitem__("scale", float("nan"))
                for s in meta[CODEC_META_KEY]
                if s.get("values") in ("int8", "int4")
            ]
            and None,
        ),
        (
            "zero_scale",
            lambda arrays, meta: [
                s.__setitem__("scale", 0.0)
                for s in meta[CODEC_META_KEY]
                if s.get("values") in ("int8", "int4")
            ]
            and None,
        ),
        (
            "hostile_zero_point",
            lambda arrays, meta: [
                s.__setitem__("zero_point", 1e9)
                for s in meta[CODEC_META_KEY]
                if s.get("values") in ("int8", "int4")
            ]
            and None,
        ),
        (
            "extent_mismatch",
            lambda arrays, meta: meta[CODEC_META_KEY][0].__setitem__(
                "idx_bytes", 1 + int(meta[CODEC_META_KEY][0]["idx_bytes"])
            )
            and None,
        ),
        (
            "truncated_plane",
            lambda arrays, meta: arrays[:-1]
            + [np.asarray(arrays[-1])[: max(1, np.asarray(arrays[-1]).size // 2)]],
        ),
        (
            "inflate_bomb",
            lambda arrays, meta: meta[COALESCE_META_KEY]["raw_len"].__setitem__(
                1, 2
            )
            and None,
        ),
    ],
)
def test_hostile_coalesced_frames_rejected_before_anchor(name, mutate):
    """Every hostile mutation of a quantized coalesced frame dies as a
    DecodingParamsError BEFORE any value is dequantized into the anchor —
    the pre-dequantize sanity screen of the wire-speed plane."""
    blob, receiver = _coalesced_frame("int8")
    hostile = _tampered(blob, mutate)
    before = receiver.export_state()
    with pytest.raises(DecodingParamsError):
        receiver.decode_frame(hostile)
    after = receiver.export_state()
    assert after["anchor_round"] == before["anchor_round"]
    for a, b in zip(before["anchor"], after["anchor"]):
        np.testing.assert_array_equal(a, b)
    # the pristine frame still decodes — the codec state survived intact
    arrays, _ = receiver.decode_frame(blob)
    assert all(np.isfinite(np.asarray(a, np.float32)).all() for a in arrays)


def test_old_peer_uncoalesced_f32_frame_still_decodes():
    """Mixed-version wire compat: a frame in the PRE-quantization layout —
    per-tensor index+value arrays, no ``values`` key, no coalesce header —
    decodes through the same entry point (an old peer on the wire)."""
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.ops.serialization import (
        encode_sparse_indices,
        serialize_arrays,
    )

    model = mlp_model(seed=0)
    anchor = model.get_parameters()
    receiver = DeltaWireCodec("r")
    receiver.set_anchor(anchor, 3)
    anchor_crc = receiver.export_state()["anchor_crc"]

    parts, spec = [], []
    rng = np.random.default_rng(11)
    deltas = []
    for leaf in anchor:
        flat = np.zeros(np.asarray(leaf).size, np.float32)
        k = max(1, flat.size // 10)
        pos = np.sort(rng.choice(flat.size, size=k, replace=False))
        vals = rng.normal(size=k).astype(np.float32) * 0.01
        flat[pos] = vals
        deltas.append(flat)
        packed, icodec = encode_sparse_indices(pos.astype(np.int64))
        parts.append(packed)
        parts.append(vals)  # float32 values, exactly the old layout
        spec.append(
            {
                "codec": "topk",
                "dtype": np.asarray(leaf).dtype.str,
                "shape": list(np.asarray(leaf).shape),
                "index_codec": icodec,
                "parts": 2,
            }
        )
    old_frame = bytes(
        serialize_arrays(
            parts,
            {
                "contributors": ["old-peer"],
                "num_samples": 3,
                "additional_info": {},
                CODEC_META_KEY: spec,
                DELTA_META_KEY: {"round": 3, "anchor_crc": anchor_crc},
            },
        )
    )
    arrays, meta = receiver.decode_frame(old_frame)
    assert meta["contributors"] == ["old-peer"]
    for got, anc, d in zip(arrays, anchor, deltas):
        np.testing.assert_allclose(
            np.asarray(got, np.float32).reshape(-1),
            np.asarray(anc, np.float32).reshape(-1) + d,
            atol=1e-6,
        )


def test_quantized_codec_is_parity_exempt_negative_control():
    """The parity plane certifies the DENSE wire (parity.md): a quantized
    sparse round-trip is lossy BY DESIGN, so its reconstruction must not
    hash-match the exact model — the negative control documenting the
    codec-scoped parity exemption."""
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    rng = np.random.default_rng(12)
    sender = mlp_model(seed=0)
    anchor = sender.get_parameters()
    cs, cr = DeltaWireCodec("s"), DeltaWireCodec("r")
    cs.set_anchor(anchor, 1)
    cr.set_anchor(anchor, 1)
    sender.set_parameters(
        [np.asarray(p) + 0.01 * rng.standard_normal(np.asarray(p).shape).astype(np.float32) for p in anchor]
    )
    sender.set_contribution(["s"], 1)
    with Settings.overridden(
        WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=1.0, WIRE_TOPK_VALUES="int4",
        COALESCE_ENABLED=True,
    ):
        blob, _ = cs.encode_tagged(sender, 1)
    arrays, _ = cr.decode_frame(blob)
    assert canonical_params_hash(arrays) != canonical_params_hash(
        sender.get_parameters()
    )


# --- frame integrity ----------------------------------------------------------


def test_sparse_frame_corruption_detected():
    """CRC32 covers the sparse index+values arrays exactly like dense
    weights: corrupting either region fails loudly."""
    rng = np.random.default_rng(6)
    a = rng.normal(size=(128, 64)).astype(np.float32)
    enc, spec = compress_arrays([a], "topk", ratio=0.1)
    blob = bytes(serialize_arrays(list(enc), {CODEC_META_KEY: spec}))
    codec = DeltaWireCodec("t")
    # pristine frame decodes
    arrays, meta = codec.decode_frame(blob)
    assert len(arrays) == 1
    # flip one byte mid-payload (inside the index/values arrays — the frame
    # tail is alignment padding, which is legitimately outside the checksum)
    corrupted = bytearray(blob)
    corrupted[len(blob) // 2] ^= 0xFF
    with pytest.raises(DecodingParamsError, match="CRC32"):
        codec.decode_frame(bytes(corrupted))


def test_stateless_decoder_rejects_delta_frames():
    """ModelHandle.set_parameters(bytes) has no anchor: a sparse delta frame
    must fail loudly instead of silently adopting anchor-less weights."""
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.models.model_handle import decode_wire_frame

    m = mlp_model(seed=0)
    codec = DeltaWireCodec("s")
    codec.set_anchor(m.get_parameters(), 0)
    with Settings.overridden(WIRE_COMPRESSION="topk"):
        blob = codec.encode_model(m, 0)
    assert blob is not None
    with pytest.raises(DecodingParamsError, match="delta"):
        decode_wire_frame(bytes(blob))


def test_encode_parameters_topk_downgrades_to_dense():
    """Anchor-less encode paths (init frames, interop wire) ship dense even
    under WIRE_COMPRESSION='topk' — a config-free receiver must decode."""
    from p2pfl_tpu.models import mlp_model

    m = mlp_model(seed=0)
    with Settings.overridden(WIRE_COMPRESSION="topk"):
        blob = m.encode_parameters()
    receiver = mlp_model(seed=1)
    receiver.set_parameters(bytes(blob))  # plain stateless decode
    for got, want in zip(receiver.get_parameters(), m.get_parameters()):
        np.testing.assert_array_equal(got, want)


# --- codec (anchors + rounds) -------------------------------------------------


def _perturbed(model, eps):
    import jax
    import jax.numpy as jnp

    model.params = jax.tree.map(lambda x: x + eps * jnp.ones_like(x), model.params)
    return model


def test_delta_codec_roundtrip_and_round_gating():
    from p2pfl_tpu.models import mlp_model

    sender, receiver = mlp_model(seed=0), mlp_model(seed=0)
    anchor = sender.get_parameters()
    cs, cr = DeltaWireCodec("s"), DeltaWireCodec("r")
    cs.set_anchor(anchor, 1)
    cr.set_anchor(anchor, 1)
    _perturbed(sender, 0.01)
    sender.set_contribution(["s"], 42)
    with Settings.overridden(WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=1.0,
                             WIRE_TOPK_VALUES="float32"):
        blob = cs.encode_model(sender, 1)
        assert blob is not None
        # wrong round -> dense fallback signal, not a bogus frame
        assert cs.encode_model(sender, 7) is None
    arrays, meta = cr.decode_frame(blob)
    assert meta[DELTA_META_KEY]["round"] == 1
    assert meta["contributors"] == ["s"] and meta["num_samples"] == 42
    for got, want in zip(arrays, sender.get_parameters()):
        np.testing.assert_allclose(got, want, atol=1e-6)
    receiver.apply_frame(arrays, meta)
    assert receiver.contributors == ["s"] and receiver.num_samples == 42

    # receiver without a matching anchor round drops the frame recoverable-y
    stale = DeltaWireCodec("x")
    with pytest.raises(DeltaAnchorError):
        stale.decode_frame(blob)
    stale.set_anchor(anchor, 2)
    with pytest.raises(DeltaAnchorError):
        stale.decode_frame(blob)

    # dense frames pass through the same decode entry point
    dense_blob = sender.encode_parameters(compression="none")
    arrays2, _ = cr.decode_frame(bytes(dense_blob))
    for got, want in zip(arrays2, sender.get_parameters()):
        np.testing.assert_array_equal(got, want)


def test_delta_codec_requires_topk_scheme():
    from p2pfl_tpu.models import mlp_model

    m = mlp_model(seed=0)
    codec = DeltaWireCodec("s")
    codec.set_anchor(m.get_parameters(), 0)
    with Settings.overridden(WIRE_COMPRESSION="none"):
        assert codec.encode_model(m, 0) is None


# --- robust aggregation satellite --------------------------------------------


def test_geometric_median_ignores_inflated_sample_counts():
    """A Byzantine peer claiming a huge num_samples must NOT gain weight:
    GeometricMedian weights contributors uniformly (robust.py)."""
    from p2pfl_tpu.learning.aggregators import GeometricMedian
    from p2pfl_tpu.models.model_handle import ModelHandle

    def _model(val, contributors, num_samples):
        return ModelHandle(
            {"w": np.full((4, 4), val, np.float32)},
            contributors=contributors,
            num_samples=num_samples,
        )

    honest = [_model(2.0, [f"h{i}"], 10) for i in range(4)]
    byz = _model(500.0, ["byz"], 10**9)  # claims a billion samples
    out = GeometricMedian(iters=16).aggregate(honest + [byz])
    np.testing.assert_allclose(
        out.get_parameters()[0], np.full((4, 4), 2.0), atol=0.5
    )


# --- live federations ---------------------------------------------------------


def _run_federation(n_nodes, rounds, seed_offset=0):
    """In-memory federation under current Settings; returns (total model-plane
    TX bytes, mean final accuracy, per-node sparse frame counts)."""
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    data = synthetic_mnist(n_train=256 * n_nodes, n_test=128)
    parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
    nodes = [
        Node(mlp_model(seed=seed_offset + i), parts[i], batch_size=32)
        for i in range(n_nodes)
    ]
    for node in nodes:
        node.start()
    try:
        for i in range(1, n_nodes):
            nodes[i].connect(nodes[0].addr)
        from p2pfl_tpu.utils.utils import wait_convergence

        wait_convergence(nodes, n_nodes - 1, wait=15)
        nodes[0].set_start_learning(rounds=rounds, epochs=1)
        deadline = time.time() + 360
        while time.time() < deadline:
            if all(
                not n.learning_in_progress() and n.learning_workflow is not None
                for n in nodes
            ):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("federation did not finish")
        tx_bytes = sum(n.protocol.gossiper.total_tx_bytes() for n in nodes)
        accs = [n.learner.evaluate()["test_acc"] for n in nodes]
        sparse_frames = sum(n.state.wire.sparse_frames for n in nodes)
        return tx_bytes, float(np.mean(accs)), sparse_frames
    finally:
        for node in nodes:
            node.stop()
        InMemoryRegistry.reset()


def test_e2e_topk_two_nodes_converges_and_shrinks_wire():
    """Fast wire-path e2e: a 2-node federation under topk@10% learns (both
    nodes clear the reference's 0.5 accuracy bar) while gossiping several
    times fewer model-plane bytes than the dense run."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    with Settings.overridden(TRAIN_SET_SIZE=2):
        with Settings.overridden(WIRE_COMPRESSION="none"):
            dense_bytes, dense_acc, _ = _run_federation(2, 2)
        with Settings.overridden(
            WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=0.1, WIRE_TOPK_VALUES="bf16"
        ):
            sparse_bytes, sparse_acc, sparse_frames = _run_federation(2, 2)
    assert sparse_frames > 0, "sparse delta path never engaged"
    assert sparse_acc > 0.5, sparse_acc
    # Init frames stay dense in both runs, and under CI load a lagging peer
    # can draw an extra dense full-model fallback frame in the sparse run —
    # at 2 nodes those dense frames are a large fraction of the total, so
    # the observed ratio swings ~2.9-4.4x. Demand a conservative 2.5x here;
    # the 8-node acceptance run below measures the real >=8x.
    assert dense_bytes > 2.5 * sparse_bytes, (dense_bytes, sparse_bytes)


@pytest.mark.slow
def test_e2e_topk_eight_nodes_acceptance():
    """Acceptance run: 8-node MNIST FedAvg, full committee, topk@10% vs
    dense — >=8x fewer model-plane wire bytes per round, final accuracy
    within 1 percentage point of the dense run."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    rounds = 3
    with Settings.overridden(TRAIN_SET_SIZE=8):
        with Settings.overridden(WIRE_COMPRESSION="none"):
            dense_bytes, dense_acc, _ = _run_federation(8, rounds)
        with Settings.overridden(
            WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=0.1, WIRE_TOPK_VALUES="bf16"
        ):
            sparse_bytes, sparse_acc, sparse_frames = _run_federation(8, rounds)
    assert sparse_frames > 0
    dense_per_round = dense_bytes / rounds
    sparse_per_round = sparse_bytes / rounds
    assert dense_per_round >= 8 * sparse_per_round, (
        f"wire reduction only {dense_per_round / sparse_per_round:.2f}x "
        f"({dense_per_round:.0f} vs {sparse_per_round:.0f} bytes/round)"
    )
    assert sparse_acc >= dense_acc - 0.01, (
        f"topk accuracy {sparse_acc:.4f} fell more than 1pp below "
        f"dense {dense_acc:.4f}"
    )
