"""Sparse delta wire path tests: jitted top-k kernels, index+values frame
layout, error-feedback residual conservation, codec round trips, corruption
detection, and live federations gossiping sparse deltas end to end."""

import time

import numpy as np
import pytest

from p2pfl_tpu.comm.delta import DELTA_META_KEY, DeltaWireCodec
from p2pfl_tpu.config import Settings
from p2pfl_tpu.exceptions import DecodingParamsError, DeltaAnchorError
from p2pfl_tpu.ops.compression import (
    CODEC_META_KEY,
    compress_arrays,
    decompress_arrays,
    ef_topk_encode,
    scatter_dense,
    topk_count,
    topk_select,
)
from p2pfl_tpu.ops.serialization import (
    decode_sparse_indices,
    encode_sparse_indices,
    serialize_arrays,
)


# --- kernels ------------------------------------------------------------------


def test_topk_select_scatter_roundtrip():
    rng = np.random.default_rng(0)
    flat = rng.normal(size=(4096,)).astype(np.float32)
    k = 409
    idx, vals = topk_select(flat, k)
    assert idx.shape == (k,) and vals.shape == (k,)
    assert (np.diff(idx) > 0).all()  # sorted ascending, unique
    # selected values are exactly the k largest magnitudes
    thresh = np.sort(np.abs(flat))[-k]
    assert (np.abs(vals) >= thresh - 1e-7).all()
    dense = scatter_dense(idx, vals, flat.size)
    np.testing.assert_array_equal(dense[idx], flat[idx])
    mask = np.ones(flat.size, bool)
    mask[idx] = False
    assert (dense[mask] == 0).all()


def test_sparse_index_codecs():
    # dense-ish indices pack as u16 gaps
    idx = np.array([0, 3, 4, 100, 65535 + 90], np.int64)
    packed, codec = encode_sparse_indices(idx)
    assert codec == "gap16" and packed.dtype == np.uint16
    np.testing.assert_array_equal(decode_sparse_indices(packed, codec), idx)
    # a >u16 gap falls back to absolute u32
    idx = np.array([5, 200_000], np.int64)
    packed, codec = encode_sparse_indices(idx)
    assert codec == "abs32" and packed.dtype == np.uint32
    np.testing.assert_array_equal(decode_sparse_indices(packed, codec), idx)
    # unsorted input is a caller bug, loudly
    with pytest.raises(ValueError, match="sorted"):
        encode_sparse_indices(np.array([5, 3], np.int64))


def test_topk_count_bounds():
    assert topk_count(100, 0.1) == 10
    assert topk_count(3, 0.1) == 1  # never zero
    assert topk_count(10, 1.0) == 10
    assert topk_count(7, 0.999) == 7  # never exceeds size


# --- stateless codec ----------------------------------------------------------


def test_topk_full_ratio_float32_is_exact():
    """dense == decode(encode) at k=100% with float32 values — the lossless
    corner pins the layout (selection covers everything, scatter inverts)."""
    rng = np.random.default_rng(1)
    arrays = [
        rng.normal(size=(64, 32)).astype(np.float32),
        rng.normal(size=(7,)).astype(np.float32),
        np.arange(5, dtype=np.int32),  # ints pass through raw
    ]
    enc, spec = compress_arrays(arrays, "topk", ratio=1.0, value_dtype="float32")
    assert [s["codec"] for s in spec] == ["topk", "topk", "raw"]
    assert len(enc) == 5  # 2 parts per sparse tensor + 1 raw
    dec = decompress_arrays(enc, spec)
    for a, b in zip(arrays, dec):
        assert b.dtype == a.dtype and b.shape == a.shape
        np.testing.assert_array_equal(a, b)


def test_topk_partial_ratio_keeps_largest_and_shrinks():
    rng = np.random.default_rng(2)
    a = rng.normal(size=(256, 128)).astype(np.float32)
    enc, spec = compress_arrays([a], "topk", ratio=0.1)
    wire = sum(e.nbytes for e in enc)
    assert wire < a.nbytes / 8  # >=8x smaller (u16 gaps + bf16 values -> ~10x)
    dec = decompress_arrays(enc, spec)[0]
    k = topk_count(a.size, 0.1)
    kept = np.flatnonzero(dec.reshape(-1))
    assert kept.size == k
    # kept values match the original to bf16 precision; dropped decode to 0
    np.testing.assert_allclose(
        dec.reshape(-1)[kept], a.reshape(-1)[kept], rtol=2**-8
    )


def test_topk_nonfinite_ships_raw():
    bad = np.array([np.nan, 1.0, np.inf], np.float32)
    enc, spec = compress_arrays([bad], "topk", ratio=0.5)
    assert spec[0]["codec"] == "raw"
    dec = decompress_arrays(enc, spec)[0]
    assert np.isnan(dec[0]) and np.isinf(dec[2])


# --- error feedback -----------------------------------------------------------


def test_error_feedback_residual_conservation():
    """scatter(sent) + new_residual == delta + old_residual EXACTLY (float32
    values): transmitted and untransmitted positions are disjoint, so no
    floating-point resummation is involved."""
    rng = np.random.default_rng(3)
    delta = rng.normal(size=(2048,)).astype(np.float32)
    residual = rng.normal(scale=0.1, size=(2048,)).astype(np.float32)
    k = 204
    idx, vals, new_resid = ef_topk_encode(delta, residual, k, value_dtype="float32")
    idx, vals, new_resid = np.asarray(idx), np.asarray(vals), np.asarray(new_resid)
    np.testing.assert_array_equal(
        scatter_dense(idx, vals, delta.size) + new_resid, delta + residual
    )
    # transmitted positions are fully drained from the residual
    assert (new_resid[idx] == 0).all()


def test_error_feedback_recovers_tail_over_rounds():
    """What top-k drops is not lost: with a CONSTANT per-round delta, the
    residual grows until every coordinate eventually ships — total
    transmitted mass approaches rounds * delta."""
    rng = np.random.default_rng(4)
    delta = rng.normal(size=(1000,)).astype(np.float32)
    k = 100
    residual = np.zeros_like(delta)
    received = np.zeros_like(delta)
    for _ in range(30):
        idx, vals, residual = ef_topk_encode(delta, residual, k, "float32")
        received += scatter_dense(np.asarray(idx), np.asarray(vals), delta.size)
        residual = np.asarray(residual)
    total = 30.0 * delta
    # conservation: received + residual == total; and the residual is small
    # relative to total (everything but the last few rounds' tail shipped)
    np.testing.assert_allclose(received + residual, total, rtol=1e-5, atol=1e-4)
    assert np.linalg.norm(residual) < 0.2 * np.linalg.norm(total)


def test_ef_bf16_quantization_error_lands_in_residual():
    rng = np.random.default_rng(5)
    delta = rng.normal(size=(512,)).astype(np.float32)
    idx, vals, resid = ef_topk_encode(delta, np.zeros_like(delta), 64, "bf16")
    idx, resid = np.asarray(idx), np.asarray(resid)
    dequant = np.asarray(vals).astype(np.float32)
    # residual at transmitted positions == exact quantization error
    np.testing.assert_array_equal(resid[idx], delta[idx] - dequant)


# --- frame integrity ----------------------------------------------------------


def test_sparse_frame_corruption_detected():
    """CRC32 covers the sparse index+values arrays exactly like dense
    weights: corrupting either region fails loudly."""
    rng = np.random.default_rng(6)
    a = rng.normal(size=(128, 64)).astype(np.float32)
    enc, spec = compress_arrays([a], "topk", ratio=0.1)
    blob = bytes(serialize_arrays(list(enc), {CODEC_META_KEY: spec}))
    codec = DeltaWireCodec("t")
    # pristine frame decodes
    arrays, meta = codec.decode_frame(blob)
    assert len(arrays) == 1
    # flip one byte mid-payload (inside the index/values arrays — the frame
    # tail is alignment padding, which is legitimately outside the checksum)
    corrupted = bytearray(blob)
    corrupted[len(blob) // 2] ^= 0xFF
    with pytest.raises(DecodingParamsError, match="CRC32"):
        codec.decode_frame(bytes(corrupted))


def test_stateless_decoder_rejects_delta_frames():
    """ModelHandle.set_parameters(bytes) has no anchor: a sparse delta frame
    must fail loudly instead of silently adopting anchor-less weights."""
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.models.model_handle import decode_wire_frame

    m = mlp_model(seed=0)
    codec = DeltaWireCodec("s")
    codec.set_anchor(m.get_parameters(), 0)
    with Settings.overridden(WIRE_COMPRESSION="topk"):
        blob = codec.encode_model(m, 0)
    assert blob is not None
    with pytest.raises(DecodingParamsError, match="delta"):
        decode_wire_frame(bytes(blob))


def test_encode_parameters_topk_downgrades_to_dense():
    """Anchor-less encode paths (init frames, interop wire) ship dense even
    under WIRE_COMPRESSION='topk' — a config-free receiver must decode."""
    from p2pfl_tpu.models import mlp_model

    m = mlp_model(seed=0)
    with Settings.overridden(WIRE_COMPRESSION="topk"):
        blob = m.encode_parameters()
    receiver = mlp_model(seed=1)
    receiver.set_parameters(bytes(blob))  # plain stateless decode
    for got, want in zip(receiver.get_parameters(), m.get_parameters()):
        np.testing.assert_array_equal(got, want)


# --- codec (anchors + rounds) -------------------------------------------------


def _perturbed(model, eps):
    import jax
    import jax.numpy as jnp

    model.params = jax.tree.map(lambda x: x + eps * jnp.ones_like(x), model.params)
    return model


def test_delta_codec_roundtrip_and_round_gating():
    from p2pfl_tpu.models import mlp_model

    sender, receiver = mlp_model(seed=0), mlp_model(seed=0)
    anchor = sender.get_parameters()
    cs, cr = DeltaWireCodec("s"), DeltaWireCodec("r")
    cs.set_anchor(anchor, 1)
    cr.set_anchor(anchor, 1)
    _perturbed(sender, 0.01)
    sender.set_contribution(["s"], 42)
    with Settings.overridden(WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=1.0,
                             WIRE_TOPK_VALUES="float32"):
        blob = cs.encode_model(sender, 1)
        assert blob is not None
        # wrong round -> dense fallback signal, not a bogus frame
        assert cs.encode_model(sender, 7) is None
    arrays, meta = cr.decode_frame(blob)
    assert meta[DELTA_META_KEY]["round"] == 1
    assert meta["contributors"] == ["s"] and meta["num_samples"] == 42
    for got, want in zip(arrays, sender.get_parameters()):
        np.testing.assert_allclose(got, want, atol=1e-6)
    receiver.apply_frame(arrays, meta)
    assert receiver.contributors == ["s"] and receiver.num_samples == 42

    # receiver without a matching anchor round drops the frame recoverable-y
    stale = DeltaWireCodec("x")
    with pytest.raises(DeltaAnchorError):
        stale.decode_frame(blob)
    stale.set_anchor(anchor, 2)
    with pytest.raises(DeltaAnchorError):
        stale.decode_frame(blob)

    # dense frames pass through the same decode entry point
    dense_blob = sender.encode_parameters(compression="none")
    arrays2, _ = cr.decode_frame(bytes(dense_blob))
    for got, want in zip(arrays2, sender.get_parameters()):
        np.testing.assert_array_equal(got, want)


def test_delta_codec_requires_topk_scheme():
    from p2pfl_tpu.models import mlp_model

    m = mlp_model(seed=0)
    codec = DeltaWireCodec("s")
    codec.set_anchor(m.get_parameters(), 0)
    with Settings.overridden(WIRE_COMPRESSION="none"):
        assert codec.encode_model(m, 0) is None


# --- robust aggregation satellite --------------------------------------------


def test_geometric_median_ignores_inflated_sample_counts():
    """A Byzantine peer claiming a huge num_samples must NOT gain weight:
    GeometricMedian weights contributors uniformly (robust.py)."""
    from p2pfl_tpu.learning.aggregators import GeometricMedian
    from p2pfl_tpu.models.model_handle import ModelHandle

    def _model(val, contributors, num_samples):
        return ModelHandle(
            {"w": np.full((4, 4), val, np.float32)},
            contributors=contributors,
            num_samples=num_samples,
        )

    honest = [_model(2.0, [f"h{i}"], 10) for i in range(4)]
    byz = _model(500.0, ["byz"], 10**9)  # claims a billion samples
    out = GeometricMedian(iters=16).aggregate(honest + [byz])
    np.testing.assert_allclose(
        out.get_parameters()[0], np.full((4, 4), 2.0), atol=0.5
    )


# --- live federations ---------------------------------------------------------


def _run_federation(n_nodes, rounds, seed_offset=0):
    """In-memory federation under current Settings; returns (total model-plane
    TX bytes, mean final accuracy, per-node sparse frame counts)."""
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    data = synthetic_mnist(n_train=256 * n_nodes, n_test=128)
    parts = data.generate_partitions(n_nodes, RandomIIDPartitionStrategy)
    nodes = [
        Node(mlp_model(seed=seed_offset + i), parts[i], batch_size=32)
        for i in range(n_nodes)
    ]
    for node in nodes:
        node.start()
    try:
        for i in range(1, n_nodes):
            nodes[i].connect(nodes[0].addr)
        from p2pfl_tpu.utils.utils import wait_convergence

        wait_convergence(nodes, n_nodes - 1, wait=15)
        nodes[0].set_start_learning(rounds=rounds, epochs=1)
        deadline = time.time() + 360
        while time.time() < deadline:
            if all(
                not n.learning_in_progress() and n.learning_workflow is not None
                for n in nodes
            ):
                break
            time.sleep(0.2)
        else:
            raise TimeoutError("federation did not finish")
        tx_bytes = sum(n.protocol.gossiper.total_tx_bytes() for n in nodes)
        accs = [n.learner.evaluate()["test_acc"] for n in nodes]
        sparse_frames = sum(n.state.wire.sparse_frames for n in nodes)
        return tx_bytes, float(np.mean(accs)), sparse_frames
    finally:
        for node in nodes:
            node.stop()
        InMemoryRegistry.reset()


def test_e2e_topk_two_nodes_converges_and_shrinks_wire():
    """Fast wire-path e2e: a 2-node federation under topk@10% learns (both
    nodes clear the reference's 0.5 accuracy bar) while gossiping several
    times fewer model-plane bytes than the dense run."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    with Settings.overridden(TRAIN_SET_SIZE=2):
        with Settings.overridden(WIRE_COMPRESSION="none"):
            dense_bytes, dense_acc, _ = _run_federation(2, 2)
        with Settings.overridden(
            WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=0.1, WIRE_TOPK_VALUES="bf16"
        ):
            sparse_bytes, sparse_acc, sparse_frames = _run_federation(2, 2)
    assert sparse_frames > 0, "sparse delta path never engaged"
    assert sparse_acc > 0.5, sparse_acc
    # Init frames stay dense in both runs, and under CI load a lagging peer
    # can draw an extra dense full-model fallback frame in the sparse run —
    # at 2 nodes those dense frames are a large fraction of the total, so
    # the observed ratio swings ~2.9-4.4x. Demand a conservative 2.5x here;
    # the 8-node acceptance run below measures the real >=8x.
    assert dense_bytes > 2.5 * sparse_bytes, (dense_bytes, sparse_bytes)


@pytest.mark.slow
def test_e2e_topk_eight_nodes_acceptance():
    """Acceptance run: 8-node MNIST FedAvg, full committee, topk@10% vs
    dense — >=8x fewer model-plane wire bytes per round, final accuracy
    within 1 percentage point of the dense run."""
    Settings.RESOURCE_MONITOR_PERIOD = 0
    rounds = 3
    with Settings.overridden(TRAIN_SET_SIZE=8):
        with Settings.overridden(WIRE_COMPRESSION="none"):
            dense_bytes, dense_acc, _ = _run_federation(8, rounds)
        with Settings.overridden(
            WIRE_COMPRESSION="topk", WIRE_TOPK_RATIO=0.1, WIRE_TOPK_VALUES="bf16"
        ):
            sparse_bytes, sparse_acc, sparse_frames = _run_federation(8, rounds)
    assert sparse_frames > 0
    dense_per_round = dense_bytes / rounds
    sparse_per_round = sparse_bytes / rounds
    assert dense_per_round >= 8 * sparse_per_round, (
        f"wire reduction only {dense_per_round / sparse_per_round:.2f}x "
        f"({dense_per_round:.0f} vs {sparse_per_round:.0f} bytes/round)"
    )
    assert sparse_acc >= dense_acc - 0.01, (
        f"topk accuracy {sparse_acc:.4f} fell more than 1pp below "
        f"dense {dense_acc:.4f}"
    )
