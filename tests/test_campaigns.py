"""Campaign universe: seeded scenario matrix, adaptive-adversary ladder,
invariant grading, campaign-scoped telemetry, perf_diff campaign arms.

Everything seeded here is a PURE function of its integers — the assertions
are exact regression pins (same discipline as tests/test_population.py),
not tolerance tests. The slow end-to-end replays live at the bottom; the
fast subset exercises the sampler/oracle/grader layers on synthetic data.
"""

from __future__ import annotations

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from p2pfl_tpu.campaigns import (
    AXES,
    CAMPAIGN_SCOPED_FAMILIES,
    FAMILIES,
    FAMILY_INVARIANTS,
    build_scenario,
    campaign_id,
    grade_scenario,
    sample_campaign,
)
from p2pfl_tpu.campaigns.invariants import ACCURACY_FLOORS, AGG_WAIT_BOUNDS
from p2pfl_tpu.chaos.plane import (
    ADAPTIVE_LADDER,
    ADAPTIVE_REJECTED_STAGES,
    AdaptiveAdversary,
    ChaosPlane,
    adaptive_attack_schedule,
)
from p2pfl_tpu.config import Settings
from p2pfl_tpu.population.scenarios import PopulationScenario
from p2pfl_tpu.telemetry import REGISTRY

# Register the campaign-scoped metric families these tests read/write
# (counters live in the modules that instrument them).
import p2pfl_tpu.comm.admission  # noqa: F401,E402 — p2pfl_updates_rejected_total
import p2pfl_tpu.learning.aggregators.base  # noqa: F401,E402 — agg wait histogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "campaign_fixtures")

SEED = 20260806


def _clear_scoped():
    REGISTRY.clear_families(CAMPAIGN_SCOPED_FAMILIES)


# --- sampler ------------------------------------------------------------------


def test_sampler_deterministic_distinct_and_prefix_stable():
    full = sample_campaign(SEED, 20)
    again = sample_campaign(SEED, 20)
    assert [c.key for c in full] == [c.key for c in again]
    # Distinctness is an acceptance property (sample_campaign raises on a
    # collision; pin it positively too).
    assert len({c.key for c in full}) == 20
    # Round-robin prefix property: the first k of ANY campaign size are
    # identical — campaign-check replays a true prefix of the full bench.
    for k in (1, 4, 9):
        assert [c.key for c in sample_campaign(SEED, k)] == [
            c.key for c in full[:k]
        ]
    # A different campaign seed is a different campaign.
    assert [c.key for c in sample_campaign(SEED + 1, 20)] != [
        c.key for c in full
    ]


def test_sampler_covers_every_family_and_leads_with_adaptive():
    full = sample_campaign(SEED, 20)
    counts = {}
    for c in full:
        counts[c.family] = counts.get(c.family, 0) + 1
    assert set(counts) == set(FAMILIES)
    assert all(v >= 2 for v in counts.values())
    # The headline family is always in the gate prefix.
    assert full[0].family == "adaptive"
    assert full[0].scenario.adaptive_adversary is not None


def test_build_scenario_is_pure_and_family_axes_hold():
    for family in FAMILIES:
        a, b = build_scenario(SEED, family, 0), build_scenario(SEED, family, 0)
        assert a.key == b.key
        assert a.scenario == b.scenario
        assert FAMILY_INVARIANTS[family]  # every family has a catalog
        assert family in ACCURACY_FLOORS
    adaptive = build_scenario(SEED, "adaptive", 0).scenario
    assert adaptive.adaptive_patience in AXES["adaptive_patience"]
    assert adaptive.rounds == 2 * adaptive.adaptive_patience + 1
    recovery = build_scenario(SEED, "recovery", 0)
    assert recovery.trace is not None and recovery.trace["rounds"] >= 6
    privacy = build_scenario(SEED, "privacy", 0).scenario
    assert privacy.privacy
    byz = build_scenario(SEED, "byzantine", 0).scenario
    assert byz.byzantine  # seeded draw materialized adversaries
    assert all(a == "signflip" for a in byz.byzantine.values())


def test_churn_family_rerolls_to_feasible_committees():
    """The churn builder rejection-samples deterministically: every sampled
    churn scenario's committee schedule derives without starving a round
    (the fused scan's static-shape requirement — the 20-scenario campaign
    originally surfaced an infeasible draw at churn[1])."""
    for index in range(4):
        cs = build_scenario(SEED, "churn", index)
        sched = cs.scenario.schedule(0)  # raises if any round starves
        assert sched.shape[0] == cs.scenario.rounds
        assert cs.scenario.churn_rate in AXES["churn_rate"]
        # Purity: the reroll chain replays identically.
        assert build_scenario(SEED, "churn", index).key == cs.key


def test_host_fault_family_trace_shape():
    """host_fault scenarios carry a seeded (rounds, kinds) trace the
    supervisor_recovered invariant re-derives into a plan_host_faults drill
    — rounds must leave room for one fault slot per kind plus a clean
    chunk, and the draw must replay identically."""
    for index in range(3):
        cs = build_scenario(SEED, "host_fault", index)
        assert cs.trace is not None
        assert tuple(cs.trace["kinds"]) in AXES["host_fault_kinds"]
        assert cs.trace["rounds"] >= len(cs.trace["kinds"]) + 1
        assert build_scenario(SEED, "host_fault", index).key == cs.key
    assert "supervisor_recovered" in FAMILY_INVARIANTS["host_fault"]


def test_campaign_id_shape():
    assert campaign_id(7, 20) == "campaign-s7-n20"


# --- adaptive ladder oracle ---------------------------------------------------


def test_adaptive_attack_schedule_closed_form():
    assert adaptive_attack_schedule(3, patience=1) == (
        "signflip", "scaled", "norm_ride",
    )
    assert adaptive_attack_schedule(5, patience=2) == (
        "signflip", "signflip", "scaled", "scaled", "norm_ride",
    )
    # The terminal stage is absorbing: nothing past norm_ride.
    assert adaptive_attack_schedule(9, patience=1)[2:] == ("norm_ride",) * 7
    assert adaptive_attack_schedule(0) == ()
    with pytest.raises(ValueError):
        adaptive_attack_schedule(3, patience=0)
    with pytest.raises(ValueError):
        adaptive_attack_schedule(3, ladder=())
    assert set(ADAPTIVE_REJECTED_STAGES) < set(ADAPTIVE_LADDER)
    assert ADAPTIVE_LADDER[-1] not in ADAPTIVE_REJECTED_STAGES


def test_adaptive_adversary_live_ladder_matches_oracle():
    """The live observer, fed one attributed rejection per rejected-stage
    round (the campaign guarantee), realizes exactly the pure schedule —
    and reports each escalation as an adaptive_switch chaos fault."""
    _clear_scoped()
    adv_addr = "unit-adv"
    rejected = REGISTRY.get("p2pfl_updates_rejected_total")
    faults = REGISTRY.get("p2pfl_chaos_faults_total")
    assert rejected is not None and faults is not None
    try:
        adv = AdaptiveAdversary(adv_addr, patience=2)
        realized = []
        for rnd in range(7):
            attack = adv.attack_for_round(rnd)
            realized.append(attack)
            if attack in ADAPTIVE_REJECTED_STAGES:
                # An honest receiver rejects and attributes the frame.
                rejected.labels("honest-0", "norm", adv_addr).inc()
        oracle = adaptive_attack_schedule(7, patience=2)
        assert tuple(realized) == oracle
        assert [d["attack"] for d in adv.decisions] == list(oracle)
        switches = sum(
            int(child.value)
            for labels, child in faults.samples()
            if labels.get("node") == adv_addr
            and labels.get("fault") == "adaptive_switch"
        )
        assert switches == sum(1 for a, b in zip(oracle, oracle[1:]) if a != b)
    finally:
        _clear_scoped()


def test_adaptive_adversary_without_rejections_never_escalates():
    """No attributed rejections -> no hits -> the ladder stays on stage 0
    (the adversary only learns from what its peers actually did)."""
    _clear_scoped()
    try:
        adv = AdaptiveAdversary("unit-adv-quiet", patience=1)
        assert [adv.attack_for_round(r) for r in range(4)] == ["signflip"] * 4
    finally:
        _clear_scoped()


def test_adaptive_scenario_validation():
    base = dict(seed=1, n_nodes=6, rounds=3, samples_per_node=32, batch_size=16)
    PopulationScenario(**base, adaptive_adversary=3)  # valid
    with pytest.raises(ValueError, match="observer"):
        PopulationScenario(**base, adaptive_adversary=0)
    with pytest.raises(ValueError, match="n_nodes >= 6"):
        PopulationScenario(
            seed=1, n_nodes=4, rounds=3, samples_per_node=32,
            batch_size=16, adaptive_adversary=1,
        )
    with pytest.raises(ValueError, match="full stable committees"):
        PopulationScenario(**base, adaptive_adversary=3, cohort_fraction=0.5)
    with pytest.raises(ValueError, match="lossless"):
        PopulationScenario(**base, adaptive_adversary=3, drop_rate=0.1)
    with pytest.raises(ValueError, match="byzantine"):
        PopulationScenario(
            **base, adaptive_adversary=3, byzantine_fraction=0.25
        )
    with pytest.raises(ValueError, match="privacy"):
        PopulationScenario(**base, adaptive_adversary=3, privacy=True)


# --- invariant grading (synthetic runs) ---------------------------------------


def _synthetic_run(cs, *, diverge_fused=False, drop_fused_round=False,
                   privacy_events=True):
    """Minimal wire/fused dicts shaped like the scenario runners' output."""
    scn = cs.scenario
    stitched = []
    wire_hashes, fused_hashes = {}, {}
    for r in range(scn.rounds):
        stitched.append({"kind": "round_open", "round": r})
        h = f"hash-{cs.family}-{r}"
        stitched.append({"kind": "aggregate_committed", "round": r, "hash": h})
        if scn.privacy and privacy_events:
            stitched.append({"kind": "privacy_masked", "round": r})
        wire_hashes[r] = h
        fused_hashes[r] = (h + "-fused") if diverge_fused else h
    if drop_fused_round:
        fused_hashes.pop(scn.rounds - 1)
    wire = {"stitched": stitched}
    fused = {"hashes": fused_hashes}
    report = {"status": "DIVERGED" if diverge_fused else "OK"}
    return wire, fused, report


def test_grade_clean_baseline_scenario_passes():
    _clear_scoped()
    cs = build_scenario(SEED, "baseline", 0)
    wire, fused, report = _synthetic_run(cs)
    assert grade_scenario(cs, wire, fused, report) == []


def test_grade_flags_parity_and_missing_rounds():
    _clear_scoped()
    cs = build_scenario(SEED, "baseline", 0)
    wire, fused, report = _synthetic_run(cs, diverge_fused=True)
    names = {v.invariant for v in grade_scenario(cs, wire, fused, report)}
    assert "parity_exact" in names

    wire, fused, report = _synthetic_run(cs, drop_fused_round=True)
    vs = grade_scenario(cs, wire, fused, report)
    names = {v.invariant for v in vs}
    assert "rounds_complete" in names and "parity_exact" in names
    assert all(v.family == "baseline" and v.render() for v in vs)


def test_grade_privacy_family_is_structural():
    """Privacy grades on masked DIVERGENCE (the negative control), not bit
    parity: equal hashes mean masking never engaged."""
    _clear_scoped()
    cs = build_scenario(SEED, "privacy", 0)
    assert "parity_exact" not in FAMILY_INVARIANTS["privacy"]
    wire, fused, report = _synthetic_run(cs, diverge_fused=True)
    assert grade_scenario(cs, wire, fused, report) == []
    # Hashes equal -> masking did not engage -> violation.
    wire, fused, report = _synthetic_run(cs)
    names = {v.invariant for v in grade_scenario(cs, wire, fused, report)}
    assert "masked_divergence" in names
    # No privacy_masked events -> violation.
    wire, fused, report = _synthetic_run(
        cs, diverge_fused=True, privacy_events=False
    )
    names = {v.invariant for v in grade_scenario(cs, wire, fused, report)}
    assert names == {"privacy_engaged"}


def test_grade_adaptive_oracle_and_attribution():
    _clear_scoped()
    cs = build_scenario(SEED, "adaptive", 0)
    scn = cs.scenario
    adv_addr = scn.node_names[scn.adaptive_adversary]
    oracle = list(scn.adaptive_schedule())
    rejected = REGISTRY.get("p2pfl_updates_rejected_total")
    faults = REGISTRY.get("p2pfl_chaos_faults_total")
    try:
        wire, fused, report = _synthetic_run(cs)
        wire["adaptive"] = {
            "decisions": [
                {"round": r, "attack": a, "rejections": r}
                for r, a in enumerate(oracle)
            ]
        }
        # Campaign-true telemetry: honest rejections attribute to the
        # adversary, one adaptive_switch per oracle transition.
        rejected.labels(scn.node_names[0], "norm", adv_addr).inc(3)
        for _ in range(sum(1 for a, b in zip(oracle, oracle[1:]) if a != b)):
            faults.labels(adv_addr, "adaptive_switch").inc()
        assert grade_scenario(cs, wire, fused, report) == []

        # A realized stream that disagrees with the oracle is caught.
        wire["adaptive"]["decisions"][-1]["attack"] = "signflip"
        names = {v.invariant for v in grade_scenario(cs, wire, fused, report)}
        assert "adaptive_oracle" in names
        wire["adaptive"]["decisions"][-1]["attack"] = oracle[-1]

        # Rejections attributed to a bystander are a stray-attribution
        # violation (the observatory must point at the REAL adversary).
        bystander = next(
            n for n in scn.node_names[1:] if n != adv_addr
        )
        rejected.labels(scn.node_names[0], "norm", bystander).inc()
        names = {v.invariant for v in grade_scenario(cs, wire, fused, report)}
        assert "rejection_attribution" in names
    finally:
        _clear_scoped()


def test_grade_recovery_trace_determinism():
    _clear_scoped()
    cs = build_scenario(SEED, "recovery", 0)
    wire, fused, report = _synthetic_run(cs)
    assert grade_scenario(cs, wire, fused, report) == []
    # A recovery scenario stripped of its trace is degenerate.
    broken = type(cs)(
        family=cs.family, index=cs.index, scenario=cs.scenario, trace=None
    )
    names = {v.invariant for v in grade_scenario(broken, wire, fused, report)}
    assert "trace_deterministic" in names


def test_agg_wait_bound_per_family():
    """The lossy-wire family gets the loose bound; the clean ones don't."""
    _clear_scoped()
    assert AGG_WAIT_BOUNDS["chaos_drop"] > 30.0
    hist = REGISTRY.get("p2pfl_aggregation_wait_seconds")
    assert hist is not None
    try:
        hist.labels("wait-unit").observe(45.0)  # gossip re-ship territory
        cs_drop = build_scenario(SEED, "chaos_drop", 0)
        wire, fused, report = _synthetic_run(cs_drop)
        assert grade_scenario(cs_drop, wire, fused, report) == []
        cs_base = build_scenario(SEED, "baseline", 0)
        wire, fused, report = _synthetic_run(cs_base)
        names = {
            v.invariant for v in grade_scenario(cs_base, wire, fused, report)
        }
        assert "agg_wait_bounded" in names
    finally:
        _clear_scoped()


# --- campaign-scoped telemetry reset (satellite) ------------------------------


def test_campaign_scoped_registry_reset_is_selective():
    """clear_families zeroes exactly the campaign-scoped families and
    leaves process-lifetime series (and the family registrations
    themselves) untouched."""
    rejected = REGISTRY.get("p2pfl_updates_rejected_total")
    scenarios_total = REGISTRY.counter(
        "p2pfl_campaign_scenarios_total",
        "Campaign scenarios executed, by family and grading verdict",
        labels=("family", "verdict"),
    )
    rejected.labels("scope-unit", "norm", "scope-adv").inc(5)
    scenarios_total.labels("scope-family", "ok").inc()
    before = sum(
        int(c.value)
        for labels, c in scenarios_total.samples()
        if labels.get("family") == "scope-family"
    )
    REGISTRY.clear_families(CAMPAIGN_SCOPED_FAMILIES)
    assert all(
        int(c.value) == 0
        for labels, c in rejected.samples()
        if labels.get("node") == "scope-unit"
    )
    # Process-lifetime family survived the scoped reset.
    after = sum(
        int(c.value)
        for labels, c in scenarios_total.samples()
        if labels.get("family") == "scope-family"
    )
    assert after == before == 1
    # Unknown names are tolerated (family may not have instrumented yet).
    REGISTRY.clear_families(("p2pfl_not_a_family_total",))


def test_run_campaign_captures_backend_errors_and_restores_scope(monkeypatch):
    """A scenario whose backend run raises becomes a verdict=error entry —
    the campaign completes the rest and the ledger campaign scope is
    restored on the way out."""
    from p2pfl_tpu.campaigns.engine import run_campaign
    from p2pfl_tpu.population import scenarios as pop_scenarios
    from p2pfl_tpu.telemetry.ledger import LEDGERS

    def boom(scn, **kw):
        raise RuntimeError("backend exploded")

    monkeypatch.setattr(pop_scenarios, "run_scenario_wire", boom)
    monkeypatch.setattr(pop_scenarios, "run_scenario_fused", boom)
    rep = run_campaign(SEED, 2, differ=object())
    assert rep["ok"] is False
    assert rep["violations_total"] == 2
    assert [s["verdict"] for s in rep["scenarios"]] == ["error", "error"]
    assert all("backend exploded" in s["error"] for s in rep["scenarios"])
    assert rep["families"]["adaptive"]["violations"] == 1
    assert LEDGERS.campaign == ""  # scope restored after the run


# --- composed chaos trace (satellite) -----------------------------------------


def _compose_trace(seed: int, order: str = "cri"):
    """One seeded lifecycle trace composing all three planners. ``order``
    permutes the CALL order — each planner derives from its own dedicated
    stream, so interleaving must not desync any of them."""
    plane = ChaosPlane()
    names = [f"trace/{i}" for i in range(6)]
    joiners = [f"joiner/{i}" for i in range(2)]
    parts = {}
    calls = {
        "c": lambda: parts.setdefault(
            "churn",
            plane.plan_churn(6, names[1:], joiners, seed=seed, start=1),
        ),
        "r": lambda: parts.setdefault(
            "recovery",
            plane.plan_recovery(
                6, names, seed=seed, crash_round=1, restart_after=1,
                partition_round=2, heal_after=2,
            ),
        ),
        "i": lambda: parts.setdefault(
            "masker",
            plane.plan_masker_dropout(6, names, seed=seed, drop_round=1),
        ),
    }
    for key in order:
        calls[key]()
    return parts["churn"], parts["recovery"], parts["masker"]


def test_composed_trace_deterministic_counts_and_no_desync():
    churn, recovery, masker = _compose_trace(41)
    # Deterministic counts: 5 leavers + 2 joiners, crash/restart +
    # partition/heal, one masker crash.
    assert len(churn) == 7
    assert sorted(e.kind for e in churn) == ["join"] * 2 + ["leave"] * 5
    assert sorted(e.kind for e in recovery) == [
        "crash", "heal", "partition", "restart",
    ]
    assert len(masker) == 1 and masker[0].kind == "crash"
    # No desync: every call order yields the SAME three traces (dedicated
    # per-planner streams — composing them can't perturb any one of them).
    for order in ("cri", "cir", "rci", "ric", "icr", "irc"):
        assert _compose_trace(41, order) == (churn, recovery, masker)
    # And the whole composition replays; a different seed moves it.
    assert _compose_trace(41) == (churn, recovery, masker)
    assert _compose_trace(42) != (churn, recovery, masker)


def test_composed_trace_replay_identical_across_thread_interleavings():
    """Eight threads derive the same composed trace concurrently (each with
    a different planner call order); every thread must observe the identical
    trace — the planners are pure seeded functions with no shared state to
    race on."""
    reference = _compose_trace(1234)
    orders = ("cri", "cir", "rci", "ric", "icr", "irc", "cri", "ric")
    results = [None] * len(orders)
    barrier = threading.Barrier(len(orders))

    def worker(i: int, order: str) -> None:
        barrier.wait()
        results[i] = _compose_trace(1234, order)

    threads = [
        threading.Thread(target=worker, args=(i, o))
        for i, o in enumerate(orders)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r == reference for r in results)


# --- perf_diff campaign arms (satellite) --------------------------------------


def _perf_diff():
    spec = importlib.util.spec_from_file_location(
        "perf_diff_campaign", os.path.join(REPO, "scripts", "perf_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _campaign_doc(ok=20, byz_violations=0, byz_seconds=12.0):
    return {
        "metric": "campaign_scenarios_ok",
        "value": ok,
        "unit": "scenarios",
        "meta": {"schema_version": 1, "git_sha": "x", "backend": "cpu", "seed": 0},
        "extra": {
            "families": {
                "byzantine": {
                    "scenarios": 3, "ok": 3 - byz_violations,
                    "violations": byz_violations, "seconds": byz_seconds,
                },
                "adaptive": {
                    "scenarios": 3, "ok": 3, "violations": 0, "seconds": 40.0,
                },
            },
        },
    }


def test_perf_diff_campaign_family_violations_regress(tmp_path):
    pd = _perf_diff()
    summary = pd.compare(_campaign_doc(), _campaign_doc(byz_violations=2))
    assert "extra.families.byzantine.violations" in summary["regressions"]
    kinds = {r["key"]: r["kind"] for r in summary["rows"]}
    assert kinds["extra.families.byzantine.violations"] == "family-count"
    # Exit code 1 end to end.
    base, cand = tmp_path / "b.json", tmp_path / "c.json"
    base.write_text(json.dumps(_campaign_doc()))
    cand.write_text(json.dumps(_campaign_doc(byz_violations=2)))
    assert pd.main([str(base), str(cand)]) == 1
    # Identical docs pass.
    cand.write_text(json.dumps(_campaign_doc()))
    assert pd.main([str(base), str(cand)]) == 0


def test_perf_diff_campaign_ok_is_higher_is_better():
    pd = _perf_diff()
    # FEWER passing scenarios: robustness regression regardless of speed.
    summary = pd.compare(_campaign_doc(ok=20), _campaign_doc(ok=18))
    assert "value(campaign_scenarios_ok)" in summary["regressions"]
    # MORE passing scenarios is never a regression.
    summary = pd.compare(_campaign_doc(ok=18), _campaign_doc(ok=20))
    assert not summary["regressions"]


def test_perf_diff_campaign_family_seconds_diffed_per_family():
    pd = _perf_diff()
    summary = pd.compare(_campaign_doc(), _campaign_doc(byz_seconds=60.0))
    row = next(
        r
        for r in summary["rows"]
        if r["key"] == "extra.families.byzantine.seconds"
    )
    assert row["regressed"]
    # The other family's timing arm is diffed independently and is quiet.
    adaptive = [
        r
        for r in summary["rows"]
        if r["key"] == "extra.families.adaptive.seconds"
    ]
    assert adaptive and not adaptive[0]["regressed"]


# --- committed baseline fixture -----------------------------------------------


def test_campaign_baseline_fixture_matches_sampler_and_oracle():
    """The committed campaign-check baseline must stay derivable from the
    configured campaign integers: keys re-derive via the sampler, the
    adaptive entry's decision stream equals the pure oracle."""
    path = os.path.join(FIXTURES, "campaign_baseline.json")
    with open(path) as f:
        baseline = json.load(f)
    assert baseline["campaign_seed"] == Settings.CAMPAIGN_SEED
    assert baseline["check_scenarios"] == Settings.CAMPAIGN_CHECK_SCENARIOS
    sampled = sample_campaign(
        baseline["campaign_seed"], baseline["check_scenarios"]
    )
    entries = baseline["scenarios"]
    assert [e["key"] for e in entries] == [c.key for c in sampled]
    assert [e["family"] for e in entries] == [c.family for c in sampled]
    adaptive = [e for e in entries if e["family"] == "adaptive"]
    assert adaptive, "the gate prefix must include the headline family"
    for entry, cs in zip(entries, sampled):
        if entry["family"] != "adaptive":
            continue
        oracle = list(cs.scenario.adaptive_schedule())
        assert [d["attack"] for d in entry["adaptive_decisions"]] == oracle
        # Rejections grow monotonically — the ladder's observed signal.
        rej = [d["rejections"] for d in entry["adaptive_decisions"]]
        assert rej == sorted(rej)
        # Committed hashes cover every round on both backends.
        rounds = [str(r) for r in range(cs.scenario.rounds)]
        assert sorted(entry["wire_hashes"]) == sorted(rounds)
        assert entry["wire_hashes"] == entry["fused_hashes"]


def test_regression_fixture_shape():
    path = os.path.join(FIXTURES, "regression_adaptive_self_screen.json")
    with open(path) as f:
        fix = json.load(f)
    scn = PopulationScenario(**fix["scenario"])
    assert list(scn.adaptive_schedule()) == fix["expected_decisions"]
    assert scn.adaptive_adversary != 0  # index 0 is the observer


# --- permissive admission (the regression's unit surface) ---------------------


def test_permissive_admission_admits_what_the_norm_screen_rejects():
    """The adaptive adversary's own admission is permissive: a frame the
    bootstrap norm bound would reject sails through (an attacker does not
    defend itself — without this the adversary rejected the entire
    federation against its own poisoned model and diverged)."""
    from p2pfl_tpu.comm.admission import AdmissionController

    class _Local:
        def get_parameters(self):
            return [np.ones((4, 4), np.float32)]

    huge = [np.full((4, 4), 1e6, np.float32)]
    _clear_scoped()
    try:
        ctl = AdmissionController("perm-unit")
        assert ctl.screen(huge, _Local(), source="adv", cmd="unit") == "norm"
        ctl.permissive = True
        assert ctl.screen(huge, _Local(), source="adv", cmd="unit") is None
    finally:
        _clear_scoped()


# --- end-to-end regression replay (slow) --------------------------------------


@pytest.mark.slow
def test_regression_adaptive_self_screen_replay():
    """Full both-backend replay of the scenario that surfaced the
    adversary-self-screening divergence: parity must be OK with
    bit-identical hashes and the realized ladder must equal the oracle."""
    from p2pfl_tpu.campaigns.engine import load_parity_differ
    from p2pfl_tpu.campaigns.matrix import CampaignScenario
    from p2pfl_tpu.population.scenarios import (
        run_scenario_fused,
        run_scenario_wire,
    )

    path = os.path.join(FIXTURES, "regression_adaptive_self_screen.json")
    with open(path) as f:
        fix = json.load(f)
    scn = PopulationScenario(**fix["scenario"])
    cs = CampaignScenario(family="adaptive", index=0, scenario=scn)
    _clear_scoped()
    try:
        wire = run_scenario_wire(scn)
        fused = run_scenario_fused(scn)
        report = load_parity_differ().compare_ledgers(
            wire["stitched"], fused["events"]
        )
        assert report["status"] == "OK", report.get("first_divergence")
        assert [d["attack"] for d in wire["adaptive"]["decisions"]] == (
            fix["expected_decisions"]
        )
        violations = grade_scenario(cs, wire, fused, report)
        assert violations == [], [v.render() for v in violations]
    finally:
        _clear_scoped()
