"""Correctness analysis plane: checker regressions over seeded-defect
fixtures, clean-tree + baseline contract, and the runtime lock sentinel.

The fixture assertions are the analyzer's own regression suite: every
seeded bug in tests/analysis_fixtures/ must be flagged by the INTENDED
checker, so a refactor of the AST machinery that blinds a checker fails
here, not in a postmortem."""

from __future__ import annotations

import json
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from p2pfl_tpu.analysis import Baseline, compare, run_checkers
from p2pfl_tpu.analysis.baseline import Suppression
from p2pfl_tpu.analysis.runtime import LockOrderSentinel

REPO = Path(__file__).resolve().parent.parent
TESTS = Path(__file__).resolve().parent


@pytest.fixture(scope="module")
def fixture_findings():
    return run_checkers(TESTS, ("analysis_fixtures",))


def _keys(findings, checker):
    return [f.key for f in findings if f.checker == checker]


# --- seeded-defect regression coverage --------------------------------------


def test_c1_flags_seeded_lock_inversion(fixture_findings):
    keys = _keys(fixture_findings, "C1")
    cycles = [k for k in keys if k.startswith("C1:cycle:")]
    assert any("Ledger._alpha_lock" in k and "Ledger._beta_lock" in k for k in cycles), keys
    assert any("self-deadlock" in k and "Ledger._guard" in k for k in keys), keys


def test_c2_flags_seeded_blocking_send(fixture_findings):
    keys = _keys(fixture_findings, "C2")
    assert any("PeerTable.announce" in k and "send" in k for k in keys), keys
    assert any("time.sleep" in k for k in keys), keys
    assert any("PeerTable.reap" in k and "join" in k for k in keys), keys


def test_c3_flags_seeded_unguarded_writes(fixture_findings):
    keys = _keys(fixture_findings, "C3")
    assert any("ProgressBoard._poll" in k and "rounds_done" in k for k in keys), keys
    assert any("best_score" in k for k in keys), keys


def test_c4_flags_seeded_impure_jit(fixture_findings):
    keys = _keys(fixture_findings, "C4")
    assert any("noisy_step" in k and "inc" in k for k in keys), keys
    assert any("np.random" in k for k in keys), keys
    # fn jitted via call site (jax.jit(_scaled_loss_impl)), not decorator
    assert any("_scaled_loss_impl" in k and "time.time" in k for k in keys), keys


def test_c5_flags_seeded_drift(fixture_findings):
    keys = _keys(fixture_findings, "C5")
    assert any(k.startswith("C5:env:") and "FIXTURE_TURBO" in k for k in keys), keys
    assert "C5:metric:p2pfl_fixture_ghost_total" in keys, keys
    assert "C5:cmd-unhandled:ghost_announce" in keys, keys


def test_intended_checker_only(fixture_findings):
    """Each fixture is flagged by the checker it seeds — C1 findings come
    from the inversion module, C2 from the blocking module, etc. (no
    cross-talk that would make the regression suite ambiguous)."""
    by = {
        "C1": "lock_inversion.py",
        "C2": "blocking_send.py",
        "C3": "unguarded_write.py",
        "C4": "impure_jit.py",
    }
    for checker, path in by.items():
        hits = [f for f in fixture_findings if f.checker == checker]
        assert hits and all(f.path.endswith(path) for f in hits), (checker, hits)


# --- the tree itself stays clean --------------------------------------------


def test_package_tree_clean_against_baseline():
    """`make analyze` as a test: the p2pfl_tpu tree must produce no finding
    outside the committed baseline, and no baseline entry may be stale."""
    findings = run_checkers(REPO, ("p2pfl_tpu",))
    baseline = Baseline.load(REPO / "analysis_baseline.json")
    new, _suppressed, stale = compare(findings, baseline)
    assert not new, "new findings:\n" + "\n".join(f.render() for f in new)
    assert not stale, f"stale suppressions: {[s.key for s in stale]}"


def test_baseline_small_and_reasoned():
    doc = json.loads((REPO / "analysis_baseline.json").read_text())
    sups = doc["suppressions"]
    assert len(sups) <= 10, "baseline growing — fix findings, don't suppress"
    assert all(s.get("reason", "").strip() for s in sups)


# --- baseline + exit-code contract ------------------------------------------


def test_baseline_rejects_reasonless_entries(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(
        json.dumps(
            {"version": 1, "suppressions": [{"checker": "C1", "key": "x", "reason": ""}]}
        )
    )
    with pytest.raises(ValueError, match="reason"):
        Baseline.load(p)


def test_compare_partitions_new_suppressed_stale(fixture_findings):
    some = fixture_findings[0]
    baseline = Baseline(
        [
            Suppression(some.checker, some.key, "seeded fixture"),
            Suppression("C1", "C1:cycle:never-matches", "stale on purpose"),
        ]
    )
    new, suppressed, stale = compare(fixture_findings, baseline)
    assert [f.key for f in suppressed] == [some.key]
    assert len(new) == len(fixture_findings) - 1
    assert [s.key for s in stale] == ["C1:cycle:never-matches"]


def _analyze(*args):
    return subprocess.run(
        [sys.executable, str(REPO / "scripts" / "analyze.py"), *args],
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_cli_exit_codes(tmp_path):
    # 0: clean tree against the committed baseline
    r = _analyze("--baseline", str(REPO / "analysis_baseline.json"))
    assert r.returncode == 0, r.stdout + r.stderr
    # 1: seeded fixtures with no baseline
    r = _analyze("--root", str(TESTS), "--subdirs", "analysis_fixtures")
    assert r.returncode == 1, r.stdout + r.stderr
    # 2: stale suppression over the clean tree
    stale = tmp_path / "stale.json"
    stale.write_text(
        json.dumps(
            {
                "version": 1,
                "suppressions": [
                    {"checker": "C1", "key": "C1:cycle:ghost", "reason": "stale"}
                ],
            }
        )
    )
    r = _analyze("--baseline", str(stale))
    assert r.returncode == 2, r.stdout + r.stderr


def test_finding_keys_are_line_number_free(fixture_findings):
    """Suppression keys must survive refactors that move code: no line
    numbers baked in."""
    for f in fixture_findings:
        for part in f.key.split(":"):
            assert not part.isdigit(), f.key


# --- runtime sentinel --------------------------------------------------------


def test_sentinel_records_and_clears_edges():
    s = LockOrderSentinel()
    with s.patched():
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
    edges = s.edges()
    assert len(edges) == 1
    ((held, acq),) = edges
    assert "test_analysis.py" in held and "test_analysis.py" in acq
    assert s.find_cycle() is None
    s.assert_acyclic()


def test_sentinel_detects_deliberate_inversion():
    s = LockOrderSentinel()
    with s.patched():
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    cycle = s.find_cycle()
    assert cycle is not None
    with pytest.raises(AssertionError, match="cycle"):
        s.assert_acyclic()


def test_sentinel_cross_thread_inversion_detected():
    """The graph is global: thread 1 takes A->B, thread 2 takes B->A —
    never deadlocking in this run, still a reportable inversion."""
    s = LockOrderSentinel()
    with s.patched():
        a = threading.Lock()
        b = threading.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        th1 = threading.Thread(target=t1)
        th1.start()
        th1.join()
        th2 = threading.Thread(target=t2)
        th2.start()
        th2.join()
    assert s.find_cycle() is not None


def test_sentinel_rlock_reentry_is_not_an_edge():
    s = LockOrderSentinel()
    with s.patched():
        r = threading.RLock()
        with r:
            with r:  # reentrant: no self-edge, no cycle
                pass
    assert s.edges() == {}
    s.assert_acyclic()


def test_sentinel_condition_and_event_survive_instrumentation():
    """threading.Condition/Event build on patched locks; the wrapper's
    _release_save/_acquire_restore hooks must keep cond.wait working AND
    the held-stack truthful across the wait."""
    s = LockOrderSentinel()
    with s.patched():
        cond = threading.Condition()
        ready = []

        def waiter():
            with cond:
                while not ready:
                    cond.wait(timeout=2.0)

        t = threading.Thread(target=waiter)
        t.start()
        ev = threading.Event()
        assert not ev.wait(timeout=0.01)
        with cond:
            ready.append(1)
            cond.notify_all()
        t.join(timeout=5.0)
        assert not t.is_alive()
        ev.set()
        assert ev.wait(timeout=1.0)
    s.assert_acyclic()


def test_sentinel_stats_and_reset():
    s = LockOrderSentinel()
    with s.patched():
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
    assert s.stats()["locks"] >= 2
    assert s.stats()["edges"] == 1
    s.reset()
    assert s.stats() == {"locks": 0, "edges": 0}


# --- the C3 fix that fell out of the pass ------------------------------------


def test_note_full_model_round_is_monotonic_and_race_free():
    from p2pfl_tpu.node_state import NodeState

    state = NodeState("test://c3")
    state.note_full_model_round(3)
    state.note_full_model_round(1)  # must not regress
    assert state.last_full_model_round == 3

    # hammer from many threads: the high-water mark must equal the max seen
    state = NodeState("test://c3b")
    barrier = threading.Barrier(8)

    def writer(vals):
        barrier.wait()
        for v in vals:
            state.note_full_model_round(v)

    threads = [
        threading.Thread(target=writer, args=([i, 100 - i, i * 7 % 50],))
        for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert state.last_full_model_round == 100
