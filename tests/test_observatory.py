"""Federation observatory tests: digest wire codec (round trip, absent
digest, unknown-version tolerance), observatory scoring against synthetic
digests, flight-recorder ring bounds + crash dump, Prometheus label
escaping, per-sender rejection attribution, and the heartbeat piggyback end
to end on the in-memory transport."""

from __future__ import annotations

import json
import os
import time

from p2pfl_tpu.telemetry import REGISTRY
from p2pfl_tpu.telemetry import digest as digest_mod
from p2pfl_tpu.telemetry.digest import HealthDigest, collect, decode
from p2pfl_tpu.telemetry.flight_recorder import FlightRecorder
from p2pfl_tpu.telemetry.observatory import Observatory


# --- digest codec ------------------------------------------------------------


def test_digest_encode_decode_round_trip():
    dig = HealthDigest(
        node="mem://node-7",
        ts=123.5,
        round=3,
        total_rounds=10,
        stage="TrainStage",
        steps_per_s=42.5,
        jit_compile_s=1.25,
        tx_bytes=1e6,
        rx_bytes=2e6,
        queue_depth=4,
        agg_waits=3,
        agg_wait_s=7.5,
        contributors=5,
        rejections={"norm": 2.0, "nonfinite": 1.0},
        rejected_by_source={"mem://node-2": 3.0},
        faults_seen=9.0,
        mem_bytes=1 << 20,
    )
    back = decode(dig.encode())
    assert back is not None
    assert back == dig


def test_digest_decode_rejects_garbage():
    assert decode("") is None
    assert decode("not json{") is None
    assert decode(json.dumps([1, 2, 3])) is None
    assert decode(json.dumps({"no_node": True})) is None
    # Oversized payloads are dropped before parsing.
    huge = json.dumps({"node": "n", "stage": "x" * digest_mod.MAX_DIGEST_BYTES})
    assert decode(huge) is None


def test_digest_unknown_version_tolerated():
    """A NEWER digest version must decode best-effort: known fields kept,
    unknown fields and retyped fields ignored."""
    payload = json.dumps(
        {
            "v": 99,
            "node": "mem://future",
            "round": 5,
            "stage": "WarpStage",
            "steps_per_s": "not-a-number",  # retyped in v99 — must not raise
            "frobnication_index": {"deeply": ["nested"]},  # unknown field
            "rejections": {"norm": 1, "bad": "x"},  # partially parseable
        }
    )
    dig = decode(payload)
    assert dig is not None
    assert dig.version == 99
    assert dig.node == "mem://future"
    assert dig.round == 5
    assert dig.stage == "WarpStage"
    assert dig.steps_per_s == 0.0  # retyped field fell back to default
    assert dig.rejections == {"norm": 1.0}


def test_collect_reads_registry_and_state():
    addr = "obs-collect-node"
    REGISTRY.gauge(
        "p2pfl_learner_steps_per_second", "", labels=("node",)
    ).labels(addr).set(17.0)
    REGISTRY.counter(
        "p2pfl_updates_rejected_total", "", labels=("node", "reason", "source")
    ).labels(addr, "norm", "evil-peer").inc(3)

    class _State:
        round = 2
        total_rounds = 5
        current_stage = "TrainStage"

    dig = collect(addr, _State())
    assert dig.node == addr
    assert dig.round == 2 and dig.total_rounds == 5
    assert dig.stage == "TrainStage"
    assert dig.steps_per_s == 17.0
    assert dig.rejected_by_source == {"evil-peer": 3.0}
    assert dig.rejections.get("norm") == 3.0
    assert dig.ts > 0


# --- gRPC control-arg mapping (wire compat without a server) -----------------


def test_grpc_mapping_round_trips_digest_and_trace():
    from p2pfl_tpu.comm.envelope import Envelope
    from p2pfl_tpu.comm.grpc.grpc_protocol import _env_to_pb, _pb_to_env

    dig = HealthDigest(node="n1", ts=1.0, round=2).encode()
    for trace, digest in [("", ""), ("t:s", ""), ("", dig), ("t:s", dig)]:
        env = Envelope(
            source="n1", cmd="beat", args=["123.0"], ttl=3, msg_id=7,
            trace=trace, digest=digest,
        )
        back = _pb_to_env(_env_to_pb(env))
        assert back.args == ["123.0"], (trace, digest)
        assert back.trace == trace
        assert back.digest == digest


def test_grpc_mapping_tolerates_absent_digest_from_old_peer():
    """A pre-digest peer's frame (no reserved args at all) must decode with
    digest == '' — wire compatibility is absence-tolerant by construction."""
    from p2pfl_tpu.comm.grpc import node_pb2
    from p2pfl_tpu.comm.grpc.grpc_protocol import _pb_to_env

    pb = node_pb2.Envelope(source="old-node", cmd="beat")
    pb.control.args.append("456.0")
    pb.control.ttl = 5
    pb.control.msg_id = 9
    env = _pb_to_env(pb)
    assert env.digest == "" and env.trace == ""
    assert env.args == ["456.0"]


# --- observatory scoring -----------------------------------------------------


def _mk(node: str, **kw) -> HealthDigest:
    kw.setdefault("ts", time.time())
    return HealthDigest(node=node, **kw)


def test_observatory_straggler_from_round_lag():
    obs = Observatory("obs-a")
    obs.ingest(_mk("obs-a", round=5, steps_per_s=10.0))
    obs.ingest(_mk("peer-fast", round=5, steps_per_s=10.0))
    obs.ingest(_mk("peer-slow", round=3, steps_per_s=10.0))
    scores = obs.scores()
    assert scores["peer-slow"]["straggler"] >= 2.0
    assert scores["peer-fast"]["straggler"] < scores["peer-slow"]["straggler"]
    assert obs.top("straggler") == "peer-slow"


def test_observatory_straggler_from_step_time_zscore():
    obs = Observatory("obs-b")
    obs.ingest(_mk("obs-b", round=1, steps_per_s=100.0))
    obs.ingest(_mk("peer-1", round=1, steps_per_s=95.0))
    obs.ingest(_mk("peer-crawl", round=1, steps_per_s=2.0))
    assert obs.top("straggler") == "peer-crawl"


def test_observatory_suspect_from_fleet_attribution():
    obs = Observatory("obs-c")
    obs.ingest(_mk("obs-c", round=1, rejected_by_source={"peer-evil": 4.0}))
    obs.ingest(_mk("peer-1", round=1, rejected_by_source={"peer-evil": 2.0}))
    obs.ingest(_mk("peer-evil", round=1))
    scores = obs.scores()
    assert scores["peer-evil"]["suspect"] == 6.0  # summed across observers
    assert obs.top("suspect") == "peer-evil"
    assert obs.top("straggler") is None  # healthy round-wise fleet: no flag


def test_observatory_forget_and_snapshot_shape():
    obs = Observatory("obs-d")
    obs.ingest(_mk("obs-d", round=2))
    obs.ingest(_mk("peer-1", round=2, stage="TrainStage"))
    snap = obs.snapshot()
    assert snap["observer"] == "obs-d"
    assert set(snap["peers"]) == {"obs-d", "peer-1"}
    assert snap["peers"]["peer-1"]["stage"] == "TrainStage"
    assert "straggler" in snap["peers"]["peer-1"]["scores"]
    json.dumps(snap)  # must be JSON-able as-is
    obs.forget("peer-1")
    assert set(obs.scores()) == {"obs-d"}


def test_observatory_ingest_reports_change_and_orders_by_ts():
    obs = Observatory("obs-e")
    assert obs.ingest(_mk("p", round=1, ts=10.0)) is True  # new peer
    assert obs.ingest(_mk("p", round=1, ts=11.0)) is False  # same round/stage
    assert obs.ingest(_mk("p", round=2, ts=12.0)) is True  # round advanced
    # Out-of-order (older ts) must not regress the view.
    assert obs.ingest(_mk("p", round=1, ts=5.0)) is False
    assert obs.scores()["p"]["round"] == 2.0


def test_observatory_exports_fed_metrics():
    obs = Observatory("obs-f")
    obs.ingest(_mk("obs-f", round=4))
    obs.ingest(_mk("peer-lag", round=1))
    fam = REGISTRY.get("p2pfl_fed_straggler_score")
    vals = {
        lbl["peer"]: c.value
        for lbl, c in fam.samples()
        if lbl["node"] == "obs-f"
    }
    assert vals.get("peer-lag", 0.0) >= 3.0
    known = REGISTRY.get("p2pfl_fed_peers_known")
    assert any(
        c.value == 2.0 for lbl, c in known.samples() if lbl["node"] == "obs-f"
    )


# --- flight recorder ---------------------------------------------------------


def test_flight_recorder_ring_bound_counts_drops():
    rec = FlightRecorder("ring-node", capacity=8)
    dropped0 = REGISTRY.get(
        "p2pfl_flightrec_events_dropped_total"
    ).labels("ring-node").value
    for i in range(20):
        rec.record("tick", i=i)
    events = rec.events()
    assert len(events) == 8
    assert [e["i"] for e in events] == list(range(12, 20))  # oldest dropped
    dropped = REGISTRY.get(
        "p2pfl_flightrec_events_dropped_total"
    ).labels("ring-node").value
    assert dropped - dropped0 == 12


def test_flight_recorder_dump_and_sanitized_filename(tmp_path):
    rec = FlightRecorder("mem://node 3:99/x", capacity=16)
    rec.record("stage", stage="TrainStage", round=1)
    rec.record("reject", reason="norm", source="mem://evil")
    path = rec.dump("crash", directory=str(tmp_path))
    assert path is not None and os.path.exists(path)
    assert os.path.basename(path) == "flightrec_mem___node_3_99_x.json"
    with open(path) as f:
        doc = json.load(f)
    assert doc["trigger"] == "crash"
    assert doc["node"] == "mem://node 3:99/x"
    assert [e["kind"] for e in doc["events"]] == ["stage", "reject"]
    assert all("t" in e for e in doc["events"])


def test_flight_recorder_dump_failure_is_contained(tmp_path):
    rec = FlightRecorder("contained-node")
    rec.record("x")
    blocked = tmp_path / "blocked"
    blocked.write_text("a file, not a directory")
    assert rec.dump("crash", directory=str(blocked)) is None  # no raise


# --- prometheus escaping + per-sender attribution ----------------------------


def test_prometheus_label_escaping():
    from p2pfl_tpu.telemetry.export import render_prometheus
    from p2pfl_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("esc_total", 'help with \\ and newline\nhere', labels=("who",))
    c.labels('evil"name\\with\nnewline').inc()
    text = reg and render_prometheus(reg)
    line = [l for l in text.splitlines() if l.startswith("esc_total{")][0]
    assert line == 'esc_total{who="evil\\"name\\\\with\\nnewline"} 1'
    assert line.count("\n") == 0  # one sample = one exposition line
    help_line = [l for l in text.splitlines() if l.startswith("# HELP")][0]
    assert "\\\\" in help_line and "\\n" in help_line


def test_prometheus_nan_value_renders():
    from p2pfl_tpu.telemetry.export import render_prometheus
    from p2pfl_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.gauge("weird_gauge").set(float("nan"))
    assert "weird_gauge NaN" in render_prometheus(reg)


def test_rejections_carry_source_label():
    from p2pfl_tpu.comm.admission import AdmissionController

    adm = AdmissionController("attr-node")
    adm.record("norm", source="mem://evil-1", cmd="partial_model")
    adm.record("norm", source="mem://evil-1", cmd="partial_model")
    adm.record("tree", source="mem://evil-2", cmd="partial_model")
    fam = REGISTRY.get("p2pfl_updates_rejected_total")
    by_src = {}
    for lbl, c in fam.samples():
        if lbl["node"] == "attr-node":
            by_src[(lbl["reason"], lbl["source"])] = c.value
    assert by_src[("norm", "mem://evil-1")] == 2.0
    assert by_src[("tree", "mem://evil-2")] == 1.0
    # rejected_count still aggregates across sources.
    assert adm.rejected_count("norm") == 2
    assert adm.rejected_count() == 3


def test_rejections_feed_flight_recorder():
    from p2pfl_tpu.comm.admission import AdmissionController

    adm = AdmissionController("attr-rec-node")
    rec = FlightRecorder("attr-rec-node", capacity=4)
    adm.recorder = rec
    adm.record("nonfinite", source="mem://evil", cmd="full_model")
    events = rec.events()
    assert events and events[-1]["kind"] == "reject"
    assert events[-1]["source"] == "mem://evil"


# --- tracer span bound -------------------------------------------------------


def test_tracer_bound_drops_oldest_and_counts():
    from p2pfl_tpu.telemetry.tracing import Tracer

    dropped_before = REGISTRY.get("p2pfl_trace_spans_dropped_total").value
    tr = Tracer(max_spans=4)
    for i in range(10):
        with tr.span(f"s{i}", node="n"):
            pass
    spans = tr.spans()
    assert len(spans) == 4
    assert [s.name for s in spans] == ["s6", "s7", "s8", "s9"]
    assert tr.dropped == 6
    assert REGISTRY.get("p2pfl_trace_spans_dropped_total").value - dropped_before == 6


def test_tracer_default_cap_comes_from_settings():
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.telemetry.tracing import Tracer

    with Settings.overridden(TRACE_MAX_SPANS=1234):
        assert Tracer()._spans.maxlen == 1234


# --- heartbeat piggyback end-to-end (in-memory transport) --------------------


def test_digests_ride_heartbeats_in_memory():
    from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry

    a = InMemoryCommunicationProtocol()
    b = InMemoryCommunicationProtocol()
    c = InMemoryCommunicationProtocol()
    c.set_digest_source(None)  # digest-free node: pre-digest wire format
    for p in (a, b, c):
        p.start()
    try:
        b.connect(a.addr)
        c.connect(a.addr)
        addrs = {a.addr, b.addr, c.addr}
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            # a and b must assemble each other (c emits nothing but still
            # ingests); all three keep beating on one shared wire.
            if (
                set(a.observatory.scores()) >= {a.addr, b.addr}
                and set(b.observatory.scores()) >= {a.addr, b.addr}
                and set(c.observatory.scores()) >= {a.addr, b.addr}
            ):
                break
            time.sleep(0.05)
        else:
            raise AssertionError(
                f"digest propagation failed: "
                f"{ {p.addr: sorted(p.observatory.scores()) for p in (a, b, c)} }"
            )
        # The digest-free node never appears in anyone's fleet view...
        assert c.addr not in a.observatory.scores()
        # ...yet stays a first-class member of the federation.
        assert c.addr in a.get_neighbors()
        assert a.addr in c.get_neighbors()
    finally:
        for p in (a, b, c):
            p.stop()
        InMemoryRegistry.reset()
