"""Telemetry plane: registry thread-safety, hot-path overhead, Prometheus
exposition format, JSON snapshot, and trace propagation across the in-memory
transport (ISSUE 2 acceptance: a counter increment stays under ~2µs; spans
on both sides of a wire hop share one trace id)."""

import json
import re
import threading
import time

import pytest

from p2pfl_tpu.telemetry import REGISTRY, TRACER
from p2pfl_tpu.telemetry.export import render_prometheus, snapshot
from p2pfl_tpu.telemetry.metrics import MetricsRegistry
from p2pfl_tpu.telemetry import tracing


# --- registry ---------------------------------------------------------------


def test_counter_thread_safety_under_concurrent_increments():
    """Gossip + heartbeat threads increment shared children concurrently;
    no update may be lost."""
    reg = MetricsRegistry()
    c = reg.counter("t_bytes_total", "b", labels=("node",))
    child = c.labels("n1")
    threads, per_thread = 8, 10_000
    barrier = threading.Barrier(threads)

    def worker():
        barrier.wait()
        for _ in range(per_thread):
            child.inc()

    ts = [threading.Thread(target=worker) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert child.value == threads * per_thread


def test_labels_creation_is_race_free():
    """Concurrent first-touch of the SAME label set must yield one child."""
    reg = MetricsRegistry()
    c = reg.counter("t_race_total", "b", labels=("k",))
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        for i in range(500):
            c.labels(str(i % 10)).inc()

    ts = [threading.Thread(target=worker) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    total = sum(child.value for _, child in c.samples())
    assert total == 8 * 500


def test_histogram_concurrent_observes_conserve_count():
    reg = MetricsRegistry()
    h = reg.histogram("t_wait_seconds", "w", labels=("node",), buckets=(0.1, 1.0))
    child = h.labels("n1")

    def worker():
        for i in range(2_000):
            child.observe(0.05 if i % 2 else 5.0)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    bounds, counts, total, count = child.snapshot()
    assert count == 8_000
    assert sum(counts) == 8_000
    assert counts[0] == 4_000  # <=0.1 bucket
    assert counts[-1] == 4_000  # +Inf bucket


def test_counter_increment_overhead_under_two_microseconds():
    """ISSUE 2 acceptance: the hot-path increment must stay cheap enough to
    live inside gossip ticks. Best-of-5 guards against CI scheduler noise."""
    reg = MetricsRegistry()
    child = reg.counter("t_hot_total", "b", labels=("node",)).labels("n1")
    n = 20_000
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            child.inc()
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 2e-6, f"counter increment costs {best*1e6:.2f}µs"


def test_registry_get_or_create_idempotent_and_kind_checked():
    reg = MetricsRegistry()
    a = reg.counter("t_same_total", "b", labels=("x",))
    assert reg.counter("t_same_total", "b", labels=("x",)) is a
    with pytest.raises(ValueError):
        reg.gauge("t_same_total", "b", labels=("x",))
    with pytest.raises(ValueError):
        reg.counter("t_same_total", "b", labels=("y",))


def test_registry_reset_keeps_module_level_handles_live():
    reg = MetricsRegistry()
    c = reg.counter("t_keep_total", "b", labels=("node",))
    child = c.labels("n1")
    child.inc(5)
    reg.reset()
    assert child.value == 0
    child.inc()  # the pre-reset handle still feeds the registered family
    assert reg.get("t_keep_total").labels("n1").value == 1


def test_counter_rejects_negative_and_gauge_moves_both_ways():
    reg = MetricsRegistry()
    c = reg.counter("t_up_total", "b")
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("t_depth", "d")
    g.set(3)
    g.inc(2)
    g.dec(4)
    assert g.value == 1


# --- exposition -------------------------------------------------------------


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    c = reg.counter("fed_bytes_total", "payload bytes", labels=("node", "cmd"))
    c.labels("n1", "full_model").inc(1024)
    g = reg.gauge("fed_depth", "queue depth", labels=("node",))
    g.labels('we"ird\\n1').set(2)
    h = reg.histogram("fed_wait_seconds", "wait", labels=("node",), buckets=(0.5, 5.0))
    h.labels("n1").observe(0.1)
    h.labels("n1").observe(60.0)

    text = render_prometheus(reg)
    assert "# HELP fed_bytes_total payload bytes\n# TYPE fed_bytes_total counter" in text
    assert 'fed_bytes_total{node="n1",cmd="full_model"} 1024' in text
    # label values escape quotes and backslashes
    assert 'fed_depth{node="we\\"ird\\\\n1"} 2' in text
    # histogram: cumulative buckets, +Inf, _sum/_count
    assert 'fed_wait_seconds_bucket{node="n1",le="0.5"} 1' in text
    assert 'fed_wait_seconds_bucket{node="n1",le="5"} 1' in text
    assert 'fed_wait_seconds_bucket{node="n1",le="+Inf"} 2' in text
    assert 'fed_wait_seconds_count{node="n1"} 2' in text
    assert re.search(r'fed_wait_seconds_sum\{node="n1"\} 60\.1', text)
    # every non-comment line is "name{labels} value"
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            assert re.match(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? \S+$', line), line


def test_snapshot_is_json_roundtrippable_and_complete():
    reg = MetricsRegistry()
    reg.counter("s_total", "c", labels=("node",)).labels("n1").inc(3)
    reg.histogram("s_seconds", "h", buckets=(1.0,)).observe(0.5)
    snap = json.loads(json.dumps(snapshot(reg)))
    assert snap["s_total"]["type"] == "counter"
    assert snap["s_total"]["samples"][0] == {"labels": {"node": "n1"}, "value": 3}
    hist = snap["s_seconds"]["samples"][0]
    assert hist["count"] == 1 and hist["buckets"]["1"] == 1


# --- tracing ----------------------------------------------------------------


def test_span_nesting_parents_and_shares_trace():
    TRACER.reset()
    with TRACER.span("outer", node="n1") as outer_ctx:
        with TRACER.span("inner", node="n1"):
            pass
    inner, outer = TRACER.spans()[-2:]
    assert (inner.name, outer.name) == ("inner", "outer")
    assert inner.trace_id == outer.trace_id == outer_ctx.trace_id
    assert inner.parent_id == outer.span_id
    assert inner.dur_s <= outer.dur_s


def test_wire_context_roundtrip_and_malformed_tolerance():
    assert tracing.parse_wire("") is None
    assert tracing.parse_wire("garbage") is None
    ctx = tracing.SpanContext("aaaa", "bbbb")
    assert tracing.parse_wire(ctx.wire()) == ctx
    with tracing.attach_wire("deadbeef:cafe"):
        assert tracing.current_trace_id() == "deadbeef"
    assert tracing.current_context() is None


def test_trace_propagates_across_in_memory_transport():
    """A control message sent inside a span on node A dispatches inside a
    receiver span on node B with the SAME trace id (the cross-node
    attribution the round tracer depends on)."""
    from p2pfl_tpu.comm.commands.command import Command
    from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol

    got = {}
    done = threading.Event()

    class Probe(Command):
        @staticmethod
        def get_name():
            return "trace_probe"

        def execute(self, source, round, *args, **kwargs):
            got["trace_id"] = tracing.current_trace_id()
            done.set()

    a = InMemoryCommunicationProtocol()
    b = InMemoryCommunicationProtocol()
    b.add_command(Probe())
    a.start()
    b.start()
    try:
        a.connect(b.addr)
        TRACER.reset()
        with TRACER.span("sender_side", node=a.addr) as ctx:
            a.send(b.addr, a.build_msg("trace_probe"))
        assert done.wait(5.0), "probe command never dispatched"
        assert got["trace_id"] == ctx.trace_id
        recv = [s for s in TRACER.spans() if s.name == "recv:trace_probe"]
        assert recv and recv[0].trace_id == ctx.trace_id
        assert recv[0].node == b.addr
    finally:
        a.stop()
        b.stop()


def test_untraced_envelopes_record_no_recv_spans():
    """Heartbeat-style traffic (no ambient span) must not churn the span
    buffer — recv_span is a no-op for an empty wire context."""
    from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol

    a = InMemoryCommunicationProtocol()
    b = InMemoryCommunicationProtocol()
    a.start()
    b.start()
    try:
        a.connect(b.addr)
        TRACER.reset()
        a.send(b.addr, a.build_msg("beat", args=["123.0"]))
        time.sleep(0.3)
        assert [s for s in TRACER.spans() if s.name.startswith("recv:")] == []
    finally:
        a.stop()
        b.stop()


def test_trace_rides_grpc_control_args_and_pflt_header():
    """The gRPC schema has no trace field: control frames carry the context
    as a reserved trailing arg (stripped before dispatch), weights frames in
    the PFLT header's __trace__ slot — both must round-trip."""
    pytest.importorskip("grpc")
    import numpy as np

    from p2pfl_tpu.comm.envelope import Envelope
    from p2pfl_tpu.comm.grpc.grpc_protocol import _env_to_pb, _pb_to_env
    from p2pfl_tpu.models.model_handle import encode_wire_frame
    from p2pfl_tpu.ops.serialization import deserialize_arrays

    with TRACER.span("s", node="n") as ctx:
        env = Envelope.message("127.0.0.1:1", "vote_train_set", args=["a", "5"], round=1)
        blob = encode_wire_frame([np.ones((3,), np.float32)], ["n"], 1, {})
    assert env.trace == ctx.wire()
    back = _pb_to_env(_env_to_pb(env))
    assert back.trace == env.trace
    assert back.args == ["a", "5"]  # sentinel stripped before dispatch

    untraced = Envelope.message("127.0.0.1:1", "beat", args=["1.0"])
    pb = _env_to_pb(untraced)
    assert list(pb.control.args) == ["1.0"]  # no sentinel when untraced
    assert _pb_to_env(pb).trace == ""

    _, meta = deserialize_arrays(bytes(blob))
    assert meta[tracing.TRACE_META_KEY] == ctx.wire()


def test_chrome_trace_export_shape():
    TRACER.reset()
    with TRACER.span("experiment", node="mem://a", round=0):
        with TRACER.span("TrainStage", node="mem://a", round=0):
            pass
    trace = TRACER.export_chrome_trace()
    events = trace["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    spans = [e for e in events if e["ph"] == "X"]
    assert meta and meta[0]["args"]["name"] == "mem://a"
    assert {s["name"] for s in spans} == {"experiment", "TrainStage"}
    for s in spans:
        assert s["dur"] >= 0 and "trace_id" in s["args"]
    json.dumps(trace)  # Perfetto loads plain JSON — must serialize clean


def test_chrome_trace_perfetto_required_fields_and_anchor():
    """Perfetto contract: complete events with µs ts/dur, integer pid/tid,
    ids in args; metadata carries the wall-clock epoch anchor that maps
    span time onto the wall (the cross-process merge key)."""
    TRACER.reset()
    wall_before = time.time()
    with TRACER.span("fit", node="mem://a", round=3):
        time.sleep(0.005)
    trace = TRACER.export_chrome_trace()
    (ev,) = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
    assert ev["dur"] >= 5_000  # microseconds, not seconds/ms
    assert ev["args"]["round"] == 3
    for key in ("trace_id", "span_id", "parent_id"):
        assert key in ev["args"]
    meta = trace["metadata"]
    assert abs(ev["ts"] / 1e6 + meta["wall_epoch_s"] - wall_before) < 5.0
    # Stable ordering: events sorted by ts; re-export is identical modulo
    # the recomputed anchor fields.
    t2 = TRACER.export_chrome_trace()
    assert [e["name"] for e in trace["traceEvents"]] == [
        e["name"] for e in t2["traceEvents"]
    ]


def test_gossiper_tx_counters_mirrored_into_registry():
    """The ad-hoc gossip byte counters now live in the shared registry."""
    from p2pfl_tpu.comm.envelope import Envelope
    from p2pfl_tpu.comm.gossiper import Gossiper

    g = Gossiper("mem://tx-test", send_fn=lambda n, e: None, get_direct_neighbors_fn=list)
    env = Envelope.weights(
        "mem://tx-test", "partial_model", 2, b"x" * 100, ["a"], 1, codec="topk-int8"
    )
    g._record_tx(env)
    fam = REGISTRY.get("p2pfl_gossip_tx_bytes_total")
    assert fam is not None
    assert fam.labels("mem://tx-test", "partial_model", "2", "topk-int8").value == 100
    assert g.bytes_for_round(2) == 100
    assert g.bytes_by_codec() == {"topk-int8": 100}
