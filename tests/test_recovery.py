"""Durable recovery plane tests.

Contract coverage for the three recovery behaviors every real deployment
hits (Papaya: restarts and splits are the NORMAL operating condition):

* torn-snapshot tolerance — a crash mid-save must never poison recovery
  (FLCheckpointer skips incomplete step directories instead of raising);
* heal detection + reconcile — a peer written off during a partition is
  re-discovered by the heartbeater's probe once the partition heals, emits a
  "recover" membership event with fresh scoring state, and whichever side
  is ahead ships its round anchor as a dense catch-up the behind side
  adopts at its next round boundary (split-brain repair, BOTH schedulers);
* quorum-aware degraded mode — below the live-peer quorum a node parks
  (state journaled, heartbeats continue) and unparks on recovery, instead
  of burning a vote timeout per unwinnable round.

The crash→restart→resume journal round-trip lives in tests/test_checkpoint.py
(the journal is a checkpointing contract); these tests cover the protocol and
stage machinery around it.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.telemetry import REGISTRY


def _metric(name: str) -> dict:
    fam = REGISTRY.get(name)
    if fam is None:
        return {}
    return {tuple(labels.values()): child.value for labels, child in fam.samples()}


def _wait(cond, timeout=30.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


# --- torn-snapshot tolerance --------------------------------------------------


def test_torn_step_directories_are_skipped(tmp_path):
    """A bare step directory (crash mid-save) must be invisible to
    latest_step/all_steps, and restore must fall back to the newest GOOD
    snapshot instead of raising."""
    from p2pfl_tpu.management.checkpoint import FLCheckpointer

    tree = {"w": np.arange(4.0, dtype=np.float32)}
    with FLCheckpointer(str(tmp_path / "ck"), max_to_keep=5) as ck:
        ck.save(1, {"w": tree["w"] * 1}, {"step": 1})
        ck.save(2, {"w": tree["w"] * 2}, {"step": 2})
        ck.wait()
        # Crash artifacts: a bare step dir, and a marker-only dir whose
        # payload never landed.
        os.makedirs(str(tmp_path / "ck" / "9"))
        os.makedirs(str(tmp_path / "ck" / "7"))
        open(str(tmp_path / "ck" / "7" / "_CHECKPOINT_METADATA"), "w").close()

        assert 9 not in ck.all_steps()
        assert ck.latest_step() == 7 or ck.latest_step() == 2  # 7 passes the
        # marker check but must still fall through on restore:
        state, meta = ck.restore({"w": np.zeros(4, np.float32)})
        assert meta["step"] == 2
        np.testing.assert_array_equal(state["w"], tree["w"] * 2)
        assert ck.restore_meta()["step"] == 2


def test_empty_checkpointer_still_raises(tmp_path):
    from p2pfl_tpu.management.checkpoint import FLCheckpointer

    with FLCheckpointer(str(tmp_path / "empty")) as ck:
        with pytest.raises(FileNotFoundError):
            ck.restore({"w": np.zeros(2, np.float32)})
        with pytest.raises(FileNotFoundError):
            ck.restore_meta()


# --- gossip backoff jitter ----------------------------------------------------


def test_backoff_jitter_deterministic_decorrelated_bounded():
    """Retry backoff must be seeded-deterministic (replayable), decorrelated
    across node pairs (no post-heal retry lockstep), and bounded to
    [0.5, 1.5) x the exponential base."""
    from p2pfl_tpu.comm.protocol import jittered_backoff

    with Settings.overridden(GOSSIP_SEND_BACKOFF=0.1, CHAOS_SEED=0):
        a = jittered_backoff("n1", "n2", 1)
        assert a == jittered_backoff("n1", "n2", 1)  # deterministic
        others = {jittered_backoff(f"n{i}", "n2", 1) for i in range(3, 10)}
        assert a not in others  # decorrelated across pairs
        base = 0.2
        for attempt, mult in ((0, 1), (1, 2), (2, 4)):
            v = jittered_backoff("x", "y", attempt)
            lo, hi = 0.1 * mult * 0.5, 0.1 * mult * 1.5
            assert lo <= v < hi, (attempt, v)
    with Settings.overridden(CHAOS_SEED=1234):
        assert jittered_backoff("n1", "n2", 1) != a  # seed moves the stream
    with Settings.overridden(GOSSIP_SEND_BACKOFF=0.0):
        assert jittered_backoff("n1", "n2", 3) == 0.0


# --- recovery scenario traces -------------------------------------------------


def test_plan_recovery_deterministic_and_counted():
    from p2pfl_tpu.chaos import CHAOS, ChaosPlane

    nodes = [f"n{i}" for i in range(8)]
    plan = ChaosPlane().plan_recovery(
        6, nodes, seed=7, crash_round=1, partition_round=2, heal_after=2
    )
    replay = ChaosPlane().plan_recovery(
        6, nodes, seed=7, crash_round=1, partition_round=2, heal_after=2
    )
    assert plan == replay
    assert ChaosPlane().plan_recovery(6, nodes, seed=8, partition_round=2) != plan
    kinds = [e.kind for e in plan]
    assert kinds.count("crash") == 1 and kinds.count("restart") == 1
    assert kinds.count("partition") == 1 and kinds.count("heal") == 1
    part = next(e for e in plan if e.kind == "partition")
    assert sorted(a for g in part.groups for a in g) == sorted(nodes)
    # executed events land in the deterministic fault table
    CHAOS.reset()
    for e in plan:
        CHAOS.recovery(e.node or "fleet", e.kind)
    assert CHAOS.fault_counts() == {"recovery": len(plan)}
    CHAOS.reset()


def test_link_blocked_is_state_only():
    """The heal probe's chaos check must draw NO randomness: interleaving it
    must not shift the per-pair decision streams, and it must count no
    faults."""
    from p2pfl_tpu.chaos import ChaosPlane

    with Settings.overridden(CHAOS_ENABLED=True, CHAOS_SEED=3, CHAOS_DROP_RATE=0.3):
        p1, p2 = ChaosPlane(), ChaosPlane()
        seq1 = [p1.intercept("a", "b").drop for _ in range(50)]
        seq2 = []
        for _ in range(50):
            p2.link_blocked("a", "b")  # interleaved probes
            seq2.append(p2.intercept("a", "b").drop)
        assert seq1 == seq2
        assert "partition" not in p2.fault_counts()
        p2.partition(["a"], ["b"])
        assert p2.link_blocked("a", "b") == "partition"
        counts_before = p2.fault_counts()
        p2.link_blocked("a", "b")
        assert p2.fault_counts() == counts_before  # probes count nothing
        p2.crash("c")
        assert p2.link_blocked("a", "c") == "crash"


# --- heal detection -----------------------------------------------------------


def test_failure_departures_enter_probe_pool_graceful_does_not():
    from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol

    p1 = InMemoryCommunicationProtocol()
    p2 = InMemoryCommunicationProtocol()
    p3 = InMemoryCommunicationProtocol()
    for p in (p1, p2, p3):
        p.start()
    try:
        p1.connect(p2.addr)
        p1.connect(p3.addr)
        p1.disconnect(p2.addr)  # graceful: no heal owed
        p1.neighbors.remove(p3.addr, notify=False)  # write-off: heal-probed
        assert p1.neighbors.departed() == [p3.addr]
    finally:
        for p in (p1, p2, p3):
            p.stop()


def test_probe_detects_heal_and_fires_recover():
    """A written-off peer that is reachable again must be re-added by the
    probe, firing the recovery listeners, the observatory's 'recover'
    membership event and the heals metric — and the probe must NOT pierce a
    still-active chaos partition."""
    from p2pfl_tpu.chaos import CHAOS
    from p2pfl_tpu.comm.memory.memory_protocol import InMemoryCommunicationProtocol

    REGISTRY.reset()
    CHAOS.reset()
    p1 = InMemoryCommunicationProtocol()
    p2 = InMemoryCommunicationProtocol()
    healed: list = []
    p1.on_neighbor_recovered(healed.append)
    p1.start()
    p2.start()
    try:
        p1.connect(p2.addr)
        p1.neighbors.remove(p2.addr, notify=False)  # simulate write-off
        assert p2.addr not in p1.get_neighbors()

        CHAOS.partition([p1.addr], [p2.addr])
        p1._probe_departed()
        assert healed == []  # the probe respects the partition
        assert p2.addr not in p1.get_neighbors()

        CHAOS.heal()
        p1._probe_departed()
        assert healed == [p2.addr]
        assert p2.addr in p1.get_neighbors()
        events = [
            e["event"]
            for e in p1.observatory.snapshot()["membership_events"]
            if e["peer"] == p2.addr
        ]
        assert "recover" in events
        assert sum(_metric("p2pfl_recovery_heals_total").values()) >= 1
        # once healed, the peer leaves the probe pool
        assert p2.addr not in p1.neighbors.departed()
    finally:
        CHAOS.reset()
        p1.stop()
        p2.stop()


def test_observatory_recover_resets_link_baseline():
    from p2pfl_tpu.telemetry.observatory import Observatory

    REGISTRY.reset()
    obs = Observatory("me")
    missed = REGISTRY.counter(
        "p2pfl_heartbeat_missed_total", "test shim", labels=("node", "peer")
    )
    missed.labels("me", "p1").inc(5)
    assert obs._link_score("p1") >= 5.0
    obs.peer_recovered("p1")
    assert obs._link_score("p1") == 0.0  # partition-era misses forgiven
    missed.labels("me", "p1").inc(2)
    assert obs._link_score("p1") >= 2.0  # fresh misses still count


# --- reconcile (split-brain repair) ------------------------------------------


def _mini_nodes(n, batch=16):
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node

    data = synthetic_mnist(n_train=64 * n, n_test=32)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    return [
        Node(mlp_model(seed=i), parts[i], batch_size=batch, executor=False)
        for i in range(n)
    ]


def test_offer_take_reconcile_semantics():
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("me")
    st.set_experiment("e", 10)
    st.experiment.round = 3
    params = [np.zeros(2, np.float32)]
    assert not st.offer_reconcile(2, params, [], "p")  # behind: rejected
    assert not st.offer_reconcile(3, params, [], "p")  # equal: rejected
    assert st.offer_reconcile(5, params, [], "p")
    assert not st.offer_reconcile(4, params, [], "q")  # older than pending
    assert st.offer_reconcile(6, params, [], "q")  # fresher replaces
    assert st.reconcile_ahead()
    st.experiment.round = 7  # caught up naturally: offer is stale
    assert st.take_reconcile() is None
    assert not st.reconcile_ahead()


def test_reconcile_model_staged_and_applied_at_boundary():
    """reconcile_model stages the catch-up; apply_pending_reconcile adopts
    it atomically: params, anchor resync, round fast-forward, events."""
    from p2pfl_tpu.comm.commands.impl import ReconcileModelCommand
    from p2pfl_tpu.stages.recovery import apply_pending_reconcile

    REGISTRY.reset()
    node = _mini_nodes(1)[0]
    node.start()
    try:
        state = node.state
        state.set_experiment("e", 10)
        state.experiment.round = 1
        state.wire.set_anchor(node.learner.get_model().get_parameters(), 1)

        ahead = node.learner.get_model().build_copy(
            params=[np.asarray(p) + 0.5 for p in node.learner.get_model().get_parameters()]
        )
        blob = ahead.encode_parameters()
        ReconcileModelCommand(node).execute(
            "peer-x", 4, weights=blob, contributors=["peer-x"], num_samples=1
        )
        assert state.reconcile_ahead()
        assert state.votes_ready_event.is_set()

        assert apply_pending_reconcile(node)
        assert state.round == 4
        assert state.wire.anchor_round == 4
        assert state.last_full_model_round == 3
        np.testing.assert_allclose(
            np.asarray(node.learner.get_model().get_parameters()[0]),
            np.asarray(ahead.get_parameters()[0]),
            rtol=0, atol=1e-6,
        )
        rec = _metric("p2pfl_recovery_reconcile_total")
        assert rec.get((node.addr, "catchup_rx")) == 1.0
        # stale frames for rounds at/behind us are ignored
        ReconcileModelCommand(node).execute(
            "peer-x", 3, weights=blob, contributors=["peer-x"], num_samples=1
        )
        assert not state.reconcile_ahead()
    finally:
        node.stop()


def test_reconcile_ping_triggers_catchup_from_ahead_peer():
    """The full ping → catch-up → staged-offer exchange between two live
    nodes: behind pings, ahead ships its round anchor, behind stages it."""
    node_b, node_a = _mini_nodes(2)
    node_a.start()
    node_b.start()
    try:
        node_b.connect(node_a.addr)
        assert _wait(lambda: node_a.addr in node_b.get_neighbors(), 10)
        # ahead node at round 5 with an anchor to ship
        node_a.state.set_experiment("e", 10)
        node_a.state.experiment.round = 5
        node_a.state.wire.set_anchor(node_a.learner.get_model().get_parameters(), 5)
        # behind node at round 1
        node_b.state.set_experiment("e", 10)
        node_b.state.experiment.round = 1
        assert node_b.send_reconcile_ping(node_a.addr)
        assert _wait(node_b.state.reconcile_ahead, 10)
        rec = _metric("p2pfl_recovery_reconcile_total")
        assert rec.get((node_a.addr, "catchup_tx"), 0) >= 1
    finally:
        node_a.stop()
        node_b.stop()


# --- quorum-aware degraded mode ----------------------------------------------


def test_park_until_quorum_parks_and_unparks():
    from p2pfl_tpu.stages.recovery import park_until_quorum

    REGISTRY.reset()
    nodes = _mini_nodes(3)
    try:
        nodes[0].start()
        nodes[1].start()
        nodes[1].connect(nodes[0].addr)
        assert _wait(lambda: nodes[1].addr in nodes[0].get_neighbors(), 10)
        st = nodes[0].state
        st.set_experiment("park", 3)
        # the known fleet is 3 — the third member is down right now
        st.session_members = {nodes[0].addr, nodes[1].addr, nodes[2].addr}
        result = [None]
        with Settings.overridden(
            RECOVERY_QUORUM_FRACTION=0.9, RECOVERY_PARK_MAX_S=30.0
        ):
            t = threading.Thread(
                target=lambda: result.__setitem__(0, park_until_quorum(nodes[0]))
            )
            t.start()
            assert _wait(lambda: st.parked, 5)
            # third member arrives: quorum met, node unparks
            nodes[2].start()
            nodes[2].connect(nodes[0].addr)
            t.join(timeout=15)
            assert result[0] is True and not st.parked
        parks = _metric("p2pfl_recovery_parks_total")
        assert parks.get((nodes[0].addr,)) == 1.0
        assert sum(_metric("p2pfl_recovery_parked_seconds_total").values()) > 0
        assert _metric("p2pfl_recovery_parked").get((nodes[0].addr,)) == 0.0
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:  # noqa: BLE001
                pass


def test_park_early_stop_and_cap():
    from p2pfl_tpu.stages.recovery import park_until_quorum

    nodes = _mini_nodes(1)
    node = nodes[0]
    node.start()
    try:
        st = node.state
        st.set_experiment("park", 3)
        st.session_members = {node.addr, "mem://ghost-a", "mem://ghost-b"}
        # early stop while parked -> False
        result = [None]
        with Settings.overridden(RECOVERY_QUORUM_FRACTION=1.0, RECOVERY_PARK_MAX_S=0.0):
            t = threading.Thread(
                target=lambda: result.__setitem__(0, park_until_quorum(node))
            )
            t.start()
            assert _wait(lambda: st.parked, 5)
            st.experiment = None
            t.join(timeout=10)
            assert result[0] is False
        # cap expiry -> proceeds degraded (True)
        st.set_experiment("park2", 3)
        st.session_members = {node.addr, "mem://ghost-a", "mem://ghost-b"}
        with Settings.overridden(RECOVERY_QUORUM_FRACTION=1.0, RECOVERY_PARK_MAX_S=0.6):
            assert park_until_quorum(node) is True
            assert not st.parked
        # quorum disabled -> no parking at all
        with Settings.overridden(RECOVERY_QUORUM_FRACTION=0.0):
            assert park_until_quorum(node) is True
    finally:
        node.stop()


def test_recovery_settings_validated():
    """The RECOVERY_* env knobs ride the validated fail-fast layer."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["P2PFL_TPU_RECOVERY_QUORUM_FRACTION"] = "1.7"
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, "-c", "import p2pfl_tpu.config"],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode != 0
    assert "RECOVERY_QUORUM_FRACTION" in out.stderr


# --- split-brain reconcile e2e (both schedulers) ------------------------------


@pytest.mark.slow
def test_partition_heal_reconciles_sync():
    """4-node sync federation, 2|2 partition held ~2 rounds, then healed:
    every node must finish, heals must be detected on both sides, and the
    behind half must adopt the ahead half's generation via dense catch-up."""
    from p2pfl_tpu.chaos import CHAOS
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model

    REGISTRY.reset()
    CHAOS.reset()
    n, rounds = 4, 6
    data = synthetic_mnist(n_train=128 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    with Settings.overridden(LOG_LEVEL="WARNING", TRAIN_SET_SIZE=4):
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        for nd in nodes:
            nd.start()
        try:
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            assert _wait(
                lambda: all(len(nd.get_neighbors()) == n - 1 for nd in nodes), 20
            )
            nodes[0].set_start_learning(rounds=rounds, epochs=1)
            assert _wait(lambda: (nodes[0].state.round or 0) >= 1, 30)
            half_a = [nodes[0].addr, nodes[1].addr]
            half_b = [nodes[2].addr, nodes[3].addr]
            CHAOS.partition(half_a, half_b)
            base = nodes[0].state.round or 0
            _wait(
                lambda: (nodes[0].state.round or rounds) >= base + 2
                or not nodes[0].learning_in_progress(),
                60,
            )
            CHAOS.heal()
            assert _wait(
                lambda: all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in nodes
                ),
                150,
            ), {nd.addr: nd.state.current_stage for nd in nodes}
            heals = _metric("p2pfl_recovery_heals_total")
            assert sum(heals.values()) >= 2, heals
            rec = _metric("p2pfl_recovery_reconcile_total")
            assert any(role == "ping_tx" for (_, role) in rec), rec
            # one federation again: everyone saturates the synthetic task
            accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in nodes]
            assert min(accs) == max(accs) == 1.0, accs
        finally:
            for nd in nodes:
                nd.stop()
            CHAOS.reset()


@pytest.mark.slow
def test_partition_heal_reconciles_async():
    """Same 2|2 split under the async scheduler: both halves keep closing
    windows during the partition, and after the heal their contributions
    merge through the staleness-weighted buffer — every node finishes with
    the task saturated."""
    from p2pfl_tpu.chaos import CHAOS
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model

    REGISTRY.reset()
    CHAOS.reset()
    n, windows = 4, 5
    data = synthetic_mnist(n_train=128 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    with Settings.overridden(LOG_LEVEL="WARNING", ASYNC_WINDOW_TIMEOUT=8.0):
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        for nd in nodes:
            # pace windows so the partition spans more than one of them
            orig = nd.learner.fit

            def slow_fit(orig=orig):
                time.sleep(0.5)
                return orig()

            nd.learner.fit = slow_fit
            nd.start()
        try:
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            assert _wait(
                lambda: all(len(nd.get_neighbors()) == n - 1 for nd in nodes), 20
            )
            nodes[0].set_start_learning(rounds=windows, epochs=1, mode="async")
            assert _wait(lambda: (nodes[0].state.round or 0) >= 1, 30)
            CHAOS.partition(
                [nodes[0].addr, nodes[1].addr], [nodes[2].addr, nodes[3].addr]
            )
            base = nodes[0].state.round or 0
            _wait(
                lambda: (nodes[0].state.round or windows) >= base + 2
                or not nodes[0].learning_in_progress(),
                60,
            )
            CHAOS.heal()
            assert _wait(
                lambda: all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in nodes
                ),
                150,
            ), {nd.addr: nd.state.current_stage for nd in nodes}
            accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in nodes]
            assert min(accs) == 1.0, accs
            for nd in nodes:
                assert nd.learning_workflow.history.count("AsyncWindowFinishedStage") >= 1
        finally:
            for nd in nodes:
                nd.stop()
            CHAOS.reset()
