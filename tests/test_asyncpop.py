"""Async-window population subsystem: arrival traces, the streaming
scheduler's invariants, and the AsyncPopulationEngine's bit-exactness
contracts.

Every assertion here is an exact regression pin on FIXED seeds — the
scheduler is a pure function of ``(plan, names, speeds)`` and the fused
window fold is constructed to reproduce the sync FedAvg (zero lag) and the
wire async buffer (any lag) bit for bit, so there are no tolerance knobs to
hide behind.
"""

from __future__ import annotations

import importlib.util
import math
import os

import numpy as np
import pytest

from p2pfl_tpu.config import Settings
from p2pfl_tpu.population.arrivals import (
    CLOSE_FILL,
    AsyncWindowPlan,
    arrival_delay,
    compile_window_schedule,
    trace_intensity,
)
from p2pfl_tpu.population.engine import vnode_names


def _load_parity_diff():
    spec = importlib.util.spec_from_file_location(
        "parity_diff",
        os.path.join(os.path.dirname(__file__), "..", "scripts", "parity_diff.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --- arrival model ------------------------------------------------------------


def test_trace_intensity_profiles():
    p = 8
    assert all(trace_intensity("uniform", w, p) == 1.0 for w in range(3 * p))
    for trace in ("diurnal", "regional"):
        vals = [trace_intensity(trace, w, p) for w in range(3 * p)]
        assert all(0.0 < v <= 1.0 for v in vals)
        # Periodic in the ABSOLUTE window index — the resume-safety property.
        assert vals[:p] == vals[p : 2 * p]
    spike = max(1, p // 5)
    for w in range(2 * p):
        got = trace_intensity("flash", w, p, flash_mult=10.0)
        assert got == (1.0 if (w % p) < spike else pytest.approx(0.1))
    with pytest.raises(ValueError, match="unknown arrival trace"):
        trace_intensity("bursty", 0, p)


def test_arrival_delay_tiers_and_determinism():
    # Tier <= 1.0 is always fresh; tier s is in [0, ceil(s) - 1]; the draw
    # is a pure function of (seed, window, name).
    assert all(arrival_delay(9, w, "vnode/00003", 1.0) == 0 for w in range(50))
    for speed in (2.0, 3.0, 5.0):
        draws = [
            arrival_delay(9, w, f"vnode/{i:05d}", speed)
            for w in range(20)
            for i in range(8)
        ]
        assert min(draws) >= 0
        assert max(draws) <= math.ceil(speed) - 1
        assert max(draws) > 0  # the slow tier really is late sometimes
    assert arrival_delay(9, 4, "vnode/00001", 5.0) == arrival_delay(
        9, 4, "vnode/00001", 5.0
    )
    assert arrival_delay(10, 4, "vnode/00001", 5.0) != arrival_delay(
        9, 4, "vnode/00001", 5.0
    ) or arrival_delay(9, 5, "vnode/00001", 5.0) != arrival_delay(
        9, 4, "vnode/00001", 5.0
    )


# --- streaming scheduler ------------------------------------------------------


def _speeds(n: int, tiers, seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed + 0x7153)
    return np.asarray(tiers, np.float32)[rng.integers(0, len(tiers), size=n)]


def test_window_schedule_chunk_and_cursor_invariance():
    """Resume-safety: the stream is a pure function of the plan — compiling
    [0, 8) in one call or as [0, 5) + [5, 8) yields identical rows, which is
    what lets a restored checkpoint replay the dead engine's exact stream."""
    n, seed = 24, 3
    names = vnode_names(n)
    speeds = _speeds(n, (1.0, 1.0, 2.0, 5.0), seed)
    plan = AsyncWindowPlan(seed=seed, fraction=0.25, names=tuple(names))
    whole = compile_window_schedule(plan, names, 8, start_window=0, speeds=speeds)
    head = compile_window_schedule(plan, names, 5, start_window=0, speeds=speeds)
    tail = compile_window_schedule(plan, names, 3, start_window=5, speeds=speeds)
    for attr in (
        "members", "present", "origin", "lag", "rank",
        "target", "solicited", "queue_depth", "dropped",
    ):
        joined = np.concatenate([getattr(head, attr), getattr(tail, attr)])
        np.testing.assert_array_equal(joined, getattr(whole, attr), err_msg=attr)
    assert whole.windows == 8 and tail.start_window == 5
    np.testing.assert_array_equal(whole.fill(), whole.present.sum(axis=1))
    # Lag bookkeeping is exact: every present slot's lag is fold - origin.
    w_abs = np.arange(8)[:, None]
    np.testing.assert_array_equal(
        whole.lag[whole.present], (w_abs - whole.origin)[whole.present]
    )


def test_window_schedule_backpressure_and_staleness_gate():
    n, seed = 64, 11
    names = vnode_names(n)
    slow = np.full(n, 5.0, np.float32)  # everyone up to 4 windows late
    plan = AsyncWindowPlan(
        seed=seed, fraction=0.25, names=tuple(names),
        trace="flash", period=6, stall_patience=2, max_lag=4,
    )
    sched = compile_window_schedule(plan, names, 24, speeds=slow)
    k = sched.cohort_k
    # Stall-patience backpressure: solicitation pauses while the queue is
    # deeper than patience*K, so it can never exceed (patience + 1) * K.
    assert sched.queue_depth.max() <= (2 + 1) * k
    assert (sched.lag[sched.present] <= 4).all()
    # A max_lag=0 gate under the same slow fleet drops the late arrivals
    # instead of folding them stale.
    strict = AsyncWindowPlan(
        seed=seed, fraction=0.25, names=tuple(names),
        trace="flash", period=6, stall_patience=2, max_lag=0,
    )
    sgate = compile_window_schedule(strict, names, 24, speeds=slow)
    assert (sgate.lag[sgate.present] == 0).all()
    assert int(sgate.dropped.sum()) > 0


def test_staleness_discount_is_the_wire_weight():
    """The fused fold and the wire buffer multiply through ONE shared pure
    function — jitted it must match the wire's scalar weight exactly."""
    import jax
    import jax.numpy as jnp

    from p2pfl_tpu.learning.aggregators import staleness_discount, staleness_weight

    alpha = float(Settings.ASYNC_STALENESS_ALPHA)
    lags = jnp.arange(0, 6, dtype=jnp.int32)
    fused = np.asarray(jax.jit(lambda l: staleness_discount(l, alpha))(lags))
    wire = np.asarray([staleness_weight(int(l)) for l in range(6)], np.float32)
    np.testing.assert_allclose(fused, wire, rtol=1e-6)
    assert fused[0] == 1.0  # fresh contributions are undiscounted
    assert (np.diff(fused) < 0).all()  # strictly decaying in lag


# --- engine: bit-exactness contracts -----------------------------------------


def test_zero_lag_async_matches_sync_engine():
    """All tiers 1.0 + uniform trace: every window folds its full cohort
    fresh with discount exactly 1.0, so the async window program IS the
    sync round program — same hash, bit for bit (not an accuracy check)."""
    from p2pfl_tpu.population import AsyncPopulationEngine, PopulationEngine
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    kw = dict(
        cohort_fraction=0.5, seed=7, samples_per_node=8, feature_dim=8,
        num_classes=4, hidden=(8,), batch_size=4, lr=0.05,
    )
    with PopulationEngine(12, **kw) as sync:
        sync.run(5)
        sync_hash = canonical_params_hash(sync.gather_params(0))
    with AsyncPopulationEngine(12, **kw) as a:
        res = a.run(5, eval_every=5)
        async_hash = canonical_params_hash(a.global_params())
    assert async_hash == sync_hash
    assert (res.close_codes == CLOSE_FILL).all()
    assert (res.schedule.lag[res.schedule.present] == 0).all()


def test_async_checkpoint_resume_replays_window_stream(tmp_path):
    """Kill after 4 windows, restore, run 3 more: the healed engine must
    re-stream the identical window/arrival schedule from the absolute
    cursor — same global hash AND same per-vnode fold accounting as the
    uninterrupted 7-window reference."""
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.population import AsyncPopulationEngine
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    kw = dict(
        cohort_fraction=0.5, seed=4, samples_per_node=8, feature_dim=8,
        num_classes=4, hidden=(8,), batch_size=4,
        speed_tiers=(1.0, 2.0, 5.0),
    )
    with AsyncPopulationEngine(12, **kw) as ref:
        ref.run(7, eval_every=10)
        ref_hash = canonical_params_hash(ref.global_params())
        ref_fill = ref.window_fill()
    ckpt = FLCheckpointer(str(tmp_path))
    with AsyncPopulationEngine(12, **kw) as victim:
        victim.run(4, eval_every=10)
        assert victim.save_to(ckpt)
    with AsyncPopulationEngine(12, **kw) as healed:
        assert healed.load_from(ckpt) == 4
        healed.run(3, eval_every=10)
        assert canonical_params_hash(healed.global_params()) == ref_hash
        np.testing.assert_allclose(healed.window_fill(), ref_fill)
    # A seed-mismatched checkpoint must refuse (the stream would diverge).
    with AsyncPopulationEngine(12, **{**kw, "seed": 5}) as wrong:
        with pytest.raises(ValueError, match="seed"):
            wrong.load_from(ckpt)


def test_wire_vs_fused_async_parity_n4():
    """The REAL AsyncBufferedAggregator replaying the compiled window
    stream must emit a ledger that aligns with the fused engine's —
    aggregate hashes bit-exact, final params bit-equal (staleness weights
    and all)."""
    import jax

    from p2pfl_tpu.population import AsyncPopulationEngine, wire_window_replay
    from p2pfl_tpu.telemetry.ledger import LEDGERS

    parity_diff = _load_parity_diff()
    par_kw = dict(
        cohort_fraction=1.0, seed=1236, samples_per_node=8, feature_dim=8,
        num_classes=4, hidden=(8,), batch_size=4,
        speed_tiers=(1.0, 1.0, 2.0, 3.0),
    )
    windows = 4
    LEDGERS.reset()
    with AsyncPopulationEngine(4, **par_kw) as fused:
        led = fused.attach_ledger("fused-async-test")
        res = fused.run(windows, eval_every=100, windows_per_call=1)
        fused_ev = led.canonical_events()
        fused_params = fused.global_params()
    assert res.schedule.lag[res.schedule.present].max() > 0  # staleness live
    weng = AsyncPopulationEngine(4, **par_kw)
    wire = wire_window_replay(weng, windows, node="wire-async-test")
    weng.close()
    wire_ev = LEDGERS.get("wire-async-test").canonical_events()
    report = parity_diff.compare_ledgers(wire_ev, fused_ev)
    assert report["status"] == "OK", report
    assert report["hashes_compared"] >= 1
    for la, lb in zip(
        jax.tree.leaves(wire["final_params"]), jax.tree.leaves(fused_params)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_async_snapshot_carries_window_columns():
    from p2pfl_tpu.population import AsyncPopulationEngine

    with AsyncPopulationEngine(
        8, cohort_fraction=0.5, seed=3, samples_per_node=8, feature_dim=8,
        num_classes=4, hidden=(8,), batch_size=4,
    ) as eng:
        res = eng.run(3, eval_every=3)
        snap = eng.snapshot(res, top_n=4)
    # top_n virtual rows + the observer's own row (wire doc-shape parity).
    assert len(snap["peers"]) == 4 + 1
    for name, peer in snap["peers"].items():
        if name == "asyncpop-engine":
            continue
        assert peer["window"] is not None and peer["window"] >= 0
        assert peer["window_fill"] is not None and 0.0 <= peer["window_fill"] <= 1.0
