"""Keras interop: handle round-trips, learner training, SCAFFOLD deltas,
keras<->flax weight translation, and the heterogeneous jax/torch/keras
federation (reference framework matrix tests: test/learning/
frameworks_test.py:63-385 — the mixed federation exceeds the reference,
which cannot combine frameworks in one experiment)."""

import numpy as np
import pytest

keras = pytest.importorskip("keras")
torch = pytest.importorskip("torch")

from p2pfl_tpu.exceptions import ModelNotMatchingError
from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
from p2pfl_tpu.learning.interop import (
    KerasLearner,
    TorchLearner,
    jax_mlp_params_to_keras,
    keras_mlp_model,
    keras_mlp_to_wire,
    keras_weights_to_jax_mlp,
    torch_mlp_model,
    torch_mlp_to_wire,
)
from p2pfl_tpu.learning.learner import LearnerFactory
from p2pfl_tpu.models import mlp_model

# keras learners train real epochs -> excluded from the fast subset
pytestmark = pytest.mark.slow



def test_keras_handle_roundtrip_and_shape_check():
    m = keras_mlp_model(seed=0)
    params = m.get_parameters()
    wire = m.encode_parameters()
    m2 = keras_mlp_model(seed=1)
    m2.set_parameters(bytes(wire))
    for a, b in zip(params, m2.get_parameters()):
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ModelNotMatchingError):
        m2.set_parameters([p[:1] for p in params])


def test_learner_factory_picks_keras():
    assert LearnerFactory.create_learner(keras_mlp_model()) is KerasLearner


def test_keras_learner_trains():
    data = synthetic_mnist(n_train=512, n_test=128)
    learner = KerasLearner(keras_mlp_model(seed=0), data, "k0", batch_size=32)
    learner.set_epochs(2)
    learner.fit()
    metrics = learner.evaluate()
    assert metrics["test_acc"] > 0.5, metrics
    assert learner.get_model().get_contributors() == ["k0"]


def test_keras_scaffold_emits_deltas():
    data = synthetic_mnist(n_train=256, n_test=64)
    model = keras_mlp_model(seed=0)
    before = [a.copy() for a in model.get_parameters()]
    learner = KerasLearner(model, data, "k0", batch_size=32, callbacks=["scaffold"])
    learner.set_epochs(1)
    learner.fit()
    info = model.get_info("scaffold")
    assert info is not None
    after = model.get_parameters()
    assert len(info["delta_y_i"]) == len(after)
    for dy, a, b in zip(info["delta_y_i"], after, before):
        np.testing.assert_allclose(dy, a.astype(np.float32) - b.astype(np.float32), atol=1e-5)
    assert any(np.abs(dc).max() > 0 for dc in info["delta_c_i"])


def test_keras_to_jax_weight_translation_exact():
    """Same weights -> same logits across frameworks (keras Dense kernels
    are already [in, out]; only re-nesting happens)."""
    km = keras_mlp_model(seed=3)
    jm = mlp_model(seed=0)
    jax_params = keras_weights_to_jax_mlp(km.params)
    x = np.random.default_rng(0).normal(size=(8, 28, 28)).astype(np.float32)
    out_k = km.apply_fn(km.params, x)
    jm.set_parameters(jax_params)
    out_j = np.asarray(jm.apply_fn(jm.params, x))
    # flax MLP computes in bfloat16 -> tolerance is bf16 rounding
    np.testing.assert_allclose(out_k, out_j, atol=0.1)

    back = jax_mlp_params_to_keras(jax_params)
    for a, b in zip(back, km.params):
        np.testing.assert_array_equal(a, b)


def test_mixed_jax_torch_keras_federation():
    """3-node heterogeneous federation — one node per framework — over the
    in-memory transport with the canonical (flax-layout) wire format. All
    nodes must converge to the same model."""
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.utils.utils import wait_convergence, wait_to_finish

    parts = synthetic_mnist(n_train=384, n_test=96).generate_partitions(
        3, RandomIIDPartitionStrategy
    )
    nodes = [
        Node(mlp_model(seed=0), parts[0], batch_size=32),
        Node(
            torch_mlp_model(seed=1, canonical=True),
            parts[1],
            learner=TorchLearner,
            batch_size=32,
        ),
        Node(
            keras_mlp_model(seed=2, canonical=True),
            parts[2],
            learner=KerasLearner,
            batch_size=32,
        ),
    ]
    try:
        for n in nodes:
            n.start()
        nodes[1].connect(nodes[0].addr)
        nodes[2].connect(nodes[0].addr)
        wait_convergence(nodes, 2, wait=8)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        wait_to_finish(nodes, timeout=180)
        # Compare in the canonical layout (native layouts differ by design).
        canon = [
            nodes[0].learner.get_model().get_parameters(),
            torch_mlp_to_wire(nodes[1].learner.get_model().params),
            keras_mlp_to_wire(nodes[2].learner.get_model().params),
        ]
        for other in canon[1:]:
            assert len(other) == len(canon[0])
            for a, b in zip(canon[0], other):
                assert a.shape == b.shape
                np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-1)
    finally:
        for n in nodes:
            n.stop()
