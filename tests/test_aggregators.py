"""Host aggregator semantics (mirrors reference test/learning/aggregator_test.py
and scaffold_test.py:32-79): contributor dedup, trainset checks, completion
event, partial aggregation, timeout paths."""

import threading
import time

import numpy as np
import pytest

from p2pfl_tpu.learning.aggregators import FedAvg, FedMedian, Krum, Scaffold, TrimmedMean
from p2pfl_tpu.models.model_handle import ModelHandle


def _model(value, contributors, num_samples=10):
    params = {"w": np.full((4, 4), float(value), np.float32)}
    return ModelHandle(params, contributors=list(contributors), num_samples=num_samples)


def test_fedavg_weighted():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b"])
    agg.add_model(_model(1.0, ["a"], num_samples=10))
    agg.add_model(_model(4.0, ["b"], num_samples=30))
    out = agg.wait_and_get_aggregation(timeout=1)
    np.testing.assert_allclose(np.asarray(out.params["w"]), 3.25, rtol=1e-6)
    assert out.get_contributors() == ["a", "b"]
    assert out.get_num_samples() == 40


def test_duplicate_contribution_ignored():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b"])
    agg.add_model(_model(1.0, ["a"]))
    res = agg.add_model(_model(9.0, ["a"]))  # duplicate
    assert res == ["a"]
    agg.add_model(_model(2.0, ["b"]))
    out = agg.wait_and_get_aggregation(timeout=1)
    np.testing.assert_allclose(np.asarray(out.params["w"]), 1.5, rtol=1e-6)


def test_out_of_trainset_rejected():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b"])
    res = agg.add_model(_model(7.0, ["evil"]))
    assert res == []
    assert agg.get_aggregated_models() == []


def test_completion_event_and_missing():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(_model(1.0, ["a"]))
    assert agg.get_missing_models() == ["b", "c"]
    assert not agg._finish_event.is_set()
    agg.add_model(_model(1.0, ["b", "c"]))  # partial model covers the rest
    assert agg._finish_event.is_set()


def test_wait_timeout_aggregates_partial():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b"])
    agg.add_model(_model(2.0, ["a"]))
    t0 = time.monotonic()
    out = agg.wait_and_get_aggregation(timeout=0.2)
    assert time.monotonic() - t0 >= 0.2
    np.testing.assert_allclose(np.asarray(out.params["w"]), 2.0, rtol=1e-6)


def test_wait_empty_raises():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a"])
    with pytest.raises(RuntimeError):
        agg.wait_and_get_aggregation(timeout=0.05)


def test_partial_model_for_gossip():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    agg.add_model(_model(1.0, ["a"], num_samples=10))
    agg.add_model(_model(3.0, ["b"], num_samples=10))
    partial = agg.get_partial_model(except_nodes=["a"])
    assert partial is not None
    assert partial.get_contributors() == ["b"]
    both = agg.get_partial_model(except_nodes=[])
    assert both.get_contributors() == ["a", "b"]
    np.testing.assert_allclose(np.asarray(both.params["w"]), 2.0, rtol=1e-6)
    assert agg.get_partial_model(except_nodes=["a", "b"]) is None


def test_double_open_raises():
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a"])
    with pytest.raises(RuntimeError):
        agg.set_nodes_to_aggregate(["b"])
    agg.clear()
    agg.set_nodes_to_aggregate(["b"])  # ok after clear


def test_concurrent_adds():
    agg = FedAvg()
    members = [f"n{i}" for i in range(16)]
    agg.set_nodes_to_aggregate(members)
    threads = [
        threading.Thread(target=agg.add_model, args=(_model(i, [f"n{i}"]),))
        for i in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert agg.get_aggregated_models() == sorted(members)
    assert agg._finish_event.is_set()


def test_fedmedian_rule():
    agg = FedMedian()
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    for v, n in [(1.0, "a"), (2.0, "b"), (100.0, "c")]:
        agg.add_model(_model(v, [n]))
    out = agg.wait_and_get_aggregation(timeout=1)
    np.testing.assert_allclose(np.asarray(out.params["w"]), 2.0, rtol=1e-6)


def test_trimmed_mean_rule():
    agg = TrimmedMean(trim_ratio=0.34)
    agg.set_nodes_to_aggregate(["a", "b", "c"])
    for v, n in [(1.0, "a"), (2.0, "b"), (1000.0, "c")]:
        agg.add_model(_model(v, [n]))
    out = agg.wait_and_get_aggregation(timeout=1)
    np.testing.assert_allclose(np.asarray(out.params["w"]), 2.0, rtol=1e-6)


def test_krum_rule_picks_clustered():
    agg = Krum(num_byzantine=1, num_selected=1)
    agg.set_nodes_to_aggregate(["a", "b", "c", "d"])
    for v, n in [(1.0, "a"), (1.01, "b"), (0.99, "c"), (500.0, "d")]:
        agg.add_model(_model(v, [n]))
    out = agg.wait_and_get_aggregation(timeout=1)
    assert abs(float(np.asarray(out.params["w"])[0, 0])) < 2.0


def test_krum_contributors_are_selected_only():
    """Krum output provenance must cover only the models that were actually
    averaged — stamping discarded Byzantine nodes as contributors would make
    partial-aggregation dedup treat them as merged."""
    agg = Krum(num_byzantine=1, num_selected=2)
    agg.set_nodes_to_aggregate(["a", "b", "c", "d"])
    for v, n in [(1.0, "a"), (1.01, "b"), (0.99, "c"), (500.0, "d")]:
        agg.add_model(_model(v, [n], num_samples=10))
    out = agg.wait_and_get_aggregation(timeout=1)
    contributors = out.get_contributors()
    assert len(contributors) == 2
    assert "d" not in contributors  # the outlier must not be stamped
    assert out.get_num_samples() == 20  # sum over selected models only


def test_scaffold_aggregation_roundtrip():
    agg = Scaffold(global_lr=1.0)
    agg.set_nodes_to_aggregate(["a", "b"])

    def scaffold_model(value, name, dy, dc):
        m = _model(value, [name])
        m.add_info(
            "scaffold",
            {
                "delta_y_i": [np.full((4, 4), dy, np.float32)],
                "delta_c_i": [np.full((4, 4), dc, np.float32)],
            },
        )
        return m

    # both clients started from global = value - dy
    agg.add_model(scaffold_model(2.0, "a", dy=1.0, dc=0.5))
    agg.add_model(scaffold_model(4.0, "b", dy=3.0, dc=0.5))
    out = agg.wait_and_get_aggregation(timeout=1)
    # global starts at 2-1=1; update = 1 + mean(1,3) = 3
    np.testing.assert_allclose(np.asarray(out.params["w"]), 3.0, rtol=1e-6)
    server_info = out.get_info("scaffold_server")
    np.testing.assert_allclose(server_info["global_c"][0], 0.5, rtol=1e-6)
    assert out.get_info("scaffold") is None


def test_scaffold_requires_callback_info():
    agg = Scaffold()
    agg.set_nodes_to_aggregate(["a"])
    agg.add_model(_model(1.0, ["a"]))
    with pytest.raises((ValueError, RuntimeError)):
        agg.wait_and_get_aggregation(timeout=0.1)
    assert agg.get_required_callbacks() == ["scaffold"]


def test_geometric_median_rule():
    """Node-mode GeometricMedian: output sits with the honest majority and
    provenance covers all contributors (no discrete selection to hide)."""
    from p2pfl_tpu.learning.aggregators import GeometricMedian

    honest = [_model(2.0, [f"h{i}"]) for i in range(4)]
    bad = _model(500.0, ["byz"])
    out = GeometricMedian(iters=16).aggregate(honest + [bad])
    np.testing.assert_allclose(out.get_parameters()[0], np.full((4, 4), 2.0), atol=0.5)
    assert set(out.get_contributors()) == {"h0", "h1", "h2", "h3", "byz"}


# --- train<->diffuse overlap: retired-round snapshots --------------------------


def test_retire_round_keeps_snapshot_for_drains():
    """retire_round closes the live table but keeps an immutable snapshot a
    background diffusion drain can serve laggards from (stages/base_node.py
    overlap path), until the NEXT retirement replaces it."""
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b"], round=3)
    agg.add_model(_model(1.0, ["a"]))
    agg.add_model(_model(3.0, ["b"]))
    agg.retire_round()
    assert agg.serves_round(3)
    # the retired snapshot still produces partials for a laggard
    partial = agg.get_partial_model_for_round(3, except_nodes=["a"])
    assert partial is not None and partial.get_contributors() == ["b"]
    assert agg.get_partial_model_for_round(3, except_nodes=["a", "b"]) is None
    # the live side reopened clean for the next round
    agg.set_nodes_to_aggregate(["a", "b"], round=4)
    agg.add_model(_model(5.0, ["a"]), round=4)
    assert agg.get_partial_model_for_round(4, ["b"]) is not None
    # next retirement replaces the snapshot: round 3 is gone
    agg.retire_round()
    assert not agg.serves_round(3) and agg.serves_round(4)
    assert agg.get_partial_model_for_round(3, []) is None


def test_add_model_round_gate_drops_cross_round_frames():
    """Under overlap, a round-r+1 partial arriving while the table is still
    open on round r must be DROPPED (the sender's gossip re-ships), never
    merged across generations."""
    agg = FedAvg()
    agg.set_nodes_to_aggregate(["a", "b"], round=2)
    assert agg.add_model(_model(1.0, ["a"]), round=3) == []
    assert agg.get_aggregated_models() == []
    assert agg.add_model(_model(1.0, ["a"]), round=2) == ["a"]
    # round-less adds (the node's own model) keep working
    assert agg.add_model(_model(2.0, ["b"])) == ["a", "b"]


def test_node_state_prev_round_coverage_and_prefit():
    from p2pfl_tpu.node_state import NodeState

    st = NodeState("mem://unit")
    st.set_experiment("exp", 3)
    st.models_aggregated["peer"] = ["a"]
    assert st.coverage(0) is st.models_aggregated
    st.increase_round()
    # the finished round's table retired; the live one is fresh
    assert st.coverage(0) == {"peer": ["a"]}
    assert st.coverage(1) == {} and st.coverage(1) is st.models_aggregated
    assert st.coverage(7) == {}
    # prefit handoff: only the matching round pops the thread
    done = threading.Event()
    t = threading.Thread(target=done.set)
    st.prefit = (1, t)
    assert st.take_prefit(1) is t
    assert st.prefit is None and st.take_prefit(1) is None
