import sys, time, json
sys.argv=["bench"]
import bench as B
from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy
from p2pfl_tpu.models import mlp_model
from p2pfl_tpu.parallel.simulation import MeshSimulation
import jax, jax.numpy as jnp

x = None
# reuse bench's data maker
import numpy as np
make = None
# inline from bench
import importlib
fn = B.bench_tpu.__code__
# simpler: replicate minimal
NUM_NODES, ROUNDS, COMMITTEE, BATCH, SPN, TS = B.NUM_NODES, B.ROUNDS, B.COMMITTEE, B.BATCH, B.SAMPLES_PER_NODE, B.TEST_SAMPLES
@jax.jit
def make_data(key):
    kt, ky, kn, kyt, knt = jax.random.split(key, 5)
    templates = jax.random.uniform(kt, (10, 28, 28), jnp.float32)
    y = jax.random.randint(ky, (NUM_NODES, SPN), 0, 10)
    xx = jnp.clip(templates[y] + 0.35 * jax.random.normal(kn, (NUM_NODES, SPN, 28, 28)), 0.0, 1.0)
    mask = jnp.ones((NUM_NODES, SPN), jnp.float32)
    yt = jax.random.randint(kyt, (TS,), 0, 10)
    xt = jnp.clip(templates[yt] + 0.35 * jax.random.normal(knt, (TS, 28, 28)), 0.0, 1.0)
    return xx, y.astype(jnp.int32), mask, xt, yt.astype(jnp.int32)
x, y, mask, xt, yt = make_data(jax.random.key(42))
jax.block_until_ready(x)
for rpc in (10,):
    print(f"building sim rpc={rpc}", flush=True)
    sim = MeshSimulation(mlp_model(seed=0), (x, y, mask), test_data=(xt, yt),
                         train_set_size=COMMITTEE, batch_size=BATCH, seed=1)
    t0=time.monotonic(); print("starting run (compile)", flush=True)
    res = sim.run(rounds=ROUNDS, epochs=1, warmup=True, rounds_per_call=rpc)
    print(f"rounds_per_call={rpc}: {res.seconds_per_round*1000:.2f} ms/round (total wall incl warmup {time.monotonic()-t0:.1f}s)")
