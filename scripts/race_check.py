"""CI gate: runtime lock-order sentinel over a real federation round
(``make race-check``).

Phase 1 — a 3-node in-memory federation runs one chaos-enabled round (5%
injected drop) with EVERY lock created by the framework wrapped in the
sentinel's instrumented lock; the observed acquisition graph must be
acyclic (no two code paths ever disagreed on lock order at runtime).

Phase 2 — negative control: a deliberate lock-order inversion is executed
under the same sentinel and MUST be detected as a cycle, proving the gate
can actually fail.

Exit 0 when both phases pass; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402  (import BEFORE patching: jax's own locks stay raw)

jax.config.update("jax_platforms", "cpu")

import threading  # noqa: E402
import time  # noqa: E402

from p2pfl_tpu.analysis.runtime import SENTINEL  # noqa: E402

ROUNDS = 1
WALL_BUDGET_S = 60.0


def _run_round() -> int:
    from p2pfl_tpu.chaos import CHAOS
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3
    REGISTRY.reset()

    n = 3
    data = synthetic_mnist(n_train=96 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    with CHAOS.overridden(seed=42, drop_rate=0.05):
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        for nd in nodes:
            nd.start()
        try:
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n - 1, wait=15)
            nodes[0].set_start_learning(rounds=ROUNDS, epochs=1)
            deadline = time.monotonic() + WALL_BUDGET_S
            while time.monotonic() < deadline:
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in nodes
                ):
                    break
                time.sleep(0.2)
            else:
                print("FAIL: round did not finish in budget", file=sys.stderr)
                return 1
        finally:
            for nd in nodes:
                nd.stop()
            InMemoryRegistry.reset()
    return 0


def main() -> int:
    # Phase 1: real round, every framework lock instrumented. The node/comm
    # modules import lazily INSIDE the patch so module-level locks (registry,
    # chaos plane, logger) are wrapped too.
    with SENTINEL.patched():
        rc = _run_round()
        if rc != 0:
            return rc
        stats = SENTINEL.stats()
        cycle = SENTINEL.find_cycle()
        if cycle is not None:
            print(
                "FAIL: runtime lock-order cycle observed: " + " -> ".join(cycle),
                file=sys.stderr,
            )
            return 1
        if stats["edges"] == 0:
            print(
                "FAIL: sentinel recorded no nested acquisitions — "
                "instrumentation is not wired",
                file=sys.stderr,
            )
            return 1

        # Phase 2: deliberate inversion under the SAME sentinel must be
        # caught (the gate can fail). Sequential, so it records the cycle
        # without actually deadlocking this process. Separate lines on
        # purpose: the sentinel groups locks into lockdep-style classes by
        # creation site, and one line would make a and b one class.
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        if SENTINEL.find_cycle() is None:
            print(
                "FAIL: deliberate inversion was not detected as a cycle",
                file=sys.stderr,
            )
            return 1

    print(
        f"race-check OK: {ROUNDS}-round 3-node chaos federation acyclic over "
        f"{stats['locks']} instrumented locks / {stats['edges']} order edges; "
        "deliberate inversion detected"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
