"""CI gate: 3-node elastic async federation with one 3x-slow peer — async
windows must complete ahead of the sync barrier under the same shape, and a
node that joins MID-RUN (cold, via the full-model catch-up bootstrap) must be
contributing within 2 windows. Fast, CPU-only, tier-1-safe — invoked by
``make async-check``.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

WINDOWS = 2
FIT_FLOOR_S = 1.5  # fast peers; the straggler fits at 3x this
SLOW_X = 3.0
#: Per-leg wall budget. The sync leg with the straggler takes about
#: WINDOWS x (3x fit + vote/gossip overhead); a regression that re-introduces
#: a barrier into async blows the comparison below, not this cap.
LEG_BUDGET_S = 90.0


def _stretch(node, floor_s):
    orig = node.learner.fit

    def fit(*a, **kw):
        t0 = time.monotonic()
        r = orig(*a, **kw)
        extra = floor_s - (time.monotonic() - t0)
        if extra > 0:
            time.sleep(extra)
        return r

    node.learner.fit = fit


def main() -> int:
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3  # full committee: the straggler always gates sync
    Settings.ASYNC_WINDOW_TIMEOUT = 15.0
    Settings.EXECUTOR_MAX_WORKERS = 0  # inline fits: sleep floors must overlap

    n = 3
    data = synthetic_mnist(n_train=128 * (n + 1), n_test=64)
    parts = data.generate_partitions(n + 1, RandomIIDPartitionStrategy)

    def run_leg(mode):
        REGISTRY.reset()
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        for i, nd in enumerate(nodes):
            _stretch(nd, FIT_FLOOR_S * (SLOW_X if i == n - 1 else 1.0))
            nd.start()
        joiner = None
        stage = "AsyncWindowFinishedStage" if mode == "async" else "RoundFinishedStage"
        try:
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n - 1, wait=15)
            fast = nodes[:-1] if mode == "async" else nodes
            t0 = time.monotonic()
            nodes[0].set_start_learning(rounds=WINDOWS, epochs=1, mode=mode)

            join_window = None
            deadline = time.monotonic() + LEG_BUDGET_S
            while time.monotonic() < deadline:
                if (
                    mode == "async"
                    and joiner is None
                    and (nodes[0].state.round or 0) >= 1
                ):
                    joiner = Node(mlp_model(seed=9), parts[n], batch_size=32)
                    _stretch(joiner, FIT_FLOOR_S)
                    joiner.start()
                    joiner.connect(nodes[0].addr)
                    time.sleep(0.3)
                    joiner.request_async_join()
                    join_window = nodes[0].state.round or 0
                    print(f"joiner entered at window {join_window}", file=sys.stderr)
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    and nd.learning_workflow.history.count(stage) >= WINDOWS
                    for nd in fast
                ):
                    break
                time.sleep(0.1)
            else:
                print(f"FAIL: {mode} leg did not finish in {LEG_BUDGET_S}s", file=sys.stderr)
                return None
            wall = time.monotonic() - t0
            first_fold = (
                nodes[0].async_agg.seen_contributors.get(joiner.addr)
                if mode == "async" and joiner is not None and nodes[0].async_agg
                else None
            )
            if mode == "async":
                nodes[0].set_stop_learning()  # release the straggler's tail windows
            return {
                "wall": wall,
                "join_window": join_window,
                "first_fold": first_fold,
                "joiner": joiner.addr if joiner else None,
            }
        finally:
            for nd in nodes:
                nd.stop()
            if joiner is not None:
                joiner.stop()
            InMemoryRegistry.reset()

    sync = run_leg("sync")
    if sync is None:
        return 1
    print(f"sync leg: {WINDOWS} rounds in {sync['wall']:.1f}s", file=sys.stderr)

    asy = run_leg("async")
    if asy is None:
        return 1
    print(f"async leg: {WINDOWS} windows in {asy['wall']:.1f}s", file=sys.stderr)

    if asy["wall"] >= sync["wall"]:
        print(
            f"FAIL: async windows ({asy['wall']:.1f}s) did not complete ahead "
            f"of sync rounds ({sync['wall']:.1f}s) with a {SLOW_X:g}x straggler",
            file=sys.stderr,
        )
        return 1
    if asy["first_fold"] is None:
        print("FAIL: mid-run joiner never contributed", file=sys.stderr)
        return 1
    lag = asy["first_fold"] - (asy["join_window"] or 0)
    if lag > 2:
        print(
            f"FAIL: joiner first contributed {lag} windows after joining "
            f"(joined w{asy['join_window']}, folded w{asy['first_fold']})",
            file=sys.stderr,
        )
        return 1

    print(
        f"async-check OK: async {WINDOWS} windows in {asy['wall']:.1f}s vs sync "
        f"{sync['wall']:.1f}s with a {SLOW_X:g}x straggler; mid-run joiner "
        f"{asy['joiner']} contributed within {max(0, lag)} window(s)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
