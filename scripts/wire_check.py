"""CI gate: wire-path raw speed (quantized top-k deltas, coalesced frames,
train<->diffuse overlap). One tiny 3-node MNIST federation runs twice:

* **baseline** — the PR 1 sparse wire: top-k @ 10% with bf16 values, one
  PFLT array pair per tensor, fully serialized stage machine
  (``OVERLAP_TRAIN_DIFFUSE=False``);
* **fast** — the same shape on the int4-quantized, coalesced+DEFLATEd codec
  with train<->diffuse overlap on.

Asserts (exit 0 when all pass; nonzero with a reason on stderr):

1. the quantized run matches the baseline's accuracy on this tiny problem
   (within ``ACC_TOL`` — the EF residual absorbs quantization noise),
2. sparse-codec model-plane bytes shrink by >= ``BYTES_X`` (per-codec TX
   attribution from the gossiper's codec-labeled table),
3. the PR 6 overlap report measures ``train_diffuse_overlap_fraction > 0``
   and a reduced serialized-diffuse total — diffusion is off the stage
   thread, the next fit dispatches during the vote RTT.

Fast, CPU-only, tier-1-safe — invoked by ``make wire-check``.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

ROUNDS = 3  # EF needs a round or two to repay the int4 grid error
ACC_TOL = 0.05  # tiny-problem accuracy tolerance between the two codecs
BYTES_X = 2.0  # sparse-codec byte shrink floor (8-node bench measures ~3x+)
FIT_FLOOR_S = 1.5  # a straggler keeps diffusion drains alive into the next fit
LEG_BUDGET_S = 120.0


def _stretch(node, floor_s):
    orig = node.learner.fit

    def fit(*a, **kw):
        t0 = time.monotonic()
        r = orig(*a, **kw)
        extra = floor_s - (time.monotonic() - t0)
        if extra > 0:
            time.sleep(extra)
        return r

    node.learner.fit = fit


def main() -> int:
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY, TRACER, CriticalPathAnalyzer
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3  # full committee: partial gossip dominates
    Settings.EXECUTOR_MAX_WORKERS = 0  # inline fits: sleep floors must overlap

    n = 3
    data = synthetic_mnist(n_train=128 * n, n_test=256)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)

    def run_leg(values, coalesce, overlap):
        REGISTRY.reset()
        TRACER.reset()
        Settings.WIRE_COMPRESSION = "topk"
        Settings.WIRE_TOPK_RATIO = 0.1
        Settings.WIRE_TOPK_VALUES = values
        Settings.COALESCE_ENABLED = coalesce
        Settings.OVERLAP_TRAIN_DIFFUSE = overlap
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        _stretch(nodes[n - 1], FIT_FLOOR_S)
        for nd in nodes:
            nd.start()
        try:
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n - 1, wait=15)
            t0 = time.monotonic()
            nodes[0].set_start_learning(rounds=ROUNDS, epochs=1)
            deadline = time.monotonic() + LEG_BUDGET_S
            while time.monotonic() < deadline:
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in nodes
                ):
                    break
                time.sleep(0.1)
            else:
                print("FAIL: leg did not finish in budget", file=sys.stderr)
                return None
            wall = time.monotonic() - t0
            by_codec: dict = {}
            for nd in nodes:
                for codec, b in nd.protocol.gossiper.bytes_by_codec().items():
                    by_codec[codec] = by_codec.get(codec, 0) + b
            accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in nodes]
            sparse_frames = sum(nd.state.wire.sparse_frames for nd in nodes)
        finally:
            for nd in nodes:
                nd.stop()
            InMemoryRegistry.reset()
        overlap_rep = CriticalPathAnalyzer.from_tracer(TRACER).overlap_report()
        return {
            "wall": wall,
            "by_codec": by_codec,
            "acc": sum(accs) / len(accs),
            "sparse_frames": sparse_frames,
            "overlap": overlap_rep,
        }

    print("wire-check: baseline leg (bf16 topk, uncoalesced, serialized)...", file=sys.stderr)
    base = run_leg("bf16", coalesce=False, overlap=False)
    if base is None:
        return 1
    print(
        f"wire-check: baseline done ({base['wall']:.1f}s, acc {base['acc']:.3f}, "
        f"codec bytes {base['by_codec']}) — fast leg (int4 + coalesce + overlap)...",
        file=sys.stderr,
    )
    fast = run_leg("int4", coalesce=True, overlap=True)
    if fast is None:
        return 1
    print(
        f"wire-check: fast leg done ({fast['wall']:.1f}s, acc {fast['acc']:.3f}, "
        f"codec bytes {fast['by_codec']})",
        file=sys.stderr,
    )

    for leg, name in ((base, "baseline"), (fast, "fast")):
        if leg["sparse_frames"] == 0:
            print(f"FAIL: {name} leg never engaged the sparse codec", file=sys.stderr)
            return 1

    # 1. accuracy parity on the tiny problem (EF absorbs quantization noise).
    if fast["acc"] < base["acc"] - ACC_TOL:
        print(
            f"FAIL: quantized accuracy {fast['acc']:.3f} fell more than "
            f"{ACC_TOL} below baseline {base['acc']:.3f}",
            file=sys.stderr,
        )
        return 1
    print(f"PASS: accuracy {fast['acc']:.3f} vs baseline {base['acc']:.3f}", file=sys.stderr)

    # 2. sparse-codec bytes shrink (per-codec TX attribution).
    base_sparse = sum(b for c, b in base["by_codec"].items() if c.startswith("topk"))
    fast_sparse = sum(b for c, b in fast["by_codec"].items() if c.startswith("topk"))
    if "topk-int4" not in fast["by_codec"]:
        print(
            f"FAIL: no bytes attributed to topk-int4 (got {fast['by_codec']})",
            file=sys.stderr,
        )
        return 1
    ratio = base_sparse / max(fast_sparse, 1)
    if ratio < BYTES_X:
        print(
            f"FAIL: sparse-codec bytes shrank only {ratio:.2f}x "
            f"({base_sparse} -> {fast_sparse}), need >= {BYTES_X}x",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: sparse-codec bytes {base_sparse} -> {fast_sparse} ({ratio:.2f}x)",
        file=sys.stderr,
    )

    # 3. measured train<->diffuse overlap > 0 and diffusion off the critical
    # path: both legs pay identical fit floors, so a wall-clock reduction is
    # exactly the serialized diffuse time the stage machine no longer waits
    # out. (The summed serialized_diffuse_s is NOT compared across legs —
    # background drains keep their spans open a gossip tick longer by
    # design; the per-leg overlap fraction and the wall are the invariants.)
    frac = fast["overlap"]["train_diffuse_overlap_fraction"]
    if not frac > 0:
        print(
            f"FAIL: train_diffuse_overlap_fraction = {frac} (expected > 0); "
            f"report: {fast['overlap']}",
            file=sys.stderr,
        )
        return 1
    if fast["wall"] >= base["wall"]:
        print(
            f"FAIL: overlapped wall {fast['wall']:.1f}s did not beat the "
            f"serialized baseline {base['wall']:.1f}s",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: overlap_fraction {frac:.3f} > 0, wall {base['wall']:.1f}s -> "
        f"{fast['wall']:.1f}s (serialized diffuse: baseline "
        f"{base['overlap']['serialized_diffuse_s']:.2f}s, overlapped leg "
        f"{fast['overlap']['serialized_diffuse_s']:.2f}s of which "
        f"{fast['overlap']['train_diffuse_overlap_s']:.2f}s under own fit)",
        file=sys.stderr,
    )
    print("wire-check PASSED", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
