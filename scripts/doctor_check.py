#!/usr/bin/env python
"""doctor-check — seeded fault scenarios must diagnose to their injected cause.

The CI gate for the diagnosis plane (``make doctor-check``, ~30 s):
drives three seeded 3-node fault scenarios plus a clean control through
the REAL telemetry planes (trajectory ledger, flight recorder, chaos
plane, observatory, metrics registry) — not mocks — captures an evidence
bundle for each, and asserts:

1. **attribution** — the top-1 diagnosis names the injected fault:
   straggler → ``straggler_gating``, signflip adversary →
   ``byzantine_active``, mid-round kill → ``churn_starved_cohort``;
2. **calibration** — the clean control produces ZERO findings (every
   rule demands an explicit anomaly signal, not just "telemetry exists");
3. **determinism** — running a scenario twice under its pinned run id
   yields replay-identical bundle manifests once the ``excluded``
   section (timestamps, volatile hashes) is stripped
   (:func:`~p2pfl_tpu.telemetry.bundle.comparable_manifest`).

Exit 0 on success, 1 with a named failure otherwise.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from p2pfl_tpu.chaos.plane import CHAOS  # noqa: E402
from p2pfl_tpu.config import Settings  # noqa: E402
from p2pfl_tpu.telemetry import bundle  # noqa: E402
from p2pfl_tpu.telemetry.digest import HealthDigest  # noqa: E402
from p2pfl_tpu.telemetry.flight_recorder import (  # noqa: E402
    FlightRecorder,
    reset_live_recorders,
)
from p2pfl_tpu.telemetry.ledger import LEDGERS  # noqa: E402
from p2pfl_tpu.telemetry.metrics import REGISTRY  # noqa: E402
from p2pfl_tpu.telemetry.observatory import Observatory  # noqa: E402

NODES = ("n0", "n1", "n2")


def _reset_world() -> None:
    """Start each scenario run from a zeroed process: same telemetry state
    both runs → same bundle manifest (the determinism assertion)."""
    REGISTRY.reset()
    LEDGERS.reset()
    CHAOS.reset()
    bundle.reset_run()
    reset_live_recorders()


def _digest(node: str, **kw) -> HealthDigest:
    d = HealthDigest(node=node, ts=time.time())
    for k, v in kw.items():
        setattr(d, k, v)
    return d


def _snapshot(obs: Observatory, workdir: str) -> None:
    obs.write_snapshot(os.path.join(workdir, "federation_snapshot.json"))


def scenario_straggler(workdir: str) -> None:
    """n2 runs 3 rounds behind at 1/20th the fleet step rate, and the
    aggregator hit its stall patience waiting for it."""
    obs = Observatory("n0")
    obs.ingest(_digest("n0", round=5, total_rounds=8, steps_per_s=100.0))
    obs.ingest(_digest("n1", round=5, total_rounds=8, steps_per_s=95.0))
    obs.ingest(_digest("n2", round=2, total_rounds=8, steps_per_s=5.0))
    REGISTRY.counter(
        "p2pfl_aggregation_stall_partials_total", labels=("node",)
    ).labels("n0").inc(2)
    _snapshot(obs, workdir)


def scenario_signflip(workdir: str) -> None:
    """A seeded signflip adversary: chaos marks the peer byzantine, the
    fleet's admission plane rejects its frames, digests attribute the
    rejections back to it."""
    CHAOS.set_byzantine("adv", "signflip")
    rejected = REGISTRY.counter(
        "p2pfl_updates_rejected_total", labels=("node", "reason", "source")
    )
    for r in (1, 2, 3):
        LEDGERS.emit(
            "n0", "admission_rejected", round=r, sender="adv",
            reason="norm_screen",
            dedup_key=("admission", r, "adv", "norm_screen"),
        )
        rejected.labels("n0", "norm_screen", "adv").inc()
    obs = Observatory("n0")
    obs.ingest(_digest("n0", round=3, total_rounds=8, steps_per_s=100.0,
                       rejected_by_source={"adv": 3.0}))
    obs.ingest(_digest("n1", round=3, total_rounds=8, steps_per_s=98.0))
    obs.ingest(_digest("n2", round=3, total_rounds=8, steps_per_s=102.0))
    obs.ingest(_digest("adv", round=3, total_rounds=8, steps_per_s=100.0))
    _snapshot(obs, workdir)


def scenario_kill(workdir: str):
    """n2 is killed mid-round: chaos blackholes its frames, the failure
    detector declares it lost (never recovered), aggregation drops its
    contribution from the expected set."""
    CHAOS.crash("n2")
    rec = FlightRecorder("n0")
    rec.record("peer_lost", peer="n2", missed=5.0)
    REGISTRY.counter(
        "p2pfl_chaos_faults_total", labels=("node", "fault")
    ).labels("n2", "crash").inc(3)
    REGISTRY.counter(
        "p2pfl_aggregation_dead_contributors_total", labels=("node",)
    ).labels("n0").inc()
    obs = Observatory("n0", recorder=rec)
    obs.ingest(_digest("n0", round=4, total_rounds=8, steps_per_s=100.0))
    obs.ingest(_digest("n1", round=4, total_rounds=8, steps_per_s=98.0))
    _snapshot(obs, workdir)
    # The live-recorder registry holds WEAK refs — keep the recorder alive
    # until write_bundle has collected its ring.
    return rec


def scenario_control(workdir: str) -> None:
    """Three healthy peers, nothing injected — must diagnose to nothing."""
    obs = Observatory("n0")
    obs.ingest(_digest("n0", round=3, total_rounds=8, steps_per_s=100.0))
    obs.ingest(_digest("n1", round=3, total_rounds=8, steps_per_s=98.0))
    obs.ingest(_digest("n2", round=3, total_rounds=8, steps_per_s=102.0))
    _snapshot(obs, workdir)


SCENARIOS = (
    # (name, builder, expected top-1 rule; None = expect zero findings)
    ("straggler", scenario_straggler, "straggler_gating"),
    ("signflip", scenario_signflip, "byzantine_active"),
    ("kill", scenario_kill, "churn_starved_cohort"),
    ("control", scenario_control, None),
)


def run_once(name, builder, root: str, attempt: int):
    """One seeded scenario pass: build the fault's telemetry story, bundle
    it, return (bundle_dir, incident_doc)."""
    workdir = os.path.join(root, f"{name}-{attempt}")
    os.makedirs(workdir, exist_ok=True)
    _reset_world()
    with Settings.overridden(RUN_ID=f"doctor-{name}"):
        keepalive = builder(workdir)  # noqa: F841 — weakly-registered recorders
        out = bundle.write_bundle(
            "doctor_check", directory=workdir, context={"scenario": name}
        )
        assert out, f"{name}: write_bundle produced nothing"
        with open(os.path.join(out, "incident.json")) as f:
            incident = json.load(f)
    return out, incident


def main() -> int:
    root = tempfile.mkdtemp(prefix="doctor_check_")
    t0 = time.time()
    failures = []
    try:
        for name, builder, expect in SCENARIOS:
            out1, inc1 = run_once(name, builder, root, 1)
            out2, _ = run_once(name, builder, root, 2)
            top = inc1.get("top")
            rules = [f["rule"] for f in inc1.get("findings", ())]
            if expect is None:
                ok = not rules
                verdict = "clean" if ok else f"UNEXPECTED findings {rules}"
            else:
                ok = top == expect
                verdict = f"top-1 {top}" + ("" if ok else f" (wanted {expect})")
            if not ok:
                failures.append(name)
            # Determinism: same scenario, same pinned run id, two fresh
            # processes-worth of state → identical comparable manifests.
            m1 = bundle.comparable_manifest(bundle.load_manifest(out1))
            m2 = bundle.comparable_manifest(bundle.load_manifest(out2))
            if m1 != m2:
                failures.append(f"{name}-manifest")
                verdict += "  MANIFEST DRIFT between identical runs"
            rid = inc1.get("run_id", "")
            if expect is not None and rid != f"doctor-{name}":
                failures.append(f"{name}-runid")
                verdict += f"  run_id {rid!r} not pinned"
            status = "ok" if name not in [f.split("-")[0] for f in failures] else "FAIL"
            print(f"  {name:<10} {status:<5} {verdict}  (findings: {rules or '-'})")
    finally:
        _reset_world()
        shutil.rmtree(root, ignore_errors=True)
    dt = time.time() - t0
    if failures:
        print(f"doctor-check FAILED ({', '.join(failures)}) in {dt:.1f}s")
        return 1
    print(f"doctor-check OK: 3 faults attributed + control clean, "
          f"manifests replay-identical ({dt:.1f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
