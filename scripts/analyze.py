#!/usr/bin/env python
"""Static correctness analysis CLI (``make analyze``).

Runs the C1-C5 checkers (p2pfl_tpu/analysis/checkers.py) over the package
tree and reconciles findings against the committed suppression baseline.

Exit codes: 0 clean | 1 new finding | 2 stale suppression | 3 usage error.

Examples:

    python scripts/analyze.py --baseline analysis_baseline.json
    python scripts/analyze.py --checks C1,C2          # subset, no baseline
    python scripts/analyze.py --baseline analysis_baseline.json \
        --write-baseline  # refresh (reasons to be filled in by hand)
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from p2pfl_tpu.analysis import (  # noqa: E402
    ALL_CHECKERS,
    Baseline,
    compare,
    run_checkers,
)
from p2pfl_tpu.analysis.baseline import Suppression  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--root",
        default=str(Path(__file__).resolve().parent.parent),
        help="repo root (default: this repo)",
    )
    ap.add_argument(
        "--subdirs",
        default="p2pfl_tpu",
        help="comma-separated subtrees to scan (default: p2pfl_tpu)",
    )
    ap.add_argument("--baseline", default=None, help="suppression baseline JSON")
    ap.add_argument(
        "--checks",
        default=None,
        help=f"comma-separated subset of {','.join(sorted(ALL_CHECKERS))}",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to --baseline (reason: TODO) and exit 0",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)

    checks = None
    if args.checks:
        checks = [c.strip().upper() for c in args.checks.split(",") if c.strip()]
        unknown = [c for c in checks if c not in ALL_CHECKERS]
        if unknown:
            print(f"unknown checks: {unknown}", file=sys.stderr)
            return 3

    root = Path(args.root).resolve()
    subdirs = [s.strip() for s in args.subdirs.split(",") if s.strip()]
    findings = run_checkers(root, subdirs, checks)

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline requires --baseline", file=sys.stderr)
            return 3
        bl = Baseline(
            [Suppression(f.checker, f.key, "TODO: justify or fix") for f in findings]
        )
        bl.save(Path(args.baseline))
        print(f"wrote {len(findings)} suppressions to {args.baseline}")
        return 0

    baseline = Baseline()
    if args.baseline:
        try:
            baseline = Baseline.load(Path(args.baseline))
        except FileNotFoundError:
            print(f"baseline {args.baseline} not found", file=sys.stderr)
            return 3
        except ValueError as exc:
            print(f"bad baseline: {exc}", file=sys.stderr)
            return 3

    new, suppressed, stale = compare(findings, baseline)

    if args.json:
        print(
            json.dumps(
                {
                    "new": [f.__dict__ for f in new],
                    "suppressed": [f.__dict__ for f in suppressed],
                    "stale": [s.to_json() for s in stale],
                },
                indent=2,
            )
        )
    else:
        for f in new:
            print(f"NEW  {f.render()}")
        for f in suppressed:
            print(f"SUPP {f.render()}")
        for s in stale:
            print(f"STALE suppression {s.key} ({s.reason})")
        print(
            f"-- {len(new)} new, {len(suppressed)} suppressed, "
            f"{len(stale)} stale (checks: {','.join(checks or sorted(ALL_CHECKERS))})"
        )

    if new:
        return 1
    if stale:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
