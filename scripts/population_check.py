"""population-check — the population-engine gate (fast CI shape, ~30 s).

Certifies the cohort-sampling contract on a small fused population so CI
catches a broken sampler before the expensive ``bench.py --population``
acceptance run does:

1. a 64-node :class:`~p2pfl_tpu.population.PopulationEngine` at 10% cohort
   WITH a seeded churn trace finishes its rounds, every elected committee
   is drawn from that round's available set, and the realized mean cohort
   fill equals K/n exactly;
2. the cohort stream is **replay-identical**: an engine driven in chunks
   (2 + 3 rounds) elects the same committees — and reaches the same node-0
   params hash — as one driven in a single 5-round call, and a freshly
   constructed :class:`~p2pfl_tpu.population.cohort.CohortPlan` rederives
   the exact schedule (resume safety without a checkpoint);
3. a different seed produces a different stream (negative control — the
   sampler must be able to disagree).

Exit 0 on pass, 1 on failure. ``make population-check`` wires it next to
the other plane gates.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.population import PopulationEngine
    from p2pfl_tpu.population.cohort import CohortPlan, committee_schedule

    n, rounds, fraction, churn, seed = 64, 5, 0.1, 0.2, 1234
    t0 = time.monotonic()
    print(
        f"population-check: n={n} rounds={rounds} cohort={fraction:g} "
        f"churn={churn:g} seed={seed} — engine arm...",
        file=sys.stderr,
    )
    eng_kw = dict(
        cohort_fraction=fraction, churn_rate=churn, seed=seed,
        samples_per_node=8, hidden=(8,),
    )
    with PopulationEngine(n, **eng_kw) as eng:
        names, plan, k = eng.names, eng.plan, eng.cohort_k
        res = eng.run(rounds)
        fill = eng.cohort_fill()
        hash_single = _hash0(eng)
        committees = np.asarray(res.committees)

    if committees.shape != (rounds, k):
        print(
            f"FAIL: committees shape {committees.shape}, wanted "
            f"({rounds}, {k})",
            file=sys.stderr,
        )
        return 1
    for r in range(rounds):
        avail = {nm for nm in names if plan.available(r, nm)}
        elected = {names[i] for i in committees[r]}
        if not elected <= avail:
            print(
                f"FAIL: round {r} elected churned-out nodes "
                f"{sorted(elected - avail)}",
                file=sys.stderr,
            )
            return 1
    if abs(float(fill.mean()) * n - k) > 1e-6:
        print(
            f"FAIL: mean cohort fill {fill.mean():.6g} != K/n {k / n:.6g}",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: {rounds} churned rounds finished; committees within the "
        f"available set; mean fill == K/n ({k}/{n})",
        file=sys.stderr,
    )

    # Replay-identical: chunked driving == one call == fresh-plan rederive.
    with PopulationEngine(n, **eng_kw) as eng2:
        res_a = eng2.run(2)
        res_b = eng2.run(3)
        chunked = np.concatenate(
            [np.asarray(res_a.committees), np.asarray(res_b.committees)]
        )
        hash_chunked = _hash0(eng2)
    if not np.array_equal(chunked, committees):
        print("FAIL: chunked cohort stream != single-call stream", file=sys.stderr)
        return 1
    if hash_chunked != hash_single:
        print(
            f"FAIL: chunked params hash {hash_chunked[:16]}… != single-call "
            f"{hash_single[:16]}…",
            file=sys.stderr,
        )
        return 1
    rederived = committee_schedule(
        CohortPlan(
            seed=seed, fraction=fraction, churn_rate=churn,
            names=tuple(names),
        ),
        names,
        rounds,
    )
    if not np.array_equal(rederived, committees):
        print("FAIL: fresh CohortPlan rederived a different schedule", file=sys.stderr)
        return 1
    print(
        "PASS: cohort stream replay-identical (chunked run, fresh plan) "
        "with bit-identical params",
        file=sys.stderr,
    )

    # Negative control: the sampler must be able to disagree.
    other = committee_schedule(
        CohortPlan(
            seed=seed + 1, fraction=fraction, churn_rate=churn,
            names=tuple(names),
        ),
        names,
        rounds,
    )
    if np.array_equal(other, committees):
        print("FAIL: seed {seed+1} produced the seed-{seed} stream", file=sys.stderr)
        return 1
    print("PASS: different seed, different stream (negative control)", file=sys.stderr)
    print(
        f"population-check PASSED in {time.monotonic() - t0:.1f}s",
        file=sys.stderr,
    )
    return 0


def _hash0(eng) -> str:
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    return canonical_params_hash(eng.gather_params(0))


if __name__ == "__main__":
    sys.exit(main())
