"""CI gate: privacy plane (committee secure aggregation + DP budget).

One tiny 3-node MNIST federation runs three legs:

* **plaintext** — the PR 12 sparse wire (top-k int8), no masking;
* **masked** — ``PRIVACY_SECAGG``: pairwise-masked lattice frames on the
  shared rand-k support, DP-SGD clipping+noise in the learner;
* **dropout** — same masked shape, but one committee member (chosen by the
  seeded ``CHAOS.plan_masker_dropout`` trace) is crashed MID-round-1;
  survivors must repair the uncancelled mask shares and finish.

Asserts (exit 0 when all pass; nonzero with a reason on stderr):

1. the masked run's accuracy lands within ``ACC_TOL`` of plaintext (the EF
   residual absorbs lattice + rand-k error within a few rounds),
2. one masker killed mid-round does not corrupt the aggregate — survivors
   finish with sane accuracy, repairs counted (``privacy_repair``),
3. the DP budget is live: every node reports a NONZERO epsilon through the
   budget ledger (and hence the digest/fed_top surface).

Fast, CPU-only, tier-1-safe — invoked by ``make privacy-check``.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

ROUNDS = 6  # EF needs a few rounds to repay rand-k + lattice error
# Dropout legs run longer: losing a masker mid-round forks the survivors'
# contributor sets for that round (both costs are honest — plaintext pays
# the same under timeout partials), and the refederation needs a few more
# rounds to contract the fork on this tiny problem.
DROPOUT_ROUNDS = 8
ACC_TOL = 0.1
LEG_BUDGET_S = 150.0
KILL_ROUND = 1


def main() -> int:
    from p2pfl_tpu.chaos import CHAOS
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import (
        RandomIIDPartitionStrategy,
        synthetic_mnist,
    )
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.privacy import BUDGETS, wire_epsilon
    from p2pfl_tpu.telemetry import REGISTRY, TRACER
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3  # full committee: every node masks
    Settings.EXECUTOR_MAX_WORKERS = 0
    Settings.PRIVACY_KEY_WAIT_S = 8.0

    n = 3
    data = synthetic_mnist(n_train=128 * n, n_test=256)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)

    def run_leg(name, secagg, dp, kill_victim=None, rounds=ROUNDS):
        REGISTRY.reset()
        TRACER.reset()
        BUDGETS.reset()
        CHAOS.reset()
        Settings.WIRE_COMPRESSION = "topk"
        Settings.WIRE_TOPK_RATIO = 0.1
        Settings.WIRE_TOPK_VALUES = "int8"
        Settings.PRIVACY_SECAGG = secagg
        # DP parameters sized for the gate, not for a privacy claim: the
        # assertion here is that the MECHANISM runs end to end (clipped
        # per-example grads, Gaussian noise, nonzero finite epsilon through
        # the budget ledger) without sinking this tiny model — the epsilon
        # such a sigma buys is large and honestly reported as such.
        Settings.PRIVACY_DP_CLIP = 8.0 if dp else 0.0
        Settings.PRIVACY_DP_SIGMA = 0.005 if dp else 0.0
        nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
        victim = None
        try:
            for nd in nodes:
                nd.start()
            for i in range(1, n):
                nodes[i].connect(nodes[0].addr)
            wait_convergence(nodes, n - 1, wait=15)
            if kill_victim is not None:
                trace = CHAOS.plan_masker_dropout(
                    rounds, [nd.addr for nd in nodes], seed=7, drop_round=KILL_ROUND
                )
                victim = next(nd for nd in nodes if nd.addr == trace[0].node)
            nodes[0].set_start_learning(rounds=rounds, epochs=1)
            killed = False
            deadline = time.monotonic() + LEG_BUDGET_S
            while time.monotonic() < deadline:
                if victim is not None and not killed:
                    if (victim.state.round or 0) >= KILL_ROUND:
                        time.sleep(0.3)  # mid-round: keys exchanged, gossip live
                        victim.crash()
                        CHAOS.recovery(victim.addr, "crash")
                        killed = True
                survivors = [nd for nd in nodes if nd is not victim or not killed]
                if all(
                    not nd.learning_in_progress()
                    and nd.learning_workflow is not None
                    for nd in survivors
                ):
                    break
                time.sleep(0.1)
            else:
                print(f"FAIL: {name} leg did not finish in budget", file=sys.stderr)
                return None
            survivors = [nd for nd in nodes if nd is not victim or not killed]
            accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in survivors]
            eps = [wire_epsilon(BUDGETS.epsilon(nd.addr)) for nd in survivors]
            repairs = 0
            fam = REGISTRY.get("p2pfl_privacy_repairs_total")
            if fam is not None:
                repairs = sum(
                    int(c.value)
                    for lbl, c in fam.samples()
                    if lbl.get("role") == "applied"
                )
            return {
                "acc": sum(accs) / len(accs),
                "accs": accs,
                "eps": eps,
                "repairs": repairs,
                "killed": killed,
            }
        finally:
            for nd in nodes:
                try:
                    nd.stop()
                except Exception:  # noqa: BLE001 — crashed victim
                    pass
            InMemoryRegistry.reset()
            CHAOS.reset()

    print("privacy-check: plaintext leg...", file=sys.stderr)
    plain = run_leg("plaintext", secagg=False, dp=False)
    if plain is None:
        return 1
    print(
        f"privacy-check: plaintext acc {plain['acc']:.3f} — masked leg...",
        file=sys.stderr,
    )
    masked = run_leg("masked", secagg=True, dp=True)
    if masked is None:
        return 1
    print(
        f"privacy-check: masked acc {masked['acc']:.3f} eps {masked['eps']} — "
        "dropout leg...",
        file=sys.stderr,
    )
    dropout = run_leg(
        "dropout", secagg=True, dp=True, kill_victim=True, rounds=DROPOUT_ROUNDS
    )
    if dropout is None:
        return 1
    print(
        f"privacy-check: masked dropout acc {dropout['acc']:.3f} — plaintext "
        "dropout reference leg...",
        file=sys.stderr,
    )
    # The fair comparator for "did the dead masker poison the sum": the SAME
    # kill on the plaintext wire — losing a third of the data degrades any
    # run; corruption would crater far below that reference.
    dropout_ref = run_leg(
        "dropout-ref", secagg=False, dp=False, kill_victim=True,
        rounds=DROPOUT_ROUNDS,
    )
    if dropout_ref is None:
        return 1

    # 1. masked accuracy parity with plaintext.
    if masked["acc"] < plain["acc"] - ACC_TOL:
        print(
            f"FAIL: masked accuracy {masked['acc']:.3f} fell more than "
            f"{ACC_TOL} below plaintext {plain['acc']:.3f}",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: masked acc {masked['acc']:.3f} vs plaintext {plain['acc']:.3f}",
        file=sys.stderr,
    )

    # 2. masker dropout: survivors finish, aggregate not corrupted, repairs
    # actually flowed.
    if not dropout["killed"] or not dropout_ref["killed"]:
        print("FAIL: a dropout leg never killed its masker", file=sys.stderr)
        return 1
    if dropout["acc"] < dropout_ref["acc"] - 2 * ACC_TOL:
        print(
            f"FAIL: masked dropout accuracy {dropout['acc']:.3f} collapsed "
            f"below the plaintext same-kill reference {dropout_ref['acc']:.3f} "
            "— the dead masker poisoned the sum",
            file=sys.stderr,
        )
        return 1
    print(
        f"PASS: one masker killed mid-round-{KILL_ROUND}; survivors at "
        f"{dropout['acc']:.3f} vs plaintext same-kill {dropout_ref['acc']:.3f} "
        f"(mask repairs applied: {dropout['repairs']})",
        file=sys.stderr,
    )

    # 3. epsilon nonzero on every node of the DP legs.
    for leg, name in ((masked, "masked"), (dropout, "dropout")):
        bad = [e for e in leg["eps"] if not e > 0]
        if bad:
            print(
                f"FAIL: {name} leg reported non-positive epsilon(s): {leg['eps']}",
                file=sys.stderr,
            )
            return 1
    print(f"PASS: epsilon nonzero on every node ({masked['eps']})", file=sys.stderr)
    print("privacy-check PASSED", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
