"""CI gate for the federation observatory: 3-node in-memory federation —
digests must propagate to every node, an injected slow peer's straggler
score must rise to the top of the fleet view, and a killed node's flight
recorder must dump to artifacts/. Fast, CPU-only, tier-1-safe — invoked by
``make observatory-check``.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

ROUNDS = 2
#: Per-fit extra delay for the seeded straggler; must exceed the stall
#: patience below so the fleet JIT-aggregates without it and real round lag
#: develops (lag is the straggler score's primary input).
STRAGGLE_S = 5.0
STALL_PATIENCE_S = 3.0
WALL_BUDGET_S = 90.0


def main() -> int:
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3
    Settings.AGGREGATION_STALL_PATIENCE = STALL_PATIENCE_S
    REGISTRY.reset()

    n = 3
    data = synthetic_mnist(n_train=128 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
    straggler = nodes[1]
    inner_fit = straggler.learner.fit

    def slow_fit(*a, **kw):
        time.sleep(STRAGGLE_S)
        return inner_fit(*a, **kw)

    straggler.learner.fit = slow_fit

    flagged_by = set()
    try:
        for nd in nodes:
            nd.start()
        for i in range(1, n):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, n - 1, wait=15)

        # --- check 1: digests propagate on heartbeats alone -----------------
        deadline = time.monotonic() + 15
        addrs = {nd.addr for nd in nodes}
        while time.monotonic() < deadline:
            if all(set(nd.observatory.scores()) >= addrs for nd in nodes):
                break
            time.sleep(0.1)
        else:
            views = {nd.addr: sorted(nd.observatory.scores()) for nd in nodes}
            print(f"FAIL: digests did not propagate to every node: {views}",
                  file=sys.stderr)
            return 1
        print("digests propagated to all 3 nodes", file=sys.stderr)

        # --- check 2: the slow peer's straggler score rises ------------------
        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=ROUNDS, epochs=1)
        observers = [nd for nd in nodes if nd is not straggler]
        finish_deadline = time.monotonic() + WALL_BUDGET_S
        while time.monotonic() < finish_deadline:
            for nd in observers:
                if nd.observatory.top("straggler") == straggler.addr:
                    flagged_by.add(nd.addr)
            if len(flagged_by) == len(observers) and all(
                not nd.learning_in_progress() and nd.learning_workflow is not None
                for nd in nodes
            ):
                break
            time.sleep(0.1)
        if len(flagged_by) != len(observers):
            missing = {nd.addr for nd in observers} - flagged_by
            print(f"FAIL: straggler never topped the fleet view on {missing}",
                  file=sys.stderr)
            return 1
        elapsed = time.monotonic() - t0
        print(
            f"straggler {straggler.addr} flagged by all observers "
            f"({elapsed:.1f}s into the run)",
            file=sys.stderr,
        )
        nodes[0].observatory.write_snapshot(
            os.path.join("artifacts", "federation_snapshot.json")
        )

        # --- check 3: flight recorder dumps on kill --------------------------
        victim = nodes[2]
        dump_path = victim.protocol.flight_recorder.dump_path("artifacts")
        try:
            os.remove(dump_path)
        except FileNotFoundError:
            pass
        victim.crash()
        if not os.path.exists(dump_path):
            print(f"FAIL: no flight-recorder dump at {dump_path} after kill",
                  file=sys.stderr)
            return 1
        import json

        with open(dump_path) as f:
            doc = json.load(f)
        if doc.get("trigger") != "crash" or not doc.get("events"):
            print(f"FAIL: malformed flight-recorder dump: {dump_path}",
                  file=sys.stderr)
            return 1
        print(
            f"flight recorder dumped {len(doc['events'])} events to {dump_path}",
            file=sys.stderr,
        )
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:  # noqa: BLE001
                pass
        InMemoryRegistry.reset()

    print(
        "observatory-check OK: digests propagated, straggler flagged by all "
        f"observers, flight recorder dumped on kill ({elapsed:.1f}s run)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
