"""campaign-check — the campaign-universe replay gate (~1-2 min CI shape).

Replays the COMMITTED campaign baseline deterministically: the first
``P2PFL_TPU_CAMPAIGN_CHECK_SCENARIOS`` scenarios of the default campaign
(one per family in rotation order, so the ADAPTIVE-adversary headline
family is always in the gate) run on BOTH backends, under the ledger
parity differ, graded against their family invariants — and the result
must match ``tests/campaign_fixtures/campaign_baseline.json`` byte for
byte on the deterministic surface:

1. the sampler re-derives the exact committed scenario keys (the campaign
   space itself didn't drift);
2. zero graded invariant violations;
3. every replay-stable family's per-round aggregate hashes equal the
   committed ones (wire AND fused — both backends, bit-for-bit);
4. the adaptive adversary's realized decision stream equals the committed
   one (the ladder escalated at the same rounds, driven by real
   admission rejections).

``--write-baseline`` regenerates the fixture after an INTENDED trajectory
change (a new optimizer, a kernel change…) — the diff then shows exactly
which hashes moved, which is the point of committing them.

Exit 0 on pass, 1 on failure. ``make campaign-check`` wires it next to
the other plane gates.
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_PATH = os.path.join(
    REPO, "tests", "campaign_fixtures", "campaign_baseline.json"
)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    write_baseline = "--write-baseline" in argv
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.campaigns import run_campaign, sample_campaign
    from p2pfl_tpu.config import Settings

    seed = int(Settings.CAMPAIGN_SEED)
    k = int(Settings.CAMPAIGN_CHECK_SCENARIOS)
    t0 = time.monotonic()
    print(
        f"campaign-check: seed={seed}, replaying {k} scenario(s) on both "
        "backends...",
        file=sys.stderr,
    )

    # Round-robin sampling makes the first k scenarios of ANY campaign
    # size identical (family = FAMILIES[i % len], per-family ordinals) —
    # the gate replays a true prefix of the full `bench.py --campaign` run.
    sampled = sample_campaign(seed, k)
    rep = run_campaign(
        seed, k, emit=lambda m: print(f"  {m}", file=sys.stderr)
    )

    if rep["violations_total"]:
        bad = [
            v for s in rep["scenarios"]
            for v in s.get("violations", [s.get("error", "")])
        ]
        return _fail(f"{rep['violations_total']} graded violation(s): {bad}")
    print(f"PASS: {k} scenario(s), zero invariant violations", file=sys.stderr)

    entries = []
    for cs, s in zip(sampled, rep["scenarios"]):
        entries.append(
            {
                "family": s["family"],
                "index": s["index"],
                "run_id": s["run_id"],
                "seed": s["seed"],
                "key": cs.key,
                "wire_hashes": s["wire_hashes"] if s["baseline_hashes"] else None,
                "fused_hashes": s["fused_hashes"] if s["baseline_hashes"] else None,
                "adaptive_decisions": (
                    s["adaptive"]["decisions"] if "adaptive" in s else None
                ),
            }
        )

    if write_baseline:
        os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
        with open(BASELINE_PATH, "w") as f:
            json.dump(
                {
                    "campaign_seed": seed,
                    "check_scenarios": k,
                    "scenarios": entries,
                },
                f,
                indent=1,
            )
            f.write("\n")
        print(f"baseline written to {BASELINE_PATH}", file=sys.stderr)
        return 0

    try:
        with open(BASELINE_PATH) as f:
            baseline = json.load(f)
    except OSError as e:
        return _fail(
            f"no committed baseline at {BASELINE_PATH} ({e}); run with "
            "--write-baseline to create one"
        )
    if baseline.get("campaign_seed") != seed or baseline.get("check_scenarios") != k:
        return _fail(
            f"baseline shape (seed={baseline.get('campaign_seed')}, "
            f"k={baseline.get('check_scenarios')}) != configured "
            f"(seed={seed}, k={k}) — regenerate with --write-baseline"
        )
    committed = baseline.get("scenarios", [])
    if len(committed) != len(entries):
        return _fail(
            f"baseline holds {len(committed)} scenario(s), replay produced "
            f"{len(entries)}"
        )
    for want, got in zip(committed, entries):
        where = f"{got['family']}[{got['index']}]"
        if want["key"] != got["key"]:
            return _fail(
                f"{where}: sampler drift — key\n  committed {want['key']}\n"
                f"  replayed  {got['key']}"
            )
        for side in ("wire_hashes", "fused_hashes"):
            if want.get(side) != got.get(side):
                return _fail(
                    f"{where}: {side} diverged from committed baseline\n"
                    f"  committed {want.get(side)}\n  replayed  {got.get(side)}"
                )
        if want.get("adaptive_decisions") != got.get("adaptive_decisions"):
            return _fail(
                f"{where}: adaptive decision stream diverged\n"
                f"  committed {want.get('adaptive_decisions')}\n"
                f"  replayed  {got.get('adaptive_decisions')}"
            )
    print(
        "PASS: committed baseline replayed bit-identically "
        f"({sum(1 for e in entries if e['wire_hashes'])} hash sets, "
        f"{sum(1 for e in entries if e['adaptive_decisions'])} adaptive "
        "stream(s))",
        file=sys.stderr,
    )
    print(
        f"campaign-check PASSED in {time.monotonic() - t0:.1f}s",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
