"""Compare two bench JSONs for performance regressions, with exit codes.

The regression gate every perf PR is judged against: run an arm twice (or
against a stored baseline), then

    python scripts/perf_diff.py BASELINE.json CANDIDATE.json

Exit codes:

* ``0`` — no regression (improvements and in-noise changes both pass),
* ``1`` — at least one compared metric regressed past its threshold,
* ``2`` — usage / unreadable input,
* ``3`` — schema refusal: the two files carry different ``meta`` /
  ``perf`` schema versions (or a different metric name) and diffing them
  would be comparing incomparable shapes; ALSO raised when the two runs
  measured different backends (``meta.backend``, e.g. a TPU baseline vs a
  CPU-fallback candidate — BENCH_r03–r05's silent degradations produced
  exactly this shape). The refusal message names each side's
  ``meta.fallback_reason`` when present, so "why did this run fall back"
  is answered by the gate instead of reverse-engineered from timestamps.
  Pass ``--allow-backend-mismatch`` to compare anyway (the numbers are
  then cross-platform and NOT regression-gateable).

What gets compared (dotted paths; ``*`` fans out over dict keys):

* lower-is-better timings — ``value`` (only when the arm's ``unit`` looks
  time-like), ``extra.sec_per_round``, ``extra.mean_round_wall_s``,
  ``extra.wall_s``, and every per-node steady-state step time under
  ``perf.steady_state.step_s.*``;
* count-like health signals — ``perf.compile.recompiles_total.*`` regresses
  only when the candidate exceeds the baseline by more than
  ``--count-slack`` (default 0: ANY new recompiles fail);
* campaign artifacts (``bench.py --campaign``) — the per-family arms under
  ``extra.families.*`` diff per scenario FAMILY: ``seconds`` as a
  lower-is-better timing per family, ``violations`` as a per-family count
  (any newly-violated family fails — aggregate summing would let one
  family's fix mask another's break), and the top-level
  ``campaign_scenarios_ok`` value gated HIGHER-is-better (fewer passing
  scenarios than the baseline is a regression even if nothing got slower);
* device-observatory fields under ``perf.devobs.*`` (present when the run
  had ``DEVOBS_ENABLED``) — ``device_peak_bytes``, ``compile_seconds``,
  ``scan_flops`` / ``scan_bytes`` — gated lower-is-better with the same
  noise-band machinery. A bench where exactly ONE side carries a
  ``perf.devobs`` section is refused (exit 3): telemetry-on vs
  telemetry-off timings are not comparable (the on side pays the aux
  stream), and silently skipping the section would read as "no devobs
  regression" when nothing was compared. Both sides absent → skipped.
* supervisor fields under ``perf.supervisor.*`` (present when the run went
  through ``bench.py --soak``) — ``journal_s_per_chunk`` and
  ``overhead_ratio`` gated lower-is-better, ``restarts`` /
  ``degrade_steps`` as counts (the soak arms are seeded, so any new
  restart is a healing regression). Exactly ONE side carrying a
  ``perf.supervisor`` section is refused (exit 3) for the same reason as
  devobs: supervised vs unsupervised timings are not comparable.

Noise-awareness: a timing regresses only when
``candidate > baseline * (1 + threshold)`` AND the absolute growth exceeds
``--min-delta-s`` (default 1 ms) — double jitter on a microsecond metric is
not a regression; baselines below the absolute floor are reported but never
fail. When a baseline value is a LIST of samples, its mean and stddev are
used and the threshold becomes ``max(rel, 2 * cv)`` — a naturally noisy
metric earns a proportionally wider band. ``--threshold`` defaults to 0.25
(the CPU-venue arms see ~10-15% run-to-run wobble; 2x regressions are what
the gate exists to catch).

Extra comparisons: repeat ``--key extra.some.path`` to add lower-is-better
metrics. Output is one human-readable line per metric plus a JSON summary
line on stdout; ``--report`` swaps the JSON line for a markdown report
(per-key table, verdict, refusal reason when the gate refused) pasteable
into a PR description.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_TIMING_KEYS = (
    "extra.sec_per_round",
    "extra.mean_round_wall_s",
    "extra.wall_s",
    "perf.steady_state.step_s.*",
    # Device-observatory fields (lower is better for all of them: HBM
    # watermark growth, compile-time growth, and compiled-program FLOP /
    # byte growth are each a real regression class).
    "perf.devobs.device_peak_bytes",
    "perf.devobs.compile_seconds",
    "perf.devobs.scan_flops",
    "perf.devobs.scan_bytes",
    # Campaign artifacts: per-scenario-family wall time (absent on
    # non-campaign benches — the fan-out just resolves to nothing).
    "extra.families.*.seconds",
    # Supervisor fields (bench.py --soak): per-chunk journal cost and the
    # supervised/unsupervised wall ratio are both lower-is-better.
    "perf.supervisor.journal_s_per_chunk",
    "perf.supervisor.overhead_ratio",
)
DEFAULT_COUNT_KEYS = (
    "perf.compile.recompiles_total.*",
    # Seeded soak arms are deterministic: any new restart or degrade step
    # is a healing regression, not noise.
    "perf.supervisor.restarts",
    "perf.supervisor.degrade_steps",
)
#: Campaign per-family violation counts, compared PER LABEL (a newly
#: violated family must fail even when another family's count dropped).
FAMILY_COUNT_KEYS = ("extra.families.*.violations",)

#: ``value`` is compared only when the arm's unit says lower-is-better time.
_TIMEY_UNITS = ("s/round", "seconds", "s", "ms", "us/counter_increment")


def _get_path(doc: Any, path: List[str]) -> List[Tuple[str, Any]]:
    """Resolve a dotted path with ``*`` fan-out; returns (flat_key, value)."""
    out: List[Tuple[str, Any]] = [("", doc)]
    for part in path:
        nxt: List[Tuple[str, Any]] = []
        for prefix, node in out:
            if not isinstance(node, dict):
                continue
            if part == "*":
                for k, v in node.items():
                    nxt.append((f"{prefix}.{k}".lstrip("."), v))
            elif part in node:
                nxt.append((f"{prefix}.{part}".lstrip("."), node[part]))
        out = nxt
    return out


def _stats(v: Any) -> Optional[Tuple[float, float]]:
    """(mean, std) of a numeric scalar or list; None when non-numeric."""
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        if math.isnan(v) or math.isinf(v):
            return None
        return float(v), 0.0
    if isinstance(v, list) and v and all(
        isinstance(x, (int, float)) and not isinstance(x, bool) for x in v
    ):
        m = sum(v) / len(v)
        var = sum((x - m) ** 2 for x in v) / len(v)
        return m, math.sqrt(var)
    return None


def _schema_of(doc: Dict[str, Any]) -> Tuple[Any, Any, Any]:
    return (
        (doc.get("meta") or {}).get("schema_version"),
        (doc.get("perf") or {}).get("schema_version"),
        doc.get("metric"),
    )


def compare(
    base: Dict[str, Any],
    cand: Dict[str, Any],
    threshold: float = 0.25,
    min_delta_s: float = 1e-3,
    count_slack: int = 0,
    extra_keys: Tuple[str, ...] = (),
) -> Dict[str, Any]:
    """Pure comparison (importable by tests / perf_check): returns the
    summary dict; ``summary["regressions"]`` non-empty means exit 1."""
    rows: List[Dict[str, Any]] = []
    regressions: List[str] = []

    timing_keys = list(DEFAULT_TIMING_KEYS) + list(extra_keys)
    unit = str(base.get("unit") or "")
    if any(unit == u or unit.endswith(u) for u in _TIMEY_UNITS):
        timing_keys.insert(0, "value")

    for key in timing_keys:
        parts = key.split(".")
        base_vals = dict(_get_path(base, parts))
        cand_vals = dict(_get_path(cand, parts))
        for flat, bv in sorted(base_vals.items()):
            bs = _stats(bv)
            cs = _stats(cand_vals.get(flat))
            if bs is None or cs is None:
                continue
            bmean, bstd = bs
            cmean, _ = cs
            rel = threshold
            if bmean > 0 and bstd > 0:
                rel = max(threshold, 2.0 * bstd / bmean)  # noise-aware band
            limit = bmean * (1.0 + rel)
            delta = cmean - bmean
            regressed = (
                bmean >= 0
                and cmean > limit
                and delta > min_delta_s
            )
            rows.append(
                {
                    "key": flat,
                    "kind": "timing",
                    "baseline": bmean,
                    "candidate": cmean,
                    "allowed_rel": round(rel, 4),
                    "regressed": regressed,
                }
            )
            if regressed:
                regressions.append(flat)

    for key in DEFAULT_COUNT_KEYS:
        parts = key.split(".")
        base_vals = dict(_get_path(base, parts))
        cand_vals = dict(_get_path(cand, parts))
        # Fanned-out per-node counts compare in AGGREGATE: which node
        # absorbs a retrace is scheduler luck run to run (observed: the
        # same fleet total landing 3/2/1… one run and 5/1/3… the next) —
        # a recompile STORM shows up in the sum, not in any one label.
        if "*" in parts:
            bsum = sum(s[0] for s in map(_stats, base_vals.values()) if s)
            csum = sum(s[0] for s in map(_stats, cand_vals.values()) if s)
            flat = ".".join(parts[:-1]) + ".sum" if parts[-1] == "*" else key
            regressed = csum > bsum + count_slack
            rows.append(
                {
                    "key": flat,
                    "kind": "count",
                    "baseline": bsum,
                    "candidate": csum,
                    "allowed_slack": count_slack,
                    "regressed": regressed,
                }
            )
            if regressed:
                regressions.append(flat)
            continue
        for flat, cv in sorted(cand_vals.items()):
            cs = _stats(cv)
            if cs is None:
                continue
            bs = _stats(base_vals.get(flat, 0))
            bcount = bs[0] if bs else 0.0
            regressed = cs[0] > bcount + count_slack
            rows.append(
                {
                    "key": flat,
                    "kind": "count",
                    "baseline": bcount,
                    "candidate": cs[0],
                    "allowed_slack": count_slack,
                    "regressed": regressed,
                }
            )
            if regressed:
                regressions.append(flat)

    for key in FAMILY_COUNT_KEYS:
        parts = key.split(".")
        base_vals = dict(_get_path(base, parts))
        cand_vals = dict(_get_path(cand, parts))
        for flat, cv in sorted(cand_vals.items()):
            cs = _stats(cv)
            if cs is None:
                continue
            bs = _stats(base_vals.get(flat, 0))
            bcount = bs[0] if bs else 0.0
            regressed = cs[0] > bcount + count_slack
            rows.append(
                {
                    "key": flat,
                    "kind": "family-count",
                    "baseline": bcount,
                    "candidate": cs[0],
                    "allowed_slack": count_slack,
                    "regressed": regressed,
                }
            )
            if regressed:
                regressions.append(flat)

    if base.get("metric") == cand.get("metric") == "campaign_scenarios_ok":
        bs = _stats(base.get("value"))
        cs = _stats(cand.get("value"))
        if bs is not None and cs is not None:
            # Higher is better: the campaign passing FEWER scenarios than
            # its baseline is a robustness regression regardless of speed.
            regressed = cs[0] < bs[0]
            rows.append(
                {
                    "key": "value",
                    "kind": "campaign-ok",
                    "baseline": bs[0],
                    "candidate": cs[0],
                    "regressed": regressed,
                }
            )
            if regressed:
                regressions.append("value(campaign_scenarios_ok)")

    return {
        "compared": len(rows),
        "rows": rows,
        "regressions": regressions,
        "threshold": threshold,
    }


def render_markdown(
    summary: Optional[Dict[str, Any]],
    baseline: str,
    candidate: str,
    refusal: Optional[str] = None,
) -> str:
    """Render a comparison (or a refusal) as a markdown report — what
    ``--report`` prints, pasteable into a PR description."""
    lines = [
        "# perf_diff report",
        "",
        f"- baseline: `{baseline}`",
        f"- candidate: `{candidate}`",
    ]
    if refusal is not None:
        lines += [
            "",
            "## Verdict: REFUSED",
            "",
            "The two bench files are not comparable; no metrics were diffed.",
            "",
            f"> {refusal}",
        ]
        return "\n".join(lines)
    assert summary is not None
    regressions = summary.get("regressions") or []
    verdict = (
        f"REGRESSED — {len(regressions)} metric(s) past threshold"
        if regressions else "PASS — no regression"
    )
    lines += [
        f"- threshold: {summary.get('threshold')}",
        f"- metrics compared: {summary.get('compared')}",
        "",
        f"## Verdict: {verdict}",
        "",
        "| key | kind | baseline | candidate | band | result |",
        "|---|---|---:|---:|---|---|",
    ]
    for row in summary.get("rows", ()):
        if "allowed_rel" in row:
            band = f"+{100.0 * row['allowed_rel']:.0f}%"
        elif "allowed_slack" in row:
            band = f"+{row['allowed_slack']}"
        else:
            band = "higher-is-better"
        lines.append(
            f"| `{row['key']}` | {row['kind']} | {row['baseline']:.6g} "
            f"| {row['candidate']:.6g} | {band} "
            f"| {'**REGRESSED**' if row['regressed'] else 'ok'} |"
        )
    if regressions:
        lines += ["", "Regressed keys: " + ", ".join(f"`{k}`" for k in regressions)]
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two bench JSONs for perf regressions"
    )
    ap.add_argument("baseline")
    ap.add_argument("candidate")
    ap.add_argument(
        "--threshold", type=float, default=0.25,
        help="relative regression threshold for timings (default 0.25)",
    )
    ap.add_argument(
        "--min-delta-s", type=float, default=1e-3,
        help="absolute floor: timing growth below this never fails",
    )
    ap.add_argument(
        "--count-slack", type=int, default=0,
        help="allowed growth in count metrics (recompiles) before failing",
    )
    ap.add_argument(
        "--key", action="append", default=[],
        help="additional lower-is-better dotted path (repeatable, * fans out)",
    )
    ap.add_argument(
        "--allow-metric-mismatch", action="store_true",
        help="compare files whose top-level metric names differ",
    )
    ap.add_argument(
        "--allow-backend-mismatch", action="store_true",
        help="compare runs measured on different backends (cross-platform "
        "numbers are not regression-gateable; see module docstring)",
    )
    ap.add_argument(
        "--report", action="store_true",
        help="print the diff as a markdown report on stdout (per-key table, "
        "verdict, refusal reasons) instead of the JSON summary line",
    )
    args = ap.parse_args(argv)

    def _refuse(msg: str) -> int:
        """Print a schema/backend/telemetry refusal (exit 3); with
        ``--report`` also render it as markdown so CI surfaces WHY the
        gate refused instead of a bare exit code."""
        print(msg, file=sys.stderr)
        if args.report:
            print(render_markdown(None, args.baseline, args.candidate, refusal=msg))
        return 3

    try:
        with open(args.baseline) as f:
            base = json.load(f)
        with open(args.candidate) as f:
            cand = json.load(f)
    except Exception as e:  # noqa: BLE001
        print(f"perf_diff: cannot read inputs: {e}", file=sys.stderr)
        return 2
    if not isinstance(base, dict) or not isinstance(cand, dict):
        print("perf_diff: inputs must be bench JSON objects", file=sys.stderr)
        return 2

    b_meta, b_perf, b_metric = _schema_of(base)
    c_meta, c_perf, c_metric = _schema_of(cand)
    if b_meta != c_meta or b_perf != c_perf:
        return _refuse(
            f"perf_diff: SCHEMA REFUSAL — baseline meta/perf schema "
            f"({b_meta}, {b_perf}) != candidate ({c_meta}, {c_perf}); "
            "re-run both sides on one schema before diffing"
        )
    if b_metric != c_metric and not args.allow_metric_mismatch:
        return _refuse(
            f"perf_diff: SCHEMA REFUSAL — metric {b_metric!r} vs "
            f"{c_metric!r} (pass --allow-metric-mismatch to override)"
        )

    def _backend_of(doc: Dict[str, Any]) -> Tuple[Any, Any]:
        meta = doc.get("meta") or {}
        return meta.get("backend"), meta.get("fallback_reason")

    b_backend, b_why = _backend_of(base)
    c_backend, c_why = _backend_of(cand)
    if (
        b_backend and c_backend and b_backend != c_backend
        and not args.allow_backend_mismatch
    ):
        def _label(backend: Any, why: Any) -> str:
            return f"{backend!r}" + (f" (fell back: {why})" if why else "")

        # Refuse loudly instead of noise-gating: a TPU baseline diffed
        # against a CPU-fallback candidate reports a 100x "regression" that
        # is actually a platform change.
        return _refuse(
            "perf_diff: BACKEND REFUSAL — baseline measured on "
            f"{_label(b_backend, b_why)} but candidate on "
            f"{_label(c_backend, c_why)}; cross-platform timings are not "
            "comparable. Re-run both sides on one backend, or pass "
            "--allow-backend-mismatch to compare anyway (not gateable)."
        )

    b_devobs = (base.get("perf") or {}).get("devobs")
    c_devobs = (cand.get("perf") or {}).get("devobs")
    if (b_devobs is None) != (c_devobs is None):
        have, lack = (
            ("baseline", "candidate") if b_devobs is not None
            else ("candidate", "baseline")
        )
        # Refuse rather than skip: a telemetry-on run diffed against a
        # telemetry-off run compares different programs, and skipping the
        # section would report "no devobs regression" without comparing
        # anything. Re-run the lacking side with DEVOBS_ENABLED matching.
        return _refuse(
            f"perf_diff: DEVOBS REFUSAL — {have} carries a perf.devobs "
            f"section but {lack} does not; one side ran with device "
            "observability the other lacked. Re-run both sides with the "
            "same P2PFL_TPU_DEVOBS_ENABLED setting before diffing."
        )

    b_sup = (base.get("perf") or {}).get("supervisor")
    c_sup = (cand.get("perf") or {}).get("supervisor")
    if (b_sup is None) != (c_sup is None):
        have, lack = (
            ("baseline", "candidate") if b_sup is not None
            else ("candidate", "baseline")
        )
        # Same shape as the devobs refusal: a supervised run pays journal
        # writes the unsupervised run does not — diffing them compares
        # different programs, and skipping the section would report "no
        # supervisor regression" without comparing anything.
        return _refuse(
            f"perf_diff: SUPERVISOR REFUSAL — {have} carries a "
            f"perf.supervisor section but {lack} does not; one side ran "
            "under the engine supervisor the other lacked. Re-run both "
            "sides through bench.py --soak (or neither) before diffing."
        )

    summary = compare(
        base, cand,
        threshold=args.threshold,
        min_delta_s=args.min_delta_s,
        count_slack=args.count_slack,
        extra_keys=tuple(args.key),
    )
    for row in summary["rows"]:
        flag = "REGRESSED" if row["regressed"] else "ok"
        print(
            f"  {row['key']}: {row['baseline']:.6g} -> "
            f"{row['candidate']:.6g}  [{row['kind']}] {flag}",
            file=sys.stderr,
        )
    if args.report:
        print(render_markdown(summary, args.baseline, args.candidate))
    else:
        print(json.dumps(summary))
    if summary["regressions"]:
        print(
            f"perf_diff: {len(summary['regressions'])} regression(s): "
            f"{summary['regressions']}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
