#!/usr/bin/env python
"""fed_doctor — diagnose a run from its evidence bundle (or live artifacts).

Points the diagnosis rule catalog (:mod:`p2pfl_tpu.telemetry.diagnosis`)
at either:

* a **bundle directory** (``artifacts/bundle_<run_id>/``) — the complete,
  run-id-coherent evidence set a failure hook captured, or
* a **live artifacts directory** (default ``artifacts/``) — whatever
  ledger dumps / flight-recorder dumps / snapshots are lying around from
  the most recent run (best-effort; no completeness guarantee).

and prints the ranked incident report. Also (re)writes ``incident.json``
next to the evidence so the fed_top DIAGNOSIS banner picks it up.

Usage::

    python scripts/fed_doctor.py                      # live artifacts/
    python scripts/fed_doctor.py artifacts/bundle_ab12cd34ef56-0f3a
    python scripts/fed_doctor.py --json               # machine-readable
    python scripts/fed_doctor.py --latest             # newest bundle dir

Exit codes: 0 = report produced (findings or clean), 2 = no evidence at
the given path.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from p2pfl_tpu.telemetry import diagnosis  # noqa: E402


def _latest_bundle(root: str) -> str:
    """Newest bundle dir under ``root`` (by directory mtime), else root."""
    bundles = [d for d in glob.glob(os.path.join(root, "bundle_*")) if os.path.isdir(d)]
    if not bundles:
        return root
    return max(bundles, key=os.path.getmtime)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "path",
        nargs="?",
        default="artifacts",
        help="bundle dir or live artifacts dir (default: artifacts)",
    )
    ap.add_argument(
        "--latest",
        action="store_true",
        help="diagnose the newest bundle_* dir under PATH instead of PATH itself",
    )
    ap.add_argument(
        "--json", action="store_true", help="print the incident doc as JSON"
    )
    ap.add_argument(
        "--no-write",
        action="store_true",
        help="do not (re)write incident.json next to the evidence",
    )
    args = ap.parse_args(argv)

    path = _latest_bundle(args.path) if args.latest else args.path
    if not os.path.isdir(path):
        print(f"fed_doctor: no such directory: {path}", file=sys.stderr)
        return 2
    ev = diagnosis.load_evidence(path)
    if not (ev.ledgers or ev.flightrecs or ev.snapshot or ev.metrics or ev.context):
        print(f"fed_doctor: no evidence found under {path}", file=sys.stderr)
        return 2
    findings = diagnosis.diagnose(ev)
    doc = diagnosis.incident_doc(findings, run_id=ev.run_id, source=path)
    if not args.no_write:
        try:
            target = os.path.join(path, "incident.json")
            with open(target, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
                f.write("\n")
            # Keep the latest-incident pointer beside federation_snapshot.json
            # fresh too, when diagnosing a bundle nested under artifacts/.
            parent = os.path.dirname(os.path.abspath(path))
            if os.path.basename(path).startswith("bundle_"):
                with open(
                    os.path.join(parent, "incident.json"), "w", encoding="utf-8"
                ) as f:
                    json.dump(doc, f, indent=1, sort_keys=True)
                    f.write("\n")
        except OSError:
            pass
    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(diagnosis.render_report(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
