"""CI gate: run a 2-node in-memory federated round and assert the exported
telemetry snapshot contains the core metric families and a shared-trace
round timeline. Fast, CPU-only, tier-1-safe — invoked by
``make telemetry-check``.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402


CORE_FAMILIES = (
    "p2pfl_gossip_tx_bytes_total",
    "p2pfl_gossip_rx_bytes_total",
    "p2pfl_gossip_msgs_sent_total",
    "p2pfl_heartbeat_live_peers",
    "p2pfl_aggregation_wait_seconds",
    "p2pfl_stage_duration_seconds",
    "p2pfl_learner_jit_compile_seconds",
)


def main() -> int:
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY, TRACER
    from p2pfl_tpu.telemetry.export import render_prometheus, snapshot
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 2
    REGISTRY.reset()
    TRACER.reset()

    data = synthetic_mnist(n_train=256, n_test=64)
    parts = data.generate_partitions(2, RandomIIDPartitionStrategy)
    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(2)]
    for nd in nodes:
        nd.start()
    try:
        nodes[1].connect(nodes[0].addr)
        wait_convergence(nodes, 1, wait=15)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        deadline = time.time() + 300
        while time.time() < deadline:
            if all(
                not nd.learning_in_progress() and nd.learning_workflow is not None
                for nd in nodes
            ):
                break
            time.sleep(0.2)
        else:
            print("FAIL: 2-node round did not finish in 300s", file=sys.stderr)
            return 1
    finally:
        for nd in nodes:
            nd.stop()
        InMemoryRegistry.reset()

    snap = snapshot(REGISTRY)
    missing = [f for f in CORE_FAMILIES if f not in snap or not snap[f]["samples"]]
    if missing:
        print(f"FAIL: metric families missing/empty: {missing}", file=sys.stderr)
        return 1

    text = render_prometheus(REGISTRY)
    for fam in CORE_FAMILIES:
        if f"# TYPE {fam}" not in text:
            print(f"FAIL: {fam} absent from Prometheus exposition", file=sys.stderr)
            return 1

    spans = TRACER.spans()
    exp_traces = {s.trace_id for s in spans if s.name == "experiment"}
    if len(exp_traces) != 1:
        print(
            f"FAIL: expected one shared experiment trace id, got {exp_traces}",
            file=sys.stderr,
        )
        return 1
    if not any(s.name.startswith("recv:") and s.trace_id in exp_traces for s in spans):
        print("FAIL: no cross-node recv spans joined the experiment trace", file=sys.stderr)
        return 1

    print(
        f"telemetry-check OK: {len(snap)} metric families, {len(spans)} spans, "
        f"trace {sorted(exp_traces)[0]}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
