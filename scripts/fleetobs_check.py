"""CI gate: 3-node async federation proving the sketch-native observability
plane end to end, fast — invoked by ``make fleetobs-check``.

Three checks in one ~20s run (one 3x-slow peer, one v1-digest peer, 2 async
windows over the real in-memory wire):

* **staleness sketches propagate on beats** — a fast observer's fleet view
  holds a peer digest whose staleness sketch decoded (v2 digests riding
  heartbeats, sketch quantiles readable off the gossip wire);
* **window attribution flags the slow peer** — the window-level critical
  path (``CriticalPathAnalyzer.window_report``) names the seeded 3x-slow
  contributor as the top gating contributor;
* **v1-digest peers are tolerated** — a node pinned to the v1 digest format
  (no sketch table) interoperates: its digests still ingest, it still
  scores, and it finishes every window.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

WINDOWS = 2
FIT_FLOOR_S = 1.0
SLOW_X = 3.0
BUDGET_S = 90.0


def _stretch(node, floor_s):
    orig = node.learner.fit

    def fit(*a, **kw):
        t0 = time.monotonic()
        r = orig(*a, **kw)
        extra = floor_s - (time.monotonic() - t0)
        if extra > 0:
            time.sleep(extra)
        return r

    node.learner.fit = fit


def main() -> int:
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY, TRACER
    from p2pfl_tpu.telemetry import digest as digest_mod
    from p2pfl_tpu.telemetry.critical_path import CriticalPathAnalyzer
    from p2pfl_tpu.telemetry.sketches import SKETCHES
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.EXECUTOR_MAX_WORKERS = 0  # inline fits: sleep floors must overlap
    Settings.ASYNC_BUFFER_K = 2  # fast pair closes windows; slow folds stale
    Settings.ASYNC_WINDOW_TIMEOUT = 12.0
    REGISTRY.reset()
    TRACER.reset()
    SKETCHES.reset()

    n = 3
    data = synthetic_mnist(n_train=128 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    # One shared apply_fn + a throwaway-learner prewarm (the async/critical-
    # path bench pattern): per-node XLA compiles serialized inside window 0
    # would drown the seeded slowdown the attribution check measures.
    from p2pfl_tpu.learning.learner import JaxLearner

    template = mlp_model(seed=0)
    warm = JaxLearner(
        template.build_copy(), parts[0], self_addr="mem://warmup",
        batch_size=32, seed=0,
    )
    warm.set_epochs(1)
    warm.fit()
    warm.evaluate()
    del warm
    SKETCHES.reset()  # the warmup learner's step times are not a node's
    nodes = [
        Node(
            template.build_copy(params=mlp_model(seed=i).get_parameters()),
            parts[i], batch_size=32,
        )
        for i in range(n)
    ]
    observer, v1_peer, slow = nodes
    _stretch(observer, FIT_FLOOR_S)
    _stretch(v1_peer, FIT_FLOOR_S)
    _stretch(slow, FIT_FLOOR_S * SLOW_X)

    # Pin one peer to the v1 digest format: same vitals, no sketch table —
    # exactly what an un-upgraded node would gossip.
    def v1_provider():
        dig = digest_mod.collect(v1_peer.addr, v1_peer.state)
        dig.version = 1
        dig.sketches = {}
        return dig

    v1_peer.protocol.set_digest_source(v1_provider)

    try:
        for nd in nodes:
            nd.start()
        for i in range(1, n):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, n - 1, wait=15)
        observer.set_start_learning(rounds=WINDOWS, epochs=1, mode="async")
        deadline = time.monotonic() + BUDGET_S
        while time.monotonic() < deadline:
            if all(
                not nd.learning_in_progress()
                and nd.learning_workflow is not None
                and nd.learning_workflow.history.count("AsyncWindowFinishedStage")
                >= WINDOWS
                for nd in nodes
            ):
                break
            time.sleep(0.1)
        else:
            print(f"FAIL: federation did not finish in {BUDGET_S}s", file=sys.stderr)
            return 1
        # Beats keep flowing after the windows end; give the last digests a
        # moment to land so the sketch-propagation check reads settled state.
        time.sleep(3 * Settings.HEARTBEAT_PERIOD)

        snap = observer.observatory.snapshot()
        peers = snap.get("peers", {})

        # 1. staleness sketch propagated from a PEER's digest on beats.
        sketch_peers = [
            addr for addr, p in peers.items()
            if addr != observer.addr and p.get("staleness_p90") is not None
        ]
        if not sketch_peers:
            print(
                f"FAIL: no peer digest carried a decodable staleness sketch "
                f"(peers: {list(peers)})",
                file=sys.stderr,
            )
            return 1

        # 2. window attribution flags the seeded slow contributor.
        wreport = CriticalPathAnalyzer.from_tracer(TRACER).window_report()
        if wreport["top_gating_contributor"] != slow.addr:
            print(
                f"FAIL: window attribution named "
                f"{wreport['top_gating_contributor']} as top gating, expected "
                f"{slow.addr} (counts: {wreport['gating_counts']})",
                file=sys.stderr,
            )
            return 1

        # 3. the v1-digest peer is a full citizen: ingested, scored, done.
        v1_entry = peers.get(v1_peer.addr)
        if v1_entry is None or v1_entry.get("version") != 1:
            print(
                f"FAIL: v1-digest peer missing/mislabelled in the fleet view: "
                f"{v1_entry}",
                file=sys.stderr,
            )
            return 1
        v1_windows = v1_peer.learning_workflow.history.count(
            "AsyncWindowFinishedStage"
        )
        if v1_windows < WINDOWS:
            print(
                f"FAIL: v1-digest peer finished {v1_windows}/{WINDOWS} windows",
                file=sys.stderr,
            )
            return 1
    finally:
        for nd in nodes:
            try:
                nd.stop()
            except Exception:  # noqa: BLE001
                pass
        InMemoryRegistry.reset()

    print(
        f"fleetobs-check OK: staleness sketch propagated from "
        f"{len(sketch_peers)} peer(s); slow peer {slow.addr} top-gates "
        f"{wreport['gating_counts'].get(slow.addr, 0)}/{WINDOWS} windows "
        f"(close reasons: {wreport['close_reason_counts']}); v1-digest peer "
        f"tolerated through {v1_windows} windows"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
