"""CI gate: 3-node in-memory federation, one node killed mid-round — the
survivors must finish ALL rounds within a wall-clock budget (i.e. the death
callbacks unblocked every wait instead of each stage sleeping out its fixed
timeout). Fast, CPU-only, tier-1-safe — invoked by ``make chaos-check``.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

ROUNDS = 2
#: Wall budget for the whole learning run. Generous for a loaded 1-core CI
#: box, yet far below the worst case of sleeping out the stalled waits
#: (ROUNDS x (VOTE_TIMEOUT + AGGREGATION_TIMEOUT) = 80s under test settings
#: plus training time) — a regression to timeout-burning blows through it.
WALL_BUDGET_S = 75.0


def main() -> int:
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3  # full committee: the victim is always a trainer
    REGISTRY.reset()

    n = 3
    data = synthetic_mnist(n_train=128 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(n)]
    for nd in nodes:
        nd.start()
    victim, survivors = nodes[2], nodes[:2]
    try:
        for i in range(1, n):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, n - 1, wait=15)

        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=ROUNDS, epochs=1)

        # Kill the victim mid-round: as soon as round 0 is in flight.
        deadline = time.time() + 20
        while time.time() < deadline and nodes[0].state.round is None:
            time.sleep(0.05)
        if nodes[0].state.round is None:
            print("FAIL: learning never started", file=sys.stderr)
            return 1
        victim.crash()
        print(f"killed {victim.addr} mid-round", file=sys.stderr)

        finish_deadline = time.monotonic() + WALL_BUDGET_S
        while time.monotonic() < finish_deadline:
            if all(
                not nd.learning_in_progress() and nd.learning_workflow is not None
                for nd in survivors
            ):
                break
            time.sleep(0.2)
        else:
            print(
                f"FAIL: survivors did not finish {ROUNDS} rounds within "
                f"{WALL_BUDGET_S:.0f}s of the kill",
                file=sys.stderr,
            )
            return 1
        elapsed = time.monotonic() - t0

        for nd in survivors:
            finished = nd.learning_workflow.history.count("RoundFinishedStage")
            if finished != ROUNDS:
                print(
                    f"FAIL: {nd.addr} finished {finished}/{ROUNDS} rounds",
                    file=sys.stderr,
                )
                return 1
            if victim.addr in nd.get_neighbors():
                print(
                    f"FAIL: {nd.addr} still lists the dead node as a neighbor",
                    file=sys.stderr,
                )
                return 1
    finally:
        for nd in nodes:
            nd.stop()
        InMemoryRegistry.reset()

    dead = REGISTRY.get("p2pfl_aggregation_dead_contributors_total")
    dead_total = sum(c.value for _, c in dead.samples()) if dead else 0
    print(
        f"chaos-check OK: {len(survivors)} survivors finished {ROUNDS} rounds "
        f"in {elapsed:.1f}s after 1 mid-round kill "
        f"(dead-contributor shrinks: {int(dead_total)})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
