"""soak-check — the supervisor's bit-exact-resume-under-fire gate (~60 s).

A seeded 64-vnode population runs under the
:class:`~p2pfl_tpu.population.supervisor.EngineSupervisor` with three
injected host faults (kill, OOM, SIGTERM) drawn from the chaos plane's
``plan_host_faults`` trace, ON BOTH fused engines:

1. **heal to bit-identity** — the supervised run completes every chunk
   and its final canonical params hash equals a fault-free control's
   (journal + rollback + seeded-stream replay is transparent to
   training);
2. **replay identity** — a second supervised run of the same seed
   produces the SAME timestamp-free event log (same journals, same
   faults, same restarts at the same cursors — event-count-identical
   and event-for-event identical);
3. **degrade ladder determinism** — a permanently failing engine walks
   chunks -> cohort halving -> park, twice, with identical event logs
   (the ladder is ledgered and replayable, mirroring quorum-park).

Exit 0 when every check passes on both engines; 1 with a reason
otherwise. ``make soak-check`` wires it next to the other plane gates.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_NODES = 64
CHUNKS = 5
SEED = 20260807
FAULT_KINDS = ("kill", "oom", "sigterm")

#: Tiny model shape: the gate grades healing semantics, not learning.
SHAPE = dict(
    samples_per_node=8, feature_dim=8, hidden=(8,), batch_size=4,
    cohort_fraction=0.25, cohort_min=4, seed=SEED,
)


def _fail(msg: str) -> int:
    print(f"FAIL: {msg}", file=sys.stderr)
    return 1


def _final_hash(engine) -> str:
    from p2pfl_tpu.telemetry.ledger import canonical_params_hash

    if hasattr(engine, "global_params"):
        return canonical_params_hash(engine.global_params())
    return canonical_params_hash(engine.gather_params(0))


def _supervised(factory, faults, label):
    """One supervised run through ``faults``; returns (report, hash)."""
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.population.supervisor import EngineSupervisor

    with tempfile.TemporaryDirectory(prefix=f"soak-{label}-") as tmp:
        with FLCheckpointer(tmp, max_to_keep=2) as ck:
            with EngineSupervisor(
                factory, ck, node=f"soak-{label}", faults=faults, backoff_s=0.0
            ) as sup:
                report = sup.run(CHUNKS, chunk=1)
                h = None if report.parked else _final_hash(sup.engine)
    return report, h


def _soak_engine(name: str, factory) -> int:
    from p2pfl_tpu.chaos.plane import ChaosPlane

    t0 = time.monotonic()
    faults = ChaosPlane().plan_host_faults(CHUNKS, seed=SEED, kinds=FAULT_KINDS)
    if len(faults) != len(FAULT_KINDS):
        return _fail(f"{name}: degenerate fault trace {faults}")

    control = factory()
    try:
        control.run(CHUNKS)
        control_hash = _final_hash(control)
    finally:
        control.close()

    report, supervised_hash = _supervised(factory, faults, name)
    if report.parked:
        return _fail(f"{name}: supervisor parked ({report.park_reason})")
    if report.completed != CHUNKS:
        return _fail(f"{name}: completed {report.completed}/{CHUNKS} chunks")
    executed = {ev.kind for ev in report.faults_executed}
    if executed != set(FAULT_KINDS):
        return _fail(f"{name}: injected kinds {sorted(executed)} != {FAULT_KINDS}")
    if supervised_hash != control_hash:
        return _fail(
            f"{name}: supervised hash {supervised_hash} != control "
            f"{control_hash} — resume is not bit-exact"
        )

    replay, replay_hash = _supervised(factory, faults, f"{name}-replay")
    if len(replay.events) != len(report.events):
        return _fail(
            f"{name}: replay event count {len(replay.events)} != "
            f"{len(report.events)}"
        )
    if replay.events != report.events:
        return _fail(
            f"{name}: replay event log diverged\n  first  {report.events}\n"
            f"  replay {replay.events}"
        )
    if replay_hash != control_hash:
        return _fail(f"{name}: replay hash {replay_hash} != control")
    print(
        f"  {name}: healed {len(faults)} fault(s) "
        f"({'+'.join(sorted(executed))}), hash == control, "
        f"replay {len(replay.events)} events identical "
        f"[{time.monotonic() - t0:.1f}s]",
        file=sys.stderr,
    )
    return 0


def _degrade_ladder() -> int:
    """A permanently failing engine must walk the full ladder (chunks ->
    cohort -> park) identically on every replay."""
    from p2pfl_tpu.management.checkpoint import FLCheckpointer
    from p2pfl_tpu.population.engine import PopulationEngine
    from p2pfl_tpu.population.supervisor import EngineSupervisor

    class FailingEngine(PopulationEngine):
        def run(self, *a, **kw):
            raise RuntimeError("soak: synthetic permanent chunk failure")

    def factory(**kw):
        args = dict(
            num_nodes=8, cohort_fraction=0.5, cohort_min=2,
            samples_per_node=8, feature_dim=8, hidden=(8,), batch_size=4,
            seed=SEED,
        )
        args.update(kw)
        return FailingEngine(**args)

    def one_run():
        with tempfile.TemporaryDirectory(prefix="soak-degrade-") as tmp:
            with FLCheckpointer(tmp, max_to_keep=2) as ck:
                with EngineSupervisor(
                    factory, ck, node="soak-degrade", max_retries=0,
                    backoff_s=0.0, degrade="cohort",
                ) as sup:
                    return sup.run(CHUNKS, chunk=4)

    first, second = one_run(), one_run()
    if not first.parked:
        return _fail("degrade: permanently failing engine did not park")
    actions = [a for a, _ in first.degrade_steps]
    if "chunks" not in actions or "cohort" not in actions:
        return _fail(f"degrade: ladder skipped a stage: {first.degrade_steps}")
    if first.events != second.events:
        return _fail(
            f"degrade: ladder replay diverged\n  first  {first.events}\n"
            f"  second {second.events}"
        )
    print(
        f"  degrade: ladder {actions} -> park, {len(first.events)} events, "
        "replay identical",
        file=sys.stderr,
    )
    return 0


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.population.async_engine import AsyncPopulationEngine
    from p2pfl_tpu.population.engine import PopulationEngine

    Settings.LOG_LEVEL = "ERROR"
    t0 = time.monotonic()
    print(
        f"soak-check: {N_NODES} vnodes, {CHUNKS} chunks, faults "
        f"{FAULT_KINDS} on both engines...",
        file=sys.stderr,
    )

    def sync_factory(**kw):
        args = dict(num_nodes=N_NODES, **SHAPE)
        args.update(kw)
        return PopulationEngine(**args)

    def async_factory(**kw):
        args = dict(num_nodes=N_NODES, **SHAPE)
        args.update(kw)
        return AsyncPopulationEngine(**args)

    rc = _soak_engine("population", sync_factory)
    if rc:
        return rc
    rc = _soak_engine("async", async_factory)
    if rc:
        return rc
    rc = _degrade_ladder()
    if rc:
        return rc
    print(f"soak-check PASSED in {time.monotonic() - t0:.1f}s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
