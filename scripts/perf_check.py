"""CI gate for the performance attribution plane: run a 3-node in-memory
federated round with one seeded-slow node, assert that the critical-path
analyzer produces a per-round path with an identified gating node (the slow
node), that the structured perf section is populated, and that
``scripts/perf_diff.py`` exits nonzero on an injected 2x regression (and
zero on a self-diff). Fast, CPU-only, tier-1-safe — invoked by
``make perf-check``.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import json  # noqa: E402
import subprocess  # noqa: E402
import tempfile  # noqa: E402
import time  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    import bench
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.management.profiler import perf_section
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY, TRACER, CriticalPathAnalyzer
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3
    Settings.AGGREGATION_STALL_PATIENCE = 60.0  # the fleet WAITS for the slow node
    REGISTRY.reset()
    TRACER.reset()

    data = synthetic_mnist(n_train=3 * 128, n_test=64)
    parts = data.generate_partitions(3, RandomIIDPartitionStrategy)
    nodes = [Node(mlp_model(seed=i), parts[i], batch_size=32) for i in range(3)]
    slow = nodes[1]
    inner_fit = slow.learner.fit

    def slow_fit(*a, **kw):
        t0 = time.monotonic()
        m = inner_fit(*a, **kw)
        time.sleep(min(2.0 * (time.monotonic() - t0), 5.0) + 1.0)
        return m

    slow.learner.fit = slow_fit
    for nd in nodes:
        nd.start()
    try:
        for i in (1, 2):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, 2, wait=15)
        nodes[0].set_start_learning(rounds=1, epochs=1)
        deadline = time.time() + 300
        while time.time() < deadline:
            if all(
                not nd.learning_in_progress() and nd.learning_workflow is not None
                for nd in nodes
            ):
                break
            time.sleep(0.2)
        else:
            print("FAIL: 3-node round did not finish in 300s", file=sys.stderr)
            return 1
    finally:
        for nd in nodes:
            nd.stop()
        InMemoryRegistry.reset()

    analyzer = CriticalPathAnalyzer.from_tracer(TRACER)
    if 0 not in analyzer.rounds():
        print(f"FAIL: no round-0 spans (rounds={analyzer.rounds()})", file=sys.stderr)
        return 1
    path = analyzer.round_path(0)
    if not path.hops:
        print("FAIL: critical path is empty for round 0", file=sys.stderr)
        return 1
    if not path.gating_node:
        print("FAIL: no gating node identified for round 0", file=sys.stderr)
        return 1
    if path.gating_node != slow.addr:
        print(
            f"FAIL: gating node {path.gating_node} is not the seeded slow "
            f"node {slow.addr}; attribution {path.attributed_by_node}",
            file=sys.stderr,
        )
        return 1

    perf = perf_section(REGISTRY, cost=nodes[0].learner.cost_analysis())
    if not perf["compile"]["first_compile_s"]:
        print("FAIL: perf section has no compile events", file=sys.stderr)
        return 1

    # --- perf_diff exit-code semantics --------------------------------------
    base = {
        "metric": "perf_check_gate",
        "value": round(path.wall_s, 4),
        "unit": "s/round",
        "meta": bench._bench_meta(seed=0, backend="cpu"),
        "perf": perf,
        "extra": {"mean_round_wall_s": round(path.wall_s, 4)},
    }
    regressed = json.loads(json.dumps(base))
    regressed["value"] *= 2.0
    regressed["extra"]["mean_round_wall_s"] *= 2.0
    diff = os.path.join(REPO, "scripts", "perf_diff.py")
    with tempfile.TemporaryDirectory() as td:
        bp = os.path.join(td, "base.json")
        rp = os.path.join(td, "regressed.json")
        with open(bp, "w") as f:
            json.dump(base, f)
        with open(rp, "w") as f:
            json.dump(regressed, f)
        rc_self = subprocess.run(
            [sys.executable, diff, bp, bp], capture_output=True, text=True
        ).returncode
        reg_run = subprocess.run(
            [sys.executable, diff, bp, rp], capture_output=True, text=True
        )
        # Cross-schema refusal: a candidate on another schema must exit 3.
        alien = json.loads(json.dumps(base))
        alien["meta"]["schema_version"] = -1
        ap = os.path.join(td, "alien.json")
        with open(ap, "w") as f:
            json.dump(alien, f)
        rc_schema = subprocess.run(
            [sys.executable, diff, bp, ap], capture_output=True, text=True
        ).returncode
    if rc_self != 0:
        print(f"FAIL: perf_diff self-diff exited {rc_self}", file=sys.stderr)
        return 1
    if reg_run.returncode != 1:
        print(
            f"FAIL: perf_diff exited {reg_run.returncode} on a 2x regression "
            f"(want 1): {reg_run.stderr[-500:]}",
            file=sys.stderr,
        )
        return 1
    if rc_schema != 3:
        print(f"FAIL: perf_diff exited {rc_schema} on a schema mismatch (want 3)", file=sys.stderr)
        return 1

    print(
        f"perf-check OK: gating node {path.gating_node} "
        f"({path.attributed_by_node.get(path.gating_node, 0):.2f}s of "
        f"{path.wall_s:.2f}s round), {len(path.hops)} hops, perf_diff "
        "semantics verified"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
