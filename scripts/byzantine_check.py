"""CI gate: 3-node in-memory federation with ONE signflip adversary (chaos
plane byzantine behavior at the send choke point) and Krum + wire admission
control on the honest side. The honest nodes must finish ALL rounds within a
wall budget, the admission plane must have rejected at least one poisoned
frame (``p2pfl_updates_rejected_total`` nonzero), and the honest final
accuracy must sit above the attacked-FedAvg floor (undefended FedAvg under a
signflip trainer converges to ~chance). Fast, CPU-only, tier-1-safe —
invoked by ``make byzantine-check``.

Exit 0 when every check passes; nonzero with a reason on stderr otherwise.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import time  # noqa: E402

ROUNDS = 2
#: Wall budget for the whole learning run. Generous for a loaded 1-core CI
#: box and covers the per-round JIT stall patience (the adversary's rejected
#: contributions never arrive, so each round stalls AGGREGATION_STALL_PATIENCE
#: before aggregating what did), yet far below sleeping out the fixed
#: timeouts (ROUNDS x (VOTE_TIMEOUT + AGGREGATION_TIMEOUT) = 80s under test
#: settings plus training time).
WALL_BUDGET_S = 90.0
#: Floor the defended accuracy must clear: an UNDEFENDED FedAvg federation
#: with a signflip trainer in a 3-committee collapses toward chance (~0.1 on
#: 10 classes); the defended run excludes the adversary and trains normally
#: (~0.7+ after 2 rounds on synthetic MNIST).
ATTACKED_FEDAVG_FLOOR = 0.3


def main() -> int:
    from p2pfl_tpu.chaos import CHAOS
    from p2pfl_tpu.comm.memory.registry import InMemoryRegistry
    from p2pfl_tpu.config import Settings
    from p2pfl_tpu.learning.aggregators import Krum
    from p2pfl_tpu.learning.dataset import RandomIIDPartitionStrategy, synthetic_mnist
    from p2pfl_tpu.models import mlp_model
    from p2pfl_tpu.node import Node
    from p2pfl_tpu.telemetry import REGISTRY
    from p2pfl_tpu.utils.utils import set_test_settings, wait_convergence

    set_test_settings()
    Settings.RESOURCE_MONITOR_PERIOD = 0
    Settings.LOG_LEVEL = "WARNING"
    Settings.TRAIN_SET_SIZE = 3  # full committee: the adversary is a trainer
    REGISTRY.reset()
    CHAOS.reset()

    n = 3
    data = synthetic_mnist(n_train=128 * n, n_test=64)
    parts = data.generate_partitions(n, RandomIIDPartitionStrategy)
    nodes = [
        Node(mlp_model(seed=i), parts[i], batch_size=32,
             aggregator=Krum(num_byzantine=1))
        for i in range(n)
    ]
    adversary, honest = nodes[2], nodes[:2]
    for nd in nodes:
        nd.start()
    try:
        CHAOS.set_byzantine(adversary.addr, "signflip")
        for i in range(1, n):
            nodes[i].connect(nodes[0].addr)
        wait_convergence(nodes, n - 1, wait=15)

        t0 = time.monotonic()
        nodes[0].set_start_learning(rounds=ROUNDS, epochs=1)

        finish_deadline = time.monotonic() + WALL_BUDGET_S
        while time.monotonic() < finish_deadline:
            if all(
                not nd.learning_in_progress() and nd.learning_workflow is not None
                for nd in honest
            ):
                break
            time.sleep(0.2)
        else:
            print(
                f"FAIL: honest nodes did not finish {ROUNDS} rounds within "
                f"{WALL_BUDGET_S:.0f}s under the signflip adversary",
                file=sys.stderr,
            )
            return 1
        elapsed = time.monotonic() - t0
        faults = CHAOS.fault_counts()

        for nd in honest:
            finished = nd.learning_workflow.history.count("RoundFinishedStage")
            if finished != ROUNDS:
                print(
                    f"FAIL: {nd.addr} finished {finished}/{ROUNDS} rounds",
                    file=sys.stderr,
                )
                return 1

        rejected = {}
        fam = REGISTRY.get("p2pfl_updates_rejected_total")
        if fam is not None:
            for labels, child in fam.samples():
                r = labels.get("reason", "?")
                rejected[r] = rejected.get(r, 0) + int(child.value)
        if sum(rejected.values()) == 0:
            print(
                "FAIL: admission control rejected nothing — the adversary's "
                f"poisoned frames were never screened (faults={faults})",
                file=sys.stderr,
            )
            return 1

        accs = [nd.learner.evaluate().get("test_acc", 0.0) for nd in honest]
        if min(accs) < ATTACKED_FEDAVG_FLOOR:
            print(
                f"FAIL: honest accuracy {min(accs):.3f} below the "
                f"attacked-FedAvg floor {ATTACKED_FEDAVG_FLOOR} "
                f"(accs={[round(a, 3) for a in accs]})",
                file=sys.stderr,
            )
            return 1
    finally:
        for nd in nodes:
            nd.stop()
        CHAOS.reset()
        InMemoryRegistry.reset()

    print(
        f"byzantine-check OK: {len(honest)} honest nodes finished {ROUNDS} "
        f"rounds in {elapsed:.1f}s with 1 signflip adversary "
        f"(rejections: {rejected}, injected: {faults}, "
        f"honest acc: {[round(a, 3) for a in accs]})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
