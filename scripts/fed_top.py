"""fed_top — live terminal view of the federation observatory.

``top`` for a p2p federation: renders the JSON federation snapshot a node's
:class:`~p2pfl_tpu.telemetry.observatory.Observatory` writes
(``Observatory.write_snapshot``; ``bench.py --observatory`` and
``scripts/observatory_check.py`` both write ``artifacts/
federation_snapshot.json``) as a continuously-refreshing table:

    python scripts/fed_top.py                         # poll the default path
    python scripts/fed_top.py artifacts/federation_snapshot.json --interval 1
    python scripts/fed_top.py --once                  # one frame, no ANSI

Columns: peer, reported round/total (``w``-prefixed for async windows),
stage, steps/s, TX/RX MiB, async staleness (p90 from the digest's
staleness sketch when the peer reports v2 digests, else the mean gauge),
straggler / suspect / link scores (sorted worst-straggler first), digest
age. The top straggler and top suspect are called out under the table,
then the FLEET section — population size (tracked + sketch-folded
overflow peers) and merged fleet quantiles (p50/p90/p99 step time,
staleness, update norm, agg wait, distinct contributors) — then the live
membership-churn tail. Snapshots written by the fused-mesh simulation
(``MeshSimulation.fleet_snapshot``; ``bench.py --fleetobs``) render in
the same view: the peer table is the top-N stragglers of a 10k-virtual-
node run, the fleet row is the whole population. When an evidence bundle
has been captured next to the snapshot (``artifacts/incident.json``,
written by the failure hooks or ``scripts/fed_doctor.py``) a DIAGNOSIS
banner names the top-ranked root cause; ``--doctor`` prints that report
once and exits (``-`` when no incident exists). Stdlib-only — no curses,
no dependencies — so it runs anywhere the repo does.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict

DEFAULT_PATH = os.path.join("artifacts", "federation_snapshot.json")

_CLEAR = "\x1b[2J\x1b[H"
_BOLD = "\x1b[1m"
_RED = "\x1b[31m"
_YELLOW = "\x1b[33m"
_DIM = "\x1b[2m"
_RESET = "\x1b[0m"


def _mib(v: float) -> str:
    return f"{v / (1 << 20):.1f}"


def _short(addr: str, width: int = 22) -> str:
    return addr if len(addr) <= width else "…" + addr[-(width - 1):]


def _parity_banner(parity: Dict[str, Any]) -> str:
    """One-line OK/DIVERGED summary of an ``artifacts/parity_diff.json``
    report (scripts/parity_diff.py)."""
    if parity.get("status") == "OK":
        return (
            f"PARITY OK — {parity.get('compared_events', 0)} events aligned, "
            f"{parity.get('hashes_compared', 0)} aggregate hashes bit-exact"
        )
    fd = parity.get("first_divergence") or {}
    ev = fd.get("a") or fd.get("b") or {}
    where = f"{ev.get('kind', '?')}@round {ev.get('round', '?')}"
    return f"PARITY DIVERGED @ {where}: {fd.get('problem', '?')}"


def _diagnosis_banner(incident: Dict[str, Any]) -> "list[str]":
    """DIAGNOSIS banner lines from an ``artifacts/incident.json`` report
    (written by the evidence-bundle hooks / scripts/fed_doctor.py)."""
    findings = incident.get("findings") or []
    if not findings:
        return ["DIAGNOSIS — (no findings; last doctor pass came back clean)"]
    top = findings[0]
    lines = [
        f"DIAGNOSIS [{str(top.get('severity', '?')).upper()}] "
        f"{top.get('rule', '?')} "
        f"({float(top.get('confidence', 0.0)):.0%}) — {top.get('summary', '')}"
    ]
    if len(findings) > 1:
        rest = ", ".join(str(f.get("rule", "?")) for f in findings[1:4])
        lines.append(f"  +{len(findings) - 1} more: {rest}")
    return lines


def _ledger_line(ev: Dict[str, Any]) -> str:
    kind = ev.get("kind", "?")
    rnd = ev.get("round")
    bits = [f"r{rnd}" if rnd is not None else "r-", f"{kind:<20}"]
    if "sender" in ev:
        bits.append(_short(str(ev["sender"]), 18))
    if "peer" in ev:
        bits.append(f"{ev.get('event', '')} {_short(str(ev['peer']), 18)}".strip())
    if "members" in ev:
        bits.append(f"{len(ev['members'])} members")
    if "hash" in ev:
        bits.append(str(ev["hash"])[:23] + "…")
    if "lag" in ev and ev.get("lag"):
        bits.append(f"lag {ev['lag']}")
    return "  ".join(bits)


def render(
    snap: Dict[str, Any],
    color: bool = True,
    parity: "Dict[str, Any] | None" = None,
    incident: "Dict[str, Any] | None" = None,
) -> str:
    def paint(code: str, s: str) -> str:
        return f"{code}{s}{_RESET}" if color else s

    peers = snap.get("peers", {})
    top_straggler = snap.get("top_straggler")
    top_suspect = snap.get("top_suspect")
    fleet = snap.get("fleet") or {}
    fleet_size = fleet.get("size", len(peers))
    title = (
        f"federation observatory — observer {snap.get('observer', '?')} "
        f"— {fleet_size} peers"
    )
    if snap.get("virtual"):
        title += f" (virtual fleet; showing top {len(peers)} stragglers)"
    elif fleet.get("overflow_peers"):
        title += f" ({len(peers)} tracked + {fleet['overflow_peers']} sketch-folded)"
    header = (
        f"{'PEER':<23} {'ROUND':>7} {'STAGE':<22} {'STEP/S':>8} "
        f"{'TX MiB':>8} {'RX MiB':>8} {'STALE':>6} {'EPS':>6} {'COHORT':>7} "
        f"{'WINDOW':>7} {'FILL':>6} "
        f"{'LOSS':>7} {'GNORM':>7} {'HBM MiB':>8} {'TRIP':>6} "
        f"{'RSTRT':>5} {'DEGR':>4} "
        f"{'STRAG':>7} {'SUSP':>7} {'LINK':>6} {'AGE s':>6}"
    )
    lines = [
        paint(_BOLD, title),
        paint(_BOLD, header),
    ]
    rows = sorted(
        peers.items(),
        key=lambda kv: -(kv[1].get("scores", {}).get("straggler", 0.0)),
    )
    for addr, p in rows:
        s = p.get("scores", {})
        rnd = p.get("round", -1)
        total = p.get("total_rounds", -1)
        round_s = f"{rnd}/{total}" if rnd >= 0 and total >= 0 else ("-" if rnd < 0 else str(rnd))
        if p.get("mode") == "async":  # windows, not barrier rounds
            round_s = f"w{round_s}"
        # Sketch-carried staleness p90 beats the mean gauge when present
        # (v2 digests): p90 is what a late-contribution SLO is written on.
        stale = p.get("staleness_p90")
        if stale is None:
            stale = p.get("staleness", 0.0)
        # Privacy budget: cumulative DP epsilon. "-" = the peer never
        # reported one (absent telemetry), "0.00" = DP active with nothing
        # released yet — a genuine zero-spend claim, not the same thing —
        # "inf" = -1 sentinel (non-private steps void the claim).
        eps = p.get("dp_epsilon")
        eps_s = "-" if eps is None else ("inf" if eps < 0 else f"{eps:.2f}")
        # Cohort-fill: realized per-round solicitation fraction under the
        # population engine's cohort sampling; "-" for real-wire peers and
        # pre-population snapshots (field absent or null).
        fill = p.get("cohort_fill")
        fill_s = "-" if fill is None else f"{fill:.2f}"
        # Async population columns: last window this vnode's contribution
        # folded into (w-prefixed; "-" = never folded or a sync snapshot)
        # and its realized fold fraction across all windows so far.
        window = p.get("window")
        window_s = "-" if window is None else ("-" if window < 0 else f"w{window}")
        wfill = p.get("window_fill")
        wfill_s = "-" if wfill is None else f"{wfill:.2f}"
        # Device-observatory columns (in-scan aux stream on fused-engine
        # snapshots; "-" for real-wire peers): last cohort/window train
        # loss, p90 in-scan update norm, device HBM watermark, and the
        # tripwire state (nonfinite | loss_diverge — rows with a trip
        # paint red, the run stopped launching chunks there).
        loss = p.get("loss")
        loss_s = "-" if loss is None else f"{loss:.3f}"
        gnorm = p.get("gnorm")
        gnorm_s = "-" if gnorm is None else f"{gnorm:.3g}"
        mem = p.get("mem_bytes")
        mem_s = "-" if not mem else _mib(float(mem))
        trip = p.get("trip")
        trip_s = "-" if not trip else str(trip)[:6]
        # Supervisor columns: engine restarts and degrade-ladder steps the
        # peer's supervisor performed; "-" for unsupervised runs and
        # pre-supervisor snapshots/digests (field absent or null).
        restarts = p.get("restarts")
        restarts_s = "-" if restarts is None else str(int(restarts))
        degrade = p.get("degrade")
        degrade_s = "-" if degrade is None else str(int(degrade))
        row = (
            f"{_short(addr):<23} {round_s:>7} {p.get('stage') or '-':<22.22} "
            f"{p.get('steps_per_s', 0.0):>8.1f} {_mib(p.get('tx_bytes', 0.0)):>8} "
            f"{_mib(p.get('rx_bytes', 0.0)):>8} "
            f"{(f'{stale:.1f}' if stale else '-'):>6} "
            f"{eps_s:>6} "
            f"{fill_s:>7} "
            f"{window_s:>7} "
            f"{wfill_s:>6} "
            f"{loss_s:>7} "
            f"{gnorm_s:>7} "
            f"{mem_s:>8} "
            f"{trip_s:>6} "
            f"{restarts_s:>5} "
            f"{degrade_s:>4} "
            f"{s.get('straggler', 0.0):>7.2f} "
            f"{s.get('suspect', 0.0):>7.1f} {s.get('link', 0.0):>6.1f} "
            f"{s.get('age_s', 0.0):>6.1f}"
        )
        if trip:
            row = paint(_RED, row)
        elif addr == top_suspect:
            row = paint(_RED, row)
        elif addr == top_straggler:
            row = paint(_YELLOW, row)
        lines.append(row)
    lines.append("")
    lines.append(
        f"top straggler: {top_straggler or '-'}    top suspect: {top_suspect or '-'}"
    )
    # DIAGNOSIS banner (artifacts/incident.json, written when a failure
    # hook captured an evidence bundle or fed_doctor ran): "-" means no
    # incident has ever been diagnosed next to this snapshot.
    if incident is None:
        lines.append(paint(_DIM, "diagnosis: -"))
    else:
        sev = (incident.get("findings") or [{}])[0].get("severity")
        code = _RED if sev == "critical" else (_YELLOW if sev == "warning" else _DIM)
        for dl in _diagnosis_banner(incident):
            lines.append(paint(code, dl))
    # Device-observatory banner (fused engines stamp the in-scan stream's
    # headline values into snap["devobs"]): a tripped run heads the panel
    # in red — the compiled program itself raised the flag.
    devobs = snap.get("devobs") or {}
    if devobs:
        tripped = devobs.get("tripped")
        mem = devobs.get("mem_bytes")
        bits = [
            f"loss {devobs['train_loss']:.4f}"
            if devobs.get("train_loss") is not None else "loss -",
            f"gnorm p90 {devobs['update_norm_p90']:.3g}"
            if devobs.get("update_norm_p90") is not None else "gnorm -",
            f"hbm {_mib(float(mem))} MiB" if mem else "hbm -",
            f"TRIPPED: {tripped}" if tripped else "trip -",
        ]
        line = "device observatory: " + "    ".join(bits)
        lines.append(paint(_RED if tripped else _BOLD, line))
    # Supervisor banner (EngineSupervisor.snapshot stamps its run totals
    # into snap["supervisor"]): a parked run heads the panel in red — the
    # degrade ladder ran out and the state is waiting in the journal.
    sup = snap.get("supervisor") or {}
    if sup:
        line = (
            f"supervisor: restarts {sup.get('restarts', 0)}    "
            f"retries {sup.get('retries', 0)}    "
            f"degrade {sup.get('degrade_steps', 0)}    "
            f"journals {sup.get('journals', 0)}"
            + ("    PARKED" if sup.get("parked") else "")
        )
        lines.append(paint(_RED if sup.get("parked") else _BOLD, line))
    # Fleet-wide model-plane bytes per wire codec (digest tx_by_codec —
    # which encoder is actually carrying the model plane, and how much of
    # the traffic still rides dense frames).
    by_codec: dict = {}
    for p in peers.values():
        for codec, b in (p.get("tx_by_codec") or {}).items():
            by_codec[codec] = by_codec.get(codec, 0.0) + float(b)
    if by_codec:
        total = sum(by_codec.values()) or 1.0
        split = "  ".join(
            f"{c} {_mib(b)} ({100.0 * b / total:.0f}%)"
            for c, b in sorted(by_codec.items(), key=lambda kv: -kv[1])
        )
        lines.append(paint(_BOLD, f"wire TX by codec: {split}"))
    quantiles = fleet.get("quantiles") or {}
    if quantiles:
        lines.append(paint(_BOLD, f"fleet ({fleet_size} nodes) — merged sketch quantiles:"))
        for name, q in sorted(quantiles.items()):
            if name == "distinct_contributors":
                lines.append(f"  distinct contributors ~{q:.0f}")
                continue
            if not isinstance(q, dict):
                continue
            lines.append(
                f"  {name:<14} p50 {q.get('p50', 0.0):>10.4g}  "
                f"p90 {q.get('p90', 0.0):>10.4g}  p99 {q.get('p99', 0.0):>10.4g}  "
                f"(n={q.get('count', 0):.0f})"
            )
    churn = snap.get("membership_events") or []
    if churn:
        tail = churn[-5:]
        lines.append(paint(_BOLD, f"membership churn ({len(churn)} events):"))
        for ev in reversed(tail):
            age = max(0.0, time.time() - float(ev.get("ts", 0.0)))
            lines.append(
                paint(
                    _DIM,
                    f"  {ev.get('event', '?'):<7} {_short(str(ev.get('peer', '?')))} "
                    f"({age:.0f}s ago)",
                )
            )
    ledger = snap.get("ledger") or {}
    tail = ledger.get("events") or []
    if tail or parity is not None:
        title = "PARITY / trajectory ledger"
        if ledger.get("run_id"):
            title += f" (run {ledger['run_id']})"
        lines.append(paint(_BOLD, title + ":"))
        if parity is not None:
            banner = _parity_banner(parity)
            lines.append(
                paint(_RED if "DIVERGED" in banner else _DIM, f"  {banner}")
            )
        for ev in tail[-8:]:
            lines.append(paint(_DIM, f"  {_ledger_line(ev)}"))
    written = snap.get("written_at")
    if written:
        lines.append(
            paint(_DIM, f"snapshot written {max(0.0, time.time() - written):.1f}s ago")
        )
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", default=DEFAULT_PATH,
                    help=f"federation snapshot JSON (default {DEFAULT_PATH})")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame (no ANSI clear) and exit")
    ap.add_argument("--doctor", action="store_true",
                    help="one-shot: print the latest incident report next to "
                         "the snapshot ('-' when none) and exit")
    args = ap.parse_args()

    color = sys.stdout.isatty() or not args.once
    # The parity report (scripts/parity_diff.py --out) lives next to the
    # snapshot; when present its OK/DIVERGED banner heads the ledger panel.
    # The incident report (evidence-bundle hooks / scripts/fed_doctor.py)
    # lives there too and feeds the DIAGNOSIS banner.
    artifacts_dir = os.path.dirname(args.path) or "."
    parity_path = os.path.join(artifacts_dir, "parity_diff.json")
    incident_path = os.path.join(artifacts_dir, "incident.json")

    if args.doctor:
        try:
            with open(incident_path) as f:
                incident = json.load(f)
        except (OSError, ValueError):
            print("-")
            return 0
        rid = incident.get("run_id") or "-"
        print(f"incident (run {rid}, source {incident.get('source') or '-'}):")
        for line in _diagnosis_banner(incident):
            print(line)
        for f_ in (incident.get("findings") or [])[1:]:
            print(
                f"  [{str(f_.get('severity', '?')).upper()}] {f_.get('rule')} "
                f"({float(f_.get('confidence', 0.0)):.0%}) — {f_.get('summary')}"
            )
        return 0

    while True:
        parity = None
        try:
            with open(parity_path) as f:
                parity = json.load(f)
        except (OSError, ValueError):
            parity = None
        incident = None
        try:
            with open(incident_path) as f:
                incident = json.load(f)
        except (OSError, ValueError):
            incident = None
        try:
            with open(args.path) as f:
                snap = json.load(f)
            frame = render(
                snap,
                color=color and not args.once,
                parity=parity,
                incident=incident,
            )
        except FileNotFoundError:
            frame = (
                f"waiting for {args.path} — run a federation that writes the "
                "snapshot (bench.py --observatory, make observatory-check, or "
                "Observatory.write_snapshot in your own run)"
            )
        except (ValueError, OSError) as exc:  # mid-write / malformed
            frame = f"unreadable snapshot at {args.path}: {exc}"
        if args.once:
            print(frame)
            return 0
        sys.stdout.write(_CLEAR + frame + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # fed_top | head — not an error
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
